#!/usr/bin/env python
"""North-star benchmark: ACL-path classification at 10k rules.

Reproduces BASELINE.md config #2/#5 — the reference's policy-perf regime
(tests/policy/perf/gen-policy.py: 1000 CIDR blocks x excepts x 20 ports)
— through the FULL fused pipeline (ip4-input → reflective sessions →
NAT44 → 10k-rule global ACL classify → ip4-lookup), measured in Mpps on
one chip against the driver-set 40 Mpps north star (BASELINE.json).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

METRIC = "acl_nat_pipeline_mpps_10k_rules"
BASELINE_MPPS = 40.0  # BASELINE.json north star, TPU v5e


def _cpu_fallback_env() -> dict:
    """Env for a CPU-only child: a WEDGED tunnel hangs even CPU-platform
    init through the eagerly-registering axon plugin, so drop it from
    PYTHONPATH and force the platform."""
    env = dict(os.environ)
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":")
        if p and "axon" not in p)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _emit_error(exc: BaseException) -> None:
    """Always leave ONE parseable JSON line, even on total failure."""
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": "Mpps",
                "vs_baseline": 0.0,
                "error": f"{type(exc).__name__}: {exc}",
            }
        )
    )


def _subprocess_probe(timeout_s: float = 90.0) -> bool:
    """Probe TPU backend health in a THROWAWAY subprocess first.

    A wedged axon tunnel (a SIGTERM'd process mid-claim) makes backend
    init HANG rather than fail — in-process that would hang this whole
    bench and the driver would record nothing. A subprocess can be
    killed safely (it holds no grant yet).

    The probe EXECUTES a matmul, not just jax.devices(): a half-wedged
    tunnel has been observed (2026-07-31) to answer device enumeration
    from cache and then hang the first real compile/execute RPC."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; jax.devices(); "
             "(jnp.ones((64,64)) @ jnp.ones((64,64))).block_until_ready(); "
             "print('ok-exec')"],
            capture_output=True, timeout=timeout_s, text=True,
        )
        return proc.returncode == 0 and "ok-exec" in proc.stdout
    except (subprocess.TimeoutExpired, OSError):
        return False


# --- section checkpointing -------------------------------------------
# The tunnel wedges MID-RUN without warning (r3: a degraded tunnel
# zeroed the io_* section; 2026-07-31: a wedge 20 min in lost the whole
# run). Each completed section is flushed to a sidecar JSON so a
# watchdog-killed run still yields every number it finished.
_PROGRESS_PATH: str | None = os.environ.get("BENCH_PROGRESS_OUT") or None
_PROGRESS_STATE: dict = {}


def _progress(**kv) -> None:
    if not _PROGRESS_PATH:
        return
    _PROGRESS_STATE.update(kv)
    tmp = f"{_PROGRESS_PATH}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(_PROGRESS_STATE, f, indent=1, default=str)
        os.replace(tmp, _PROGRESS_PATH)
    except OSError:
        pass


def _jit_compiles_now() -> int:
    """Total pipeline-step XLA compiles so far (the runtime jit-compile
    guard, pipeline/dataplane.py). Recorded per priority-ladder section
    as <section>_jit_compiles so a recompile regression — the PR-4
    fresh-closure class — shows up in the BENCH_* trajectory, not just
    in wall-clock drift."""
    try:
        from vpp_tpu.pipeline.dataplane import jit_compile_totals

        return sum(jit_compile_totals().values())
    except Exception:  # noqa: BLE001 — accounting must never kill a run
        return 0


def _transfer_bytes_now() -> int:
    """Total device->host bytes through the counted fetch sites so far
    (the runtime device-transfer guard, pipeline/dataplane.py).
    Recorded per priority-ladder section as <section>_transfer_bytes so
    a table-column fetch creeping onto a measured path — the PR-6/8/12
    "aggregate on host" class — shows up in the BENCH_* trajectory."""
    try:
        from vpp_tpu.pipeline.dataplane import device_transfer_totals

        return sum(device_transfer_totals().values())
    except Exception:  # noqa: BLE001 — accounting must never kill a run
        return 0


def _probe_backend(retries: int, delay: float):
    """Initialize the JAX backend, retrying transient axon/tunnel init
    failures (round-1 bench died on 'Unable to initialize backend axon'
    before measuring anything; round-3 saw init HANG on a wedged
    tunnel — hence the subprocess pre-probe)."""
    retries = max(1, retries)
    for attempt in range(retries):
        # checkpoint each attempt: the supervisor watches sidecar mtime
        # and must not mistake a legitimate probe window for a wedge
        _progress(probe_attempt=attempt + 1)
        if _subprocess_probe():
            break
        if attempt + 1 >= retries:
            raise RuntimeError("TPU backend unreachable (subprocess probe)")
        time.sleep(delay)
    import jax

    last: BaseException | None = None
    for attempt in range(retries):
        try:
            return jax.default_backend()
        except RuntimeError as e:  # backend init failure
            last = e
            if attempt + 1 < retries:
                time.sleep(delay)
    raise last  # type: ignore[misc]


def build_rules(n_rules: int):
    """Policy rule set shaped like tests/policy/perf/gen-policy.py:
    CIDR-block x port permits with interleaved deny excepts, then a
    terminal deny-all (the renderer-cache table form)."""
    import ipaddress

    from vpp_tpu.ir.rule import Action, ContivRule, Protocol

    rules = []
    i = 0
    while len(rules) < n_rules - 1:
        block = i % 1000
        port = 8000 + (i // 1000) % 20
        net = ipaddress.ip_network(
            f"172.{16 + block // 256}.{block % 256}.0/24"
        )
        action = Action.DENY if i % 6 == 5 else Action.PERMIT
        rules.append(
            ContivRule(
                action=action,
                src_network=net,
                protocol=Protocol.TCP,
                dest_port=port,
            )
        )
        i += 1
    rules.append(ContivRule(action=Action.DENY))
    return rules


def build_dataplane(n_rules: int, n_backends: int, ml_stage: str = "off",
                    telemetry: str = "off"):
    from vpp_tpu.ir.rule import Action, ContivRule
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import Disposition, ip4

    config = DataplaneConfig(
        max_tables=2,
        max_rules=16,
        max_global_rules=n_rules,
        max_ifaces=16,
        fib_slots=64,
        sess_slots=1 << 15,
        nat_mappings=4,
        nat_backends=max(n_backends, 1),
        ml_stage=ml_stage,
        telemetry=telemetry,
    )
    dp = Dataplane(config)
    uplink = dp.add_uplink()
    server_if = dp.add_pod_interface(("default", "server"))
    dp.builder.add_route("10.1.1.0/24", server_if, Disposition.LOCAL)
    dp.builder.add_route("0.0.0.0/0", uplink, Disposition.REMOTE, node_id=1)
    dp.builder.set_global_table(build_rules(n_rules))
    # NAT44 VIP with weighted backends (BASELINE config #3 shape).
    dp.builder.set_nat_mapping(
        0,
        ext_ip=ip4("10.96.0.10"),
        ext_port=80,
        proto=6,
        backends=[(ip4("10.1.1.2") + i, 80, 1 + (i % 2)) for i in range(n_backends)],
        boff=0,
    )
    dp.swap()
    return dp, uplink


def build_traffic(n_pkts: int, uplink: int, seed: int = 7):
    """Uplink traffic: TCP flows from the rule-space CIDR blocks toward
    the local pod subnet + a slice of VIP (NAT) traffic."""
    import jax.numpy as jnp

    from vpp_tpu.pipeline.vector import FLAG_VALID, PacketVector, ip4

    rng = np.random.default_rng(seed)
    block = rng.integers(0, 1000, n_pkts)
    src = (
        (172 << 24)
        | ((16 + block // 256) << 16)
        | ((block % 256) << 8)
        | rng.integers(1, 255, n_pkts)
    ).astype(np.uint32)
    dst = (ip4("10.1.1.0") + rng.integers(2, 250, n_pkts)).astype(np.uint32)
    # ~1/8 of traffic targets the service VIP (exercises DNAT + session).
    vip_mask = rng.random(n_pkts) < 0.125
    dst = np.where(vip_mask, np.uint32(ip4("10.96.0.10")), dst)
    dport = np.where(
        vip_mask, 80, 8000 + rng.integers(0, 20, n_pkts)
    ).astype(np.int32)
    return PacketVector(
        src_ip=jnp.asarray(src),
        dst_ip=jnp.asarray(dst),
        proto=jnp.full((n_pkts,), 6, jnp.int32),
        sport=jnp.asarray(rng.integers(1024, 65535, n_pkts).astype(np.int32)),
        dport=jnp.asarray(dport),
        ttl=jnp.full((n_pkts,), 64, jnp.int32),
        pkt_len=jnp.full((n_pkts,), 512, jnp.int32),
        rx_if=jnp.full((n_pkts,), uplink, jnp.int32),
        flags=jnp.full((n_pkts,), FLAG_VALID, jnp.int32),
    )


def build_fwd_dataplane(telemetry: str = "off"):
    """BASELINE config #1: pod-to-pod ip4-lookup only (no policy/NAT).
    ``telemetry`` enables the device latency histogram for sections
    that tie host-side and on-device latency from the same round
    (the ISSUE 13 host-vs-device sanity check)."""
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import Disposition

    config = DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=16, max_ifaces=64,
        fib_slots=64, sess_slots=1 << 12, nat_mappings=1, nat_backends=1,
        telemetry=telemetry,
    )
    dp = Dataplane(config)
    for i in range(32):
        idx = dp.add_pod_interface(("default", f"p{i}"))
        dp.builder.add_route(f"10.1.1.{i + 2}/32", idx, Disposition.LOCAL)
    dp.swap()
    return dp


def build_pod_traffic(n_pkts: int, seed: int = 3):
    import jax.numpy as jnp

    from vpp_tpu.pipeline.vector import FLAG_VALID, PacketVector, ip4

    rng = np.random.default_rng(seed)
    src = (ip4("10.1.1.0") + rng.integers(2, 34, n_pkts)).astype(np.uint32)
    dst = (ip4("10.1.1.0") + rng.integers(2, 34, n_pkts)).astype(np.uint32)
    return PacketVector(
        src_ip=jnp.asarray(src),
        dst_ip=jnp.asarray(dst),
        proto=jnp.full((n_pkts,), 17, jnp.int32),
        sport=jnp.asarray(rng.integers(1024, 65535, n_pkts).astype(np.int32)),
        dport=jnp.full((n_pkts,), 5201, jnp.int32),
        ttl=jnp.full((n_pkts,), 64, jnp.int32),
        pkt_len=jnp.full((n_pkts,), 1400, jnp.int32),
        rx_if=jnp.asarray(rng.integers(1, 33, n_pkts).astype(np.int32)),
        flags=jnp.full((n_pkts,), FLAG_VALID, jnp.int32),
    )


def measure_mpps(step, tables, pkts, iters, warmup, now0=1):
    import jax
    import jax.numpy as jnp

    n = int(pkts.src_ip.shape[0])
    for i in range(warmup):
        res = step(tables, pkts, jnp.int32(now0 + i))
        tables = res.tables
    jax.block_until_ready(tables)
    t0 = time.perf_counter()
    for i in range(iters):
        res = step(tables, pkts, jnp.int32(now0 + warmup + i))
        tables = res.tables
    jax.block_until_ready(res)
    return n * iters / (time.perf_counter() - t0) / 1e6, res.tables


def commit_bench(args, iters: int = 10) -> dict:
    """Control-plane commit latency at the policy-churn regime
    (reference tests/policy/perf/gen-policy.py: 1000-CIDR x 20-port
    sets). Measures a full global-table commit (pack + bit-plane
    compile + upload + swap) and a CNI-style commit (route+interface
    only) that must NOT re-upload the rule planes.

    Runs on its OWN dataplane: the throughput loop donates its tables
    into the jit, which would invalidate the upload cache a subsequent
    swap relies on (tables.py to_device docstring)."""
    import jax

    n_rules = args.rules
    dp, _ = build_dataplane(n_rules, 4)
    # rule-set generation is not commit work: pre-build the churn
    # sequence outside the clock. Each iteration changes ONE policy's
    # worth of rules (~32 rows at a moving offset) — the reference's
    # policy-churn regime, where an ACL replace is an incremental
    # update, not a from-scratch table build
    # (acl_renderer.go:124-264). The first full-table commit (the
    # resync case) is reported separately.
    from vpp_tpu.ir.rule import ContivRule as _CR

    def shift_ports(rules, delta):
        return [
            _CR(action=r.action, src_network=r.src_network,
                protocol=r.protocol,
                dest_port=(r.dest_port + delta
                           if 0 < r.dest_port < 65000 else r.dest_port))
            for r in rules
        ]

    base_rules = build_rules(n_rules)
    # full-upload case: EVERY row differs from the already-committed
    # table (build_dataplane committed base_rules), so the incremental
    # path must fall back to the full device upload — the resync case
    full_rules = shift_ports(base_rules, 7)
    churn = min(32, n_rules)
    rule_sets = []
    rules = list(full_rules)
    for i in range(iters):
        off = (i * 977) % max(1, n_rules - churn + 1)
        for j in range(churn):
            r = rules[off + j]
            rules[off + j] = _CR(action=r.action,
                                 src_network=r.src_network,
                                 protocol=r.protocol,
                                 dest_port=9000 + i)
        rule_sets.append(list(rules))
    out = {"commit_rules": n_rules}
    # reset the incremental diff base so this measurement is the FULL
    # device upload by construction (at some rule counts the changed
    # span fits a block ladder width and would otherwise scatter)
    dp.builder._glb_prev = None
    t0 = time.perf_counter()
    with dp.commit_lock:
        dp.builder.set_global_table(full_rules)
        dp.swap()
    jax.block_until_ready(dp.tables.glb_mxu_coeff)
    out["commit_ms_global_full"] = round(
        (time.perf_counter() - t0) * 1e3, 2
    )
    # warm the incremental-update program (one-time jit, not commit work)
    with dp.commit_lock:
        dp.builder.set_global_table(rule_sets[0])
        dp.swap()
    jax.block_until_ready(dp.tables.glb_mxu_coeff)
    t0 = time.perf_counter()
    for rules in rule_sets[1:]:
        with dp.commit_lock:
            dp.builder.set_global_table(rules)
            dp.swap()
    jax.block_until_ready(dp.tables.glb_mxu_coeff)
    out["commit_ms_global_table"] = round(
        (time.perf_counter() - t0) / max(1, iters - 1) * 1e3, 2
    )
    from vpp_tpu.pipeline.vector import Disposition

    t0 = time.perf_counter()
    for i in range(iters):
        with dp.commit_lock:
            dp.builder.add_route(f"10.1.9.{i + 1}/32", 2,
                                 Disposition.LOCAL)
            dp.swap()
    jax.block_until_ready(dp.tables.fib_prefix)
    out["commit_ms_cni_route"] = round(
        (time.perf_counter() - t0) / iters * 1e3, 2
    )
    return out


def acl_classifier_bench(args, batch: int = 2048, iters: int = 20) -> dict:
    """Classifier shoot-out (ISSUE 4 tentpole): dense vs MXU vs BV
    global classify in isolation at 1,024 and the headline rule count,
    order-alternated medians like the ``sess_election_*`` pattern (a
    fixed order biased those r4 numbers by warmup/cache state). Each
    round re-validates the ``classifier: auto`` default with evidence:

      * ``acl_classifier_selected``      — what auto picked at the
        headline count on THIS backend
      * ``acl_classify_{dense,mxu,bv}_ns_pkt`` (+ ``_1k`` variants)
      * ``acl_bv_build_ms``              — commit-time structure build
      * ``acl_classifier_speedup_bv_vs_dense`` (acceptance: >= 5x at
        10,240 rules on the CPU harness)
    """
    import jax
    import jax.numpy as jnp

    from vpp_tpu.pipeline.graph import _classifier_fns

    out = {}
    for n_rules in sorted({1024, args.rules}):
        suffix = "" if n_rules == args.rules else "_1k"
        dp, uplink = build_dataplane(n_rules, 4)
        pkts = build_traffic(batch, uplink, seed=17)
        if n_rules == args.rules:
            out["acl_classifier_selected"] = dp.classifier_impl
            out["acl_classifier_rules"] = n_rules
        if dp.builder.bv_enabled:
            out[f"acl_bv_build_ms{suffix}"] = round(
                dp.builder.bv_build_ms, 2)
        impls = ["dense", "bv"] if dp.builder.bv_enabled else ["dense"]
        if dp.builder.mxu_enabled and dp.builder.glb_mxu.ok:
            impls.insert(1, "mxu")
        fns = {}
        for impl in impls:
            fns[impl] = jax.jit(_classifier_fns(impl)[0])
            jax.block_until_ready(fns[impl](dp.tables, pkts).permit)
        acc = {impl: [] for impl in impls}
        for rep in range(3):
            order = impls if rep % 2 == 0 else impls[::-1]
            for impl in order:
                t0 = time.perf_counter()
                for _ in range(iters):
                    v = fns[impl](dp.tables, pkts)
                jax.block_until_ready(v.permit)
                acc[impl].append(
                    (time.perf_counter() - t0) / iters / batch * 1e9)
        for impl, vals in acc.items():
            out[f"acl_classify_{impl}_ns_pkt{suffix}"] = round(
                float(np.median(vals)), 1)
        if n_rules == args.rules:
            # fold the probe time into the observability twin of this
            # measurement (vpp_tpu_pump_stage_seconds{stage="classify"})
            try:
                dp.time_classifier(batch=min(batch, 256), iters=4)
            except Exception:  # noqa: BLE001 — diagnostic only
                pass
    dense = out.get("acl_classify_dense_ns_pkt")
    bv = out.get("acl_classify_bv_ns_pkt")
    if dense and bv:
        out["acl_classifier_speedup_bv_vs_dense"] = round(dense / bv, 2)
    return out


def fib_bench(args, batch: int = 2048, iters: int = 12) -> dict:
    """Million-route LPM FIB capture (ISSUE 15 tentpole).

    Builds a BGP-shaped route table at 1M prefixes (memory-guarded
    downshift like snapshot_bench), validates the ``fib_impl: auto``
    ladder picked LPM, and measures:

      * ``fib_lookup_lpm_ns_pkt``    — LPM lookup at the full table
        (acceptance: within 2x of the small-table dense lookup at its
        native scale on real accelerators; the 1-core CPU harness
        measures ~4-6x because dense@64 is L1-resident while 1M-route
        probes end in cold DRAM — docs/LATENCY.md round 15)
      * ``fib_lookup_dense_ns_pkt``  — dense at its NATIVE node scale
        (64 routes — what the seed-era FIB actually served)
      * ``fib_lookup_dense_1m_ns_pkt_extrapolated`` — dense cost fit
        over two mid scales and extrapolated to the route count (the
        dense [P, F] compare cannot even be ALLOCATED at 1M:
        2048 x 1M bools is ~8 GB — which is the point); acceptance:
        LPM >= 10x faster than this
      * ``fib_build_ms`` / ``fib_churn_commit_ms`` — full staging+
        upload cost, and ONE /24 flap's commit: must re-ship only the
        touched length plane + the count vector + a bounded slot blob
        (``fib_churn_planes``/``fib_churn_bytes`` pin it)
      * ``fib_ecmp_spread_pct``      — min/max member share over an
        8-way group under hashed flows (the session hash family)
    """
    import jax
    import jax.numpy as jnp

    from vpp_tpu.ops.fib import fib_lookup_dense
    from vpp_tpu.ops.lpm import fib_lookup_lpm, lpm_plane_bytes
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import (
        FLAG_VALID,
        Disposition,
        PacketVector,
        ip4,
    )

    out = {}
    rng = np.random.default_rng(15)
    routes = 1 << 20
    avail = _mem_available_bytes()
    # per-slot columns + planes + host staging + diff base ~ 60 B/route
    # x a 4x safety factor; small boxes downshift instead of OOMing
    while routes > (1 << 16) and avail and routes * 240 > avail:
        routes //= 4
    out["fib_routes"] = routes

    # BGP-shaped length mix (fractions of the feed)
    mix = ((24, 0.55), (23, 0.10), (22, 0.08), (20, 0.07), (19, 0.05),
           (16, 0.06), (21, 0.04), (18, 0.03), (32, 0.015), (8, 0.005))

    def uniq_prefixes(plen, n):
        """n distinct pre-masked networks of one length."""
        shift = 32 - plen
        want = rng.integers(0, 1 << min(plen, 62), int(n * 1.15) + 8,
                            dtype=np.int64)
        want = np.unique(want)[:n]
        return (want.astype(np.uint64) << shift).astype(np.uint32)

    nets, plens = [], []
    left = routes - 1   # one /0 default staged separately
    for plen, frac in mix:
        n = min(int(routes * frac), left)
        if n <= 0:
            continue
        p = uniq_prefixes(plen, n)
        nets.append(p)
        plens.append(np.full(len(p), plen, np.int32))
        left -= len(p)
    if left > 0:  # remainder lands on /24
        p = uniq_prefixes(24, left)
        nets.append(p)
        plens.append(np.full(len(p), 24, np.int32))
    nets = np.concatenate(nets)
    plens = np.concatenate(plens)
    counts = np.bincount(plens, minlength=33)
    counts[0] += 1    # the default route
    counts[25] += 1   # the ECMP capture route (a length the random
    #                   feed never uses, so it can't be shadowed by an
    #                   equal-length duplicate)
    caps = [0] * 33
    for L in range(33):
        if counts[L]:
            caps[L] = int(counts[L] + 64)
    config = DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=16,
        fib_slots=len(nets) + 16, sess_slots=256, nat_mappings=1,
        nat_backends=1, fib_impl="auto", fib_lpm_min_routes=256,
        fib_lpm_mem_mb=512, fib_lpm_plen_caps=tuple(caps),
        fib_ecmp_groups=8, fib_ecmp_ways=8)
    t0 = time.perf_counter()
    dp = Dataplane(config)
    uplink = dp.add_uplink()
    dp.builder.set_nh_group(0, [(ip4("192.168.0.2") + i, uplink, i % 4)
                                for i in range(8)])
    dp.builder.add_routes_np(
        nets, plens, tx_if=np.full(len(nets), uplink, np.int32),
        disp=np.full(len(nets), int(Disposition.REMOTE), np.int32),
        node_id=1)
    dp.builder.add_route("0.0.0.0/0", uplink, Disposition.REMOTE,
                         slot=len(nets), node_id=1)
    # the ECMP spread capture rides a dedicated /25 (longest match
    # beats any feed /8../24 cover; the feed never stages /25s)
    dp.builder.add_route("230.77.0.0/25", uplink, Disposition.REMOTE,
                         slot=len(nets) + 1, group=0)
    dp.swap()
    out["fib_build_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    out["fib_impl_selected"] = dp.fib_impl
    out["fib_plane_mb"] = round(lpm_plane_bytes(config) / (1 << 20), 2)

    def traffic(n, inside_frac=0.7, seed=16):
        r2 = np.random.default_rng(seed)
        dst = r2.integers(0, 1 << 32, n).astype(np.uint32)
        picks = r2.integers(0, len(nets), n)
        host = r2.integers(0, 1 << 32, n).astype(np.uint32)
        masks = np.array([((1 << 32) - 1) ^ ((1 << (32 - p)) - 1)
                          if p else 0 for p in range(33)],
                         np.uint32)[plens[picks]]
        inside = nets[picks] | (host & ~masks)
        dst = np.where(r2.random(n) < inside_frac, inside, dst)
        return PacketVector(
            src_ip=jnp.asarray(r2.integers(0, 1 << 32, n)
                               .astype(np.uint32)),
            dst_ip=jnp.asarray(dst),
            proto=jnp.full((n,), 6, jnp.int32),
            sport=jnp.asarray(r2.integers(1024, 65000, n)
                              .astype(np.int32)),
            dport=jnp.full((n,), 443, jnp.int32),
            ttl=jnp.full((n,), 64, jnp.int32),
            pkt_len=jnp.full((n,), 512, jnp.int32),
            rx_if=jnp.full((n,), uplink, jnp.int32),
            flags=jnp.full((n,), FLAG_VALID, jnp.int32),
        )

    def time_lookup(fn, tables, pkts):
        jfn = jax.jit(fn)
        jax.block_until_ready(jfn(tables, pkts).tx_if)
        ts = []
        for _ in range(iters):
            t1 = time.perf_counter()
            r = jfn(tables, pkts)
            jax.block_until_ready(r.tx_if)
            ts.append(time.perf_counter() - t1)
        n = int(pkts.dst_ip.shape[0])
        return float(np.median(ts)) / n * 1e9

    pkts = traffic(batch)
    out["fib_lookup_lpm_ns_pkt"] = round(
        time_lookup(fib_lookup_lpm, dp.tables, pkts), 1)

    def dense_at(n_routes, dense_batch):
        cfg = DataplaneConfig(
            max_tables=2, max_rules=8, max_global_rules=8,
            max_ifaces=16, fib_slots=n_routes + 4, sess_slots=64,
            nat_mappings=1, nat_backends=1, fib_impl="dense")
        d = Dataplane(cfg)
        up = d.add_uplink()
        k = min(n_routes, len(nets))
        d.builder.add_routes_np(
            nets[:k], plens[:k],
            tx_if=np.full(k, up, np.int32),
            disp=np.full(k, int(Disposition.REMOTE), np.int32))
        d.builder.add_route("0.0.0.0/0", up, Disposition.REMOTE,
                            slot=k)
        d.swap()
        return time_lookup(fib_lookup_dense, d.tables,
                           traffic(dense_batch))

    # native node scale: the seed-era FIB regime (tens of entries)
    out["fib_lookup_dense_ns_pkt"] = round(dense_at(64, batch), 1)
    # linear fit over two mid scales -> extrapolated 1M cost (the
    # [P, F] hit matrix makes a direct 1M dense run unallocatable)
    f1, f2 = 2048, 8192
    n1 = dense_at(f1, 256)
    n2 = dense_at(f2, 256)
    out["fib_lookup_dense_mid_ns_pkt"] = round(n2, 1)
    slope = max((n2 - n1) / (f2 - f1), 0.0)
    extrap = n2 + slope * (routes - f2)
    out["fib_lookup_dense_1m_ns_pkt_extrapolated"] = round(extrap, 1)
    out["fib_lpm_speedup_vs_dense_1m"] = round(
        extrap / max(out["fib_lookup_lpm_ns_pkt"], 1e-9), 1)
    out["fib_lpm_vs_dense_native_x"] = round(
        out["fib_lookup_lpm_ns_pkt"]
        / max(out["fib_lookup_dense_ns_pkt"], 1e-9), 2)

    # --- route churn: ONE /24 flap's commit cost + what it shipped ---
    slot = int(np.nonzero(plens == 24)[0][0])
    pfx = int(nets[slot])
    pfx_s = (f"{pfx >> 24 & 255}.{pfx >> 16 & 255}."
             f"{pfx >> 8 & 255}.{pfx & 255}/24")
    t1 = time.perf_counter()
    dp.builder.del_route(pfx_s)
    dp.builder.add_route(pfx_s, uplink, Disposition.REMOTE, slot=slot,
                         node_id=1)
    dp.swap()
    out["fib_churn_swap_ms"] = round(
        (time.perf_counter() - t1) * 1e3, 2)
    up = dp.builder.fib_upload
    out["fib_churn_commit_ms"] = round(float(up.get("ms", 0.0)), 2)
    out["fib_churn_bytes"] = int(up.get("bytes", 0))
    out["fib_churn_planes"] = sum(
        1 for f in up.get("fields", ()) if f.startswith("fib_lpm_p"))
    out["fib_churn_blob_bytes"] = int(up.get("blob_bytes", 0))

    # --- ECMP spread over the 8-member group (hashed distinct flows) --
    r3 = np.random.default_rng(18)
    n = 4096
    epkts = PacketVector(
        src_ip=jnp.asarray(r3.integers(0, 1 << 32, n)
                           .astype(np.uint32)),
        dst_ip=jnp.asarray((np.uint32(ip4("230.77.0.0"))
                            | r3.integers(0, 128, n)
                            .astype(np.uint32))),
        proto=jnp.full((n,), 6, jnp.int32),
        sport=jnp.asarray(r3.integers(1024, 65000, n)
                          .astype(np.int32)),
        dport=jnp.full((n,), 443, jnp.int32),
        ttl=jnp.full((n,), 64, jnp.int32),
        pkt_len=jnp.full((n,), 512, jnp.int32),
        rx_if=jnp.full((n,), uplink, jnp.int32),
        flags=jnp.full((n,), FLAG_VALID, jnp.int32),
    )
    res = jax.jit(fib_lookup_lpm)(dp.tables, epkts)
    on_grp = np.asarray(res.grp) >= 0
    nh = np.asarray(res.next_hop)[on_grp].astype(np.int64)
    shares = np.bincount(nh - nh.min(), minlength=8)
    shares = np.sort(shares[shares > 0])
    out["fib_ecmp_members_hit"] = int(len(shares))
    out["fib_ecmp_spread_pct"] = round(
        100.0 * float(shares[0]) / max(float(shares[-1]), 1.0), 1)
    return out


def fastpath_bench(args, iters: int = 12, batch: int = 2048) -> dict:
    """Two-tier fast path (ISSUE 3 tentpole): the classify-free
    established-flow kernel vs the full fused chain on an IDENTICAL
    all-established batch, at the headline rule count.

    Primes sessions with one full-chain pass over forward traffic,
    builds the reply batch from the POST-NAT forwarded outputs (what
    the wire would actually carry back), verifies the auto dispatcher
    takes the fast kernel (StepStats.fastpath == 1), then times both
    tiers on fixed tables/now. Reports:

      * ``pipeline_fastpath_us``  — auto-dispatched (fast) step, median
      * ``pipeline_fullpath_us``  — always-full-chain step, median
      * ``fastpath_speedup_x``    — full/fast (acceptance: >= 3x)
    """
    import jax
    import jax.numpy as jnp

    from vpp_tpu.pipeline.graph import make_pipeline_step
    from vpp_tpu.pipeline.vector import Disposition, FLAG_VALID, PacketVector

    dp, uplink = build_dataplane(args.rules, 4)
    # mirror the dataplane's own kernel selection (classifier impl +
    # local-skip gate) so the comparison is the DEPLOYED full chain vs
    # the deployed fast tier
    impl, skip = dp.classifier_impl, dp._skip_local
    step_full = jax.jit(make_pipeline_step(impl, skip, fast=False))
    step_auto = jax.jit(make_pipeline_step(impl, skip, fast=True))

    fwd = build_traffic(batch, uplink, seed=21)
    r1 = step_full(dp.tables, fwd, jnp.int32(1))
    jax.block_until_ready(r1.disp)
    tables = r1.tables
    # replies of every forwarded packet: swap the post-NAT endpoints,
    # ingress on the egress interface (rx_if 0 placeholder on the
    # non-forwarded slots, which are marked invalid)
    fwd_ok = np.asarray(r1.disp) != int(Disposition.DROP)
    pk = r1.pkts
    reply = PacketVector(
        src_ip=jnp.asarray(np.asarray(pk.dst_ip)),
        dst_ip=jnp.asarray(np.asarray(pk.src_ip)),
        proto=pk.proto,
        sport=jnp.asarray(np.asarray(pk.dport)),
        dport=jnp.asarray(np.asarray(pk.sport)),
        ttl=jnp.full((batch,), 64, jnp.int32),
        pkt_len=pk.pkt_len,
        rx_if=jnp.asarray(
            np.where(fwd_ok, np.asarray(r1.tx_if), 0).astype(np.int32)
        ),
        flags=jnp.asarray(
            np.where(fwd_ok, FLAG_VALID, 0).astype(np.int32)
        ),
    )
    out = {"fastpath_batch": batch, "fastpath_rules": args.rules}
    probe = step_auto(tables, reply, jnp.int32(2))
    jax.block_until_ready(probe.disp)
    out["fastpath_engaged"] = bool(int(probe.stats.fastpath) == 1)
    out["fastpath_hit_pkts"] = int(probe.stats.sess_hits)

    def med_us(step):
        jax.block_until_ready(step(tables, reply, jnp.int32(2)).disp)
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(step(tables, reply, jnp.int32(2)).disp)
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)) * 1e6

    full_us = med_us(step_full)
    fast_us = med_us(step_auto)
    out["pipeline_fullpath_us"] = round(full_us, 1)
    out["pipeline_fastpath_us"] = round(fast_us, 1)
    out["fastpath_speedup_x"] = round(full_us / max(fast_us, 1e-9), 2)
    return out


def ml_stage_bench(args, iters: int = 12, batch: int = 2048) -> dict:
    """Per-packet ML scoring stage (ISSUE 10 tentpole): the ADDED cost
    of int8 MLP inference riding inside the fused step, at the
    headline rule count.

    Compiles the deployed chain twice — ml_mode off vs score (same
    classifier impl/local-skip selection, same tables: the glb_ml_*
    planes are staged either way, the off variant just never reads
    them) — and reports the delta. The stage rides INSIDE the one
    jitted program (no extra dispatch), so the delta IS the marginal
    matmul cost. Keys:

      * ``ml_stage_ns_pkt``           — (t_score − t_off)/batch
      * ``ml_headline_overhead_pct``  — 100·(t_score − t_off)/t_off
                                        (acceptance: < 10)
      * ``ml_enforce_overhead_pct``   — enforce-mode delta (the
                                        verdict fold's extra cost)
      * ``ml_swap_zero_reship``       — 1 when an ACL-only epoch swap
                                        reuses the staged model's
                                        device arrays by identity
                                        (acceptance: 1)
    """
    import jax
    import jax.numpy as jnp

    from vpp_tpu.ml.train import train_and_pack
    from vpp_tpu.pipeline.graph import make_pipeline_step

    dp, uplink = build_dataplane(args.rules, 4, ml_stage="score")
    model, report = train_and_pack(kind="mlp", hidden=16,
                                   samples=2048, action="drop")
    with dp.commit_lock:
        dp.builder.set_ml_model(model)
        dp.swap()
    out = {
        "ml_stage_batch": batch, "ml_stage_rules": args.rules,
        "ml_stage_kind": model.kind, "ml_stage_hidden": model.hidden,
        "ml_train_accuracy": round(report["accuracy"], 4),
    }
    impl, skip = dp.classifier_impl, dp._skip_local
    steps = {
        mode: jax.jit(make_pipeline_step(impl, skip, ml_mode=mode))
        for mode in ("off", "score", "enforce")
    }
    pkts = build_traffic(batch, uplink, seed=33)
    tables = dp.tables

    def med_us(step):
        jax.block_until_ready(step(tables, pkts, jnp.int32(2)).disp)
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(step(tables, pkts, jnp.int32(2)).disp)
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)) * 1e6

    t_off = med_us(steps["off"])
    t_score = med_us(steps["score"])
    t_enforce = med_us(steps["enforce"])
    probe = steps["score"](tables, pkts, jnp.int32(2))
    out["ml_stage_scored"] = int(probe.stats.ml_scored)
    out["ml_stage_flagged_pct"] = round(
        100.0 * int(probe.stats.ml_flagged)
        / max(int(probe.stats.ml_scored), 1), 2)
    out["ml_fullpath_us"] = round(t_off, 1)
    out["ml_scorepath_us"] = round(t_score, 1)
    out["ml_stage_ns_pkt"] = round(
        max(t_score - t_off, 0.0) / batch * 1e3, 2)
    out["ml_headline_overhead_pct"] = round(
        100.0 * (t_score - t_off) / max(t_off, 1e-9), 2)
    out["ml_enforce_overhead_pct"] = round(
        100.0 * (t_enforce - t_off) / max(t_off, 1e-9), 2)
    # model epoch-swap plane reuse: an ACL-only churn must NOT re-ship
    # the model group — the cached device arrays carry over by identity
    ml_plane_before = dp.tables.glb_ml_w1
    with dp.commit_lock:
        dp.builder.set_global_table(build_rules(max(args.rules // 2, 2)))
        dp.swap()
    out["ml_swap_zero_reship"] = int(
        dp.tables.glb_ml_w1 is ml_plane_before)
    return out


def latency_telemetry_bench(args, iters: int = 12,
                            batch: int = 2048) -> dict:
    """Device telemetry plane (ISSUE 11 tentpole): the cost of the
    in-step wire-latency histogram + flow sketch, and the dataset the
    adaptive latency governor (ROADMAP item 3) will close its loop on.

    Three captures:

      * **overhead** — the fused chain compiled with telemetry off vs
        full over the same tables/traffic; the delta IS the marginal
        scatter-add/compare cost (``telemetry_overhead_pct``,
        acceptance: < 5).
      * **offered load vs on-device tail** — an open-loop sweep: each
        packed batch is stamped with its scheduled GENERATION time and
        paced at 50/80/95% of the measured service rate; the device
        histograms ``dispatch − stamp``, so queueing delay shows up in
        the on-device p99/p99.9 exactly as it would for a governor
        (``latency_telemetry_sweep`` + the headline
        ``wire_latency_{p50,p99,p999}_us_device`` from the top rung).
      * **sketch fidelity** — a Zipf flow mix through a fresh sketch;
        count-min estimates vs exact host counts
        (``flow_sketch_error_pct`` = aggregate overcount share) and
        the top-K candidate table's recall of the true heavy hitters
        (``flow_topk_recall``).
    """
    import jax
    import jax.numpy as jnp

    from vpp_tpu.ops.telemetry import (
        quantiles_from_bins,
        sketch_cols,
        tel_clock_us,
        tel_flow_hash_np,
    )
    from vpp_tpu.pipeline.dataplane import (
        pack_packet_columns,
        packed_input_zeros,
    )
    from vpp_tpu.pipeline.vector import FLAG_VALID, PacketVector, ip4

    out = {"latency_telemetry_batch": batch,
           "latency_telemetry_rules": args.rules}

    # --- (1) overhead: off vs full over the PACKED boundary ---
    # Timed on process_packed, not the plain step: the wire-latency
    # histogram update lives in the packed/chained/ring boundary
    # wrappers (dataplane._packed_call), so a plain-step delta would
    # structurally exclude it and only measure the sketch fold. The
    # packed delta is the telemetry cost the pump actually pays.
    dp_off, _up_off = build_dataplane(args.rules, 4, telemetry="off")
    dp, uplink = build_dataplane(args.rules, 4, telemetry="full")
    pkts = build_traffic(batch, uplink, seed=41)
    cols = {f: np.asarray(getattr(pkts, f))
            for f in ("src_ip", "dst_ip", "proto", "sport", "dport",
                      "ttl", "pkt_len", "rx_if", "flags")}
    flat = packed_input_zeros(batch)
    pack_packet_columns(flat.view(np.uint32), cols, batch)

    # interleaved windows, per-mode MINIMUM of window medians (the
    # session-bench honest estimator: sequential medians drift with
    # box load and can even read negative) — off-mode dataplanes
    # ignore the stamp kwargs, so one call shape serves both sides
    for d in (dp_off, dp):
        jax.block_until_ready(d.process_packed(flat, now=2,
                                               stamp_us=7, now_us=9))
    best = {"off": float("inf"), "full": float("inf")}
    for _w in range(max(iters // 2, 3)):
        for mode, d in (("off", dp_off), ("full", dp)):
            ts = []
            for _ in range(4):
                t0 = time.perf_counter()
                jax.block_until_ready(d.process_packed(
                    flat, now=3, stamp_us=7, now_us=9))
                ts.append(time.perf_counter() - t0)
            best[mode] = min(best[mode], float(np.median(ts)))
    t_off = best["off"] * 1e6
    t_full = best["full"] * 1e6
    out["telemetry_fullpath_us"] = round(t_off, 1)
    out["telemetry_telpath_us"] = round(t_full, 1)
    out["telemetry_ns_pkt"] = round(
        max(t_full - t_off, 0.0) / batch * 1e3, 2)
    out["telemetry_overhead_pct"] = round(
        100.0 * (t_full - t_off) / max(t_off, 1e-9), 2)

    # --- (2) open-loop offered-load sweep on the packed path ---
    service_us = max(t_full, 1.0)
    out["telemetry_service_us"] = round(service_us, 1)

    def run_rung(load_pct: int, rounds: int = 40) -> dict:
        before = dp.telemetry_snapshot()["bins"].copy()
        interarrival = service_us * 100.0 / load_pct
        g = float(tel_clock_us()) + 2 * interarrival
        for _ in range(rounds):
            # clamp the pace wait: a tel_clock_us() 31-bit wrap
            # mid-rung would otherwise compute a ~2^31 µs sleep and
            # hang the bench for half an hour (the device side already
            # discards wrap-spanning samples as negative latency)
            wait_us = min(g - tel_clock_us(), 5 * interarrival)
            if wait_us > 0:
                time.sleep(wait_us / 1e6)
            jax.block_until_ready(dp.process_packed(
                flat, now=4, stamp_us=int(g) & 0x7FFFFFFF))
            g += interarrival
        bins = dp.telemetry_snapshot()["bins"] - before
        p50, p99, p999 = quantiles_from_bins(bins)
        return {"load_pct": load_pct, "p50_us": round(p50, 1),
                "p99_us": round(p99, 1), "p999_us": round(p999, 1),
                "observed": int(bins.sum())}

    sweep = [run_rung(pct) for pct in (50, 80, 95)]
    out["latency_telemetry_sweep"] = sweep
    top = sweep[-1]
    out["wire_latency_p50_us_device"] = top["p50_us"]
    out["wire_latency_p99_us_device"] = top["p99_us"]
    out["wire_latency_p999_us_device"] = top["p999_us"]
    _progress(telemetry_overhead_pct=out["telemetry_overhead_pct"],
              wire_latency_p99_us_device=top["p99_us"])

    # --- (3) sketch fidelity on a FRESH sketch (small dataplane) ---
    dp3, up3 = build_dataplane(64, 2, telemetry="full")
    rng = np.random.default_rng(17)
    n_flows, rounds, b3 = 512, 40, 512
    ranks = np.arange(1, n_flows + 1, dtype=np.float64)
    probs = ranks ** -1.2
    probs /= probs.sum()
    true = np.zeros(n_flows, np.int64)
    base_src = ip4("198.18.0.0")
    dst = ip4("10.1.1.9")
    for r in range(rounds):
        ids = rng.choice(n_flows, b3, p=probs)
        np.add.at(true, ids, 1)
        pv = PacketVector(
            src_ip=jnp.asarray((base_src + ids).astype(np.uint32)),
            dst_ip=jnp.full((b3,), dst, jnp.uint32),
            proto=jnp.full((b3,), 6, jnp.int32),
            sport=jnp.asarray((1024 + ids).astype(np.int32)),
            dport=jnp.full((b3,), 8080, jnp.int32),
            ttl=jnp.full((b3,), 64, jnp.int32),
            pkt_len=jnp.full((b3,), 128, jnp.int32),
            rx_if=jnp.full((b3,), up3, jnp.int32),
            flags=jnp.full((b3,), FLAG_VALID, jnp.int32),
        )
        dp3.process(pv, now=2 + r)
    snap = dp3.telemetry_snapshot()
    sk = np.asarray(dp3.tables.tel_sketch)
    d, w = sk.shape
    ids = np.arange(n_flows)
    h0 = tel_flow_hash_np(
        (base_src + ids).astype(np.uint32),
        np.full(n_flows, dst, np.uint32), 1024 + ids,
        np.full(n_flows, 8080), np.full(n_flows, 6))
    est = np.min(np.stack(
        [sk[r_, sketch_cols(h0, r_, w)] for r_ in range(d)]), axis=0)
    over = est.astype(np.int64) - true
    out["flow_sketch_overcount_max"] = int(over.max())
    out["flow_sketch_error_pct"] = round(
        100.0 * float(over.sum()) / max(float(true.sum()), 1.0), 3)
    k = len(snap["top_key"])
    top_true = set(h0[np.argsort(-true)[:k]].tolist())
    out["flow_topk_recall"] = round(
        len(top_true & set(snap["top_key"].tolist())) / k, 3)
    _progress(flow_sketch_error_pct=out["flow_sketch_error_pct"],
              flow_topk_recall=out["flow_topk_recall"])
    return out


def latency_slo_bench(args, frame_pkts: int = 16,
                      rung_s: float = 1.2) -> dict:
    """Reflex-plane latency governor ladder (ISSUE 13 tentpole;
    ROADMAP item 3's bench keys). The ring-to-ring wire path under a
    mixed load — bulk UDP frames plus a paced priority lane (dport
    9999) — swept at 50/80/95/120% of the measured saturation rate,
    once UNGOVERNED (the open-loop pre-13 pump) and once GOVERNED
    (``latency_slo_us`` = 2x the lone-frame floor), plus a square-wave
    burst scenario for tail amplification. Headline keys:

      * ``latency_slo_p50/p99/p999_us`` — the governed PRIORITY lane
        at the 95% rung (acceptance: p99 within 2x of
        ``latency_slo_floor_us`` while
        ``latency_slo_goodput_ratio`` >= 0.9);
      * ``latency_slo_shed_pct`` — attributed overload shedding at
        the 120% rung (the SLO-unattainable regime — bulk drops are
        explicit ``drops_overload``, never silent queue growth);
      * ``latency_slo_burst_p99_us_{governed,ungoverned}`` — the
        priority tail under a square-wave offered load;
      * ``latency_slo_io_callbacks`` / ``latency_slo_new_step_variants``
        — the governor must keep the ring io_callback-free and trace
        ZERO new jitted step variants (host-side shaping only).
    """
    import collections
    import threading

    from vpp_tpu.io.governor import LatencyGovernor, PriorityFilter
    from vpp_tpu.io.pump import DataplanePump
    from vpp_tpu.io.rings import IORingPair
    from vpp_tpu.native.pktio import PacketCodec
    from vpp_tpu.pipeline.dataplane import jit_compile_totals
    from vpp_tpu.pipeline.vector import VEC

    dp = build_fwd_dataplane()
    client_if = dp.pod_if[("default", "p0")]
    bulk_wire = [wire_udp(i) for i in range(frame_pkts)]
    pri_wire = [wire_udp(7, dport=9999)]  # 1-pkt reflex frame

    def capture(bulk_fps, pri_fps, duration, slo_us=0,
                square=None) -> dict:
        """One pump lifecycle: paced bulk + priority producers,
        sequence-stamped ring-to-ring latency per frame, split by
        lane. ``square=(hi_fps, lo_fps, half_s)`` overrides bulk
        pacing with a square wave."""
        rings = IORingPair(n_slots=256, snap=512)
        codec = PacketCodec(snap=rings.rx.snap)
        scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        gov = None
        if slo_us > 0:
            gov = LatencyGovernor(slo_us, tick_s=0.01,
                                  brownout_ticks=2, recover_ticks=3)
        pump = DataplanePump(dp, rings, mode="persistent",
                             governor=gov,
                             priority=PriorityFilter(ports=(9999,)))
        pump.warm()
        pump.start()
        push_log = {}   # seq -> (t_push, is_pri, n_pkts)
        lat = collections.defaultdict(list)   # lane -> [seconds]
        counts = {"offered_bulk": 0, "offered_pri": 0,
                  "delivered_bulk": 0, "delivered_pri": 0,
                  "pushed_fail": 0}
        seq_box = [0]
        stop = threading.Event()

        def push(wire, is_pri) -> None:
            cols, n = codec.parse(wire, client_if, scratch)
            seq = seq_box[0]
            cols["meta"][:n] = seq
            t = time.perf_counter()
            if rings.rx.push(cols, n, payload=scratch):
                push_log[seq] = (t, is_pri, n)
                seq_box[0] += 1
                counts["offered_pri" if is_pri else "offered_bulk"] += n
            else:
                counts["pushed_fail"] += 1

        def producer() -> None:
            t0 = time.perf_counter()
            bulk_credit = pri_credit = 0.0
            last = t0
            while not stop.is_set():
                now = time.perf_counter()
                dt, last = now - last, now
                fps = bulk_fps
                if square is not None:
                    hi, lo, half = square
                    fps = hi if int((now - t0) / half) % 2 == 0 else lo
                bulk_credit = min(bulk_credit + fps * dt, 64.0)
                pri_credit = min(pri_credit + pri_fps * dt, 8.0)
                while pri_credit >= 1.0:
                    push(pri_wire, True)
                    pri_credit -= 1.0
                while bulk_credit >= 1.0:
                    push(bulk_wire, False)
                    bulk_credit -= 1.0
                time.sleep(0.001)

        def drain_one() -> bool:
            g = rings.tx.peek()
            if g is None:
                return False
            seq = int(g.cols["meta"][0])
            rings.tx.release()
            rec = push_log.pop(seq, None)
            if rec is not None:
                t_push, is_pri, n = rec
                lat["pri" if is_pri else "bulk"].append(
                    time.perf_counter() - t_push)
                counts["delivered_pri" if is_pri
                       else "delivered_bulk"] += n
            return True

        prod = threading.Thread(target=producer, daemon=True)
        t_start = time.perf_counter()
        prod.start()
        while time.perf_counter() < t_start + duration:
            if not drain_one():
                time.sleep(0.0002)
        stop.set()
        prod.join()
        # bounded flush: shed frames never reach tx, so idle silence
        # (not an empty push_log) ends the drain
        idle_since = None
        flush_deadline = time.perf_counter() + 8.0
        while push_log and time.perf_counter() < flush_deadline:
            if drain_one():
                idle_since = None
                continue
            now = time.perf_counter()
            if idle_since is None:
                idle_since = now
            elif now - idle_since > 1.0:
                break
            time.sleep(0.002)
        elapsed = time.perf_counter() - t_start
        pump.stop()
        s = dict(pump.stats)
        rings.close()

        def pcts(xs):
            if not xs:
                return 0.0, 0.0, 0.0
            a = np.asarray(xs) * 1e6
            return (float(np.percentile(a, 50)),
                    float(np.percentile(a, 99)),
                    float(np.percentile(a, 99.9)))

        p50a, p99a, p999a = pcts(lat["pri"] + lat["bulk"])
        p50p, p99p, p999p = pcts(lat["pri"])
        offered = counts["offered_bulk"] + counts["offered_pri"]
        return {
            "p50_us": round(p50a, 1), "p99_us": round(p99a, 1),
            "p999_us": round(p999a, 1),
            "pri_p50_us": round(p50p, 1), "pri_p99_us": round(p99p, 1),
            "pri_p999_us": round(p999p, 1),
            "bulk_goodput_fps": round(
                len(lat["bulk"]) / max(elapsed, 1e-9), 1),
            "bulk_delivered_pkts": counts["delivered_bulk"],
            "offered_pkts": offered,
            "shed_pct": round(100.0 * int(s.get("drops_overload", 0))
                              / max(offered, 1), 2),
            "preempts": int(s.get("priority_preempts", 0)),
            "io_callbacks": int(s.get("io_callbacks", 0)),
            "mode": (gov.snapshot()["mode"] if gov is not None
                     else "off"),
            "frames_drained": len(lat["pri"]) + len(lat["bulk"]),
        }

    out = {"latency_slo_frame_pkts": frame_pkts}
    # (1) lone-frame floor: a paced priority-only trickle — the
    # latency the reflex lane is entitled to
    floor = capture(bulk_fps=0, pri_fps=50, duration=rung_s)
    floor_us = max(floor["pri_p50_us"], 1.0)
    out["latency_slo_floor_us"] = round(floor_us, 1)
    # every later capture must reuse the already-compiled ring
    # variants: the governor is host-side shaping ONLY
    jit_labels0 = set(jit_compile_totals())
    # (2) harness saturation rate (unpaced bulk)
    sat = capture(bulk_fps=1e9, pri_fps=0, duration=1.5)
    sat_fps = max(sat["bulk_goodput_fps"], 1.0)
    out["latency_slo_sat_fps"] = round(sat_fps, 1)
    slo_us = 2.0 * floor_us
    out["latency_slo_us"] = round(slo_us, 1)
    # (3) the offered-load ladder x {ungoverned, governed}
    ladder = []
    io_callbacks = 0
    for pct in (50, 80, 95, 120):
        for governed in (False, True):
            row = capture(bulk_fps=sat_fps * pct / 100.0, pri_fps=50,
                          duration=rung_s,
                          slo_us=slo_us if governed else 0)
            row["load_pct"] = pct
            row["governed"] = int(governed)
            io_callbacks += row.pop("io_callbacks")
            ladder.append(row)
    out["latency_slo_ladder"] = ladder

    def _row(pct, governed):
        return next(r for r in ladder
                    if r["load_pct"] == pct and r["governed"] == governed)

    g95, u95 = _row(95, 1), _row(95, 0)
    # all three headline quantiles are the PRIORITY lane's (the key
    # table's contract) — the combined distribution is bulk-dominated
    # at this rung and lives in the ladder rows as p*_us
    out["latency_slo_p50_us"] = g95["pri_p50_us"]
    out["latency_slo_p99_us"] = g95["pri_p99_us"]
    out["latency_slo_p999_us"] = g95["pri_p999_us"]
    out["latency_slo_p99_vs_floor_x"] = round(
        g95["pri_p99_us"] / max(floor_us, 1e-9), 2)
    out["latency_slo_p99_vs_ungoverned_x"] = round(
        u95["pri_p99_us"] / max(g95["pri_p99_us"], 1e-9), 2)
    out["latency_slo_goodput_ratio"] = round(
        g95["bulk_delivered_pkts"] / max(u95["bulk_delivered_pkts"], 1),
        3)
    out["latency_slo_shed_pct"] = _row(120, 1)["shed_pct"]
    out["latency_slo_ungoverned_p99_us"] = u95["p99_us"]
    # (4) tail amplification under burst: square-wave offered load
    # (130% / 10% of saturation), priority lane paced through it
    for governed in (False, True):
        row = capture(bulk_fps=0, pri_fps=50, duration=2.4,
                      slo_us=slo_us if governed else 0,
                      square=(sat_fps * 1.3, sat_fps * 0.1, 0.3))
        key = "governed" if governed else "ungoverned"
        out[f"latency_slo_burst_p99_us_{key}"] = row["pri_p99_us"]
        io_callbacks += row["io_callbacks"]
    out["latency_slo_burst_amplification_x"] = round(
        out["latency_slo_burst_p99_us_ungoverned"]
        / max(out["latency_slo_burst_p99_us_governed"], 1e-9), 2)
    out["latency_slo_io_callbacks"] = io_callbacks
    out["latency_slo_new_step_variants"] = len(
        set(jit_compile_totals()) - jit_labels0)
    _progress(latency_slo_p99_us=out["latency_slo_p99_us"],
              latency_slo_floor_us=out["latency_slo_floor_us"],
              latency_slo_goodput_ratio=out["latency_slo_goodput_ratio"],
              latency_slo_shed_pct=out["latency_slo_shed_pct"])
    return out


def tenant_isolation_bench(args, frame_pkts: int = 16,
                           phase_s: float = 1.0) -> dict:
    """Multi-tenant isolation scenario (ISSUE 14 acceptance;
    docs/TENANCY.md). Four tenants on the persistent wire path —
    device token buckets + capacity attribution + the pump's
    weighted-fair dequeue — with tenant 4 misbehaving at 4x its quota
    through a square-wave burst while tenants 1..3 stay inside
    theirs. Proof keys:

      * ``tenant_isolation_goodput_ratio_min`` — the worst
        well-behaved tenant's overload-phase goodput vs its SOLO run
        (acceptance: >= 0.9; one hog must not tax the rest);
      * ``tenant_isolation_p99_ratio_max`` — the worst well-behaved
        p99 amplification vs solo (acceptance: <= 2x);
      * ``tenant_isolation_attributed_pct`` — the misbehaving
        tenant's overage accounted as
        ``drops_total{reason="tenant_quota"}`` (device bucket) +
        per-tenant brownout sheds (``reason="overload"``) — nothing
        silent;
      * ``tenant_isolation_conserved`` — EXACT packet conservation
        over the whole overload phase:
        offered == goodput + tenant_quota + shed + shutdown/error.
    """
    import collections
    import threading

    from vpp_tpu.io.governor import LatencyGovernor
    from vpp_tpu.io.pump import DataplanePump
    from vpp_tpu.io.rings import IORingPair
    from vpp_tpu.native.pktio import PacketCodec
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import VEC, Disposition
    from vpp_tpu.tenancy.sched import (
        TenantClassifier,
        tenant_entries_from_config,
    )

    N, MIS = 4, 4  # tenants 1..N, tenant MIS misbehaves
    config = DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=16, max_ifaces=64,
        fib_slots=64, sess_slots=1 << 12, nat_mappings=1,
        nat_backends=1, tenancy="on", tenancy_tenants=N + 1,
    )
    dp = Dataplane(config)
    for i in range(32):
        idx = dp.add_pod_interface(("default", f"p{i}"))
        dp.builder.add_route(f"10.1.1.{i + 2}/32", idx,
                             Disposition.LOCAL)
    t_net = {t: f"10.{50 + t}.0.0/16" for t in range(1, N + 1)}
    t_src = {t: f"10.{50 + t}.0.9" for t in range(1, N + 1)}
    # WFQ weights: the well-behaved class outweighs the (eventual)
    # hog 4:1 — the gold-vs-bronze shape real gateways run; quotas
    # are staged after the sat capture (rate 0 = unlimited for now)
    t_weight = {t: (1 if t == MIS else 4) for t in range(1, N + 1)}
    for t in range(1, N + 1):
        dp.builder.set_tenant(t, prefixes=[t_net[t]],
                              weight=t_weight[t])
    dp.swap()
    client_if = dp.pod_if[("default", "p0")]
    wires = {t: [wire_udp(i, src=t_src[t]) for i in range(frame_pkts)]
             for t in range(1, N + 1)}
    classifier = TenantClassifier(tenant_entries_from_config(
        [{"id": t, "prefixes": [t_net[t]], "weight": t_weight[t]}
         for t in range(1, N + 1)]))

    def capture(offered_fps, duration, slo_us=0, square_t=None,
                square=None) -> dict:
        """One pump lifecycle: per-tenant paced producers
        (``offered_fps``: tenant -> frames/s; ``square`` overrides
        tenant ``square_t``'s pacing with (hi, lo, half_s)),
        sequence-stamped wire latency split per tenant, device
        tenant-plane DELTAS (the state planes persist across pump
        lifecycles) and the pump's per-tenant lane ledger."""
        snap0 = dp.tenant_snapshot()
        rings = IORingPair(n_slots=256, snap=512)
        codec = PacketCodec(snap=rings.rx.snap)
        scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        gov = (LatencyGovernor(slo_us, tick_s=0.01, brownout_ticks=2,
                               recover_ticks=3) if slo_us > 0 else None)
        # latency-lean geometry for the whole scenario: 1-slot ring
        # windows + a one-frame (frame_pkts=16) WFQ service quantum
        # bound every frame's wait behind OTHER tenants' bulk in the
        # shared window pipeline (the WFQ delay bound scales with the
        # quantum), so the isolation comparison measures the bucket +
        # the lanes, not ring batching depth
        pump = DataplanePump(dp, rings, mode="persistent",
                             governor=gov, tenants=classifier,
                             ring_slots=1,
                             tenant_quantum=frame_pkts)
        pump.warm()
        pump.start()
        push_log = {}
        lat = collections.defaultdict(list)
        offered = {t: 0 for t in offered_fps}
        seq_box = [0]
        stop = threading.Event()

        def push(t) -> None:
            cols, n = codec.parse(wires[t], client_if, scratch)
            seq = seq_box[0]
            cols["meta"][:n] = seq
            tm = time.perf_counter()
            if rings.rx.push(cols, n, payload=scratch):
                push_log[seq] = (tm, t)
                seq_box[0] += 1
                offered[t] += n

        def producer() -> None:
            t0 = time.perf_counter()
            # staggered initial credits de-synchronize same-rate
            # producers: without the offsets every tenant's frame
            # lands in the same pacing tick and the WFQ tie-break
            # (by tenant id) turns into a fixed service-order bias
            credit = {t: i / max(1, len(offered_fps))
                      for i, t in enumerate(offered_fps)}
            last = t0
            while not stop.is_set():
                now = time.perf_counter()
                dt, last = now - last, now
                for t, fps in offered_fps.items():
                    if square is not None and t == square_t:
                        hi, lo, half = square
                        fps = (hi if int((now - t0) / half) % 2 == 0
                               else lo)
                    credit[t] = min(credit[t] + fps * dt, 32.0)
                    while credit[t] >= 1.0:
                        push(t)
                        credit[t] -= 1.0
                time.sleep(0.001)

        def drain_one() -> bool:
            g = rings.tx.peek()
            if g is None:
                return False
            seq = int(g.cols["meta"][0])
            rings.tx.release()
            rec = push_log.pop(seq, None)
            if rec is not None:
                lat[rec[1]].append(time.perf_counter() - rec[0])
            return True

        prod = threading.Thread(target=producer, daemon=True)
        t_start = time.perf_counter()
        prod.start()
        while time.perf_counter() < t_start + duration:
            if not drain_one():
                time.sleep(0.0002)
        stop.set()
        prod.join()
        idle_since = None
        flush_deadline = time.perf_counter() + 8.0
        while push_log and time.perf_counter() < flush_deadline:
            if drain_one():
                idle_since = None
                continue
            now = time.perf_counter()
            if idle_since is None:
                idle_since = now
            elif now - idle_since > 1.0:
                break
            time.sleep(0.002)
        elapsed = time.perf_counter() - t_start
        pump.stop()  # grafts the ring-carried tenant planes back
        s = dict(pump.stats)
        tsnap = pump.tenant_io_snapshot()
        tio = tsnap["io"]
        # WFQ-lane residue: frames still queued when the flush
        # deadline expired are neither goodput nor an attributed drop
        # (stop() abandons only DISPATCHED frames as drops_shutdown;
        # the scheduler queues are simply left) — the conservation
        # identity must count them or a slow flush reads as a
        # (nonexistent) conservation bug
        queued_residual = sum(q.get("pkts", 0)
                              for q in tsnap["queued"].values())
        # frames the stalled scan frontier never classified sit in the
        # rx ring at the deadline: offered minus scan-classified
        # (io["pkts"] counts at classification) — without this term a
        # slow flush on the 1-core harness reads as a conservation
        # violation
        unclassified = max(0, sum(offered.values())
                           - sum(v.get("pkts", 0)
                                 for v in tio.values()))
        rings.close()
        snap1 = dp.tenant_snapshot()

        def delta(key, t):
            d0 = int(snap0[key][t]) if snap0 is not None else 0
            return int(snap1[key][t]) - d0

        rows = {}
        for t in offered_fps:
            xs = np.asarray(lat[t]) * 1e6 if lat[t] else None
            rows[t] = {
                "offered_pkts": offered[t],
                "goodput_pkts": delta("tx", t),
                "goodput_fps": round(len(lat[t]) / max(elapsed, 1e-9),
                                     1),
                "quota_drop_pkts": delta("rl_drops", t),
                "dev_rx_pkts": delta("rx", t),
                "shed_pkts": int(tio.get(t, {}).get("shed_pkts", 0)),
                "p50_us": (round(float(np.percentile(xs, 50)), 1)
                           if xs is not None else 0.0),
                "p99_us": (round(float(np.percentile(xs, 99)), 1)
                           if xs is not None else 0.0),
            }
        return {
            "tenants": rows,
            "drops_shutdown": int(s.get("drops_shutdown", 0)),
            "drops_error": int(s.get("drops_error", 0)),
            "queued_residual": int(queued_residual) + int(unclassified),
            "io_callbacks": int(s.get("io_callbacks", 0)),
        }

    out = {"tenant_isolation_tenants": N,
           "tenant_isolation_frame_pkts": frame_pkts}
    # (1) floor + harness saturation (tenant 1, unlimited quota)
    floor = capture({1: 40}, duration=0.8)["tenants"][1]
    floor_us = max(floor["p50_us"], 1.0)
    sat = capture({1: 1e9}, duration=1.2)["tenants"][1]
    sat_fps = max(sat["goodput_fps"], 4.0)
    out["tenant_isolation_floor_us"] = round(floor_us, 1)
    out["tenant_isolation_sat_fps"] = round(sat_fps, 1)
    # (2) quotas: each tenant gets 5% of sat so even the hog's 4x
    # overage keeps TOTAL offered well under saturation (~32% avg,
    # 42% burst-high) — on this CPU harness a quota-dropped packet
    # costs the same device time as a forwarded one (the LATENCY.md
    # round-13 caveat), so the comparison must isolate the BUCKET and
    # the WFQ lanes, not queueing collapse; well-behaved tenants
    # offer 80% of quota, the hog 4x quota through a square wave
    quota_fps = max(1.0, 0.10 * sat_fps)
    quota_pps = quota_fps * frame_pkts
    rate = max(1, int(round(quota_pps / Dataplane.TICKS_PER_SEC)))
    with dp.commit_lock:
        for t in range(1, N + 1):
            dp.builder.set_tenant(t, prefixes=[t_net[t]],
                                  weight=t_weight[t],
                                  rate=rate, burst=4 * rate)
        dp.swap()
    out["tenant_isolation_quota_pps"] = round(quota_pps, 1)
    well_fps = 0.8 * quota_fps
    # (3) solo baselines for the well-behaved tenants
    solo = {}
    for t in range(1, N):
        solo[t] = capture({t: well_fps},
                          duration=3.0 * phase_s)["tenants"][t]
    out["tenant_isolation_solo"] = {
        str(t): {"goodput_fps": solo[t]["goodput_fps"],
                 "p99_us": solo[t]["p99_us"]} for t in solo}
    # (4) the overload phase: tenant MIS at 4x quota (square wave
    # 6x/2x), everyone else unchanged. The device token bucket
    # absorbs the overage (attributed tenant_quota) and WFQ keeps the
    # well-behaved tenants' queues empty; the shallow ring windows
    # above keep their in-flight depth solo-like
    over = capture(
        {**{t: well_fps for t in range(1, N)}, MIS: 4 * quota_fps},
        duration=5.0, square_t=MIS,
        square=(6 * quota_fps, 2 * quota_fps, 0.25))
    rows = over["tenants"]
    out["tenant_isolation_overload"] = {
        str(t): dict(rows[t]) for t in rows}
    ratios_g, ratios_p = [], []
    # the well-behaved tenants are configured IDENTICALLY (same rate/
    # burst/weight/offered), so the median of their solo p99s is one
    # shared baseline: a single tenant's ~75-sample solo p99 swings
    # 2x run-to-run on this 1-core harness (the dominant ratio noise),
    # the median-of-3 does not — per-tenant overload p99s still
    # compare individually against it
    solo_p99_med = max(float(np.median([s["p99_us"]
                                        for s in solo.values()])), 1e-9)
    for t in range(1, N):
        ratios_g.append(rows[t]["goodput_fps"]
                        / max(solo[t]["goodput_fps"], 1e-9))
        ratios_p.append(rows[t]["p99_us"] / solo_p99_med)
    out["tenant_isolation_goodput_ratio_min"] = round(min(ratios_g), 3)
    out["tenant_isolation_p99_ratio_max"] = round(max(ratios_p), 2)
    # (5) attribution + EXACT conservation over the overload phase
    mis = rows[MIS]
    overage = max(1, mis["offered_pkts"] - mis["goodput_pkts"])
    out["tenant_isolation_mis_quota_drop_pkts"] = mis["quota_drop_pkts"]
    out["tenant_isolation_mis_shed_pkts"] = mis["shed_pkts"]
    out["tenant_isolation_attributed_pct"] = round(
        100.0 * (mis["quota_drop_pkts"] + mis["shed_pkts"]) / overage,
        2)
    offered_total = sum(r["offered_pkts"] for r in rows.values())
    accounted = (sum(r["goodput_pkts"] + r["quota_drop_pkts"]
                     + r["shed_pkts"] for r in rows.values())
                 + over["drops_shutdown"] + over["drops_error"]
                 + over["queued_residual"])
    out["tenant_isolation_conserved"] = int(offered_total == accounted)
    out["tenant_isolation_residual_pkts"] = over["queued_residual"]
    out["tenant_isolation_io_callbacks"] = over["io_callbacks"]
    _progress(
        tenant_isolation_goodput_ratio_min=out[
            "tenant_isolation_goodput_ratio_min"],
        tenant_isolation_p99_ratio_max=out[
            "tenant_isolation_p99_ratio_max"],
        tenant_isolation_attributed_pct=out[
            "tenant_isolation_attributed_pct"],
        tenant_isolation_conserved=out["tenant_isolation_conserved"])
    return out


def sub_benches(args):
    """BASELINE configs #1/#3/#4 as secondary metrics."""
    import jax
    import jax.numpy as jnp

    from vpp_tpu.pipeline.graph import pipeline_step
    from vpp_tpu.pipeline.vector import ip4

    out = {}
    step = jax.jit(pipeline_step, donate_argnums=(0,))

    # #1 pod-to-pod forwarding (iperf analog)
    dp = build_fwd_dataplane()
    mpps, _ = measure_mpps(
        step, dp.tables, build_pod_traffic(args.packets), args.iters, args.warmup
    )
    out["pod_to_pod_fwd_mpps"] = round(mpps, 1)
    _progress(pod_to_pod_fwd_mpps=out["pod_to_pod_fwd_mpps"])

    # #3 NAT44 100-backend LB: all traffic through the VIP
    dp, uplink = build_dataplane(16, args.backends)
    pkts = build_traffic(args.packets, uplink, seed=5)
    pkts = pkts._replace(
        dst_ip=jnp.full_like(pkts.dst_ip, ip4("10.96.0.10")),
        dport=jnp.full_like(pkts.dport, 80),
    )
    mpps, _ = measure_mpps(step, dp.tables, pkts, args.iters, args.warmup)
    out["nat44_vip_lb_mpps"] = round(mpps, 1)
    _progress(nat44_vip_lb_mpps=out["nat44_vip_lb_mpps"])

    # #4 VXLAN overlay: remote-disposed traffic + encap kernel
    from vpp_tpu.ops.vxlan import vxlan_encap
    from vpp_tpu.pipeline.vector import Disposition

    dp, uplink = build_dataplane(16, 1)
    dp.builder.add_route(
        "10.2.0.0/16", uplink, Disposition.REMOTE,
        next_hop=ip4("192.168.16.2"), node_id=2,
    )
    dp.swap()
    pkts = build_traffic(args.packets, uplink, seed=9)
    pkts = pkts._replace(
        dst_ip=(ip4("10.2.0.0") + np.random.default_rng(4).integers(
            2, 1 << 15, args.packets)).astype(np.uint32)
    )
    vtep = jnp.uint32(ip4("192.168.16.1"))
    encap = jax.jit(vxlan_encap)

    # Two jits, like the deployment shape (Dataplane.process +
    # encap_remote). Note: fusing encap INTO the step jit measured ~140x
    # slower on v5e (XLA scheduling pathology) — keep them separate.
    tables = dp.tables
    n = int(pkts.src_ip.shape[0])
    for i in range(args.warmup):
        res = step(tables, pkts, jnp.int32(1 + i))
        outer = encap(res.pkts, res.disp == int(Disposition.REMOTE),
                      vtep, res.next_hop)
        tables = res.tables
    jax.block_until_ready(outer)
    t0 = time.perf_counter()
    for i in range(args.iters):
        res = step(tables, pkts, jnp.int32(100 + i))
        outer = encap(res.pkts, res.disp == int(Disposition.REMOTE),
                      vtep, res.next_hop)
        tables = res.tables
    jax.block_until_ready(outer)
    mpps = n * args.iters / (time.perf_counter() - t0) / 1e6
    out["vxlan_overlay_encap_mpps"] = round(mpps, 1)
    _progress(vxlan_overlay_encap_mpps=out["vxlan_overlay_encap_mpps"])

    # (the IO front-end wire sections — io_ring_bench / io_daemon_bench
    # — run in the PRIORITY capture phase of _run() now, before the
    # headline compile: VERDICT r5 Next #1)
    return out


def session_election_bench(args, batch: int = 2048, iters: int = 30) -> dict:
    """Time hashmap_insert under BOTH election strategies (claim
    scatter-min vs stable-sort — ops/session.py module doc) at the
    headline table size, on whatever backend this bench runs on.
    One random batch is built once and EVERY timed call inserts it
    into the same pristine table snapshot (``t`` is never threaded
    forward), so each iteration pays full insert pressure — threading
    the result tables back in would turn iterations 2+ into pure
    refresh hits and invalidate the numbers. Three repetitions with
    ALTERNATING mode order, median reported: a fixed order biased the
    r4-era numbers by warmup/cache state (fixed-order showed claim
    966 vs sort 893 where order-alternated medians showed 509 vs 442
    on the same host) — the whole point of this key is to flip the
    sort default with evidence if a backend disagrees, so the
    methodology must not bias it."""
    import os as _os

    import jax as _jax
    import jax.numpy as jnp

    from vpp_tpu.ops.session import session_insert
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import make_packet_vector
    from vpp_tpu.ops import session as _sess

    slots = 1 << 15  # the headline pipeline's session table size
    dp = Dataplane(DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=32, max_ifaces=8,
        fib_slots=32, sess_slots=slots, nat_mappings=4, nat_backends=4,
    ))
    dp.add_uplink()
    dp.swap()
    pv = make_packet_vector([{"src": "10.0.0.1", "dst": "10.1.1.3",
                              "proto": 6, "sport": 1024, "dport": 80,
                              "rx_if": 1}], n=batch)
    rng = np.random.default_rng(0)
    pv = pv._replace(
        src_ip=jnp.asarray(rng.integers(1, 1 << 30, batch).astype(np.uint32)),
        sport=jnp.asarray(rng.integers(1024, 65000, batch).astype(np.int32)),
        flags=jnp.ones(batch, np.int32))
    want = jnp.ones(batch, bool)

    out = {"sess_election_selected": _sess.election_mode(slots),
           "sess_election_slots": slots}
    saved = _os.environ.get("VPPT_SESS_ELECTION")
    try:
        fns = {}
        for mode in ("claim", "sort"):
            _os.environ["VPPT_SESS_ELECTION"] = mode
            fns[mode] = _jax.jit(session_insert)  # fresh jit per
            # mode: the strategy is baked in at trace time
            _jax.block_until_ready(fns[mode](dp.tables, pv, want,
                                             jnp.int32(1)))
        acc = {"claim": [], "sort": []}
        for rep in range(3):
            order = (("claim", "sort") if rep % 2 == 0
                     else ("sort", "claim"))
            for mode in order:
                t = dp.tables
                t0 = time.perf_counter()
                for i in range(iters):
                    t2, ins, fail, _ev_exp, _ev_vic = fns[mode](
                        t, pv, want, jnp.int32(2 + i))
                _jax.block_until_ready(t2)
                acc[mode].append(
                    (time.perf_counter() - t0) / iters / batch * 1e9)
        for mode, vals in acc.items():
            out[f"sess_election_{mode}_ns_pkt"] = round(
                float(np.median(vals)), 1)
    finally:
        if saved is None:
            _os.environ.pop("VPPT_SESS_ELECTION", None)
        else:
            _os.environ["VPPT_SESS_ELECTION"] = saved
    return out


def pallas_kernel_bench(args, batch: int = 2048, iters: int = 20) -> dict:
    """Pallas kernel shoot-out (ISSUE 16): time the fused rungs of the
    three gather-bound hot ops against their jnp reference rungs on
    this backend, and record whether the pair is bit-exact. On a TPU
    the kernels compile natively (the perf claim); elsewhere they run
    in INTERPRET mode at a reduced batch — an emulator priced per
    lowered op, so those ns/pkt rows validate semantics cost, not
    speed (``pallas_interpret`` = 1 marks the regime). Keys:
    pallas_{bv,lpm,sess}_ns_pkt + *_ref_ns_pkt + *_bitexact."""
    import functools as _ft

    import jax as _jax
    import jax.numpy as jnp

    from vpp_tpu.ops._pallas import pallas_available, use_pallas
    from vpp_tpu.ops.acl_bv import bv_first_match, bv_first_match_fused
    from vpp_tpu.ops.lpm import _fib_lookup_lpm_pallas, fib_lookup_lpm
    from vpp_tpu.ops.session import _probe_ways_reference, sess_probe_ways
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import Disposition

    on_tpu = use_pallas()
    interpret = not on_tpu
    if interpret:
        batch, iters = 256, 3
    out = {"pallas_backend": _jax.default_backend(),
           "pallas_available": int(pallas_available()),
           "pallas_interpret": int(interpret)}
    if not pallas_available():
        return out

    dp = Dataplane(DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=64, max_ifaces=8,
        fib_slots=256, sess_slots=1 << 12, nat_mappings=4,
        nat_backends=4, classifier="bv", fib_impl="lpm"))
    uplink = dp.add_uplink()
    rules = build_rules(48)
    dp.builder.set_global_table(rules)
    rng = np.random.default_rng(5)
    for i in range(60):
        plen = int(rng.choice([8, 16, 24, 24, 32]))
        net = int(rng.integers(0, 1 << 32)) & (0xFFFFFFFF << (32 - plen))
        dp.builder.add_route(
            f"{net >> 24 & 255}.{net >> 16 & 255}."
            f"{net >> 8 & 255}.{net & 255}/{plen}",
            1, Disposition.LOCAL)
    dp.swap()
    tables = dp.tables
    pkts = build_traffic(batch, uplink, seed=21)

    def ns_pkt(fn, *a):
        r = fn(*a)
        _jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(*a)
        _jax.block_until_ready(r)
        return round((time.perf_counter() - t0) / iters / batch * 1e9,
                     1), r

    bv_args = (
        tables.glb_bv_bnd_src, tables.glb_bv_bnd_dst,
        tables.glb_bv_bnd_sport, tables.glb_bv_bnd_dport,
        tables.glb_bv_nbnd, tables.glb_bv_src, tables.glb_bv_dst,
        tables.glb_bv_sport, tables.glb_bv_dport, tables.glb_bv_proto,
        pkts)
    out["pallas_bv_ns_pkt"], got = ns_pkt(
        _jax.jit(_ft.partial(bv_first_match_fused, interpret=interpret)),
        *bv_args)
    out["pallas_bv_ref_ns_pkt"], ref = ns_pkt(_jax.jit(bv_first_match),
                                              *bv_args)
    out["pallas_bv_bitexact"] = int(
        bool(jnp.all(got[0] == ref[0]) & jnp.all(got[1] == ref[1])))

    out["pallas_lpm_ns_pkt"], got = ns_pkt(
        _jax.jit(_ft.partial(_fib_lookup_lpm_pallas, interpret=interpret)),
        tables, pkts)
    out["pallas_lpm_ref_ns_pkt"], ref = ns_pkt(_jax.jit(fib_lookup_lpm),
                                               tables, pkts)
    out["pallas_lpm_bitexact"] = int(all(
        bool(jnp.all(g == r)) for g, r in zip(got, ref)))

    nb, ways = tables.sess_valid.shape
    b = jnp.asarray(rng.integers(0, nb, batch).astype(np.int32))
    keys = [jnp.asarray(rng.integers(0, 1 << 32, batch, dtype=np.uint64)
                        .astype(np.uint32)) for _ in range(4)]
    sess_args = (b, *keys, tables.sess_valid, tables.sess_src,
                 tables.sess_dst, tables.sess_ports, tables.sess_proto,
                 tables.sess_time, jnp.int32(0), jnp.int32(1 << 30))
    out["pallas_sess_ns_pkt"], got = ns_pkt(
        _ft.partial(sess_probe_ways, interpret=interpret), *sess_args)
    out["pallas_sess_ref_ns_pkt"], ref = ns_pkt(
        _jax.jit(_probe_ways_reference), *sess_args)
    out["pallas_sess_bitexact"] = int(
        bool(jnp.all(got[0] == ref[0]) & jnp.all(got[1] == ref[1])))
    out["pallas_sess_ways"] = int(ways)
    return out


def _mem_available_bytes() -> int:
    """Best-effort MemAvailable (0 when unreadable) — gates the
    10M-session scale config so a small CI box downshifts instead of
    getting OOM-killed mid-run."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def session_scale_bench(args, batch: int = 2048, iters: int = 24) -> dict:
    """Set-associative session-table capture (ISSUE 6), two parts.

    **Old-vs-new** at the headline table size (1<<15 slots): the W-way
    single-election insert (ops/session.hashmap_insert) against the
    retained linear-probe baseline (hashmap_insert_linear — the
    pre-rework algorithm, verbatim). Methodology (docs/SESSIONS.md):

      * **kernel-level, donated, scan-chained** — both inserts run
        directly over the six session COLUMNS with donated buffers,
        and all `calls` chained inserts execute inside ONE jitted
        lax.scan program, exactly how the fused pipeline step runs
        them in production (in-place updates, no per-call table copy,
        no per-call dispatch). Whole-DataplaneTables dispatch was
        measured at ~325 ns/pkt of pure pytree/donation overhead and
        the per-call jit dispatch at ~700 us/call on this harness —
        additive constants on BOTH sides that compressed the real
        algorithmic ratio.
      * **fresh distinct flows per call** (pre-built outside the
        clock, stacked [calls, batch] for the scan) keep every chained
        insert at full pressure without the refresh-hit pollution that
        forward-threading one batch would cause; 8 calls x batch into
        1<<15 slots tops out at 50% load, well under the eviction
        regime.
      * **per-mode MINIMUM over interleaved windows** — the unloaded-
        cost estimator. This box runs concurrent load with multi-x
        wall-clock swings; medians of long runs inherit whatever
        landed on top of them, while tightly alternated small windows
        give every mode the same shot at the quiet slices.

    Keys: ``sess_insert_ns_pkt`` / ``sess_insert_linear_ns_pkt`` /
    ``sess_insert_speedup_x`` (acceptance: >= 3x).

    **Scale**: a 10M+-resident config (``sess_slots`` 1<<24, override
    with VPPT_SESS_SCALE_SLOTS; downshifts automatically when
    MemAvailable can't hold ~3x the table) is prefilled on-device to
    ~62% live occupancy, then fresh-flow batches are admitted through
    a tables-donating jit (in-place threading — the production-step
    donation story lives in docs/SESSIONS.md). Keys:
    ``sessions_resident_millions`` (live entries after admission) and
    ``session_admission_ksps`` (inserted flows/sec at that residency),
    plus ``sess_scale_insert_ns_pkt``. The new insert's cost is
    O(batch), table-size independent — which is the whole point of the
    sort-rank election — so the scale rows measure memory pressure,
    not an algorithmic cliff.
    """
    import os as _os

    import jax as _jax
    import jax.numpy as jnp

    from vpp_tpu.ops.session import session_insert
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import make_packet_vector

    out = {}

    def flow_batch(rng, n):
        pv = make_packet_vector([{"src": "10.0.0.1", "dst": "10.1.1.3",
                                  "proto": 6, "sport": 1024, "dport": 80,
                                  "rx_if": 1}], n=n)
        return pv._replace(
            src_ip=jnp.asarray(
                rng.integers(1, 1 << 30, n).astype(np.uint32)),
            sport=jnp.asarray(
                rng.integers(1024, 65000, n).astype(np.int32)),
            flags=jnp.ones(n, np.int32))

    # --- part 1: old-vs-new at the headline table size ---
    from vpp_tpu.ops.session import (
        _hash, _pack_ports, hashmap_insert, hashmap_insert_linear)

    slots = 1 << 15
    ways = 4
    nb = slots // ways
    calls = 8          # flows offered per window: 8 x batch = 50% load
    windows = 10
    out["sess_insert_slots"] = slots
    out["sess_insert_ways"] = ways

    rng = np.random.default_rng(1)
    # distinct flows per call, stacked [calls, batch], built OUTSIDE
    # the clock — the scan below consumes one row per chained insert
    kvs = (
        jnp.asarray(np.stack(
            [(1 + i * batch + np.arange(batch)).astype(np.uint32)
             for i in range(calls)])),
        jnp.full((calls, batch), 0x0A010103, jnp.uint32),
        _pack_ports(
            jnp.asarray(rng.integers(
                1024, 65000, (calls, batch)).astype(np.int32)),
            jnp.full((calls, batch), 80, jnp.int32)),
        jnp.full((calls, batch), 6, jnp.int32),
    )
    nows = jnp.arange(2, 2 + calls, dtype=jnp.int32)
    want = jnp.ones(batch, bool)
    max_age = jnp.int32(3000)

    # both modes run their `calls` chained inserts inside ONE jitted
    # lax.scan program: production runs the insert inside the fused
    # step, so per-dispatch overhead (~700 us/call measured on this
    # harness) is not kernel cost — paying it per call was an additive
    # constant on BOTH sides that compressed the algorithmic ratio
    def assoc_prog(valid, tme, k0, k1, k2, k3, kvs, nows):
        def body(carry, x):
            valid, tme, ks = carry
            kv, now = tuple(x[:4]), x[4]
            h = _hash(*kv, nb)
            r = hashmap_insert(valid, tme, ks, kv, (), (), h, want,
                               now, max_age=max_age)
            return (r[0], r[1], r[2]), 0
        (valid, tme, ks), _ = _jax.lax.scan(
            body, (valid, tme, (k0, k1, k2, k3)), (*kvs, nows))
        return valid, tme, ks

    def linear_prog(valid, tme, k0, k1, k2, k3, kvs, nows):
        def body(carry, x):
            valid, tme, ks = carry
            kv, now = tuple(x[:4]), x[4]
            h = _hash(*kv, slots)
            r = hashmap_insert_linear(valid, tme, ks, kv, h, want,
                                      now, max_age=max_age)
            return (r[0], r[1], r[2]), 0
        (valid, tme, ks), _ = _jax.lax.scan(
            body, (valid, tme, (k0, k1, k2, k3)), (*kvs, nows))
        return valid, tme, ks

    fns = {
        "assoc": (_jax.jit(assoc_prog, donate_argnums=(0, 1, 2, 3, 4, 5)),
                  (nb, ways)),
        "linear": (_jax.jit(linear_prog, donate_argnums=(0, 1, 2, 3, 4, 5)),
                   (slots,)),
    }

    def pristine(shape):
        cols = [jnp.zeros(shape, jnp.int32), jnp.zeros(shape, jnp.int32),
                jnp.zeros(shape, jnp.uint32), jnp.zeros(shape, jnp.uint32),
                jnp.zeros(shape, jnp.uint32), jnp.zeros(shape, jnp.int32)]
        _jax.block_until_ready(cols)
        return cols

    for fn, shape in fns.values():  # compile + warm outside the clock
        _jax.block_until_ready(
            _jax.tree.leaves(fn(*pristine(shape), kvs, nows)))
    mins = {"assoc": float("inf"), "linear": float("inf")}
    for rep in range(windows):
        order = (("assoc", "linear") if rep % 2 == 0
                 else ("linear", "assoc"))
        for mode in order:
            fn, shape = fns[mode]
            cols = pristine(shape)
            t0 = time.perf_counter()
            res = fn(*cols, kvs, nows)
            _jax.block_until_ready((res[0], res[1]))
            mins[mode] = min(
                mins[mode],
                (time.perf_counter() - t0) / calls / batch * 1e9)
    new_ns = mins["assoc"]
    old_ns = mins["linear"]
    out["sess_insert_ns_pkt"] = round(new_ns, 1)
    out["sess_insert_linear_ns_pkt"] = round(old_ns, 1)
    out["sess_insert_speedup_x"] = round(old_ns / max(new_ns, 1e-9), 2)

    # --- part 2: 10M-resident scale config ---
    scale_slots = int(_os.environ.get("VPPT_SESS_SCALE_SLOTS", 1 << 24))
    # ~24 B/slot across the 6 session columns; require ~3x headroom
    # (donation transients + the numpy-free device fill)
    need = scale_slots * 24 * 3
    avail = _mem_available_bytes()
    while avail and need > avail and scale_slots > (1 << 18):
        scale_slots >>= 1
        need = scale_slots * 24 * 3
    ways = 4
    cfg = DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=32, max_ifaces=8,
        fib_slots=32, sess_slots=scale_slots, sess_ways=ways,
        natsess_slots=1 << 12, nat_mappings=4, nat_backends=4,
    )
    dp2 = Dataplane(cfg)
    dp2.add_uplink()
    dp2.swap()
    n_buckets = scale_slots // ways
    target = min(int(scale_slots * 0.625), scale_slots)
    full_ways = target // n_buckets            # whole ways filled
    part = target - full_ways * n_buckets      # buckets with one more
    t = dp2.tables
    valid = t.sess_valid
    if full_ways:
        valid = valid.at[:, :full_ways].set(1)
    if part:
        valid = valid.at[:part, full_ways].set(1)
    # unique synthetic keys (bucket id / way) — residency + admission
    # probe the live/free way machinery, not key recall
    bid = jnp.arange(n_buckets, dtype=jnp.uint32)[:, None]
    t = t._replace(
        sess_valid=valid,
        sess_time=jnp.where(valid == 1, jnp.int32(1), 0),
        sess_src=jnp.broadcast_to(bid, valid.shape),
        sess_dst=jnp.broadcast_to(
            jnp.arange(ways, dtype=jnp.uint32)[None, :], valid.shape),
    )
    insert = _jax.jit(
        lambda tt, p, w, n: session_insert(tt, p, w, n),
        donate_argnums=(0,))
    rng2 = np.random.default_rng(9)
    # fresh-flow batches built OUTSIDE the clock (host-side numpy +
    # packet-vector assembly would otherwise dominate the timed loop)
    pvs = [flow_batch(rng2, batch) for _ in range(iters + 1)]
    _jax.block_until_ready([pv.src_ip for pv in pvs])
    t, ins, _f, _e, _v = insert(t, pvs[0], want, jnp.int32(2))  # compile
    _jax.block_until_ready(t.sess_valid)
    inserted = int(np.asarray(ins).sum())
    ins_acc = jnp.int32(0)      # accumulate on-device; one sync at the end
    t0 = time.perf_counter()
    for i in range(iters):
        t, ins, _f, _e, _v = insert(t, pvs[1 + i], want, jnp.int32(3 + i))
        ins_acc = ins_acc + jnp.sum(ins, dtype=jnp.int32)
    _jax.block_until_ready((t.sess_valid, ins_acc))
    dt = time.perf_counter() - t0
    inserted += int(np.asarray(ins_acc).item())
    resident = int(np.asarray(jnp.sum(t.sess_valid)).item())
    out["sess_scale_slots"] = scale_slots
    out["sess_scale_ways"] = ways
    out["sessions_resident_millions"] = round(resident / 1e6, 3)
    out["session_admission_ksps"] = round(iters * batch / dt / 1e3, 1)
    out["sess_scale_insert_ns_pkt"] = round(
        dt / iters / batch * 1e9, 1)
    out["sess_scale_insert_failed"] = iters * batch + batch - inserted
    return out


def snapshot_bench(args, batch: int = 2048, iters: int = 24) -> dict:
    """Crash-consistent snapshot capture (ISSUE 8) at the scale config.

    Prefills the 1<<24-slot table (VPPT_SESS_SCALE_SLOTS override;
    memory/disk-guarded downshift like session_scale_bench) to ~62%
    live, then measures:

      * ``snapshot_drain_s`` / ``snapshot_chunks`` / ``snapshot_mb`` /
        ``snapshot_chunk_ms`` — the FULL first-generation drain in
        bounded chunks (the ~400 MB sess column set must never ship
        as one transfer — chunk_ms is the bound that proves it);
      * ``snapshot_incremental_s`` — the clean second generation
        (content digests: nothing re-ships);
      * ``snapshot_step_stall_pct`` — the headline number: median
        fused-step time while a full drain runs concurrently vs
        unloaded, as a percentage increase. Acceptance: < 10% — the
        snapshot must never stall the hot path.
    """
    import shutil as _shutil
    import tempfile as _tempfile
    import threading as _threading

    import jax as _jax
    import jax.numpy as jnp

    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.snapshot import SessionSnapshotter
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import make_packet_vector

    out = {}
    scale_slots = int(os.environ.get("VPPT_SESS_SCALE_SLOTS", 1 << 24))
    # ~24 B/slot on device + the host chunk staging + the on-disk
    # snapshot copy: require ~4x headroom, and the snapshot dir must
    # hold ~1.5x the column bytes
    need = scale_slots * 24 * 4
    avail = _mem_available_bytes()
    while avail and need > avail and scale_slots > (1 << 18):
        scale_slots >>= 1
        need = scale_slots * 24 * 4
    td = _tempfile.mkdtemp(prefix="snapbench_")
    free_disk = _shutil.disk_usage(td).free
    while scale_slots * 24 * 1.5 > free_disk and scale_slots > (1 << 18):
        scale_slots >>= 1
    ways = 4
    cfg = DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=32, max_ifaces=8,
        fib_slots=32, sess_slots=scale_slots, sess_ways=ways,
        natsess_slots=1 << 12, nat_mappings=4, nat_backends=4,
    )
    dp = Dataplane(cfg)
    from vpp_tpu.pipeline.vector import Disposition

    up = dp.add_uplink()
    dp.builder.add_route("10.1.0.0/16", up, Disposition.LOCAL)
    dp.swap()
    n_buckets = scale_slots // ways
    target = int(scale_slots * 0.625)
    full_ways = target // n_buckets
    part = target - full_ways * n_buckets
    t = dp.tables
    valid = t.sess_valid
    if full_ways:
        valid = valid.at[:, :full_ways].set(1)
    if part:
        valid = valid.at[:part, full_ways].set(1)
    bid = jnp.arange(n_buckets, dtype=jnp.uint32)[:, None]
    dp.tables = t._replace(
        sess_valid=valid,
        sess_time=jnp.where(valid == 1, jnp.int32(1), 0),
        sess_src=jnp.broadcast_to(bid, valid.shape),
        sess_dst=jnp.broadcast_to(
            jnp.arange(ways, dtype=jnp.uint32)[None, :], valid.shape),
    )
    dp._now = 2
    out["snapshot_slots"] = scale_slots

    # fresh-flow step batches (prebuilt outside the clock) for the
    # stall probe: the production-shaped hot path next to the drain
    rng = np.random.default_rng(11)

    def flow_batch(n):
        pv = make_packet_vector(
            [{"src": "10.0.0.1", "dst": "10.1.1.3", "proto": 6,
              "sport": 1024, "dport": 80, "rx_if": up}], n=n)
        import jax.numpy as _jnp

        return pv._replace(
            src_ip=_jnp.asarray(
                rng.integers(1, 1 << 30, n).astype(np.uint32)),
            sport=_jnp.asarray(
                rng.integers(1024, 65000, n).astype(np.int32)),
            flags=_jnp.ones(n, np.int32))

    pvs = [flow_batch(batch) for _ in range(iters * 4 + 2)]
    _jax.block_until_ready([pv.src_ip for pv in pvs])
    dp.process(pvs[0], now=3)  # compile + warm
    pv_i = 1

    def step_samples(k, now0):
        nonlocal pv_i
        samples = []
        for i in range(k):
            t0 = time.perf_counter()
            res = dp.process(pvs[pv_i], now=now0 + i)
            _jax.block_until_ready(res.tables.sess_valid)
            samples.append(time.perf_counter() - t0)
            pv_i += 1
        return samples

    try:
        base = step_samples(iters, 10)
        base_ms = float(np.median(base) * 1e3)

        # pace_s: breathe between chunk drains so the drain never
        # monopolizes the transport/host — the agent default a
        # latency-sensitive deployment would run with
        snap = SessionSnapshotter(dp, td, chunk_buckets=4096,
                                  pace_s=0.005)
        # concurrent: the FULL first-generation drain against live
        # steps — the stall number the acceptance bar cares about
        overlap: list = []
        th = _threading.Thread(target=snap.snapshot, daemon=True)
        t0 = time.perf_counter()
        th.start()
        while th.is_alive():
            overlap.extend(step_samples(2, 1000 + pv_i))
            if pv_i >= len(pvs) - 1:
                pv_i = 1  # reuse batches; refresh-vs-insert mix is
                # stable enough for a median
        th.join()
        drain_s = time.perf_counter() - t0
        s = snap.stats_snapshot()
        if s["snapshot_failures"]:
            raise RuntimeError(f"snapshot failed: {s['last_error']}")
        over_ms = float(np.median(overlap) * 1e3) if overlap else base_ms
        out["snapshot_drain_s"] = round(drain_s, 2)
        out["snapshot_chunks"] = s["chunks_written"]
        out["snapshot_mb"] = round(s["bytes_written"] / 1e6, 1)
        out["snapshot_chunk_ms"] = round(
            s["chunk_seconds"] / max(1, s["chunks_written"]) * 1e3, 2)
        out["snapshot_step_ms_unloaded"] = round(base_ms, 3)
        out["snapshot_step_ms_draining"] = round(over_ms, 3)
        out["snapshot_step_stall_pct"] = round(
            max(0.0, (over_ms - base_ms) / base_ms * 100.0), 1)
        # clean incremental generation: digests unchanged except the
        # buckets the stall probe dirtied
        t1 = time.perf_counter()
        snap.snapshot()
        out["snapshot_incremental_s"] = round(
            time.perf_counter() - t1, 2)
        s2 = snap.stats_snapshot()
        out["snapshot_incremental_chunks"] = (
            s2["chunks_written"] - s["chunks_written"])
    finally:
        _shutil.rmtree(td, ignore_errors=True)
    return out


def wire_udp(i: int, dport: int = 80, src: str = "10.1.1.2") -> bytes:
    """One test UDP frame ``src`` → 10.1.1.3 (shared by the ring bench
    and the daemon-bench sender subprocess; ``dport`` lets the
    latency-SLO ladder tag priority-lane traffic, ``src`` lets the
    tenant-isolation scenario derive per-tenant flows)."""
    import ipaddress
    import struct

    src = ipaddress.ip_address(src).packed
    dst = ipaddress.ip_address("10.1.1.3").packed
    eth = b"\x02\x00\x00\x00\x00\x02\x02\x00\x00\x00\x00\x01\x08\x00"
    l4 = struct.pack("!HHHH", 40000 + (i % 1024), dport, 16, 0) + b"y" * 8
    hdr = struct.pack("!BBHHHBBH4s4s", 0x45, 0, 20 + len(l4), i & 0xFFFF,
                      0x4000, 64, 17, 0, src, dst)
    return eth + hdr + l4


def io_ring_bench(args, frame_pkts: int = 256,
                  sat_s: float = 5.0, paced_s: float = 5.0) -> dict:
    import collections
    import threading

    import jax as _jax

    from vpp_tpu.io.pump import DataplanePump
    from vpp_tpu.io.rings import IORingPair
    from vpp_tpu.native.pktio import PacketCodec
    from vpp_tpu.pipeline.vector import VEC

    dp = build_fwd_dataplane()
    client_if = dp.pod_if[("default", "p0")]

    frames = [wire_udp(i) for i in range(frame_pkts)]
    # deep ring + large coalesce + parallel fetchers: over the axon
    # tunnel a result fetch is an ~80-130 ms RPC, so throughput comes
    # from batch size × fetch concurrency (see docs/LATENCY.md)
    max_batch, workers = 16384, 8
    rings = IORingPair(n_slots=512, snap=512)
    codec = PacketCodec(snap=rings.rx.snap)
    scratch = np.zeros((VEC, rings.rx.snap), np.uint8)


    # transport bandwidth floor: the packed boundary is 20 B/packet
    # each way, so host↔device bandwidth IS the wire-path ceiling on a
    # transfer-limited transport (the axon tunnel measures single-digit
    # MB/s on bad days; report the floor so a low Mpps number is
    # attributable). Median of 3 runs of a 2 MB block each way.
    probe = np.zeros((128, 4096), np.int32)  # 2 MiB
    ups, downs = [], []
    for _ in range(3):
        t0 = time.perf_counter()
        dev = _jax.block_until_ready(_jax.device_put(probe))
        ups.append(probe.nbytes / (time.perf_counter() - t0))
        t0 = time.perf_counter()
        _jax.device_get(dev)
        downs.append(probe.nbytes / (time.perf_counter() - t0))
        del dev
    up_mbps = float(np.median(ups)) / 1e6
    down_mbps = float(np.median(downs)) / 1e6
    bytes_per_pkt = 20.0
    ceiling_mpps = min(up_mbps, down_mbps) / bytes_per_pkt

    pump = DataplanePump(dp, rings, max_batch=max_batch,
                         workers=workers)
    pump.warm()  # compile every dispatch bucket rung before measuring
    pump.start()

    def warm_barrier() -> None:
        # push one frame through the full ring→device→ring path and
        # wait for it to drain, so the measured phases never pay
        # time-to-first-drain (dispatch ramp + first fetch RTT) out of
        # their window — that skew zeroed the r3 sat phase on a slow
        # tunnel
        warm_cols, warm_n = codec.parse(frames, client_if, scratch)
        warm_cols["meta"][:warm_n] = -1
        if rings.rx.push(warm_cols, warm_n, payload=scratch):
            warm_deadline = time.perf_counter() + 120
            while time.perf_counter() < warm_deadline:
                g = rings.tx.peek()
                if g is not None:
                    rings.tx.release()
                    break
                time.sleep(0.005)

    warm_barrier()
    seq_counter = [0]

    def run_phase(duration: float, pace_fps: float = 0.0) -> dict:
        # frames are sequence-stamped through the ring's meta column so
        # latency pairing survives drops (tx-ring-full discards a frame
        # without a tx counterpart; positional pairing would then skew
        # every later sample)
        push_times: "collections.deque" = collections.deque()
        stop = threading.Event()
        stats = {"pushed": 0, "drained": 0, "dropped": 0, "lat": []}

        def producer():
            period = 1.0 / pace_fps if pace_fps else 0.0
            next_t = time.perf_counter()
            while not stop.is_set():
                if period:
                    now = time.perf_counter()
                    if now < next_t:
                        time.sleep(min(period / 8, next_t - now))
                        continue
                    next_t += period
                cols, n = codec.parse(frames, client_if, scratch)
                seq = seq_counter[0]
                cols["meta"][:n] = seq
                # enqueue BEFORE push: the drain thread may see the tx
                # frame before a post-push append would land
                push_times.append((seq, time.perf_counter()))
                if rings.rx.push(cols, n, payload=scratch):
                    seq_counter[0] += 1
                    stats["pushed"] += 1
                else:
                    push_times.pop()
                    time.sleep(0.0002)

        def drain_one(record: bool) -> bool:
            g = rings.tx.peek()
            if g is None:
                return False
            seq = int(g.cols["meta"][0])
            if record:
                codec.rewrite(g.cols, g.payload, g.n)
            rings.tx.release()
            now = time.perf_counter()
            while push_times and push_times[0][0] < seq:
                push_times.popleft()           # frame dropped in-pump
                stats["dropped"] += 1
            if push_times and push_times[0][0] == seq:
                _, t_push = push_times.popleft()
                if record:
                    stats["lat"].append(now - t_push)
            stats["drained"] += 1
            return True

        prod = threading.Thread(target=producer, daemon=True)
        t0 = time.perf_counter()
        prod.start()
        deadline = t0 + duration
        while time.perf_counter() < deadline:
            if not drain_one(record=True):
                time.sleep(0.0002)
        stop.set()
        prod.join()
        stats["elapsed"] = time.perf_counter() - t0
        # flush everything still in flight so the next phase starts
        # clean; a second of continuous silence means the pump is idle
        # (trailing entries whose frames were dropped never drain)
        flush_deadline = time.perf_counter() + 10
        idle_since = None
        while push_times and time.perf_counter() < flush_deadline:
            if drain_one(record=False):
                idle_since = None
                continue
            now = time.perf_counter()
            if idle_since is None:
                idle_since = now
            elif now - idle_since > 1.0:
                break
            time.sleep(0.002)
        push_times.clear()
        return stats

    try:
        try:
            sat = run_phase(sat_s)
            fps = sat["drained"] / sat["elapsed"]
            mpps = fps * frame_pkts / 1e6
            # paced phase at ~50% of saturation: queueing-free
            # experienced latency (what a packet actually waits,
            # ring to ring)
            paced = run_phase(paced_s, pace_fps=max(fps * 0.5, 1.0))
            lat_us = (np.asarray(paced["lat"][5:]) * 1e6
                      if len(paced["lat"]) > 5 else np.asarray([0.0]))
            out = {
                "io_ring_wire_mpps": round(mpps, 4),
                "io_wire_frame_pkts": frame_pkts,
                "io_wire_max_coalesce": pump.stats["max_coalesce"],
                "io_wire_lat_p50_us": round(
                    float(np.percentile(lat_us, 50)), 1),
                "io_wire_lat_p99_us": round(
                    float(np.percentile(lat_us, 99)), 1),
                "io_wire_paced_mpps": round(
                    paced["drained"] * frame_pkts / paced["elapsed"] / 1e6,
                    4),
                "xfer_up_MBps": round(up_mbps, 2),
                "xfer_down_MBps": round(down_mbps, 2),
                "io_wire_bytes_per_pkt": bytes_per_pkt,
                "io_wire_xfer_ceiling_mpps": round(ceiling_mpps, 3),
            }
        finally:
            pump.stop()

        # Overlap-ladder phase (r6 tentpole): the SAME path with the
        # adaptive chainer armed — backlog past one max_batch bucket
        # folds into one process_packed_chain K-stack, so a fetch
        # round trip is paid once per K buckets. Reported next to the
        # unchained row so the ladder's win (or its CPU-harness
        # neutrality) is a measured fact, not an inference. jit cache
        # note: the bucket rungs are already compiled on this
        # dataplane; only the chain rungs compile here.
        try:
            opump = DataplanePump(dp, rings, max_batch=max_batch,
                                  workers=workers, chain_k=8)
            try:
                opump.warm()
                opump.start()
                warm_barrier()
                osat = run_phase(sat_s)
                out.update({
                    "io_wire_overlap_mpps": round(
                        osat["drained"] / osat["elapsed"]
                        * frame_pkts / 1e6, 4),
                    "io_wire_chain_batches":
                        opump.stats["chain_batches"],
                    "io_wire_chain_k_peak":
                        opump.stats["chain_k_peak"],
                    "io_wire_inflight_peak":
                        opump.stats["inflight_peak"],
                    "io_wire_fetch_workers": opump.workers,
                })
            finally:
                opump.stop()
        except Exception as exc:  # noqa: BLE001 — additive phase
            out["io_wire_overlap_error"] = f"{type(exc).__name__}: {exc}"

        # Persistent resident-loop mode (docs/LATENCY.md lever #2,
        # VERDICT r4 Next #2): the SAME ring-to-ring path served by
        # mode="persistent" — one resident device program fed through
        # ordered io_callbacks instead of per-batch dispatches. Its
        # regime is the latency floor, so the paced-latency rows are
        # the headline; the sat row shows what that trade costs in
        # throughput. Failures here must not void the dispatch-mode
        # numbers above.
        try:
            # a telemetry-enabled twin of the forwarding dataplane:
            # the persistent round then histograms per-packet wire
            # latency ON DEVICE while the harness measures the same
            # frames host-side — the two tails are tied below (ISSUE
            # 13 satellite) so governor acceptance can trust one
            # source. A separate dp keeps the dispatch-mode rows
            # above byte-comparable with earlier rounds.
            dp_tel = build_fwd_dataplane(telemetry="latency")
            ppump = DataplanePump(dp_tel, rings, mode="persistent")
            try:
                ppump.warm()
                ppump.start()
                warm_barrier()
                psat = run_phase(min(sat_s, 4.0))
                pfps = psat["drained"] / psat["elapsed"]
                tel_before = ppump.tel_snapshot()
                bins0 = (np.asarray(tel_before["bins"], np.int64)
                         if tel_before is not None else None)
                ppaced = run_phase(min(paced_s, 4.0),
                                   pace_fps=max(pfps * 0.5, 1.0))
                plat_us = (np.asarray(ppaced["lat"][5:]) * 1e6
                           if len(ppaced["lat"]) > 5
                           else np.asarray([0.0]))
                pmpps = pfps * frame_pkts / 1e6
                # the io_callback-free claim as MEASURED keys (ISSUE
                # 7): windows exchanged vs host callbacks the device
                # program made (the ring steady state makes none —
                # this key regressing above 0 means the two-blocking-
                # callbacks-per-frame design came back), and the
                # persistent path as a fraction of the SAME capture's
                # transfer ceiling (acceptance: ratio >= 0.5, i.e.
                # within 2x of the ceiling)
                rwin = int(ppump.stats.get("ring_windows", 0))
                out.update({
                    "io_wire_persistent_mpps": round(pmpps, 4),
                    "io_wire_persistent_lat_p50_us": round(
                        float(np.percentile(plat_us, 50)), 1),
                    "io_wire_persistent_lat_p99_us": round(
                        float(np.percentile(plat_us, 99)), 1),
                    "io_wire_ceiling_ratio": round(
                        pmpps / ceiling_mpps, 4) if ceiling_mpps else 0.0,
                    "io_wire_ring_windows": rwin,
                    "io_wire_ring_frames": int(
                        ppump.stats.get("ring_frames", 0)),
                    "io_wire_callbacks_per_window": round(
                        int(ppump.stats.get("io_callbacks", 0))
                        / max(1, rwin), 4),
                })
                # host↔device latency tie (ISSUE 13 satellite): the
                # host-side p99 (ring-to-ring, sequence-stamped) and
                # the device-histogram p99 (pack → device tx-append)
                # from the SAME paced round. The host leg is a strict
                # superset (rx-ring wait + result fetch + tx write +
                # drain), so a ratio far above 2 — or below 1 — means
                # one of the two clocks is lying and neither source
                # should anchor governor acceptance.
                tel_after = ppump.tel_snapshot()
                if tel_after is not None and bins0 is not None:
                    from vpp_tpu.ops.telemetry import quantiles_from_bins

                    dbins = (np.asarray(tel_after["bins"], np.int64)
                             - bins0)
                    if int(dbins.sum()) > 0:
                        _d50, d99, _d999 = quantiles_from_bins(dbins)
                        host_p99 = float(np.percentile(plat_us, 99))
                        ratio = (host_p99 / d99) if d99 > 0 else 0.0
                        out.update({
                            "wire_latency_p99_us_device_wire": round(
                                d99, 1),
                            "wire_latency_host_vs_device_ratio": round(
                                ratio, 3),
                            "wire_latency_host_device_divergent": int(
                                ratio > 2.0 or (0 < ratio < 1.0)),
                        })
            finally:
                ppump.stop()
        except Exception as exc:  # noqa: BLE001 — report, keep section
            out["io_wire_persistent_error"] = (
                f"{type(exc).__name__}: {exc}")
        return out
    finally:
        # unconditional: an exception in the DISPATCH phase must not
        # leak the shared-memory ring pair either
        rings.close()


def hoststack_bench(args, duration_s: float = 2.5) -> dict:
    """RPS/CPS under policy — the reference's wrk perf harness analog
    (tests/policy/perf/RPS.sh, CPS.sh: 50 connections, keep-alive vs
    Connection: close) over the VCL session-filtered host stack.

    A server app namespace answers a minimal request/response protocol
    on loopback; a client namespace drives it with the session-rule
    engine packed to a gen-policy.py-shaped 1000-rule set. Session
    rules filter connection SETUP (VPP session-layer semantics), so RPS
    measures the steady state while CPS pays an admission check per
    wave — client connects ride connect_batch (one engine batch per
    wave), server accepts are admission-checked in waves too. Also
    reports the engine's raw batched admission capacity, the device
    ceiling on CPS."""
    import threading

    from vpp_tpu.hoststack.session_rules import (
        RuleAction,
        RuleScope,
        SessionRule,
        SessionRuleEngine,
    )
    from vpp_tpu.hoststack.vcl import HostStackApp, _ip_int

    LOOP = _ip_int("127.0.0.1")
    engine = SessionRuleEngine(capacity=2048)

    # gen-policy-shaped filler: 1000 CIDR x port rules (5:1 permit:deny)
    filler = []
    for i in range(996):
        net = ((10 << 24) | ((i // 250) << 16) | ((i % 250) << 8))
        filler.append(SessionRule(
            scope=int(RuleScope.LOCAL), appns_index=1, transport_proto=6,
            lcl_net=0, lcl_plen=0, rmt_net=net, rmt_plen=24,
            lcl_port=0, rmt_port=8000 + i % 20,
            action=int(RuleAction.DENY if i % 6 == 5 else RuleAction.ALLOW),
        ))
    engine.apply(add=filler)

    server = HostStackApp(engine, appns_index=2)
    srv = server.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(256)
    port = srv.getsockname()[1]

    # specific admits over default-deny in BOTH scopes, so the connect
    # check (LOCAL) and the accept check (GLOBAL) each decide something
    # real — the engine default-allows unmatched connections, so the
    # deny-alls are what make the allows load-bearing
    engine.apply(add=[
        SessionRule(scope=int(RuleScope.LOCAL), appns_index=1,
                    transport_proto=6, lcl_net=0, lcl_plen=0,
                    rmt_net=LOOP, rmt_plen=32, lcl_port=0, rmt_port=port,
                    action=int(RuleAction.ALLOW)),
        SessionRule(scope=int(RuleScope.LOCAL), appns_index=1,
                    transport_proto=6, lcl_net=0, lcl_plen=0,
                    rmt_net=0, rmt_plen=0, lcl_port=0, rmt_port=0,
                    action=int(RuleAction.DENY)),
        SessionRule(scope=int(RuleScope.GLOBAL), appns_index=-1,
                    transport_proto=6, lcl_net=LOOP, lcl_plen=32,
                    rmt_net=0, rmt_plen=0, lcl_port=port, rmt_port=0,
                    action=int(RuleAction.ALLOW)),
        SessionRule(scope=int(RuleScope.GLOBAL), appns_index=-1,
                    transport_proto=6, lcl_net=0, lcl_plen=0,
                    rmt_net=0, rmt_plen=0, lcl_port=0, rmt_port=0,
                    action=int(RuleAction.DENY)),
    ])

    client = HostStackApp(engine, appns_index=1)

    # warm every engine batch shape the timed windows can hit: check()
    # pads to powers of two and jits per padded shape, and a first
    # compile (20-40 s on TPU) inside a 2.5 s window would make the
    # reported RPS/CPS a compile-time artifact
    for shape in (8, 16, 32, 64):
        engine.check_connect([(1, 6, 0, 0, LOOP, port)] * shape)
        engine.check_accept([(6, LOOP, port, LOOP, 40000)] * shape)

    stop = threading.Event()

    def serve_conn(conn):
        try:
            while True:
                req = conn.recv(64)
                if not req:
                    return
                conn.sendall(b"HTTP/1.1 200 OK\r\n\r\nok")
        except OSError:
            pass
        finally:
            conn.close()

    def acceptor():
        """Wave admission via FilteredSocket.accept_batch: one engine
        batch per wave of pending connections (VPP filters inbound
        sessions in its session tables; waves are the batched form)."""
        while not stop.is_set():
            try:
                wave = srv.accept_batch(max_n=64, first_timeout=0.01)
            except OSError:
                return  # listener closed: shutdown
            for fconn, _peer in wave:
                threading.Thread(target=serve_conn, args=(fconn.sock,),
                                 daemon=True).start()

    acc = threading.Thread(target=acceptor, daemon=True)
    acc.start()
    out = {"hoststack_rules": engine.num_rules}
    try:
        # --- RPS: 50 persistent session-admitted connections ---
        conns = [c for c in client.connect_batch(
            [("127.0.0.1", port)] * 50) if c is not None]
        if len(conns) != 50:
            raise RuntimeError(f"admission failed: {len(conns)}/50")
        for c in conns:
            c.settimeout(10)
        reqs = 0
        deadline = time.perf_counter() + duration_s
        t0 = time.perf_counter()
        while time.perf_counter() < deadline:
            c = conns[reqs % 50]
            c.send(b"GET / HTTP/1.1\r\n\r\n")
            if not c.recv(64):
                raise RuntimeError("server closed mid-RPS")
            reqs += 1
        out["hoststack_rps"] = round(reqs / (time.perf_counter() - t0), 1)
        for c in conns:
            c.close()

        # --- CPS: connect+request+close, 32-wide admission waves ---
        done = 0
        deadline = time.perf_counter() + duration_s
        t0 = time.perf_counter()
        while time.perf_counter() < deadline:
            wave = [c for c in client.connect_batch(
                [("127.0.0.1", port)] * 32) if c is not None]
            for c in wave:
                c.settimeout(10)
                c.send(b"GET / HTTP/1.1\r\nConnection: close\r\n\r\n")
                if c.recv(64):
                    done += 1
                c.close()
        out["hoststack_cps"] = round(done / (time.perf_counter() - t0), 1)

        # --- raw admission capacity: 4096-conn batched checks ---
        rng = np.random.default_rng(5)
        batch = [(1, 6, 0, 0, int(x), 8000 + int(x) % 20)
                 for x in rng.integers(10 << 24, (10 << 24) + (1 << 20),
                                       4096)]
        engine.check_connect(batch)  # compile/warm
        t0 = time.perf_counter()
        iters = 20
        for _ in range(iters):
            engine.check_connect(batch)
        # hoststack policy-engine connect-check rate — renamed from
        # "session_admission_ksps" when the session-table scale bench
        # (session_scale_bench) claimed that key: hoststack_bench runs
        # AFTER the priority sections merge into the final details, so
        # the shared name silently overwrote the table's admission rate
        out["hoststack_admission_ksps"] = round(
            4096 * iters / (time.perf_counter() - t0) / 1e3, 1
        )

        # --- ldpreload iperf analog (BASELINE row: pod<->pod iperf,
        # kernel stack vs VCL/ldpreload,
        # tests/robot/suites/one_node_two_pods_ldpreload_iperf.robot):
        # bulk TCP between two REAL subprocesses, once bare-kernel and
        # once under libvclshim.so admission. Session rules filter
        # connection SETUP only, so the two should track each other —
        # the VCL number proves policy admission costs nothing on the
        # data path.
        try:
            out.update(vcl_iperf_bench(engine))
        except Exception as e:  # noqa: BLE001 — optional, env-dependent
            out["vcl_iperf_error"] = f"{type(e).__name__}: {e}"
        return out
    finally:
        stop.set()
        srv.close()


def proxy_chain_bench(args, duration_s: float = 2.5,
                      n_rules: int = 10240) -> dict:
    """nginx-istio analog (BASELINE config #5, reference
    tests/nginx-istio/nginx-envoy.yaml): HTTP client → proxy → backend
    with the session-policy engine at gen-policy scale (10,240 rules)
    between EVERY hop — four jitted admission verdicts per fresh chain
    (client connect, proxy accept, proxy upstream connect, backend
    accept). RPS = keep-alive steady state through both hops (the
    wrk-shaped number); CPS = full fresh chains per second. The e2e
    form of the same chain (real subprocesses under the LD_PRELOAD
    shim, fail-closed) is tests/test_proxy_chain_e2e.py."""
    import threading

    from vpp_tpu.hoststack.scenarios import (
        gen_policy_filler,
        proxy_chain_rules,
    )
    from vpp_tpu.hoststack.session_rules import SessionRuleEngine
    from vpp_tpu.hoststack.vcl import HostStackApp, _ip_int

    LOOP = _ip_int("127.0.0.1")
    CLIENT_NS, PROXY_NS, BACKEND_NS = 1, 2, 3
    engine = SessionRuleEngine(capacity=16384)
    engine.apply(add=gen_policy_filler(n_rules - 7))

    backend_app = HostStackApp(engine, appns_index=BACKEND_NS)
    bsrv = backend_app.socket()
    bsrv.bind(("127.0.0.1", 0))
    bsrv.listen(256)
    bport = bsrv.getsockname()[1]
    proxy_app = HostStackApp(engine, appns_index=PROXY_NS)
    psrv = proxy_app.socket()
    psrv.bind(("127.0.0.1", 0))
    psrv.listen(256)
    pport = psrv.getsockname()[1]

    # the mesh seam: each namespace may reach exactly its next hop,
    # deny-all underneath — the verdicts are load-bearing at 10k rules
    engine.apply(add=proxy_chain_rules(LOOP, CLIENT_NS, PROXY_NS,
                                       pport, bport))
    client_app = HostStackApp(engine, appns_index=CLIENT_NS)

    # warm the engine's padded batch shapes (jit-per-shape)
    for shape in (8, 16, 32, 64):
        engine.check_connect([(CLIENT_NS, 6, 0, 0, LOOP, pport)] * shape)
        engine.check_accept([(6, LOOP, pport, LOOP, 40000)] * shape)

    BODY = b"x" * 64
    RESP = (b"HTTP/1.1 200 OK\r\nContent-Length: %d\r\n\r\n"
            % len(BODY)) + BODY
    RESP_LEN = len(RESP)
    REQ = b"GET / HTTP/1.1\r\nHost: b\r\n\r\n"
    stop = threading.Event()

    def recv_exact(sock, n):
        buf = b""
        while len(buf) < n:
            d = sock.recv(n - len(buf))
            if not d:
                return buf
            buf += d
        return buf

    def serve_backend(conn):
        try:
            while True:
                if not recv_exact(conn, len(REQ)):
                    return
                conn.sendall(RESP)
        except OSError:
            pass
        finally:
            conn.close()

    def serve_proxy(conn):
        """One upstream per downstream (Envoy's per-connection HTTP/1.1
        upstream), both keep-alive; the upstream connect is the third
        admission verdict of the chain."""
        ups = None
        try:
            ups = proxy_app.socket()
            ups.settimeout(10)
            ups.connect(("127.0.0.1", bport))
            while True:
                req = recv_exact(conn, len(REQ))
                if not req:
                    return
                ups.sendall(req)
                rsp = recv_exact(ups.sock, RESP_LEN)
                if not rsp:
                    return
                conn.sendall(rsp)
        except OSError:
            pass
        finally:
            if ups is not None:
                ups.close()
            conn.close()

    def acceptor(listener, handler):
        def run():
            while not stop.is_set():
                try:
                    wave = listener.accept_batch(max_n=64,
                                                 first_timeout=0.01)
                except OSError:
                    return
                for fconn, _peer in wave:
                    threading.Thread(target=handler, args=(fconn.sock,),
                                     daemon=True).start()
        t = threading.Thread(target=run, daemon=True)
        t.start()
        return t

    acceptor(bsrv, serve_backend)
    acceptor(psrv, serve_proxy)
    out = {"nginx_istio_rules": engine.num_rules}
    try:
        # --- RPS: 50 keep-alive chains (wrk-shaped) ---
        conns = [c for c in client_app.connect_batch(
            [("127.0.0.1", pport)] * 50) if c is not None]
        if len(conns) != 50:
            raise RuntimeError(f"chain admission failed: {len(conns)}/50")
        for c in conns:
            c.settimeout(10)
        reqs = 0
        deadline = time.perf_counter() + duration_s
        t0 = time.perf_counter()
        while time.perf_counter() < deadline:
            c = conns[reqs % 50]
            c.sendall(REQ)
            if len(recv_exact(c.sock, RESP_LEN)) != RESP_LEN:
                raise RuntimeError("chain closed mid-RPS")
            reqs += 1
        out["nginx_istio_rps"] = round(reqs / (time.perf_counter() - t0), 1)
        for c in conns:
            c.close()

        # --- CPS: full fresh chains (4 admission verdicts each) ---
        done = 0
        deadline = time.perf_counter() + duration_s
        t0 = time.perf_counter()
        while time.perf_counter() < deadline:
            wave = [c for c in client_app.connect_batch(
                [("127.0.0.1", pport)] * 16) if c is not None]
            for c in wave:
                c.settimeout(10)
                c.sendall(REQ)
                if len(recv_exact(c.sock, RESP_LEN)) == RESP_LEN:
                    done += 1
                c.close()
        out["nginx_istio_cps"] = round(done / (time.perf_counter() - t0), 1)
        return out
    finally:
        stop.set()
        psrv.close()
        bsrv.close()
        # let serve threads drain out of any in-flight jitted admission
        # check: a daemon thread killed inside an XLA call at
        # interpreter exit aborts the process (observed as "FATAL:
        # exception not rethrown" when this bench ran last)
        time.sleep(0.25)


def vcl_iperf_bench(engine, mb: int = 256, port: int = 15201) -> dict:
    """Bulk-transfer Gbps over loopback: bare kernel vs under the
    LD_PRELOAD session shim (admission served from ``engine``).

    The engine arrives with hoststack_bench's deny-alls installed in
    both scopes, so the iperf port needs explicit admits — which makes
    the shim's verdicts load-bearing, same as the RPS section."""
    import subprocess
    import tempfile

    from vpp_tpu.hoststack.admission import VclAdmissionServer
    from vpp_tpu.hoststack.preload import vcl_env
    from vpp_tpu.hoststack.session_rules import (
        RuleAction, RuleScope, SessionRule,
    )
    from vpp_tpu.hoststack.vcl import _ip_int

    LOOP = _ip_int("127.0.0.1")
    engine.apply(add=[
        SessionRule(scope=int(RuleScope.LOCAL), appns_index=1,
                    transport_proto=6, lcl_net=0, lcl_plen=0,
                    rmt_net=LOOP, rmt_plen=32, lcl_port=0, rmt_port=port,
                    action=int(RuleAction.ALLOW)),
        SessionRule(scope=int(RuleScope.GLOBAL), appns_index=-1,
                    transport_proto=6, lcl_net=LOOP, lcl_plen=32,
                    rmt_net=0, rmt_plen=0, lcl_port=port, rmt_port=0,
                    action=int(RuleAction.ALLOW)),
    ])

    total = mb << 20
    server_code = (
        "import socket, sys\n"
        "ls = socket.socket()\n"
        "ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
        f"ls.bind((\"127.0.0.1\", {port}))\n"
        "ls.listen(1)\n"
        "print(ls.getsockname()[1], flush=True)\n"
        "c, _ = ls.accept()\n"
        "buf = memoryview(bytearray(1 << 20))\n"
        "n = 0\n"
        "while True:\n"
        "    r = c.recv_into(buf)\n"
        "    if not r:\n"
        "        break\n"
        "    n += r\n"
        "print(n)\n"
    )
    client_code = (
        "import socket, sys, time\n"
        f"total = {total}\n"
        "c = socket.create_connection((\"127.0.0.1\", int(sys.argv[1])),"
        " timeout=30)\n"
        "chunk = b\"x\" * (1 << 20)\n"
        "t0 = time.perf_counter()\n"
        "sent = 0\n"
        "while sent < total:\n"
        "    c.sendall(chunk)\n"
        "    sent += len(chunk)\n"
        "c.close()\n"
        "print(time.perf_counter() - t0)\n"
    )

    def one(env) -> float:
        srv_p = subprocess.Popen([sys.executable, "-c", server_code],
                                 env=env, stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE, text=True)
        try:
            port = int(srv_p.stdout.readline())
            cli = subprocess.run([sys.executable, "-c", client_code,
                                  str(port)], env=env,
                                 capture_output=True, text=True,
                                 timeout=120)
            if cli.returncode != 0:
                raise RuntimeError(f"iperf client: {cli.stderr[-300:]}")
            dt = float(cli.stdout.strip())
            got = int(srv_p.stdout.readline())
            if got != total:
                raise RuntimeError(f"iperf short read {got}/{total}")
            return total * 8 / dt / 1e9
        finally:
            srv_p.kill()
            srv_p.wait(timeout=10)

    kernel_gbps = one(dict(os.environ))
    with tempfile.TemporaryDirectory() as td:
        sock = os.path.join(td, "vcl.sock")
        adm = VclAdmissionServer(engine, sock).start()
        try:
            vcl_gbps = one(vcl_env(sock, appns_index=1))
        finally:
            adm.stop()
    return {
        "iperf_kernel_gbps": round(kernel_gbps, 2),
        "iperf_vcl_ldpreload_gbps": round(vcl_gbps, 2),
    }


def io_daemon_bench(args, duration_s: float = 5.0) -> dict:
    """Real-packet throughput through the FULL node data path: kernel
    veth → AF_PACKET → IO daemon (recvmmsg batch rx) → rx ring →
    pipelined pump → device pipeline → tx ring → daemon (sendmmsg batch
    tx) → AF_PACKET → kernel veth. The reference's whole purpose is
    moving real packets (SURVEY §3.5); this is the number a deployed
    node actually sees. Skipped (empty dict) without CAP_NET_ADMIN."""
    import subprocess

    import jax as _jax

    def sh(*a):
        return subprocess.run(["ip", *a], capture_output=True, timeout=15)

    # capability check + fixture
    created = []
    for pair in (("vppbnA0", "vppbnA1"), ("vppbnB0", "vppbnB1")):
        sh("link", "del", pair[0])
        if sh("link", "add", pair[0], "type", "veth", "peer", "name",
              pair[1]).returncode != 0:
            for leg in created:  # don't leak a half-built fixture
                sh("link", "del", leg)
            return {}
        created.append(pair[0])
        for leg in pair:
            sh("link", "set", leg, "up")

    # everything from here runs under the cleanup block: a failing
    # import/compile/ring setup (busy TPU is a realistic one) must not
    # leak the veth pairs onto the host
    rings = daemon = pump = ppump = None
    try:
        from vpp_tpu.io.daemon import IODaemon
        from vpp_tpu.io.pump import DataplanePump
        from vpp_tpu.io.rings import IORingPair
        from vpp_tpu.io.transport import AfPacketTransport
        from vpp_tpu.pipeline.dataplane import Dataplane
        from vpp_tpu.pipeline.tables import DataplaneConfig
        from vpp_tpu.pipeline.vector import VEC, Disposition

        dp = Dataplane(DataplaneConfig())
        if_a = dp.add_pod_interface(("default", "a"))
        if_b = dp.add_pod_interface(("default", "b"))
        dp.builder.add_route("10.1.1.3/32", if_b, Disposition.LOCAL)
        dp.swap()

        rings = IORingPair(n_slots=256, snap=512)
        daemon = IODaemon(
            rings,
            {if_a: AfPacketTransport("vppbnA0"),
             if_b: AfPacketTransport("vppbnB0")},
            uplink_if=0,
        ).start()
        # the deployed ladder shape (cmd/config.py IOConfig defaults):
        # auto fetch workers + the adaptive chainer armed
        pump = DataplanePump(dp, rings, max_batch=16384, chain_k=4)
        pump.warm()
        pump.start()

        # warm-up barrier: one real packet through veth → daemon →
        # device → daemon before the measured window, so the window
        # never pays dispatch ramp + first fetch RTT (zeroed the r3
        # number on a slow tunnel). The warm frame reaches vppbnB1
        # before the receiver binds — unaccounted by design.
        warm_tx = AfPacketTransport("vppbnA1")
        warm_deadline = time.perf_counter() + 120
        while (pump.stats["frames"] == 0
               and time.perf_counter() < warm_deadline):
            warm_tx.send_frame(wire_udp(0))
            time.sleep(0.2)
        warm_tx.close()
        # drain to quiescence: warm frames still in the rx ring /
        # in-flight batches would otherwise reach vppbnB1 after the
        # receiver binds and count in 'got' but never in 'offered'
        stable_since = time.perf_counter()
        stable_count = pump.stats["frames"]
        while time.perf_counter() < warm_deadline:
            time.sleep(0.1)
            now, cnt = time.perf_counter(), pump.stats["frames"]
            if cnt != stable_count:
                stable_count, stable_since = cnt, now
            elif now - stable_since > 1.5:
                break
        # report window-only pump counters: warm-up traffic must not
        # mask "zero frames moved during the measured window"
        pump_base = dict(pump.stats)

        # sender/receiver as SUBPROCESSES: in-process Python threads
        # would fight the daemon+pump threads for the GIL and the
        # receiver would undercount by dropping at its own socket —
        # separate interpreters measure the daemon, not the harness.
        # (They import only the native codec + transports, no jax.)
        env = dict(os.environ)
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.abspath(__file__))
            + os.pathsep + env.get("PYTHONPATH", "")
        )
        def make_sender(pace_pps: float | None) -> str:
            if pace_pps is None:
                loop = (
                    "while time.perf_counter() < deadline:\n"
                    "    k = codec.send_batch(t.batch_fd, payload, rows, "
                    "lens, VEC)\n"
                    "    sent += k\n"
                    "    if k < VEC:\n"
                    "        time.sleep(0.0005)\n"
                )
            else:
                # paced: BURST frames per interval, absolute schedule
                # (next_t += interval) so pacing error doesn't accumulate
                loop = (
                    "BURST = 64\n"
                    f"interval = BURST / {pace_pps}\n"
                    "next_t = t0\n"
                    "while True:\n"
                    "    now = time.perf_counter()\n"
                    "    if now >= deadline:\n"
                    "        break\n"
                    "    if now < next_t:\n"
                    "        time.sleep(min(next_t - now, 0.001))\n"
                    "        continue\n"
                    "    k = codec.send_batch(t.batch_fd, payload, rows, "
                    "lens, BURST)\n"
                    "    sent += k\n"
                    "    next_t += interval\n"
                )
            return (
                "import time\n"
                "import numpy as np\n"
                "from bench import wire_udp\n"
                "from vpp_tpu.io.transport import AfPacketTransport\n"
                "from vpp_tpu.native.pktio import PacketCodec\n"
                "VEC = 256\n"
                "codec = PacketCodec(snap=512)\n"
                "t = AfPacketTransport('vppbnA1')\n"
                "payload = np.zeros((VEC, 512), np.uint8)\n"
                "lens = np.zeros(VEC, np.uint32)\n"
                "for i in range(VEC):\n"
                "    f = wire_udp(i)\n"
                "    payload[i, :len(f)] = np.frombuffer(f, np.uint8)\n"
                "    lens[i] = len(f)\n"
                "rows = np.arange(VEC, dtype=np.uint32)\n"
                # the sender times its own loop: interpreter/numpy
                # startup and frame building must not dilute the window
                "t0 = time.perf_counter()\n"
                f"deadline = t0 + {duration_s}\n"
                "sent = 0\n"
                + loop +
                "print(sent, time.perf_counter() - t0)\n"
            )
        recv_code = (
            "import socket, time\n"
            "import numpy as np\n"
            "from vpp_tpu.io.transport import AfPacketTransport\n"
            "from vpp_tpu.native.pktio import PacketCodec\n"
            "codec = PacketCodec(snap=512)\n"
            "t = AfPacketTransport('vppbnB1')\n"
            "SO_RCVBUFFORCE = 33\n"
            "t.sock.setsockopt(socket.SOL_SOCKET, SO_RCVBUFFORCE,\n"
            "                  256 << 20)\n"  # past rmem_max (CAP_NET_ADMIN)
            "print('READY', flush=True)\n"
            "scratch = np.zeros((256, 512), np.uint8)\n"
            "lens = np.zeros(256, np.uint32)\n"
            f"deadline = time.perf_counter() + {duration_s + 10.0}\n"
            "got, idle_since = 0, None\n"
            "while time.perf_counter() < deadline:\n"
            "    n = codec.recv_batch(t.batch_fd, scratch, lens)\n"
            "    if n > 0:\n"
            "        got += n\n"
            "        idle_since = None\n"
            "    else:\n"
            "        now = time.perf_counter()\n"
            "        if idle_since is None:\n"
            "            idle_since = now\n"
            f"        elif got and now - idle_since > 1.5:\n"
            "            break\n"  # sender done, queue drained
            "        time.sleep(0.0002)\n"
            "print(got)\n"
        )
        def run_round(pace_pps: float | None):
            """One sender/receiver subprocess round; returns
            (offered, got, send_window_s)."""
            recv_proc = subprocess.Popen(
                [sys.executable, "-c", recv_code], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            # wait for the receiver's socket to exist before offering
            # load — frames forwarded to vppbnB1 before the bind are
            # unaccountable
            ready = recv_proc.stdout.readline()
            if "READY" not in ready:
                _, r_err = recv_proc.communicate(timeout=30)
                raise RuntimeError(
                    f"receiver failed to start: {r_err[-300:]}")
            send_proc = subprocess.Popen(
                [sys.executable, "-c", make_sender(pace_pps)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            s_out, s_err = send_proc.communicate(timeout=duration_s + 60)
            r_out, r_err = recv_proc.communicate(timeout=duration_s + 60)
            # a dead endpoint must surface as an ERROR, not as a
            # plausible 0.0 Mpps datum
            if send_proc.returncode != 0 or not s_out.strip():
                raise RuntimeError(f"sender failed: {s_err[-300:]}")
            if recv_proc.returncode != 0 or not r_out.strip():
                raise RuntimeError(f"receiver failed: {r_err[-300:]}")
            offered_s, window_s = s_out.split()
            return int(offered_s), int(r_out.strip()), float(window_s)

        def wait_quiesce(p) -> None:
            """Let in-flight traffic drain through pump ``p``, under a
            HARD cap — trickling background frames (e.g. kernel ND
            chatter) must not reset the wait forever."""
            q_deadline = time.perf_counter() + 20
            q_since, q_cnt = time.perf_counter(), p.stats["frames"]
            while time.perf_counter() < q_deadline:
                time.sleep(0.1)
                cnt = p.stats["frames"]
                if cnt != q_cnt:
                    q_cnt, q_since = cnt, time.perf_counter()
                elif time.perf_counter() - q_since > 1.5:
                    break

        offered, got, send_window = run_round(None)
        # snapshot NOW: the reported pump window counters must cover
        # exactly the saturation round they are named for, not the
        # quiesce drain + paced round that follow
        pump_sat = dict(pump.stats)

        # paced round: offer at ~60% of the measured saturation
        # DELIVERY rate — the deployment regime (goodput at a
        # sustainable load), vs the saturation row where sender-side
        # kernel drops dominate on a shared core (docs/IO_PATH.md).
        # A fresh flow set would re-miss the session cache, so reuse.
        paced = {}
        sat_pps = got / send_window
        if sat_pps > 0:
            try:
                wait_quiesce(pump)
                # the latency window must cover exactly this paced
                # round — saturation-round batches in the deque would
                # report queueing delay as paced latency
                pump.reset_latency()
                p_off, p_got, p_win = run_round(
                    max(sat_pps * 0.6, 5_000.0))
                paced = {
                    "io_daemon_paced_mpps": round(p_got / p_win / 1e6, 4),
                    "io_daemon_paced_offered_mpps": round(
                        p_off / p_win / 1e6, 4),
                    "io_daemon_paced_goodput_pct": round(
                        100.0 * p_got / p_off, 1) if p_off else 0.0,
                }
            except Exception as e:  # noqa: BLE001 — the paced round is
                # additive; its failure must not discard the measured
                # saturation numbers
                paced = {"io_daemon_paced_error":
                         f"{type(e).__name__}: {e}"}

        # persistent-mode round on the SAME deployed path (VERDICT r4
        # Next #2: experienced wire latency in both pump modes). The
        # resident loop is the latency-floor regime — one frame per
        # loop iteration — so pacing it at the DISPATCH ladder's rate
        # (the r5 methodology) asked it for throughput it
        # architecturally doesn't offer and booked the shortfall as
        # 61.7% goodput "loss". Measure ITS saturation first, then
        # pace at 60% of that: goodput at its own sustainable rate is
        # the deployment question (VERDICT r5 Next #2 done-condition).
        dlat = pump.latency_us()
        persistent = {}
        if sat_pps > 0:
            try:
                pump.stop()
                ppump = DataplanePump(dp, rings, mode="persistent")
                ppump.warm()
                ppump.start()
                wait_quiesce(ppump)
                pp_soff, pp_sgot, pp_swin = run_round(None)
                pp_sat_pps = pp_sgot / pp_swin
                wait_quiesce(ppump)
                ppump.reset_latency()  # warm/sat frames excluded
                pp_off, pp_got, pp_win = run_round(
                    max(pp_sat_pps * 0.6, 5_000.0))
                plat = ppump.latency_us()
                # drop-cause attribution (ISSUE 7 satellite): the r5
                # goodput pct hid WHERE loss happened — split it into
                # daemon rx-ring overflow vs pump tx stall vs shutdown
                # so a bad number is diagnosable from the JSON alone
                rwin = int(ppump.stats.get("ring_windows", 0))
                persistent = {
                    "io_daemon_persistent_sat_mpps": round(
                        pp_sat_pps / 1e6, 4),
                    "io_daemon_persistent_mpps": round(
                        pp_got / pp_win / 1e6, 4),
                    "io_daemon_persistent_goodput_pct": round(
                        100.0 * pp_got / max(1, pp_off), 1),
                    "io_daemon_persistent_drops_rx_full": int(
                        daemon.stats.get("drops_rx_full", 0)),
                    "io_daemon_persistent_drops_tx_stall": int(
                        ppump.stats.get("drops_tx_stall", 0)),
                    "io_daemon_persistent_drops_shutdown": int(
                        ppump.stats.get("drops_shutdown", 0)),
                    "io_daemon_persistent_ring_windows": rwin,
                    "io_daemon_persistent_callbacks_per_window": round(
                        int(ppump.stats.get("io_callbacks", 0))
                        / max(1, rwin), 4),
                }
                if plat["n"]:
                    persistent.update({
                        "io_daemon_persistent_pump_lat_p50_us": round(
                            plat["p50"], 1),
                        "io_daemon_persistent_pump_lat_p99_us": round(
                            plat["p99"], 1),
                    })
            except Exception as e:  # noqa: BLE001 — additive round
                persistent = {"io_daemon_persistent_error":
                              f"{type(e).__name__}: {e}"}

        # rate over the offered window (the receiver's post-drain of its
        # kernel queue belongs to that window's traffic)
        return {
            **paced,
            **persistent,
            # n == 0 means the paced round died after reset_latency():
            # omitting beats emitting a plausible-perfect 0.0 datum
            **({"io_daemon_pump_lat_p50_us": round(dlat["p50"], 1),
                "io_daemon_pump_lat_p99_us": round(dlat["p99"], 1)}
               if dlat["n"] else {}),
            "io_daemon_veth_mpps": round(got / send_window / 1e6, 4),
            # the acceptance-named alias of the veth saturation row
            "io_daemon_mpps": round(got / send_window / 1e6, 4),
            "io_daemon_offered_mpps": round(offered / send_window / 1e6, 4),
            # the overlap ladder's shape + activity in the window
            "io_daemon_fetch_workers": pump.workers,
            "io_daemon_max_inflight": pump.max_inflight,
            "io_daemon_chain_k": pump.chain_k,
            "io_daemon_chain_batches":
                pump_sat["chain_batches"] - pump_base["chain_batches"],
            "io_daemon_inflight_peak": pump_sat["inflight_peak"],
            # diagnosability: what the pump actually moved during the
            # measured window, warm-up excluded (a zero delivered count
            # with nonzero pump frames points at the tx side; zero pump
            # frames points at rx/dispatch)
            "io_daemon_pump_frames":
                pump_sat["frames"] - pump_base["frames"],
            "io_daemon_pump_batches":
                pump_sat["batches"] - pump_base["batches"],
            # per-stage pump time attribution (cumulative seconds in
            # the window): which leg of ring->device->ring bounds the
            # wire path (VERDICT r3 Weak #3 diagnosability)
            "io_daemon_t_pack_s": round(
                pump_sat["t_pack"] - pump_base["t_pack"], 3),
            "io_daemon_t_dispatch_s": round(
                pump_sat["t_dispatch"] - pump_base["t_dispatch"], 3),
            # fetch split (io/pump.py): t_fetch is the serial result
            # COPY; t_fetch_wait is waiting for results to become
            # ready — overlapped across the in-flight window, i.e.
            # hidden time, reported so the overlap is observable
            "io_daemon_t_fetch_s": round(
                pump_sat["t_fetch"] - pump_base["t_fetch"], 3),
            "io_daemon_t_fetch_wait_s": round(
                pump_sat["t_fetch_wait"] - pump_base["t_fetch_wait"], 3),
            "io_daemon_t_write_s": round(
                pump_sat["t_write"] - pump_base["t_write"], 3),
        }
    finally:
        if pump is not None:
            pump.stop()
        if ppump is not None:
            ppump.stop()
        if daemon is not None:
            daemon.stop()
            for t in daemon.transports.values():
                t.close()
        if rings is not None:
            rings.close()
        for leg in ("vppbnA0", "vppbnB0"):
            sh("link", "del", leg)


def fleet_bench(args, frame_pkts: int = 1024, iters: int = 8) -> dict:
    """Gateway fleet: elastic scale-out + live rebalance (ISSUE 18).

    Scale-out ladder — N in {1, 2, 4} identical sym-hash instances
    behind one FleetSteering tier, the SAME offered load per rung.
    The deployment model is one instance per host, so each instance's
    packed-step throughput is measured SEQUENTIALLY (they never share
    this harness's cores inside a sample) and the rung aggregates as
    parallel capacity: ``offered / (steer + max(per-instance))``. The
    steering tier's partition cost is charged as a serial prefix — the
    rung only scales if steering stays cheap relative to the step.
    Acceptance: fleet_scaleout_ratio (per-doubling geometric mean)
    >= 1.8. CPU-harness caveat: the sequential-measure/sum framing is
    what makes the rung meaningful on one host; on a real multi-host
    deployment the same keys measure true aggregate.

    Live rebalance — a 2-instance fleet takes a 3rd member under
    continuous FleetPump load; the newcomer's rendezvous-won ranges
    migrate live (fence → drain → adopt → commit → release). Keys
    prove the tentpole bar: EXACT conservation (zero unattributed
    loss), bounded dispatch p99 across the move, and fastpath
    hit-rate >= 0.9 on the migrated flows within a bounded number of
    post-move windows.
    """
    import threading

    import jax as _jax

    from vpp_tpu.fleet.hashring import assign_ranges
    from vpp_tpu.fleet.membership import FleetMembership
    from vpp_tpu.fleet.steering import FleetSteering
    from vpp_tpu.io.fleet import FleetPump
    from vpp_tpu.ir.rule import Action, ContivRule, Protocol
    from vpp_tpu.kvstore.store import KVStore
    from vpp_tpu.pipeline.dataplane import (
        Dataplane,
        pack_packet_columns,
    )
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import Disposition

    shrink = _jax.default_backend() == "cpu" and not args.cpu_full
    if shrink:
        frame_pkts, iters = 512, 4
    n_frames = 32 if shrink else 64
    sess_slots = (1 << 16) if shrink else (1 << 18)
    # many ranges per instance smooth the rendezvous spread — a
    # 4-member rung owns ~16 ranges each, so per-host load imbalance
    # stays small and the ladder measures steering + step cost, not
    # assignment variance
    n_ranges = 64

    def mk_dp():
        cfg = DataplaneConfig(
            max_tables=2, max_rules=16, max_global_rules=16,
            max_ifaces=8, fib_slots=16, sess_slots=sess_slots,
            sess_ways=4, nat_mappings=2, nat_backends=2,
            sess_sweep_stride=0, sess_hash="sym")
        dp = Dataplane(cfg)
        dp.add_uplink()
        dp.add_pod_interface(("default", "web"))
        dp.builder.add_route("10.1.1.0/24", 2, Disposition.LOCAL)
        dp.builder.set_global_table([
            ContivRule(action=Action.PERMIT, protocol=Protocol.TCP),
            ContivRule(action=Action.DENY)])
        dp.swap()
        return dp

    pod_ip = np.uint32((10 << 24) | (1 << 16) | (1 << 8) | 2)

    def mk_frames(n_fr, width, reply=False):
        """Packed [5, width] frames of distinct TCP flows; ``reply``
        reverses direction (same canonical buckets under sym hash)."""
        out = []
        for f in range(n_fr):
            flow = f * width + np.arange(width)
            src = (np.uint32((10 << 24) | (9 << 16))
                   + (flow % 65536).astype(np.uint32))
            sport = (1024 + flow % 40000).astype(np.int32)
            n = width
            cols = {
                "src_ip": np.full(n, pod_ip) if reply else src,
                "dst_ip": src if reply else np.full(n, pod_ip),
                "proto": np.full(n, 6, np.int32),
                "sport": np.full(n, 80, np.int32) if reply else sport,
                "dport": sport if reply else np.full(n, 80, np.int32),
                "ttl": np.full(n, 64, np.int32),
                "pkt_len": np.full(n, 64, np.int32),
                "rx_if": np.full(n, 2 if reply else 1, np.int32),
                "flags": np.ones(n, np.int32),
            }
            flat = np.zeros((5, n), np.int32)
            pack_packet_columns(flat.view(np.uint32), cols, n)
            out.append(flat)
        return out

    out: dict = {}
    fr = mk_frames(n_frames, frame_pkts)
    offered = n_frames * frame_pkts
    out["fleet_scaleout_pkts"] = offered
    rungs = (1, 2, 4)
    fleets = {}
    try:
        for n_inst in rungs:
            names = [f"gw{i}" for i in range(n_inst)]
            dps = {nm: mk_dp() for nm in names}
            st = FleetSteering(dps, n_ranges=n_ranges)
            # warm/compile once (instances share one geometry → one
            # cached packed step) before any timed sample
            for dp in dps.values():
                _jax.block_until_ready(
                    dp.process_packed(fr[0], commit=False))
            parts = [st.partition(f)[0] for f in fr]
            plan = []
            for nm in names:
                share = [np.ascontiguousarray(f[:, idx])
                         for f, groups in zip(fr, parts)
                         for idx in (groups.get(nm),)
                         if idx is not None and idx.size]
                cols = np.concatenate(share, axis=1)
                npk = cols.shape[1]
                pad = (-npk) % frame_pkts
                if pad:
                    cols = np.concatenate(
                        [cols, np.zeros((5, pad), np.int32)],
                        axis=1)
                inst_frames = [cols[:, i:i + frame_pkts]
                               for i in range(0, cols.shape[1],
                                              frame_pkts)]
                # equal-DURATION samples: scale iterations so every
                # sample moves the same packet total regardless of
                # share size (a quarter-share loop is otherwise so
                # short it fits inside one host-scheduler throttling
                # window and reads 30-40% slow)
                it = max(1, round(offered * iters / npk))
                plan.append((dps[nm], nm, npk, it, inst_frames))
            fleets[n_inst] = (st, plan)

        # INTERLEAVED best-of-3 over all rungs: the harness's
        # sustained rate drifts on ~minute timescales (burst credits,
        # frequency scaling), so measuring rung 1 minutes before rung
        # 4 folds host drift straight into the scaling ratio; a
        # round-robin pass hits every rung inside each drift window
        # and best-of picks each instance's sustained floor
        steer_best = {n: float("inf") for n in rungs}
        proc_best: dict = {}
        for _ in range(3):
            for n_inst in rungs:
                st, plan = fleets[n_inst]
                t0 = time.perf_counter()
                for f in fr:
                    st.partition(f)
                steer_best[n_inst] = min(
                    steer_best[n_inst], time.perf_counter() - t0)
                for dp, nm, npk, it, inst_frames in plan:
                    t0 = time.perf_counter()
                    res = None
                    for _ in range(it):
                        for flat in inst_frames:
                            res = dp.process_packed(flat,
                                                    commit=True)
                    _jax.block_until_ready(res)
                    _jax.block_until_ready(dp.tables.sess_valid)
                    dt = time.perf_counter() - t0
                    key = (n_inst, nm)
                    proc_best[key] = min(
                        proc_best.get(key, float("inf")), dt)

        mpps = {}
        for n_inst in rungs:
            st, plan = fleets[n_inst]
            # padded tail slots are processed but not credited — the
            # per-host rate only counts real packets; hosts run in
            # parallel (one instance per host) so their rates SUM,
            # and the dispatch tier's serial partition rate caps the
            # aggregate — the rung only scales while steering stays
            # off the critical path
            tput = [npk * it / proc_best[(n_inst, nm)]
                    for _, nm, npk, it, _f in plan]
            steer_rate = offered / steer_best[n_inst]
            mpps[n_inst] = min(sum(tput), steer_rate) / 1e6
            out[f"fleet_scaleout_mpps_{n_inst}"] = round(
                mpps[n_inst], 3)
        out["fleet_steer_ns_pkt"] = round(
            steer_best[4] / offered * 1e9, 1)
    finally:
        for st, _plan in fleets.values():
            st.close()
    out["fleet_scaleout_ratio"] = round(
        (mpps[4] / mpps[1]) ** 0.5, 2)

    # --- live rebalance under load -----------------------------------
    width = 256
    n_flows = 2048 if shrink else 8192
    fwd = mk_frames(n_flows // width, width)
    rev = mk_frames(n_flows // width, width, reply=True)
    names = ["gw0", "gw1", "gw2"]
    dps = {nm: mk_dp() for nm in names}
    st = FleetSteering(
        dps, membership=FleetMembership(KVStore(), name="bench"),
        n_ranges=n_ranges)
    pump = FleetPump(st, frame_width=width, queue_slots=256)

    def drain(timeout=60.0):
        pump.flush()
        t0 = time.perf_counter()
        while pump.pending() and time.perf_counter() - t0 < timeout:
            time.sleep(0.001)

    seen = {"hits": 0, "deliv": 0}

    def window(frames_list):
        lats = []
        for f in frames_list:
            t0 = time.perf_counter()
            pump.submit(f)
            lats.append(time.perf_counter() - t0)
        drain()
        snap = pump.stats_snapshot()
        hits = sum(a.get("sess_hits", 0)
                   for a in snap["aux"].values())
        deliv = sum(snap["delivered"].values())
        dh = hits - seen["hits"]
        dd = deliv - seen["deliv"]
        seen["hits"], seen["deliv"] = hits, deliv
        return lats, (dh / dd if dd else 0.0)

    try:
        # shrink the fleet to two members, then establish every flow
        st.rebalance(target=assign_ranges(["gw0", "gw1"], n_ranges))
        pump.start()
        for f in fwd:
            pump.submit(f)
        drain()
        # prime the per-window delta baseline PAST the establishment
        # phase (inserts, not hits) so window hit rates measure only
        # reply traffic
        snap0 = pump.stats_snapshot()
        seen["hits"] = sum(a.get("sess_hits", 0)
                           for a in snap0["aux"].values())
        seen["deliv"] = sum(snap0["delivered"].values())
        base_lats, base_hit = window(rev)
        out["fleet_rebalance_hit_rate_base"] = round(base_hit, 3)

        # the newcomer joins: default target re-runs rendezvous over
        # all three instances; its won ranges migrate live while
        # reply windows keep flowing through the pump
        ss0 = st.stats_snapshot()
        mover = threading.Thread(target=st.rebalance, daemon=True)
        move_lats: list = []
        mover.start()
        while mover.is_alive():
            lats, _ = window(rev)
            move_lats.extend(lats)
        mover.join()

        recovery = -1
        max_w = 10
        for w in range(1, max_w + 1):
            _, hit = window(rev)
            if hit >= 0.9:
                recovery = w
                break
        out["fleet_rebalance_hit_rate_final"] = round(hit, 3)
        out["fleet_rebalance_recovery_windows"] = recovery
        pump.stop()
        cons = pump.conservation()
        attributed = (cons["delivered"] + cons["fenced_drops"]
                      + cons["no_owner_drops"] + cons["queue_drops"]
                      + cons["pending"])
        out["fleet_rebalance_offered"] = cons["offered"]
        out["fleet_rebalance_delivered"] = cons["delivered"]
        out["fleet_rebalance_fenced_drops"] = cons["fenced_drops"]
        out["fleet_rebalance_queue_drops"] = cons["queue_drops"]
        out["fleet_rebalance_conservation_exact"] = int(
            cons["offered"] == attributed and cons["pending"] == 0)
        ss = st.stats_snapshot()
        out["fleet_rebalance_ranges_moved"] = (
            ss["migrated_ranges"] - ss0["migrated_ranges"])
        out["fleet_rebalance_sessions_moved"] = (
            ss["migrated_sessions"] - ss0["migrated_sessions"])
        out["fleet_rebalance_p99_ms_base"] = round(
            float(np.percentile(np.array(base_lats) * 1e3, 99)), 3)
        if move_lats:
            out["fleet_rebalance_p99_ms_move"] = round(
                float(np.percentile(np.array(move_lats) * 1e3, 99)), 3)
    finally:
        try:
            pump.stop()
        except Exception:  # noqa: BLE001 — already stopped
            pass
        st.close()
    return out


def overlay_bench(args, iters: int = 12, batch: int = 2048) -> dict:
    """Device-resident VXLAN overlay + svc NAT44 planes (ISSUE 19
    tentpole): three captures.

      * **encap overhead** — the deployed chain compiled overlay off
        vs vxlan over IDENTICAL east-west traffic at the headline rule
        count; the vxlan variant additionally runs the decap
        validator, the per-packet outer-header math and the outer-FIB
        walk INSIDE the one jitted program, so the delta IS the
        always-paid overlay cost (``overlay_encap_overhead_pct``,
        acceptance: <= 15).
      * **east-west round** — pod-to-pod across a 2-instance gateway
        fleet: VXLAN frames addressed to the anycast VTEP are spread
        by the steering tier (outer entropy sport — exactly how
        underlay ECMP spreads them), decapped on whichever instance
        owns the flow, delivered locally or re-encapped toward the
        destination node. Per-tenant VNI isolation: an unknown VNI
        fails CLOSED (drop_overlay) on every instance, conservation
        exact.
      * **backend churn** — a rolling service-backend replacement at
        svc scale ships ONLY the svc group's few-KB scatter blob
        (``svc_churn_bytes``; every non-svc device array carries over
        by identity) and keeps surviving backends' hash ways
        (``svc_sticky_kept_pct`` — only the replaced backend's flows
        move, with zero unattributed loss).

    CPU-harness caveat: overhead pct compares two compilations of the
    same chain on the same backend, so the RATIO is meaningful even
    when the absolute step cost is CPU-bound (the fleet_bench
    framing); on TPU the same keys price the real deployment.
    """
    import jax
    import jax.numpy as jnp

    from vpp_tpu.fleet.steering import FleetSteering
    from vpp_tpu.ir.rule import Action, ContivRule, Protocol
    from vpp_tpu.ops.vxlan import OUTER_TTL, VXLAN_PORT, ENCAP_OVERHEAD
    from vpp_tpu.pipeline.dataplane import Dataplane, pack_packet_columns
    from vpp_tpu.pipeline.graph import make_pipeline_step
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import (
        Disposition,
        FLAG_VALID,
        PacketVector,
        ip4,
    )

    shrink = jax.default_backend() == "cpu" and not args.cpu_full
    if shrink:
        iters = max(iters // 2, 4)
    out: dict = {"overlay_batch": batch, "overlay_rules": args.rules}

    # --- the overlay + svc gateway under test (parts 1 and 3) ---
    config = DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=args.rules + 1,
        max_ifaces=16, fib_slots=64, sess_slots=1 << 14,
        nat_mappings=4, nat_backends=4, overlay="vxlan",
        svc_vips=64, svc_backend_ways=8,
    )
    dp = Dataplane(config)
    uplink = dp.add_uplink()
    pod_if = dp.add_pod_interface(("default", "server"))
    dp.set_vtep(ip4("192.168.16.1"))
    dp.builder.add_route("10.1.1.0/24", pod_if, Disposition.LOCAL)
    # svc backends live behind the pod interface
    dp.builder.add_route("10.200.0.0/16", pod_if, Disposition.LOCAL)
    # 16 remote pod /24s, each behind a peer VTEP (inner FIB), plus the
    # VTEP underlay /24 the OUTER header resolves through — the second
    # FIB walk the vxlan variant pays every step
    for x in range(16):
        dp.builder.add_route(
            f"10.2.{x}.0/24", uplink, Disposition.REMOTE,
            next_hop=ip4(f"192.168.16.{2 + x % 8}"), node_id=2 + x)
    dp.builder.add_route("192.168.16.0/24", uplink, Disposition.REMOTE)
    rules = build_rules(args.rules)
    # VIP traffic (dport 80) rides the same table as the east-west mix
    rules.insert(0, ContivRule(action=Action.PERMIT,
                               protocol=Protocol.TCP, dest_port=80))
    dp.builder.set_global_table(rules)
    # 48 service VIPs x 4 backends: the svc planes at deployment scale
    # (64-row capacity), so the churn round exercises the incremental
    # blob path (the w-ladder needs blocks smaller than the VIP axis)
    vips = {}
    for v in range(48):
        key = (ip4(f"10.96.{v // 250}.{2 + v % 250}"), 80, 6)
        backends = [(ip4(f"10.200.{v}.10") + j, 80, 1) for j in range(4)]
        dp.builder.set_service(*key, backends)
        vips[v] = (key, backends)
    dp.swap()
    out["svc_full_upload_bytes"] = int(dp.builder.svc_upload["bytes"])

    # --- part 1: the always-paid overlay stage cost -------------------
    # East-west transit shaped on the rule grid (src block <-> dport
    # pairing of build_rules) so the batch actually forwards: permitted
    # frames take a REMOTE next_hop route and the vxlan variant
    # re-encaps them toward the peer VTEP on-device.
    rng = np.random.default_rng(19)
    ridx = rng.integers(0, max(args.rules - 1, 1), batch)
    ridx = ridx + (ridx % 6 == 5)  # step off the interleaved DENY rows
    block = ridx % 1000
    src = ((172 << 24) | ((16 + block // 256) << 16)
           | ((block % 256) << 8)
           | rng.integers(1, 255, batch)).astype(np.uint32)
    dst = ((10 << 24) | (2 << 16) | ((ridx % 16) << 8)
           | rng.integers(2, 250, batch)).astype(np.uint32)
    pkts = PacketVector(
        src_ip=jnp.asarray(src),
        dst_ip=jnp.asarray(dst),
        proto=jnp.full((batch,), 6, jnp.int32),
        sport=jnp.asarray(
            rng.integers(1024, 65535, batch).astype(np.int32)),
        dport=jnp.asarray(
            (8000 + (ridx // 1000) % 20).astype(np.int32)),
        ttl=jnp.full((batch,), 64, jnp.int32),
        pkt_len=jnp.full((batch,), 512, jnp.int32),
        rx_if=jnp.full((batch,), uplink, jnp.int32),
        flags=jnp.full((batch,), FLAG_VALID, jnp.int32),
    )
    impl, skip = dp.classifier_impl, dp._skip_local
    step_off = jax.jit(make_pipeline_step(impl, skip,
                                          fib_impl=dp.fib_impl))
    step_ovl = jax.jit(make_pipeline_step(impl, skip,
                                          fib_impl=dp.fib_impl,
                                          overlay="vxlan"))
    tables = dp.tables
    no_frames = jnp.full((batch,), -1, jnp.int32)  # plain-IP sidecar

    def med_us(step, *extra):
        jax.block_until_ready(step(tables, pkts, jnp.int32(2),
                                   *extra).disp)
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(step(tables, pkts, jnp.int32(2),
                                       *extra).disp)
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)) * 1e6

    t_off = med_us(step_off)
    t_ovl = med_us(step_ovl, pkts, no_frames)
    probe = step_ovl(tables, pkts, jnp.int32(2), pkts, no_frames)
    out["overlay_encap_pkts"] = int(probe.stats.ovl_encap)
    out["overlay_off_us"] = round(t_off, 1)
    out["overlay_on_us"] = round(t_ovl, 1)
    out["overlay_stage_ns_pkt"] = round(
        max(t_ovl - t_off, 0.0) / batch * 1e3, 2)
    out["overlay_encap_overhead_pct"] = round(
        100.0 * (t_ovl - t_off) / max(t_off, 1e-9), 2)

    # --- part 3: rolling backend replacement (zero-reship churn) ------
    # Flow fan toward one VIP; probe() observes the hash-way pick
    # without committing sessions, so stickiness below is the svc
    # plane's sticky fill — not session pinning.
    n_flows = 512
    vkey, vbackends = vips[7]
    frng = np.random.default_rng(23)
    vip_pkts = PacketVector(
        src_ip=jnp.asarray(
            (ip4("172.16.0.0")
             + frng.integers(1, 255, n_flows)).astype(np.uint32)),
        dst_ip=jnp.full((n_flows,), vkey[0], jnp.uint32),
        proto=jnp.full((n_flows,), 6, jnp.int32),
        sport=jnp.asarray(
            (1024 + np.arange(n_flows) * 13 % 50000).astype(np.int32)),
        dport=jnp.full((n_flows,), 80, jnp.int32),
        ttl=jnp.full((n_flows,), 64, jnp.int32),
        pkt_len=jnp.full((n_flows,), 128, jnp.int32),
        rx_if=jnp.full((n_flows,), uplink, jnp.int32),
        flags=jnp.full((n_flows,), FLAG_VALID, jnp.int32),
    )
    r0 = dp.probe(vip_pkts, now=3)
    picks0 = np.asarray(r0.pkts.dst_ip)
    ok0 = np.asarray(r0.disp) != int(Disposition.DROP)
    pins = (dp.tables.glb_src_net, dp.tables.acl_src_net,
            dp.tables.fib_prefix, dp.tables.tnt_vni)
    # roll ONE backend of ONE vip — the Deployment rolling-update beat
    replaced = vbackends[3]
    new_bk = (ip4("10.200.99.99"), 80, 1)
    with dp.commit_lock:
        dp.builder.set_service(*vkey, vbackends[:3] + [new_bk])
        dp.swap()
    up = dp.builder.svc_upload
    out["svc_churn_bytes"] = int(up["bytes"])
    out["svc_churn_blob_bytes"] = int(up["blob_bytes"])
    out["svc_churn_fields"] = len(up["fields"])
    out["svc_churn_ms"] = round(float(up["ms"]), 3)
    out["svc_churn_zero_reship"] = int(all(
        a is b for a, b in zip(pins, (
            dp.tables.glb_src_net, dp.tables.acl_src_net,
            dp.tables.fib_prefix, dp.tables.tnt_vni))))
    r1 = dp.probe(vip_pkts, now=4)
    picks1 = np.asarray(r1.pkts.dst_ip)
    ok1 = np.asarray(r1.disp) != int(Disposition.DROP)
    survivor = ok0 & (picks0 != np.uint32(replaced[0]))
    moved = ok0 & (picks0 == np.uint32(replaced[0]))
    out["svc_churn_flows"] = int(ok0.sum())
    out["svc_churn_loss"] = int(ok0.sum() - ok1.sum())
    out["svc_sticky_kept_pct"] = round(
        100.0 * float((picks1[survivor] == picks0[survivor]).mean())
        if survivor.any() else 100.0, 2)
    out["svc_moved_flows"] = int(moved.sum())
    out["svc_moved_to_new_pct"] = round(
        100.0 * float((picks1[moved] == np.uint32(new_bk[0])).mean())
        if moved.any() else 100.0, 2)

    # --- part 2: pod-to-pod across the fleet, per-tenant VNIs ---------
    def mk_gw():
        cfg = DataplaneConfig(
            max_tables=2, max_rules=16, max_global_rules=8,
            max_ifaces=8, fib_slots=32, sess_slots=1 << 12,
            sess_ways=4, sess_hash="sym", nat_mappings=1,
            nat_backends=1, tenancy="on", tenancy_tenants=4,
            overlay="vxlan")
        gw = Dataplane(cfg)
        gup = gw.add_uplink()
        gpod = gw.add_pod_interface(("default", "east"))
        gw.set_vtep(ip4("192.168.32.1"))  # anycast gateway VTEP
        gw.builder.set_tenant(1, prefixes=["10.61.0.0/16"], vni=100)
        gw.builder.set_tenant(2, prefixes=["10.62.0.0/16"], vni=200)
        for t in (61, 62):
            gw.builder.add_route(f"10.{t}.1.0/24", gpod,
                                 Disposition.LOCAL)
            gw.builder.add_route(
                f"10.{t}.2.0/24", gup, Disposition.REMOTE,
                next_hop=ip4("192.168.32.9"), node_id=3)
        gw.builder.add_route("192.168.32.0/24", gup,
                             Disposition.REMOTE)
        gw.builder.set_global_table([
            ContivRule(action=Action.PERMIT, protocol=Protocol.TCP),
            ContivRule(action=Action.DENY)])
        gw.swap()
        return gw, gup

    n2 = 512
    lanes = np.arange(n2)
    tnt = 1 + (lanes % 2)
    bad = (lanes % 8) == 7
    to_local = (lanes // 2) % 2 == 0
    inner_src = ((10 << 24) | ((60 + tnt) << 16) | (9 << 8)
                 | (1 + lanes % 250)).astype(np.uint32)
    inner_dst = ((10 << 24) | ((60 + tnt) << 16)
                 | (np.where(to_local, 1, 2) << 8)
                 | (2 + lanes % 250)).astype(np.uint32)
    vni = np.where(bad, 999, np.where(tnt == 1, 100, 200)).astype(
        np.int32)
    outer_cols = {
        "src_ip": np.full(n2, ip4("192.168.32.50"), np.uint32),
        "dst_ip": np.full(n2, ip4("192.168.32.1"), np.uint32),
        "proto": np.full(n2, 17, np.int32),
        "sport": (49152 + lanes % 16384).astype(np.int32),
        "dport": np.full(n2, VXLAN_PORT, np.int32),
        "ttl": np.full(n2, OUTER_TTL, np.int32),
        "pkt_len": np.full(n2, 128 + ENCAP_OVERHEAD, np.int32),
        "rx_if": np.ones(n2, np.int32),
        "flags": np.full(n2, FLAG_VALID, np.int32),
    }
    flat = np.zeros((5, n2), np.int32)
    pack_packet_columns(flat.view(np.uint32), outer_cols, n2)

    gws = {"gw-a": mk_gw(), "gw-b": mk_gw()}
    st = FleetSteering({nm: g for nm, (g, _) in gws.items()})
    try:
        groups, sdrops = st.partition(flat)
        delivered = reencapped = decapped = bad_dropped = 0
        bad_offered = int(bad.sum())
        spread = {}
        for nm, idx in groups.items():
            gw, gup = gws[nm]
            k = idx.size
            spread[nm] = k
            sel = np.concatenate(
                [idx, np.zeros(n2 - k, np.int64)]).astype(np.int64)
            live = np.arange(n2) < k
            outer_pv = PacketVector(
                src_ip=jnp.asarray(outer_cols["src_ip"][sel]),
                dst_ip=jnp.asarray(outer_cols["dst_ip"][sel]),
                proto=jnp.asarray(outer_cols["proto"][sel]),
                sport=jnp.asarray(outer_cols["sport"][sel]),
                dport=jnp.asarray(outer_cols["dport"][sel]),
                ttl=jnp.asarray(outer_cols["ttl"][sel]),
                pkt_len=jnp.asarray(outer_cols["pkt_len"][sel]),
                rx_if=jnp.full((n2,), 1, jnp.int32),
                flags=jnp.asarray(
                    np.where(live, FLAG_VALID, 0).astype(np.int32)),
            )
            inner_pv = PacketVector(
                src_ip=jnp.asarray(inner_src[sel]),
                dst_ip=jnp.asarray(inner_dst[sel]),
                proto=jnp.full((n2,), 6, jnp.int32),
                sport=jnp.asarray(
                    (1024 + sel % 40000).astype(np.int32)),
                dport=jnp.full((n2,), 80, jnp.int32),
                ttl=jnp.full((n2,), 64, jnp.int32),
                pkt_len=jnp.full((n2,), 128, jnp.int32),
                rx_if=jnp.full((n2,), 1, jnp.int32),
                flags=jnp.asarray(
                    np.where(live, FLAG_VALID, 0).astype(np.int32)),
            )
            vni_pv = np.where(live, vni[sel], -1).astype(np.int32)
            r = gw.process(outer_pv, now=5, ovl_inner=inner_pv,
                           ovl_vni=vni_pv)
            disp = np.asarray(r.disp)[:k]
            delivered += int((disp == int(Disposition.LOCAL)).sum())
            reencapped += int(r.stats.ovl_encap)
            decapped += int(r.stats.ovl_decap)
            bad_dropped += int(r.stats.drop_overlay)
        n_good = n2 - bad_offered - sdrops["fenced"] - \
            sdrops["no_owner"]
        out["overlay_eastwest_frames"] = n2
        out["overlay_eastwest_instances"] = len(gws)
        out["overlay_eastwest_spread_min_pct"] = round(
            100.0 * min(spread.values(), default=0) / n2, 1)
        out["overlay_eastwest_decapped"] = decapped
        out["overlay_eastwest_delivered"] = delivered
        out["overlay_eastwest_reencapped"] = reencapped
        out["overlay_eastwest_delivered_pct"] = round(
            100.0 * (delivered + reencapped) / max(n_good, 1), 1)
        out["overlay_eastwest_bad_vni"] = bad_offered
        out["overlay_eastwest_bad_dropped"] = bad_dropped
        out["overlay_eastwest_isolated"] = int(
            bad_dropped == bad_offered)
        out["overlay_eastwest_conservation_exact"] = int(
            delivered + reencapped + bad_dropped
            + sdrops["fenced"] + sdrops["no_owner"] == n2)
    finally:
        st.close()
    return out


def main():
    try:
        # Supervisor by default: the axon tunnel wedges MID-RUN without
        # warning (r3's driver run fell back to CPU whole; a 2026-07-31
        # wedge 20+ min in lost everything). The top-level invocation
        # runs the real bench as a CHILD with a progress sidecar,
        # watches for stalls, and on a wedge salvages the completed TPU
        # sections + fills the rest from a CPU re-run — the driver
        # always gets a JSON line with every number that was
        # measurable. --inner/--cpu run the bench directly.
        if "--inner" in sys.argv[1:] or "--cpu" in sys.argv[1:]:
            if "--inner" in sys.argv[1:]:
                sys.argv.remove("--inner")
            _run()
        else:
            _supervise()
    except BaseException as e:  # noqa: BLE001 — driver needs a JSON line
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            raise
        _emit_error(e)
        sys.exit(0)


# the longest legitimate gap between sidecar checkpoints on a healthy
# tunnel is the first compile+headline stretch (a few minutes); 8 min of
# silence means the tunnel is wedged, not slow
SUPERVISE_STALL_S = 480.0
SUPERVISE_TOTAL_S = 2700.0


def _autotune_profile():
    """The committed tuned/<backend>.json knobs, if the repo carries a
    profile for this backend (None otherwise) — so a bench round and
    the config a deployment would boot with land in one JSON line."""
    try:
        import jax as _jax

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tuned", f"{_jax.default_backend()}.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            prof = json.load(f)
        return {"path": os.path.relpath(path, os.getcwd()),
                "knobs": prof.get("knobs"),
                "floor_us": prof.get("floor_us")}
    except Exception as e:  # noqa: BLE001 — additive, never fatal
        return {"error": f"{type(e).__name__}: {e}"}


def _read_json(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def _supervise() -> None:
    import subprocess
    import tempfile

    td = tempfile.mkdtemp(prefix="bench_sup_")
    # honor a caller-supplied sidecar (tools/tpu_watch.py passes one and
    # reads it after a deadline kill): monitor THAT file, don't shadow
    # it with our own — two --progress-out flags would desync us
    passthrough = list(sys.argv[1:])
    side_tpu = os.path.join(td, "tpu.json")
    if "--progress-out" in passthrough:
        i = passthrough.index("--progress-out")
        side_tpu = passthrough[i + 1]
        del passthrough[i:i + 2]
    else:
        for i, a in enumerate(passthrough):
            if a.startswith("--progress-out="):
                side_tpu = a.split("=", 1)[1]
                del passthrough[i]
                break
    side_cpu = os.path.join(td, "cpu.json")

    def run_child(extra, sidecar, budget_s, env=None):
        """Run the inner bench; returns (final_json_or_None, stalled)."""
        argv = [sys.executable, os.path.abspath(__file__), "--inner",
                "--progress-out", sidecar] + extra + passthrough
        child = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                 stderr=subprocess.DEVNULL, text=True,
                                 env=env)
        deadline = time.monotonic() + budget_s
        last_change = time.monotonic()
        last_mtime = 0.0
        while child.poll() is None:
            time.sleep(5)
            try:
                mtime = os.path.getmtime(sidecar)
            except OSError:
                mtime = 0.0
            if mtime != last_mtime:
                last_mtime, last_change = mtime, time.monotonic()
            now = time.monotonic()
            if now > deadline or now - last_change > SUPERVISE_STALL_S:
                child.kill()
                child.wait(timeout=30)
                return None, True
        out_lines = [ln for ln in (child.stdout.read() or "").splitlines()
                     if ln.strip()]
        if child.returncode == 0 and out_lines:
            try:
                return json.loads(out_lines[-1]), False
            except json.JSONDecodeError:
                pass
        return None, False

    result, stalled = run_child([], side_tpu, SUPERVISE_TOTAL_S)
    if result is not None and "error" not in result:
        print(json.dumps(result))
        return

    # salvage: whatever sections the wedged/failed run checkpointed,
    # then fill the gaps on CPU. A WEDGED tunnel hangs even
    # CPU-platform init through the eagerly-registering axon plugin —
    # drop it from PYTHONPATH for the fallback child (same trick as
    # _run's execve fallback).
    tpu_part = _read_json(side_tpu)
    cpu_res, _ = run_child(["--cpu"], side_cpu, SUPERVISE_TOTAL_S,
                           env=_cpu_fallback_env())
    print(json.dumps(_merge_salvage(tpu_part, cpu_res, stalled,
                                    cpu_side=_read_json(side_cpu))))


# sidecar bookkeeping keys that are not measured sections
_SIDECAR_META = frozenset((
    "backend", "host_cores", "started_at", "load_at_start", "completed",
    "probe_attempt", "cpu_fallback_reduced", "rules", "packets_per_step",
    "nat_backends", "latency_frame",
))


def _merge_salvage(tpu_part: dict, cpu_res: dict | None,
                   stalled: bool, cpu_side: dict | None = None) -> dict:
    """Final driver JSON from a wedged TPU partial + a CPU fill run.

    TPU-measured sections win; anything only the CPU run produced is
    listed in ``cpu_filled_sections``. Every CPU source is used: a
    completed fill run, the fill run's own sidecar (it may ALSO have
    been killed), and an inner partial that had already fallen back to
    CPU — a stalled fill must not zero numbers that were measured."""
    tpu_keys = {k for k in tpu_part if k not in _SIDECAR_META}
    partial_was_tpu = tpu_part.get("backend") == "tpu"
    merged: dict = {}
    cpu_details: dict = {}
    if not partial_was_tpu and tpu_part:
        cpu_details.update({k: v for k, v in tpu_part.items()
                            if k != "completed"})
    if cpu_side:
        cpu_details.update({k: v for k, v in cpu_side.items()
                            if k != "completed"})
    cpu_details.update((cpu_res or {}).get("details", {}))
    merged.update(cpu_details)
    if partial_was_tpu:
        merged.update({k: v for k, v in tpu_part.items()
                       if k != "completed"})
        merged["cpu_filled_sections"] = sorted(
            k for k in cpu_details
            if k not in tpu_keys and k not in _SIDECAR_META
            and not k.startswith("cpu_"))
    if partial_was_tpu and "headline_mpps" in tpu_part:
        headline = tpu_part["headline_mpps"]
    elif cpu_res is not None and cpu_res.get("value"):
        headline = cpu_res["value"]
    else:
        # an errored fill run emits value 0.0 — its sidecar may still
        # hold the measured headline
        headline = cpu_details.get("headline_mpps", 0.0)
    merged["supervisor"] = (
        f"inner run {'stalled (tunnel wedge)' if stalled else 'failed'}; "
        f"tpu sections salvaged: {len(tpu_keys) if partial_was_tpu else 0}, "
        f"rest from cpu fallback")
    merged.pop("headline_mpps", None)
    return {
        "metric": METRIC,
        "value": round(float(headline or 0.0), 3),
        "unit": "Mpps",
        "vs_baseline": round(float(headline or 0.0) / BASELINE_MPPS, 4),
        "details": merged,
    }


def _run():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=10240)
    ap.add_argument("--packets", type=int, default=None,
                    help="packets per pipeline step (throughput run; "
                         "default 65536, auto-shrunk on CPU fallback)")
    ap.add_argument("--backends", type=int, default=100)
    ap.add_argument("--iters", type=int, default=None,
                    help="throughput iterations (default 50, "
                         "auto-shrunk on CPU fallback)")
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--latency-frame", type=int, default=256,
                    help="frame size for the added-latency measurement")
    ap.add_argument("--cpu", action="store_true", help="force CPU (debug)")
    ap.add_argument("--cpu-full", action="store_true", dest="cpu_full",
                    help="run full-size workloads even on the CPU "
                         "fallback (slow; default shrinks them)")
    ap.add_argument("--no-subbench", action="store_true",
                    help="skip the secondary BASELINE configs (#1/#3/#4)")
    # generous probe window: the axon tunnel wedges for long stretches
    # (hours observed) and recovers on its own; a premature CPU
    # fallback records a meaningless headline for the round, so spend
    # up to ~15 min looking for the chip before giving up on it
    ap.add_argument("--retries", type=int, default=12,
                    help="TPU backend init attempts before CPU fallback")
    ap.add_argument("--retry-delay", type=float, default=15.0)
    ap.add_argument("--progress-out", default=None,
                    help="sidecar JSON checkpointing each completed "
                         "section (survives a mid-run tunnel wedge)")
    args = ap.parse_args()

    global _PROGRESS_PATH
    if args.progress_out:
        _PROGRESS_PATH = args.progress_out

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    try:
        if not args.cpu:
            # --cpu must never touch (or wait on) the TPU backend — it
            # exists exactly for when that backend is unreachable
            _probe_backend(args.retries, args.retry_delay)
    except RuntimeError:
        if not args.cpu:
            # The failed axon init poisons this process's backend state;
            # fall back to CPU in a FRESH process (where jax.config can
            # still force the platform before first backend touch). A
            # WEDGED tunnel hangs even CPU-platform init through the
            # eagerly-registering axon plugin, so drop it from
            # PYTHONPATH for the fallback process.
            env = _cpu_fallback_env()
            os.execve(
                sys.executable,
                [sys.executable, os.path.abspath(__file__), "--cpu"]
                + [a for a in sys.argv[1:] if a != "--cpu"],
                env,
            )
        raise
    import jax
    import jax.numpy as jnp

    from vpp_tpu.pipeline.graph import make_pipeline_step

    # CPU fallback: a full-size step costs ~8.5 s on this host (the
    # whole run would exceed typical driver timeouts and record
    # NOTHING). Defaults shrink to diagnostic sizes; explicitly passed
    # sizes are honored (None sentinels distinguish the two).
    shrink = (jax.default_backend() == "cpu" and not args.cpu_full)
    cpu_fallback = False
    if args.packets is None:
        args.packets = 8192 if shrink else 65536
        cpu_fallback = cpu_fallback or shrink
    if args.iters is None:
        args.iters = 10 if shrink else 50
        cpu_fallback = cpu_fallback or shrink

    _progress(backend=jax.default_backend(), host_cores=os.cpu_count(),
              started_at=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
              load_at_start=os.getloadavg()[0])

    # --- priority capture (VERDICT r5 Next #1): the sections that have
    # never been measured on real hardware run FIRST — sess_election_*,
    # commit_ms_*, the ring-to-ring wire path in both pump modes, and
    # the deployed io-daemon rows — BEFORE the multi-minute headline
    # compile, so a short healthy-tunnel window still yields them. Each
    # is individually guarded: a failure records its error key and the
    # run continues.
    pri = {}
    _jc = _jit_compiles_now()
    _tb = _transfer_bytes_now()
    try:
        pri.update(session_election_bench(args))
    except Exception as e:  # noqa: BLE001 — priority sections are
        # individually additive
        pri["sess_election_error"] = f"{type(e).__name__}: {e}"
    _jc_now = _jit_compiles_now()
    pri["sess_election_jit_compiles"] = _jc_now - _jc
    _jc = _jc_now
    _tb_now = _transfer_bytes_now()
    pri["sess_election_transfer_bytes"] = _tb_now - _tb
    _tb = _tb_now
    _progress(**pri)
    try:
        # set-associative session table (ISSUE 6): old-vs-new insert
        # medians + the 10M-resident scale rows (admission ksps,
        # resident millions) — acceptance: sess_insert_speedup_x >= 3
        pri.update(session_scale_bench(args))
    except Exception as e:  # noqa: BLE001
        pri["session_scale_error"] = f"{type(e).__name__}: {e}"
    _jc_now = _jit_compiles_now()
    pri["session_scale_jit_compiles"] = _jc_now - _jc
    _jc = _jc_now
    _tb_now = _transfer_bytes_now()
    pri["session_scale_transfer_bytes"] = _tb_now - _tb
    _tb = _tb_now
    _progress(**pri)
    try:
        # crash-consistent snapshot at the scale config (ISSUE 8):
        # chunked-drain cost + the concurrent per-step stall —
        # acceptance: snapshot_step_stall_pct < 10
        pri.update(snapshot_bench(args))
    except Exception as e:  # noqa: BLE001
        pri["snapshot_bench_error"] = f"{type(e).__name__}: {e}"
    _jc_now = _jit_compiles_now()
    pri["snapshot_jit_compiles"] = _jc_now - _jc
    _jc = _jc_now
    _tb_now = _transfer_bytes_now()
    pri["snapshot_transfer_bytes"] = _tb_now - _tb
    _tb = _tb_now
    _progress(**pri)
    try:
        pri.update(commit_bench(args))
    except Exception as e:  # noqa: BLE001
        pri["commit_bench_error"] = f"{type(e).__name__}: {e}"
    _jc_now = _jit_compiles_now()
    pri["commit_jit_compiles"] = _jc_now - _jc
    _jc = _jc_now
    _tb_now = _transfer_bytes_now()
    pri["commit_transfer_bytes"] = _tb_now - _tb
    _tb = _tb_now
    _progress(**pri)
    try:
        # classifier shoot-out (ISSUE 4): dense vs MXU vs BV at 1,024
        # and the headline rule count — re-validates the auto default
        pri.update(acl_classifier_bench(args))
    except Exception as e:  # noqa: BLE001
        pri["acl_classifier_bench_error"] = f"{type(e).__name__}: {e}"
    _jc_now = _jit_compiles_now()
    pri["acl_classifier_jit_compiles"] = _jc_now - _jc
    _jc = _jc_now
    _tb_now = _transfer_bytes_now()
    pri["acl_classifier_transfer_bytes"] = _tb_now - _tb
    _tb = _tb_now
    _progress(**pri)
    try:
        # million-route LPM FIB (ISSUE 15): 1M-route build, LPM vs
        # dense lookup ns/pkt (+ the dense-at-1M extrapolation), one
        # /24 flap's bounded commit, ECMP member spread — acceptance:
        # lpm <= 2x dense-at-native, >= 10x dense-extrapolated-to-1M,
        # churn ships only the touched length plane
        pri.update(fib_bench(args))
    except Exception as e:  # noqa: BLE001
        pri["fib_bench_error"] = f"{type(e).__name__}: {e}"
    _jc_now = _jit_compiles_now()
    pri["fib_jit_compiles"] = _jc_now - _jc
    _jc = _jc_now
    _tb_now = _transfer_bytes_now()
    pri["fib_transfer_bytes"] = _tb_now - _tb
    _tb = _tb_now
    _progress(**pri)
    try:
        # pallas kernel rungs (ISSUE 16): fused vs reference ns/pkt +
        # bit-exactness for the three gather-bound hot ops — native on
        # TPU, interpret-mode semantics pricing elsewhere
        pri.update(pallas_kernel_bench(args))
    except Exception as e:  # noqa: BLE001
        pri["pallas_kernel_bench_error"] = f"{type(e).__name__}: {e}"
    _jc_now = _jit_compiles_now()
    pri["pallas_kernel_jit_compiles"] = _jc_now - _jc
    _jc = _jc_now
    _tb_now = _transfer_bytes_now()
    pri["pallas_kernel_transfer_bytes"] = _tb_now - _tb
    _tb = _tb_now
    _progress(**pri)
    try:
        # tentpole capture: the two-tier fast path's measured win at
        # the headline rule count (acceptance: >= 3x on all-established)
        pri.update(fastpath_bench(args))
    except Exception as e:  # noqa: BLE001
        pri["fastpath_bench_error"] = f"{type(e).__name__}: {e}"
    _jc_now = _jit_compiles_now()
    pri["fastpath_jit_compiles"] = _jc_now - _jc
    _jc = _jc_now
    _tb_now = _transfer_bytes_now()
    pri["fastpath_transfer_bytes"] = _tb_now - _tb
    _tb = _tb_now
    _progress(**pri)
    try:
        # per-packet ML stage (ISSUE 10): marginal in-step cost of the
        # int8 MLP + the zero-re-ship model-swap check (acceptance:
        # ml_headline_overhead_pct < 10, ml_swap_zero_reship == 1)
        pri.update(ml_stage_bench(args))
    except Exception as e:  # noqa: BLE001
        pri["ml_stage_bench_error"] = f"{type(e).__name__}: {e}"
    _jc_now = _jit_compiles_now()
    pri["ml_stage_jit_compiles"] = _jc_now - _jc
    _jc = _jc_now
    _tb_now = _transfer_bytes_now()
    pri["ml_stage_transfer_bytes"] = _tb_now - _tb
    _tb = _tb_now
    _progress(**pri)
    try:
        # device telemetry plane (ISSUE 11): in-step histogram/sketch
        # overhead + the on-device load-vs-tail sweep + sketch
        # fidelity (acceptance: telemetry_overhead_pct < 5,
        # flow_topk_recall >= 0.9)
        pri.update(latency_telemetry_bench(args))
    except Exception as e:  # noqa: BLE001
        pri["latency_telemetry_error"] = f"{type(e).__name__}: {e}"
    _jc_now = _jit_compiles_now()
    pri["latency_telemetry_jit_compiles"] = _jc_now - _jc
    _jc = _jc_now
    _tb_now = _transfer_bytes_now()
    pri["latency_telemetry_transfer_bytes"] = _tb_now - _tb
    _tb = _tb_now
    _progress(**pri)
    try:
        # gateway fleet (ISSUE 18): the scale-out ladder (1→2→4
        # instances, acceptance fleet_scaleout_ratio >= 1.8 per
        # doubling) + live rebalance under pump load (acceptance:
        # conservation EXACT, hit-rate recovery >= 0.9)
        pri.update(fleet_bench(args))
    except Exception as e:  # noqa: BLE001
        pri["fleet_bench_error"] = f"{type(e).__name__}: {e}"
    _jc_now = _jit_compiles_now()
    pri["fleet_jit_compiles"] = _jc_now - _jc
    _jc = _jc_now
    _tb_now = _transfer_bytes_now()
    pri["fleet_transfer_bytes"] = _tb_now - _tb
    _tb = _tb_now
    _progress(**pri)
    try:
        # device-resident VXLAN overlay + svc NAT44 planes (ISSUE 19):
        # the always-paid overlay stage cost at the headline rule
        # count (acceptance: overlay_encap_overhead_pct <= 15), the
        # pod-to-pod cross-instance round over the steering tier with
        # per-tenant VNI isolation, and the rolling backend
        # replacement's svc-only blob (svc_churn_bytes — a few KB,
        # every non-svc plane identity-pinned)
        pri.update(overlay_bench(args))
    except Exception as e:  # noqa: BLE001
        pri["overlay_bench_error"] = f"{type(e).__name__}: {e}"
    _jc_now = _jit_compiles_now()
    pri["overlay_jit_compiles"] = _jc_now - _jc
    _jc = _jc_now
    _tb_now = _transfer_bytes_now()
    pri["overlay_transfer_bytes"] = _tb_now - _tb
    _tb = _tb_now
    _progress(**pri)
    if not args.no_subbench:
        try:
            pri.update(io_ring_bench(args))
        except Exception as e:  # noqa: BLE001
            pri["io_ring_bench_error"] = f"{type(e).__name__}: {e}"
        _jc_now = _jit_compiles_now()
        pri["io_ring_jit_compiles"] = _jc_now - _jc
        _jc = _jc_now
        _tb_now = _transfer_bytes_now()
        pri["io_ring_transfer_bytes"] = _tb_now - _tb
        _tb = _tb_now
        _progress(**pri)
        try:
            # reflex-plane latency governor (ISSUE 13): the priority
            # ladder at 50/80/95/120% of sat x {ungoverned, governed}
            # + the square-wave burst scenario (acceptance: governed
            # priority p99 <= 2x the lone-frame floor,
            # latency_slo_goodput_ratio >= 0.9, io_callbacks == 0,
            # zero new step variants)
            pri.update(latency_slo_bench(args))
        except Exception as e:  # noqa: BLE001
            pri["latency_slo_bench_error"] = f"{type(e).__name__}: {e}"
        _jc_now = _jit_compiles_now()
        pri["latency_slo_jit_compiles"] = _jc_now - _jc
        _jc = _jc_now
        _tb_now = _transfer_bytes_now()
        pri["latency_slo_transfer_bytes"] = _tb_now - _tb
        _tb = _tb_now
        _progress(**pri)
        try:
            # multi-tenant isolation (ISSUE 14): 4 tenants on the
            # wire path, tenant 4 at 4x quota with a square-wave
            # burst (acceptance: well-behaved goodput >= 0.9x solo,
            # p99 <= 2x solo, overage fully attributed
            # tenant_quota/overload, conservation exact)
            pri.update(tenant_isolation_bench(args))
        except Exception as e:  # noqa: BLE001
            pri["tenant_isolation_bench_error"] = \
                f"{type(e).__name__}: {e}"
        _jc_now = _jit_compiles_now()
        pri["tenant_isolation_jit_compiles"] = _jc_now - _jc
        _jc = _jc_now
        _tb_now = _transfer_bytes_now()
        pri["tenant_isolation_transfer_bytes"] = _tb_now - _tb
        _tb = _tb_now
        _progress(**pri)
        try:
            pri.update(io_daemon_bench(args))
        except Exception as e:  # noqa: BLE001 — optional, env-dependent
            pri["io_daemon_bench_error"] = f"{type(e).__name__}: {e}"
        _jc_now = _jit_compiles_now()
        pri["io_daemon_jit_compiles"] = _jc_now - _jc
        _jc = _jc_now
        _tb_now = _transfer_bytes_now()
        pri["io_daemon_transfer_bytes"] = _tb_now - _tb
        _tb = _tb_now
        _progress(**pri)

    dp, uplink = build_dataplane(args.rules, args.backends)
    # headline runs whatever the deployed dataplane selected (the
    # classifier: auto ladder — BV at the 10k regime, re-validated by
    # the acl_classifier_* shoot-out above — AND the fib_impl ladder,
    # dense at the headline's node-scale FIB; fib_bench above carries
    # the million-route LPM rows)
    step_fn = make_pipeline_step(dp.classifier_impl, dp._skip_local,
                                 fib_impl=dp.fib_impl)
    step = jax.jit(step_fn, donate_argnums=(0,))

    # --- throughput: K chained steps, sessions threaded through ---
    pkts = build_traffic(args.packets, uplink)
    tables = dp.tables
    for i in range(args.warmup):
        res = step(tables, pkts, jnp.int32(i + 1))
        tables = res.tables
    jax.block_until_ready(tables)

    t0 = time.perf_counter()
    for i in range(args.iters):
        res = step(tables, pkts, jnp.int32(100 + i))
        tables = res.tables
    jax.block_until_ready(res)
    dt = time.perf_counter() - t0
    mpps = args.packets * args.iters / dt / 1e6
    _progress(headline_mpps=round(mpps, 3), rules=args.rules,
              packets_per_step=args.packets, iters=args.iters,
              headline_fib_impl=dp.fib_impl,
              headline_jit_compiles=_jit_compiles_now() - _jc,
              jit_compiles_total=_jit_compiles_now())

    # --- added latency: single small-frame step, p50/p99 ---
    def pack_frame(pv, n):
        """Latency-section staging: one packed [5, n] int32 frame from
        a PacketVector (shared by the chained and persistent levers —
        they must measure identical traffic)."""
        from vpp_tpu.pipeline.dataplane import pack_packet_columns

        cols = {
            f: np.asarray(getattr(pv, f))
            for f in ("src_ip", "dst_ip", "proto", "sport", "dport",
                      "ttl", "pkt_len", "rx_if", "flags")
        }
        flat = np.zeros((5, n), np.int32)
        pack_packet_columns(flat.view(np.uint32), cols, n)
        return flat

    frame = build_traffic(args.latency_frame, uplink, seed=11)
    lat = []
    for i in range(args.warmup):
        out = step(tables, frame, jnp.int32(i))
        jax.block_until_ready(out.disp)
        tables = out.tables
    for i in range(200):
        t0 = time.perf_counter()
        out = step(tables, frame, jnp.int32(1000 + i))
        jax.block_until_ready(out.disp)
        lat.append(time.perf_counter() - t0)
        tables = out.tables
    lat_us = np.array(lat) * 1e6
    _progress(frame_latency_p50_us=round(float(np.percentile(lat_us, 50)), 1),
              frame_latency_p99_us=round(float(np.percentile(lat_us, 99)), 1))

    # steady-state (pipelined) per-frame latency: dispatch K frames
    # back-to-back without host sync — the per-frame cost once dispatch
    # overlaps execution, the deployment regime of a streaming data plane
    K = 64
    t0 = time.perf_counter()
    for i in range(K):
        out = step(tables, frame, jnp.int32(2000 + i))
        tables = out.tables
    jax.block_until_ready(out.disp)
    pipelined_us = (time.perf_counter() - t0) / K * 1e6
    _progress(frame_latency_pipelined_us=round(pipelined_us, 1))

    # chained quantum (VERDICT r3 Next #4 lever): K packed frames run
    # inside ONE device program (lax.scan) with ONE dispatch + ONE
    # sync, vs K separate dispatches above. Amortizes the per-step
    # host round trip; measured per frame.
    KC = 16
    chain_dp, chain_up = build_dataplane(args.rules, args.backends)
    cframe = build_traffic(args.latency_frame, chain_up, seed=12)
    one = pack_frame(cframe, args.latency_frame)
    flats = np.broadcast_to(
        one, (KC, 5, args.latency_frame)).copy()
    jax.block_until_ready(
        chain_dp.process_packed_chain(flats.copy(), now=1)
    )  # compile
    chain_lat = []
    for i in range(20):
        t0 = time.perf_counter()
        jax.block_until_ready(
            chain_dp.process_packed_chain(flats.copy(), now=10 + i)
        )
        chain_lat.append((time.perf_counter() - t0) / KC * 1e6)
    chained_us = float(np.percentile(np.array(chain_lat), 50))
    _progress(frame_latency_chained_us=round(chained_us, 1))

    # persistent device-ring path (docs/LATENCY.md round-7 lever):
    # frames ride device-resident descriptor-ring windows — a lone
    # frame ships in a 1-slot window, so this ping-pong measures the
    # single-window exchange quantum (zero io_callbacks). Latency-
    # floor regime; additive and best-effort.
    persistent_us = None
    pump_p = None
    try:
        from vpp_tpu.pipeline.persistent import PersistentPump

        pdp, pup = build_dataplane(args.rules, args.backends)
        pflat = pack_frame(build_traffic(args.latency_frame, pup,
                                         seed=13), args.latency_frame)
        pump_p = PersistentPump(pdp.tables, batch=args.latency_frame,
                                classifier=pdp.classifier_impl,
                                skip_local=pdp._skip_local)
        pump_p.start()
        pump_p.submit(pflat, now=1)          # warm (traces the loop)
        pump_p.result(timeout=600)
        lat_p = []
        for i in range(50):
            t0 = time.perf_counter()
            pump_p.submit(pflat, now=2 + i)
            pump_p.result(timeout=120)
            lat_p.append(time.perf_counter() - t0)
        persistent_us = round(
            float(np.percentile(np.array(lat_p) * 1e6, 50)), 1)
        _progress(frame_latency_persistent_us=persistent_us)
    except Exception as e:  # noqa: BLE001 — prototype lever, optional
        persistent_us = f"error: {type(e).__name__}: {e}"
    finally:
        # the resident program must NOT outlive this section: on a
        # single-execution-stream device it would block everything
        # after it (it sits in host_fetch waiting for frames)
        if pump_p is not None:
            try:
                pump_p.stop()
            except Exception:  # noqa: BLE001 — already recorded above
                pass

    # per-stage `show run` snapshot (trace/cycles.py) in the official
    # output: attributes headline movements between rounds to a stage
    # instead of leaving regressions unexplained (VERDICT r3 Weak #2).
    # Isolated-stage timings include one dispatch each — compare rows
    # across ROUNDS, trust the FUSED row as the real per-frame cost.
    stage_ns = {}
    try:
        from vpp_tpu.trace.cycles import profile_stages

        for t in profile_stages(chain_dp.tables, cframe, iters=10):
            stage_ns[t.node] = round(t.ns_per_packet, 1)
    except Exception as e:  # noqa: BLE001 — diagnostics must not kill
        stage_ns["error"] = f"{type(e).__name__}: {e}"
    _progress(stage_ns_per_pkt=stage_ns)

    subs = {} if args.no_subbench else sub_benches(args)
    subs.update(pri)  # priority-capture sections into the final details
    _progress(**subs)
    if not args.no_subbench:
        try:
            subs.update(hoststack_bench(args))
        except Exception as e:  # noqa: BLE001 — optional, env-dependent
            subs["hoststack_bench_error"] = f"{type(e).__name__}: {e}"
        _progress(**subs)
        try:
            subs.update(proxy_chain_bench(args))
        except Exception as e:  # noqa: BLE001 — optional, env-dependent
            subs["nginx_istio_error"] = f"{type(e).__name__}: {e}"
        _progress(**subs)
    _progress(**subs, completed=True)
    # the honest experienced figure: ring-to-ring wire-path latency at
    # a paced (non-saturating) offered load, NOT pipelined-throughput/N
    # (VERDICT r2 Weak #2); the wire bench fills it in when it ran
    if "io_wire_lat_p99_us" in subs:
        subs["added_latency_p99_us_experienced"] = subs["io_wire_lat_p99_us"]

    print(
        json.dumps(
            {
                "metric": "acl_nat_pipeline_mpps_10k_rules",
                "value": round(mpps, 3),
                "unit": "Mpps",
                "vs_baseline": round(mpps / BASELINE_MPPS, 4),
                "details": {
                    "rules": args.rules,
                    "packets_per_step": args.packets,
                    "nat_backends": args.backends,
                    "frame_latency_p50_us": round(float(np.percentile(lat_us, 50)), 1),
                    "frame_latency_p99_us": round(float(np.percentile(lat_us, 99)), 1),
                    "frame_latency_pipelined_us": round(pipelined_us, 1),
                    # K frames inside ONE device program, one
                    # dispatch+sync (lax.scan chain) — the bounded-sync
                    # quantum, per frame (docs/LATENCY.md lever #4)
                    "frame_latency_chained_us": round(chained_us, 1),
                    # resident while_loop + io_callback refills: zero
                    # per-frame dispatch (docs/LATENCY.md lever #5)
                    "frame_latency_persistent_us": persistent_us,
                    "stage_ns_per_pkt": stage_ns,
                    # throughput at the DEPLOYED frame size (VPP's 256-
                    # packet frames), not the 65536-packet bench steps —
                    # the honest companion to the batch-inflated headline
                    "pipeline_mpps_at_frame": round(
                        args.latency_frame / pipelined_us, 3
                    ),
                    "per_packet_added_latency_us": round(
                        pipelined_us / args.latency_frame, 3
                    ),
                    "latency_frame": args.latency_frame,
                    # runtime jit-compile guard roll-up: per-section
                    # *_jit_compiles deltas ride in via **subs; this is
                    # the whole-run total (flat across rounds unless a
                    # recompile regression landed)
                    "jit_compiles_total": _jit_compiles_now(),
                    "device_transfer_bytes_total": _transfer_bytes_now(),
                    # committed autotuner profile for this backend
                    # (tools/autotune.py; ISSUE 16) — the knobs a
                    # deployment loading tuned/<backend>.json would
                    # run with, alongside the numbers measured here
                    "autotune_profile": _autotune_profile(),
                    "backend": jax.default_backend(),
                    # wire-path numbers are host-CPU-bound too: on a
                    # 1-core host the sender/daemon/pump/receiver AND
                    # (on CPU fallback) the XLA step all share one core
                    "host_cores": os.cpu_count(),
                    "cpu_fallback_reduced": cpu_fallback,
                    **subs,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
