#!/usr/bin/env python
"""Render k8s manifests from chart/vpp-tpu.yaml.tmpl + chart/values.yaml.

The Helm-values analog for this repo's minimal manifests (reference
ships a chart under k8s/contiv-vpp; SURVEY §7 scopes this build to
minimal manifests, so parametrization is one template + one values
file + this renderer — no external tooling):

    python k8s/render.py                          # defaults -> stdout
    python k8s/render.py --set image=reg/vpp:1.2  # overrides
    python k8s/render.py -o k8s/vpp-tpu.yaml      # write

`{{name}}` placeholders come from values.yaml (overridable with
--set); rendering fails on unknown or leftover placeholders, so a
template/values drift can't produce a silently broken manifest.
`${NODE_NAME}` is NOT a template variable — it survives into the
rendered ConfigMap and is resolved per-node at runtime.
"""

from __future__ import annotations

import argparse
import os
import re
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))


def load_values(path: str) -> dict:
    import yaml

    with open(path) as f:
        return yaml.safe_load(f) or {}


def render(values: dict) -> str:
    with open(os.path.join(_DIR, "chart", "vpp-tpu.yaml.tmpl")) as f:
        tmpl = f.read()
    values = dict(values)
    # conditional mesh section: nodes > 0 turns the agent config into
    # mesh mode (cmd/config.py MeshConfig; the init supervisor passes
    # the same contiv.yaml to vpp-tpu-mesh-agent)
    if int(values.get("mesh_nodes", 0)) > 0:
        values["mesh_section"] = (
            "    mesh:\n"
            f"      nodes: {int(values['mesh_nodes'])}\n"
            f"      rule_shards: {int(values.get('mesh_rule_shards', 1))}\n"
        )
    else:
        values["mesh_section"] = ""

    def sub(m: re.Match) -> str:
        key = m.group(1)
        if key not in values:
            raise KeyError(f"template references unknown value {key!r}")
        return str(values[key])

    out = re.sub(r"\{\{(\w+)\}\}", sub, tmpl)
    leftover = re.search(r"\{\{\w+\}\}", out)
    if leftover:
        raise ValueError(f"unrendered placeholder: {leftover.group(0)}")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="render.py")
    ap.add_argument("--values",
                    default=os.path.join(_DIR, "chart", "values.yaml"))
    ap.add_argument("--set", dest="sets", action="append", default=[],
                    metavar="KEY=VALUE")
    ap.add_argument("-o", "--output", default=None)
    args = ap.parse_args(argv)
    values = load_values(args.values)
    for kv in args.sets:
        key, eq, val = kv.partition("=")
        if not eq:
            raise SystemExit(f"--set {kv!r}: expected KEY=VALUE")
        if key not in values:
            raise SystemExit(f"--set {key}: not a known value "
                             f"(see {args.values})")
        values[key] = val
    text = render(values)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
