"""Fused-step VXLAN overlay (ISSUE 19): decap at ip4-input, encap at
tx, outer FIB, per-tenant VNI admission — differential against the
host-side RFC 7348 byte oracle (``encode_frame``/``decode_frame``).

The pact under test: the overlay rides INSIDE the one jitted step
(knob-gated ``overlay: off|vxlan``, exactly one new step-form
dimension, zero io_callbacks), an overlay-ADDRESSED frame that cannot
be admitted fails CLOSED (DROP_OVERLAY), and the on-device outer
header is bit-exact with what the byte codec would put on the wire.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from vpp_tpu.ops.vxlan import (
    DEFAULT_VNI,
    ENCAP_OVERHEAD,
    OUTER_TTL,
    VXLAN_PORT,
    decode_frame,
    encode_frame,
)
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.graph import DROP_OVERLAY
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import (
    Disposition,
    FLAG_VALID,
    PacketVector,
    ip4,
    make_packet_vector,
)

VTEP_A = ip4("192.168.16.1")   # this node
VTEP_B = ip4("192.168.16.2")   # remote peer


def mk_dp(**over):
    base = dict(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=32, sess_slots=512, nat_mappings=2, nat_backends=4,
        overlay="vxlan",
    )
    base.update(over)
    dp = Dataplane(DataplaneConfig(**base))
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("default", "a"))
    dp.set_vtep(VTEP_A)
    dp.builder.add_route("10.1.1.0/24", pod, Disposition.LOCAL)
    # remote pod subnet behind peer VTEP (inner FIB) + the VTEP
    # underlay route the OUTER header resolves through
    dp.builder.add_route("10.2.0.0/16", up, Disposition.REMOTE,
                         next_hop=VTEP_B, node_id=2)
    dp.builder.add_route("192.168.16.0/24", up, Disposition.REMOTE)
    dp.swap()
    return dp, up, pod


def vxlan_lanes(up, specs):
    """Outer + inner + vni sidecar vectors from per-lane specs:
    (inner_src, inner_dst, sport, vni) or None for a plain lane
    filled by the caller."""
    n = len(specs)
    outer = make_packet_vector(
        [{"src": "192.168.16.2", "dst": "192.168.16.1", "proto": 17,
          "sport": 49152 + i, "dport": VXLAN_PORT, "ttl": OUTER_TTL,
          "len": 128 + ENCAP_OVERHEAD, "rx_if": up}
         for i in range(n)], n=n)
    inner = make_packet_vector(
        [{"src": s[0], "dst": s[1], "proto": 6, "sport": s[2],
          "dport": 80, "ttl": 64, "len": 128, "rx_if": up}
         for s in specs], n=n)
    vni = jnp.asarray(np.array([s[3] for s in specs], np.int32))
    return outer, inner, vni


class TestStepOverlay:
    def test_decap_forward_reencap_roundtrip(self):
        """A VXLAN frame for a remote pod transits: decap at
        ip4-input, inner FIB to the peer, re-encap at tx with the
        outer resolved through the OUTER FIB walk."""
        dp, up, pod = mk_dp()
        outer, inner, vni = vxlan_lanes(up, [
            ("10.9.0.2", "10.1.1.5", 40000, DEFAULT_VNI),  # deliver
            ("10.9.0.3", "10.2.1.5", 40001, DEFAULT_VNI),  # transit
            ("10.9.0.4", "10.1.1.5", 40002, 999),          # bad VNI
        ])
        r = dp.process(outer, now=1, ovl_inner=inner, ovl_vni=vni)
        s = r.stats
        assert int(s.ovl_decap) == 2
        assert int(s.drop_overlay) == 1
        disp = np.asarray(r.disp)
        assert disp[0] == int(Disposition.LOCAL)
        assert disp[1] == int(Disposition.REMOTE)
        assert disp[2] == int(Disposition.DROP)
        assert int(np.asarray(r.drop_cause)[2]) == DROP_OVERLAY
        # decapped inner rides the step in place: post-step headers
        # are the INNER tuple
        assert int(r.pkts.dst_ip[0]) == ip4("10.1.1.5")
        assert int(r.pkts.dst_ip[1]) == ip4("10.2.1.5")
        # transit lane re-encapped toward the peer VTEP
        assert bool(np.asarray(r.ovl_encap)[1])
        assert int(r.ovl_outer.dst_ip[1]) == VTEP_B
        assert int(r.ovl_outer.src_ip[1]) == VTEP_A
        assert int(r.ovl_vni[1]) == DEFAULT_VNI
        assert int(r.ovl_vni[0]) == -1 and int(r.ovl_vni[2]) == -1

    def test_encap_bit_exact_vs_byte_oracle(self):
        """Device-built outer headers survive the host byte codec
        round trip bit-exact: encode_frame(device outer, device inner)
        → decode_frame → every field equals what the device holds."""
        dp, up, pod = mk_dp()
        pkts = make_packet_vector(
            [{"src": f"10.1.1.{2 + i}", "dst": f"10.2.3.{2 + i}",
              "proto": 6, "sport": 41000 + 977 * i, "dport": 80,
              "ttl": 64, "len": 200, "rx_if": pod}
             for i in range(8)], n=8)
        r = dp.process(pkts, now=1)
        enc = np.asarray(r.ovl_encap)
        assert enc[:8].all()
        for i in range(8):
            outer = {
                "src": int(r.ovl_outer.src_ip[i]),
                "dst": int(r.ovl_outer.dst_ip[i]),
                "sport": int(r.ovl_outer.sport[i]),
                "ttl": int(r.ovl_outer.ttl[i]),
            }
            inner = {
                "src": int(r.pkts.src_ip[i]),
                "dst": int(r.pkts.dst_ip[i]),
                "proto": int(r.pkts.proto[i]),
                "ttl": int(r.pkts.ttl[i]),
                "sport": int(r.pkts.sport[i]),
                "dport": int(r.pkts.dport[i]),
            }
            wire = encode_frame(outer, inner, vni=int(r.ovl_vni[i]))
            o, in_, vni, _ = decode_frame(wire)
            assert o["src"] == VTEP_A and o["dst"] == VTEP_B
            assert o["sport"] == outer["sport"]
            assert o["dport"] == VXLAN_PORT
            assert o["ttl"] == OUTER_TTL
            assert vni == int(r.ovl_vni[i]) == DEFAULT_VNI
            for k in ("src", "dst", "proto", "ttl", "sport", "dport"):
                assert in_[k] == inner[k], (i, k)

    def test_decap_differential_vs_oracle_mask(self):
        """Random lane mix (framed good/bad-VNI/wrong-port/not-ours +
        plain remote): the device admission mask equals the NumPy
        oracle applying the RFC 7348 checks the byte codec enforces."""
        rng = np.random.default_rng(19)
        dp, up, pod = mk_dp()
        n = 64
        kind = rng.integers(0, 5, n)  # 0 good 1 badvni 2 badport
        #                               3 not-ours 4 plain
        o_dst = np.where(kind == 3, ip4("192.168.16.7"),
                         VTEP_A).astype(np.uint32)
        o_dport = np.where(kind == 2, 5789, VXLAN_PORT)
        o_proto = np.where(kind == 4, 6, 17)
        vni = np.where(kind == 1, 999, DEFAULT_VNI).astype(np.int32)
        outer = PacketVector(
            src_ip=jnp.full((n,), VTEP_B, jnp.uint32),
            dst_ip=jnp.asarray(o_dst),
            proto=jnp.asarray(o_proto.astype(np.int32)),
            sport=jnp.asarray(
                (49152 + rng.integers(0, 16384, n)).astype(np.int32)),
            dport=jnp.asarray(o_dport.astype(np.int32)),
            ttl=jnp.full((n,), OUTER_TTL, jnp.int32),
            pkt_len=jnp.full((n,), 178, jnp.int32),
            rx_if=jnp.full((n,), up, jnp.int32),
            flags=jnp.full((n,), FLAG_VALID, jnp.int32),
        )
        inner = PacketVector(
            src_ip=jnp.asarray(
                (ip4("10.9.0.0")
                 + rng.integers(2, 250, n)).astype(np.uint32)),
            dst_ip=jnp.asarray(
                (ip4("10.2.1.0")
                 + rng.integers(2, 250, n)).astype(np.uint32)),
            proto=jnp.full((n,), 6, jnp.int32),
            sport=jnp.asarray(
                (1024 + rng.integers(0, 50000, n)).astype(np.int32)),
            dport=jnp.full((n,), 80, jnp.int32),
            ttl=jnp.full((n,), 64, jnp.int32),
            pkt_len=jnp.full((n,), 128, jnp.int32),
            rx_if=jnp.full((n,), up, jnp.int32),
            flags=jnp.full((n,), FLAG_VALID, jnp.int32),
        )
        r = dp.process(outer, now=1, ovl_inner=inner,
                       ovl_vni=jnp.asarray(vni))
        # oracle: addressed iff UDP/4789 to OUR vtep; admitted iff the
        # VNI names a tenant (single-tenant map: DEFAULT_VNI only)
        addressed = (o_proto == 17) & (o_dport == VXLAN_PORT) \
            & (o_dst == VTEP_A)
        admit = addressed & (vni == DEFAULT_VNI)
        fail_closed = addressed & ~admit
        assert int(r.stats.ovl_decap) == int(admit.sum())
        assert int(r.stats.drop_overlay) == int(fail_closed.sum())
        disp = np.asarray(r.disp)
        assert (disp[fail_closed] == int(Disposition.DROP)).all()
        assert (np.asarray(r.drop_cause)[fail_closed]
                == DROP_OVERLAY).all()
        # admitted lanes carry the INNER tuple through the step
        got_dst = np.asarray(r.pkts.dst_ip)
        assert (got_dst[admit] == np.asarray(inner.dst_ip)[admit]).all()
        # unaddressed lanes are untouched plain traffic
        plain = ~addressed
        assert (got_dst[plain] == o_dst[plain]).all()

    @pytest.mark.slow  # ~10 s: malformed-framing sweep; fail-closed stays fast via the VNI fails-closed test, decap differential stays fast
    def test_unparseable_framing_fails_closed_like_the_oracle(self):
        """The bad-UDP edge: a frame TO the VTEP the host codec cannot
        parse arrives with the no-framing sidecar (vni -1) — the codec
        raises, the device drops it OVERLAY-attributed. Both reject."""
        wire = bytearray(encode_frame(
            {"src": VTEP_B, "dst": VTEP_A},
            {"src": ip4("10.9.0.2"), "dst": ip4("10.1.1.5"),
             "proto": 6, "sport": 40000, "dport": 80}))
        wire[22] = 0x01  # corrupt the UDP dst port bytes
        wire[23] = 0x02
        with pytest.raises(ValueError):
            decode_frame(bytes(wire))
        dp, up, pod = mk_dp()
        outer = make_packet_vector(
            [{"src": "192.168.16.2", "dst": "192.168.16.1",
              "proto": 17, "sport": 50000, "dport": VXLAN_PORT,
              "ttl": OUTER_TTL, "len": 178, "rx_if": up}])
        r = dp.process(outer, now=1)  # default sidecar: vni -1
        assert int(r.stats.drop_overlay) == 1
        assert int(np.asarray(r.drop_cause)[0]) == DROP_OVERLAY
        # probe() synthesizes the same fail-closed sidecar
        rp = dp.probe(outer, now=2)
        assert int(rp.stats.drop_overlay) == 1

    def test_overlay_off_identity(self):
        """overlay=off IS the baseline: bit-exact verdicts vs a
        dataplane that never heard of the knob, no overlay sidecar in
        the result, overlay counters pinned at zero."""
        dp_off, up, pod = mk_dp(overlay="off")
        base = Dataplane(DataplaneConfig(
            max_tables=2, max_rules=8, max_global_rules=8,
            max_ifaces=8, fib_slots=32, sess_slots=512,
            nat_mappings=2, nat_backends=4))
        base.add_uplink()
        bpod = base.add_pod_interface(("default", "a"))
        base.builder.add_route("10.1.1.0/24", bpod, Disposition.LOCAL)
        base.builder.add_route("10.2.0.0/16", up, Disposition.REMOTE,
                               next_hop=VTEP_B, node_id=2)
        base.builder.add_route("192.168.16.0/24", up,
                               Disposition.REMOTE)
        base.swap()
        pkts = make_packet_vector(
            [{"src": f"10.1.1.{5 + i}", "dst": f"10.2.3.{4 + i}",
              "proto": 6, "sport": 40000 + i, "dport": 80,
              "rx_if": pod} for i in range(8)], n=8)
        r = dp_off.process(pkts, now=1)
        rb = base.process(pkts, now=1)
        assert r.ovl_outer is None
        assert r.ovl_encap is None and r.ovl_vni is None
        assert int(r.stats.ovl_decap) == 0
        assert int(r.stats.ovl_encap) == 0
        assert int(r.stats.drop_overlay) == 0
        np.testing.assert_array_equal(np.asarray(r.disp),
                                      np.asarray(rb.disp))
        np.testing.assert_array_equal(np.asarray(r.tx_if),
                                      np.asarray(rb.tx_if))
        np.testing.assert_array_equal(np.asarray(r.pkts.dst_ip),
                                      np.asarray(rb.pkts.dst_ip))
        assert int(r.disp[0]) == int(Disposition.REMOTE)

    def test_overlay_rejects_packed_forms(self):
        """The overlay stage pair is the plain step's: the packed wire
        forms refuse the knob loudly rather than silently skipping
        decap (the sidecar has no packed lane yet)."""
        dp, up, pod = mk_dp()
        flat = np.zeros((5, 8), np.int32)
        with pytest.raises(ValueError):
            dp.process_packed(flat)


class TestVniTenantMap:
    def mk_tenant_dp(self):
        dp, up, pod = mk_dp(tenancy="on", tenancy_tenants=4,
                            sess_slots=1024)
        dp.builder.set_tenant(1, prefixes=["10.61.0.0/16"], vni=100)
        dp.builder.set_tenant(2, prefixes=["10.62.0.0/16"], vni=200)
        dp.builder.add_route("10.61.1.0/24", pod, Disposition.LOCAL)
        dp.builder.add_route("10.62.1.0/24", pod, Disposition.LOCAL)
        dp.swap()
        return dp, up, pod

    def test_vni_names_the_tenant_on_device(self):
        from vpp_tpu.tenancy.derive import vni_tenant

        dp, up, pod = self.mk_tenant_dp()
        vni = jnp.asarray(np.array([100, 200, 999, -1], np.int32))
        tid, known = vni_tenant(dp.tables, vni)
        assert np.asarray(tid)[:2].tolist() == [1, 2]
        assert np.asarray(known).tolist() == [True, True, False,
                                              False]

    def test_wire_vni_overrides_address_derivation(self):
        """Tenant isolation pact: the VNI that CARRIED the frame names
        the tenant — a frame on tenant 2's VNI whose inner src sits in
        tenant 1's prefix is admitted as tenant 2 (the wire is
        authoritative; addresses can be spoofed)."""
        dp, up, pod = self.mk_tenant_dp()
        outer, inner, _ = vxlan_lanes(up, [
            ("10.61.0.9", "10.61.1.5", 40000, 0),
        ])
        rx0 = dp.tenant_snapshot()["rx"].copy()
        r = dp.process(outer, now=1, ovl_inner=inner,
                       ovl_vni=jnp.asarray(np.array([200], np.int32)))
        assert int(r.stats.ovl_decap) == 1
        d = dp.tenant_snapshot()["rx"] - rx0
        assert d[2] == 1, d
        assert d[1] == 0, d

    def test_unregistered_vni_fails_closed_per_tenant(self):
        dp, up, pod = self.mk_tenant_dp()
        outer, inner, _ = vxlan_lanes(up, [
            ("10.61.0.9", "10.61.1.5", 40000, 0),
            ("10.62.0.9", "10.62.1.5", 40001, 0),
            ("10.61.0.9", "10.61.1.6", 40002, 0),
        ])
        vni = jnp.asarray(np.array([100, 200, 300], np.int32))
        r = dp.process(outer, now=1, ovl_inner=inner, ovl_vni=vni)
        assert int(r.stats.ovl_decap) == 2
        assert int(r.stats.drop_overlay) == 1
        disp = np.asarray(r.disp)
        assert disp[0] == int(Disposition.LOCAL)
        assert disp[1] == int(Disposition.LOCAL)
        assert disp[2] == int(Disposition.DROP)
        # default tenant 0 has no VNI under tenancy: DEFAULT_VNI is
        # only auto-admitted in the tenancy-off single-tenant posture
        r2 = dp.process(outer, now=2, ovl_inner=inner,
                        ovl_vni=jnp.asarray(
                            np.array([DEFAULT_VNI] * 3, np.int32)))
        assert int(r2.stats.ovl_decap) == 0
        assert int(r2.stats.drop_overlay) == 3
