"""Randomized end-to-end policy differential test.

Reference model: mock/aclengine's semantic connectivity checks, pushed
further — random NetworkPolicies and pods are run through the ENTIRE
pipeline (cache → processor → configurator → renderer cache → device
tables → jitted verdicts) and compared against a direct pure-Python
oracle evaluating K8s NetworkPolicy semantics.
"""

import random

import pytest

from vpp_tpu.ir.rule import PodID
from vpp_tpu.ksr import model as m
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector
from vpp_tpu.policy import PolicyCache, PolicyConfigurator, PolicyProcessor
from vpp_tpu.renderer.tpu import TpuRenderer

LABEL_KEYS = ("app", "tier")
LABEL_VALS = ("web", "db", "cache")
PORTS = (80, 443, 5432)


def k8s_allowed(policies, pods, labels, src, dst, port):
    """Pure oracle for ingress NetworkPolicy semantics."""
    applying = [
        p for p in policies
        if p.pods.matches(labels[dst]) and p.applies_ingress()
    ]
    if not applying:
        return True  # not isolated
    for pol in applying:
        for rule in pol.ingress_rules:
            port_ok = (not rule.ports) or any(
                pp.port == port for pp in rule.ports
            )
            peer_ok = (not rule.peers) or any(
                peer.pods is not None and peer.pods.matches(labels[src])
                for peer in rule.peers
            )
            if port_ok and peer_ok:
                return True
    return False


@pytest.mark.parametrize("seed", [1, 7, 23])
def test_random_policies_match_oracle(seed):
    rng = random.Random(seed)
    n_pods = 5
    pods = [PodID("default", f"p{i}") for i in range(n_pods)]
    labels = {
        p: {k: rng.choice(LABEL_VALS) for k in LABEL_KEYS if rng.random() < 0.8}
        for p in pods
    }
    ips = {p: f"10.1.1.{i + 2}" for i, p in enumerate(pods)}

    dp = Dataplane(DataplaneConfig(sess_slots=256, max_tables=32))
    dp.add_uplink()
    cache = PolicyCache()
    configurator = PolicyConfigurator(cache)
    renderer = TpuRenderer(dp)
    configurator.register_renderer(renderer)
    processor = PolicyProcessor(cache, configurator)

    cache.update_namespace(m.Namespace(name="default", labels={}))
    for p in pods:
        idx = dp.add_pod_interface(p)
        dp.builder.add_route(f"{ips[p]}/32", idx, Disposition.LOCAL)
        cache.update_pod(m.Pod(name=p.name, namespace=p.namespace,
                               labels=labels[p], ip_address=ips[p]))
    dp.swap()

    # random ingress policies
    policies = []
    for i in range(rng.randint(1, 4)):
        sel_key = rng.choice(LABEL_KEYS)
        pol = m.Policy(
            name=f"pol{i}", namespace="default",
            pods=m.LabelSelector(
                match_labels={sel_key: rng.choice(LABEL_VALS)}),
            policy_type=m.POLICY_INGRESS,
            ingress_rules=[
                m.PolicyRule(
                    ports=[m.PolicyPort(protocol="TCP", port=rng.choice(PORTS))]
                    if rng.random() < 0.8 else [],
                    peers=[m.PolicyPeer(pods=m.LabelSelector(
                        match_labels={rng.choice(LABEL_KEYS): rng.choice(LABEL_VALS)}
                    ))] if rng.random() < 0.8 else [],
                )
                for _ in range(rng.randint(0, 2))
            ],
        )
        policies.append(pol)
        cache.update_policy(pol)

    # compare verdicts for every (src, dst, port) triple
    mismatches = []
    for src in pods:
        for dst in pods:
            if src == dst:
                continue
            for port in PORTS:
                pkts = make_packet_vector([
                    dict(src=ips[src], dst=ips[dst], proto=6,
                         sport=40000, dport=port, rx_if=dp.pod_if[src])
                ])
                got = int(dp.process(pkts).disp[0]) == int(Disposition.LOCAL)
                want = k8s_allowed(policies, pods, labels, src, dst, port)
                if got != want:
                    mismatches.append(
                        (src.name, dst.name, port, "got",
                         "allow" if got else "deny",
                         "want", "allow" if want else "deny")
                    )
    assert not mismatches, mismatches[:10]
