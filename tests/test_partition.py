"""Partition-rule layer (ISSUE 12): name-regex → PartitionSpec.

Two halves:

* **Rule-matching units** — ordering (first match wins), anchoring,
  the unmatched-field error (never a silent replicate), manifest
  completeness, stale-rule findings, the divisibility validators, and
  the ``--partitions`` lint pass tier-1 runs from here.
* **Mesh differentials** — 2- and 4-way rule-sharded clusters running
  the FULL selection (word-sharded BV classify, hidden/tree-sharded
  int8 ML enforce, bucket-sharded sessions, SPMD-uniform fastpath
  dispatch) against a standalone Dataplane with the identical config
  on identical seeded traffic: verdicts, stats and session STATE must
  be bit-exact, the fastpath predicate must not diverge per shard
  under mixed traffic, and the cluster snapshot must round-trip
  bit-identical per-shard session state (and refuse a different mesh).
"""

import importlib.util
import pathlib

import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from vpp_tpu.parallel import partition as pt
from vpp_tpu.pipeline.tables import DataplaneConfig, DataplaneTables


# --- rule-matching units ---------------------------------------------


def test_first_match_wins_in_order():
    rules = (
        pt.PartitionRule(r"^glb_bv_bnd_", P("node"), "boundaries"),
        pt.PartitionRule(r"^glb_bv_", P("node", None, "rule"), "planes"),
    )
    bnd = pt.match_partition_rules("glb_bv_bnd_src", rules)
    plane = pt.match_partition_rules("glb_bv_src", rules)
    assert bnd.reason == "boundaries"
    assert plane.reason == "planes"
    # reversed order would swallow the boundary fields into the plane
    # rule — first match wins, so order is load-bearing
    swapped = (rules[1], rules[0])
    assert pt.match_partition_rules(
        "glb_bv_bnd_src", swapped).reason == "planes"


def test_anchoring_keeps_scalars_out_of_the_bucket_grids():
    """The session scalar fields must resolve to their explicit rules,
    not the [NB, W] bucket-grid rule right below them."""
    m = pt.spec_manifest()
    assert m["sess_max_age"].spec == P(pt.NODE_AXIS)
    assert m["sess_sweep_cursor"].spec == P(pt.NODE_AXIS)
    assert m["natsess_sweep_cursor"].spec == P(pt.NODE_AXIS)
    assert m["sess_valid"].spec == P(pt.NODE_AXIS, pt.RULE_AXIS)
    assert m["natsess_valid"].spec == P(pt.NODE_AXIS, pt.RULE_AXIS)


def test_manifest_names_every_field():
    m = pt.spec_manifest()
    assert set(m) == set(DataplaneTables._fields)
    for f, entry in m.items():
        assert entry.field == f
        assert entry.reason  # every placement is a documented decision


def test_unmatched_field_is_an_error_not_a_silent_replicate():
    # a truncated rule set that misses the session grids entirely
    rules = (pt.PartitionRule(r"^glb_", P("node", "rule"), "glb"),)
    with pytest.raises(pt.PartitionError, match="matches no partition"):
        for f in DataplaneTables._fields:
            pt.spec_for(f, rules)


def test_spec_for_unknown_name_raises():
    with pytest.raises(pt.PartitionError,
                       match="no_such_field_anywhere"):
        pt.spec_for("no_such_field_anywhere",
                    (pt.PartitionRule(r"^glb_", P("node"), "x"),))


def test_partition_lint_flags_stale_rules(monkeypatch):
    stale = pt.PARTITION_RULES + (
        pt.PartitionRule(r"^zz_never_matches_", P("node"), "stale"),
    )
    monkeypatch.setattr(pt, "PARTITION_RULES", stale)
    problems = pt.partition_lint()
    assert any("zz_never_matches_" in p for p in problems)


def test_partitions_lint_pass_green():
    """The tier-1 hook: the shipped rule set must resolve every field
    and carry no stale rules (tools/lint.py --partitions)."""
    repo = pathlib.Path(__file__).resolve().parents[1]
    spec = importlib.util.spec_from_file_location(
        "vppt_lint", repo / "tools" / "lint.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.partitions_lint() == []


def test_validate_partitioning_divisibility():
    cfg = DataplaneConfig(sess_slots=256, sess_ways=4)  # 64 buckets
    pt.validate_partitioning(cfg, 4)   # 64 % 4 == 0
    with pytest.raises(ValueError, match="buckets"):
        pt.validate_partitioning(
            cfg._replace(sess_slots=8, sess_ways=4), 4)  # 2 buckets
    ml = cfg._replace(ml_stage="score", ml_hidden=6)
    with pytest.raises(ValueError, match="ml_hidden"):
        pt.validate_partitioning(ml, 4)
    pt.validate_partitioning(ml._replace(ml_hidden=8), 4)
    # rule_shards == 1 divides everything
    pt.validate_partitioning(cfg._replace(sess_slots=8), 1)


def test_bv_mesh_ok_word_alignment():
    cfg = DataplaneConfig(classifier="bv", max_global_rules=256)
    assert pt.bv_mesh_ok(cfg, 4)          # 256 % 128 == 0
    assert not pt.bv_mesh_ok(cfg._replace(max_global_rules=96), 2)
    assert pt.bv_mesh_ok(cfg._replace(max_global_rules=96), 1)
    assert not pt.bv_mesh_ok(cfg._replace(classifier="dense"), 1)


# --- mesh differentials ----------------------------------------------


def _stage(node, rules, model):
    from vpp_tpu.pipeline.vector import Disposition

    node.add_uplink()
    pod_if = node.add_pod_interface(("part", "pod"))
    node.builder.add_route("10.1.1.2/32", pod_if, Disposition.LOCAL)
    node.builder.set_global_table(rules)
    if model is not None:
        node.builder.set_ml_model(model)
    return pod_if


def _build_pair(shards, ml_kind="mlp", sess_slots=512):
    """(cluster, standalone, pod_if): a 1-node x S-shard mesh and a
    standalone Dataplane with IDENTICAL staged config. Sweep disabled:
    the differential compares session state cell-for-cell and the
    cluster sweeps twice per step (two pipeline passes)."""
    import ipaddress

    from vpp_tpu.ir.rule import Action, ContivRule, Protocol
    from vpp_tpu.ml.train import train_and_pack
    from vpp_tpu.parallel.cluster import ClusterDataplane
    from vpp_tpu.parallel.mesh import cluster_mesh
    from vpp_tpu.pipeline.dataplane import Dataplane

    cfg = DataplaneConfig(
        max_tables=4, max_rules=16, max_global_rules=256, max_ifaces=8,
        fib_slots=32, sess_slots=sess_slots, nat_mappings=2,
        nat_backends=4, classifier="bv", fastpath=True,
        ml_stage="enforce", ml_hidden=8, ml_trees=4, ml_depth=2,
        sess_sweep_stride=0,
    )
    rules = [
        ContivRule(action=Action.DENY, protocol=Protocol.TCP,
                   src_network=ipaddress.ip_network(f"10.9.{i}.0/24"),
                   dest_port=9000 + i)
        for i in range(40)
    ] + [ContivRule(action=Action.PERMIT)]
    model, _ = train_and_pack(kind=ml_kind, hidden=8, trees=4, depth=2,
                              seed=7)
    clus = ClusterDataplane(cluster_mesh(1, shards), cfg)
    pod_if = _stage(clus.node(0), rules, model)
    clus.swap()
    solo = Dataplane(cfg)
    assert _stage(solo, rules, model) == pod_if
    solo.swap()
    return clus, solo, pod_if


def _mixed_frames(pod_if, seed, n=48, reverse=False):
    rng = np.random.default_rng(seed)
    pk = []
    for i in range(n):
        sport = 20000 + i
        dport = int(rng.integers(8990, 9080))
        src = f"10.9.{int(rng.integers(0, 64))}.{i % 200 + 1}"
        dst = "10.1.1.2"
        if reverse:
            src, dst, sport, dport = dst, src, dport, sport
        pk.append({"src": src, "dst": dst, "proto": 6, "sport": sport,
                   "dport": dport, "rx_if": pod_if})
    return pk


def _assert_step_bitexact(clus, solo, pk, now, check_fastpath=None):
    import jax

    from vpp_tpu.pipeline.vector import make_packet_vector

    c_res = clus.step(clus.make_frames([pk], n=64), now=now)
    s_res = solo.process(make_packet_vector(pk, n=64), now=now)
    jax.block_until_ready(c_res.tables.sess_valid)
    n = len(pk)
    for f in ("disp", "tx_if", "drop_cause"):
        np.testing.assert_array_equal(
            np.asarray(getattr(c_res.local, f))[0][:n],
            np.asarray(getattr(s_res, f))[:n], err_msg=f)
    # cluster stats sum BOTH pipeline passes; pass 2 sees no valid
    # packets here (no REMOTE routes), so the packet-indexed counters
    # must match the standalone single pass exactly
    for f in ("rx", "tx", "drop_acl", "drop_no_route", "sess_hits",
              "ml_scored", "ml_flagged", "ml_drops",
              "sess_insert_fail"):
        assert int(np.asarray(getattr(c_res.stats, f)).sum()) == \
            int(np.asarray(getattr(s_res.stats, f))), f
    for f in ("sess_valid", "sess_src", "sess_dst", "sess_ports",
              "sess_proto", "sess_time"):
        np.testing.assert_array_equal(
            np.asarray(getattr(clus.tables, f))[0],
            np.asarray(getattr(solo.tables, f)), err_msg=f)
    if check_fastpath is not None:
        # pass 1 carries the real dispatch; pass 2 (all-invalid) is
        # vacuously fast — subtract it
        fp = int(np.asarray(c_res.stats.fastpath).sum()) - 1
        assert fp == check_fastpath, (
            f"pass-1 fastpath {fp} != {check_fastpath}")
        assert int(np.asarray(s_res.stats.fastpath)) == check_fastpath
    return c_res, s_res


def test_mesh_2way_bv_ml_sessions_bitexact():
    """2-way differential: sharded BV classify + hidden-sharded MLP
    enforce + bucket-sharded session insert/lookup, three steps of
    seeded mixed traffic including repeats (refresh path) — verdicts,
    stats and session cells bit-exact vs the standalone dataplane."""
    clus, solo, pod_if = _build_pair(2)
    assert clus.classifier_impl == "bv"
    assert clus.ml_selected == "enforce"
    fwd = _mixed_frames(pod_if, seed=1)
    _assert_step_bitexact(clus, solo, fwd, now=1)
    # repeat (intra-table refresh + established hits), then new flows
    _assert_step_bitexact(clus, solo, fwd, now=2)
    _assert_step_bitexact(clus, solo, _mixed_frames(pod_if, seed=2),
                          now=3)


@pytest.mark.slow  # ~22 s: 4-way mesh compile; the 2-way bitexact differential stays the fast anchor
def test_mesh_4way_bitexact_and_fastpath_uniform():
    """4-way differential + the SPMD-uniform fastpath dispatch: mixed
    traffic must take the full chain on EVERY shard (no divergence —
    the step completes and matches standalone), and an all-established
    reply batch must engage the classify-free tier on the mesh."""
    clus, solo, pod_if = _build_pair(4, sess_slots=512)
    assert clus.fastpath_selected
    fwd = [p for p in _mixed_frames(pod_if, seed=3, n=32)]
    # step 1: fresh flows — not established, full chain everywhere
    _assert_step_bitexact(clus, solo, fwd, now=1, check_fastpath=0)
    # step 2: the SAME packets are forward-direction repeats of
    # installed sessions — still not reverse hits; mixed with one new
    # flow the predicate stays down and every shard agrees
    _assert_step_bitexact(clus, solo, fwd + _mixed_frames(
        pod_if, seed=4, n=8), now=2, check_fastpath=0)
    # step 3: pure REPLY traffic of the permitted flows — every valid
    # packet rides an established session, the all-reduced predicate
    # goes up on every shard, and the fast tier result still matches
    # standalone bit-for-bit. Replies are synthesized from the LIVE
    # session table (post-NAT forward keys), reversed.
    assert np.asarray(clus.tables.sess_valid).sum() > 0
    reply = []
    live_src = np.asarray(clus.tables.sess_src)[0]
    live_dst = np.asarray(clus.tables.sess_dst)[0]
    live_ports = np.asarray(clus.tables.sess_ports)[0]
    live_ok = np.asarray(clus.tables.sess_valid)[0] == 1
    for b, w in zip(*np.nonzero(live_ok)):
        sport = int(live_ports[b, w]) >> 16
        dport = int(live_ports[b, w]) & 0xFFFF
        reply.append({
            "src": ".".join(str((int(live_dst[b, w]) >> s) & 255)
                            for s in (24, 16, 8, 0)),
            "dst": ".".join(str((int(live_src[b, w]) >> s) & 255)
                            for s in (24, 16, 8, 0)),
            "proto": 6, "sport": dport, "dport": sport,
            "rx_if": pod_if,
        })
        if len(reply) == 24:
            break
    _assert_step_bitexact(clus, solo, reply, now=3, check_fastpath=1)


@pytest.mark.slow  # the forest gates compile their own cluster+solo
# programs (~17 s); the MLP differential above already pins the
# psum-reduce contract, and the MULTICHIP dry run covers selection
def test_mesh_forest_ml_tree_sharded_bitexact():
    """The oblivious-forest kernel with the TREE axis sharded: partial
    vote sums psum to the standalone forest score exactly."""
    clus, solo, pod_if = _build_pair(2, ml_kind="forest")
    assert clus._ml_kind == "forest"
    _assert_step_bitexact(clus, solo, _mixed_frames(pod_if, seed=5),
                          now=1)


def test_cluster_snapshot_roundtrip_and_mesh_refusal(tmp_path):
    """Per-shard drains into one manifest: a same-mesh restore comes
    back bit-identical; a different rule-shard count refuses cleanly
    (outcome counted, nothing half-restored)."""
    from vpp_tpu.parallel.cluster import ClusterDataplane
    from vpp_tpu.parallel.mesh import cluster_mesh
    from vpp_tpu.pipeline.snapshot import SessionSnapshotter

    clus, _solo, pod_if = _build_pair(2)
    clus.step(clus.make_frames(
        [_mixed_frames(pod_if, seed=6)], n=64), now=1)
    snap = SessionSnapshotter(clus, str(tmp_path), chunk_buckets=64)
    assert snap.snapshot() == 1
    # chunk files never straddle a shard boundary: every entry's
    # bucket range maps to exactly one shard
    m = snap._load_manifest()
    per_shard = (clus.config.sess_slots // clus.config.sess_ways) // 2
    for tab in m["tables"].values():
        for e in tab["chunks"]:
            assert e["start"] // per_shard == e["shard"] or \
                tab["chunk_buckets"] > per_shard

    clus2, _solo2, _ = _build_pair(2)
    snap2 = SessionSnapshotter(clus2, str(tmp_path), chunk_buckets=64)
    assert snap2.restore_into()
    for f in ("sess_valid", "sess_src", "sess_dst", "sess_ports",
              "sess_proto"):
        np.testing.assert_array_equal(
            np.asarray(getattr(clus.tables, f)),
            np.asarray(getattr(clus2.tables, f)), err_msg=f)

    from vpp_tpu.pipeline.tables import DataplaneConfig as _DC  # noqa: F401
    clus4 = ClusterDataplane(cluster_mesh(1, 4), clus.config)
    _stage_min(clus4.node(0))
    clus4.swap()
    snap4 = SessionSnapshotter(clus4, str(tmp_path), chunk_buckets=64)
    sessions, outcome = snap4.restore()
    assert sessions is None and outcome == "geometry"
    assert snap4.stats["restores"]["geometry"] == 1


def _stage_min(node):
    from vpp_tpu.ir.rule import Action, ContivRule
    from vpp_tpu.pipeline.vector import Disposition

    node.add_uplink()
    pod_if = node.add_pod_interface(("part", "pod"))
    node.builder.add_route("10.1.1.2/32", pod_if, Disposition.LOCAL)
    node.builder.set_global_table([ContivRule(action=Action.PERMIT)])
    return pod_if


def test_incremental_upload_groups_reship_only_rebuilt_planes():
    """The mesh swap's per-shard upload groups: a second swap with one
    node's global-table churn re-ships the glb group (and only the
    REBUILT BV dimension planes); everything else reuses the cached
    sharded device arrays."""
    import ipaddress

    from vpp_tpu.ir.rule import Action, ContivRule, Protocol

    clus, _solo, _pod_if = _build_pair(2)
    first = dict(clus.upload_stats)
    assert first["fields_reused"] == 0
    node = clus.node(0)
    # port-only churn: the identity-diff pack + dimension-incremental
    # BV compile rebuild only the dport plane
    rules = [
        ContivRule(action=Action.DENY, protocol=Protocol.TCP,
                   src_network=ipaddress.ip_network(f"10.9.{i}.0/24"),
                   dest_port=9100 + i)
        for i in range(40)
    ] + [ContivRule(action=Action.PERMIT)]
    with node._lock:
        node.builder.set_global_table(rules)
    clus.swap()
    second = dict(clus.upload_stats)
    assert second["fields_reused"] > 0
    # glb dense rows re-ship; acl/if/fib/nat/ml groups must all reuse
    total = second["fields_shipped"] + second["fields_reused"]
    assert second["fields_shipped"] < total // 2
    # a no-op swap re-ships nothing at all
    clus.swap()
    assert clus.upload_stats["fields_shipped"] == 0


def test_partition_observability_cli_and_gauges():
    """`show partitions` + the vpp_tpu_partition_info /
    vpp_tpu_shard_sessions_resident gauges (collector wired via
    set_cluster)."""
    import types

    from vpp_tpu.cli import DebugCLI
    from vpp_tpu.stats.collector import StatsCollector

    clus, _solo, pod_if = _build_pair(2)
    clus.step(clus.make_frames(
        [_mixed_frames(pod_if, seed=8)], n=64), now=1)
    cli = DebugCLI(clus.node(0),
                   mesh_runtime=types.SimpleNamespace(cluster=clus))
    page = cli.run("show partitions")
    assert "rule shards" in page and "classifier=bv" in page
    assert "per-shard sessions resident" in page
    coll = StatsCollector(clus.node(0))
    coll.set_cluster(clus)
    coll.publish()
    part = coll.partition_gauge
    assert part.get(field="glb_bv_src", axis="rule", shards="2") == 1.0
    assert part.get(field="sess_valid", axis="rule", shards="2") == 1.0
    assert part.get(field="fib_prefix", axis="replicated",
                    shards="2") == 1.0
    res0 = coll.shard_sessions_gauge.get(shard="0")
    res1 = coll.shard_sessions_gauge.get(shard="1")
    assert res0 + res1 > 0
    assert coll.shard_rule_bytes_gauge.get(shard="0") > 0
