"""Device-resident telemetry plane (ISSUE 11): differential suite.

The device kernels (ops/telemetry.py) are validated against
INDEPENDENT NumPy recomputes implemented from the documented contract:

* the wire-latency log2 histogram must be BIT-EXACT against a
  per-packet host recompute over seeded mixed traffic (the bucketing
  is pure integer compares, so equality is exact, not approximate);
* count-min sketch estimates must respect the hard CM guarantee
  (never under-count) and sit within the (d, w) theoretical error
  bound on a seeded Zipf flow mix, with top-K recall >= 0.9;
* the ring path with telemetry on must still make ZERO io_callbacks
  (counter + lowered-program check), with the bins riding the
  window's one result fetch;
* ``telemetry: off`` must compile the plane out — no extra step
  variants traced (jit-budget guard), labels unchanged;
* the aux rider's packed/chained/ring layouts are pinned against the
  ONE schema constant (PACKED_AUX_SCHEMA) so the next widening is a
  one-line change;
* the exposition face (vpp_tpu_wire_latency_seconds + quantile gauges
  + flow-sketch families + vpp_tpu_build_info) passes the scrape
  conformance contract of tests/test_exposition.py.
"""

from __future__ import annotations

import urllib.request

import pytest

import numpy as np

import jax.numpy as jnp

from vpp_tpu.ops.telemetry import (
    lat_bucket,
    lat_bucket_np,
    quantiles_from_bins,
    sketch_cols,
    tel_flow_hash_np,
    tel_rider_width,
    unpack_tel_rider,
)
from vpp_tpu.pipeline.dataplane import (
    PACKED_AUX_ROWS,
    PACKED_AUX_SCHEMA,
    Dataplane,
    pack_packet_columns,
)
from vpp_tpu.pipeline.tables import (
    DataplaneConfig,
    TableBuilder,
    tel_capacity,
)
from vpp_tpu.pipeline.vector import (
    FLAG_VALID,
    Disposition,
    PacketVector,
    ip4,
    make_packet_vector,
)

from test_exposition import validate_body


def small_cfg(**kw) -> DataplaneConfig:
    base = dict(max_tables=2, max_rules=8, max_global_rules=16,
                max_ifaces=8, fib_slots=16, sess_slots=64,
                nat_mappings=2, nat_backends=4)
    base.update(kw)
    return DataplaneConfig(**base)


def build_dp(telemetry: str, **kw):
    dp = Dataplane(small_cfg(telemetry=telemetry, **kw))
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("d", "p"))
    dp.builder.add_route("10.1.1.0/24", pod, Disposition.LOCAL)
    dp.builder.add_route("0.0.0.0/0", up, Disposition.REMOTE,
                         node_id=1)
    dp.swap()
    return dp, up


def packed_frame(batch: int, up: int, sport, dport=80, n_valid=None,
                 src="10.9.0.9", dst="10.1.1.2", proto=6):
    """One packed [5, batch] frame; ``n_valid`` < batch leaves invalid
    tail lanes (flags 0) the telemetry must NOT observe."""
    if n_valid is None:
        n_valid = batch
    sport = np.broadcast_to(np.asarray(sport, np.uint32), (batch,))
    flags = np.zeros(batch, np.uint32)
    flags[:n_valid] = 1
    cols = {
        "src_ip": np.full(batch, ip4(src), np.uint32),
        "dst_ip": np.full(batch, ip4(dst), np.uint32),
        "proto": np.full(batch, proto, np.uint32),
        "sport": sport.copy(),
        "dport": np.full(batch, dport, np.uint32),
        "ttl": np.full(batch, 64, np.uint32),
        "pkt_len": np.full(batch, 128, np.uint32),
        "rx_if": np.full(batch, up, np.uint32),
        "flags": flags,
    }
    flat = np.zeros((5, batch), np.int32)
    pack_packet_columns(flat.view(np.uint32), cols, batch)
    return flat


# --------------------------------------------------------------------
# exact log2 bucketing
# --------------------------------------------------------------------


class TestBucketing:
    def test_device_bucketing_matches_oracle_on_edges(self):
        nb = 24
        edges = []
        for k in range(nb + 2):
            v = 1 << k
            edges += [v - 1, v, v + 1]
        lat = np.asarray([0, 1] + edges, np.int64)
        lat = np.clip(lat, 0, 0x7FFFFFFF).astype(np.int32)
        dev = np.asarray(lat_bucket(jnp.asarray(lat), nb))
        host = lat_bucket_np(lat, nb)
        assert np.array_equal(dev, host)
        # the contract itself: 0/1 -> bucket 0, [2^b, 2^(b+1)) -> b,
        # saturation at nb-1
        assert host[0] == 0 and host[1] == 0
        assert lat_bucket_np(np.asarray([2, 3]), nb).tolist() == [1, 1]
        assert int(lat_bucket_np(
            np.asarray([1 << (nb + 1)]), nb)[0]) == nb - 1

    def test_device_bucketing_matches_oracle_random(self):
        rng = np.random.default_rng(5)
        lat = rng.integers(0, 1 << 30, 4096).astype(np.int32)
        dev = np.asarray(lat_bucket(jnp.asarray(lat), 24))
        assert np.array_equal(dev, lat_bucket_np(lat, 24))

    def test_quantiles_from_bins(self):
        bins = np.zeros(24, np.int64)
        bins[3] = 100  # all latency in [8, 16) µs
        p50, p99, p999 = quantiles_from_bins(bins)
        assert 8.0 <= p50 <= 16.0 and 8.0 <= p999 <= 16.0
        assert quantiles_from_bins(np.zeros(24)) == (0.0, 0.0, 0.0)


# --------------------------------------------------------------------
# the histogram differential: device bins bit-exact vs host recompute
# --------------------------------------------------------------------


class TestHistogramDifferential:
    def test_packed_path_bins_bit_exact_vs_host_recompute(self):
        """Seeded mixed traffic (varying valid counts, stamps, and
        dispatch clocks — including an unstamped batch and a clock-wrap
        negative latency, both unobserved) through process_packed; the
        device bins must equal a per-packet NumPy recompute EXACTLY."""
        B = 32
        dp, up = build_dp("latency")
        nb = tel_capacity(dp.config)[0]
        rng = np.random.default_rng(11)
        expect = np.zeros(nb, np.int64)
        expect_count = 0
        for i in range(12):
            n_valid = int(rng.integers(1, B + 1))
            flat = packed_frame(B, up, sport=3000 + i,
                               n_valid=n_valid)
            if i == 4:
                stamp, now_us = 0, 10_000           # unstamped
            elif i == 7:
                stamp, now_us = 50_000, 40_000      # negative lat
            else:
                stamp = int(rng.integers(1, 1 << 20))
                now_us = stamp + int(rng.integers(0, 1 << 22))
            dp.process_packed(flat, now=i + 1, stamp_us=stamp,
                              now_us=now_us)
            lat = now_us - stamp
            if stamp > 0 and lat >= 0:
                b = int(lat_bucket_np(np.asarray([lat]), nb)[0])
                expect[b] += n_valid
                expect_count += n_valid
        snap = dp.telemetry_snapshot()
        assert np.array_equal(np.asarray(snap["bins"], np.int64),
                              expect)
        assert int(snap["bins"].sum()) == expect_count

    @pytest.mark.slow  # ~12 s: mode-interaction variant compile; histogram and sketch differentials each stay fast on their own
    def test_latency_mode_skips_sketch(self):
        dp, up = build_dp("latency")
        dp.process_packed(packed_frame(8, up, sport=1000), now=1,
                          stamp_us=10, now_us=20)
        snap = dp.telemetry_snapshot()
        assert snap["sketched"] == 0
        res = dp.process(make_packet_vector(
            [dict(src="10.9.0.1", dst="10.1.1.2", proto=6,
                  sport=1, dport=80, rx_if=up)]), now=2)
        assert int(res.stats.tel_sketched) == 0

    def test_histogram_survives_epoch_swap(self):
        """The telemetry planes ride the session carry: an epoch swap
        must not reset the bins (the sweep-cursor contract)."""
        dp, up = build_dp("latency")
        dp.process_packed(packed_frame(8, up, sport=1000), now=1,
                          stamp_us=10, now_us=20)
        before = dp.telemetry_snapshot()["bins"].copy()
        assert before.sum() == 8
        with dp.commit_lock:
            dp.builder.add_route("10.3.0.0/24", up, Disposition.REMOTE,
                                 node_id=1)
            dp.swap()
        assert np.array_equal(dp.telemetry_snapshot()["bins"], before)


# --------------------------------------------------------------------
# count-min sketch + top-K (telemetry "full")
# --------------------------------------------------------------------


def zipf_flows(n_flows: int, alpha: float, rounds: int, batch: int,
               seed: int = 3):
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_flows + 1, dtype=np.float64)
    probs = ranks ** -alpha
    probs /= probs.sum()
    return [rng.choice(n_flows, batch, p=probs) for _ in range(rounds)]


class TestFlowSketch:
    def _drive(self, dp, up, draws):
        base = ip4("198.18.0.0")
        dst = ip4("10.1.1.9")
        true = np.zeros(512, np.int64)
        for r, ids in enumerate(draws):
            np.add.at(true, ids, 1)
            b = len(ids)
            pv = PacketVector(
                src_ip=jnp.asarray((base + ids).astype(np.uint32)),
                dst_ip=jnp.full((b,), dst, jnp.uint32),
                proto=jnp.full((b,), 6, jnp.int32),
                sport=jnp.asarray((1024 + ids).astype(np.int32)),
                dport=jnp.full((b,), 8080, jnp.int32),
                ttl=jnp.full((b,), 64, jnp.int32),
                pkt_len=jnp.full((b,), 128, jnp.int32),
                rx_if=jnp.full((b,), up, jnp.int32),
                flags=jnp.full((b,), FLAG_VALID, jnp.int32),
            )
            dp.process(pv, now=2 + r)
        n_flows = len(true)
        ids = np.arange(n_flows)
        h0 = tel_flow_hash_np(
            (base + ids).astype(np.uint32),
            np.full(n_flows, dst, np.uint32), 1024 + ids,
            np.full(n_flows, 8080), np.full(n_flows, 6))
        return true, h0

    def test_estimates_within_cm_bound_and_never_undercount(self):
        dp, up = build_dp("full", telemetry_sketch_cols=1024,
                          telemetry_sketch_rows=2)
        draws = zipf_flows(512, 1.2, 24, 256)
        true, h0 = self._drive(dp, up, draws)
        sk = np.asarray(dp.tables.tel_sketch)
        d, w = sk.shape
        est = np.min(np.stack(
            [sk[r, sketch_cols(h0, r, w)] for r in range(d)]), axis=0
        ).astype(np.int64)
        n_total = int(np.asarray(dp.tables.tel_sketched))
        assert n_total == int(true.sum())
        # hard CM guarantee: never under-count
        assert (est >= true).all()
        # theoretical bound: overestimate > e*N/w with prob <= e^-d
        # per flow; seeded, so assert the bound holds for >= 95% of
        # flows and that nothing explodes past 3x the bound
        bound = np.e * n_total / w
        over = est - true
        assert (over <= bound).mean() >= 0.95, \
            f"CM bound violated too often: {over.max()} vs {bound}"
        assert over.max() <= 3 * bound + 1

    def test_topk_recall_on_zipf_mix(self):
        """Recall >= 0.9 of the TRUE top-K on a heavy-tailed mix (the
        acceptance bar). alpha=1.5 separates the head clearly — the
        amortized one-leader-per-step election must still converge on
        it over the rounds."""
        dp, up = build_dp("full", telemetry_topk=8)
        draws = zipf_flows(512, 1.5, 40, 256, seed=9)
        true, h0 = self._drive(dp, up, draws)
        snap = dp.telemetry_snapshot()
        k = len(snap["top_key"])
        top_true = set(h0[np.argsort(-true)[:k]].tolist())
        got = set(snap["top_key"].tolist())
        recall = len(top_true & got) / k
        assert recall >= 0.9, (recall, sorted(true)[-k:])
        # candidate counts are count-min estimates: each resident
        # candidate's count must not under-count its true traffic
        by_hash = {int(h): int(t) for h, t in zip(h0, true)}
        for key, cnt in zip(snap["top_key"], snap["top_cnt"]):
            if int(cnt) > 0 and int(key) in by_hash:
                assert int(cnt) >= 0  # estimates start below true
                                       # mid-run; final >= is not
                                       # guaranteed for late entrants
        # the top slot's flow is identifiable (src/dst/ports planes)
        best = int(np.argmax(snap["top_cnt"]))
        assert int(snap["top_dst"][best]) == ip4("10.1.1.9")

    def test_both_tiers_feed_the_sketch(self):
        """The fast tier must sketch too: an all-established reply
        batch (fastpath engaged) still advances tel_sketched."""
        dp, up = build_dp("full")
        pod = dp.pod_if[("d", "p")]
        fwd = make_packet_vector(
            [dict(src="10.1.1.2", dst="10.9.0.5", proto=6,
                  sport=7000 + i, dport=80, rx_if=pod)
             for i in range(8)])
        r1 = dp.process(fwd, now=1)  # installs reflective sessions
        assert int(r1.stats.tx) == 8
        reply = make_packet_vector(
            [dict(src="10.9.0.5", dst="10.1.1.2", proto=6,
                  sport=80, dport=7000 + i, rx_if=up)
             for i in range(8)])
        r2 = dp.process(reply, now=2)  # all-established -> fast tier
        assert int(r2.stats.fastpath) == 1
        assert int(r1.stats.tel_sketched) == 8
        assert int(r2.stats.tel_sketched) == 8


# --------------------------------------------------------------------
# ring path: telemetry with zero io_callbacks
# --------------------------------------------------------------------


class TestRingTelemetry:
    def test_ring_telemetry_rider_and_zero_callbacks(self):
        from vpp_tpu.pipeline.persistent import PersistentPump

        B = 32
        dp, up = build_dp("latency")
        nb, _d, _w, k = tel_capacity(dp.config)
        pump = PersistentPump(
            dp.tables, batch=B, fastpath=dp._use_fastpath,
            classifier=dp._classifier_impl,
            skip_local=dp._skip_local, ring_slots=4, ring_windows=2,
            tel_mode="latency").start()
        try:
            stamps = []
            for i in range(6):
                stamp = 1000 + 100 * i
                stamps.append(stamp)
                pump.submit(packed_frame(B, up, sport=5000 + i),
                            now=i + 1, stamp_us=stamp)
            got = [pump.result_ex(timeout=180) for _ in range(6)]
        finally:
            final = pump.stop()
        snap = pump.stats_snapshot()
        assert snap["io_callbacks"] == 0
        assert snap["ring_frames"] == 6
        # the rider rode the window fetch: raw width matches the
        # config geometry and the bins count every valid packet
        raw = pump.tel_raw()
        assert raw is not None
        assert raw.shape == (tel_rider_width(nb, k),)
        tel = unpack_tel_rider(raw, nb, k)
        assert int(tel["bins"].sum()) == 6 * B
        # aux row 8 (tel_observed) counted per frame
        idx = PACKED_AUX_SCHEMA.index("tel_observed")
        assert all(int(aux[idx]) == B for _out, aux in got)
        # final tables carry the same bins (the stop-merge graft path)
        assert int(np.asarray(final.tel_lat_hist).sum()) == 6 * B

    def test_ring_telemetry_program_has_no_callbacks(self):
        """The io_callback-free claim, measured on the TELEMETRY
        window program itself (the test_device_rings lowering check,
        re-run on the tel-widened signature; unique geometry so the
        compile-once session guard stays green)."""
        from vpp_tpu.pipeline.dataplane import _jitted_step

        tables = TableBuilder(small_cfg(telemetry="latency")).to_device()
        step = _jitted_step("dense", False, False, "ring",
                            ring_slots=2, tel_mode="latency")
        lowered = step.lower(
            tables, jnp.int32(0), np.zeros((2, 5, 16), np.int32),
            np.zeros(2, np.int32), np.zeros(2, np.int32),
            jnp.int32(0), np.int32(1))
        text = lowered.as_text().lower()
        assert "callback" not in text, \
            "host callback reintroduced into the telemetry ring program"


# --------------------------------------------------------------------
# off state: compiled out, zero extra variants
# --------------------------------------------------------------------


class TestOffCompiledOut:
    def test_off_labels_and_signatures_unchanged(self):
        from vpp_tpu.pipeline.dataplane import _step_label

        assert _step_label("dense", False, False, "packed", 256) == \
            "dense_packed"
        assert "_tel" in _step_label("dense", False, False, "packed",
                                     256, tel_mode="full")

    def test_off_traces_no_extra_variants(self):
        """jit-budget proof of the zero-cost off state: a tel-off
        dataplane serving plain + packed traffic compiles exactly the
        two variants it always compiled — telemetry added nothing.
        (Unique sess geometry so this test owns its cache keys.)"""
        from vpp_tpu.pipeline.dataplane import jit_compile_budget

        dp = Dataplane(small_cfg(telemetry="off", sess_slots=32))
        up = dp.add_uplink()
        pod = dp.add_pod_interface(("d", "q"))
        dp.builder.add_route("10.1.1.0/24", pod, Disposition.LOCAL)
        dp.swap()
        with jit_compile_budget(2):
            dp.process(make_packet_vector(
                [dict(src="10.9.0.1", dst="10.1.1.2", proto=6,
                      sport=1, dport=80, rx_if=up)], n=8), now=1)
            dp.process_packed(packed_frame(8, up, sport=2), now=2)
        # placeholder planes only, nothing accumulated
        assert dp.telemetry_snapshot() is None
        assert dp.tables.tel_lat_hist.shape == (1,)
        assert dp.tables.tel_sketch.shape == (1, 1)


# --------------------------------------------------------------------
# aux rider width evolution (satellite): one schema constant, three
# dispatch forms
# --------------------------------------------------------------------


class TestAuxSchema:
    def test_schema_is_the_single_width_authority(self):
        assert PACKED_AUX_ROWS == len(PACKED_AUX_SCHEMA)
        assert PACKED_AUX_SCHEMA[:3] == ("fastpath", "rx", "sess_hits")
        # history: the 5-row and 8-row prefixes are frozen — widening
        # appends, it never reorders (readers index by name, but the
        # device packs positionally)
        assert PACKED_AUX_SCHEMA[3:8] == (
            "insert_fails", "evictions",
            "ml_scored", "ml_flagged", "ml_drops")

    def test_all_three_dispatch_forms_match_schema_width(self):
        """Table-driven: packed, chained and ring aux layouts all
        derive from PACKED_AUX_SCHEMA — one widening, three forms."""
        from vpp_tpu.pipeline.persistent import PersistentPump

        B = 16
        dp, up = build_dp("latency", sess_slots=128)
        rows = {}
        _out, aux = dp.process_packed(packed_frame(B, up, sport=100),
                                      now=1, with_aux=True,
                                      stamp_us=5, now_us=10)
        rows["packed"] = np.asarray(aux).shape
        flats = np.stack([packed_frame(B, up, sport=200 + i)
                          for i in range(2)])
        _outs, auxs = dp.process_packed_chain(
            flats, now=2, with_aux=True,
            stamps_us=np.asarray([5, 5], np.int32))
        rows["chain"] = np.asarray(auxs).shape[1:]
        pump = PersistentPump(
            dp.tables, batch=B, fastpath=dp._use_fastpath,
            classifier=dp._classifier_impl,
            skip_local=dp._skip_local, ring_slots=2, ring_windows=2,
            tel_mode="latency").start()
        try:
            pump.submit(packed_frame(B, up, sport=300), now=3,
                        stamp_us=7)
            _o, ring_aux = pump.result_ex(timeout=180)
        finally:
            pump.stop()
        rows["ring"] = np.asarray(ring_aux).shape
        for form, shape in rows.items():
            assert shape == (len(PACKED_AUX_SCHEMA),), (form, shape)

    def test_aux_parity_lint_is_clean_and_catches_gaps(self):
        import importlib.util
        from pathlib import Path

        lint_path = Path(__file__).resolve().parent.parent / "tools" \
            / "lint.py"
        spec = importlib.util.spec_from_file_location("tl", lint_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.counters_lint() == []


# --------------------------------------------------------------------
# exposition: the native histogram + info gauges over real HTTP
# --------------------------------------------------------------------


class TestExposition:
    def test_wire_latency_family_scrape_conformance(self):
        from vpp_tpu.stats import StatsHTTPServer
        from vpp_tpu.stats.collector import STATS_PATH, StatsCollector

        dp, up = build_dp("full")
        coll = StatsCollector(dp)
        res = dp.process(make_packet_vector(
            [dict(src="10.9.0.1", dst="10.1.1.2", proto=6,
                  sport=1, dport=80, rx_if=up)]), now=1)
        coll.update(res.stats)
        dp.process_packed(packed_frame(16, up, sport=50), now=2,
                          stamp_us=100, now_us=1000)
        coll.publish()
        server = StatsHTTPServer(coll.registry, port=0)
        server.start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}{STATS_PATH}",
                timeout=10).read().decode()
        finally:
            server.close()
        types, samples = validate_body(body)
        assert types.get("vpp_tpu_wire_latency_seconds") == "histogram"
        by_name = {}
        for name, labels, value in samples:
            by_name.setdefault(name, []).append((labels, value))
        # the device bins made it out: count == 16 observed packets
        counts = by_name.get("vpp_tpu_wire_latency_seconds_count")
        assert counts and counts[0][1] == 16.0
        # derived quantile gauges: 900 µs lands in [512, 1024)
        p99 = by_name["vpp_tpu_wire_latency_p99_us"][0][1]
        assert 512.0 <= p99 <= 1024.0
        # flow-sketch families + mode gauge + build info
        assert by_name["vpp_tpu_flow_sketch_packets"][0][1] == 1.0
        modes = {l["mode"]: v for l, v in by_name["vpp_tpu_telemetry"]}
        assert modes == {"off": 0.0, "latency": 0.0, "full": 1.0}
        ranks = {l["rank"] for l, _v in
                 by_name["vpp_tpu_flow_sketch_top_count"]}
        assert len(ranks) == tel_capacity(dp.config)[3]
        info = by_name["vpp_tpu_build_info"]
        assert len(info) == 1 and info[0][1] == 1.0
        labels = info[0][0]
        assert set(labels) == {"version", "jax", "backend",
                               "classifier"}
        assert labels["backend"] and labels["version"]

    def test_cli_pages_render_from_host_state(self):
        from vpp_tpu.cli import DebugCLI

        dp, up = build_dp("full")
        dp.process_packed(packed_frame(16, up, sport=60), now=1,
                          stamp_us=100, now_us=700)
        dp.process(make_packet_vector(
            [dict(src="10.9.0.2", dst="10.1.1.3", proto=17,
                  sport=9999, dport=53, rx_if=up)]), now=2)
        cli = DebugCLI(dp)
        lat = cli.run("show latency")
        assert "16 packets" in lat and "p99" in lat
        top = cli.run("show top-flows")
        assert "10.9.0.2:9999 -> 10.1.1.3:53" in top
        # off-state messages
        dp_off = Dataplane(small_cfg())
        cli_off = DebugCLI(dp_off)
        assert "telemetry off" in cli_off.run("show latency")
        assert "flow sketch off" in cli_off.run("show top-flows")


# --------------------------------------------------------------------
# PacketTracer satellite: ml-score node + ml-drop leaf
# --------------------------------------------------------------------


class TestTracerMlNodes:
    def _ml_dp(self):
        from vpp_tpu.ir.rule import Action, ContivRule, Protocol
        from vpp_tpu.ml.model import MlModel
        from vpp_tpu.ops.mlscore import ML_FEATURES

        dp = Dataplane(small_cfg(ml_stage="enforce"))
        up = dp.add_uplink()
        pod = dp.add_pod_interface(("d", "p"))
        dp.builder.add_route("10.1.1.0/24", pod, Disposition.LOCAL)
        dp.builder.set_global_table([
            ContivRule(action=Action.PERMIT, protocol=Protocol.ANY)])
        # score == the proto byte (the test_ml_stage proto model):
        # flag_thresh 10 drops UDP (17), passes TCP (6)
        w1 = np.zeros((ML_FEATURES, 4), np.int8)
        w1[12, 0] = 1
        dp.builder.set_ml_model(MlModel(
            kind="mlp", version=1, n_features=ML_FEATURES,
            w1=w1, b1=np.zeros(4, np.int32), s1=0,
            w2=np.array([1, 0, 0, 0], np.int8), b2=0,
            flag_thresh=10, action="drop"))
        dp.swap()
        assert dp._ml_mode == "enforce"
        return dp, up

    def test_trace_renders_ml_score_and_ml_drop(self):
        from vpp_tpu.trace.tracer import PacketTracer

        dp, up = self._ml_dp()
        tracer = PacketTracer()
        dp.tracer = tracer
        tracer.add(4)
        dp.process(make_packet_vector([
            dict(src="10.9.0.1", dst="10.1.1.2", proto=17,
                 sport=53, dport=9002, rx_if=up),      # UDP: ml-drop
            dict(src="10.9.0.1", dst="10.1.1.2", proto=6,
                 sport=444, dport=80, rx_if=up),       # TCP: forwards
        ]), now=3)
        entries = tracer.entries()
        assert len(entries) == 2
        udp, tcp = entries
        assert "ml-score (score 17, flagged)" in udp.path
        assert "error-drop (ml-drop)" in udp.path
        assert udp.drop_cause == "ml-drop"
        assert "ml-score (score 6)" in tcp.path
        assert "error-drop (ml-drop)" not in tcp.path
        # sample-output shape of docs/PACKET_TRACING.md
        txt = udp.format()
        assert "ml-score" in txt and "error-drop (ml-drop)" in txt

    def test_trace_without_ml_stage_unchanged(self):
        from vpp_tpu.trace.tracer import PacketTracer

        dp, up = build_dp("off")
        tracer = PacketTracer()
        dp.tracer = tracer
        tracer.add(1)
        dp.process(make_packet_vector(
            [dict(src="10.9.0.1", dst="10.1.1.2", proto=6,
                  sport=1, dport=80, rx_if=up)]), now=1)
        (entry,) = tracer.entries()
        assert not any("ml-score" in n for n in entry.path)
