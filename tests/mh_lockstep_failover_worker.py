"""Worker for the lockstep-across-store-failover scenario (run directly).

Two JAX processes lockstep-ticking against the FENCED HA store trio
(witness + primary + standby). Mid-run the parent SIGKILLs the
primary; the workers' clients fail over (reads keep working on the
follower, writes resume once the witness grants the claim), and the
control loop proves itself post-failover: P1 stages a deny-all and
requests a commit through the NEW primary — both processes publish the
epoch on the same tick and traffic is cut cluster-wide, exactly as
with the original primary.

argv: pid nprocs coord_port store_url
"""

import json
import os
import sys
import time

PROC_ID = int(sys.argv[1])
NUM_PROCS = int(sys.argv[2])
PORT = sys.argv[3]
STORE_URL = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from vpp_tpu.parallel.multihost import (  # noqa: E402
    LockstepDriver, MultiHostCluster, barrier, init_multihost,
)
from mh_common import (  # noqa: E402
    LOCKSTEP_N_NODES, lockstep_config, lockstep_deliveries,
    lockstep_frames, pod_ips, stage_full_mesh,
)
from vpp_tpu.ir.rule import Action, ContivRule  # noqa: E402
from vpp_tpu.kvstore.client import connect_store  # noqa: E402

init_multihost(f"127.0.0.1:{PORT}", NUM_PROCS, PROC_ID,
               heartbeat_timeout_s=600)

N_NODES = LOCKSTEP_N_NODES
cluster = MultiHostCluster(N_NODES, lockstep_config())
# generous timeouts: a get/put issued INSIDE the failover window must
# ride the endpoint rotation + witness-arbitrated promotion (~fence
# ttl) within one call instead of surfacing a transient error
store = connect_store(STORE_URL, request_timeout=90.0,
                      reconnect_timeout=90.0)
driver = LockstepDriver(cluster, store, expire_every=3)

pod_if = stage_full_mesh(cluster)

barrier("staged")
cluster.publish()

all_pod_ip = pod_ips(N_NODES)


def frames_for_tick(sport):
    return lockstep_frames(cluster, PROC_ID, all_pod_ip, pod_if, sport)


def deliveries(res):
    return lockstep_deliveries(cluster, PROC_ID, res)


verdict = {"proc": PROC_ID}

res = driver.tick(frames_for_tick(1000), n=8)
verdict["t1_delivered"] = deliveries(res)

# signal the parent we're mid-run, then wait out the failover it
# injects. Reads work on the follower throughout; no collectives here,
# so the two processes may resume at different instants — the barrier
# below resynchronizes the fleet before ticking resumes.
store.put(f"mhf/ready/{PROC_ID}", 1)
deadline = time.monotonic() + 180
while time.monotonic() < deadline:
    try:
        if store.get("mhf/go") == 1:
            break
    except Exception:  # noqa: BLE001 — mid-failover transient
        pass
    time.sleep(0.5)
else:
    raise SystemExit("parent never signalled go")
barrier("failover-done")

# the cluster keeps forwarding on the failed-over store
res = driver.tick(frames_for_tick(1001), n=8)
verdict["t2_delivered"] = deliveries(res)

# and the control loop works against the NEW primary: stage + commit
if PROC_ID == 1:
    cluster.node(2).builder.set_global_table(
        [ContivRule(action=Action.DENY)])
    driver.request_commit()
barrier("change-requested")

res = driver.tick(frames_for_tick(1002), n=8)
verdict["t3_delivered"] = deliveries(res)
verdict["t3_epoch"] = cluster.epoch
verdict["applied"] = driver.applied

# the client's fencing epoch refreshes lazily on its first WRITE
# against the new primary (a stale stamp is rejected and retried with
# the refreshed epoch) — write once so the recorded value proves this
# worker's writes now ride the post-failover history
store.put(f"mhf/done/{PROC_ID}", 1)
verdict["fence_epoch"] = store.fencing_epoch

barrier("done")
print("VERDICT " + json.dumps(verdict), flush=True)
