"""Packet-IO front-end: real wire frames through the full data plane.

VERDICT r1 Missing #1 / Next #2: nothing could receive a packet. These
tests drive actual ethernet frames through Transport -> native codec ->
rx ring -> DataplanePump -> jitted pipeline -> tx ring -> native rewrite
-> Transport, asserting forwarding, policy drops, NAT rewrites with
valid checksums, VXLAN encap toward peers, and non-IP punt.

Reference analog: VPP's af-packet-input .. interface-output chain
(docs/VPP_PACKET_TRACING_K8S.md:28-50) exercised by the robot suites'
pod-to-pod UDP/TCP cases (tests/robot/suites/two_node_two_pods.robot).
"""

from __future__ import annotations

import ipaddress
import socket
import struct
import time

import numpy as np
import pytest

from wire import ip_checksum_ok, make_frame

from vpp_tpu.io import DataplanePump, IODaemon, IORingPair, SocketPairTransport
from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.pipeline.dataplane import Dataplane, packed_input_zeros
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, ip4

CLIENT_IP = "10.1.1.2"
SERVER_IP = "10.1.1.3"
REMOTE_POD = "10.1.2.5"
GW_IP = "10.1.1.1"
VTEP_SELF = "192.168.10.1"
VTEP_PEER = "192.168.10.2"


class IoHarness:
    """One-node data plane with pod/uplink/host socketpair transports."""

    def __init__(self):
        self.dp = Dataplane(DataplaneConfig())
        dp = self.dp
        self.uplink_if = dp.add_uplink()
        self.host_if = dp.add_host_interface()
        self.client_if = dp.add_pod_interface(("default", "client"))
        self.server_if = dp.add_pod_interface(("default", "server"))
        dp.builder.add_route(f"{CLIENT_IP}/32", self.client_if,
                             Disposition.LOCAL)
        dp.builder.add_route(f"{SERVER_IP}/32", self.server_if,
                             Disposition.LOCAL)
        dp.builder.add_route("10.1.2.0/24", self.uplink_if,
                             Disposition.REMOTE, node_id=2,
                             next_hop=ip4(VTEP_PEER))
        dp.set_vtep(ip4(VTEP_SELF))
        # policy on server: allow UDP:80 from anywhere, deny rest
        slot = dp.alloc_table_slot("t-server")
        dp.builder.set_local_table(slot, [
            ContivRule(action=Action.PERMIT,
                       dest_network=ipaddress.ip_network(f"{SERVER_IP}/32"),
                       protocol=Protocol.UDP, dest_port=80),
            ContivRule(action=Action.PERMIT,
                       dest_network=ipaddress.ip_network("10.1.2.0/24"),
                       protocol=Protocol.UDP, dest_port=80),
            ContivRule(action=Action.DENY),
        ])
        dp.assign_pod_table(("default", "client"), "t-server")
        dp.builder.set_global_table(
            [ContivRule(action=Action.PERMIT, protocol=Protocol.ANY)]
        )
        dp.swap()

        # compile the pipeline step before any wire traffic so recv
        # timeouts measure the data path, not the first jit trace (the
        # pump's hot path is the packed single-transfer step)
        from vpp_tpu.pipeline.vector import make_packet_vector

        self.dp.process(make_packet_vector([]))
        self.dp.process_packed(packed_input_zeros(256))

        self.rings = IORingPair(n_slots=8)
        self.transports = {}
        self.outside = {}
        for if_idx, name in ((self.client_if, "client"),
                             (self.server_if, "server"),
                             (self.uplink_if, "uplink"),
                             (self.host_if, "host")):
            inside, outside = SocketPairTransport.pair(name)
            self.transports[if_idx] = inside
            self.outside[name] = outside
        self.daemon = IODaemon(
            self.rings, self.transports, uplink_if=self.uplink_if,
            host_if=self.host_if, vtep_ip=ip4(VTEP_SELF),
        ).start()
        self.pump = DataplanePump(self.dp, self.rings,
                                  icmp_src_ip=ip4(GW_IP)).start()

    def send(self, name: str, frame: bytes) -> None:
        self.outside[name].send_frame(frame)

    def recv(self, name: str, timeout: float = 5.0) -> bytes:
        sock = self.outside[name].sock
        sock.setblocking(True)
        sock.settimeout(timeout)
        try:
            return sock.recv(65535)
        finally:
            sock.setblocking(False)

    def close(self):
        self.pump.stop()
        self.daemon.stop()
        for t in self.transports.values():
            t.close()
        for t in self.outside.values():
            t.close()
        self.rings.close()


@pytest.fixture(scope="module")
def harness():
    h = IoHarness()
    yield h
    h.close()


class TestWireToWire:
    def test_permitted_udp_forwarded_to_server(self, harness):
        frame = make_frame(CLIENT_IP, SERVER_IP, proto=17, dport=80)
        harness.send("client", frame)
        out = harness.recv("server")
        # same packet, TTL decremented, checksums valid
        assert out[14 + 12:14 + 16] == ipaddress.ip_address(CLIENT_IP).packed
        assert out[14 + 16:14 + 20] == ipaddress.ip_address(SERVER_IP).packed
        assert out[22] == 63  # ttl 64 -> 63
        assert ip_checksum_ok(out[14:34])
        assert out[34 + 8:] == frame[34 + 8:]  # payload untouched

    def test_denied_udp_dropped(self, harness):
        before = harness.daemon.stats["tx_drops"]
        frame = make_frame(CLIENT_IP, SERVER_IP, proto=17, dport=9999)
        harness.send("client", frame)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if harness.daemon.stats["tx_drops"] > before:
                break
            time.sleep(0.01)
        assert harness.daemon.stats["tx_drops"] > before
        # nothing must reach the server
        with pytest.raises((socket.timeout, TimeoutError)):
            harness.recv("server", timeout=0.3)

    def test_remote_pod_vxlan_encapped_to_peer(self, harness):
        frame = make_frame(CLIENT_IP, REMOTE_POD, proto=17, dport=80)
        harness.send("client", frame)
        wire = harness.recv("uplink")
        # outer IPv4/UDP/VXLAN toward the peer VTEP
        assert wire[12:14] == b"\x08\x00"
        assert wire[14 + 16:14 + 20] == ipaddress.ip_address(VTEP_PEER).packed
        assert ip_checksum_ok(wire[14:34])
        udp_dport = struct.unpack("!H", wire[36:38])[0]
        assert udp_dport == 4789
        inner = wire[14 + 20 + 8 + 8:]
        assert inner[14 + 16:14 + 20] == \
            ipaddress.ip_address(REMOTE_POD).packed
        assert inner[22] == 63

    def test_armed_tracer_captures_pump_traffic(self, harness):
        """The pump's tracing slow path (dispatch via the unpacked step
        so the tracer sees a full StepResult) must still forward the
        frame AND capture a trace entry — regression for the packed
        [5,B] boundary breaking the slow branch's column decode."""
        from vpp_tpu.trace.tracer import PacketTracer

        tracer = PacketTracer()
        harness.dp.tracer = tracer
        tracer.add(4)
        try:
            frame = make_frame(CLIENT_IP, SERVER_IP, proto=17, dport=80)
            harness.send("client", frame)
            out = harness.recv("server")
            assert out[14 + 16:14 + 20] == \
                ipaddress.ip_address(SERVER_IP).packed
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and not tracer.entries():
                time.sleep(0.01)
            entries = tracer.entries()
            assert entries, "armed tracer captured nothing from the pump"
            assert any(e.dst == SERVER_IP for e in entries)
        finally:
            harness.dp.tracer = None

    def test_non_ip_frame_punted_to_host(self, harness):
        arp = b"\xff" * 6 + b"\x02\x00\x00\x00\x00\x01" + b"\x08\x06" \
            + b"\x00" * 28
        harness.send("client", arp)
        out = harness.recv("host")
        assert out == arp

    def test_vxlan_from_peer_decapped_and_delivered(self, harness):
        """A frame arriving VXLAN-encapped on the uplink (from a peer
        node) is decapped and forwarded by inner dst."""
        from vpp_tpu.native.pktio import PacketCodec

        inner = make_frame(REMOTE_POD, SERVER_IP, proto=17, dport=80)
        codec = PacketCodec()
        arr = np.frombuffer(inner, np.uint8)
        wire = codec.encap(
            np.ascontiguousarray(arr), len(inner), ip4(VTEP_PEER),
            ip4(VTEP_SELF), 50000, 10,
            b"\x02\x00\x00\x00\x00\x09", b"\x02\x00\x00\x00\x00\x08",
        )
        harness.send("uplink", wire)
        out = harness.recv("server")
        assert out[14 + 12:14 + 16] == \
            ipaddress.ip_address(REMOTE_POD).packed
        assert out[14 + 16:14 + 20] == \
            ipaddress.ip_address(SERVER_IP).packed

    def test_stats_account_traffic(self, harness):
        s = harness.daemon.stats
        # counters are incremented by the daemon tx thread AFTER
        # send_frame; the previous test's recv() can beat that by a few
        # instructions, so give the counters a moment to settle
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline and s["tx_pkts"] < 3:
            time.sleep(0.01)
        assert s["rx_frames"] >= 4
        assert s["tx_pkts"] >= 3
        assert s["vxlan_encap"] >= 1
        assert s["vxlan_decap"] >= 1
        assert harness.pump.stats["frames"] >= 4


class TestPipelinedPump:
    """The pump keeps frames in flight and coalesces under backlog
    (VERDICT r2 Next #2) — results must still come out per-frame, in
    order, with the right per-packet verdicts."""

    def test_backlog_coalesced_in_order(self):
        from vpp_tpu.io.rings import IORingPair
        from vpp_tpu.native.pktio import PacketCodec
        from vpp_tpu.pipeline.vector import VEC

        dp = Dataplane(DataplaneConfig())
        a = dp.add_pod_interface(("default", "a"))
        b = dp.add_pod_interface(("default", "b"))
        dp.builder.add_route(f"{CLIENT_IP}/32", a, Disposition.LOCAL)
        dp.builder.add_route(f"{SERVER_IP}/32", b, Disposition.LOCAL)
        dp.swap()
        codec = PacketCodec()
        rings = IORingPair(n_slots=32)
        scratch = np.zeros((VEC, rings.rx.snap), np.uint8)

        # fill the rx ring with 16 frames BEFORE starting the pump: the
        # first dispatch must coalesce several of them into one batch
        n_frames, per = 16, 8
        for k in range(n_frames):
            frames = [
                make_frame(CLIENT_IP, SERVER_IP, proto=17, sport=20000 + k,
                           dport=1000 + k * per + j)
                for j in range(per)
            ]
            cols, n = codec.parse(frames, a, scratch)
            assert rings.rx.push(cols, n, payload=scratch)
        pump = DataplanePump(dp, rings, max_batch=2048).start()
        try:
            got = []
            deadline = time.monotonic() + 120
            while len(got) < n_frames and time.monotonic() < deadline:
                f = rings.tx.peek()
                if f is None:
                    time.sleep(0.005)
                    continue
                got.append((f.cols["sport"][:f.n].copy(),
                            f.cols["dport"][:f.n].copy(),
                            f.cols["rx_if"][:f.n].copy(),
                            f.n))
                rings.tx.release()
            assert len(got) == n_frames
            for k, (sports, dports, tx_ifs, n) in enumerate(got):
                assert n == per
                # order preserved: frame k carries sport 20000+k
                assert (sports == 20000 + k).all()
                assert list(dports) == [1000 + k * per + j
                                        for j in range(per)]
                assert (tx_ifs == b).all()
            assert pump.stats["frames"] == n_frames
            assert pump.stats["pkts"] == n_frames * per
            # backlog must have produced at least one multi-frame batch
            assert pump.stats["max_coalesce"] > 1
            assert pump.stats["batches"] < n_frames
            lat = pump.latency_us()
            assert lat["n"] == pump.stats["batches"] and lat["p99"] > 0
        finally:
            pump.stop()
            rings.close()


class TestPersistentPumpMode:
    """mode="persistent": the pump feeds ONE resident device program
    (pipeline/persistent.py) instead of per-batch dispatches — the
    deployed form of docs/LATENCY.md lever #2 (VERDICT r4 Next #2).
    Same ring contract, same in-order per-frame results; config swaps
    restart the loop without losing traffic or session state."""

    def _mk(self):
        from vpp_tpu.io.rings import IORingPair

        dp = Dataplane(DataplaneConfig())
        a = dp.add_pod_interface(("default", "a"))
        b = dp.add_pod_interface(("default", "b"))
        dp.builder.add_route(f"{CLIENT_IP}/32", a, Disposition.LOCAL)
        dp.builder.add_route(f"{SERVER_IP}/32", b, Disposition.LOCAL)
        dp.swap()
        return dp, a, b, IORingPair(n_slots=32)

    def _push(self, rings, codec, scratch, rx_if, k, per=4):
        from vpp_tpu.native.pktio import PacketCodec  # noqa: F401

        frames = [
            make_frame(CLIENT_IP, SERVER_IP, proto=6, sport=30000 + k,
                       dport=2000 + k * per + j)
            for j in range(per)
        ]
        cols, n = codec.parse(frames, rx_if, scratch)
        assert rings.rx.push(cols, n, payload=scratch)
        return per

    def _drain(self, rings, want, timeout=240):
        got = []
        deadline = time.monotonic() + timeout
        while len(got) < want and time.monotonic() < deadline:
            f = rings.tx.peek()
            if f is None:
                time.sleep(0.005)
                continue
            got.append((f.cols["sport"][:f.n].copy(),
                        f.cols["rx_if"][:f.n].copy(), f.n))
            rings.tx.release()
        return got

    def test_resident_loop_serves_frames_in_order(self):
        from vpp_tpu.native.pktio import PacketCodec
        from vpp_tpu.pipeline.vector import VEC

        dp, a, b, rings = self._mk()
        codec = PacketCodec()
        scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        pump = DataplanePump(dp, rings, mode="persistent")
        assert pump.warm() == [VEC]  # loop resident + hot
        pump.start()
        try:
            n_frames, per = 6, 4
            for k in range(n_frames):
                self._push(rings, codec, scratch, a, k, per)
            got = self._drain(rings, n_frames)
            assert len(got) == n_frames
            for k, (sports, tx_ifs, n) in enumerate(got):
                assert n == per
                assert (sports == 30000 + k).all()  # submission order
                assert (tx_ifs == b).all()
            assert pump.stats["frames"] == n_frames
            # the device-ring pump COMPACTS small frames into shared
            # VEC-packet descriptor slots (ISSUE 7 header compaction),
            # so batches counts coalesce groups, not frames — and the
            # steady state made zero host callbacks
            assert 1 <= pump.stats["batches"] <= n_frames
            assert pump.stats["io_callbacks"] == 0
            assert pump.stats["ring_windows"] >= 1
        finally:
            assert pump.stop()
            rings.close()
        # the loop's session state was grafted back at shutdown: the
        # permitted TCP flows live in the dataplane's tables now
        assert int(np.asarray(dp.tables.sess_valid).sum()) > 0

    def test_config_swap_restarts_loop_without_losing_traffic(self):
        from vpp_tpu.native.pktio import PacketCodec
        from vpp_tpu.pipeline.vector import VEC

        dp, a, b, rings = self._mk()
        codec = PacketCodec()
        scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        pump = DataplanePump(dp, rings, mode="persistent")
        pump.warm()
        pump.start()
        try:
            self._push(rings, codec, scratch, a, 0)
            assert len(self._drain(rings, 1)) == 1
            epoch0 = pump._persist_epoch
            # live config change: a new route -> dp.swap bumps the
            # epoch; the pump must restart the resident loop and keep
            # serving (the reference's non-stalling renderer Commit)
            dp.builder.add_route("10.9.9.9/32", b, Disposition.LOCAL)
            dp.swap()
            self._push(rings, codec, scratch, a, 1)
            got = self._drain(rings, 1)
            assert len(got) == 1 and (got[0][0] == 30001).all()
            assert pump._persist_epoch > epoch0  # loop was relaunched
        finally:
            assert pump.stop()
            rings.close()


class TestCodecSafety:
    """Adversarial wire input must never leak slot memory or over-read."""

    def test_lying_ip_length_marks_trunc_and_never_transmits(self):
        from vpp_tpu.native.pktio import FLAG_TRUNC, PacketCodec

        codec = PacketCodec()
        payload = np.full((256, 2048), 0xAB, np.uint8)  # poisoned slot
        frame = bytearray(make_frame(CLIENT_IP, SERVER_IP, proto=17,
                                     dport=80))
        # claim 1500 bytes in the IPv4 total-length field of a ~74B frame
        frame[16:18] = (1500).to_bytes(2, "big")
        cols, n = codec.parse([bytes(frame)], 0, payload)
        assert cols["flags"][0] & FLAG_TRUNC
        # pkt_len clamped to captured bytes: nothing can read into the
        # poisoned residue
        assert int(cols["pkt_len"][0]) <= len(frame) - 14

    def test_oversnap_frame_marked_trunc(self):
        from vpp_tpu.native.pktio import FLAG_TRUNC, PacketCodec

        codec = PacketCodec(snap=256)
        payload = np.zeros((256, 256), np.uint8)
        big = make_frame(CLIENT_IP, SERVER_IP, proto=17, dport=80,
                         payload=b"z" * 900)
        cols, n = codec.parse([big], 0, payload)
        assert cols["flags"][0] & FLAG_TRUNC

    def test_crafted_ihl_decap_no_overread(self):
        from vpp_tpu.native.pktio import PacketCodec

        codec = PacketCodec()
        # 64-byte frame claiming IHL=15 (60-byte IP header), proto UDP:
        # the UDP header would sit past the end of the buffer
        frame = bytearray(64)
        frame[12:14] = b"\x08\x00"
        frame[14] = 0x4F          # v4, ihl=15
        frame[14 + 9] = 17        # udp
        assert codec.decap_offset(bytes(frame), 10) == 0
        # IHL<20 and non-v4 likewise rejected
        frame[14] = 0x43
        assert codec.decap_offset(bytes(frame), 10) == 0
        frame[14] = 0x65
        assert codec.decap_offset(bytes(frame), 10) == 0

    def test_decap_requires_flag_and_vni_match(self):
        from vpp_tpu.native.pktio import PacketCodec

        codec = PacketCodec()
        inner = make_frame(CLIENT_IP, SERVER_IP, proto=17, dport=80)
        arr = np.frombuffer(inner, np.uint8)
        wire = bytearray(codec.encap(
            arr, len(arr), 0x0A000001, 0x0A000002, 49152, 10,
            b"\x02" * 6, b"\x04" * 6,
        ))
        off = codec.decap_offset(bytes(wire), 10)
        assert off and bytes(wire[off:off + len(inner)]) == inner
        # wrong segment: a frame from VNI 11 must not be injected
        assert codec.decap_offset(bytes(wire), 11) == 0
        # I-flag clear (no VNI present): reject even if port matches
        ihl = (wire[14] & 0x0F) * 4
        wire[14 + ihl + 8] = 0x00
        assert codec.decap_offset(bytes(wire), 10) == 0

    def test_runt_frame_marked_trunc(self):
        from vpp_tpu.native.pktio import FLAG_TRUNC, PacketCodec

        codec = PacketCodec()
        payload = np.full((256, 2048), 0xAB, np.uint8)  # poisoned slots
        cols, n = codec.parse([b"\x02\x04\x06"], 0, payload)
        assert n == 1
        # a 3-byte runt must never reach tx: wire_len would include
        # residual bytes from the slot's previous occupant
        assert cols["flags"][0] & FLAG_TRUNC


def _can_netadmin() -> bool:
    import subprocess

    try:
        r = subprocess.run(
            ["ip", "link", "add", "vpptselfck0", "type", "veth",
             "peer", "name", "vpptselfck1"],
            capture_output=True, timeout=10,
        )
        if r.returncode == 0:
            subprocess.run(["ip", "link", "del", "vpptselfck0"],
                           capture_output=True, timeout=10)
            return True
    except Exception:
        pass
    return False


@pytest.mark.skipif(not _can_netadmin(), reason="needs CAP_NET_ADMIN (veth)")
class TestVethAfPacket:
    """Kernel-interface e2e: real veth devices + AF_PACKET transports —
    the closest analog to the reference's af_packet pod wiring
    (plugins/contiv/pod.go:262-360) this environment can host."""

    def test_udp_through_kernel_interfaces(self):
        import subprocess

        from vpp_tpu.io.transport import AfPacketTransport, ETH_P_ALL
        from vpp_tpu.pipeline.vector import make_packet_vector

        links = [("vppc0", "vppc1"), ("vpps0", "vpps1")]
        for a, b in links:
            subprocess.run(["ip", "link", "del", a], capture_output=True)
            subprocess.run(
                ["ip", "link", "add", a, "type", "veth", "peer", "name", b],
                check=True, capture_output=True,
            )
            for dev in (a, b):
                subprocess.run(["ip", "link", "set", dev, "up"],
                               check=True, capture_output=True)
        try:
            dp = Dataplane(DataplaneConfig())
            dp.add_uplink()
            client_if = dp.add_pod_interface(("default", "vc"))
            server_if = dp.add_pod_interface(("default", "vs"))
            dp.builder.add_route(f"{CLIENT_IP}/32", client_if,
                                 Disposition.LOCAL)
            dp.builder.add_route(f"{SERVER_IP}/32", server_if,
                                 Disposition.LOCAL)
            dp.builder.set_global_table(
                [ContivRule(action=Action.PERMIT, protocol=Protocol.ANY)]
            )
            dp.set_vtep(ip4(VTEP_SELF))
            dp.swap()
            dp.process(make_packet_vector([]))

            rings = IORingPair(n_slots=8)
            transports = {
                client_if: AfPacketTransport("vppc0"),
                server_if: AfPacketTransport("vpps0"),
            }
            daemon = IODaemon(rings, transports, uplink_if=-1).start()
            pump = DataplanePump(dp, rings).start()

            import socket as socket_mod

            send_sock = socket_mod.socket(
                socket_mod.AF_PACKET, socket_mod.SOCK_RAW,
                socket_mod.htons(ETH_P_ALL),
            )
            send_sock.bind(("vppc1", 0))
            recv_sock = socket_mod.socket(
                socket_mod.AF_PACKET, socket_mod.SOCK_RAW,
                socket_mod.htons(ETH_P_ALL),
            )
            recv_sock.bind(("vpps1", 0))
            recv_sock.settimeout(1.0)
            try:
                frame = make_frame(CLIENT_IP, SERVER_IP, proto=17, dport=80,
                                   payload=b"veth-e2e")
                out = None
                deadline = time.monotonic() + 15
                while time.monotonic() < deadline:
                    send_sock.send(frame)
                    try:
                        cand = recv_sock.recv(65535)
                    except (socket_mod.timeout, TimeoutError):
                        continue
                    # ignore kernel noise (IPv6 RS, LLDP...)
                    if len(cand) > 34 and cand[12:14] == b"\x08\x00" \
                            and cand[14 + 16:14 + 20] == \
                            ipaddress.ip_address(SERVER_IP).packed:
                        out = cand
                        break
                assert out is not None, "UDP packet never crossed the veths"
                assert out[22] == 63  # TTL decremented by the pipeline
                assert ip_checksum_ok(out[14:34])
                assert out.endswith(b"veth-e2e")
            finally:
                send_sock.close()
                recv_sock.close()
                pump.stop()
                daemon.stop()
                for t in transports.values():
                    t.close()
                rings.close()
        finally:
            for a, _ in links:
                subprocess.run(["ip", "link", "del", a],
                               capture_output=True)


class TestCrossProcessDaemon:
    def test_io_daemon_subprocess_over_shm(self):
        """The production split: vpp-tpu-io runs as its own process,
        attached to the agent's shared-memory rings, owning the packet
        endpoints (inherited fds standing in for AF_PACKET sockets)."""
        import os
        import subprocess
        import sys

        from vpp_tpu.pipeline.vector import make_packet_vector

        dp = Dataplane(DataplaneConfig())
        uplink_if = dp.add_uplink()
        client_if = dp.add_pod_interface(("default", "c"))
        server_if = dp.add_pod_interface(("default", "s"))
        dp.builder.add_route(f"{CLIENT_IP}/32", client_if, Disposition.LOCAL)
        dp.builder.add_route(f"{SERVER_IP}/32", server_if, Disposition.LOCAL)
        dp.set_vtep(ip4(VTEP_SELF))
        from vpp_tpu.ir.rule import Protocol as P

        dp.builder.set_global_table(
            [ContivRule(action=Action.PERMIT, protocol=P.ANY)]
        )
        dp.swap()
        dp.process(make_packet_vector([]))  # pre-compile

        shm_name = f"vpp_tpu_io_test_{os.getpid()}"
        rings = IORingPair(n_slots=8, shm_name=shm_name, create=True)
        pump = DataplanePump(dp, rings).start()

        pairs = {name: SocketPairTransport.pair(name)
                 for name in ("client", "server", "uplink")}
        if_of = {"client": client_if, "server": server_if,
                 "uplink": uplink_if}
        fds = [p[0].fileno() for p in pairs.values()]
        cmd = [
            sys.executable, "-m", "vpp_tpu.cmd.io_daemon",
            "--shm", shm_name, "--slots", "8",
            "--uplink", str(uplink_if), "--vtep", str(ip4(VTEP_SELF)),
        ]
        for name, (inside, _) in pairs.items():
            cmd += ["--if", f"{if_of[name]}:fd:{inside.fileno()}"]
        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(cmd, pass_fds=fds, env=env)
        try:
            frame = make_frame(CLIENT_IP, SERVER_IP, proto=6, dport=80)
            out = None
            # generous: covers the daemon subprocess's interpreter boot
            # on a loaded single-core host (observed >20 s under the
            # race harness with a concurrent suite)
            deadline = time.monotonic() + 60
            srv_sock = pairs["server"][1].sock
            srv_sock.setblocking(True)
            srv_sock.settimeout(1.0)
            while time.monotonic() < deadline:
                pairs["client"][1].send_frame(frame)
                try:
                    out = srv_sock.recv(65535)
                    break
                except (socket.timeout, TimeoutError):
                    continue
            assert out is not None, "no frame crossed the process boundary"
            assert out[14 + 16:14 + 20] == \
                ipaddress.ip_address(SERVER_IP).packed
            assert out[22] == 63
        finally:
            proc.terminate()
            proc.wait(timeout=10)
            pump.stop()
            for inside, outside in pairs.values():
                inside.close()
                outside.close()
            rings.close(unlink=True)


class TestBatchSyscalls:
    """recvmmsg/sendmmsg native batch path (pio_recv_batch/send_batch)."""

    def test_recv_batch_reports_true_length_for_oversized(self):
        """MSG_TRUNC: a frame longer than snap must report its REAL wire
        length so the parser sets FLAG_TRUNC — otherwise the punt path
        would transmit a silently truncated frame."""
        from vpp_tpu.native.pktio import FLAG_TRUNC, PacketCodec

        codec = PacketCodec(snap=256)
        a, b = SocketPairTransport.pair("trunc")
        try:
            big = make_frame(CLIENT_IP, SERVER_IP, proto=17, dport=80,
                             payload=b"z" * 900)
            small = make_frame(CLIENT_IP, SERVER_IP, proto=17, dport=80)
            b.sock.send(big)
            b.sock.send(small)
            time.sleep(0.05)
            scratch = np.zeros((256, 256), np.uint8)
            lens = np.zeros(256, np.uint32)
            n = codec.recv_batch(a.batch_fd, scratch, lens)
            assert n == 2
            assert int(lens[0]) == len(big)      # true length, not snap
            assert int(lens[1]) == len(small)
            cols, n = codec.parse_inplace(scratch, lens, n, 0)
            assert cols["flags"][0] & FLAG_TRUNC
            assert not (cols["flags"][1] & FLAG_TRUNC)
        finally:
            a.close()
            b.close()

    def test_recv_batch_distinguishes_dead_fd_from_idle(self):
        from vpp_tpu.native.pktio import PacketCodec

        codec = PacketCodec(snap=256)
        a, b = SocketPairTransport.pair("dead")
        scratch = np.zeros((8, 256), np.uint8)
        lens = np.zeros(8, np.uint32)
        fd = a.batch_fd
        assert codec.recv_batch(fd, scratch, lens) == 0   # idle
        a.close()
        b.close()
        assert codec.recv_batch(fd, scratch, lens) == -1  # dead

    def test_send_batch_roundtrip(self):
        from vpp_tpu.native.pktio import PacketCodec

        codec = PacketCodec(snap=512)
        a, b = SocketPairTransport.pair("sb")
        try:
            payload = np.zeros((4, 512), np.uint8)
            frames = [make_frame(CLIENT_IP, SERVER_IP, sport=5000 + i,
                                 dport=80) for i in range(4)]
            for i, f in enumerate(frames):
                payload[i, :len(f)] = np.frombuffer(f, np.uint8)
            rows = np.arange(4, dtype=np.uint32)
            lens = np.asarray([len(f) for f in frames], np.uint32)
            sent = codec.send_batch(a.batch_fd, payload, rows, lens, 4)
            assert sent == 4
            got = [b.sock.recv(65535) for _ in range(4)]
            assert got == frames
        finally:
            a.close()
            b.close()


class TestIcmpErrors:
    """ICMP error generation for attributed drops (VERDICT r3 Next #8;
    VPP's ip4-icmp-error node: traceroute shows the vswitch hop)."""

    def _expect_icmp(self, harness, sock_name, icmp_type, orig_dst,
                     orig_src=CLIENT_IP):
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            try:
                out = harness.recv(sock_name, timeout=1.0)
            except (socket.timeout, TimeoutError):
                continue
            if out[23] == 1:  # IP proto == ICMP
                break
        else:
            raise AssertionError("no ICMP error received")
        assert out[14 + 12:14 + 16] == ipaddress.ip_address(GW_IP).packed, \
            "error originates from the pod gateway (the vswitch hop)"
        assert out[14 + 16:14 + 20] == ipaddress.ip_address(orig_src).packed
        assert ip_checksum_ok(out[14:34])
        icmp = out[34:]
        assert icmp[0] == icmp_type and icmp[1] == 0
        # RFC 792: quoted original IP header + first 8 L4 bytes
        quoted = icmp[8:]
        assert quoted[12:16] == ipaddress.ip_address(orig_src).packed
        assert quoted[16:20] == ipaddress.ip_address(orig_dst).packed
        return out

    def test_ttl_expired_generates_time_exceeded(self, harness):
        frame = make_frame(CLIENT_IP, SERVER_IP, proto=17, dport=80, ttl=1)
        harness.send("client", frame)
        self._expect_icmp(harness, "client", 11, SERVER_IP)

    def test_no_route_generates_net_unreachable(self, harness):
        # from the non-isolated server pod (no local table): the packet
        # is PERMITTED, then misses the FIB — a policy deny would drop
        # silently before routing ever ran
        frame = make_frame(SERVER_IP, "203.0.113.9", proto=17, dport=80)
        harness.send("server", frame)
        self._expect_icmp(harness, "server", 3, "203.0.113.9",
                          orig_src=SERVER_IP)

    def test_policy_deny_generates_no_icmp(self, harness):
        """Policy drops are silent (VPP ACL deny != unreachable)."""
        before = harness.pump.stats.get("icmp_errors", 0)
        frame = make_frame(CLIENT_IP, SERVER_IP, proto=17, dport=9999)
        harness.send("client", frame)
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline:
            time.sleep(0.05)
        assert harness.pump.stats.get("icmp_errors", 0) == before

    def test_remote_sender_gets_vxlan_encapped_time_exceeded(self, harness):
        """Cross-node traceroute: a TTL=1 packet from a REMOTE pod
        (VXLAN-decapped off the uplink) expires here; the generated
        time-exceeded is routed back THROUGH THE PIPELINE — picking up
        the remote route's next_hop — and leaves VXLAN-encapsulated
        toward the peer VTEP, not as a bare frame."""
        from vpp_tpu.native.pktio import PacketCodec

        inner = make_frame(REMOTE_POD, SERVER_IP, proto=17, dport=80,
                           ttl=1)
        codec = PacketCodec()
        arr = np.frombuffer(inner, np.uint8)
        wire = codec.encap(
            np.ascontiguousarray(arr), len(inner), ip4(VTEP_PEER),
            ip4(VTEP_SELF), 50000, 10,
            b"\x02\x00\x00\x00\x00\x09", b"\x02\x00\x00\x00\x00\x08",
        )
        harness.send("uplink", wire)
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline:
            try:
                out = harness.recv("uplink", timeout=1.0)
            except (socket.timeout, TimeoutError):
                continue
            # outer IPv4/UDP VXLAN toward the peer VTEP?
            if len(out) < 50 + 34 or out[23] != 17:
                continue
            if out[14 + 16:14 + 20] != \
                    ipaddress.ip_address(VTEP_PEER).packed:
                continue
            icmp_inner = out[50:]  # skip outer eth+ip+udp+vxlan
            if icmp_inner[23] == 1:  # inner proto ICMP
                break
        else:
            raise AssertionError("no VXLAN-encapped ICMP toward the peer")
        assert icmp_inner[14 + 12:14 + 16] == \
            ipaddress.ip_address(GW_IP).packed
        assert icmp_inner[14 + 16:14 + 20] == \
            ipaddress.ip_address(REMOTE_POD).packed
        assert icmp_inner[34] == 11  # time exceeded
        # RFC 792 quote: the invoking packet's header (remote pod ->
        # server) rides inside the error
        quoted = icmp_inner[34 + 8:]
        assert quoted[12:16] == ipaddress.ip_address(REMOTE_POD).packed
        assert quoted[16:20] == ipaddress.ip_address(SERVER_IP).packed
