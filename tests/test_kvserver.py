"""Served kvstore: KVServer + RemoteKVStore across threads and processes.

VERDICT r1 Missing #2 / Next #3: the reference deploys etcd
(/root/reference/k8s/contiv-vpp.yaml:72-114) and every plugin shares
state through it; these tests prove the served store gives separate
processes the same watch/CAS/resync semantics the in-process KVStore
gives threads.
"""

from __future__ import annotations

import os
import queue
import subprocess
import sys
import threading
import time

import pytest

from vpp_tpu.kvstore.client import RemoteKVStore, connect_store
from vpp_tpu.kvstore.server import KVServer
from vpp_tpu.kvstore.store import Broker, KVStore, Op


@pytest.fixture()
def server():
    srv = KVServer(host="127.0.0.1", port=0).start()
    yield srv
    srv.close()


@pytest.fixture()
def client(server):
    c = RemoteKVStore("127.0.0.1", server.port, request_timeout=5.0)
    yield c
    c.close()


def wait_for(pred, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


class TestBasicOps:
    def test_request_histogram_observes_and_clamps_garbage_ops(
            self, server, client):
        """Every served request lands in the op-labelled latency
        histogram; garbage op values (wrong type included) clamp to
        "other" and must not tear the connection down."""
        import json
        import socket

        client.put("h/k", 1)
        assert client.get("h/k") == 1
        assert server.request_hist.get_count(op="put") >= 1
        assert server.request_hist.get_count(op="get") >= 1
        # raw frame with an unhashable op: error reply, connection lives
        s = socket.create_connection(("127.0.0.1", server.port), timeout=5)
        try:
            s.sendall(b'{"id": 1, "op": ["get"]}\n')
            buf = b""
            while b"\n" not in buf:
                buf += s.recv(4096)
            reply = json.loads(buf.split(b"\n", 1)[0])
            assert reply["ok"] is False
            # same connection still answers a valid request
            s.sendall(b'{"id": 2, "op": "ping"}\n')
            buf = buf.split(b"\n", 1)[1]
            while b"\n" not in buf:
                buf += s.recv(4096)
            reply = json.loads(buf.split(b"\n", 1)[0])
            assert reply == {"id": 2, "ok": True, "result": "pong"}
        finally:
            s.close()
        assert server.request_hist.get_count(op="other") >= 1

    def test_put_get_delete(self, client):
        rev = client.put("a/b", {"x": 1})
        assert rev >= 1
        assert client.get("a/b") == {"x": 1}
        assert client.delete("a/b") is True
        assert client.delete("a/b") is False
        assert client.get("a/b") is None

    def test_cas_semantics(self, client):
        assert client.compare_and_put("id/5", None, "node-1") is True
        # second claimant loses, exactly like the node-ID allocator path
        assert client.compare_and_put("id/5", None, "node-2") is False
        assert client.compare_and_put("id/5", "node-1", "node-9") is True
        assert client.compare_and_delete("id/5", "bogus") is False
        assert client.compare_and_delete("id/5", "node-9") is True

    def test_list_and_rev(self, client):
        client.put("k8s/pod/a", 1)
        client.put("k8s/pod/b", 2)
        client.put("k8s/svc/c", 3)
        assert client.list_values("k8s/pod/") == {
            "k8s/pod/a": 1, "k8s/pod/b": 2,
        }
        assert client.list_keys("k8s/") == [
            "k8s/pod/a", "k8s/pod/b", "k8s/svc/c",
        ]
        assert client.revision == 3

    def test_broker_works_over_remote(self, client):
        broker = Broker(client, "agent/node-1/")
        broker.put("cfg", {"mtu": 1450})
        assert client.get("agent/node-1/cfg") == {"mtu": 1450}
        assert broker.list_values() == {"cfg": {"mtu": 1450}}


class TestWatch:
    def test_watch_sees_other_clients_changes(self, server, client):
        other = RemoteKVStore("127.0.0.1", server.port)
        try:
            events = queue.Queue()
            client.watch("ksr/", events.put)
            other.put("ksr/pod/a", {"ip": "10.1.1.2"})
            other.delete("ksr/pod/a")
            ev1 = events.get(timeout=5)
            ev2 = events.get(timeout=5)
            assert (ev1.op, ev1.key, ev1.value) == (
                Op.PUT, "ksr/pod/a", {"ip": "10.1.1.2"}
            )
            assert (ev2.op, ev2.key) == (Op.DELETE, "ksr/pod/a")
            assert ev2.prev_value == {"ip": "10.1.1.2"}
            assert ev2.rev > ev1.rev
        finally:
            other.close()

    def test_watch_prefix_filtering_and_cancel(self, client):
        events = queue.Queue()
        cancel = client.watch("a/", events.put)
        client.put("b/x", 1)          # outside prefix
        client.put("a/x", 2)
        ev = events.get(timeout=5)
        assert ev.key == "a/x"
        cancel()
        client.put("a/y", 3)
        with pytest.raises(queue.Empty):
            events.get(timeout=0.3)

    def test_watch_with_snapshot_is_gapless(self, server, client):
        client.put("s/a", 1)
        snapshot, rev, cancel = client.watch_with_snapshot(
            "s/", lambda ev: None
        )
        assert snapshot == {"s/a": 1}
        assert rev == server.store.revision

    def test_callback_may_reenter_store(self, client):
        """A watch callback doing store ops must not deadlock (the agent
        watch bridge writes rendered state back while handling events)."""
        done = threading.Event()

        def cb(ev):
            client.put("derived/" + ev.key, ev.value)
            done.set()

        client.watch("src/", cb)
        client.put("src/x", 42)
        assert done.wait(5)
        assert client.get("derived/src/x") == 42

    def test_event_order_matches_revision_order(self, client):
        events = []
        got = threading.Event()

        def cb(ev):
            events.append(ev)
            if len(events) == 50:
                got.set()

        client.watch("seq/", cb)
        for i in range(50):
            client.put(f"seq/{i:02d}", i)
        assert got.wait(5)
        revs = [ev.rev for ev in events]
        assert revs == sorted(revs)


class TestReconnect:
    def test_reconnect_and_resync_hook(self):
        store = KVStore()
        srv = KVServer(store=store, host="127.0.0.1", port=0).start()
        port = srv.port
        c = RemoteKVStore("127.0.0.1", port, reconnect_timeout=10.0)
        try:
            events = queue.Queue()
            resyncs = queue.Queue()
            c.watch("ksr/", events.put,
                    on_resync=lambda snap, rev: resyncs.put((snap, rev)))
            # registration itself delivers the first (empty) snapshot
            snap0, _ = resyncs.get(timeout=5)
            assert snap0 == {}
            store.put("ksr/a", 1)
            assert events.get(timeout=5).key == "ksr/a"

            # kill the server; mutate state while the client is away;
            # restart on the same port and same backing store
            srv.close()
            store.put("ksr/b", 2)
            store.delete("ksr/a")
            srv2 = KVServer(store=store, host="127.0.0.1", port=port).start()
            try:
                snap, rev = resyncs.get(timeout=10)
                # resync snapshot reflects the outage-time changes: the
                # consumer mark-and-sweeps 'a' away and adopts 'b'
                assert snap == {"ksr/b": 2}
                assert rev == store.revision
                # live watch works again after reconnect
                store.put("ksr/c", 3)
                wait_for(lambda: c.get("ksr/c") == 3, msg="reconnected get")
                ev = events.get(timeout=5)
                while ev.key != "ksr/c":
                    ev = events.get(timeout=5)
            finally:
                srv2.close()
        finally:
            c.close()

    def test_connect_store_dispatch(self, server):
        local = connect_store("")
        assert isinstance(local, KVStore)
        remote = connect_store(f"tcp://127.0.0.1:{server.port}")
        try:
            assert isinstance(remote, RemoteKVStore)
            assert remote.ping()
        finally:
            remote.close()
        with pytest.raises(ValueError):
            connect_store("zk://x:1")


CHILD_SCRIPT = r"""
import sys
from vpp_tpu.kvstore.client import RemoteKVStore
from vpp_tpu.kvstore.store import Broker

port = int(sys.argv[1])
store = RemoteKVStore("127.0.0.1", port)
broker = Broker(store, "ksr/")
# claim a node id with CAS, then publish pods (the KSR-process role)
assert store.compare_and_put("ids/7", None, "child") is True
assert store.compare_and_put("ids/7", None, "child-again") is False
for i in range(5):
    broker.put(f"k8s/pod/p{i}/namespace/default", {"ip": f"10.1.1.{i}"})
# read back something the parent wrote before spawning us
assert store.get("parent/marker") == "hello"
store.close()
print("CHILD_OK")
"""


class TestCrossProcess:
    def test_separate_processes_share_watches(self, server):
        """The KSR-and-agent-in-separate-processes criterion: a child
        process writes through the served store; the parent's watch
        bridge sees every event."""
        parent = RemoteKVStore("127.0.0.1", server.port)
        try:
            parent.put("parent/marker", "hello")
            events = queue.Queue()
            parent.watch("ksr/", events.put)
            env = dict(os.environ)
            env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
            env.setdefault("JAX_PLATFORMS", "cpu")
            proc = subprocess.run(
                [sys.executable, "-c", CHILD_SCRIPT, str(server.port)],
                capture_output=True, text=True, timeout=60, env=env,
            )
            assert proc.returncode == 0, proc.stderr
            assert "CHILD_OK" in proc.stdout
            seen = set()
            while len(seen) < 5:
                ev = events.get(timeout=5)
                assert ev.op == Op.PUT
                seen.add(ev.key)
            assert seen == {
                f"ksr/k8s/pod/p{i}/namespace/default" for i in range(5)
            }
            # CAS outcome visible to parent
            assert parent.get("ids/7") == "child"
        finally:
            parent.close()


class TestLeases:
    """Lease/TTL keys: node-liveness semantics (VERDICT r2 Next #8;
    etcd-lease analog). Keys die with their lease; keepalive holds them."""

    def test_lease_expiry_deletes_key_and_notifies_watchers(self, client):
        events = queue.Queue()
        client.watch("live/", events.put)
        lease = client.lease_grant(0.6)
        client.put("live/7", {"ip": "10.0.0.7"}, lease=lease)
        ev = events.get(timeout=5)
        assert ev.op == Op.PUT and ev.key == "live/7"
        # no keepalive: the server-side sweeper must delete it
        ev = events.get(timeout=5)
        assert ev.op == Op.DELETE and ev.key == "live/7"
        assert client.get("live/7") is None

    def test_keepalive_holds_key_alive(self, client):
        lease = client.lease_grant(0.8)
        client.put("live/8", {"ip": "10.0.0.8"}, lease=lease)
        for _ in range(4):
            time.sleep(0.4)
            assert client.lease_keepalive(lease)
            assert client.get("live/8") is not None
        client.lease_revoke(lease)
        wait_for(lambda: client.get("live/8") is None,
                 msg="revoke deletes key")

    def test_put_with_unknown_lease_rejected(self, client):
        with pytest.raises(RuntimeError):
            client.put("live/9", {}, lease=424242)

    def test_leases_do_not_survive_restart(self, tmp_path):
        path = str(tmp_path / "snap.json")
        store = KVStore(persist_path=path)
        lease = store.lease_grant(60.0)
        store.put("live/1", {"ip": "10.0.0.1"}, lease=lease)
        store.put("cfg/a", 1)
        store.save()
        store2 = KVStore(persist_path=path)
        # durable data survives; lease-attached liveness starts expired
        assert store2.get("cfg/a") == 1
        assert store2.get("live/1") is None


class TestCrashSafety:
    """kill -9 the kvserver mid-write; restart; state intact
    (VERDICT r2 Next #8)."""

    def test_kill9_mid_write_leaves_loadable_snapshot(self, tmp_path):
        path = str(tmp_path / "state.json")
        code = (
            "import sys\n"
            "sys.path.insert(0, %r)\n"
            "from vpp_tpu.cmd.kvserver import main\n"
            "main(['--host', '127.0.0.1', '--port', '0',\n"
            "      '--persist', %r, '--port-file', %r])\n"
        ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
             path, path + ".port")
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        proc = subprocess.Popen([sys.executable, "-c", code], env=env)
        try:
            wait_for(lambda: os.path.exists(path + ".port"), timeout=15,
                     msg="server port file")
            port = int(open(path + ".port").read())
            cli = RemoteKVStore("127.0.0.1", port, request_timeout=5.0)
            # hammer puts so a save is overwhelmingly likely in flight
            # when the SIGKILL lands (autosave debounce is 0.2 s)
            for i in range(400):
                cli.put(f"k/{i:04d}", {"i": i, "pad": "x" * 200})
            proc.kill()
            proc.wait(timeout=10)
            cli.close()
        finally:
            if proc.poll() is None:
                proc.kill()
        # restart: the snapshot must parse and contain a consistent
        # prefix of the writes (atomic rename: old-or-new, never torn)
        store = KVStore(persist_path=path)
        keys = store.list_keys("k/")
        assert keys, "no state survived the crash"
        for k in keys:
            v = store.get(k)
            assert v["pad"] == "x" * 200
            assert f"k/{v['i']:04d}" == k


class TestReplication:
    """Warm-standby HA: follower replication, read-only posture,
    promotion on primary loss, client endpoint failover
    (kvstore/replica.py; the reference leans on a single-replica etcd
    Deployment, k8s/contiv-vpp.yaml:72-114)."""

    def test_refollow_never_stacks_heartbeat_threads(self):
        """_try_refollow on a flapping primary link must not start a
        second heartbeat loop while one is alive — the r5-era leak
        accumulated one pinger per refollow cycle, each independently
        able to fire _promote (ADVICE r5)."""
        from vpp_tpu.kvstore.replica import Replicator

        primary = KVServer(host="127.0.0.1", port=0).start()
        fstore = KVStore()
        repl = None
        try:
            repl = Replicator(fstore, "127.0.0.1", primary.port,
                              promote_after=30.0).start()
            first = repl._heartbeat_thread
            assert first is not None and first.is_alive()
            # a few refollow cycles against the same healthy primary:
            # the heartbeat thread object must not churn
            for _ in range(3):
                assert repl._try_refollow() is True
                assert repl._heartbeat_thread is first
            hb_threads = [t for t in threading.enumerate()
                          if t.name == "kv-replica-hb"]
            assert len(hb_threads) == 1
        finally:
            if repl is not None:
                repl.stop()
            primary.close()

    def test_follower_replicates_and_rejects_writes(self):
        from vpp_tpu.kvstore.replica import Replicator

        primary = KVServer(host="127.0.0.1", port=0).start()
        primary.store.put("ksr/pod/a", {"ip": "10.1.1.2"})
        fstore = KVStore()
        follower = KVServer(store=fstore, host="127.0.0.1", port=0)
        follower.read_only = True
        follower.start()
        repl = None
        try:
            repl = Replicator(fstore, "127.0.0.1", primary.port,
                              promote_after=2.0).start()
            # initial snapshot applied before start() returned
            assert fstore.get("ksr/pod/a") == {"ip": "10.1.1.2"}
            # live stream: put + delete flow through
            primary.store.put("ksr/pod/b", 2)
            wait_for(lambda: fstore.get("ksr/pod/b") == 2, msg="repl put")
            primary.store.delete("ksr/pod/a")
            wait_for(lambda: fstore.get("ksr/pod/a") is None,
                     msg="repl delete")
            # reads served, writes refused while following
            c = RemoteKVStore("127.0.0.1", follower.port,
                              request_timeout=5.0)
            try:
                assert c.get("ksr/pod/b") == 2
                with pytest.raises(RuntimeError, match="not primary"):
                    c.put("ksr/pod/c", 3)
            finally:
                c.close()
        finally:
            if repl is not None:
                repl.stop()
            follower.close()
            primary.close()

    def test_promotion_and_client_failover(self):
        from vpp_tpu.kvstore.replica import Replicator

        primary = KVServer(host="127.0.0.1", port=0).start()
        primary.store.put("agent/node/1", "up")
        fstore = KVStore()
        follower = KVServer(store=fstore, host="127.0.0.1", port=0)
        follower.read_only = True
        follower.start()
        repl = Replicator(fstore, "127.0.0.1", primary.port,
                          promote_after=1.0,
                          on_promote=lambda: setattr(
                              follower, "read_only", False))
        repl.start()
        # an agent configured with both endpoints
        c = connect_store(
            f"tcp://127.0.0.1:{primary.port},127.0.0.1:{follower.port}",
            request_timeout=5.0, reconnect_timeout=15.0,
            reconnect_backoff=(0.05, 0.2),
        )
        try:
            assert c.get("agent/node/1") == "up"
            events = queue.Queue()
            c.watch("agent/", events.put)

            primary.close()  # the outage
            wait_for(lambda: repl.promoted.is_set(), timeout=15.0,
                     msg="follower promotion")
            assert not follower.read_only
            # client fails over to the standby; state intact; writes
            # resume; the re-registered watch sees them
            wait_for(lambda: c.get("agent/node/1") == "up", timeout=15.0,
                     msg="failover read")
            c.put("agent/node/2", "up")
            assert fstore.get("agent/node/2") == "up"
            ev = events.get(timeout=5)
            while ev.key != "agent/node/2":
                ev = events.get(timeout=5)
        finally:
            c.close()
            repl.stop()
            follower.close()

    def test_promotion_grace_leases_liveness_keys(self):
        """Leases don't replicate; at promotion, keys under the grace
        prefixes get a fresh short lease so a dead node's liveness key
        expires instead of pinning its routes forever."""
        from vpp_tpu.kvstore.replica import Replicator

        primary = KVServer(host="127.0.0.1", port=0).start()
        lease = primary.store.lease_grant(30.0)
        primary.store.put("nodeliveness/3", {"ip": "10.3.0.1"},
                          lease=lease)
        fstore = KVStore()
        follower = KVServer(store=fstore, host="127.0.0.1", port=0)
        follower.read_only = True
        follower.start()
        repl = Replicator(fstore, "127.0.0.1", primary.port,
                          promote_after=0.5,
                          grace_prefixes=("nodeliveness/",),
                          grace_ttl_s=0.5)
        repl.start()
        try:
            assert fstore.get("nodeliveness/3") == {"ip": "10.3.0.1"}
            primary.close()
            wait_for(lambda: repl.promoted.is_set(), timeout=15.0,
                     msg="promotion")
            # the dead node never keeps its grace lease alive; the
            # follower's own sweeper (running via KVServer) expires it
            wait_for(lambda: fstore.get("nodeliveness/3") is None,
                     timeout=10.0, msg="grace lease expiry")
        finally:
            repl.stop()
            follower.close()

    def test_write_rotates_off_readonly_follower(self):
        """A client connected to a live-but-read-only follower must not
        be stranded: 'not primary' rejections advance the endpoint
        rotation until a writable server answers (the transient-primary-
        blip case: clients failed over before the standby promoted)."""
        primary = KVServer(host="127.0.0.1", port=0).start()
        fstore = KVStore()
        follower = KVServer(store=fstore, host="127.0.0.1", port=0)
        follower.read_only = True
        follower.start()
        try:
            # follower listed FIRST: the client connects there
            c = connect_store(
                f"tcp://127.0.0.1:{follower.port},"
                f"127.0.0.1:{primary.port}",
                request_timeout=10.0, reconnect_timeout=10.0,
                reconnect_backoff=(0.05, 0.2),
            )
            try:
                assert (c.host, c.port) == ("127.0.0.1", follower.port)
                c.put("a", 1)  # rotates to the writable primary
                assert primary.store.get("a") == 1
                assert (c.host, c.port) == ("127.0.0.1", primary.port)
            finally:
                c.close()
        finally:
            follower.close()
            primary.close()

    def test_follower_with_primary_down_at_start_promotes(self):
        """Correlated failure: the standby restarts while the primary is
        already down. It must promote from its persisted replica rather
        than crash-loop (Replicator.start swallows the initial
        ConnectionError and promotes)."""
        from vpp_tpu.kvstore.replica import Replicator

        dead = KVServer(host="127.0.0.1", port=0).start()
        dead_port = dead.port
        dead.close()  # nothing listens here any more

        fstore = KVStore()
        fstore.put("agent/persisted", "state")  # the surviving replica
        follower = KVServer(store=fstore, host="127.0.0.1", port=0)
        follower.read_only = True
        follower.start()
        try:
            repl = Replicator(
                fstore, "127.0.0.1", dead_port, promote_after=1.0,
                on_promote=lambda: setattr(follower, "read_only", False),
            ).start()
            try:
                wait_for(lambda: repl.promoted.is_set(), timeout=15.0,
                         msg="promotion with primary down at start")
                c = RemoteKVStore("127.0.0.1", follower.port)
                try:
                    assert c.get("agent/persisted") == "state"
                    c.put("agent/new", 1)
                finally:
                    c.close()
            finally:
                repl.stop()
        finally:
            follower.close()

    def test_silent_primary_death_promotes_via_heartbeat(self):
        """Power loss / partition sends no FIN: the replication socket
        just blocks. The heartbeat must still promote. Simulated with
        SIGSTOP on a real kvserver subprocess — the TCP connection
        stays ESTABLISHED but nothing answers."""
        import signal
        import tempfile

        from vpp_tpu.kvstore.replica import Replicator

        with tempfile.TemporaryDirectory() as tmp:
            port_file = os.path.join(tmp, "port")
            proc = subprocess.Popen(
                [sys.executable, "-m", "vpp_tpu.cmd.kvserver",
                 "--host", "127.0.0.1", "--port", "0",
                 "--port-file", port_file],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            try:
                wait_for(lambda: os.path.exists(port_file), timeout=20.0,
                         msg="primary start")
                pport = int(open(port_file).read())
                seed = RemoteKVStore("127.0.0.1", pport)
                seed.put("k", 1)
                seed.close()

                fstore = KVStore()
                repl = Replicator(fstore, "127.0.0.1", pport,
                                  promote_after=1.5)
                repl.start()
                try:
                    assert fstore.get("k") == 1
                    os.kill(proc.pid, signal.SIGSTOP)  # silent death
                    wait_for(lambda: repl.promoted.is_set(), timeout=30.0,
                             msg="heartbeat promotion on silent death")
                finally:
                    repl.stop()
            finally:
                try:
                    os.kill(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                proc.wait(timeout=10)
