"""Property tests for the bit-packed host<->device boundary.

The [5,B] packed layout (pipeline/dataplane.py _packed_call) carries
every header field in sub-32-bit lanes; a packing bug silently corrupts
whichever field shares the word. These tests drive random field values
through pack → unpack (host round trip) and through the jitted packed
step vs the unpacked step (device-path equivalence) — the randomized
differential style of the reference's policy tests (SURVEY §4) applied
to the wire boundary.
"""

from __future__ import annotations

import numpy as np
from hypothesis import given, settings
from hypothesis import strategies as st

from vpp_tpu.pipeline.dataplane import (
    PACKED_IN_ROWS,
    pack_packet_columns,
    packed_input_zeros,
    unpack_packet_input,
    unpack_packet_result,
)

VEC = 256

field_ranges = {
    "src_ip": (0, 0xFFFFFFFF),
    "dst_ip": (0, 0xFFFFFFFF),
    "proto": (0, 255),
    "sport": (0, 65535),
    "dport": (0, 65535),
    "ttl": (0, 255),
    "pkt_len": (0, 65535),
    "rx_if": (0, (1 << 24) - 1),
    "flags": (0, 255),
}


@st.composite
def column_batches(draw):
    n = draw(st.integers(min_value=1, max_value=VEC))
    rng = np.random.default_rng(draw(st.integers(0, 2**32 - 1)))
    cols = {}
    for name, (lo, hi) in field_ranges.items():
        vals = rng.integers(lo, hi + 1, n, dtype=np.uint64)
        dtype = np.uint32 if name in ("src_ip", "dst_ip") else np.int32
        cols[name] = vals.astype(np.uint32).view(np.uint32) if \
            dtype is np.uint32 else vals.astype(np.int32)
    return cols, n


@given(column_batches())
@settings(max_examples=50, deadline=None)
def test_pack_unpack_input_round_trip(batch):
    cols, n = batch
    flat = packed_input_zeros(VEC)
    assert flat.shape == (PACKED_IN_ROWS, VEC)
    pack_packet_columns(flat.view(np.uint32), cols, n)
    rt = unpack_packet_input(flat)
    for name in field_ranges:
        got = rt[name][:n].astype(np.uint32)
        want = cols[name][:n].astype(np.uint32)
        assert np.array_equal(got, want), name


@given(st.integers(0, 2**32 - 1))
@settings(max_examples=30, deadline=None)
def test_result_decode_field_isolation(seed):
    """Device-side result packing (disp<<24 | ttl<<16 | tx_if) decoded
    on the host must isolate every field, including the tx_if == 0xFFFF
    → -1 sentinel."""
    rng = np.random.default_rng(seed)
    n = VEC
    src = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    dst = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    sport = rng.integers(0, 1 << 16, n).astype(np.uint32)
    dport = rng.integers(0, 1 << 16, n).astype(np.uint32)
    disp = rng.integers(0, 5, n).astype(np.uint32)
    ttl = rng.integers(0, 256, n).astype(np.uint32)
    tx_if = rng.integers(0, 1 << 16, n).astype(np.uint32)
    tx_if[0] = 0xFFFF  # always cover the sentinel
    nh = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)

    out = np.stack([
        src, dst, (sport << 16) | dport,
        (disp << 24) | (ttl << 16) | tx_if, nh,
    ]).astype(np.uint32).view(np.int32)
    dec = unpack_packet_result(np.array(out))
    assert np.array_equal(dec["src_ip"], src)
    assert np.array_equal(dec["dst_ip"], dst)
    assert np.array_equal(dec["sport"].astype(np.uint32), sport)
    assert np.array_equal(dec["dport"].astype(np.uint32), dport)
    assert np.array_equal(dec["disp"].astype(np.uint32), disp)
    assert np.array_equal(dec["ttl"].astype(np.uint32), ttl)
    want_tx = tx_if.astype(np.int32)
    want_tx[tx_if == 0xFFFF] = -1
    assert np.array_equal(dec["tx_if"], want_tx)
    assert np.array_equal(dec["next_hop"], nh)
    assert dec["tx_if"][0] == -1


def test_packed_step_equals_unpacked_step():
    """Random traffic through process_packed must agree field-for-field
    with the unpacked pipeline step on the same dataplane state."""
    import jax

    from vpp_tpu.ir.rule import Action, ContivRule, Protocol
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import Disposition, ip4, make_packet_vector

    dp = Dataplane(DataplaneConfig())
    uplink = dp.add_uplink()
    a = dp.add_pod_interface(("default", "a"))
    b = dp.add_pod_interface(("default", "b"))
    dp.builder.add_route("10.1.1.2/32", a, Disposition.LOCAL)
    dp.builder.add_route("10.1.1.3/32", b, Disposition.LOCAL)
    dp.builder.add_route("0.0.0.0/0", uplink, Disposition.REMOTE, node_id=1)
    slot = dp.alloc_table_slot("t")
    dp.builder.set_local_table(slot, [
        ContivRule(action=Action.PERMIT, protocol=Protocol.UDP,
                   dest_port=53),
        ContivRule(action=Action.DENY),
    ])
    dp.assign_pod_table(("default", "a"), "t")
    dp.builder.set_nat_mapping(
        0, ext_ip=ip4("10.96.0.10"), ext_port=80, proto=6,
        backends=[(ip4("10.1.1.3"), 8080, 1)], boff=0,
    )
    dp.swap()

    rng = np.random.default_rng(7)
    n = VEC
    specs = []
    for i in range(n):
        kind = i % 4
        if kind == 0:
            d, proto, dport = "10.1.1.3", 17, 53        # permitted
        elif kind == 1:
            d, proto, dport = "10.1.1.3", 6, 80         # denied (TCP)
        elif kind == 2:
            d, proto, dport = "10.96.0.10", 6, 80       # VIP DNAT
        else:
            d, proto, dport = "8.8.8.8", 17, 53         # remote
        specs.append({"src": "10.1.1.2", "dst": d, "proto": proto,
                      "sport": int(rng.integers(1024, 65535)),
                      "dport": dport, "rx_if": a})
    pv = make_packet_vector(specs)
    ref = dp.process(pv, now=1000)

    flat = packed_input_zeros(n)
    cols = {
        "src_ip": np.asarray(pv.src_ip), "dst_ip": np.asarray(pv.dst_ip),
        "proto": np.asarray(pv.proto), "sport": np.asarray(pv.sport),
        "dport": np.asarray(pv.dport), "ttl": np.asarray(pv.ttl),
        "pkt_len": np.asarray(pv.pkt_len), "rx_if": np.asarray(pv.rx_if),
        "flags": np.asarray(pv.flags),
    }
    pack_packet_columns(flat.view(np.uint32), cols, n)
    out = np.array(jax.device_get(dp.process_packed(flat, now=1000)))
    dec = unpack_packet_result(out)

    assert np.array_equal(dec["disp"], np.asarray(ref.disp))
    assert np.array_equal(dec["tx_if"], np.asarray(ref.tx_if))
    assert np.array_equal(dec["dst_ip"], np.asarray(ref.pkts.dst_ip))
    assert np.array_equal(dec["sport"], np.asarray(ref.pkts.sport))
    assert np.array_equal(dec["dport"], np.asarray(ref.pkts.dport))
    assert np.array_equal(dec["ttl"], np.asarray(ref.pkts.ttl))
    assert np.array_equal(dec["next_hop"], np.asarray(ref.next_hop))


def test_chained_steps_equal_sequential_packed():
    """process_packed_chain (K steps in one device program) must equal
    K sequential process_packed dispatches: same packed outputs, same
    session-table evolution (lax.scan threads tables identically)."""
    import numpy as np

    from vpp_tpu.pipeline.dataplane import (
        Dataplane, packed_input_zeros, unpack_packet_result,
    )
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import Disposition

    def build():
        cfg = DataplaneConfig(max_tables=2, max_rules=8,
                              max_global_rules=16, max_ifaces=8,
                              fib_slots=16, sess_slots=64,
                              nat_mappings=2, nat_backends=4)
        dp = Dataplane(cfg)
        a = dp.add_pod_interface(("d", "a"))
        b = dp.add_pod_interface(("d", "b"))
        dp.builder.add_route("10.0.0.3/32", b, Disposition.LOCAL)
        dp.swap()
        return dp, a

    K, B = 4, 256
    flats = np.zeros((K, 5, B), np.int32)
    dp, rx = build()
    for k in range(K):
        fu = flats[k].view(np.uint32)
        fu[0] = 0x0A000002
        fu[1] = 0x0A000003
        fu[2] = ((40000 + k) << 16) | 80
        fu[3] = (128 << 16) | (6 << 8) | 64
        fu[4] = (rx << 8) | 1

    import jax

    chained = np.array(jax.device_get(dp.process_packed_chain(flats, now=1)))
    sess_chain = int(np.asarray(dp.tables.sess_valid).sum())

    dp2, rx2 = build()
    assert rx2 == rx
    seq = np.stack([
        np.array(jax.device_get(dp2.process_packed(flats[k].copy(), now=1)))
        for k in range(K)
    ])
    np.testing.assert_array_equal(chained, seq)
    assert sess_chain == int(np.asarray(dp2.tables.sess_valid).sum())
    dec = unpack_packet_result(np.array(chained[0]))
    assert (dec["disp"][:1] == int(Disposition.LOCAL)).all()
