"""vpp-tpu-init bootstrap: sequencing, supervision, uplink pre-config
(VERDICT r2 Next #5; reference cmd/contiv-init/main.go:201-273 +
vppcfg.go:74-559). Driven entirely against fakes — no root, no real
processes."""

from __future__ import annotations

import json
import threading
import time

import pytest

from vpp_tpu.cmd.config import AgentConfig, IOConfig
from vpp_tpu.cmd.init_main import InitSupervisor, configure_uplink


class FakeProc:
    def __init__(self, argv):
        self.argv = argv
        self.returncode = None
        self.terminated = False

    def poll(self):
        return self.returncode

    def terminate(self):
        self.terminated = True
        self.returncode = 0

    def wait(self, timeout=None):
        return self.returncode

    def kill(self):
        self.returncode = -9

    def die(self, rc=1):
        self.returncode = rc


class FakeSpawner:
    """Mimics the real children: spawning the "agent" writes the IO
    plan file (the handshake the real agent performs once its shm rings
    exist), unless plan_on_agent=False."""

    def __init__(self, cfg=None, plan_on_agent=True):
        self.cfg = cfg
        self.plan_on_agent = plan_on_agent
        self.spawned = []

    def __call__(self, argv):
        p = FakeProc(argv)
        self.spawned.append(p)
        if (self.cfg is not None and self.plan_on_agent
                and "vpp_tpu.cmd.agent" in argv):
            write_plan(self.cfg)
        if (self.cfg is not None and self.plan_on_agent
                and "vpp_tpu.cmd.mesh_main" in argv):
            # a mesh agent writes one plan per node (suffixed paths)
            for i in range(self.cfg.mesh.nodes):
                write_plan(self.cfg, _suffix=f".{i}",
                           shm=f"vpp-shm.{i}",
                           control_socket=f"/run/vpp-tpu/io-ctl.sock.{i}")
        return p

    def by_module(self, module):
        return [p for p in self.spawned if module in p.argv]


def cfg_with_io(tmp_path, **kw):
    return AgentConfig(
        node_name="n1",
        io=IOConfig(
            enabled=True, shm_name="vpp-shm", n_slots=32, snap=1024,
            control_socket="/run/vpp-tpu/io-ctl.sock",
            uplink_interface="eth9",
            plan_path=str(tmp_path / "io-plan.json"),
            **kw,
        ),
    )


def write_plan(cfg, _suffix="", **over):
    plan = {
        "shm": "vpp-shm", "slots": 32, "snap": 1024, "uplink_if": 63,
        "host_if": 62, "uplink_interface": "eth9",
        "vtep": 0xC0A81E01, "vni": 10,
        "control_socket": "/run/vpp-tpu/io-ctl.sock",
    }
    plan.update(over)
    with open(cfg.io.plan_path + _suffix, "w") as f:
        json.dump(plan, f)
    return plan


class TestBootSequence:
    def test_agent_then_plan_then_io(self, tmp_path):
        cfg = cfg_with_io(tmp_path)
        spawner = FakeSpawner(cfg)
        sup = InitSupervisor(cfg, "/etc/vpp-tpu/contiv.yaml",
                             spawn=spawner, plan_timeout_s=5.0)
        sup.start()
        agent_argv, io_argv = (spawner.spawned[0].argv,
                               spawner.spawned[1].argv)
        assert "vpp_tpu.cmd.agent" in agent_argv
        assert "--config" in agent_argv
        assert "vpp_tpu.cmd.io_daemon" in io_argv
        # geometry + endpoints come from the agent's plan, not guesses
        assert io_argv[io_argv.index("--shm") + 1] == "vpp-shm"
        assert io_argv[io_argv.index("--uplink") + 1] == "63"
        assert io_argv[io_argv.index("--host-if") + 1] == "62"
        assert io_argv[io_argv.index("--control") + 1] == \
            "/run/vpp-tpu/io-ctl.sock"
        assert f"63:afpacket:eth9" in io_argv
        sup.stop()

    def test_plan_timeout_is_an_error(self, tmp_path):
        cfg = cfg_with_io(tmp_path)
        sup = InitSupervisor(cfg, None,
                             spawn=FakeSpawner(cfg, plan_on_agent=False),
                             plan_timeout_s=0.3)
        with pytest.raises(TimeoutError):
            sup.start()
        sup.stop()


class TestSupervision:
    def test_dead_agent_restart_also_restarts_io(self, tmp_path):
        """A replacement agent reclaims + recreates the shm rings, so
        the io daemon must be restarted with it — an io daemon mapped to
        the orphaned segment would pump disjoint memory."""
        cfg = cfg_with_io(tmp_path)
        spawner = FakeSpawner(cfg)
        sup = InitSupervisor(cfg, None, spawn=spawner, plan_timeout_s=2.0)
        sup.RESTART_BACKOFF_S = (0.05, 0.05, 0.05, 0.05)
        sup.start()
        first_io = sup.procs["io"]
        t = threading.Thread(target=sup.supervise, daemon=True)
        t.start()
        try:
            spawner.spawned[0].die(rc=2)  # agent crashes
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if (len(spawner.by_module("vpp_tpu.cmd.agent")) >= 2
                        and len(spawner.by_module(
                            "vpp_tpu.cmd.io_daemon")) >= 2):
                    break
                time.sleep(0.05)
            assert sup.restarts["agent"] >= 1
            assert len(spawner.by_module("vpp_tpu.cmd.agent")) >= 2
            # io restarted alongside the agent, old one torn down
            assert len(spawner.by_module("vpp_tpu.cmd.io_daemon")) >= 2
            assert first_io.terminated
        finally:
            sup.stop()
            t.join(timeout=5)
        assert not t.is_alive()

    def test_dead_io_is_restarted_alone(self, tmp_path):
        cfg = cfg_with_io(tmp_path)
        spawner = FakeSpawner(cfg)
        sup = InitSupervisor(cfg, None, spawn=spawner, plan_timeout_s=2.0)
        sup.RESTART_BACKOFF_S = (0.05,)
        sup.start()
        t = threading.Thread(target=sup.supervise, daemon=True)
        t.start()
        try:
            sup.procs["io"].die(rc=1)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if len(spawner.by_module("vpp_tpu.cmd.io_daemon")) >= 2:
                    break
                time.sleep(0.05)
            assert len(spawner.by_module("vpp_tpu.cmd.io_daemon")) >= 2
            # the agent was never touched
            assert len(spawner.by_module("vpp_tpu.cmd.agent")) == 1
        finally:
            sup.stop()
            t.join(timeout=5)

    def test_stop_tears_down_io_before_agent(self, tmp_path):
        cfg = cfg_with_io(tmp_path)
        order = []

        class OrderedSpawner(FakeSpawner):
            def __call__(self, argv):
                p = super().__call__(argv)
                orig = p.terminate

                def term():
                    order.append(p.argv)
                    orig()

                p.terminate = term
                return p

        sup = InitSupervisor(cfg, None, spawn=OrderedSpawner(cfg),
                             plan_timeout_s=2.0)
        sup.start()
        sup.stop()
        assert len(order) == 2
        assert "vpp_tpu.cmd.io_daemon" in order[0]
        assert "vpp_tpu.cmd.agent" in order[1]


class TestUplinkPreconfig:
    def test_static_ip_and_proxy_arp(self, tmp_path):
        calls = []

        def fake_run(argv, **kw):
            calls.append(argv)

            class R:
                returncode = 0
                stdout = stderr = ""

            return R()

        cfg = cfg_with_io(tmp_path, uplink_ip="192.168.16.5/24",
                          proxy_arp=True)
        applied = configure_uplink(cfg, run=fake_run)
        assert ["ip", "link", "set", "eth9", "up"] in calls
        assert ["ip", "addr", "replace", "192.168.16.5/24",
                "dev", "eth9"] in calls
        assert ["sysctl", "-w", "net.ipv4.conf.eth9.proxy_arp=1"] in calls
        assert applied == {"interface": "eth9", "ip": "192.168.16.5/24",
                           "dhcp": False, "proxy_arp": True}

    def test_no_uplink_is_a_noop(self, tmp_path):
        cfg = AgentConfig(node_name="n1")
        applied = configure_uplink(
            cfg, run=lambda *a, **k: (_ for _ in ()).throw(
                AssertionError("must not shell out")))
        assert applied["interface"] == ""



class TestMeshBoot:
    def _cfg(self, tmp_path):
        from vpp_tpu.cmd.config import MeshConfig

        cfg = cfg_with_io(tmp_path)
        cfg.mesh = MeshConfig(nodes=2, rule_shards=1)
        return cfg

    def test_mesh_agent_and_per_node_io(self, tmp_path):
        """mesh: config -> vpp-tpu-mesh-agent is the vswitch and ONE io
        daemon boots per node plan (suffixed shm/control endpoints)."""
        cfg = self._cfg(tmp_path)
        spawner = FakeSpawner(cfg)
        sup = InitSupervisor(cfg, None, spawn=spawner, plan_timeout_s=5.0)
        # settle window is 1.5s inside read_plans
        sup.start()
        assert spawner.by_module("vpp_tpu.cmd.mesh_main")
        assert not spawner.by_module("vpp_tpu.cmd.agent")
        ios = spawner.by_module("vpp_tpu.cmd.io_daemon")
        assert len(ios) == 2
        shms = sorted(a[a.index("--shm") + 1] for a in
                      (p.argv for p in ios))
        assert shms == ["vpp-shm.0", "vpp-shm.1"]

    def test_one_io_death_respawns_only_it(self, tmp_path):
        cfg = self._cfg(tmp_path)
        spawner = FakeSpawner(cfg)
        sup = InitSupervisor(cfg, None, spawn=spawner, plan_timeout_s=5.0)
        sup.start()
        t = threading.Thread(target=sup.supervise, daemon=True)
        t.start()
        try:
            ios = spawner.by_module("vpp_tpu.cmd.io_daemon")
            ios[0].die(rc=3)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                now = spawner.by_module("vpp_tpu.cmd.io_daemon")
                if len(now) == 3:
                    break
                time.sleep(0.05)
            assert len(spawner.by_module("vpp_tpu.cmd.io_daemon")) == 3
            # the mesh agent was NOT restarted
            assert len(spawner.by_module("vpp_tpu.cmd.mesh_main")) == 1
        finally:
            sup.stop()
            t.join(timeout=10)

    def test_mesh_agent_death_restarts_all_io(self, tmp_path):
        cfg = self._cfg(tmp_path)
        spawner = FakeSpawner(cfg)
        sup = InitSupervisor(cfg, None, spawn=spawner, plan_timeout_s=5.0)
        sup.start()
        t = threading.Thread(target=sup.supervise, daemon=True)
        t.start()
        try:
            spawner.by_module("vpp_tpu.cmd.mesh_main")[0].die(rc=2)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if (len(spawner.by_module("vpp_tpu.cmd.mesh_main")) >= 2
                        and len(spawner.by_module(
                            "vpp_tpu.cmd.io_daemon")) >= 4):
                    break
                time.sleep(0.05)
            assert len(spawner.by_module("vpp_tpu.cmd.mesh_main")) == 2
            assert len(spawner.by_module("vpp_tpu.cmd.io_daemon")) == 4
        finally:
            sup.stop()
            t.join(timeout=10)


def test_mesh_plans_straggle_past_settle_window(tmp_path):
    """Known node count: init must wait for ALL plans even when node
    boots straggle (a settle heuristic committed to a partial set when
    writes were >1.5s apart — e.g. a host-interconnect wire wait
    between agent boots)."""
    from vpp_tpu.cmd.config import MeshConfig

    cfg = cfg_with_io(tmp_path)
    cfg.mesh = MeshConfig(nodes=2, rule_shards=1)
    spawner = FakeSpawner(cfg, plan_on_agent=False)
    sup = InitSupervisor(cfg, None, spawn=spawner, plan_timeout_s=15.0)

    def slow_agent_boots():
        # deterministic ordering, not a sleep: _clear_plan runs
        # immediately before the agent spawn, so once the spawner has
        # the mesh agent the clear is done — a plan written before it
        # would be (correctly) deleted as stale and this test would
        # time out waiting for a .0 that never returns
        deadline = time.monotonic() + 10
        while not spawner.by_module("vpp_tpu.cmd.mesh_main") \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        write_plan(cfg, _suffix=".0", shm="vpp-shm.0")
        time.sleep(3.0)   # well past the old 1.5s settle window
        write_plan(cfg, _suffix=".1", shm="vpp-shm.1")

    threading.Thread(target=slow_agent_boots, daemon=True).start()
    sup.start()
    ios = spawner.by_module("vpp_tpu.cmd.io_daemon")
    assert len(ios) == 2, "partial plan set committed"
    shms = sorted(p.argv[p.argv.index("--shm") + 1] for p in ios)
    assert shms == ["vpp-shm.0", "vpp-shm.1"]


def test_multihost_waits_for_local_plans_only(tmp_path):
    """Multi-host (mesh.coordinator set): mesh.nodes counts the WHOLE
    cluster's rows, but this host's MultiHostRuntime writes plan files
    only for the rows its local devices own. Waiting for the global
    count timed out on every host and left the deployment with no io
    daemons (ADVICE r4 #1) — the settle heuristic must apply instead."""
    from vpp_tpu.cmd.config import MeshConfig

    cfg = cfg_with_io(tmp_path)
    cfg.mesh = MeshConfig(nodes=4, rule_shards=1,
                          coordinator="10.0.0.1:1234",
                          num_processes=2, process_id=0)
    spawner = FakeSpawner(cfg, plan_on_agent=False)
    sup = InitSupervisor(cfg, None, spawn=spawner, plan_timeout_s=8.0)

    def local_rows_boot():
        # after _clear_plan, deterministically (the agent spawn
        # immediately follows the clear — same ordering discipline as
        # the straggle test above): this host owns rows 0 and 1 of
        # the 4-row cluster
        deadline = time.monotonic() + 10
        while not spawner.by_module("vpp_tpu.cmd.mesh_main") \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        write_plan(cfg, _suffix=".0", shm="vpp-shm.0")
        write_plan(cfg, _suffix=".1", shm="vpp-shm.1")

    threading.Thread(target=local_rows_boot, daemon=True).start()
    sup.start()
    try:
        ios = spawner.by_module("vpp_tpu.cmd.io_daemon")
        assert len(ios) == 2, (
            f"expected io daemons for the 2 LOCAL rows, got {len(ios)}")
        shms = sorted(p.argv[p.argv.index("--shm") + 1] for p in ios)
        assert shms == ["vpp-shm.0", "vpp-shm.1"]
    finally:
        sup.stop()
