"""Shared jittered backoff (vpp_tpu.net.backoff) + the reconnect
storm it exists to break up (ISSUE 18 satellite).

The jitter pact every retry loop in the tree leans on: delay for
attempt ``a`` is ``min(cap, base * 2**a)`` scaled by a [0.5, 1.0)
draw — exponential growth, a hard cap, a floor that guarantees
forward progress, and per-loop decorrelation. The storm test drives
the real surface: a fleet of RemoteKVStore clients holding the
FleetMembership prefix watch all lose the server at once, reconnect
on their own jittered schedules, re-register the watch, and resync
the member churn they missed — event-gated, no wall-clock sleeps.
"""

from __future__ import annotations

import queue
import random

from vpp_tpu.fleet.membership import FleetMembership
from vpp_tpu.kvstore.client import RemoteKVStore
from vpp_tpu.kvstore.server import KVServer
from vpp_tpu.kvstore.store import KVStore
from vpp_tpu.net.backoff import Backoff, backoff_with_jitter


def _envelope(attempt, base, cap):
    return min(cap, base * 2.0 ** attempt)


class TestJitterBounds:
    def test_delay_stays_inside_the_jitter_band(self):
        """Every draw lands in [env/2, env): the 0.5 floor is what
        stops a reconnect loop from busy-spinning on a ~0 draw, the
        open top keeps callers under the exponential envelope."""
        rng = random.Random(1)
        base, cap = 0.1, 2.0
        for attempt in range(14):
            env = _envelope(attempt, base, cap)
            for _ in range(200):
                d = backoff_with_jitter(attempt, base, cap, rng=rng)
                assert 0.5 * env <= d < env

    def test_cap_bounds_late_attempts(self):
        rng = random.Random(2)
        for attempt in (6, 20, 63, 1000):
            d = backoff_with_jitter(attempt, 0.1, 2.0, rng=rng)
            assert d < 2.0  # 2**attempt must not outrun the cap

    def test_negative_attempt_clamps_to_base(self):
        rng = random.Random(3)
        d = backoff_with_jitter(-5, 0.1, 2.0, rng=rng)
        assert 0.05 <= d < 0.1

    def test_seeded_schedule_is_reproducible(self):
        """Determinism for tests is the rng parameter's whole job:
        same seed, same schedule — different seeds decorrelate."""
        sched = [Backoff(0.1, 2.0, rng=random.Random(7)).next()
                 for _ in range(1)]
        a = Backoff(0.1, 2.0, rng=random.Random(7))
        b = Backoff(0.1, 2.0, rng=random.Random(7))
        sa = [a.next() for _ in range(10)]
        sb = [b.next() for _ in range(10)]
        assert sa == sb
        assert sa[0] == sched[0]
        c = Backoff(0.1, 2.0, rng=random.Random(8))
        assert [c.next() for _ in range(10)] != sa

    def test_herd_desynchronizes(self):
        """16 pacers with distinct seeds: no two share a schedule —
        the property that spreads a thundering herd."""
        scheds = []
        for seed in range(16):
            bo = Backoff(0.1, 2.0, rng=random.Random(seed))
            scheds.append(tuple(bo.next() for _ in range(6)))
        assert len(set(scheds)) == 16

    def test_reset_returns_to_the_base_envelope(self):
        bo = Backoff(0.1, 2.0, rng=random.Random(9))
        for _ in range(8):
            bo.next()
        assert bo.attempt == 8
        bo.reset()
        assert bo.attempt == 0 and bo.last_delay == 0.0
        assert bo.next() < 0.1  # first-attempt envelope again
        st = bo.state()
        assert st["base_s"] == 0.1 and st["attempt"] == 1


class TestReconnectStorm:
    def test_membership_watchers_survive_a_server_restart(self):
        """The storm: every steering tier in a fleet holds the
        FleetMembership prefix watch through ONE kvserver. The server
        dies and restarts; each client reconnects on its own jittered
        schedule, re-registers the watch, and the resync snapshot
        hands it the member churn it missed — no watcher is left
        gapped, no watcher needs a manual re-subscribe. Seeded rng,
        event-gated throughout (queue timeouts, not sleeps)."""
        random.seed(0xB0FF)  # module-rng draws inside the clients
        store = KVStore()
        gw = {n: FleetMembership(store, name=n, ttl_s=600.0)
              for n in ("gw0", "gw1", "gw2")}
        gw["gw0"].join()
        srv = KVServer(store=store, host="127.0.0.1", port=0).start()
        port = srv.port
        clients, queues, cancels = [], [], []
        try:
            for i in range(5):
                c = RemoteKVStore("127.0.0.1", port,
                                  reconnect_backoff=(0.05, 0.2),
                                  reconnect_timeout=10.0)
                clients.append(c)
                q = queue.Queue()
                queues.append(q)
                initial, cancel = FleetMembership(
                    c, name=f"steer{i}").watch_members(q.put)
                cancels.append(cancel)
                assert initial == ["gw0"]

            gw["gw1"].join()
            for q in queues:
                assert "gw1" in q.get(timeout=5)

            # the storm: one server death under every watcher at once;
            # churn happens while the fleet is away
            srv.close()
            gw["gw1"].leave()
            gw["gw2"].join()
            srv = KVServer(store=store, host="127.0.0.1",
                           port=port).start()

            # every client must converge on the post-outage truth via
            # its re-registered watch (resync or the next event)
            want = ["gw0", "gw2"]
            for i, (c, q) in enumerate(zip(clients, queues)):
                seen = None
                while seen != want:
                    seen = q.get(timeout=15)
                assert sorted(FleetMembership(
                    c, name=f"steer{i}").members()) == want

            # and the stream is LIVE again, not just resynced
            gw["gw1"].join()
            for q in queues:
                names = q.get(timeout=5)
                while "gw1" not in names:
                    names = q.get(timeout=5)
        finally:
            for cancel in cancels:
                cancel()
            for c in clients:
                c.close()
            srv.close()
