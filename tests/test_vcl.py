"""VCL socket-shim tests: real loopback connections filtered by session
rules (the ld_preload/iperf suite analog, over localhost instead of
pods)."""

import threading

import pytest

from vpp_tpu.hoststack import RuleAction, RuleScope, SessionRule, SessionRuleEngine
from vpp_tpu.hoststack.session_rules import GLOBAL_NS
from vpp_tpu.hoststack.vcl import HostStackApp, PolicyDenied
from vpp_tpu.pipeline.vector import ip4

LOOP = ip4("127.0.0.1")


def deny_connect_rule(ns, rmt_port=0):
    return SessionRule(
        scope=int(RuleScope.LOCAL), appns_index=ns, transport_proto=6,
        lcl_net=0, lcl_plen=0, rmt_net=LOOP, rmt_plen=32,
        lcl_port=0, rmt_port=rmt_port, action=int(RuleAction.DENY),
    )


def echo_server(app):
    srv = app.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen()
    port = srv.getsockname()[1]

    def serve():
        try:
            conn, _ = srv.accept()
            conn.send(conn.recv(64))
            conn.close()
        except OSError:
            pass

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    return srv, port, t


def test_allowed_connect_end_to_end():
    engine = SessionRuleEngine(capacity=64)
    server_app = HostStackApp(engine, appns_index=2)
    client_app = HostStackApp(engine, appns_index=1)
    srv, port, t = echo_server(server_app)
    with client_app.socket() as c:
        c.settimeout(10)
        c.connect(("127.0.0.1", port))
        c.send(b"ping")
        assert c.recv(64) == b"ping"
    srv.close()


def test_denied_connect_never_reaches_server():
    engine = SessionRuleEngine(capacity=64)
    client_app = HostStackApp(engine, appns_index=1)
    engine.apply(add=[deny_connect_rule(ns=1)])
    with client_app.socket() as c:
        with pytest.raises(PolicyDenied):
            c.connect(("127.0.0.1", 1))
    # other namespaces unaffected
    other = HostStackApp(engine, appns_index=9)
    srv, port, t = echo_server(other)
    with other.socket() as c:
        c.settimeout(10)
        c.connect(("127.0.0.1", port))
    srv.close()


def test_denied_accept_closes_peer_and_keeps_listening():
    engine = SessionRuleEngine(capacity=64)
    server_app = HostStackApp(engine, appns_index=2)
    srv = server_app.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen()
    port = srv.getsockname()[1]

    # GLOBAL rules filter accepts: deny peers with src port == their
    # bound port unknown; instead deny everything, then allow nothing →
    # accept() should close the first conn; we then allow and retry.
    engine.apply(add=[SessionRule(
        scope=int(RuleScope.GLOBAL), appns_index=GLOBAL_NS,
        transport_proto=6, lcl_net=LOOP, lcl_plen=32,
        rmt_net=0, rmt_plen=0, lcl_port=port, rmt_port=0,
        action=int(RuleAction.DENY),
    )])

    results = []

    def serve():
        srv.sock.settimeout(30)
        try:
            conn, peer = srv.accept()
            results.append(("accepted", peer))
            conn.close()
        except OSError as e:
            results.append(("err", e))

    t = threading.Thread(target=serve, daemon=True)
    t.start()

    import socket as s

    # first client: denied at accept → its connection gets closed
    c1 = s.socket()
    c1.settimeout(10)
    c1.connect(("127.0.0.1", port))
    # the server should close it (recv returns b"" on clean close/reset)
    c1.settimeout(10)
    try:
        got = c1.recv(16)
        assert got == b""
    except ConnectionError:
        pass
    c1.close()
    assert not results, "denied peer must not be accepted"

    # permit: flip the rule and the next client is accepted
    engine.flush()
    c2 = s.socket()
    c2.settimeout(10)
    c2.connect(("127.0.0.1", port))
    t.join(timeout=30)
    assert results and results[0][0] == "accepted"
    c2.close()
    srv.close()


def test_connect_batch_mixed_verdicts():
    """One engine batch admits a whole wave of connects; denied
    addresses come back as None without touching the server."""
    engine = SessionRuleEngine(capacity=64)
    server_app = HostStackApp(engine, appns_index=2)
    client_app = HostStackApp(engine, appns_index=1)

    srv = server_app.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen()
    port = srv.getsockname()[1]
    # deny a port nobody listens on; the live port stays allowed
    engine.apply(add=[deny_connect_rule(ns=1, rmt_port=port + 1)])

    served = []

    def serve():
        try:
            while True:
                conn, _ = srv.accept()
                served.append(conn)
        except OSError:
            pass

    t = threading.Thread(target=serve, daemon=True)
    t.start()

    wave = [("127.0.0.1", port), ("127.0.0.1", port + 1),
            ("127.0.0.1", port), ("127.0.0.1", port + 1)]
    socks = client_app.connect_batch(wave)
    assert [s is not None for s in socks] == [True, False, True, False]
    for s in socks:
        if s is not None:
            s.close()
    srv.close()


def test_accept_batch_mixed_verdicts():
    """The server-side twin of connect_batch: one engine batch admits a
    wave of pending inbound connections; denied peers are closed."""
    import socket as socket_mod

    from vpp_tpu.hoststack.vcl import _ip_int

    engine = SessionRuleEngine(capacity=64)
    server_app = HostStackApp(engine, appns_index=2)
    srv = server_app.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(16)
    port = srv.getsockname()[1]
    # GLOBAL scope: allow only source port 39991 toward this listener,
    # deny everything else inbound
    engine.apply(add=[
        SessionRule(scope=int(RuleScope.GLOBAL), appns_index=-1,
                    transport_proto=6, lcl_net=_ip_int("127.0.0.1"),
                    lcl_plen=32, rmt_net=0, rmt_plen=0,
                    lcl_port=port, rmt_port=39991,
                    action=int(RuleAction.ALLOW)),
        SessionRule(scope=int(RuleScope.GLOBAL), appns_index=-1,
                    transport_proto=6, lcl_net=0, lcl_plen=0,
                    rmt_net=0, rmt_plen=0, lcl_port=0, rmt_port=0,
                    action=int(RuleAction.DENY)),
    ])

    good = socket_mod.socket()
    good.bind(("127.0.0.1", 39991))
    good.connect(("127.0.0.1", port))
    bad = socket_mod.socket()
    bad.connect(("127.0.0.1", port))

    admitted = []
    for _ in range(50):
        admitted += srv.accept_batch(max_n=8, first_timeout=0.05)
        if admitted:
            break
    assert len(admitted) == 1
    fconn, peer = admitted[0]
    assert peer[1] == 39991
    fconn.send(b"hi")
    assert good.recv(16) == b"hi"
    # the denied peer was closed by the wave
    bad.settimeout(2)
    assert bad.recv(16) == b""
    for s in (good, bad, fconn):
        s.close()
    srv.close()
