"""Differential suite for the million-route LPM FIB (ISSUE 15).

Pins ops/lpm.py (per-length binary-search planes), the shared ECMP
resolver (ops/fib.py), the per-length incremental churn path
(pipeline/tables.py) and the fib_impl selection ladder against an
INDEPENDENT NumPy per-packet oracle — reimplemented here from the spec
(longest match, lowest slot on ties, the session hash family for the
member pick), never by calling the device kernels.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from vpp_tpu.ops.fib import fib_lookup_dense, ip4_lookup
from vpp_tpu.ops.lpm import LPM_PAD, fib_lookup_lpm, lpm_field
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig, TableBuilder
from vpp_tpu.pipeline.vector import (
    FLAG_VALID,
    Disposition,
    PacketVector,
    ip4,
)

M32 = (1 << 32) - 1


def _mask_of(plen: int) -> int:
    return (M32 ^ ((1 << (32 - plen)) - 1)) if plen else 0


def np_flow_mix(src, dst, sport, dport, proto):
    """Independent reimplementation of the session 5-tuple hash family
    (the ECMP member-pick contract, docs/ROUTING.md) — uint32 wrap
    semantics spelled out by hand."""
    src = np.asarray(src, np.uint64)
    dst = np.asarray(dst, np.uint64)
    ports = ((np.asarray(sport, np.uint64) << 16)
             | (np.asarray(dport, np.uint64) & 0xFFFF)) & M32
    proto = np.asarray(proto, np.uint64)
    h = (src * 0x9E3779B1) & M32
    h ^= (dst * 0x85EBCA77) & M32
    h ^= (ports * 0xC2B2AE3D) & M32
    h ^= (proto * 0x27D4EB2F) & M32
    h ^= h >> 15
    h = (h * 0x2545F491) & M32
    h ^= h >> 13
    return h.astype(np.uint32)


class NumpyLpmOracle:
    """Per-packet longest-prefix-match + ECMP resolve over a staged
    TableBuilder, straight from the route arrays."""

    def __init__(self, b: TableBuilder):
        self.plen = np.asarray(b.fib_plen).copy()
        self.pfx = np.asarray(b.fib_prefix).copy()
        self.mask = np.asarray(b.fib_mask).copy()
        self.tx_if = np.asarray(b.fib_tx_if).copy()
        self.disp = np.asarray(b.fib_disp).copy()
        self.nh = np.asarray(b.fib_next_hop).copy()
        self.node = np.asarray(b.fib_node_id).copy()
        self.snat = np.asarray(b.fib_snat).copy()
        self.grp = np.asarray(b.fib_grp).copy()
        self.grp_nh = np.asarray(b.fib_grp_nh).copy()
        self.grp_tx = np.asarray(b.fib_grp_tx_if).copy()
        self.grp_node = np.asarray(b.fib_grp_node).copy()
        self.grp_n = np.asarray(b.fib_grp_n).copy()

    def lookup_one(self, src, dst, sport, dport, proto):
        best_slot, best_len = -1, -1
        for s in range(len(self.plen)):
            L = int(self.plen[s])
            if L < 0:
                continue
            if (dst & _mask_of(L)) == int(self.pfx[s]) and L > best_len:
                best_slot, best_len = s, L
        if best_slot < 0:
            return dict(matched=False, tx_if=-1,
                        disp=int(Disposition.DROP), next_hop=0,
                        node_id=-1, snat=False, grp=-1, way=0)
        s = best_slot
        g = int(self.grp[s])
        ways = self.grp_nh.shape[1]
        if g >= 0:
            if int(self.grp_n[g]) == 0:
                # empty group fails closed as a no-route miss
                return dict(matched=False, tx_if=-1,
                            disp=int(Disposition.DROP), next_hop=0,
                            node_id=-1, snat=False, grp=-1, way=0)
            w = int(np_flow_mix(src, dst, sport, dport, proto)) \
                & (ways - 1)
            return dict(matched=True, tx_if=int(self.grp_tx[g, w]),
                        disp=int(self.disp[s]),
                        next_hop=int(self.grp_nh[g, w]),
                        node_id=int(self.grp_node[g, w]),
                        snat=bool(self.snat[s]), grp=g, way=w)
        return dict(matched=True, tx_if=int(self.tx_if[s]),
                    disp=int(self.disp[s]), next_hop=int(self.nh[s]),
                    node_id=int(self.node[s]), snat=bool(self.snat[s]),
                    grp=-1, way=0)

    def lookup(self, pkts: PacketVector):
        src = np.asarray(pkts.src_ip)
        dst = np.asarray(pkts.dst_ip)
        sp = np.asarray(pkts.sport)
        dp_ = np.asarray(pkts.dport)
        pr = np.asarray(pkts.proto)
        rows = [self.lookup_one(int(src[i]), int(dst[i]), int(sp[i]),
                                int(dp_[i]), int(pr[i]))
                for i in range(len(dst))]
        return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}


def assert_fib_equal(res, oracle_out):
    np.testing.assert_array_equal(np.asarray(res.matched),
                                  oracle_out["matched"])
    np.testing.assert_array_equal(np.asarray(res.tx_if),
                                  oracle_out["tx_if"])
    np.testing.assert_array_equal(np.asarray(res.disp),
                                  oracle_out["disp"])
    np.testing.assert_array_equal(
        np.asarray(res.next_hop).astype(np.int64),
        oracle_out["next_hop"].astype(np.int64))
    np.testing.assert_array_equal(np.asarray(res.node_id),
                                  oracle_out["node_id"])
    np.testing.assert_array_equal(np.asarray(res.snat),
                                  oracle_out["snat"])
    np.testing.assert_array_equal(np.asarray(res.grp),
                                  oracle_out["grp"])


# every prefix length this suite stages (restricting the populated-
# length tuple keeps the compiled LPM kernels at ~14 unrolled lengths
# instead of 33 — pure tier-1 compile-time budget, zero semantics)
_TEST_PLENS = (0, 8, 10, 12, 16, 18, 20, 22, 23, 24, 28, 30, 31, 32)


def _cfg(fib_slots=256, **kw):
    kw.setdefault("fib_lpm_plen_caps",
                  tuple(fib_slots if L in _TEST_PLENS else 0
                        for L in range(33)))
    # the two-tier dispatcher doubles every compiled program and this
    # suite never exercises session-hit traffic — plain chain only
    # (budget; the fastpath x LPM interplay rides the shared fib_fn,
    # already pinned by the step factory's composition)
    kw.setdefault("fastpath", False)
    return DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=16,
        fib_slots=fib_slots, sess_slots=64, nat_mappings=2,
        nat_backends=4, **kw)


# weighted length mix shaped like a BGP feed tail
_LENGTHS = [0, 8, 10, 12, 16, 18, 20, 22, 23, 24, 28, 30, 32]
_WEIGHTS = [1, 1, 1, 2, 4, 3, 4, 6, 5, 20, 2, 1, 4]


def _random_table(seed: int, n_routes: int, fib_slots: int,
                  ecmp_groups: int = 0) -> TableBuilder:
    rng = np.random.default_rng(seed)
    b = TableBuilder(_cfg(fib_slots=fib_slots, fib_impl="lpm",
                          fib_ecmp_groups=ecmp_groups,
                          fib_ecmp_ways=4))
    if ecmp_groups:
        for g in range(ecmp_groups):
            members = [(int(rng.integers(1, M32)),
                        int(rng.integers(0, 8)),
                        int(rng.integers(-1, 3)))
                       for _ in range(int(rng.integers(1, 5)))]
            b.set_nh_group(g, members)
    p = np.asarray(_WEIGHTS, float) / sum(_WEIGHTS)
    for i in range(n_routes):
        L = int(rng.choice(_LENGTHS, p=p))
        addr = int(rng.integers(0, 1 << 32)) & _mask_of(L)
        disp = int(rng.choice([int(Disposition.LOCAL),
                               int(Disposition.REMOTE),
                               int(Disposition.HOST),
                               int(Disposition.DROP)],
                              p=[0.4, 0.4, 0.1, 0.1]))
        group = (int(rng.integers(0, ecmp_groups))
                 if ecmp_groups and rng.random() < 0.25 else None)
        b.add_route(f"{addr >> 24 & 255}.{addr >> 16 & 255}."
                    f"{addr >> 8 & 255}.{addr & 255}/{L}",
                    tx_if=int(rng.integers(0, 8)),
                    disposition=Disposition(disp),
                    next_hop=int(rng.integers(0, 1 << 32)),
                    node_id=int(rng.integers(-1, 4)),
                    snat=bool(rng.random() < 0.2),
                    slot=i, group=group)
    return b


def _probe_traffic(b: TableBuilder, rng, n_pkts: int) -> PacketVector:
    """Half the packets aim INSIDE staged prefixes (guaranteed hits,
    overlapping covers exercised), half are uniform random."""
    live = np.nonzero(np.asarray(b.fib_plen) >= 0)[0]
    dst = rng.integers(0, 1 << 32, n_pkts).astype(np.uint32)
    take = rng.random(n_pkts) < 0.5
    picks = rng.choice(live, n_pkts)
    inside = (np.asarray(b.fib_prefix)[picks]
              | (dst & ~np.asarray(b.fib_mask)[picks])).astype(np.uint32)
    dst = np.where(take, inside, dst)
    return PacketVector(
        src_ip=jnp.asarray(rng.integers(0, 1 << 32, n_pkts)
                           .astype(np.uint32)),
        dst_ip=jnp.asarray(dst),
        proto=jnp.asarray(rng.choice([1, 6, 17], n_pkts)
                          .astype(np.int32)),
        sport=jnp.asarray(rng.integers(0, 65536, n_pkts)
                          .astype(np.int32)),
        dport=jnp.asarray(rng.integers(0, 65536, n_pkts)
                          .astype(np.int32)),
        ttl=jnp.full((n_pkts,), 64, jnp.int32),
        pkt_len=jnp.full((n_pkts,), 256, jnp.int32),
        rx_if=jnp.zeros((n_pkts,), jnp.int32),
        flags=jnp.full((n_pkts,), FLAG_VALID, jnp.int32),
    )


@pytest.mark.parametrize("seed,n_routes,fib_slots",
                         [(3, 40, 64), (7, 200, 256), (11, 900, 1024)])
def test_lpm_matches_oracle_and_dense(seed, n_routes, fib_slots):
    """Seeded random tables at multiple scales: the LPM lookup, the
    dense lookup and the NumPy oracle agree bit-exactly on every
    FibResult field (ECMP member picks included)."""
    b = _random_table(seed, n_routes, fib_slots, ecmp_groups=4)
    t = b.to_device()
    rng = np.random.default_rng(seed + 1)
    pkts = _probe_traffic(b, rng, 512)
    oracle = NumpyLpmOracle(b).lookup(pkts)
    assert_fib_equal(fib_lookup_lpm(t, pkts), oracle)
    assert_fib_equal(fib_lookup_dense(t, pkts), oracle)


def test_default_host_and_overlapping_covers():
    """/0 default + nested /8 /16 /24 /32 covers of one address:
    longest populated length wins at every nesting step, and deleting
    the middle cover re-resolves to the next one down."""
    b = TableBuilder(_cfg(fib_impl="lpm"))
    b.add_route("0.0.0.0/0", 1, Disposition.REMOTE, node_id=1)
    b.add_route("10.0.0.0/8", 2, Disposition.REMOTE)
    b.add_route("10.1.0.0/16", 3, Disposition.REMOTE)
    b.add_route("10.1.1.0/24", 4, Disposition.LOCAL)
    b.add_route("10.1.1.7/32", 5, Disposition.LOCAL)
    b.add_route("255.255.255.255/32", 6, Disposition.HOST)

    def tx(dst):
        t = b.to_device()
        pk = PacketVector(
            src_ip=jnp.asarray(np.uint32([ip4("1.2.3.4")])),
            dst_ip=jnp.asarray(np.uint32([ip4(dst)])),
            proto=jnp.asarray(np.int32([6])),
            sport=jnp.asarray(np.int32([4000])),
            dport=jnp.asarray(np.int32([80])),
            ttl=jnp.asarray(np.int32([64])),
            pkt_len=jnp.asarray(np.int32([64])),
            rx_if=jnp.asarray(np.int32([0])),
            flags=jnp.asarray(np.int32([FLAG_VALID])),
        )
        return int(np.asarray(fib_lookup_lpm(t, pk).tx_if)[0])

    assert tx("10.1.1.7") == 5
    assert tx("10.1.1.9") == 4
    assert tx("10.1.9.9") == 3
    assert tx("10.9.9.9") == 2
    assert tx("9.9.9.9") == 1
    assert tx("255.255.255.255") == 6   # the pad-value address, live
    assert b.del_route("10.1.1.0/24")
    assert tx("10.1.1.9") == 3          # next cover down
    assert b.del_route("255.255.255.255/32")
    assert tx("255.255.255.255") == 1   # falls to the default


def test_duplicate_prefix_keeps_lowest_slot():
    """Two slots staging the same (prefix, length): both impls must
    resolve the LOWER slot (the dense argmax tie-break)."""
    b = TableBuilder(_cfg(fib_impl="lpm"))
    b.add_route("10.1.1.0/24", 3, Disposition.LOCAL, slot=2)
    b.add_route("10.1.1.0/24", 7, Disposition.LOCAL, slot=9)
    t = b.to_device()
    dst = jnp.asarray(np.uint32([ip4("10.1.1.5")]))
    assert int(np.asarray(ip4_lookup(t, dst).tx_if)[0]) == 3


def test_ecmp_stickiness_under_member_churn():
    """Flow→member assignment: adding a member only remaps flows whose
    way was reassigned; removing one never remaps flows on surviving
    members (the sticky way-fill contract of set_nh_group)."""
    b = TableBuilder(_cfg(fib_impl="lpm", fib_ecmp_groups=2,
                          fib_ecmp_ways=8))
    A, B, C = (ip4("1.0.0.1"), 1, -1), (ip4("1.0.0.2"), 2, -1), \
        (ip4("1.0.0.3"), 3, -1)
    b.set_nh_group(0, [A, B])
    b.add_route("10.0.0.0/8", 1, Disposition.REMOTE, group=0)
    rng = np.random.default_rng(5)
    pkts = _probe_traffic(b, rng, 256)

    hit0 = np.asarray(
        fib_lookup_lpm(b.to_device(), pkts).matched)

    def members(bld):
        res = fib_lookup_lpm(bld.to_device(), pkts)
        return np.asarray(res.next_hop)[hit0].copy(), \
            np.asarray(res.way)[hit0].copy()

    nh1, way1 = members(b)
    assert set(int(x) for x in np.unique(nh1)) == {A[0], B[0]}
    # spread: both members serve a nontrivial share of the hashed flows
    assert min((nh1 == A[0]).sum(), (nh1 == B[0]).sum()) > 16
    assign1 = list(b.nh_groups[0]["assign"])
    b.set_nh_group(0, [A, B, C])
    assign2 = list(b.nh_groups[0]["assign"])
    nh2, way2 = members(b)
    np.testing.assert_array_equal(way1, way2)  # hash never moves
    for w in range(8):
        if assign2[w] == assign1[w]:
            same = way1 == w
            np.testing.assert_array_equal(nh1[same], nh2[same])
    # removing B: flows on A/C ways keep their member exactly
    b.set_nh_group(0, [A, C])
    assign3 = list(b.nh_groups[0]["assign"])
    nh3, _ = members(b)
    for w in range(8):
        if assign3[w] == assign2[w]:
            same = way2 == w
            np.testing.assert_array_equal(nh2[same], nh3[same])
    assert B[0] not in set(np.unique(nh3))


def test_bulk_loader_validates_group_range():
    """add_routes_np enforces the same ECMP-group range checks as
    add_route — an out-of-range id would be clipped on-device onto a
    REAL group and silently forward via its members."""
    b = TableBuilder(_cfg(fib_impl="lpm", fib_ecmp_groups=4))
    nets = np.array([ip4("10.0.0.0")], np.uint32)
    plens = np.array([8], np.int32)
    with pytest.raises(ValueError, match="0..3"):
        b.add_routes_np(nets, plens, tx_if=1,
                        disp=int(Disposition.REMOTE), group=7)
    b2 = TableBuilder(_cfg(fib_impl="lpm"))
    with pytest.raises(ValueError, match="fib_ecmp_groups"):
        b2.add_routes_np(nets, plens, tx_if=1,
                         disp=int(Disposition.REMOTE), group=0)


def test_empty_group_fails_closed():
    """A route pointing at an unconfigured/deleted group resolves as a
    no-route miss, never a zero next-hop forward."""
    b = TableBuilder(_cfg(fib_impl="lpm", fib_ecmp_groups=2))
    b.set_nh_group(1, [(ip4("1.0.0.1"), 1, -1)])
    b.add_route("10.0.0.0/8", 1, Disposition.REMOTE, group=1)
    rng = np.random.default_rng(9)
    pkts = _probe_traffic(b, rng, 64)
    t = b.to_device()
    assert bool(np.asarray(fib_lookup_lpm(t, pkts).matched).any())
    assert b.del_nh_group(1)
    t = b.to_device()
    res = fib_lookup_lpm(t, pkts)
    in_grp = (np.asarray(pkts.dst_ip) >> 24) == 10
    assert not np.asarray(res.matched)[in_grp].any()
    assert_fib_equal(res, NumpyLpmOracle(b).lookup(pkts))


class TestIncrementalChurn:
    def test_flap_reships_only_touched_length_plane(self):
        """A /24 flap re-ships fib_lpm_p24 + the count vector + a
        bounded slot blob; every other length plane (and the ECMP
        tables) keeps device-array identity."""
        b = _random_table(21, 600, 2048)
        t1 = b.to_device()
        # flap one /24: withdraw + re-announce
        slot = int(np.nonzero(np.asarray(b.fib_plen) == 24)[0][0])
        pfx = int(b.fib_prefix[slot])
        pfx_s = (f"{pfx >> 24 & 255}.{pfx >> 16 & 255}."
                 f"{pfx >> 8 & 255}.{pfx & 255}/24")
        assert b.del_route(pfx_s)
        b.add_route(pfx_s, 5, Disposition.REMOTE, slot=slot)
        t2 = b.to_device(sessions=t1)
        up = b.fib_upload
        # the touched plane + count vector (+ the hint rows when the
        # plane is big enough to carry them) — and NOTHING else
        assert "fib_lpm_p24" in up["fields"]
        assert set(up["fields"]) <= {"fib_lpm_p24", "fib_lpm_cnt",
                                     "fib_lpm_hint"}
        assert up["blob_bytes"] > 0     # per-slot rows went as a blob
        assert up["blob_bytes"] < 64 * 1024
        for length in range(33):
            if length == 24:
                continue
            assert getattr(t2, lpm_field(length)) \
                is getattr(t1, lpm_field(length)), length
        assert t2.fib_grp_nh is t1.fib_grp_nh
        # the churned table still matches the oracle
        rng = np.random.default_rng(22)
        pkts = _probe_traffic(b, rng, 256)
        assert_fib_equal(fib_lookup_lpm(t2, pkts),
                         NumpyLpmOracle(b).lookup(pkts))

    def test_noop_commit_ships_nothing(self):
        b = _random_table(23, 100, 256)
        t1 = b.to_device()
        t2 = b.to_device(sessions=t1)
        for length in range(33):
            assert getattr(t2, lpm_field(length)) \
                is getattr(t1, lpm_field(length))
        assert t2.fib_prefix is t1.fib_prefix
        assert t2.fib_grp is t1.fib_grp

    def test_churn_parity_vs_scratch(self):
        """After a sequence of adds/deletes/group churn, the
        incremental planes equal a scratch rebuild bit-for-bit."""
        b = _random_table(31, 200, 512, ecmp_groups=2)
        b.to_device()
        rng = np.random.default_rng(32)
        for _ in range(30):
            if rng.random() < 0.4:
                live = np.nonzero(np.asarray(b.fib_plen) >= 0)[0]
                s = int(rng.choice(live))
                L = int(b.fib_plen[s])
                pfx = int(b.fib_prefix[s])
                b.del_route(f"{pfx >> 24 & 255}.{pfx >> 16 & 255}."
                            f"{pfx >> 8 & 255}.{pfx & 255}/{L}")
            else:
                L = int(rng.choice(_LENGTHS))
                addr = int(rng.integers(0, 1 << 32)) & _mask_of(L)
                free = np.nonzero(np.asarray(b.fib_plen) < 0)[0]
                b.add_route(
                    f"{addr >> 24 & 255}.{addr >> 16 & 255}."
                    f"{addr >> 8 & 255}.{addr & 255}/{L}",
                    int(rng.integers(0, 8)), Disposition.LOCAL,
                    slot=int(free[0]))
        b._restage_lpm()
        scratch = TableBuilder(b.config)
        for arr in ("fib_prefix", "fib_mask", "fib_plen", "fib_tx_if",
                    "fib_disp", "fib_next_hop", "fib_node_id",
                    "fib_snat", "fib_grp"):
            getattr(scratch, arr)[...] = getattr(b, arr)
        for g, e in b.nh_groups.items():
            scratch.set_nh_group(g, e["members"])
        scratch._lpm_dirty_lens = set(range(33))
        scratch._restage_lpm()
        for length in range(33):
            np.testing.assert_array_equal(
                b.lpm_planes[lpm_field(length)],
                scratch.lpm_planes[lpm_field(length)], str(length))
        np.testing.assert_array_equal(b.lpm_cnt, scratch.lpm_cnt)

    def test_state_snapshot_restore_roundtrip(self):
        """Builder rollback (the txn path) restores routes, planes and
        groups; the next to_device serves pre-mutation lookups."""
        b = _random_table(41, 80, 128, ecmp_groups=2)
        rng = np.random.default_rng(42)
        pkts = _probe_traffic(b, rng, 128)
        before = NumpyLpmOracle(b).lookup(pkts)
        snap = b.state_snapshot()
        b.add_route("77.0.0.0/8", 7, Disposition.LOCAL)
        b.set_nh_group(0, [(ip4("9.9.9.9"), 1, -1)])
        assert b.del_route("77.0.0.0/8") or True
        b.state_restore(snap)
        t = b.to_device()
        assert_fib_equal(fib_lookup_lpm(t, pkts), before)
        assert_fib_equal(fib_lookup_dense(t, pkts), before)


def test_plane_overflow_regates_to_dense():
    """A length over its configured cap makes lpm_ok() false and the
    auto ladder falls back to dense — loudly visible, never a wrong
    lookup."""
    caps = [0] * 25
    caps[24] = 2
    caps[0] = 1
    dp = Dataplane(_cfg(fib_impl="auto", fib_lpm_min_routes=1,
                        fib_lpm_plen_caps=tuple(caps)))
    dp.builder.add_route("10.1.1.0/24", 1, Disposition.LOCAL)
    dp.builder.add_route("10.1.2.0/24", 1, Disposition.LOCAL)
    dp.swap()
    assert dp.fib_impl == "lpm"
    dp.builder.add_route("10.1.3.0/24", 1, Disposition.LOCAL)
    dp.swap()
    assert not dp.builder.lpm_ok()
    assert dp.fib_impl == "dense"
    # a length with cap 0 is not served either
    dp.builder.add_route("10.0.0.0/8", 1, Disposition.REMOTE)
    dp.swap()
    assert dp.fib_impl == "dense"


def test_mem_cap_disables_lpm():
    """auto honors fib_lpm_mem_mb: a cap below the plane bytes keeps
    the builder off LPM entirely (zero-width placeholders)."""
    dp = Dataplane(_cfg(fib_slots=4096, fib_impl="auto",
                        fib_lpm_mem_mb=0))
    assert not dp.builder.lpm_enabled
    assert dp.tables.fib_lpm_p24.shape[1] == 0
    dp.builder.add_route("10.1.1.0/24", 1, Disposition.LOCAL)
    dp.swap()
    assert dp.fib_impl == "dense"
    # the route histogram must not depend on LPM staging: dense-only
    # configs still report their per-length counts
    snap = dp.fib_snapshot()
    assert snap["by_length"] == {24: 1} and snap["routes"] == 1


@pytest.mark.jit_budget(4)
def test_auto_regates_at_swap_with_bounded_compiles():
    """fib_impl auto flips dense→lpm at the route threshold across
    epoch swaps; the flip costs exactly the two step programs (one per
    rung) and churn AFTER the flip compiles nothing new — the
    zero-new-step-form contract (only the fib_impl key)."""
    from vpp_tpu.cli import DebugCLI
    from vpp_tpu.stats.collector import StatsCollector

    dp = Dataplane(_cfg(fib_impl="auto", fib_lpm_min_routes=8))
    up = dp.add_uplink()
    dp.builder.add_route("10.1.1.0/24", up, Disposition.LOCAL)
    dp.swap()
    assert dp.fib_impl == "dense"
    pkts = _probe_traffic(dp.builder, np.random.default_rng(2), 64)
    pkts = pkts._replace(rx_if=jnp.full(pkts.rx_if.shape, up,
                                        jnp.int32))
    dp.process(pkts)
    for i in range(10):
        dp.builder.add_route(f"10.{i + 2}.0.0/16", up,
                             Disposition.LOCAL)
    dp.swap()
    assert dp.fib_impl == "lpm"
    dp.process(pkts)
    coll = StatsCollector(dp)
    coll.publish()
    page = coll.registry.render("/stats")
    assert 'vpp_tpu_fib_impl{impl="lpm"} 1' in page
    assert 'vpp_tpu_fib_impl{impl="dense"} 0' in page
    assert "impl lpm" in DebugCLI(dp).run("show fib")
    # churn at the same rung: swap + process retraces nothing (the
    # jit_budget marker enforces the ceiling at test end)
    dp.builder.add_route("10.99.0.0/16", up, Disposition.LOCAL)
    dp.swap()
    assert dp.fib_impl == "lpm"
    dp.process(pkts)


def test_end_to_end_lpm_equals_dense_dataplane():
    """Full fused-pipeline differential: identical config except the
    fib_impl knob must produce identical dispositions, drop causes and
    counters over mixed traffic (the classifier-knob test's twin)."""
    rng = np.random.default_rng(51)
    rows = []
    for i in range(96):
        rows.append({"src": f"172.16.{i % 8}.{1 + i % 250}",
                     "dst": rng.choice(
                         ["10.1.1.2", "10.1.2.9", "10.9.1.1",
                          "8.8.8.8", "10.1.1.255"]),
                     "proto": 6, "sport": 1024 + i,
                     "dport": int(rng.choice([80, 443, 8080]))})
    out = {}
    for knob in ("dense", "lpm"):
        dp = Dataplane(_cfg(fib_impl=knob, fib_ecmp_groups=2,
                            fib_ecmp_ways=4))
        up = dp.add_uplink()
        dp.builder.set_nh_group(0, [(ip4("192.168.0.2"), up, 1),
                                    (ip4("192.168.0.3"), up, 2)])
        dp.builder.add_route("10.1.1.0/24", up, Disposition.LOCAL)
        dp.builder.add_route("10.1.0.0/16", up, Disposition.REMOTE,
                             node_id=1)
        dp.builder.add_route("10.0.0.0/8", up, Disposition.REMOTE,
                             group=0)
        dp.builder.add_route("0.0.0.0/0", up, Disposition.DROP)
        dp.swap()
        if knob == "lpm":
            assert dp.fib_impl == "lpm"
        from vpp_tpu.pipeline.vector import make_packet_vector

        pkts = make_packet_vector(
            [dict(r, rx_if=up) for r in rows], n=len(rows))
        res = dp.process(pkts)
        out[knob] = (np.asarray(res.disp), np.asarray(res.drop_cause),
                     np.asarray(res.tx_if), np.asarray(res.next_hop),
                     int(res.stats.tx), int(res.stats.drop_no_route))
    for a, bb in zip(out["dense"], out["lpm"]):
        np.testing.assert_array_equal(a, bb)


def test_ecmp_accounting_plane_and_family():
    """Forwarded ECMP packets land in the carried [G, W] accounting
    plane (exact conservation vs StepStats.tx on a pure-ECMP batch)
    and render on the labelled vpp_tpu_fib_ecmp_packets family."""
    from vpp_tpu.stats.collector import StatsCollector

    dp = Dataplane(_cfg(fib_impl="lpm", fib_ecmp_groups=2,
                        fib_ecmp_ways=4))
    up = dp.add_uplink()
    dp.builder.set_nh_group(0, [(ip4("192.168.0.2"), up, 1),
                                (ip4("192.168.0.3"), up, 2)])
    dp.builder.add_route("10.0.0.0/8", up, Disposition.REMOTE, group=0)
    dp.swap()
    from vpp_tpu.pipeline.vector import make_packet_vector

    rng = np.random.default_rng(61)
    pkts = make_packet_vector(
        [{"src": f"172.16.0.{1 + i % 250}", "dst": f"10.2.3.{i % 250}",
          "proto": 17, "sport": int(rng.integers(1024, 65000)),
          "dport": 53, "rx_if": up} for i in range(64)], n=64)
    res = dp.process(pkts)
    fwd = int(res.stats.tx)
    assert fwd == 64
    plane = np.asarray(dp.tables.fib_ecmp_c)
    assert int(plane.sum()) == fwd
    assert int(plane[0].sum()) == fwd
    snap = dp.fib_snapshot()
    assert int(snap["ecmp_c"].sum()) == fwd
    coll = StatsCollector(dp)
    coll.publish()
    page = coll.registry.render("/stats")
    # BOTH members render as their own series (full identity labels —
    # the two members here share nothing, but members differing only
    # in node_id must not collapse either)
    assert 'member="192.168.0.2:if' in page
    assert 'member="192.168.0.3:if' in page
    # swap carries the plane by reference (state, like telemetry)
    before = dp.tables.fib_ecmp_c
    dp.builder.add_route("10.7.0.0/16", up, Disposition.LOCAL)
    dp.swap()
    assert dp.tables.fib_ecmp_c is before


def test_show_fib_summary_filter_and_scale_guard():
    """`show fib` leads with the summary header; big tables render no
    per-slot rows without a prefix filter; the filter matches with one
    vectorized pass (covering + covered routes)."""
    from vpp_tpu.cli import DebugCLI

    b_dp = Dataplane(_cfg(fib_slots=1024, fib_impl="lpm"))
    cli = DebugCLI(b_dp)
    for i in range(600):
        b_dp.builder.add_route(f"10.{i // 250}.{i % 250}.0/24", 1,
                               Disposition.LOCAL, slot=i)
    b_dp.builder.add_route("0.0.0.0/0", 2, Disposition.REMOTE,
                           slot=1000)
    b_dp.swap()
    out = cli.run("show fib")
    assert "impl lpm" in out and "routes 601" in out
    assert "/24:600" in out
    assert "prefix filter" in out          # too big to list
    assert "10.1.17.0/24" not in out
    filt = cli.run("show fib 10.1.17.0/24")
    assert "10.1.17.0/24" in filt
    assert "0.0.0.0/0" in filt             # the covering default shows
    assert "10.1.18.0/24" not in filt
    assert "bad prefix filter" in cli.run("show fib bogus")


def test_pad_address_and_planes_inert():
    """The 255.255.255.255 pad value is still a servable address, and
    pad rows past each plane's live count never match (the lint
    invariant, exercised through the kernel)."""
    b = TableBuilder(_cfg(fib_impl="lpm"))
    b.add_route("255.255.255.254/31", 4, Disposition.LOCAL)
    t = b.to_device()
    assert int(b.lpm_cnt[31]) == 1
    plane = b.lpm_planes[lpm_field(31)]
    assert (plane[0, 1:] == LPM_PAD).all()
    dst = jnp.asarray(np.uint32([ip4("255.255.255.255"),
                                 ip4("255.255.255.253")]))
    res = ip4_lookup(t, dst)
    assert bool(np.asarray(res.matched)[0])
    assert not bool(np.asarray(res.matched)[1])


def test_tables_lint_lpm_invariants():
    """tools/lint.py --tables runs the LPM structure pass from tier-1
    (strict sort, pad inertness, group membership)."""
    import importlib.util
    import sys
    from pathlib import Path

    tools = Path(__file__).resolve().parents[1] / "tools"
    if str(tools) not in sys.path:
        sys.path.insert(0, str(tools))
    spec = importlib.util.spec_from_file_location(
        "vppt_lint", tools / "lint.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from analysis.registries import _lpm_plane_problems

    assert _lpm_plane_problems() == []
