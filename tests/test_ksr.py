"""KSR tests: models round-trip, reflector events, mark-and-sweep resync.

Mirrors the reference's per-reflector tests (plugins/ksr/*_test.go) using
the mock list-watch seam.
"""

from vpp_tpu.ksr import MockK8sListWatch, make_standard_reflectors
from vpp_tpu.ksr import model as m
from vpp_tpu.kvstore import Broker, KVStore


def make_env():
    store = KVStore()
    broker = Broker(store, "/vnf-agent/contiv-ksr/")
    sources = {}
    registry = make_standard_reflectors(broker, sources)
    return store, broker, sources, registry


def sample_pod(name="web-1", ip="10.1.1.2"):
    return m.Pod(
        name=name,
        namespace="default",
        labels={"app": "web"},
        ip_address=ip,
        host_ip_address="192.168.16.1",
        containers=[m.Container(name="c", ports=[m.ContainerPort(name="http", container_port=8080)])],
    )


def test_model_round_trip():
    pod = sample_pod()
    again = m.Pod.from_dict(pod.to_dict())
    assert again == pod
    assert again.containers[0].ports[0].container_port == 8080

    pol = m.Policy(
        name="allow-web",
        namespace="default",
        pods=m.LabelSelector(match_labels={"app": "web"}),
        policy_type=m.POLICY_INGRESS,
        ingress_rules=[
            m.PolicyRule(
                ports=[m.PolicyPort(protocol="TCP", port=8080)],
                peers=[
                    m.PolicyPeer(
                        pods=m.LabelSelector(
                            match_expressions=[m.LabelExpression(key="tier", operator=m.IN, values=["fe"])]
                        )
                    ),
                    m.PolicyPeer(ip_block=m.IPBlock(cidr="10.0.0.0/8", except_cidrs=["10.1.0.0/16"])),
                ],
            )
        ],
    )
    again = m.Policy.from_dict(pol.to_dict())
    assert again == pol
    assert again.ingress_rules[0].peers[1].ip_block.cidr == "10.0.0.0/8"


def test_label_selector_semantics():
    sel = m.LabelSelector(
        match_labels={"app": "web"},
        match_expressions=[
            m.LabelExpression(key="tier", operator=m.NOT_IN, values=["db"]),
            m.LabelExpression(key="zone", operator=m.EXISTS),
        ],
    )
    assert sel.matches({"app": "web", "zone": "a"})
    assert not sel.matches({"app": "web"})  # zone missing
    assert not sel.matches({"app": "web", "zone": "a", "tier": "db"})
    assert m.LabelSelector().matches({"anything": "x"})  # empty matches all


def test_key_scheme():
    pod = sample_pod()
    assert pod.key() == "k8s/pod/web-1/namespace/default"
    assert m.Node(name="n1").key() == "k8s/node/n1"
    parsed = m.parse_key(pod.key())
    assert parsed == {"type": "pod", "name": "web-1", "namespace": "default"}


def test_reflector_event_flow():
    store, broker, sources, registry = make_env()
    registry.start_all()
    assert registry.all_synced()

    pod = sample_pod()
    sources["pod"].add("default/web-1", pod)
    assert broker.get(pod.key()) == pod.to_dict()

    pod2 = sample_pod(ip="10.1.1.9")
    sources["pod"].update("default/web-1", pod2)
    assert broker.get(pod.key())["ip_address"] == "10.1.1.9"

    sources["pod"].delete("default/web-1")
    assert broker.get(pod.key()) is None

    stats = registry.stats()["pod"]
    assert (stats["adds"], stats["updates"], stats["deletes"]) == (1, 1, 1)


def test_mark_and_sweep_resync():
    store, broker, sources, registry = make_env()
    # Stale item in the store from a previous life; live item in "K8s".
    stale = sample_pod(name="gone")
    broker.put(stale.key(), stale.to_dict())
    live = sample_pod(name="alive")
    sources["pod"] = MockK8sListWatch()
    sources["pod"].add("default/alive", live)

    registry2 = make_standard_reflectors(broker, sources)
    registry2.start_all()
    assert broker.get(stale.key()) is None          # swept
    assert broker.get(live.key()) == live.to_dict()  # marked


def test_events_paused_until_synced():
    store, broker, sources, registry = make_env()
    r = registry.reflectors["pod"]
    r.start()
    r.stop_data_store_updates()
    pod = sample_pod()
    sources["pod"].add("default/web-1", pod)
    assert broker.get(pod.key()) is None  # write suppressed while unsynced
    r.resync()
    assert broker.get(pod.key()) == pod.to_dict()  # resync catches up
