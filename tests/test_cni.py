"""CNI subsystem tests: server Add/Delete semantics, persistence resync,
the unix-socket transport, and the shim's CNI-spec translation.

Reference model: plugins/contiv/remote_cni_server_test.go (server logic
against a tracked backend) + cmd/contiv-cni/contiv_cni_test.go.
"""

import json


from vpp_tpu.cni import (
    CNIRequest,
    ContainerIndex,
    RemoteCNIServer,
    ResultCode,
)
from vpp_tpu.cni import shim
from vpp_tpu.cni.transport import CNITransportServer, cni_call
from vpp_tpu.ipam.ipam import IPAM
from vpp_tpu.kvstore.store import Broker, KVStore
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector


def make_server(store=None):
    dp = Dataplane(DataplaneConfig(sess_slots=256))
    dp.add_uplink()
    broker = Broker(store, "agent1/") if store is not None else None
    ipam = IPAM(node_id=1, broker=broker)
    index = ContainerIndex(broker)
    srv = RemoteCNIServer(dp, ipam, index)
    srv.set_ready()
    return srv, dp, ipam


def add_req(cid, name, ns="default"):
    return CNIRequest(
        container_id=cid,
        netns=f"/proc/ns/{cid}",
        extra_args={"K8S_POD_NAME": name, "K8S_POD_NAMESPACE": ns},
    )


def test_add_wires_pod_and_traffic_flows():
    srv, dp, ipam = make_server()
    r1 = srv.add(add_req("c1", "client"))
    r2 = srv.add(add_req("c2", "server"))
    assert r1.result == ResultCode.OK and r2.result == ResultCode.OK
    ip1 = r1.interfaces[0].ip_addresses[0].address.split("/")[0]
    ip2 = r2.interfaces[0].ip_addresses[0].address.split("/")[0]
    assert ip1 != ip2
    assert r1.routes[0].dst == "0.0.0.0/0"
    assert r1.interfaces[0].ip_addresses[0].gateway == str(ipam.pod_gateway_ip())

    # semantic check: pod1 → pod2 traffic is actually forwarded
    if1 = dp.pod_if[("default", "client")]
    if2 = dp.pod_if[("default", "server")]
    res = dp.process(make_packet_vector(
        [dict(src=ip1, dst=ip2, proto=6, sport=1234, dport=80, rx_if=if1)]
    ))
    assert int(res.disp[0]) == int(Disposition.LOCAL)
    assert int(res.tx_if[0]) == if2


def test_add_not_ready_returns_try_again():
    srv, dp, _ = make_server()
    srv._ready = False
    r = srv.add(add_req("c1", "p1"))
    assert r.result == ResultCode.TRY_AGAIN


def test_add_is_idempotent():
    srv, dp, _ = make_server()
    r1 = srv.add(add_req("c1", "p1"))
    r2 = srv.add(add_req("c1", "p1"))
    assert r2.result == ResultCode.OK
    assert r1.interfaces[0].ip_addresses == r2.interfaces[0].ip_addresses
    assert len(dp.pod_if) == 1


def test_delete_releases_everything():
    srv, dp, ipam = make_server()
    r = srv.add(add_req("c1", "p1"))
    ip = r.interfaces[0].ip_addresses[0].address.split("/")[0]
    assert srv.delete(CNIRequest(container_id="c1")).result == ResultCode.OK
    assert ("default", "p1") not in dp.pod_if
    assert ipam.assigned_count() == 0
    # packet to the released IP no longer routes locally
    res = dp.process(make_packet_vector(
        [dict(src="10.1.1.9", dst=ip, proto=6, sport=1, dport=2, rx_if=1)]
    ))
    assert int(res.disp[0]) != int(Disposition.LOCAL)
    # second delete is a no-op success (CNI DEL idempotency)
    assert srv.delete(CNIRequest(container_id="c1")).result == ResultCode.OK


def test_sandbox_recreation_survives_stale_delete():
    """ADD with a new container ID for an existing pod replaces the old
    sandbox; kubelet's late DEL of the old ID must not cut connectivity."""
    srv, dp, ipam = make_server()
    srv.add(add_req("c-old", "p1"))
    r2 = srv.add(add_req("c-new", "p1"))
    assert r2.result == ResultCode.OK
    assert ipam.assigned_count() == 1  # old IP released
    ip2 = r2.interfaces[0].ip_addresses[0].address.split("/")[0]

    # stale DEL of the old sandbox: harmless no-op
    assert srv.delete(CNIRequest(container_id="c-old")).result == ResultCode.OK
    assert ("default", "p1") in dp.pod_if
    if_idx = dp.pod_if[("default", "p1")]
    res = dp.process(make_packet_vector(
        [dict(src="10.9.9.9", dst=ip2, proto=6, sport=1, dport=2,
              rx_if=dp.uplink_if)]
    ))
    assert int(res.disp[0]) == int(Disposition.LOCAL)
    assert int(res.tx_if[0]) == if_idx


def test_failed_add_releases_ip():
    srv, dp, ipam = make_server()
    # exhaust the interface table so add_pod_interface raises
    dp._free_ifs = []
    r = srv.add(add_req("c1", "p1"))
    assert r.result == ResultCode.ERROR
    assert ipam.assigned_count() == 0, "partial Add must not leak the IP"


def test_pod_change_notifications_fire():
    events = []
    srv, dp, _ = make_server()
    srv.on_pod_change = lambda: events.append(1)
    srv.add(add_req("c1", "p1"))
    srv.delete(CNIRequest(container_id="c1"))
    assert len(events) == 2


def test_restart_resync_rewires_pods():
    store = KVStore()
    srv, dp, _ = make_server(store)
    r = srv.add(add_req("c1", "p1"))
    ip = r.interfaces[0].ip_addresses[0].address.split("/")[0]

    # "restart": fresh dataplane + server over the same store
    srv2, dp2, ipam2 = make_server(store)
    n = srv2.resync()
    assert n == 1
    assert ("default", "p1") in dp2.pod_if
    # IPAM must remember the assignment across restart (persisted broker)
    assert ipam2.assigned_count() == 1
    if_idx = dp2.pod_if[("default", "p1")]
    res = dp2.process(make_packet_vector(
        [dict(src="10.9.9.9", dst=ip, proto=6, sport=1, dport=2,
              rx_if=dp2.uplink_if)]
    ))
    assert int(res.disp[0]) == int(Disposition.LOCAL)
    assert int(res.tx_if[0]) == if_idx
    # the restarted server can still answer the original container
    r2 = srv2.add(add_req("c1", "p1"))
    assert r2.interfaces[0].ip_addresses[0].address.startswith(ip)


def test_transport_roundtrip(tmp_path):
    srv, dp, _ = make_server()
    sock = str(tmp_path / "cni.sock")
    ts = CNITransportServer(sock, srv.dispatch)
    ts.start()
    try:
        reply = cni_call(sock, "Add", add_req("c9", "podx").to_dict())
        assert reply["result"] == 0
        assert reply["interfaces"][0]["ip_addresses"][0]["address"].endswith("/32")
        reply = cni_call(sock, "Bogus", {"container_id": "c9"})
        assert reply["result"] == 1
    finally:
        ts.close()


def test_shim_add_del_flow(tmp_path):
    srv, dp, _ = make_server()
    sock = str(tmp_path / "cni.sock")
    ts = CNITransportServer(sock, srv.dispatch)
    ts.start()
    try:
        env = {
            "CNI_COMMAND": "ADD",
            "CNI_CONTAINERID": "c42",
            "CNI_NETNS": "/proc/42/ns/net",
            "CNI_IFNAME": "eth0",
            "CNI_ARGS": "IgnoreUnknown=1;K8S_POD_NAME=web;K8S_POD_NAMESPACE=prod",
        }
        conf = json.dumps({"cniVersion": "0.3.1", "grpcServer": sock}).encode()
        out, code = shim.run(env, conf)
        assert code == 0
        result = json.loads(out)
        assert result["cniVersion"] == "0.3.1"
        assert result["ips"][0]["address"].endswith("/32")
        assert result["ips"][0]["version"] == "4"
        assert result["interfaces"][0]["name"] == "eth0"
        assert ("prod", "web") in dp.pod_if

        env["CNI_COMMAND"] = "DEL"
        out, code = shim.run(env, conf)
        assert code == 0 and out == ""
        assert ("prod", "web") not in dp.pod_if
    finally:
        ts.close()


def test_shim_version_and_errors(tmp_path):
    out, code = shim.run({"CNI_COMMAND": "VERSION"}, b"")
    assert code == 0
    assert "0.3.1" in json.loads(out)["supportedVersions"]

    out, code = shim.run({"CNI_COMMAND": "ADD"}, b"")
    assert code == 1
    assert json.loads(out)["code"] == shim.ERR_INVALID_ENV

    # agent unreachable → ERR_IO
    env = {"CNI_COMMAND": "ADD", "CNI_CONTAINERID": "c1"}
    conf = json.dumps({"grpcServer": str(tmp_path / "nope.sock")}).encode()
    out, code = shim.run(env, conf)
    assert code == 1
    assert json.loads(out)["code"] == shim.ERR_IO


def test_shim_try_again_when_agent_not_ready(tmp_path):
    srv, dp, _ = make_server()
    srv._ready = False
    sock = str(tmp_path / "cni.sock")
    ts = CNITransportServer(sock, srv.dispatch)
    ts.start()
    try:
        env = {"CNI_COMMAND": "ADD", "CNI_CONTAINERID": "c1"}
        conf = json.dumps({"grpcServer": sock}).encode()
        out, code = shim.run(env, conf)
        assert code == 1
        assert json.loads(out)["code"] == shim.ERR_TRY_AGAIN
    finally:
        ts.close()
