"""Declarative config transactions: record/apply/journal/replay
(the vpp-agent localclient txn + api-trace analog; VERDICT r2 L2 gap).
"""

from __future__ import annotations

import ipaddress

import pytest

from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig, InterfaceType
from vpp_tpu.pipeline.txn import (
    ConfigTxn,
    TxnJournal,
    apply_txn,
    rule_from_dict,
    rule_to_dict,
)
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector


RULES = [
    ContivRule(action=Action.PERMIT,
               src_network=ipaddress.ip_network("172.16.0.0/12"),
               protocol=Protocol.TCP, dest_port=80),
    ContivRule(action=Action.DENY,
               dest_network=ipaddress.ip_network("10.1.1.0/24"),
               protocol=Protocol.UDP),
    ContivRule(action=Action.DENY),
]


def test_rule_serialization_roundtrip():
    for r in RULES:
        assert rule_from_dict(rule_to_dict(r)) == r


def make_txn() -> ConfigTxn:
    txn = ConfigTxn(label="bootstrap")
    txn.set_interface(2, InterfaceType.UPLINK, apply_global=True)
    txn.set_interface(3, InterfaceType.POD)
    txn.add_route("10.1.1.3/32", 3, Disposition.LOCAL)
    txn.add_route("10.2.0.0/16", 2, Disposition.REMOTE,
                  next_hop=0xC0A81E02, node_id=2)
    txn.set_global_table(RULES)
    txn.set_nat_mapping(0, ext_ip=0x0A600001, ext_port=80, proto=6,
                        backends=[(0x0A010103, 8080, 1)], boff=0)
    txn.set_snat_ip(0xC0A81001)
    return txn


def verdicts(dp):
    r = dp.process(make_packet_vector([
        {"src": "172.16.5.5", "dst": "10.1.1.3", "proto": 6, "sport": 9,
         "dport": 80, "rx_if": 2},
        {"src": "9.9.9.9", "dst": "10.1.1.3", "proto": 17, "sport": 9,
         "dport": 53, "rx_if": 2},
        {"src": "10.1.1.3", "dst": "10.2.0.9", "proto": 6, "sport": 9,
         "dport": 443, "rx_if": 3},
    ]))
    return [Disposition(int(r.disp[i])) for i in range(3)]


def test_apply_txn_is_one_epoch_and_enforces(tmp_path):
    dp = Dataplane(DataplaneConfig())
    journal = TxnJournal(str(tmp_path / "txns.jsonl"))
    e0 = dp.epoch
    epoch = apply_txn(dp, make_txn(), journal)
    assert epoch == e0 + 1              # all ops, ONE swap
    assert verdicts(dp) == [Disposition.LOCAL, Disposition.DROP,
                            Disposition.REMOTE]
    assert journal.applied == 1


def test_journal_replay_reproduces_config(tmp_path):
    path = str(tmp_path / "txns.jsonl")
    dp = Dataplane(DataplaneConfig())
    journal = TxnJournal(path)
    apply_txn(dp, make_txn(), journal)
    # a later incremental txn (policy narrowed)
    txn2 = ConfigTxn(label="narrow").set_global_table(
        [ContivRule(action=Action.DENY)]
    )
    apply_txn(dp, txn2, journal)
    want = verdicts(dp)

    # fresh dataplane on another "machine": replay the journal
    dp2 = Dataplane(DataplaneConfig())
    replayed = TxnJournal(path).replay(dp2.builder)
    assert replayed == 2
    dp2.swap()
    # uplink ingress now deny-all-TCP; pod-originated egress is not
    # globally classified (global table binds to apply_global ingress)
    assert verdicts(dp2) == want == [Disposition.DROP, Disposition.DROP,
                                     Disposition.REMOTE]


def test_unknown_op_rejected():
    with pytest.raises(ValueError):
        ConfigTxn()._record("format_disk")


def test_torn_trailing_journal_line_tolerated(tmp_path):
    """Crash mid-append (kill between write() and the page hitting
    disk) leaves a truncated trailing JSONL line: load()/replay() must
    tolerate it — counting it in ``torn_lines`` — instead of raising,
    or a single unclean shutdown would brick config recovery."""
    import json

    path = str(tmp_path / "torn.jsonl")
    dp = Dataplane(DataplaneConfig())
    journal = TxnJournal(path)
    apply_txn(dp, make_txn(), journal)
    txn2 = ConfigTxn(label="second").add_route(
        "10.3.0.0/16", 2, Disposition.REMOTE)
    apply_txn(dp, txn2, journal)
    # simulate the torn append: truncate the last line mid-JSON
    with open(path) as f:
        raw = f.read()
    torn = raw.rstrip("\n")[:-17] + "\n"
    with open(path, "w") as f:
        f.write(torn)

    reloaded = TxnJournal(path)
    txns = reloaded.load()
    assert [t.label for t in txns] == ["bootstrap"]
    assert reloaded.torn_lines == 1

    # replay still works, applying only the intact prefix
    dp2 = Dataplane(DataplaneConfig())
    replayer = TxnJournal(path)
    assert replayer.replay(dp2.builder) == 1
    assert replayer.torn_lines == 1
    dp2.swap()
    assert verdicts(dp2) == verdicts_of_first_txn_only(dp)

    # an intact journal reports zero torn lines
    clean = TxnJournal(path)
    with open(path, "w") as f:
        f.write(raw.splitlines()[0] + "\n")
    clean.load()
    assert clean.torn_lines == 0

    # `show config-history` surfaces the tolerated torn line
    from vpp_tpu.cli import DebugCLI

    dp3 = Dataplane(DataplaneConfig())
    with open(path, "w") as f:
        f.write(torn)
    dp3.journal = TxnJournal(path)
    out = DebugCLI(dp3).run("show config-history")
    assert "torn trailing line" in out
    assert "bootstrap" in out

    # mid-file corruption (valid entries AFTER the bad line) is NOT
    # tolerated: that's real damage, not a crash tail
    lines = raw.splitlines()
    with open(path, "w") as f:
        f.write(lines[0][:-10] + "\n" + lines[1] + "\n")
    with pytest.raises(json.JSONDecodeError):
        TxnJournal(path).load()


def verdicts_of_first_txn_only(dp_reference):
    """The expected verdict set after only the bootstrap txn: same as
    the full journal here because txn2 only adds an unrelated route."""
    return verdicts(dp_reference)


def test_load_tail_entries_is_bounded_and_tolerant(tmp_path):
    """The /debug/txns serving path: last-N entries from a bounded
    tail read — a window-cut first line is discarded, a torn trailing
    line tolerated, and only ``limit`` entries come back."""
    import json

    path = str(tmp_path / "big.jsonl")
    with open(path, "w") as f:
        for i in range(50):
            f.write(json.dumps({"t": float(i), "epoch": i,
                                "label": f"txn-{i}", "ops": []}) + "\n")
    journal = TxnJournal(path)
    tail = journal.load_tail_entries(5)
    assert [e["epoch"] for e in tail] == [45, 46, 47, 48, 49]
    assert journal.torn_lines == 0
    # a max_bytes window smaller than the file drops the cut first line
    # but still returns complete trailing entries
    windowed = journal.load_tail_entries(100, max_bytes=200)
    assert windowed and [e["epoch"] for e in windowed][-1] == 49
    assert all(isinstance(e["epoch"], int) for e in windowed)
    # torn trailing line: tolerated + counted, prefix served
    with open(path) as f:
        raw = f.read()
    with open(path, "w") as f:
        f.write(raw[:-20])
    tail = journal.load_tail_entries(5)
    assert journal.torn_lines == 1
    assert [e["epoch"] for e in tail] == [44, 45, 46, 47, 48]


def test_failed_txn_rolls_back_completely(tmp_path):
    """All-or-nothing: a failing op mid-txn must leave no trace — the
    next unrelated commit can never publish a half-applied txn."""
    dp = Dataplane(DataplaneConfig(fib_slots=4))
    journal = TxnJournal(str(tmp_path / "j.jsonl"))
    ok = ConfigTxn(label="ok")
    ok.set_interface(2, InterfaceType.UPLINK, apply_global=True)
    ok.set_interface(3, InterfaceType.POD)
    ok.add_route("10.1.1.3/32", 3, Disposition.LOCAL)
    ok.set_global_table([ContivRule(action=Action.PERMIT,
                                    protocol=Protocol.ANY)])
    apply_txn(dp, ok, journal)
    want = verdicts(dp)
    epoch = dp.epoch

    bad = ConfigTxn(label="bad")
    bad.set_global_table([ContivRule(action=Action.DENY)])  # staged first
    for i in range(8):  # ...then overflows the 4-slot FIB
        bad.add_route(f"10.9.{i}.0/24", 2, Disposition.REMOTE)
    with pytest.raises(ValueError):
        apply_txn(dp, bad, journal)
    assert dp.epoch == epoch            # nothing published
    assert journal.applied == 1         # nothing journaled
    # an unrelated follow-up commit must NOT leak the staged DENY table
    apply_txn(dp, ConfigTxn(label="unrelated").add_route(
        "10.7.0.0/24", 2, Disposition.REMOTE), journal)
    assert verdicts(dp) == want
    # and the journal replays to the same verdicts (bad txn absent)
    dp2 = Dataplane(DataplaneConfig(fib_slots=4))
    TxnJournal(journal.path).replay(dp2.builder)
    dp2.swap()
    assert verdicts(dp2) == want


def test_live_agent_journal_replays_to_identical_tables(tmp_path):
    """The api-trace e2e (VERDICT r3 Next #7): a REAL agent run — base
    config, CNI adds, a rendered NetworkPolicy, a service with
    endpoints, node events — journals every NB commit transparently;
    replaying the journal onto a fresh builder reproduces the exact
    table state the live agent enforced."""
    import numpy as np

    from vpp_tpu.cmd import AgentConfig, ContivAgent
    from vpp_tpu.cmd.ksr_main import KsrAgent
    from vpp_tpu.cni.model import CNIRequest
    from vpp_tpu.ksr import model as m
    from vpp_tpu.kvstore.store import KVStore
    from vpp_tpu.pipeline.dataplane import Dataplane

    journal_path = str(tmp_path / "txn-journal.jsonl")
    store = KVStore()
    ksr = KsrAgent(store=store, serve_http=False)
    ksr.start()
    agent = ContivAgent(
        AgentConfig(node_name="jrnl-node", serve_http=False,
                    txn_journal_path=journal_path),
        store=store,
    )
    agent.start()

    def add_pod(cid, name):
        reply = agent.cni_server.add(CNIRequest(
            container_id=cid,
            extra_args={"K8S_POD_NAME": name,
                        "K8S_POD_NAMESPACE": "default"}))
        assert reply.result == 0
        return reply.interfaces[0].ip_addresses[0].address.split("/")[0]

    ip_web = add_pod("c-web", "web")
    ip_db = add_pod("c-db", "db")
    for name, ip, labels in (("web", ip_web, {"app": "web"}),
                             ("db", ip_db, {"app": "db"})):
        ksr.sources[m.Pod.TYPE].add(
            f"default/{name}",
            m.Pod(name=name, namespace="default", labels=labels,
                  ip_address=ip))
    ksr.sources[m.Namespace.TYPE].add(
        "default", m.Namespace(name="default", labels={}))
    ksr.sources[m.Policy.TYPE].add("default/db-policy", m.Policy(
        name="db-policy", namespace="default",
        pods=m.LabelSelector(match_labels={"app": "db"}),
        policy_type=m.POLICY_INGRESS,
        ingress_rules=[m.PolicyRule(
            ports=[m.PolicyPort(protocol="TCP", port=5432)],
            peers=[m.PolicyPeer(
                pods=m.LabelSelector(match_labels={"app": "web"}))],
        )]))
    ksr.sources[m.Service.TYPE].add("default/db-svc", m.Service(
        name="db-svc", namespace="default", cluster_ip="10.96.0.77",
        ports=[m.ServicePort(name="pg", protocol="TCP", port=5432,
                             target_port="pg")]))
    ksr.sources[m.Endpoints.TYPE].add("default/db-svc", m.Endpoints(
        name="db-svc", namespace="default",
        subsets=[m.EndpointSubset(
            addresses=[m.EndpointAddress(ip=ip_db, node_name="jrnl-node")],
            ports=[m.EndpointPort(name="pg", port=5432, protocol="TCP")],
        )]))
    # one pod deleted too: the journal must carry del ops
    agent.cni_server.delete(CNIRequest(container_id="c-web"))

    live = {k: np.copy(v)
            for k, v in agent.dataplane.builder.host_arrays().items()}
    n_journaled = agent.dataplane.journal.applied
    assert n_journaled >= 5, "base + cni x3 + policy + service commits"
    agent.close()

    # Replay onto a FRESH dataplane (same sizing config, no agent).
    from vpp_tpu.pipeline.txn import TxnJournal

    fresh = Dataplane(agent.config.dataplane)
    n = TxnJournal(journal_path).replay(fresh.builder)
    assert n == n_journaled
    replayed = fresh.builder.host_arrays()
    for field, arr in live.items():
        np.testing.assert_array_equal(
            arr, replayed[field], err_msg=f"field {field} diverged"
        )


def test_cli_config_history_and_replay(tmp_path):
    """`show config-history` tails the journal; `config replay` restores
    a journal into a live dataplane as one transaction."""
    from vpp_tpu.cli import DebugCLI
    from vpp_tpu.ir.rule import Action, ContivRule
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig, InterfaceType

    cfg = DataplaneConfig(max_tables=2, max_rules=8, max_global_rules=8,
                          max_ifaces=8, fib_slots=16, sess_slots=64,
                          nat_mappings=2, nat_backends=4)
    path = str(tmp_path / "j.jsonl")
    dp = Dataplane(cfg)
    dp.enable_journal(path)
    dp.builder.txn_label = "seed"
    dp.builder.set_interface(1, InterfaceType.POD)
    dp.builder.add_route("10.9.0.2/32", 1, Disposition.LOCAL)
    dp.builder.set_global_table([ContivRule(action=Action.PERMIT)])
    dp.swap()

    cli = DebugCLI(dp)
    out = cli.run("show config-history")
    assert "seed" in out and "1 txns journaled" in out

    dp2 = Dataplane(cfg)
    cli2 = DebugCLI(dp2)
    out = cli2.run(f"config replay {path}")
    assert "replayed 1 txns" in out
    import numpy as np

    a = dp.builder.host_arrays()
    b = dp2.builder.host_arrays()
    for field in a:
        np.testing.assert_array_equal(a[field], b[field], err_msg=field)
    # a dataplane without a journal reports that cleanly
    assert "not enabled" in cli2.run("show config-history")
