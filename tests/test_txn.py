"""Declarative config transactions: record/apply/journal/replay
(the vpp-agent localclient txn + api-trace analog; VERDICT r2 L2 gap).
"""

from __future__ import annotations

import ipaddress

import pytest

from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig, InterfaceType
from vpp_tpu.pipeline.txn import (
    ConfigTxn,
    TxnJournal,
    apply_txn,
    rule_from_dict,
    rule_to_dict,
)
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector


RULES = [
    ContivRule(action=Action.PERMIT,
               src_network=ipaddress.ip_network("172.16.0.0/12"),
               protocol=Protocol.TCP, dest_port=80),
    ContivRule(action=Action.DENY,
               dest_network=ipaddress.ip_network("10.1.1.0/24"),
               protocol=Protocol.UDP),
    ContivRule(action=Action.DENY),
]


def test_rule_serialization_roundtrip():
    for r in RULES:
        assert rule_from_dict(rule_to_dict(r)) == r


def make_txn() -> ConfigTxn:
    txn = ConfigTxn(label="bootstrap")
    txn.set_interface(2, InterfaceType.UPLINK, apply_global=True)
    txn.set_interface(3, InterfaceType.POD)
    txn.add_route("10.1.1.3/32", 3, Disposition.LOCAL)
    txn.add_route("10.2.0.0/16", 2, Disposition.REMOTE,
                  next_hop=0xC0A81E02, node_id=2)
    txn.set_global_table(RULES)
    txn.set_nat_mapping(0, ext_ip=0x0A600001, ext_port=80, proto=6,
                        backends=[(0x0A010103, 8080, 1)], boff=0)
    txn.set_snat_ip(0xC0A81001)
    return txn


def verdicts(dp):
    r = dp.process(make_packet_vector([
        {"src": "172.16.5.5", "dst": "10.1.1.3", "proto": 6, "sport": 9,
         "dport": 80, "rx_if": 2},
        {"src": "9.9.9.9", "dst": "10.1.1.3", "proto": 17, "sport": 9,
         "dport": 53, "rx_if": 2},
        {"src": "10.1.1.3", "dst": "10.2.0.9", "proto": 6, "sport": 9,
         "dport": 443, "rx_if": 3},
    ]))
    return [Disposition(int(r.disp[i])) for i in range(3)]


def test_apply_txn_is_one_epoch_and_enforces(tmp_path):
    dp = Dataplane(DataplaneConfig())
    journal = TxnJournal(str(tmp_path / "txns.jsonl"))
    e0 = dp.epoch
    epoch = apply_txn(dp, make_txn(), journal)
    assert epoch == e0 + 1              # all ops, ONE swap
    assert verdicts(dp) == [Disposition.LOCAL, Disposition.DROP,
                            Disposition.REMOTE]
    assert journal.applied == 1


def test_journal_replay_reproduces_config(tmp_path):
    path = str(tmp_path / "txns.jsonl")
    dp = Dataplane(DataplaneConfig())
    journal = TxnJournal(path)
    apply_txn(dp, make_txn(), journal)
    # a later incremental txn (policy narrowed)
    txn2 = ConfigTxn(label="narrow").set_global_table(
        [ContivRule(action=Action.DENY)]
    )
    apply_txn(dp, txn2, journal)
    want = verdicts(dp)

    # fresh dataplane on another "machine": replay the journal
    dp2 = Dataplane(DataplaneConfig())
    replayed = TxnJournal(path).replay(dp2.builder)
    assert replayed == 2
    dp2.swap()
    # uplink ingress now deny-all-TCP; pod-originated egress is not
    # globally classified (global table binds to apply_global ingress)
    assert verdicts(dp2) == want == [Disposition.DROP, Disposition.DROP,
                                     Disposition.REMOTE]


def test_unknown_op_rejected():
    with pytest.raises(ValueError):
        ConfigTxn()._record("format_disk")


def test_failed_txn_rolls_back_completely(tmp_path):
    """All-or-nothing: a failing op mid-txn must leave no trace — the
    next unrelated commit can never publish a half-applied txn."""
    dp = Dataplane(DataplaneConfig(fib_slots=4))
    journal = TxnJournal(str(tmp_path / "j.jsonl"))
    ok = ConfigTxn(label="ok")
    ok.set_interface(2, InterfaceType.UPLINK, apply_global=True)
    ok.set_interface(3, InterfaceType.POD)
    ok.add_route("10.1.1.3/32", 3, Disposition.LOCAL)
    ok.set_global_table([ContivRule(action=Action.PERMIT,
                                    protocol=Protocol.ANY)])
    apply_txn(dp, ok, journal)
    want = verdicts(dp)
    epoch = dp.epoch

    bad = ConfigTxn(label="bad")
    bad.set_global_table([ContivRule(action=Action.DENY)])  # staged first
    for i in range(8):  # ...then overflows the 4-slot FIB
        bad.add_route(f"10.9.{i}.0/24", 2, Disposition.REMOTE)
    with pytest.raises(ValueError):
        apply_txn(dp, bad, journal)
    assert dp.epoch == epoch            # nothing published
    assert journal.applied == 1         # nothing journaled
    # an unrelated follow-up commit must NOT leak the staged DENY table
    apply_txn(dp, ConfigTxn(label="unrelated").add_route(
        "10.7.0.0/24", 2, Disposition.REMOTE), journal)
    assert verdicts(dp) == want
    # and the journal replays to the same verdicts (bad txn absent)
    dp2 = Dataplane(DataplaneConfig(fib_slots=4))
    TxnJournal(journal.path).replay(dp2.builder)
    dp2.swap()
    assert verdicts(dp2) == want
