"""Proxy-chained HTTP through policy at 10k rules — the nginx-istio
service-mesh scenario (VERDICT r4 Next #7).

Reference analog: tests/nginx-istio/nginx-envoy.yaml + BASELINE.md
config #5 — an HTTP client reaching nginx through an Envoy proxy, with
the mesh's policy plumbing between every hop. Here the chain is three
REAL python subprocesses that never import vpp_tpu, each interposed by
the LD_PRELOAD session shim (libvclshim.so) against one
VclAdmissionServer whose SessionRuleEngine holds a gen-policy-scale
10,240-rule set, shim configured FAIL-CLOSED:

    client --HTTP--> proxy --HTTP--> backend
      |connect:CLIENT ns    |connect:PROXY ns
      |accept: proxy port   |accept: backend port

Every arrow is two admission verdicts (connect on the client side of
the hop, accept on the server side) computed by the jitted rule
classify over the full rule set. The policy seam is load-bearing: the
client can ONLY reach the backend through the proxy, and revoking the
proxy's upstream permission breaks the chain live.
"""

from __future__ import annotations

import socket
import struct
import subprocess
import sys
import time

import pytest

from vpp_tpu.hoststack.admission import VclAdmissionServer
from vpp_tpu.hoststack.preload import vcl_env
from vpp_tpu.hoststack.scenarios import gen_policy_filler, proxy_chain_rules
from vpp_tpu.hoststack.session_rules import SessionRuleEngine

CLIENT_NS, PROXY_NS, BACKEND_NS = 11, 12, 13
N_FILLER = 10240


def ipi(a: str) -> int:
    return struct.unpack("!I", socket.inet_aton(a))[0]


LOOP = None  # set in fixture (ipi needs no jax; keep module import light)


BACKEND_CODE = r"""
import socket, sys
ls = socket.socket()
ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
ls.bind(("127.0.0.1", 0))
ls.listen(64)
print(ls.getsockname()[1], flush=True)
BODY = b"hello-from-backend\n"
RESP = (b"HTTP/1.1 200 OK\r\nContent-Length: %d\r\n"
        b"Connection: close\r\n\r\n" % len(BODY)) + BODY
while True:
    c, _ = ls.accept()
    try:
        buf = b""
        while b"\r\n\r\n" not in buf:
            d = c.recv(4096)
            if not d:
                break
            buf += d
        if buf:
            c.sendall(RESP)
    finally:
        c.close()
"""

PROXY_CODE = r"""
import socket, sys
upstream = ("127.0.0.1", int(sys.argv[1]))
ls = socket.socket()
ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
ls.bind(("127.0.0.1", 0))
ls.listen(64)
print(ls.getsockname()[1], flush=True)
while True:
    c, _ = ls.accept()
    try:
        buf = b""
        while b"\r\n\r\n" not in buf:
            d = c.recv(4096)
            if not d:
                break
            buf += d
        if not buf:
            continue
        try:
            u = socket.create_connection(upstream, timeout=10)
        except OSError:
            c.sendall(b"HTTP/1.1 502 Bad Gateway\r\n"
                      b"Content-Length: 0\r\nConnection: close\r\n\r\n")
            continue
        try:
            u.sendall(buf)
            while True:
                d = u.recv(4096)
                if not d:
                    break
                c.sendall(d)
        finally:
            u.close()
    finally:
        try:
            c.close()
        except OSError:
            pass
"""

CLIENT_CODE = r"""
import socket, sys
port = int(sys.argv[1])
s = socket.socket()
s.settimeout(15)
try:
    s.connect(("127.0.0.1", port))
except OSError:
    print("REFUSED")
    raise SystemExit(0)
s.sendall(b"GET / HTTP/1.1\r\nHost: t\r\nConnection: close\r\n\r\n")
buf = b""
try:
    while True:
        d = s.recv(4096)
        if not d:
            break
        buf += d
except OSError:
    pass
if not buf:
    print("EMPTY")
else:
    head, _, body = buf.partition(b"\r\n\r\n")
    print(head.split(b"\r\n")[0].decode(), body.decode().strip())
"""


@pytest.fixture(scope="module")
def mesh(tmp_path_factory):
    """Admission server + 10k-rule engine (shared scenario builders,
    vpp_tpu/hoststack/scenarios.py — the same rule shapes
    bench.proxy_chain_bench measures) + backend and proxy subprocesses
    under the fail-closed shim."""
    loop = ipi("127.0.0.1")
    engine = SessionRuleEngine(capacity=16384)
    engine.apply(add=gen_policy_filler(N_FILLER))
    path = str(tmp_path_factory.mktemp("vcl") / "vcl.sock")
    srv = VclAdmissionServer(engine, path).start()
    procs = []
    try:
        backend = subprocess.Popen(
            [sys.executable, "-c", BACKEND_CODE],
            env=vcl_env(path, appns_index=BACKEND_NS, fail_closed=True),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        procs.append(backend)
        bport = int(backend.stdout.readline())
        proxy = subprocess.Popen(
            [sys.executable, "-c", PROXY_CODE, str(bport)],
            env=vcl_env(path, appns_index=PROXY_NS, fail_closed=True),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        procs.append(proxy)
        pport = int(proxy.stdout.readline())

        chain = proxy_chain_rules(loop, CLIENT_NS, PROXY_NS, pport, bport)
        engine.apply(add=chain)
        yield engine, path, pport, bport, chain
    finally:
        # also covers PARTIAL setup failure (a subprocess that never
        # printed its port): whatever started is torn down
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=10)
        srv.stop()


def run_client(path, port, timeout=60):
    out = subprocess.run(
        [sys.executable, "-c", CLIENT_CODE, str(port)],
        env=vcl_env(path, appns_index=CLIENT_NS, fail_closed=True),
        capture_output=True, text=True, timeout=timeout)
    assert out.returncode == 0, out.stderr[-500:]
    return out.stdout.strip()


def test_http_through_proxy_chain(mesh):
    """The full chain serves: client -> proxy -> backend, four
    admission verdicts against the 10k-rule set per request chain."""
    engine, path, pport, bport, _ = mesh
    assert run_client(path, pport) == "HTTP/1.1 200 OK hello-from-backend"


def test_direct_backend_access_denied(mesh):
    """The mesh seam: the client's namespace has no permit for the
    backend port — bypassing the proxy must fail at connect()."""
    engine, path, pport, bport, _ = mesh
    assert run_client(path, bport) == "REFUSED"


def test_revoking_proxy_upstream_breaks_chain_live(mesh):
    """Policy update mid-flight: deleting the proxy->backend permit
    turns the chain into 502 (the proxy's own connect is refused);
    re-adding restores 200 — no process restarts anywhere."""
    engine, path, pport, bport, chain = mesh
    upstream_allow = chain[2]
    engine.apply(delete=[upstream_allow])
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            got = run_client(path, pport)
            if got == "HTTP/1.1 502 Bad Gateway":
                break
            time.sleep(0.2)
        assert got == "HTTP/1.1 502 Bad Gateway", got
    finally:
        engine.apply(add=[upstream_allow])
    assert run_client(path, pport) == "HTTP/1.1 200 OK hello-from-backend"
