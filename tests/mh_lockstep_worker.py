"""Worker for test_multihost lockstep-commit scenario (run directly).

Two JAX processes + one shared TCP kvstore: process 1 stages a
policy change on ITS node mid-run and requests a commit through the
store; the LockstepDriver's collective min-agreement makes both
processes publish the new epoch on the same tick, and traffic that was
flowing cross-process gets cut off cluster-wide.
"""

import json
import os
import sys

PROC_ID = int(sys.argv[1])
NUM_PROCS = int(sys.argv[2])
PORT = sys.argv[3]
KV_PORT = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from vpp_tpu.parallel.multihost import (  # noqa: E402
    LockstepDriver, MultiHostCluster, barrier, init_multihost,
)
from mh_common import (  # noqa: E402
    LOCKSTEP_N_NODES, lockstep_config, lockstep_deliveries,
    lockstep_frames, pod_ips, stage_full_mesh,
)
from vpp_tpu.ir.rule import Action, ContivRule  # noqa: E402
from vpp_tpu.kvstore.client import connect_store  # noqa: E402

init_multihost(f"127.0.0.1:{PORT}", NUM_PROCS, PROC_ID,
               heartbeat_timeout_s=600)

N_NODES = LOCKSTEP_N_NODES
cluster = MultiHostCluster(N_NODES, lockstep_config())
store = connect_store(f"tcp://127.0.0.1:{KV_PORT}")
# expire_every=3: tick 3 runs the collective session aging pass too
driver = LockstepDriver(cluster, store, expire_every=3)

pod_if = stage_full_mesh(cluster)

barrier("staged")
cluster.publish()

all_pod_ip = pod_ips(N_NODES)


def frames_for_tick(sport):
    return lockstep_frames(cluster, PROC_ID, all_pod_ip, pod_if, sport)


def deliveries(res):
    return lockstep_deliveries(cluster, PROC_ID, res)


verdict = {"proc": PROC_ID}

res = driver.tick(frames_for_tick(1000), n=8)
verdict["t1_delivered"] = deliveries(res)

# P1 stages a deny-all on ITS node 2 and asks the fleet to commit
if PROC_ID == 1:
    cluster.node(2).builder.set_global_table(
        [ContivRule(action=Action.DENY)])
    driver.request_commit()
barrier("change-requested")   # both processes have the request visible

res = driver.tick(frames_for_tick(1001), n=8)
verdict["t2_delivered"] = deliveries(res)
verdict["t2_epoch"] = cluster.epoch
if PROC_ID == 1:
    verdict["t2_acl_drops"] = int(
        cluster.local_rows(res.stats.drop_acl)[0])

res = driver.tick(frames_for_tick(1002), n=8)
verdict["t3_delivered"] = deliveries(res)
verdict["applied"] = driver.applied

barrier("done")
print("VERDICT " + json.dumps(verdict), flush=True)
