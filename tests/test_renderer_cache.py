"""Tests for the renderer cache: shared local tables, global table, diffs.

Scenario style mirrors the reference's renderer/cache/cache_test.go
(behavioral assertions on table sharing and minimal diffs).
"""

import ipaddress

from vpp_tpu.ir import Action, ContivRule, PodID, Protocol
from vpp_tpu.ir.table import TableType
from vpp_tpu.renderer.api import PodConfig
from vpp_tpu.renderer.cache import Orientation, RendererCache


def net(s):
    return ipaddress.ip_network(s)


POD1 = PodID("default", "pod1")
POD2 = PodID("default", "pod2")
POD3 = PodID("default", "pod3")

IP1 = net("10.1.1.1/32")
IP2 = net("10.1.1.2/32")
IP3 = net("10.1.1.3/32")


def ingress_allow_tcp80():
    """Typical K8s policy rendering: allow TCP:80 in, deny the rest."""
    return [
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP, dest_port=80),
        ContivRule(action=Action.DENY, protocol=Protocol.TCP),
        ContivRule(action=Action.DENY, protocol=Protocol.UDP),
    ]


def test_single_pod_ingress_table():
    cache = RendererCache(Orientation.INGRESS)
    txn = cache.new_txn()
    txn.update(POD1, PodConfig(pod_ip=IP1, ingress=ingress_allow_tcp80(), egress=[]))
    changes = txn.get_changes()
    # One new local table (global stays empty / allow-all).
    assert len(changes) == 1
    table = changes[0].table
    assert table.type == TableType.LOCAL
    assert POD1 in table.pods
    assert changes[0].previous_pods == set()
    assert table.num_of_rules > 0
    txn.commit()
    assert cache.get_all_pods() == {POD1}
    assert cache.get_isolated_pods() == {POD1}
    assert cache.get_local_table_by_pod(POD1) is not None
    assert cache.get_global_table().num_of_rules == 0


def test_identical_rule_sets_share_table():
    cache = RendererCache(Orientation.INGRESS)
    txn = cache.new_txn()
    txn.update(POD1, PodConfig(pod_ip=IP1, ingress=ingress_allow_tcp80(), egress=[]))
    txn.update(POD2, PodConfig(pod_ip=IP2, ingress=ingress_allow_tcp80(), egress=[]))
    txn.commit()
    t1 = cache.get_local_table_by_pod(POD1)
    t2 = cache.get_local_table_by_pod(POD2)
    assert t1 is t2
    assert t1.pods == {POD1, POD2}


def test_unisolated_pod_has_no_table():
    cache = RendererCache(Orientation.INGRESS)
    txn = cache.new_txn()
    txn.update(POD1, PodConfig(pod_ip=IP1, ingress=[], egress=[]))
    txn.commit()
    assert cache.get_all_pods() == {POD1}
    assert cache.get_isolated_pods() == set()
    assert cache.get_local_table_by_pod(POD1) is None


def test_local_table_gets_default_allow_rules():
    cache = RendererCache(Orientation.INGRESS)
    txn = cache.new_txn()
    txn.update(POD1, PodConfig(pod_ip=IP1, ingress=ingress_allow_tcp80(), egress=[]))
    txn.commit()
    table = cache.get_local_table_by_pod(POD1)
    # deny-all TCP and UDP came from the config; the cache does not need to
    # append permits because deny-all rules are already total.
    protos = {(r.protocol, r.action) for r in table.rules}
    assert (Protocol.TCP, Action.DENY) in protos
    assert (Protocol.UDP, Action.DENY) in protos


def test_pod_removal_releases_table():
    cache = RendererCache(Orientation.INGRESS)
    txn = cache.new_txn()
    txn.update(POD1, PodConfig(pod_ip=IP1, ingress=ingress_allow_tcp80(), egress=[]))
    txn.update(POD2, PodConfig(pod_ip=IP2, ingress=ingress_allow_tcp80(), egress=[]))
    txn.commit()

    txn2 = cache.new_txn()
    txn2.update(POD1, PodConfig(removed=True))
    changes = txn2.get_changes()
    # Shared table loses POD1 but survives with POD2.
    assert len(changes) == 1
    assert changes[0].previous_pods == {POD1, POD2}
    assert changes[0].table.pods == {POD2}
    txn2.commit()
    assert cache.get_all_pods() == {POD2}
    assert cache.get_local_table_by_pod(POD2) is not None

    txn3 = cache.new_txn()
    txn3.update(POD2, PodConfig(removed=True))
    changes = txn3.get_changes()
    assert len(changes) == 1
    assert changes[0].table.pods == set()
    txn3.commit()
    assert cache.get_all_pods() == set()
    assert len(cache.local_tables.tables) == 0


def test_no_changes_for_identical_update():
    cache = RendererCache(Orientation.INGRESS)
    cfg = PodConfig(pod_ip=IP1, ingress=ingress_allow_tcp80(), egress=[])
    txn = cache.new_txn()
    txn.update(POD1, cfg)
    txn.commit()

    txn2 = cache.new_txn()
    txn2.update(POD1, PodConfig(pod_ip=IP1, ingress=ingress_allow_tcp80(), egress=[]))
    assert txn2.get_changes() == []


def test_egress_folds_into_global_table():
    """With ingress orientation, a pod's egress restrictions land in the
    global table (destination pinned to the pod IP)."""
    cache = RendererCache(Orientation.INGRESS)
    egress = [
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP, dest_port=53),
        ContivRule(action=Action.DENY, protocol=Protocol.TCP),
        ContivRule(action=Action.DENY, protocol=Protocol.UDP),
    ]
    txn = cache.new_txn()
    txn.update(POD1, PodConfig(pod_ip=IP1, ingress=[], egress=egress))
    txn.commit()
    gt = cache.get_global_table()
    assert gt.num_of_rules > 0
    # Every folded rule must pin dest to the pod IP; plus trailing allow-alls.
    pinned = [r for r in gt.rules if r.dest_network == IP1]
    assert len(pinned) == len(egress)
    allow_all = [r for r in gt.rules if r.dest_network is None and r.src_network is None]
    assert {r.protocol for r in allow_all} == {Protocol.TCP, Protocol.UDP}


def test_ingress_egress_intersection_between_pods():
    """Direction naming is from the vswitch POV (reference renderer/api.go):
    a pod's *ingress* rules describe traffic the pod sends (src unset),
    its *egress* rules describe traffic the pod receives (dst unset).

    POD1 may send to TCP:80+8080 (ingress); POD2 may receive only TCP:80
    (egress). Under ingress orientation POD1's local table must allow
    sending to POD2 only on TCP:80 (the intersection), with deny-the-rest
    pinned to POD2's IP as destination."""
    cache = RendererCache(Orientation.INGRESS)
    ingress1 = [
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP, dest_port=80),
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP, dest_port=8080),
        ContivRule(action=Action.DENY, protocol=Protocol.TCP),
        ContivRule(action=Action.DENY, protocol=Protocol.UDP),
    ]
    egress2 = [
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP, dest_port=80),
        ContivRule(action=Action.DENY, protocol=Protocol.TCP),
        ContivRule(action=Action.DENY, protocol=Protocol.UDP),
    ]
    txn = cache.new_txn()
    txn.update(POD1, PodConfig(pod_ip=IP1, ingress=ingress1, egress=[]))
    txn.update(POD2, PodConfig(pod_ip=IP2, ingress=[], egress=egress2))
    txn.commit()

    t1 = cache.get_local_table_by_pod(POD1)
    to_pod2 = [r for r in t1.rules if r.dest_network == IP2]
    permits = {r.dest_port for r in to_pod2 if r.action == Action.PERMIT}
    denies = [r for r in to_pod2 if r.action == Action.DENY and r.dest_port == 0]
    assert permits == {80}
    assert len(denies) >= 1  # deny-the-rest toward POD2


def test_resync_then_update():
    cache = RendererCache(Orientation.INGRESS)
    txn = cache.new_txn()
    txn.update(POD1, PodConfig(pod_ip=IP1, ingress=ingress_allow_tcp80(), egress=[]))
    txn.commit()
    dumped = [cache.get_local_table_by_pod(POD1), cache.get_global_table()]

    cache2 = RendererCache(Orientation.INGRESS)
    cache2.resync(dumped)
    assert cache2.get_all_pods() == {POD1}
    # Follow-up txn reconciling POD1's config produces no changes.
    txn2 = cache2.new_txn()
    txn2.update(POD1, PodConfig(pod_ip=IP1, ingress=ingress_allow_tcp80(), egress=[]))
    assert txn2.get_changes() == []


def test_icmp_permit_does_not_open_udp():
    """Regression: a PERMIT ICMP rule must not be folded into the UDP port
    set (which would disable UDP restrictions toward the pod)."""
    cache = RendererCache(Orientation.INGRESS)
    egress2 = [
        ContivRule(action=Action.PERMIT, protocol=Protocol.ICMP),
        ContivRule(action=Action.DENY, protocol=Protocol.TCP),
        ContivRule(action=Action.DENY, protocol=Protocol.UDP),
    ]
    ingress1 = [
        ContivRule(action=Action.PERMIT, protocol=Protocol.UDP, dest_port=53),
        ContivRule(action=Action.DENY, protocol=Protocol.TCP),
        ContivRule(action=Action.DENY, protocol=Protocol.UDP),
    ]
    txn = cache.new_txn()
    txn.update(POD1, PodConfig(pod_ip=IP1, ingress=ingress1, egress=[]))
    txn.update(POD2, PodConfig(pod_ip=IP2, ingress=[], egress=egress2))
    txn.commit()
    t1 = cache.get_local_table_by_pod(POD1)
    # POD2 receives nothing on UDP => deny-all UDP toward POD2 must exist,
    # and no UDP permit toward POD2 may appear.
    to_pod2_udp = [r for r in t1.rules if r.dest_network == IP2 and r.protocol == Protocol.UDP]
    assert any(r.action == Action.DENY and r.dest_port == 0 for r in to_pod2_udp)
    assert not any(r.action == Action.PERMIT for r in to_pod2_udp)


def test_table_id_counter_survives_resync():
    cache = RendererCache(Orientation.INGRESS)
    txn = cache.new_txn()
    txn.update(POD1, PodConfig(pod_ip=IP1, ingress=ingress_allow_tcp80(), egress=[]))
    txn.commit()
    dumped = [cache.get_local_table_by_pod(POD1), cache.get_global_table()]
    dumped_id = dumped[0].id

    cache2 = RendererCache(Orientation.INGRESS)
    cache2.resync(dumped)
    # Newly generated IDs must not collide with dumped ones.
    assert cache2._generate_table_id() != dumped_id
