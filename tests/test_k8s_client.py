"""Real-K8s list-watch source: converters, watch stream, reconnect diff.

VERDICT r1 Missing #3 / Next #4: reflectors must run against a real
API-server protocol, not only MockK8sListWatch. A fake HTTP API server
speaks enough of the K8s REST/watch protocol (list + chunked watch
stream + resourceVersion) to drive KubernetesListWatch end-to-end into a
live Reflector. Reference semantics: plugins/ksr/pod_reflector.go:39-142,
ksr_reflector.go:185-232.
"""

from __future__ import annotations

import base64
import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from vpp_tpu.ksr import model
from vpp_tpu.ksr.k8s_client import (
    K8sApi,
    K8sApiConfig,
    RESOURCES,
    KubernetesListWatch,
    convert_endpoints,
    convert_node,
    convert_pod,
    convert_policy,
    convert_service,
    make_k8s_sources,
)
from vpp_tpu.ksr.reflector import Reflector
from vpp_tpu.kvstore.store import Broker, KVStore


POD_JSON = {
    "metadata": {
        "name": "web-0", "namespace": "prod",
        "labels": {"app": "web", "tier": "fe"},
        "resourceVersion": "101",
    },
    "spec": {
        "nodeName": "node-1",
        "containers": [
            {"name": "nginx",
             "ports": [{"name": "http", "containerPort": 80,
                        "protocol": "TCP"}]},
        ],
    },
    "status": {"podIP": "10.1.1.7", "hostIP": "192.168.0.11"},
}

POLICY_JSON = {
    "metadata": {"name": "allow-fe", "namespace": "prod",
                 "resourceVersion": "55"},
    "spec": {
        "podSelector": {"matchLabels": {"app": "web"}},
        "policyTypes": ["Ingress", "Egress"],
        "ingress": [{
            "from": [
                {"podSelector": {"matchExpressions": [
                    {"key": "tier", "operator": "In",
                     "values": ["fe", "lb"]}]}},
                {"ipBlock": {"cidr": "172.17.0.0/16",
                             "except": ["172.17.1.0/24"]}},
            ],
            "ports": [{"protocol": "TCP", "port": 80},
                      {"protocol": "TCP", "port": "metrics"}],
        }],
        "egress": [{
            "to": [{"namespaceSelector": {
                "matchLabels": {"env": "prod"}}}],
        }],
    },
}


class TestConverters:
    def test_pod(self):
        p = convert_pod(POD_JSON)
        assert p.name == "web-0" and p.namespace == "prod"
        assert p.ip_address == "10.1.1.7"
        assert p.host_ip_address == "192.168.0.11"
        assert p.labels == {"app": "web", "tier": "fe"}
        assert p.containers[0].ports[0].container_port == 80
        assert p.key() == "k8s/pod/web-0/namespace/prod"

    def test_policy(self):
        pol = convert_policy(POLICY_JSON)
        assert pol.policy_type == model.POLICY_BOTH
        assert pol.pods.match_labels == {"app": "web"}
        ing = pol.ingress_rules[0]
        assert ing.ports[0].port == 80
        assert ing.ports[1].port is None and ing.ports[1].port_name == "metrics"
        assert ing.peers[0].pods.match_expressions[0].values == ["fe", "lb"]
        assert ing.peers[1].ip_block.cidr == "172.17.0.0/16"
        assert ing.peers[1].ip_block.except_cidrs == ["172.17.1.0/24"]
        assert pol.egress_rules[0].peers[0].namespaces.match_labels == {
            "env": "prod"}

    def test_policy_default_type_when_unset(self):
        pol = convert_policy({
            "metadata": {"name": "p", "namespace": "d"},
            "spec": {"podSelector": {}},
        })
        assert pol.policy_type == model.POLICY_DEFAULT

    def test_service(self):
        s = convert_service({
            "metadata": {"name": "web", "namespace": "prod"},
            "spec": {
                "clusterIP": "10.96.0.10", "type": "NodePort",
                "selector": {"app": "web"},
                "externalTrafficPolicy": "Local",
                "externalIPs": ["1.2.3.4"],
                "ports": [{"name": "http", "port": 80,
                           "targetPort": "http-alt", "nodePort": 30080}],
            },
        })
        assert s.cluster_ip == "10.96.0.10"
        assert s.service_type == "NodePort"
        assert s.external_traffic_policy == "Local"
        assert s.ports[0].target_port == "http-alt"
        assert s.ports[0].node_port == 30080

    def test_endpoints(self):
        e = convert_endpoints({
            "metadata": {"name": "web", "namespace": "prod"},
            "subsets": [{
                "addresses": [{"ip": "10.1.1.7", "nodeName": "node-1",
                               "targetRef": {"kind": "Pod", "name": "web-0",
                                             "namespace": "prod"}}],
                "notReadyAddresses": [{"ip": "10.1.2.9"}],
                "ports": [{"name": "http", "port": 80, "protocol": "TCP"}],
            }],
        })
        sub = e.subsets[0]
        assert sub.addresses[0].target_pod == "prod/web-0"
        assert sub.not_ready_addresses[0].ip == "10.1.2.9"
        assert sub.ports[0].port == 80

    def test_node(self):
        n = convert_node({
            "metadata": {"name": "node-1"},
            "spec": {"podCIDR": "10.1.1.0/24"},
            "status": {"addresses": [
                {"type": "InternalIP", "address": "192.168.0.11"},
                {"type": "Hostname", "address": "node-1"},
            ]},
        })
        assert n.pod_cidr == "10.1.1.0/24"
        assert n.addresses[0].address == "192.168.0.11"
        assert n.key() == "k8s/node/node-1"


# --------------------------------------------------------------------------
# fake API server speaking list + watch
# --------------------------------------------------------------------------

class FakeK8sApiServer:
    """Serves /api/... list GETs from an object dict and watch GETs from a
    per-path event queue (blocking stream, like a real API server)."""

    def __init__(self):
        self.objects: dict = {}          # path -> {key: raw obj}
        self.rv = 100
        self.watch_queues: dict = {}     # path -> queue of event dicts
        self.list_calls: dict = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def do_GET(self):
                parsed = urlparse(self.path)
                q = parse_qs(parsed.query)
                path = parsed.path
                if q.get("watch", ["false"])[0] == "true":
                    self._serve_watch(path)
                else:
                    self._serve_list(path)

            def _serve_list(self, path):
                outer.list_calls[path] = outer.list_calls.get(path, 0) + 1
                items = list(outer.objects.get(path, {}).values())
                body = json.dumps({
                    "kind": "List",
                    "metadata": {"resourceVersion": str(outer.rv)},
                    "items": items,
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _serve_watch(self, path):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                wq = outer.watch_queues.setdefault(path, queue.Queue())
                while True:
                    ev = wq.get()
                    if ev is None:       # end of stream
                        self.wfile.write(b"0\r\n\r\n")
                        return
                    data = json.dumps(ev).encode() + b"\n"
                    chunk = f"{len(data):x}\r\n".encode() + data + b"\r\n"
                    try:
                        self.wfile.write(chunk)
                        self.wfile.flush()
                    except OSError:
                        return

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.server.daemon_threads = True
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    @property
    def url(self) -> str:
        host, port = self.server.server_address
        return f"http://{host}:{port}"

    def set_objects(self, path, objs):
        self.objects[path] = objs
        self.rv += 1

    def push_event(self, path, etype, obj):
        self.rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
        q = self.watch_queues.setdefault(path, queue.Queue())
        q.put({"type": etype, "object": obj})

    def end_stream(self, path):
        self.watch_queues.setdefault(path, queue.Queue()).put(None)

    def expire_stream(self, path):
        """Simulate 410 Gone: watch continuity lost, client must re-list."""
        q = self.watch_queues.setdefault(path, queue.Queue())
        q.put({"type": "ERROR", "object": {
            "kind": "Status", "code": 410,
            "message": "too old resource version"}})
        q.put(None)

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def fake_k8s():
    srv = FakeK8sApiServer()
    yield srv
    srv.close()


def wait_for(pred, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def make_lw(fake, obj_type="pod"):
    api = K8sApi(K8sApiConfig(server=fake.url))
    lw = KubernetesListWatch(api, RESOURCES[obj_type])
    # fast reconnects in tests
    lw.RECONNECT_BACKOFF = (0.05, 0.2)
    return lw


POD_PATH = "/api/v1/pods"


class TestListWatch:
    def test_list_converts_and_caches(self, fake_k8s):
        fake_k8s.set_objects(POD_PATH, {"p": POD_JSON})
        lw = make_lw(fake_k8s)
        items = lw.list()
        assert len(items) == 1 and items[0].name == "web-0"
        lw.stop()

    def test_watch_feeds_reflector(self, fake_k8s):
        store = KVStore()
        broker = Broker(store, "ksr/")
        lw = make_lw(fake_k8s)
        refl = Reflector("pod", broker, lw, lambda m: m)
        refl.start()
        try:
            pod_key = "ksr/k8s/pod/web-0/namespace/prod"
            fake_k8s.push_event(POD_PATH, "ADDED", json.loads(
                json.dumps(POD_JSON)))
            wait_for(lambda: store.get(pod_key) is not None, msg="pod add")
            assert store.get(pod_key)["ip_address"] == "10.1.1.7"

            modified = json.loads(json.dumps(POD_JSON))
            modified["status"]["podIP"] = "10.1.1.8"
            fake_k8s.push_event(POD_PATH, "MODIFIED", modified)
            wait_for(
                lambda: store.get(pod_key)["ip_address"] == "10.1.1.8",
                msg="pod modify",
            )

            fake_k8s.push_event(POD_PATH, "DELETED", modified)
            wait_for(lambda: store.get(pod_key) is None, msg="pod delete")
            assert refl.stats.adds == 1
            assert refl.stats.deletes == 1
        finally:
            lw.stop()
            fake_k8s.end_stream(POD_PATH)

    def test_reconnect_relists_and_diffs(self, fake_k8s):
        """410 Gone -> re-list; objects that vanished during the outage
        must be synthesized as deletes (informer semantics). A clean
        stream end (server watch timeout) must NOT re-list — continuity
        holds via resourceVersion."""
        store = KVStore()
        broker = Broker(store, "ksr/")
        fake_k8s.set_objects(POD_PATH, {"p": POD_JSON})
        lw = make_lw(fake_k8s)
        refl = Reflector("pod", broker, lw, lambda m: m)
        refl.start()
        pod_key = "ksr/k8s/pod/web-0/namespace/prod"
        try:
            wait_for(lambda: store.get(pod_key) is not None,
                     msg="initial list")
            # outage: pod disappears while the stream is down
            other = {
                "metadata": {"name": "db-0", "namespace": "prod"},
                "spec": {}, "status": {"podIP": "10.1.9.9"},
            }
            fake_k8s.set_objects(POD_PATH, {"q": other})
            lists_before = fake_k8s.list_calls.get(POD_PATH, 0)
            fake_k8s.expire_stream(POD_PATH)
            wait_for(lambda: store.get(pod_key) is None,
                     msg="synthesized delete after re-list")
            wait_for(
                lambda: store.get("ksr/k8s/pod/db-0/namespace/prod")
                is not None,
                msg="synthesized add after re-list",
            )
            assert fake_k8s.list_calls[POD_PATH] > lists_before

            # clean end: re-watch only, no re-list
            wait_for(
                lambda: POD_PATH in fake_k8s.watch_queues,
                msg="watch re-established",
            )
            lists_before = fake_k8s.list_calls[POD_PATH]
            fake_k8s.end_stream(POD_PATH)
            time.sleep(0.4)
            assert fake_k8s.list_calls[POD_PATH] == lists_before
        finally:
            lw.stop()
            fake_k8s.end_stream(POD_PATH)

    def test_bookmark_advances_rv_only(self, fake_k8s):
        lw = make_lw(fake_k8s)
        calls = []
        lw.subscribe(lambda m: calls.append(("add", m)),
                     lambda o, n: calls.append(("upd", n)),
                     lambda m: calls.append(("del", m)))
        try:
            fake_k8s.push_event(POD_PATH, "BOOKMARK", {
                "metadata": {"resourceVersion": "999"}})
            fake_k8s.push_event(POD_PATH, "ADDED",
                                json.loads(json.dumps(POD_JSON)))
            wait_for(lambda: len(calls) == 1, msg="only the ADDED dispatches")
            assert calls[0][0] == "add"
        finally:
            lw.stop()
            fake_k8s.end_stream(POD_PATH)

    def test_make_sources_covers_all_types(self, fake_k8s):
        sources = make_k8s_sources(config=K8sApiConfig(server=fake_k8s.url))
        assert set(sources) == set(model.MODEL_TYPES)
        for lw in sources.values():
            lw.stop()


class TestKubeconfig:
    def test_parse_token_and_inline_ca(self, tmp_path):
        ca_b64 = base64.b64encode(b"FAKECA").decode()
        cfg = {
            "current-context": "ctx",
            "contexts": [{"name": "ctx",
                          "context": {"cluster": "c", "user": "u"}}],
            "clusters": [{"name": "c", "cluster": {
                "server": "https://1.2.3.4:6443",
                "certificate-authority-data": ca_b64}}],
            "users": [{"name": "u", "user": {"token": "sekrit"}}],
        }
        import yaml

        p = tmp_path / "kubeconfig"
        p.write_text(yaml.safe_dump(cfg))
        c = K8sApiConfig.from_kubeconfig(str(p))
        assert c.server == "https://1.2.3.4:6443"
        assert c.token == "sekrit"
        with open(c.ca_file, "rb") as fh:
            assert fh.read() == b"FAKECA"

    def test_missing_context_raises(self, tmp_path):
        p = tmp_path / "kc"
        p.write_text("{}")
        with pytest.raises(ValueError):
            K8sApiConfig.from_kubeconfig(str(p))
