"""Manifest parametrization (the Helm-values analog, VERDICT r3
Missing #5): k8s/render.py + chart/values.yaml must reproduce the
committed manifest byte-for-byte with defaults, apply overrides, and
fail loudly on template/values drift."""

import os
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def render(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "k8s", "render.py"), *args],
        capture_output=True, text=True, timeout=60,
    )


def test_default_render_matches_committed_manifest():
    r = render()
    assert r.returncode == 0, r.stderr
    with open(os.path.join(REPO, "k8s", "vpp-tpu.yaml")) as f:
        assert r.stdout == f.read(), \
            "k8s/vpp-tpu.yaml drifted from the chart — regenerate with " \
            "`python k8s/render.py -o k8s/vpp-tpu.yaml`"


def test_overrides_apply_and_are_valid_yaml():
    import yaml

    r = render("--set", "image=registry.example/vpp-tpu:2.1",
               "--set", "pod_subnet_cidr=10.9.0.0/16",
               "--set", "mesh_nodes=4", "--set", "tpu_count=8")
    assert r.returncode == 0, r.stderr
    docs = list(yaml.safe_load_all(
        r.stdout.replace("${NODE_NAME}", "node-x")
    ))
    assert len(docs) >= 8
    assert "registry.example/vpp-tpu:2.1" in r.stdout
    assert "10.9.0.0/16" in r.stdout
    cfg = next(d for d in docs if d.get("kind") == "ConfigMap")
    agent_yaml = yaml.safe_load(cfg["data"]["contiv.yaml"])
    assert agent_yaml["mesh"] == {"nodes": 4, "rule_shards": 1}
    # the rendered agent config must parse as a real AgentConfig
    sys.path.insert(0, REPO)
    from vpp_tpu.cmd.config import AgentConfig

    parsed = AgentConfig.from_dict(agent_yaml)
    assert parsed.mesh.nodes == 4
    ds = next(d for d in docs if d.get("kind") == "DaemonSet")
    limits = ds["spec"]["template"]["spec"]["containers"][0][
        "resources"]["limits"]
    assert limits == {"google.com/tpu": 8}


def test_unknown_value_rejected():
    r = render("--set", "no_such_knob=1")
    assert r.returncode != 0
    assert "not a known value" in (r.stderr + r.stdout)


def test_witness_epoch_storage_is_a_pvc_not_hostpath():
    """ADVICE r5: the witness's persisted fencing epoch IS the
    cluster's fencing history — on a hostPath a node reschedule lost
    it, defeating the epoch-adoption guard. The Deployment must mount
    a PersistentVolumeClaim that follows the Pod across nodes."""
    import yaml

    r = render()
    assert r.returncode == 0, r.stderr
    docs = list(yaml.safe_load_all(
        r.stdout.replace("${NODE_NAME}", "node-x")
    ))
    pvcs = [d for d in docs if d.get("kind") == "PersistentVolumeClaim"]
    assert any(d["metadata"]["name"] == "vpp-tpu-kvwitness-data"
               for d in pvcs), "witness PVC missing from the chart"
    witness = next(
        d for d in docs if d.get("kind") == "Deployment"
        and d["metadata"]["name"] == "vpp-tpu-kvwitness"
    )
    volumes = witness["spec"]["template"]["spec"]["volumes"]
    data = next(v for v in volumes if v["name"] == "data")
    assert "hostPath" not in data, \
        "witness epoch on hostPath: fencing state dies with the node"
    assert data["persistentVolumeClaim"]["claimName"] == \
        "vpp-tpu-kvwitness-data"
