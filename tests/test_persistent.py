"""Persistent device loop: one resident while_loop program pumps many
frames through host io_callbacks — verdicts identical to the
per-dispatch packed path, session state threaded frame-to-frame,
clean stop returning the final tables."""

import numpy as np

from vpp_tpu.pipeline.dataplane import Dataplane, pack_packet_columns
from vpp_tpu.pipeline.persistent import PersistentPump
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, ip4
from vpp_tpu.ir.rule import Action, ContivRule, Protocol

B = 64


def build_dp():
    dp = Dataplane(DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=32, max_ifaces=8,
        fib_slots=32, sess_slots=256, nat_mappings=4, nat_backends=4,
    ))
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("d", "p"))
    dp.builder.add_route("10.1.1.2/32", pod, Disposition.LOCAL)
    dp.builder.set_global_table([
        ContivRule(action=Action.DENY, protocol=Protocol.TCP,
                   dest_port=23),
        ContivRule(action=Action.PERMIT),
    ])
    dp.swap()
    return dp, up, pod


def packed_frame(dport, sport, up):
    cols = {
        "src_ip": np.full(B, ip4("10.9.0.9"), np.uint32),
        "dst_ip": np.full(B, ip4("10.1.1.2"), np.uint32),
        "proto": np.full(B, 6, np.uint32),
        "sport": np.full(B, sport, np.uint32),
        "dport": np.full(B, dport, np.uint32),
        "ttl": np.full(B, 64, np.uint32),
        "pkt_len": np.full(B, 64, np.uint32),
        "rx_if": np.full(B, up, np.uint32),
        "flags": np.ones(B, np.uint32),
    }
    flat = np.zeros((5, B), np.int32)
    pack_packet_columns(flat.view(np.uint32), cols, B)
    return flat


def out_disp(out):
    return (out.view(np.uint32)[3] >> 24) & 0xF


def test_persistent_matches_dispatch_and_threads_sessions():
    dp, up, pod = build_dp()
    pump = PersistentPump(dp.tables, batch=B).start()
    try:
        # frame 1: telnet denied, frame 2: http allowed
        pump.submit(packed_frame(23, 1000, up), now=1)
        pump.submit(packed_frame(80, 2000, up), now=2)
        o1 = pump.result(timeout=120)
        o2 = pump.result(timeout=120)
        assert (out_disp(o1) == int(Disposition.DROP)).all()
        assert (out_disp(o2) == int(Disposition.LOCAL)).all()

        # per-dispatch oracle: identical verdict rows
        ref = dp.process_packed(packed_frame(80, 3000, up), now=3)
        pump.submit(packed_frame(80, 3000, up), now=3)
        o4 = pump.result(timeout=120)
        # dp.process_packed ran on ITS copy of the tables (fresh flow)
        assert np.array_equal(out_disp(np.asarray(ref)),
                              out_disp(o4))
    finally:
        final = pump.stop()
    # sessions installed inside the loop survive into the returned
    # tables (frames 2-3 were permitted fresh flows)
    assert int(np.asarray(final.sess_valid).sum()) > 0


def test_stop_without_traffic():
    dp, up, pod = build_dp()
    pump = PersistentPump(dp.tables, batch=B).start()
    final = pump.stop()
    assert final is not None
    assert int(np.asarray(final.sess_valid).sum()) == 0
