"""Worker: fleet-agreed MXU selection in MultiHostCluster (run
directly; single jax.distributed process, 2 virtual devices).

At 600+ bit-plane-compatible global rules publish() must select the
MXU classifier (ClusterDataplane.swap's rule), and its verdicts must
be identical to a dense-forced twin cluster on the same frames.
"""

import json
import os
import sys

COORD_PORT = sys.argv[1]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ipaddress  # noqa: E402

import numpy as np  # noqa: E402

from vpp_tpu.ir.rule import Action, ContivRule, Protocol  # noqa: E402
from vpp_tpu.parallel.multihost import (  # noqa: E402
    MultiHostCluster,
)
from vpp_tpu.pipeline.tables import DataplaneConfig  # noqa: E402
from vpp_tpu.pipeline.vector import Disposition  # noqa: E402

# no jax.distributed here: a single-process "fleet" works without it
# (process_count==1), and the coordinator's heartbeat can die under
# the compile storm this worker intentionally creates
_ = COORD_PORT

N_RULES = 640
rules = []
for i in range(N_RULES - 1):
    net = ipaddress.ip_network(
        f"172.{16 + (i % 1000) // 256}.{(i % 1000) % 256}.0/24")
    rules.append(ContivRule(
        action=Action.DENY if i % 6 == 5 else Action.PERMIT,
        src_network=net, protocol=Protocol.TCP,
        dest_port=8000 + i % 20))
rules.append(ContivRule(action=Action.DENY))

cfg = DataplaneConfig(
    max_tables=4, max_rules=16, max_global_rules=N_RULES, max_ifaces=8,
    fib_slots=32, sess_slots=256, nat_mappings=4, nat_backends=16,
)


def build(force_dense: bool) -> MultiHostCluster:
    cl = MultiHostCluster(2, cfg)
    if force_dense:
        cl.mxu_threshold = 1 << 30
    for nid in range(2):
        n = cl.node(nid)
        up = n.add_uplink()
        pi = n.add_pod_interface(("d", f"p{nid}"))
        n.builder.add_route(f"10.{nid + 1}.0.2/32", pi,
                            Disposition.LOCAL)
        other = 1 - nid
        n.builder.add_route(f"10.{other + 1}.0.0/24", up,
                            Disposition.REMOTE, node_id=other)
        n.builder.set_global_table(list(rules))
    cl.publish()
    return cl


def frames(cl):
    rng = np.random.default_rng(3)
    pkts = []
    for k in range(32):
        blk = int(rng.integers(0, 1000))
        pkts.append(dict(
            src=f"172.{16 + blk // 256}.{blk % 256}.{1 + k % 250}",
            dst="10.2.0.2", proto=6, sport=1000 + k,
            dport=8000 + int(rng.integers(0, 20)),
            rx_if=cl.node(0).pod_if[("d", "p0")]))
    return cl.make_frames([pkts, []], n=64)


mxu = build(force_dense=False)
dense = build(force_dense=True)
assert mxu._use_mxu, "MXU not selected at 640 compatible rules"
assert not dense._use_mxu

r_m = mxu.step(frames(mxu), now=1)
r_d = dense.step(frames(dense), now=1)

same = (np.array_equal(np.asarray(mxu.local_rows(r_m.local.disp)),
                       np.asarray(dense.local_rows(r_d.local.disp)))
        and np.array_equal(
            np.asarray(mxu.local_rows(r_m.delivered.disp)),
            np.asarray(dense.local_rows(r_d.delivered.disp))))
dropped = int(np.asarray(mxu.local_rows(r_m.stats.drop_acl)).sum())
delivered = int((np.asarray(mxu.local_rows(r_m.delivered.disp))
                 == int(Disposition.LOCAL)).sum())
print("VERDICT " + json.dumps({
    "mxu_selected": bool(mxu._use_mxu),
    "verdicts_equal": bool(same),
    "drop_acl": dropped,
    "delivered": delivered,
}), flush=True)
