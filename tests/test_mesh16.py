"""Cluster sharding beyond the default test mesh: 16 virtual devices.

The driver validates the multi-chip path at 8 devices
(__graft_entry__.dryrun_multichip); this proves the (node, rule) mesh
factorization, shardings and collectives also compile and execute at
the next power of two — in a subprocess, because the device count must
be fixed before jax initializes.
"""

import os
import subprocess
import sys

import pytest


# slow: the subprocess re-compiles the ENTIRE multichip dry run (mesh
# runtime, wire steps, MXU variants and the ISSUE-12 partition
# section) at 16 devices — the driver's own multichip check already
# runs dryrun_multichip, so tier-1 doesn't pay for the 16-device
# doubling; `pytest -m slow tests/test_mesh16.py` runs it on demand.
@pytest.mark.slow
def test_dryrun_multichip_16_devices():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(16); "
         "print('OK16')"],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK16" in proc.stdout
