"""Dynamic pod attach e2e: CNI Add wires a REAL netns pod (VERDICT r2
Next #1).

A CNI Add must leave a working kernel path: veth pair created, container
side configured inside the pod's netns (IP, routes, static gateway ARP),
host side attached to the IO daemon through its control socket — and a
UDP datagram sent by one netns pod must cross Transport → codec → ring →
device pipeline → ring → Transport into the other netns pod. After a
deny policy lands, the same traffic must die in the data plane.

Reference analog: plugins/contiv/pod.go:262-452 (pod connectivity
builders), remote_cni_server.go:895-1250 (configureContainerConnectivity)
and the robot suite's pod↔pod UDP case
(tests/robot/suites/one_node_two_pods.robot).
"""

from __future__ import annotations

import ipaddress
import subprocess
import sys
import time

import pytest

from vpp_tpu.cni.model import CNIRequest, ResultCode
from vpp_tpu.cni.server import RemoteCNIServer
from vpp_tpu.cni.wiring import VethPodWirer, host_ifname
from vpp_tpu.io.control import IOControlClient, IOControlServer
from vpp_tpu.io.daemon import IODaemon
from vpp_tpu.io.pump import DataplanePump
from vpp_tpu.io.rings import IORingPair
from vpp_tpu.ipam.ipam import IPAM
from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.pipeline.dataplane import Dataplane, packed_input_zeros
from vpp_tpu.pipeline.tables import DataplaneConfig


def _can_netns() -> bool:
    try:
        r = subprocess.run(["ip", "netns", "add", "vpptselfns"],
                           capture_output=True, timeout=10)
        if r.returncode == 0:
            subprocess.run(["ip", "netns", "del", "vpptselfns"],
                           capture_output=True, timeout=10)
            return True
        return False
    except (OSError, subprocess.TimeoutExpired):
        return False


pytestmark = pytest.mark.skipif(
    not _can_netns(), reason="needs CAP_NET_ADMIN (netns/veth)"
)

NS_A, NS_B = "vppt-poda", "vppt-podb"
CID_A = "aaaa1111bbbb2222cccc"
CID_B = "dddd3333eeee4444ffff"


def _netns_path(name: str) -> str:
    return f"/var/run/netns/{name}"


def _cleanup():
    for ns in (NS_A, NS_B):
        subprocess.run(["ip", "netns", "del", ns], capture_output=True)
    for cid in (CID_A, CID_B):
        subprocess.run(["ip", "link", "del", host_ifname(cid)],
                       capture_output=True)
    subprocess.run(["ip", "link", "del", "vpptpu-host"],
                   capture_output=True)


@pytest.fixture()
def stack(tmp_path):
    """Dataplane + CNI server w/ wirer + in-process IO daemon with a
    real control socket, plus two empty named netns "pods"."""
    _cleanup()
    for ns in (NS_A, NS_B):
        subprocess.run(["ip", "netns", "add", ns], check=True, timeout=10)

    dp = Dataplane(DataplaneConfig())
    uplink = dp.add_uplink()
    host_if = dp.add_host_interface()
    # no NetworkPolicy installed yet -> default allow (the classifier
    # fails closed with an empty global table)
    dp.builder.set_global_table(
        [ContivRule(action=Action.PERMIT, protocol=Protocol.ANY)]
    )
    dp.swap()
    dp.process_packed(packed_input_zeros(256))  # pre-compile

    rings = IORingPair(n_slots=32)
    daemon = IODaemon(rings, {}, uplink_if=uplink, host_if=host_if).start()
    ctl_sock = str(tmp_path / "io-ctl.sock")
    control = IOControlServer(daemon, ctl_sock).start()
    ipam = IPAM(node_id=1)
    pump = DataplanePump(dp, rings,
                         icmp_src_ip=int(ipam.pod_gateway_ip())).start()
    wirer = VethPodWirer(IOControlClient(ctl_sock),
                         gateway_ip=str(ipam.pod_gateway_ip()))
    server = RemoteCNIServer(dp, ipam, wirer=wirer)
    server.set_ready()
    try:
        yield {"dp": dp, "server": server, "daemon": daemon,
               "ipam": ipam, "ctl_sock": ctl_sock, "host_if": host_if}
    finally:
        pump.stop()
        control.close()
        daemon.stop()
        for t in daemon.transports.values():
            t.close()
        rings.close()
        _cleanup()


def _add_pod(server, cid: str, ns: str, name: str):
    reply = server.add(CNIRequest(
        container_id=cid, netns=_netns_path(ns), if_name="eth0",
        extra_args={"K8S_POD_NAME": name, "K8S_POD_NAMESPACE": "default"},
    ))
    assert reply.result == ResultCode.OK, reply.error
    addr = reply.interfaces[0].ip_addresses[0].address
    return addr.split("/")[0]


def _udp_recv_proc(ns: str, port: int):
    return subprocess.Popen(
        ["ip", "netns", "exec", ns, sys.executable, "-c",
         "import socket,sys\n"
         "s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
         f"s.bind(('0.0.0.0', {port}))\n"
         "s.settimeout(30)\n"
         "data, peer = s.recvfrom(4096)\n"
         "print(data.decode() + '|' + peer[0], flush=True)\n"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def _udp_send(ns: str, dst: str, port: int, msg: str, times: int = 20):
    # retried sends: first packets race the receiver bind + daemon select
    subprocess.run(
        ["ip", "netns", "exec", ns, sys.executable, "-c",
         "import socket, time\n"
         "s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
         f"for _ in range({times}):\n"
         f"    s.sendto({msg!r}.encode(), ('{dst}', {port}))\n"
         "    time.sleep(0.1)\n"],
        check=True, timeout=60, capture_output=True,
    )


class TestPodWiring:
    def test_add_wires_real_interfaces_and_udp_flows(self, stack):
        server, dp = stack["server"], stack["dp"]
        ip_a = _add_pod(server, CID_A, NS_A, "pod-a")
        ip_b = _add_pod(server, CID_B, NS_B, "pod-b")

        # kernel artifacts exist: host-side veths, container eth0 w/ IP
        assert subprocess.run(
            ["ip", "link", "show", host_ifname(CID_A)],
            capture_output=True).returncode == 0
        out = subprocess.run(
            ["ip", "-n", NS_B, "-o", "addr", "show", "eth0"],
            capture_output=True, text=True).stdout
        assert ip_b in out

        # daemon got both attachments
        assert set(stack["daemon"].transports) >= {
            dp.pod_if[("default", "pod-a")],
            dp.pod_if[("default", "pod-b")],
        }

        # pod A -> pod B UDP through the device pipeline
        recv = _udp_recv_proc(NS_B, 5354)
        time.sleep(0.5)
        _udp_send(NS_A, ip_b, 5354, "hello-through-tpu")
        out, err = recv.communicate(timeout=40)
        assert "hello-through-tpu" in out, (out, err)
        assert ip_a in out  # source IP preserved through the pipeline

        # deny UDP:5355 toward pod B (NetworkPolicy analog), keep the
        # rest: traffic must now die in the classifier
        slot = dp.alloc_table_slot("deny-b")
        with dp.commit_lock:
            dp.builder.set_local_table(slot, [
                ContivRule(action=Action.DENY,
                           dest_network=ipaddress.ip_network(f"{ip_b}/32"),
                           protocol=Protocol.UDP, dest_port=5355),
                ContivRule(action=Action.PERMIT),
            ])
            dp.assign_pod_table(("default", "pod-a"), "deny-b")
            dp.swap()
        recv2 = _udp_recv_proc(NS_B, 5355)
        time.sleep(0.5)
        drops_before = stack["daemon"].stats["tx_drops"]
        _udp_send(NS_A, ip_b, 5355, "must-not-arrive", times=5)
        time.sleep(1.0)
        assert stack["daemon"].stats["tx_drops"] > drops_before
        recv2.kill()
        out2, _ = recv2.communicate(timeout=10)
        assert "must-not-arrive" not in (out2 or "")

        # CNI Delete tears the kernel path down
        reply = server.delete(CNIRequest(container_id=CID_A))
        assert reply.result == ResultCode.OK
        assert subprocess.run(
            ["ip", "link", "show", host_ifname(CID_A)],
            capture_output=True).returncode != 0
        assert dp.pod_if.get(("default", "pod-a")) is None

    def test_failed_wire_rolls_back(self, stack):
        server = stack["server"]
        ipam = stack["ipam"]
        before = ipam.assigned_count()
        # nonexistent netns: the wire step must fail and roll back the
        # dataplane + IPAM state
        reply = server.add(CNIRequest(
            container_id="feedfacefeedface", netns="/var/run/netns/nope",
            if_name="eth0",
            extra_args={"K8S_POD_NAME": "ghost"},
        ))
        assert reply.result == ResultCode.ERROR
        assert ipam.assigned_count() == before
        assert stack["dp"].pod_if.get(("default", "ghost")) is None
        # and the retry path stays clean (no stale index/interface)
        assert server.index.lookup("feedfacefeedface") is None


def _traceroute_hop(ns: str, dst: str, ttl: int, port: int = 33434):
    """One traceroute probe from inside the pod netns: a UDP datagram
    with the given TTL + a raw-ICMP listener; prints 'hop_ip|type' the
    way traceroute discovers each hop."""
    code = (
        "import socket, time\n"
        "icmp = socket.socket(socket.AF_INET, socket.SOCK_RAW,\n"
        "                     socket.IPPROTO_ICMP)\n"
        "icmp.settimeout(20)\n"
        "s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
        f"s.setsockopt(socket.IPPROTO_IP, socket.IP_TTL, {ttl})\n"
        "for _ in range(20):\n"
        f"    s.sendto(b'probe', ('{dst}', {port}))\n"
        "    time.sleep(0.1)\n"
        "    try:\n"
        "        data, peer = icmp.recvfrom(4096)\n"
        "    except socket.timeout:\n"
        "        continue\n"
        "    ihl = (data[0] & 0xF) * 4\n"
        "    print(peer[0] + '|' + str(data[ihl]), flush=True)\n"
        "    break\n"
    )
    return subprocess.run(
        ["ip", "netns", "exec", ns, sys.executable, "-c", code],
        capture_output=True, text=True, timeout=60,
    )


class TestTracerouteHop:
    def test_ttl1_probe_reports_vswitch_gateway_hop(self, stack):
        """The traceroute semantic (VERDICT r3 Next #8): a TTL=1 UDP
        probe from pod A toward pod B expires at the vswitch, and the
        pod receives ICMP time-exceeded FROM THE GATEWAY IP — the hop
        traceroute prints (reference: VPP ip4-icmp-error branch,
        docs/VPP_PACKET_TRACING_K8S.md:28-50)."""
        server, ipam = stack["server"], stack["ipam"]
        _add_pod(server, CID_A, NS_A, "pod-a")
        ip_b = _add_pod(server, CID_B, NS_B, "pod-b")

        res = _traceroute_hop(NS_A, ip_b, ttl=1)
        assert res.returncode == 0, res.stderr
        assert res.stdout.strip(), f"no ICMP hop reply: {res.stderr}"
        hop_ip, icmp_type = res.stdout.strip().split("|")
        assert hop_ip == str(ipam.pod_gateway_ip()), \
            "time-exceeded must come from the vswitch gateway hop"
        assert int(icmp_type) == 11  # time exceeded

        # with a normal TTL the probe traverses the vswitch and reaches
        # pod B, whose kernel answers port-unreachable — the terminal
        # hop of a traceroute. The vswitch must NOT be the responder.
        res2 = _traceroute_hop(NS_A, ip_b, ttl=8)
        if res2.stdout.strip():
            hop2, t2 = res2.stdout.strip().split("|")
            assert hop2 == ip_b and int(t2) == 3, \
                "full-TTL probe must reach the destination pod"


def _ping(ns: str, dst: str, count: int = 5, timeout: float = 2.0):
    """ICMP echo from inside the pod netns (no ping binary in this
    image): craft echo requests on a raw socket, count echo replies.
    Prints 'sent|received' like ping's summary line."""
    code = (
        "import os, socket, struct, time\n"
        "def csum(b):\n"
        "    if len(b) % 2: b += b'\\0'\n"
        "    s = sum(struct.unpack('>%dH' % (len(b)//2), b))\n"
        "    s = (s & 0xFFFF) + (s >> 16)\n"
        "    s = (s & 0xFFFF) + (s >> 16)\n"
        "    return ~s & 0xFFFF\n"
        "ident = os.getpid() & 0xFFFF\n"
        "s = socket.socket(socket.AF_INET, socket.SOCK_RAW,\n"
        "                  socket.IPPROTO_ICMP)\n"
        f"s.settimeout({timeout})\n"
        "got = 0\n"
        f"for seq in range({count}):\n"
        "    hdr = struct.pack('>BBHHH', 8, 0, 0, ident, seq)\n"
        "    pay = b'vpp-tpu-ping-payload'\n"
        "    pkt = struct.pack('>BBHHH', 8, 0, csum(hdr + pay), ident,\n"
        "                      seq) + pay\n"
        f"    s.sendto(pkt, ('{dst}', 0))\n"
        f"    deadline = time.monotonic() + {timeout}\n"
        "    while time.monotonic() < deadline:\n"
        "        try:\n"
        "            data, peer = s.recvfrom(4096)\n"
        "        except socket.timeout:\n"
        "            break\n"
        "        ihl = (data[0] & 0xF) * 4\n"
        "        typ, _, _, rid, rseq = struct.unpack(\n"
        "            '>BBHHH', data[ihl:ihl + 8])\n"
        f"        if (typ == 0 and rid == ident and rseq == seq\n"
        f"                and peer[0] == '{dst}'):\n"
        "            got += 1\n"
        "            break\n"
        "    time.sleep(0.1)\n"
        f"print(str({count}) + '|' + str(got), flush=True)\n"
    )
    argv = [sys.executable, "-c", code]
    if ns is not None:
        argv = ["ip", "netns", "exec", ns] + argv
    return subprocess.run(argv, capture_output=True, text=True, timeout=90)


class TestPingAndTCP:
    """The robot suites' headline connectivity checks, kernel-real:
    Pod_To_Nginx_Ping (ICMP echo round-trip, 0% loss) and the curl
    case's transport (a full TCP handshake + request/response), both
    crossing veth → daemon → rings → device pipeline → rings → veth
    (reference: tests/robot/suites/one_node_two_pods_with_nginx.robot)."""

    def test_ping_pod_to_pod_zero_loss(self, stack):
        server = stack["server"]
        _add_pod(server, CID_A, NS_A, "pod-a")
        ip_b = _add_pod(server, CID_B, NS_B, "pod-b")
        # warm the path (first packets race the attach/select loop)
        _ping(NS_A, ip_b, count=2)
        res = _ping(NS_A, ip_b, count=5)
        assert res.returncode == 0, res.stderr
        sent, got = res.stdout.strip().split("|")
        assert (sent, got) == ("5", "5"), \
            f"packet loss: {got}/{sent} replies ({res.stderr})"

    def test_tcp_handshake_reflective_return(self, stack):
        """TCP client in pod A ↔ server in pod B while pod B's table
        DENIES unsolicited traffic toward A: the SYN-ACK and all reply
        segments are admitted by the reflective session the permitted
        SYN created — VPP's acl-plugin reflective-ACL semantic
        (SURVEY §2.3 ACL row) on a real kernel TCP stack."""
        server, dp = stack["server"], stack["dp"]
        ip_a = _add_pod(server, CID_A, NS_A, "pod-a")
        ip_b = _add_pod(server, CID_B, NS_B, "pod-b")

        slot = dp.alloc_table_slot("b-sends")
        with dp.commit_lock:
            dp.builder.set_local_table(slot, [
                ContivRule(action=Action.DENY,
                           dest_network=ipaddress.ip_network(f"{ip_a}/32")),
                ContivRule(action=Action.PERMIT),
            ])
            dp.assign_pod_table(("default", "pod-b"), "b-sends")
            dp.swap()

        # the deny is live: pod B cannot originate traffic to pod A
        drops_before = stack["daemon"].stats["tx_drops"]
        _udp_send(NS_B, ip_a, 9999, "unsolicited", times=3)
        time.sleep(0.5)
        assert stack["daemon"].stats["tx_drops"] > drops_before

        # serve until one full exchange lands: a client attempt that
        # connects but times out mid-exchange must not consume the only
        # accept and strand every later retry on a closed listener
        srv = subprocess.Popen(
            ["ip", "netns", "exec", NS_B, sys.executable, "-c",
             "import socket, time\n"
             "ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)\n"
             "ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
             "ls.bind(('0.0.0.0', 8080))\n"
             "ls.listen(4)\n"
             "ls.settimeout(60)\n"
             "deadline = time.monotonic() + 60\n"
             "while time.monotonic() < deadline:\n"
             "    c, peer = ls.accept()\n"
             "    c.settimeout(10)\n"
             "    try:\n"
             "        data = c.recv(4096)\n"
             "        if data:\n"
             "            c.sendall(b'pong:' + data)\n"
             "            print('served ' + peer[0], flush=True)\n"
             "            break\n"
             "    except OSError:\n"
             "        pass\n"
             "    finally:\n"
             "        c.close()\n"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            time.sleep(0.5)
            cli = subprocess.run(
                ["ip", "netns", "exec", NS_A, sys.executable, "-c",
                 "import socket, time\n"
                 "last = None\n"
                 "for _ in range(10):\n"
                 "    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)\n"
                 "    s.settimeout(8)\n"
                 "    try:\n"
                 f"        s.connect(('{ip_b}', 8080))\n"
                 "        s.sendall(b'ping-tcp')\n"
                 "        print(s.recv(4096).decode(), flush=True)\n"
                 "        s.close()\n"
                 "        break\n"
                 "    except OSError as e:\n"
                 "        last = e\n"
                 "        s.close()\n"
                 "        time.sleep(0.5)\n"
                 "else:\n"
                 "    raise SystemExit(f'connect failed: {last}')\n"],
                capture_output=True, text=True, timeout=120,
            )
            assert cli.returncode == 0, (cli.stdout, cli.stderr)
            assert "pong:ping-tcp" in cli.stdout
            out, err = srv.communicate(timeout=30)
            assert ip_a in out, (out, err)
        finally:
            srv.kill()
            srv.wait(timeout=10)


class TestHostInterconnect:
    """Host↔pod connectivity through the VPP↔host interconnect veth
    (reference: interconnectVethHost/interconnectVethVpp + host routes,
    host.go:105-200 & :44-86; robot Host_To_Nginx_Ping /
    Get_Web_Page_From_Host analogs)."""

    def test_host_pings_pod_through_dataplane(self, stack):
        from vpp_tpu.cni.wiring import HostInterconnectWirer
        from vpp_tpu.io.control import IOControlClient
        from vpp_tpu.pipeline.vector import Disposition

        server, dp, ipam = stack["server"], stack["dp"], stack["ipam"]
        ip_b = _add_pod(server, CID_B, NS_B, "pod-b")
        # the agent stages this route in __init__ (routesToHost analog);
        # this hand-built stack stages it here
        with dp.commit_lock:
            dp.builder.add_route(str(ipam.vpp_host_network),
                                 stack["host_if"], Disposition.HOST)
            dp.swap()

        wirer = HostInterconnectWirer(
            IOControlClient(stack["ctl_sock"]), ipam)
        wirer.wire(stack["host_if"])
        try:
            # kernel artifacts: host end carries the IPAM address +
            # routes for the pod and service subnets via the vswitch
            out = subprocess.run(
                ["ip", "-o", "addr", "show", "vpptpu-host"],
                capture_output=True, text=True).stdout
            assert str(ipam.veth_host_end_ip()) in out
            routes = subprocess.run(
                ["ip", "route", "show"],
                capture_output=True, text=True).stdout
            assert str(ipam.pod_subnet) in routes
            assert str(ipam.service_network) in routes

            _ping(None, ip_b, count=2)  # warm the path
            res = _ping(None, ip_b, count=5)
            assert res.returncode == 0, res.stderr
            sent, got = res.stdout.strip().split("|")
            assert (sent, got) == ("5", "5"), \
                f"host->pod loss: {got}/{sent} ({res.stderr})"

            # pod reaches the host stack back through the same path
            res2 = _ping(NS_B, str(ipam.veth_host_end_ip()), count=3)
            assert res2.returncode == 0, res2.stderr
            s2, g2 = res2.stdout.strip().split("|")
            assert (s2, g2) == ("3", "3"), \
                f"pod->host loss: {g2}/{s2} ({res2.stderr})"
        finally:
            wirer.unwire(stack["host_if"])
        assert subprocess.run(
            ["ip", "link", "show", "vpptpu-host"],
            capture_output=True).returncode != 0
