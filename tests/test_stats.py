"""Statscollector + Prometheus exposition tests.

Reference model: plugins/statscollector/plugin_statscollector_test.go
(mockPrometheus + mockContiv injection → assert gauge values and pod
labels) and the KSR gauge surface (ksr_statscollector.go).
"""

import urllib.request


from vpp_tpu.cni import ContainerIndex, RemoteCNIServer
from vpp_tpu.cni.model import CNIRequest
from vpp_tpu.ipam.ipam import IPAM
from vpp_tpu.ksr.reflector import ReflectorRegistry, Reflector, MockK8sListWatch
from vpp_tpu.kvstore.store import Broker, KVStore
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import make_packet_vector
from vpp_tpu.stats import Gauge, MetricsRegistry, StatsCollector, StatsHTTPServer
from vpp_tpu.stats.collector import STATS_PATH, register_ksr_gauges


def wired_node():
    dp = Dataplane(DataplaneConfig(sess_slots=256))
    dp.add_uplink()
    dp.add_host_interface()
    ipam = IPAM(node_id=1)
    index = ContainerIndex()
    srv = RemoteCNIServer(dp, ipam, index)
    srv.set_ready()
    r1 = srv.add(CNIRequest(container_id="c1", extra_args={
        "K8S_POD_NAME": "web", "K8S_POD_NAMESPACE": "prod"}))
    r2 = srv.add(CNIRequest(container_id="c2", extra_args={
        "K8S_POD_NAME": "db", "K8S_POD_NAMESPACE": "prod"}))
    ip1 = r1.interfaces[0].ip_addresses[0].address.split("/")[0]
    ip2 = r2.interfaces[0].ip_addresses[0].address.split("/")[0]
    return dp, index, srv, ip1, ip2


def test_collector_pod_labels_and_counts():
    dp, index, srv, ip1, ip2 = wired_node()
    coll = StatsCollector(dp, index)
    if1 = dp.pod_if[("prod", "web")]
    res = dp.process(make_packet_vector(
        [dict(src=ip1, dst=ip2, proto=6, sport=1000 + i, dport=80,
              len=100, rx_if=if1) for i in range(5)]
    ))
    assert int(res.stats.tx) == 5
    coll.update(res.stats)
    coll.publish()

    g_in = coll.if_gauges["vpp_tpu_if_in_packets"]
    g_out = coll.if_gauges["vpp_tpu_if_out_packets"]
    g_bytes = coll.if_gauges["vpp_tpu_if_in_bytes"]
    web = dict(podName="web", podNamespace="prod", interfaceName="eth0")
    db = dict(podName="db", podNamespace="prod", interfaceName="eth0")
    assert g_in.get(**web) == 5
    assert g_bytes.get(**web) == 500
    assert g_out.get(**db) == 5
    assert coll.node_gauges["vpp_tpu_node_rx_packets"].get() == 5
    assert coll.node_gauges["vpp_tpu_node_tx_packets"].get() == 5
    # accumulation across frames
    res2 = dp.process(make_packet_vector(
        [dict(src=ip1, dst=ip2, proto=6, sport=2000, dport=80,
              len=100, rx_if=if1)]
    ))
    coll.update(res2.stats)
    coll.publish()
    assert g_in.get(**web) == 6


def test_collector_drop_attribution():
    dp, index, srv, ip1, ip2 = wired_node()
    coll = StatsCollector(dp, index)
    if1 = dp.pod_if[("prod", "web")]
    res = dp.process(make_packet_vector(
        [dict(src=ip1, dst="203.0.113.9", proto=6, sport=1, dport=2,
              rx_if=if1)]  # no route
    ))
    coll.update(res.stats)
    coll.publish()
    web = dict(podName="web", podNamespace="prod", interfaceName="eth0")
    assert coll.if_gauges["vpp_tpu_if_drop_packets"].get(**web) == 1
    assert coll.node_gauges["vpp_tpu_node_drop_no_route"].get() == 1


def test_deleted_pod_gauges_removed():
    dp, index, srv, ip1, ip2 = wired_node()
    coll = StatsCollector(dp, index)
    if1 = dp.pod_if[("prod", "web")]
    res = dp.process(make_packet_vector(
        [dict(src=ip1, dst=ip2, proto=6, sport=1, dport=80, rx_if=if1)]
    ))
    coll.update(res.stats)
    coll.publish()
    web = dict(podName="web", podNamespace="prod", interfaceName="eth0")
    assert coll.if_gauges["vpp_tpu_if_in_packets"].get(**web) == 1

    srv.delete(CNIRequest(container_id="c1"))
    coll.publish()
    assert coll.if_gauges["vpp_tpu_if_in_packets"].get(**web) == 0


def test_http_exposition_roundtrip():
    dp, index, srv, ip1, ip2 = wired_node()
    coll = StatsCollector(dp, index)
    if1 = dp.pod_if[("prod", "web")]
    res = dp.process(make_packet_vector(
        [dict(src=ip1, dst=ip2, proto=6, sport=1, dport=80, rx_if=if1)]
    ))
    coll.update(res.stats)
    coll.publish()
    server = StatsHTTPServer(coll.registry, port=0)
    server.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{STATS_PATH}", timeout=10
        ).read().decode()
        assert 'vpp_tpu_if_in_packets{interfaceName="eth0",podName="web",podNamespace="prod"} 1' in body
        assert "# TYPE vpp_tpu_node_rx_packets gauge" in body
        # unknown path → 404
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/nope", timeout=10
            )
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.close()


def test_ksr_gauges():
    store = KVStore()
    watch = MockK8sListWatch()
    registry = ReflectorRegistry()

    class Obj:
        def __init__(self, name):
            self.name = name

        def key(self):
            return f"k8s/pod/{self.name}"

        def to_dict(self):
            return {"name": self.name}

    refl = Reflector(
        obj_type="pod",
        broker=Broker(store, "ksr/"),
        list_watch=watch,
        converter=lambda o: Obj(o["name"]),
    )
    registry.add(refl)
    refl.start()
    watch.add("p1", {"name": "p1"})
    watch.add("p2", {"name": "p2"})
    watch.delete("p1")

    mreg = MetricsRegistry()
    gauges, publish_ksr = register_ksr_gauges(mreg, registry)
    publish_ksr()
    assert gauges["adds"].get(reflector="pod") == 2
    assert gauges["deletes"].get(reflector="pod") == 1
    body = mreg.render("/metrics")
    assert 'vpp_tpu_ksr_adds{reflector="pod"} 2' in body


def test_reused_interface_slot_starts_at_zero():
    dp, index, srv, ip1, ip2 = wired_node()
    coll = StatsCollector(dp, index)
    if1 = dp.pod_if[("prod", "web")]
    res = dp.process(make_packet_vector(
        [dict(src=ip1, dst=ip2, proto=6, sport=1, dport=80, rx_if=if1)]
    ))
    coll.update(res.stats)
    srv.delete(CNIRequest(container_id="c1"))
    # new pod reuses the freed slot (LIFO allocator)
    srv.add(CNIRequest(container_id="c3", extra_args={
        "K8S_POD_NAME": "api", "K8S_POD_NAMESPACE": "prod"}))
    assert dp.pod_if[("prod", "api")] == if1
    coll.publish()
    api = dict(podName="api", podNamespace="prod", interfaceName="eth0")
    assert coll.if_gauges["vpp_tpu_if_in_packets"].get(**api) == 0


def test_gauge_large_values_exact():
    g = Gauge("big")
    g.set(12345678)
    assert "big 12345678" in g.render()
    g2 = Gauge("frac")
    g2.set(0.25)
    assert "frac 0.25" in g2.render()


def test_http_path_with_query_string():
    reg = MetricsRegistry()
    reg.register(STATS_PATH, Gauge("x")).set(1)
    server = StatsHTTPServer(reg, port=0)
    server.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{STATS_PATH}?ts=123", timeout=10
        ).read().decode()
        assert "x 1" in body
    finally:
        server.close()


def test_gauge_render_escaping():
    g = Gauge("x", "help")
    g.set(1, name='we"ird\\pod')
    lines = g.render()
    assert 'x{name="we\\"ird\\\\pod"} 1' in lines


def test_pump_counters_exported_over_prometheus():
    """IO pump counters (single-node or cluster pump — same stats
    contract) reach the Prometheus text exposition via set_pump()."""
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.stats.collector import StatsCollector

    class FakePump:
        stats = {"frames": 7, "pkts": 1792, "batches": 3,
                 "tx_ring_full": 1, "batch_errors": 0,
                 "icmp_errors": 2, "fabric_pkts": 512,
                 "inflight": 5, "inflight_peak": 8,
                 "chain_batches": 4, "chain_k_peak": 2,
                 "t_pack": 0.25, "t_dispatch": 1.5,
                 "t_fetch_wait": 12.75, "t_fetch": 0.5, "t_write": 2.0,
                 "drops_tx_stall": 9, "drops_shutdown": 3,
                 "drops_rx_full": 0, "drops_error": 2,
                 "ring_windows": 6, "ring_frames": 11,
                 "ring_inflight": 1, "ring_lag": 2, "io_callbacks": 0,
                 "ml_scored": 1500, "ml_flagged": 42, "ml_drops": 17}

        @staticmethod
        def latency_us():
            return {"p50": 123.0, "p99": 456.0, "n": 3}

    dp = Dataplane(DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=4))
    coll = StatsCollector(dp)
    coll.set_pump(FakePump())
    coll.publish()
    text = coll.registry.render("/stats")
    assert "vpp_tpu_pump_packets 1792" in text
    assert "vpp_tpu_pump_fabric_packets 512" in text
    assert "vpp_tpu_pump_icmp_errors 2" in text
    assert "vpp_tpu_pump_batch_latency_p99_us 456" in text
    # overlapped fetch ladder observability (ISSUE 1): the in-flight
    # window and the adaptive chainer's activity are exported...
    assert "vpp_tpu_pump_inflight_depth 5" in text
    assert "vpp_tpu_pump_inflight_peak 8" in text
    assert "vpp_tpu_pump_chained_dispatches 4" in text
    assert "vpp_tpu_pump_chain_k_peak 2" in text
    # ...and the per-stage cumulative seconds go out as one labelled
    # COUNTER family (so rate() gives per-second stage occupancy)
    assert "# TYPE vpp_tpu_pump_stage_seconds counter" in text
    assert 'vpp_tpu_pump_stage_seconds{stage="pack"} 0.25' in text
    assert 'vpp_tpu_pump_stage_seconds{stage="fetch_wait"} 12.75' in text
    assert 'vpp_tpu_pump_stage_seconds{stage="fetch"} 0.5' in text
    assert 'vpp_tpu_pump_stage_seconds{stage="write"} 2' in text
    # device-ring telemetry + drop-cause attribution (ISSUE 7): the
    # io_callback-free steady state and the r5 goodput loss split are
    # exported, not inferred
    assert "vpp_tpu_pump_ring_windows 6" in text
    assert "vpp_tpu_pump_ring_frames 11" in text
    assert "vpp_tpu_pump_ring_inflight 1" in text
    assert "vpp_tpu_pump_ring_writeback_lag 2" in text
    assert "vpp_tpu_pump_io_callbacks 0" in text
    assert "# TYPE vpp_tpu_pump_drops_total counter" in text
    assert 'vpp_tpu_pump_drops_total{reason="tx_stall"} 9' in text
    assert 'vpp_tpu_pump_drops_total{reason="shutdown"} 3' in text
    assert 'vpp_tpu_pump_drops_total{reason="rx_full"} 0' in text
    assert 'vpp_tpu_pump_drops_total{reason="error"} 2' in text
    # ML-stage aux riders (ISSUE 10): the pump-side verdict counters
    assert "vpp_tpu_ml_pump_scored 1500" in text
    assert "vpp_tpu_ml_pump_flagged 42" in text
    assert "vpp_tpu_ml_pump_drops 17" in text


def test_pump_drops_rx_full_merges_daemon_stats():
    """The rx_full drop cause is counted where it happens — the IO
    daemon's rx thread — and folded into the same
    vpp_tpu_pump_drops_total family via set_io_daemon()."""
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.stats.collector import StatsCollector

    class FakePump:
        stats = {"drops_rx_full": 0, "drops_tx_stall": 1,
                 "drops_shutdown": 0}

        @staticmethod
        def latency_us():
            return {"p50": 0.0, "p99": 0.0, "n": 0}

    dp = Dataplane(DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=4))
    coll = StatsCollector(dp)
    coll.set_pump(FakePump())
    coll.set_io_daemon(lambda: {"drops_rx_full": 41})
    coll.publish()
    text = coll.registry.render("/stats")
    assert 'vpp_tpu_pump_drops_total{reason="rx_full"} 41' in text
    assert 'vpp_tpu_pump_drops_total{reason="tx_stall"} 1' in text
    # mesh mode: set_io_daemon WITHOUT set_pump (the pump is attached
    # to one designated collector cluster-wide) — daemon rx overflow
    # must still export, not be fetched and discarded
    coll2 = StatsCollector(dp, registry=None)
    coll2.set_io_daemon(lambda: {"drops_rx_full": 7})
    coll2.publish()
    text2 = coll2.registry.render("/stats")
    assert 'vpp_tpu_pump_drops_total{reason="rx_full"} 7' in text2


def test_ml_stage_families_exported():
    """Per-packet ML stage (ISSUE 10): StepStats verdict counters,
    the mode/version info gauges, the load ledger and the ml degraded
    component all reach the exposition."""
    import numpy as np

    from vpp_tpu.ir.rule import Action, ContivRule, Protocol
    from vpp_tpu.ml.model import MlModel
    from vpp_tpu.ops.mlscore import ML_FEATURES
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import Disposition

    w1 = np.zeros((ML_FEATURES, 4), np.int8)
    w1[12, 0] = 1  # score == proto byte
    model = MlModel(
        kind="mlp", version=7, n_features=ML_FEATURES, w1=w1,
        b1=np.zeros(4, np.int32), s1=0,
        w2=np.array([1, 0, 0, 0], np.int8), b2=0,
        flag_thresh=10, action="drop").validate()
    dp = Dataplane(DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=4,
        ml_stage="enforce", ml_hidden=4))
    uplink = dp.add_uplink()
    dp.builder.add_route("0.0.0.0/0", uplink, Disposition.REMOTE)
    dp.builder.set_global_table(
        [ContivRule(action=Action.PERMIT, protocol=Protocol.ANY)])
    dp.builder.set_ml_model(model)
    dp.swap()
    coll = StatsCollector(dp)
    res = dp.process(make_packet_vector(
        [dict(src="198.18.0.1", dst="203.0.113.9", proto=17, sport=53,
              dport=9000, rx_if=uplink),
         dict(src="198.18.0.2", dst="203.0.113.9", proto=6, sport=443,
              dport=9001, rx_if=uplink)]))
    coll.update(res.stats)

    class FailingSource:
        degraded = True

        @staticmethod
        def stats_snapshot():
            return {"outcomes": {"loaded": 1, "corrupt": 2},
                    "degraded": True, "last_error": "x",
                    "loaded_version": 7, "loaded_kind": "mlp",
                    "path": "/m.json"}

    coll.set_ml(FailingSource())
    coll.publish()
    text = coll.registry.render("/stats")
    assert "vpp_tpu_ml_scored_packets 2" in text
    assert "vpp_tpu_ml_flagged_packets 1" in text      # UDP flagged
    assert "vpp_tpu_ml_dropped_packets 1" in text      # and dropped
    assert 'vpp_tpu_ml_stage{mode="enforce"} 1' in text
    assert 'vpp_tpu_ml_stage{mode="off"} 0' in text
    assert "vpp_tpu_ml_model_version 7" in text
    assert 'vpp_tpu_ml_load_total{outcome="corrupt"} 2' in text
    assert 'vpp_tpu_degraded{component="ml"} 1' in text


def test_ml_degraded_defaults_healthy_without_source():
    """The ml degraded component always exports (0 = healthy) even
    with no loader attached — series absence is a wiring bug."""
    dp = Dataplane(DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=4))
    coll = StatsCollector(dp)
    coll.publish()
    text = coll.registry.render("/stats")
    assert 'vpp_tpu_degraded{component="ml"} 0' in text
    assert 'vpp_tpu_ml_stage{mode="off"} 1' in text
    assert "vpp_tpu_ml_model_version 0" in text


def test_pump_stage_gauges_absent_keys_degrade_to_zero():
    """A pump without the ladder stats (the cluster pump predates some
    keys; a remote daemon may be an older build) must publish zeros,
    not crash the scrape path."""
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.stats.collector import StatsCollector

    class BarePump:
        stats = {"frames": 1, "pkts": 2, "batches": 1}

        @staticmethod
        def latency_us():
            return {"p50": 0.0, "p99": 0.0, "n": 0}

    dp = Dataplane(DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=4))
    coll = StatsCollector(dp)
    coll.set_pump(BarePump())
    coll.publish()
    text = coll.registry.render("/stats")
    assert "vpp_tpu_pump_inflight_depth 0" in text
    assert 'vpp_tpu_pump_stage_seconds{stage="dispatch"} 0' in text
