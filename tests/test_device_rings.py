"""Device-resident descriptor rings (ISSUE 7).

Host-ring edge cases the device-ring pump leans on (slot wraparound
under peek_nth, push_packed against a nearly-full ring), the
DeviceDescRing double-buffer swap raced against concurrent release(),
and the tentpole's acceptance differential: the ring-window persistent
path must be BIT-EXACT against the per-dispatch packed path — same
outputs, same aux riders, sessions threaded identically — while making
zero host callbacks.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np
import pytest

from wire import make_frame

from vpp_tpu.io.rings import VEC, DeviceDescRing, IORingPair
from vpp_tpu.native.pktio import PacketCodec

CLIENT_IP = "10.1.1.2"
SERVER_IP = "10.1.1.3"


def _push_one(rings, codec, scratch, rx_if, tag, per=4):
    frames = [
        make_frame(CLIENT_IP, SERVER_IP, proto=17, sport=tag,
                   dport=2000 + j)
        for j in range(per)
    ]
    cols, n = codec.parse(frames, rx_if, scratch)
    return rings.rx.push(cols, n, payload=scratch)


class TestHostRingEdges:
    def test_peek_nth_across_slot_wraparound(self):
        """peek_nth(k) must address the k-th oldest PENDING frame even
        when the pending span wraps the slot array boundary (the
        device-ring pump holds frames in flight exactly this way)."""
        rings = IORingPair(n_slots=4)
        codec = PacketCodec(snap=rings.rx.snap)
        scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        try:
            for tag in (100, 101, 102, 103):
                assert _push_one(rings, codec, scratch, 1, tag)
            assert not _push_one(rings, codec, scratch, 1, 999)  # full
            # consume two, refill two: pending now spans the wrap
            for expect in (100, 101):
                f = rings.rx.peek()
                assert int(f.cols["sport"][0]) == expect
                rings.rx.release()
            for tag in (104, 105):
                assert _push_one(rings, codec, scratch, 1, tag)
            assert rings.rx.pending() == 4
            for k, expect in enumerate((102, 103, 104, 105)):
                f = rings.rx.peek_nth(k)
                assert f is not None
                assert int(f.cols["sport"][0]) == expect
                # payload rows ride the same wrapped slot index
                assert f.payload is not None
            assert rings.rx.peek_nth(4) is None
        finally:
            rings.close()

    def test_push_packed_one_slot_short_then_full(self):
        """push_packed must land in the LAST free slot and fail clean
        (False, no partial commit) once the ring is full."""
        rings = IORingPair(n_slots=2)
        codec = PacketCodec(snap=rings.rx.snap)
        scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        try:
            assert _push_one(rings, codec, scratch, 1, 100, per=3)
            rx_frame = rings.rx.peek()
            n = rx_frame.n
            batch = np.zeros((5, VEC), np.int32)
            cause = np.zeros(VEC, np.int32)
            # tx ring: occupy one of the two slots, leaving ONE short
            assert rings.tx.push_packed(batch, 0, n, rx_frame, -1, 0,
                                        cause)
            # the last free slot still takes a packed push...
            assert rings.tx.push_packed(batch, 0, n, rx_frame, -1, 0,
                                        cause)
            assert rings.tx.pending() == 2
            # ...and a full ring refuses without corrupting state
            assert not rings.tx.push_packed(batch, 0, n, rx_frame, -1,
                                            0, cause)
            assert rings.tx.pending() == 2
            got = rings.tx.peek()
            assert got.n == n
        finally:
            rings.close()


class TestDeviceDescRing:
    def test_geometry_validation(self):
        with pytest.raises(ValueError):
            DeviceDescRing(slots=3)
        with pytest.raises(ValueError):
            DeviceDescRing(windows=1)  # no double buffer
        with pytest.raises(ValueError):
            DeviceDescRing(windows=3)

    def test_acquire_is_cyclic_and_backpressures(self):
        ring = DeviceDescRing(slots=2, batch=8, windows=2)
        w0, d0, n0, s0 = ring.acquire(timeout=1)
        w1, d1, n1, _s1 = ring.acquire(timeout=1)
        assert (w0, w1) == (0, 1)
        assert d0.shape == (2, 5, 8) and n0.shape == (2,)
        assert s0.shape == (2,)  # the rx-enqueue stamp lane (ISSUE 11)
        assert ring.in_flight() == 2
        # every window in flight: acquire times out (host backpressure)
        assert ring.acquire(timeout=0.05) is None
        ring.release(w0)
        got = ring.acquire(timeout=1)
        assert got is not None and got[0] == 0  # strict ring order
        ring.release(0)
        ring.release(1)
        with pytest.raises(RuntimeError):
            ring.release(0)  # double release

    def test_double_buffer_swap_under_concurrent_release(self):
        """Race the stager's cyclic acquire against a fetcher releasing
        from another thread: the swap must stay strictly cyclic, never
        hand out a held window, and wake a blocked acquire exactly
        when its window frees."""
        ring = DeviceDescRing(slots=2, batch=4, windows=2)
        release_q: "queue.Queue" = queue.Queue()
        errors: list = []

        def fetcher():
            while True:
                w = release_q.get()
                if w is None:
                    return
                time.sleep(0.0005)
                try:
                    ring.release(w)
                except Exception as e:  # noqa: BLE001 — surfaced below
                    errors.append(e)

        t = threading.Thread(target=fetcher)
        t.start()
        order = []
        try:
            for _ in range(200):
                got = ring.acquire(timeout=5)
                assert got is not None, "acquire starved"
                order.append(got[0])
                release_q.put(got[0])
        finally:
            release_q.put(None)
            t.join()
        assert not errors
        assert order == [i % 2 for i in range(200)]  # cyclic swap held
        assert ring.in_flight() == 0


class TestCallbackFreeProgram:
    def test_window_program_contains_no_host_callbacks(self):
        """The io_callback-free claim, measured on the PROGRAM itself:
        lower the ring window program and assert no host-callback
        custom call appears in the StableHLO. The runtime
        ``io_callbacks`` counter is the claim's exported face, but a
        counter nothing increments can't catch a regression by itself
        — a reintroduced io_callback/pure_callback lowers to a
        ``*callback*`` custom call and fails HERE. (Unique geometry:
        slots=2 x batch=32 — this lowering is the key's only trace,
        so the compile-once session guard stays green.)"""
        import jax.numpy as jnp

        from vpp_tpu.pipeline.dataplane import _jitted_step
        from vpp_tpu.pipeline.tables import DataplaneConfig, TableBuilder

        tables = TableBuilder(DataplaneConfig(
            max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=4,
            fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=2,
        )).to_device()
        step = _jitted_step("dense", False, False, "ring",
                            ring_slots=2)
        lowered = step.lower(
            tables, jnp.int32(0), np.zeros((2, 5, 32), np.int32),
            np.zeros(2, np.int32), np.int32(1))
        text = lowered.as_text().lower()
        assert "callback" not in text, \
            "host callback reintroduced into the ring window program"


def _build_dp(config_cls, dataplane_cls):
    from vpp_tpu.ir.rule import Action, ContivRule, Protocol
    from vpp_tpu.pipeline.vector import Disposition

    dp = dataplane_cls(config_cls(
        max_tables=2, max_rules=16, max_global_rules=32, max_ifaces=8,
        fib_slots=32, sess_slots=256, nat_mappings=4, nat_backends=4,
    ))
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("d", "p"))
    dp.builder.add_route("10.1.1.2/32", pod, Disposition.LOCAL)
    dp.builder.set_global_table([
        ContivRule(action=Action.DENY, protocol=Protocol.TCP,
                   dest_port=23),
        ContivRule(action=Action.PERMIT),
    ])
    dp.swap()
    return dp, up


def _packed_frame(batch, dport, sport, up):
    from vpp_tpu.pipeline.dataplane import pack_packet_columns
    from vpp_tpu.pipeline.vector import ip4

    cols = {
        "src_ip": np.full(batch, ip4("10.9.0.9"), np.uint32),
        "dst_ip": np.full(batch, ip4("10.1.1.2"), np.uint32),
        "proto": np.full(batch, 6, np.uint32),
        "sport": np.full(batch, sport, np.uint32),
        "dport": np.full(batch, dport, np.uint32),
        "ttl": np.full(batch, 64, np.uint32),
        "pkt_len": np.full(batch, 64, np.uint32),
        "rx_if": np.full(batch, up, np.uint32),
        "flags": np.ones(batch, np.uint32),
    }
    flat = np.zeros((5, batch), np.int32)
    pack_packet_columns(flat.view(np.uint32), cols, batch)
    return flat


class TestRingDifferential:
    def test_ring_path_bit_exact_vs_dispatch_path(self):
        """The acceptance differential: N frames (mixed deny/permit,
        repeated flows so sessions install and later hit) through the
        window-ring persistent pump vs the SAME frames issued as
        sequential process_packed dispatches on an identically
        configured dataplane. Outputs and aux riders must match bit
        for bit — sessions thread window-to-window exactly as they
        thread dispatch-to-dispatch — and the ring path must have made
        ZERO host callbacks."""
        from vpp_tpu.pipeline.dataplane import Dataplane
        from vpp_tpu.pipeline.persistent import PersistentPump
        from vpp_tpu.pipeline.tables import DataplaneConfig

        B = 64
        dp_ring, up1 = _build_dp(DataplaneConfig, Dataplane)
        dp_ref, up2 = _build_dp(DataplaneConfig, Dataplane)
        assert up1 == up2
        # mixed regime: telnet (denied), http (permitted, installs
        # sessions), then REPEATS of the http flows (established hits
        # — the fast tier engages mid-stream inside a window)
        plan = [(23, 1000), (80, 2000), (80, 3000), (80, 2000),
                (80, 3000), (23, 4000), (80, 2000), (80, 5000),
                (80, 5000), (80, 2000)]
        frames = [_packed_frame(B, dport, sport, up1)
                  for dport, sport in plan]
        # mirror the dataplane's own epoch selection, as the pump does
        pump = PersistentPump(
            dp_ring.tables, batch=B,
            fastpath=dp_ref._use_fastpath,
            classifier=dp_ref._classifier_impl,
            skip_local=dp_ref._skip_local,
            ring_slots=4, ring_windows=2,
        ).start()
        try:
            for k, flat in enumerate(frames):
                pump.submit(flat, now=k + 1)
            got = [pump.result_ex(timeout=180) for _ in frames]
        finally:
            final = pump.stop()
        for k, flat in enumerate(frames):
            ref_out, ref_aux = dp_ref.process_packed(
                flat, now=k + 1, with_aux=True)
            assert np.array_equal(np.asarray(ref_out), got[k][0]), \
                f"frame {k} output diverged"
            assert np.array_equal(np.asarray(ref_aux), got[k][1]), \
                f"frame {k} aux diverged"
        # zero io_callbacks, measured — with frames actually windowed
        snap = pump.stats_snapshot()
        assert snap["io_callbacks"] == 0
        assert snap["ring_frames"] == len(frames)
        assert 1 <= snap["ring_windows"] <= len(frames)
        assert snap["ring_lag"] == 0  # everything written back
        # session state threaded through the windows matches the
        # sequential oracle's end state
        assert np.array_equal(np.asarray(final.sess_valid),
                              np.asarray(dp_ref.tables.sess_valid))

    def test_window_compaction_preserves_order_and_identity(self):
        """Multi-frame windows (slots > 1) must deliver per-frame
        results in submission order even when several frames land in
        one window and the LAST window ships partially filled."""
        from vpp_tpu.pipeline.dataplane import Dataplane
        from vpp_tpu.pipeline.persistent import PersistentPump
        from vpp_tpu.pipeline.tables import DataplaneConfig

        B = 64
        dp, up = _build_dp(DataplaneConfig, Dataplane)
        pump = PersistentPump(dp.tables, batch=B, ring_slots=4,
                              ring_windows=2).start()
        try:
            # 7 frames: not a multiple of the window size, so the tail
            # window is partial; sport identifies each frame
            for k in range(7):
                pump.submit(_packed_frame(B, 80, 6000 + k, up),
                            now=k + 1)
            outs = [pump.result(timeout=180) for _ in range(7)]
        finally:
            pump.stop()
        for k, out in enumerate(outs):
            sport = (out.view(np.uint32)[2] >> 16)
            assert (sport == 6000 + k).all(), "order or identity lost"
