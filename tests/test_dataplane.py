"""Data-plane pipeline tests on the virtual CPU backend.

Follows the reference's mock/aclengine idea: assert *connectivity
semantics* (is this 5-tuple allowed? where does it go?) rather than
config bytes, and cross-check the vectorized kernels against pure-Python
oracles — including a randomized differential test for the ACL classify.
"""

import ipaddress
import random

import jax.numpy as jnp
import numpy as np

from vpp_tpu.ir import Action, ContivRule, Protocol
from vpp_tpu.ops.acl import acl_classify_local
from vpp_tpu.ops.fib import ip4_lookup
from vpp_tpu.ops.session import session_expire
from vpp_tpu.pipeline.graph import pipeline_step
from vpp_tpu.pipeline.tables import DataplaneConfig, InterfaceType, TableBuilder
from vpp_tpu.pipeline.vector import (
    VEC,
    Disposition,
    ip4,
    ip4_str,
    make_packet_vector,
)

NOW = jnp.int32(100)

# interface layout used across tests
IF_POD1, IF_POD2, IF_POD3, IF_UPLINK, IF_HOST = 0, 1, 2, 3, 4
POD1_IP, POD2_IP, POD3_IP = "10.1.1.1", "10.1.1.2", "10.1.1.3"


def base_builder():
    b = TableBuilder(DataplaneConfig())
    b.set_interface(IF_POD1, InterfaceType.POD)
    b.set_interface(IF_POD2, InterfaceType.POD)
    b.set_interface(IF_POD3, InterfaceType.POD)
    b.set_interface(IF_UPLINK, InterfaceType.UPLINK, apply_global=True)
    b.set_interface(IF_HOST, InterfaceType.HOST)
    b.add_route(f"{POD1_IP}/32", IF_POD1, Disposition.LOCAL)
    b.add_route(f"{POD2_IP}/32", IF_POD2, Disposition.LOCAL)
    b.add_route(f"{POD3_IP}/32", IF_POD3, Disposition.LOCAL)
    b.add_route("10.2.0.0/16", IF_UPLINK, Disposition.REMOTE, next_hop=ip4("192.168.16.2"), node_id=2)
    b.add_route("0.0.0.0/0", IF_UPLINK, Disposition.REMOTE, next_hop=ip4("192.168.16.100"), node_id=-1)
    return b


def run(tables, pkts):
    return pipeline_step(tables, pkts, NOW)


def test_forwarding_and_ttl():
    t = base_builder().to_device()
    pkts = make_packet_vector(
        [
            {"src": POD1_IP, "dst": POD2_IP, "proto": 6, "sport": 1234, "dport": 80, "rx_if": IF_POD1},
            {"src": POD1_IP, "dst": "10.2.0.9", "proto": 17, "sport": 53, "dport": 53, "rx_if": IF_POD1},
            {"src": POD1_IP, "dst": POD2_IP, "proto": 6, "sport": 1, "dport": 2, "rx_if": IF_POD1, "ttl": 1},
        ]
    )
    r = run(t, pkts)
    # local forward
    assert int(r.disp[0]) == Disposition.LOCAL and int(r.tx_if[0]) == IF_POD2
    assert int(r.pkts.ttl[0]) == 63
    # remote forward over the overlay
    assert int(r.disp[1]) == Disposition.REMOTE and int(r.node_id[1]) == 2
    assert ip4_str(r.next_hop[1]) == "192.168.16.2"
    # ttl expiry
    assert int(r.disp[2]) == Disposition.DROP
    assert int(r.stats.drop_ip4) == 1
    assert int(r.stats.rx) == 2  # ttl-expired packet never counted live
    assert int(r.stats.tx) == 2


def test_lpm_prefers_longest_prefix():
    b = base_builder()
    b.add_route("10.2.3.0/24", IF_POD3, Disposition.LOCAL)
    t = b.to_device()
    res = ip4_lookup(t, jnp.asarray(np.array([ip4("10.2.3.4"), ip4("10.2.9.9")], np.uint32)))
    assert int(res.tx_if[0]) == IF_POD3  # /24 beats /16
    assert int(res.tx_if[1]) == IF_UPLINK


def test_fib_miss_drops():
    b = TableBuilder(DataplaneConfig())
    b.set_interface(IF_POD1, InterfaceType.POD)
    b.add_route(f"{POD1_IP}/32", IF_POD1, Disposition.LOCAL)
    t = b.to_device()
    pkts = make_packet_vector(
        [{"src": POD1_IP, "dst": "8.8.8.8", "proto": 6, "sport": 5, "dport": 80, "rx_if": IF_POD1}]
    )
    r = run(t, pkts)
    assert int(r.disp[0]) == Disposition.DROP
    assert int(r.stats.drop_no_route) == 1


def policy_rules():
    """pod1 may send TCP only to port 80; UDP only to port 53."""
    net = ipaddress.ip_network
    return [
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP, dest_port=80),
        ContivRule(action=Action.PERMIT, protocol=Protocol.UDP, dest_port=53),
        ContivRule(action=Action.DENY, protocol=Protocol.TCP),
        ContivRule(action=Action.DENY, protocol=Protocol.UDP),
    ]


def test_acl_policy_enforcement():
    b = base_builder()
    b.set_local_table(0, policy_rules())
    b.set_interface(IF_POD1, InterfaceType.POD, local_table=0)
    t = b.to_device()
    pkts = make_packet_vector(
        [
            {"src": POD1_IP, "dst": POD2_IP, "proto": 6, "sport": 999, "dport": 80, "rx_if": IF_POD1},
            {"src": POD1_IP, "dst": POD2_IP, "proto": 6, "sport": 999, "dport": 443, "rx_if": IF_POD1},
            {"src": POD1_IP, "dst": POD2_IP, "proto": 17, "sport": 999, "dport": 53, "rx_if": IF_POD1},
            # pod2 has no table => unrestricted
            {"src": POD2_IP, "dst": POD1_IP, "proto": 6, "sport": 1, "dport": 9999, "rx_if": IF_POD2},
        ]
    )
    r = run(t, pkts)
    assert int(r.disp[0]) == Disposition.LOCAL
    assert int(r.disp[1]) == Disposition.DROP
    assert int(r.disp[2]) == Disposition.LOCAL
    assert int(r.disp[3]) == Disposition.LOCAL
    assert int(r.stats.drop_acl) == 1


def test_reflective_session_allows_return_traffic():
    """pod2's policy would deny pod2->pod1 traffic, but as *return* traffic
    of an established pod1->pod2 flow it must pass (reflective ACL)."""
    b = base_builder()
    deny_all = [
        ContivRule(action=Action.DENY, protocol=Protocol.TCP),
        ContivRule(action=Action.DENY, protocol=Protocol.UDP),
    ]
    b.set_local_table(0, policy_rules())
    b.set_local_table(1, deny_all)
    b.set_interface(IF_POD1, InterfaceType.POD, local_table=0)
    b.set_interface(IF_POD2, InterfaceType.POD, local_table=1)
    t = b.to_device()

    fwd = make_packet_vector(
        [{"src": POD1_IP, "dst": POD2_IP, "proto": 6, "sport": 5555, "dport": 80, "rx_if": IF_POD1}]
    )
    r1 = run(t, fwd)
    assert int(r1.disp[0]) == Disposition.LOCAL
    t = r1.tables  # session installed

    rev = make_packet_vector(
        [
            {"src": POD2_IP, "dst": POD1_IP, "proto": 6, "sport": 80, "dport": 5555, "rx_if": IF_POD2},
            # unrelated pod2->pod1 flow is still denied
            {"src": POD2_IP, "dst": POD1_IP, "proto": 6, "sport": 81, "dport": 4444, "rx_if": IF_POD2},
        ]
    )
    r2 = run(t, rev)
    assert int(r2.disp[0]) == Disposition.LOCAL
    assert int(r2.disp[1]) == Disposition.DROP

    # After expiry, return traffic is denied again.
    aged = session_expire(r2.tables, now=1000, max_age=60)
    r3 = run(aged, rev)
    assert int(r3.disp[0]) == Disposition.DROP


def test_nat_dnat_and_reverse():
    b = base_builder()
    vip = ip4("10.96.0.10")
    backends = [(ip4(POD2_IP), 8080, 1), (ip4(POD3_IP), 8080, 1)]
    b.set_nat_mapping(0, vip, 80, 6, backends, boff=0)
    t = b.to_device()

    pkts = make_packet_vector(
        [{"src": POD1_IP, "dst": "10.96.0.10", "proto": 6, "sport": 7777, "dport": 80, "rx_if": IF_POD1}]
    )
    r = run(t, pkts)
    chosen = ip4_str(r.pkts.dst_ip[0])
    assert chosen in (POD2_IP, POD3_IP)
    assert int(r.pkts.dport[0]) == 8080
    assert int(r.disp[0]) == Disposition.LOCAL
    assert int(r.tx_if[0]) in (IF_POD2, IF_POD3)

    # Same flow always picks the same backend (consistent hashing).
    r_again = run(t, pkts)
    assert ip4_str(r_again.pkts.dst_ip[0]) == chosen

    # Reply from the backend is translated back to the VIP.
    reply = make_packet_vector(
        [{"src": chosen, "dst": POD1_IP, "proto": 6, "sport": 8080, "dport": 7777, "rx_if": IF_POD2}]
    )
    r2 = run(r.tables, reply)
    assert ip4_str(r2.pkts.src_ip[0]) == "10.96.0.10"
    assert int(r2.pkts.sport[0]) == 80
    assert int(r2.disp[0]) == Disposition.LOCAL


def test_nat_balances_across_backends():
    b = base_builder()
    vip = ip4("10.96.0.10")
    backends = [(ip4(POD2_IP), 8080, 1), (ip4(POD3_IP), 8080, 3)]  # 1:3 weights
    b.set_nat_mapping(0, vip, 80, 6, backends, boff=0)
    t = b.to_device()
    pkts = make_packet_vector(
        [
            {"src": POD1_IP, "dst": "10.96.0.10", "proto": 6, "sport": 1000 + i, "dport": 80, "rx_if": IF_POD1}
            for i in range(VEC)
        ]
    )
    r = run(t, pkts)
    counts = {
        POD2_IP: int(np.sum(np.asarray(r.pkts.dst_ip) == ip4(POD2_IP))),
        POD3_IP: int(np.sum(np.asarray(r.pkts.dst_ip) == ip4(POD3_IP))),
    }
    assert counts[POD2_IP] + counts[POD3_IP] == VEC
    # 3x weight => roughly 3x the share (generous tolerance, hash-based).
    assert counts[POD3_IP] > counts[POD2_IP] * 1.5


def _oracle_classify(rules, src, dst, proto, sport, dport):
    """First-match oracle in plain Python (IANA proto numbers)."""
    for i, r in enumerate(rules):
        if r.protocol.ip_proto != -1 and r.protocol.ip_proto != proto:
            continue
        if r.src_network is not None and ipaddress.ip_address(src) not in r.src_network:
            continue
        if r.dest_network is not None and ipaddress.ip_address(dst) not in r.dest_network:
            continue
        if r.src_port and sport != r.src_port:
            continue
        if r.dest_port and dport != r.dest_port:
            continue
        return r.action == Action.PERMIT, i
    # Unmatched: empty table allows all; non-empty denies unmatched TCP/UDP
    # but permits other protocols (kernel default = reference's appended
    # ICMP permits).
    return (len(rules) == 0 or proto not in (6, 17)), -1


def test_acl_differential_random():
    """Randomized differential test: dense TPU classify vs Python oracle."""
    rng = random.Random(42)
    nets = [None, "10.0.0.0/8", "10.1.0.0/16", "10.1.1.0/24", "10.1.1.1/32", "10.1.1.128/25"]
    rules = []
    for _ in range(60):
        rules.append(
            ContivRule(
                action=rng.choice([Action.PERMIT, Action.DENY]),
                src_network=(lambda s: ipaddress.ip_network(s) if s else None)(rng.choice(nets)),
                dest_network=(lambda s: ipaddress.ip_network(s) if s else None)(rng.choice(nets)),
                protocol=rng.choice([Protocol.TCP, Protocol.UDP, Protocol.ANY, Protocol.ICMP]),
                src_port=rng.choice([0, 0, 80, 443, 1234]),
                dest_port=rng.choice([0, 80, 443, 8080]),
            )
        )
    b = TableBuilder(DataplaneConfig())
    b.set_local_table(0, rules)
    b.set_interface(0, InterfaceType.POD, local_table=0)
    t = b.to_device()

    specs = []
    for _ in range(VEC):
        specs.append(
            {
                "src": f"10.{rng.randint(0,2)}.{rng.randint(0,2)}.{rng.randint(0,255)}",
                "dst": f"10.{rng.randint(0,2)}.{rng.randint(0,2)}.{rng.randint(0,255)}",
                "proto": rng.choice([6, 17, 1]),
                "sport": rng.choice([80, 443, 1234, 55555]),
                "dport": rng.choice([80, 443, 8080, 1000]),
                "rx_if": 0,
            }
        )
    pkts = make_packet_vector(specs)
    verdict = acl_classify_local(t, pkts)
    for i, s in enumerate(specs):
        want_permit, want_idx = _oracle_classify(
            rules, s["src"], s["dst"], s["proto"], s["sport"], s["dport"]
        )
        assert bool(verdict.permit[i]) == want_permit, f"pkt {i}: {s}"
        assert int(verdict.rule_idx[i]) == want_idx, f"pkt {i}: {s}"


def test_session_table_many_flows():
    """Insert ~200 flows in one vector; all reverse lookups must hit."""
    t = base_builder().to_device()
    n = 200
    specs = [
        {"src": POD1_IP, "dst": POD2_IP, "proto": 6, "sport": 10000 + i, "dport": 80, "rx_if": IF_POD1}
        for i in range(n)
    ]
    r = run(t, make_packet_vector(specs))
    rev_specs = [
        {"src": POD2_IP, "dst": POD1_IP, "proto": 6, "sport": 80, "dport": 10000 + i, "rx_if": IF_POD2}
        for i in range(n)
    ]
    from vpp_tpu.ops.session import session_lookup_reverse

    hits = session_lookup_reverse(r.tables, make_packet_vector(rev_specs))
    # The batch-parallel insert may lose a few same-slot elections within
    # one vector, but the vast majority must land.
    assert int(np.sum(np.asarray(hits)[:n])) >= n - 8


def test_unconfigured_interface_drops():
    """Traffic claiming an interface slot that was never configured must be
    dropped (VPP analog: unknown sw_if_index -> error-drop)."""
    t = base_builder().to_device()
    pkts = make_packet_vector(
        [{"src": POD1_IP, "dst": POD2_IP, "proto": 6, "sport": 1, "dport": 80, "rx_if": 63}]
    )
    r = run(t, pkts)
    assert int(r.disp[0]) == Disposition.DROP


def test_unmatched_icmp_permitted():
    """A TCP/UDP-only policy table must not break ICMP (reference appends
    explicit ICMP permits; our kernel encodes that as the default)."""
    b = base_builder()
    b.set_local_table(0, policy_rules())
    b.set_interface(IF_POD1, InterfaceType.POD, local_table=0)
    t = b.to_device()
    pkts = make_packet_vector(
        [{"src": POD1_IP, "dst": POD2_IP, "proto": 1, "sport": 0, "dport": 0, "rx_if": IF_POD1}]
    )
    r = run(t, pkts)
    assert int(r.disp[0]) == Disposition.LOCAL


def test_nat_exact_port_beats_wildcard():
    """A port-0 wildcard mapping in a lower slot must not shadow an
    exact-port mapping for the same IP/proto."""
    b = base_builder()
    node_ip = ip4("192.168.16.1")
    b.add_route("192.168.16.1/32", IF_HOST, Disposition.HOST)
    b.set_nat_mapping(0, node_ip, 0, 6, [(node_ip, 0, 1)], boff=0)  # passthrough
    b.set_nat_mapping(1, node_ip, 30080, 6, [(ip4(POD2_IP), 8080, 1)], boff=8)
    t = b.to_device()
    pkts = make_packet_vector(
        [{"src": "10.2.0.5", "dst": "192.168.16.1", "proto": 6, "sport": 5, "dport": 30080, "rx_if": IF_UPLINK}]
    )
    r = run(t, pkts)
    assert ip4_str(r.pkts.dst_ip[0]) == POD2_IP
    assert int(r.pkts.dport[0]) == 8080


def test_nat_session_not_recorded_for_denied_flow():
    """A DNAT'd packet that the ACL then denies must not consume a NAT
    session slot."""
    b = base_builder()
    deny_all = [
        ContivRule(action=Action.DENY, protocol=Protocol.TCP),
        ContivRule(action=Action.DENY, protocol=Protocol.UDP),
    ]
    b.set_local_table(0, deny_all)
    b.set_interface(IF_POD1, InterfaceType.POD, local_table=0)
    b.set_nat_mapping(0, ip4("10.96.0.10"), 80, 6, [(ip4(POD2_IP), 8080, 1)], boff=0)
    t = b.to_device()
    pkts = make_packet_vector(
        [{"src": POD1_IP, "dst": "10.96.0.10", "proto": 6, "sport": 7, "dport": 80, "rx_if": IF_POD1}]
    )
    r = run(t, pkts)
    assert int(r.disp[0]) == Disposition.DROP
    assert int(np.sum(np.asarray(r.tables.natsess_valid))) == 0
    assert int(np.sum(np.asarray(r.tables.sess_valid))) == 0


def test_session_insert_no_duplicates_same_vector():
    """Two packets of the same flow in one vector must produce one session
    entry, and NAT session aging must work via session_expire."""
    t = base_builder().to_device()
    specs = [
        {"src": POD1_IP, "dst": POD2_IP, "proto": 6, "sport": 123, "dport": 80, "rx_if": IF_POD1}
    ] * 2
    r = run(t, make_packet_vector(specs))
    assert int(np.sum(np.asarray(r.tables.sess_valid))) == 1
    aged = session_expire(r.tables, now=10_000, max_age=60)
    assert int(np.sum(np.asarray(aged.sess_valid))) == 0


def test_ipv6_rules_skipped_not_fatal():
    """IPv6 is a designed limitation (README "Scope"): a v6 rule in a
    NetworkPolicy must not fail the whole table commit — it's skipped
    (non-IPv4 traffic never reaches the classifier; the IO front-end
    punts it) while the v4 rules still enforce."""
    import ipaddress

    from vpp_tpu.ir.rule import Action, ContivRule, Protocol
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import Disposition, make_packet_vector

    dp = Dataplane(DataplaneConfig())
    uplink = dp.add_uplink()
    pod = dp.add_pod_interface(("default", "p"))
    dp.builder.add_route("10.1.1.2/32", pod, Disposition.LOCAL)
    dp.builder.set_global_table([
        ContivRule(action=Action.DENY,
                   src_network=ipaddress.ip_network("fd00::/8"),
                   protocol=Protocol.TCP),
        ContivRule(action=Action.PERMIT,
                   dest_network=ipaddress.ip_network("10.1.1.0/24"),
                   protocol=Protocol.UDP, dest_port=53),
        ContivRule(action=Action.DENY),
    ])
    dp.swap()  # must not raise despite the v6 rule
    r = dp.process(make_packet_vector([
        {"src": "10.9.9.9", "dst": "10.1.1.2", "proto": 17, "sport": 9,
         "dport": 53, "rx_if": uplink},
        {"src": "10.9.9.9", "dst": "10.1.1.2", "proto": 6, "sport": 9,
         "dport": 80, "rx_if": uplink},
    ]))
    assert Disposition(int(r.disp[0])) == Disposition.LOCAL
    assert Disposition(int(r.disp[1])) == Disposition.DROP


def test_incremental_swap_reuses_clean_device_arrays():
    """VERDICT r2 Weak #4: a CNI-style change (fib+if) must not re-ship
    the multi-MB global-table bit-planes — clean upload groups reuse the
    previous epoch's device arrays identically."""
    from vpp_tpu.ir.rule import Action, ContivRule, Protocol
    from vpp_tpu.pipeline.tables import DataplaneConfig, TableBuilder
    from vpp_tpu.pipeline.vector import Disposition

    b = TableBuilder(DataplaneConfig(max_global_rules=512))
    b.set_global_table(
        [ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                    dest_port=1000 + i) for i in range(400)]
        + [ContivRule(action=Action.DENY)]
    )
    t1 = b.to_device()

    # pod add: routes + interface only
    b.set_interface(5, 1)
    b.add_route("10.1.1.7/32", 5, Disposition.LOCAL)
    t2 = b.to_device(sessions=t1)
    assert t2.glb_mxu_coeff is t1.glb_mxu_coeff     # clean: reused
    assert t2.acl_action is t1.acl_action
    assert t2.nat_ext_ip is t1.nat_ext_ip
    assert t2.fib_prefix is not t1.fib_prefix        # dirty: re-uploaded
    assert t2.if_type is not t1.if_type

    # policy change: global table re-uploads, fib untouched
    b.set_global_table([ContivRule(action=Action.PERMIT)])
    t3 = b.to_device(sessions=t2)
    assert t3.glb_mxu_coeff is not t2.glb_mxu_coeff
    assert t3.fib_prefix is t2.fib_prefix
    # verdicts still correct after the reuse chain
    import numpy as np

    assert int(np.asarray(t3.glb_nrules)) == 1


def test_incremental_glb_commit_matches_full_upload():
    """A small rule change commits as a block update into the cached
    device arrays (VERDICT r3 Next #6); the resulting tables must be
    bit-identical to a from-scratch full upload, including the MXU
    bit-planes, and verdicts must track the change."""
    import numpy as np

    from vpp_tpu.ir.rule import Action, ContivRule, Protocol
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import Disposition, make_packet_vector

    cfg = DataplaneConfig(max_tables=2, max_rules=8, max_global_rules=2048,
                          max_ifaces=8, fib_slots=16, sess_slots=64,
                          nat_mappings=2, nat_backends=4)

    def rules(block_port):
        out = [
            ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                       dest_port=8000 + (i % 19))
            for i in range(2000)
        ]
        out[1500] = ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                               dest_port=block_port)
        out.append(ContivRule(action=Action.DENY))
        return out

    dp = Dataplane(cfg)
    uplink = dp.add_uplink()
    pod = dp.add_pod_interface(("ns", "p"))
    dp.builder.add_route("10.0.0.9/32", pod, Disposition.LOCAL)
    dp.builder.set_global_table(rules(9100))
    dp.swap()
    coeff_before = dp.tables.glb_mxu_coeff

    # churn: one rule changes -> must take the INCREMENTAL block path,
    # not a full re-upload (spy pins which path ran — without it a
    # silent regression to full uploads would keep this test green)
    took = []
    orig = type(dp.builder)._glb_incremental

    def spy(builder, host_np):
        r = orig(builder, host_np)
        took.append(r)
        return r

    dp.builder._glb_incremental = spy.__get__(dp.builder)
    dp.builder.set_global_table(rules(9200))
    dp.swap()
    assert took == [True], "churn commit must scatter a block, not re-upload"
    assert dp.tables.glb_mxu_coeff is not coeff_before

    # reference: a fresh dataplane with the same final rules (full path)
    ref = Dataplane(cfg)
    ref.add_uplink()
    ref_pod = ref.add_pod_interface(("ns", "p"))
    ref.builder.add_route("10.0.0.9/32", ref_pod, Disposition.LOCAL)
    ref.builder.set_global_table(rules(9200))
    ref.swap()
    for f in ("glb_src_net", "glb_dst_mask", "glb_proto", "glb_action",
              "glb_dport_lo", "glb_dport_hi", "glb_mxu_k", "glb_mxu_act",
              "glb_mxu_coeff"):
        np.testing.assert_array_equal(
            np.asarray(getattr(dp.tables, f)),
            np.asarray(getattr(ref.tables, f)), err_msg=f,
        )
    # behavior tracks: the changed rule (port 9200) now permits, the
    # old one (9100) falls to the terminal deny
    pkts = make_packet_vector([
        {"src": "9.9.9.9", "dst": "10.0.0.9", "proto": 6, "sport": 1,
         "dport": 9200, "rx_if": uplink},
        {"src": "9.9.9.9", "dst": "10.0.0.9", "proto": 6, "sport": 2,
         "dport": 9100, "rx_if": uplink},
    ])
    res = dp.process(pkts)
    assert int(res.disp[0]) == int(Disposition.LOCAL)
    assert int(res.disp[1]) == int(Disposition.DROP)
