"""W-way set-associative session table vs a NumPy dict oracle (ISSUE 6).

The vectorized insert (ops/session.py hashmap_insert) resolves a whole
batch in one election round; its semantics are specified sequentially —
"process pending packets in packet-index order, first W pending packets
of a bucket are its reps, a flow's first packet wins its rank-th best
way" (module doc). The only trustworthy check of a vectorized kernel
against a sequential spec is a differential one: an INDEPENDENT NumPy
implementation written in the obvious per-packet loop form, compared
bit-for-bit on every mask and every table column under randomized churn
(insert / refresh / payload conflict / idle expiry / victim eviction /
intra-batch duplicates / over-budget buckets), with the amortized sweep
(session_sweep / _sweep_one) running between batches.

The oracle keeps the table as plain NumPy arrays plus a dict view
(flow key -> (bucket, way)) so eviction bookkeeping — which entry a
victim eviction kills — is explicit and auditable.
"""

from __future__ import annotations

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.ops import session as sess_ops
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector

WAYS = 4
FREE_PRI_BASE = -(1 << 30)


# --- the oracle ------------------------------------------------------


class DictOracle:
    """Sequential NumPy model of the W-way insert + sweep semantics.

    Deliberately written the way the module doc SPEAKS the algorithm
    (per-packet loops, per-bucket rep lists), not the way the kernel
    computes it (sorts, gathers, scatters) — structural independence is
    what gives the comparison teeth.
    """

    def __init__(self, n_buckets: int, ways: int, max_age: int,
                 n_keys: int = 4, n_extras: int = 0):
        self.nb, self.W, self.max_age = n_buckets, ways, max_age
        # int64 holds uint32 and int32 columns alike, no wrap surprises
        self.valid = np.zeros((n_buckets, ways), np.int64)
        self.time = np.zeros((n_buckets, ways), np.int64)
        self.keys = np.zeros((n_buckets, ways, n_keys), np.int64)
        self.extras = np.zeros((n_buckets, ways, n_extras), np.int64)
        self.cursor = 0
        self.flows = {}  # key tuple -> (bucket, way)

    def _live(self, b: int, w: int, now: int) -> bool:
        return (self.valid[b, w] == 1
                and now - self.time[b, w] <= self.max_age)

    def insert(self, h, kv, ev, want, now):
        """One batch. h [B] buckets, kv [B, K] keys, ev [B, E] payloads,
        want [B] bool. Returns the per-packet outcome masks in the
        kernel's order: (inserted, conflict, failed, ev_exp, ev_vic)."""
        B = len(h)
        inserted = np.zeros(B, bool)
        conflict = np.zeros(B, bool)
        failed = np.zeros(B, bool)
        ev_exp = np.zeros(B, bool)
        ev_vic = np.zeros(B, bool)

        # pass 1 against the PRE-batch table: refresh / conflict
        exists = np.zeros(B, bool)
        exist_way = np.zeros(B, int)
        for p in range(B):
            if not want[p]:
                continue
            b = h[p]
            for w in range(self.W):
                if self._live(b, w, now) and (
                        self.keys[b, w] == kv[p]).all():
                    exists[p], exist_way[p] = True, w
                    break
        refresh = np.zeros(B, bool)
        for p in np.nonzero(want & exists)[0]:
            if (self.extras[h[p], exist_way[p]] == ev[p]).all():
                refresh[p] = True
            else:
                conflict[p] = True  # entry owned by a different flow
        pending = want & ~exists

        # reps: the first W pending packets of each bucket, in packet
        # order. Duplicates of one flow occupy window slots, but ranks
        # are dense over DISTINCT flows (kernel parity): a bursty
        # sibling must not inflate another flow's rank into a free-way
        # skip / spurious victim eviction.
        reps: dict = {}
        for p in np.nonzero(pending)[0]:
            r = reps.setdefault(h[p], [])
            if len(r) < self.W:
                r.append(p)
        rep_ranks: dict = {}   # bucket -> distinct-flow rank per slot
        for b, r in reps.items():
            seen: dict = {}
            rep_ranks[b] = [
                seen.setdefault(tuple(kv[rp]), len(seen)) for rp in r]

        # refresh timestamps land BEFORE the way priority is computed:
        # a way refreshed by this batch is active *now*, and electing it
        # as the oldest-time victim off its stale pre-batch timestamp
        # would evict the very flow that just touched it (the kernel's
        # refresh scatter runs before the election for the same reason)
        for p in np.nonzero(refresh)[0]:
            self.time[h[p], exist_way[p]] = now
            inserted[p] = True

        # per-bucket way priority (post-refresh times): free ways first
        # (ascending way index), then live ways oldest-time first,
        # time ties broken toward the lower way index
        way_order = {}
        for b in reps:
            pri = [(self.time[b, w], w) if self._live(b, w, now)
                   else (FREE_PRI_BASE + w, w) for w in range(self.W)]
            way_order[b] = [w for _, w in sorted(pri)]

        # leaders, winners, followers
        rank = np.full(B, -1)
        leader = np.full(B, -1)
        for p in np.nonzero(pending)[0]:
            for j, rp in enumerate(reps[h[p]]):
                if (kv[rp] == kv[p]).all():
                    rank[p], leader[p] = rep_ranks[h[p]][j], rp
                    break
            if leader[p] < 0:
                failed[p] = True  # over the bucket's W-packet budget

        for p in np.nonzero(pending)[0]:
            if leader[p] == p:  # winner
                b = h[p]
                w = way_order[b][rank[p]]
                if self.valid[b, w] == 1:
                    if self._live(b, w, now):
                        ev_vic[p] = True  # evicts the oldest live way
                    else:
                        ev_exp[p] = True  # reclaims an idle-expired way
                    self.flows.pop(tuple(self.keys[b, w]), None)
                self.valid[b, w] = 1
                self.time[b, w] = now
                self.keys[b, w] = kv[p]
                self.extras[b, w] = ev[p]
                self.flows[tuple(kv[p])] = (b, w)
                inserted[p] = True
            elif leader[p] >= 0:  # follower: inherit the leader
                if (ev[leader[p]] == ev[p]).all():
                    inserted[p] = True
                else:
                    conflict[p] = True  # intra-batch reply-key collision
        return inserted, conflict, failed, ev_exp, ev_vic

    def sweep(self, now: int, stride: int):
        """One amortized aging step: clear idle-expired entries in
        ``stride`` buckets from the cursor, advance the cursor."""
        s = min(stride, self.nb)
        rows = slice(self.cursor, self.cursor + s)
        stale = (self.valid[rows] == 1) & (
            now - self.time[rows] > self.max_age)
        for b, w in zip(*np.nonzero(stale)):
            self.flows.pop(tuple(self.keys[self.cursor + b, w]), None)
        self.valid[rows] = np.where(stale, 0, self.valid[rows])
        self.cursor = (self.cursor + s) % self.nb


# --- kernel driver ---------------------------------------------------


def make_device_table(nb: int, ways: int):
    return dict(
        valid=jnp.zeros((nb, ways), jnp.int32),
        time=jnp.zeros((nb, ways), jnp.int32),
        k0=jnp.zeros((nb, ways), jnp.uint32),
        k1=jnp.zeros((nb, ways), jnp.uint32),
        k2=jnp.zeros((nb, ways), jnp.uint32),
        k3=jnp.zeros((nb, ways), jnp.int32),
        e0=jnp.zeros((nb, ways), jnp.int32),
        cursor=jnp.int32(0),
    )


@functools.partial(jax.jit, static_argnames=("max_age",))
def _kernel_insert(t, kv, ev, want, now, max_age):
    nb = t["valid"].shape[0]
    key_vals = (kv[:, 0].astype(jnp.uint32), kv[:, 1].astype(jnp.uint32),
                kv[:, 2].astype(jnp.uint32), kv[:, 3].astype(jnp.int32))
    h = sess_ops._hash(*key_vals, nb)
    (valid, time, keys, extras, inserted, conflict, failed,
     ev_exp, ev_vic) = sess_ops.hashmap_insert(
        t["valid"], t["time"], (t["k0"], t["k1"], t["k2"], t["k3"]),
        key_vals, (t["e0"],), (ev[:, 0].astype(jnp.int32),), h, want,
        now, max_age=jnp.int32(max_age))
    out = dict(t, valid=valid, time=time, k0=keys[0], k1=keys[1],
               k2=keys[2], k3=keys[3], e0=extras[0])
    return out, h, (inserted, conflict, failed, ev_exp, ev_vic)


@functools.partial(jax.jit, static_argnames=("max_age", "stride"))
def _kernel_sweep(t, now, max_age, stride):
    valid, cursor = sess_ops._sweep_one(
        t["valid"], t["time"], t["cursor"], now, jnp.int32(max_age),
        stride)
    return dict(t, valid=valid, cursor=cursor)


def assert_tables_equal(t, oracle: DictOracle, ctx: str):
    np.testing.assert_array_equal(
        np.asarray(t["valid"]), oracle.valid, err_msg=f"{ctx}: valid")
    live = oracle.valid == 1
    # time/keys/extras of DEAD ways are unspecified scratch (the kernel
    # never reads them behind valid==0) — compare live cells only
    for name, col, ocol in (
        ("time", t["time"], oracle.time),
        ("k0", t["k0"], oracle.keys[:, :, 0]),
        ("k1", t["k1"], oracle.keys[:, :, 1]),
        ("k2", t["k2"], oracle.keys[:, :, 2]),
        ("k3", t["k3"], oracle.keys[:, :, 3]),
        ("e0", t["e0"], oracle.extras[:, :, 0]),
    ):
        got = np.asarray(col).astype(np.int64)[live]
        np.testing.assert_array_equal(
            got, ocol[live], err_msg=f"{ctx}: {name} (live cells)")


# --- churn generator -------------------------------------------------


def flow_cols(fid: int):
    """Deterministic 4-column key for a synthetic flow id."""
    return (fid & 0xFFFFFFFF,
            (fid * 2654435761) & 0xFFFFFFFF,
            ((1024 + fid) << 16 | 80) & 0xFFFFFFFF,
            6)


def churn_batch(rng, B, known_flows, next_fid):
    """One batch mixing new flows, refreshes of known flows, payload
    conflicts against known flows, and intra-batch duplicates."""
    kv = np.zeros((B, 4), np.int64)
    ev = np.zeros((B, 1), np.int64)
    want = rng.random(B) < 0.9
    known = list(known_flows)
    i = 0
    while i < B:
        r = rng.random()
        if known and r < 0.3:       # refresh: same key, same payload
            fid = known[rng.integers(len(known))]
            kv[i], ev[i, 0] = flow_cols(fid), fid
        elif known and r < 0.4:     # conflict: same key, WRONG payload
            fid = known[rng.integers(len(known))]
            kv[i], ev[i, 0] = flow_cols(fid), fid + 1
        else:                       # fresh flow
            fid, next_fid = next_fid, next_fid + 1
            kv[i], ev[i, 0] = flow_cols(fid), fid
            if i + 1 < B and rng.random() < 0.25:  # intra-batch dup
                i += 1
                kv[i] = kv[i - 1]
                # half the dups carry a conflicting payload
                ev[i, 0] = fid if rng.random() < 0.5 else fid + 7
        i += 1
    return kv, ev, want, next_fid


class TestDictOracleChurn:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_churn_differential(self, seed):
        """Randomized churn, every batch compared mask-for-mask and
        cell-for-cell, with the amortized sweep interleaved and two
        clock jumps past max_age (mass idle expiry mid-run)."""
        nb, B, max_age, stride = 8, 64, 50, 2
        rng = np.random.default_rng(seed)
        oracle = DictOracle(nb, WAYS, max_age, n_extras=1)
        t = make_device_table(nb, WAYS)
        now, next_fid = 1, 1
        for step in range(14):
            kv, ev, want, next_fid = churn_batch(
                rng, B, [k[0] for k in oracle.flows], next_fid)
            t, h, masks = _kernel_insert(
                t, jnp.asarray(kv), jnp.asarray(ev), jnp.asarray(want),
                jnp.int32(now), max_age)
            o_masks = oracle.insert(
                np.asarray(h), kv, ev, want, now)
            for name, got, exp in zip(
                    ("inserted", "conflict", "failed",
                     "evict_expired", "evict_victim"), masks, o_masks):
                np.testing.assert_array_equal(
                    np.asarray(got), exp,
                    err_msg=f"seed {seed} step {step}: {name}")
            assert_tables_equal(t, oracle, f"seed {seed} step {step}")
            if step % 2 == 1:  # amortized aging between batches
                t = _kernel_sweep(t, jnp.int32(now), max_age, stride)
                oracle.sweep(now, stride)
                assert int(np.asarray(t["cursor"])) == oracle.cursor
                assert_tables_equal(
                    t, oracle, f"seed {seed} step {step} post-sweep")
            # advance the clock; twice jump far past max_age
            now += int(rng.integers(0, 20))
            if step in (5, 9):
                now += max_age + 10

    def test_full_bucket_victim_eviction_and_fail_closed(self):
        """Craft >W fresh flows into ONE full live bucket in one batch:
        exactly W admit (each victim-evicting an oldest live way), the
        rest fail (counted, retried on the flow's next packet)."""
        nb, max_age = 8, 1000
        oracle = DictOracle(nb, WAYS, max_age, n_extras=1)
        t = make_device_table(nb, WAYS)

        def bucket_of(fid):
            c = flow_cols(fid)
            return int(np.asarray(sess_ops._hash(
                jnp.uint32(c[0]), jnp.uint32(c[1]), jnp.uint32(c[2]),
                jnp.int32(c[3]), nb)))

        target = bucket_of(1)
        same_bucket = [f for f in range(1, 4000)
                       if bucket_of(f) == target]
        assert len(same_bucket) >= 2 * WAYS + 2
        B = 16

        def run(fids, now):
            nonlocal t
            kv = np.zeros((B, 4), np.int64)
            ev = np.zeros((B, 1), np.int64)
            want = np.zeros(B, bool)
            for i, fid in enumerate(fids):
                kv[i], ev[i, 0], want[i] = flow_cols(fid), fid, True
            t, h, masks = _kernel_insert(
                t, jnp.asarray(kv), jnp.asarray(ev), jnp.asarray(want),
                jnp.int32(now), max_age)
            o = oracle.insert(np.asarray(h), kv, ev, want, now)
            for name, got, exp in zip(
                    ("inserted", "conflict", "failed", "ee", "ev"),
                    masks, o):
                np.testing.assert_array_equal(np.asarray(got), exp, name)
            return masks

        # fill the bucket with W live flows (distinct times for a
        # deterministic victim order)
        for i, fid in enumerate(same_bucket[:WAYS]):
            run([fid], now=10 + i)
        assert int(np.asarray(t["valid"]).sum()) == WAYS

        # W+2 fresh flows, same bucket, one batch
        fresh = same_bucket[WAYS:2 * WAYS + 2]
        ins, conf, fail, ev_exp, ev_vic = run(fresh, now=100)
        assert int(np.asarray(ins).sum()) == WAYS
        assert int(np.asarray(ev_vic).sum()) == WAYS  # all ways were live
        assert int(np.asarray(ev_exp).sum()) == 0
        assert int(np.asarray(fail).sum()) == 2
        assert int(np.asarray(conf).sum()) == 0
        # the bucket stayed exactly full — eviction, not growth
        assert int(np.asarray(t["valid"]).sum()) == WAYS

    def test_intra_batch_duplicates_do_not_inflate_sibling_ranks(self):
        """A bursty flow's duplicate packets occupy rep slots but must
        NOT inflate a sibling flow's way rank: with free ways in the
        bucket, the sibling takes a free way — never a victim eviction
        of a live session (the slot-index-rank regression class)."""
        nb, max_age = 8, 1000
        oracle = DictOracle(nb, WAYS, max_age, n_extras=1)
        t = make_device_table(nb, WAYS)

        def bucket_of(fid):
            c = flow_cols(fid)
            return int(np.asarray(sess_ops._hash(
                jnp.uint32(c[0]), jnp.uint32(c[1]), jnp.uint32(c[2]),
                jnp.int32(c[3]), nb)))

        target = bucket_of(1)
        same_bucket = [f for f in range(1, 4000)
                       if bucket_of(f) == target]
        B = 16

        def run(fids, now):
            nonlocal t
            kv = np.zeros((B, 4), np.int64)
            ev = np.zeros((B, 1), np.int64)
            want = np.zeros(B, bool)
            for i, fid in enumerate(fids):
                kv[i], ev[i, 0], want[i] = flow_cols(fid), fid, True
            t, h, masks = _kernel_insert(
                t, jnp.asarray(kv), jnp.asarray(ev), jnp.asarray(want),
                jnp.int32(now), max_age)
            o = oracle.insert(np.asarray(h), kv, ev, want, now)
            for name, got, exp in zip(
                    ("inserted", "conflict", "failed", "ee", "ev"),
                    masks, o):
                np.testing.assert_array_equal(np.asarray(got), exp, name)
            return masks

        # 2 live flows -> 2 live + 2 free ways in the target bucket
        live = same_bucket[:2]
        for i, fid in enumerate(live):
            run([fid], now=10 + i)
        assert int(np.asarray(t["valid"]).sum()) == 2

        # one batch: 3 packets of fresh flow A + 1 of fresh flow B.
        # A's duplicates burn rep slots 0-2; a slot-index rank would
        # hand B priority position 3 (victim!) with free position 1
        # unused. Distinct-flow ranks give A->0, B->1: both free ways.
        a, b = same_bucket[2], same_bucket[3]
        ins, conf, fail, ev_exp, ev_vic = run([a, a, a, b], now=50)
        assert int(np.asarray(ins).sum()) == 4          # all satisfied
        assert int(np.asarray(ev_vic).sum()) == 0       # NO victim
        assert int(np.asarray(ev_exp).sum()) == 0
        assert int(np.asarray(fail).sum()) == 0
        assert int(np.asarray(t["valid"]).sum()) == 4   # 2 live + A + B
        # the original live sessions survived
        for fid in live:
            assert tuple(flow_cols(fid)) in oracle.flows

        # residual (documented) window limit: >=W duplicate packets of
        # one flow still exhaust the W-packet rep window, so a sibling
        # flow's FIRST packet past it fails closed and retries. The
        # bucket is now FULL of live ways, so c's admission victim-
        # evicts exactly one session — the oldest (live[0], tick 10) —
        # and ONLY one: c's duplicates inherit the leader's way, they
        # don't evict again
        c, d = same_bucket[4], same_bucket[5]
        ins, conf, fail, ev_exp, ev_vic = run(
            [c] * WAYS + [d], now=60)
        assert bool(np.asarray(ins)[:WAYS].all())       # c admitted
        assert bool(np.asarray(fail)[WAYS])             # d retries
        assert int(np.asarray(ev_vic).sum()) == 1
        assert bool(np.asarray(ev_vic)[0])              # the leader only
        assert int(np.asarray(ev_exp).sum()) == 0
        assert int(np.asarray(t["valid"]).sum()) == 4   # still full
        assert tuple(flow_cols(live[0])) not in oracle.flows  # evicted
        for fid in (live[1], a, b, c):                  # survivors + c
            assert tuple(flow_cols(fid)) in oracle.flows

    def test_sweep_full_cycle_matches_bulk_expire(self):
        """Driving the stride sweep around the whole ring reclaims
        exactly what one monolithic expire pass would, and the cursor
        wraps to its origin."""
        nb, max_age, stride = 16, 50, 4
        rng = np.random.default_rng(7)
        oracle = DictOracle(nb, WAYS, max_age, n_extras=1)
        t = make_device_table(nb, WAYS)
        kv = np.zeros((64, 4), np.int64)
        ev = np.zeros((64, 1), np.int64)
        for i in range(64):
            kv[i], ev[i, 0] = flow_cols(i + 1), i + 1
        want = np.ones(64, bool)
        t, h, _ = _kernel_insert(
            t, jnp.asarray(kv), jnp.asarray(ev), jnp.asarray(want),
            jnp.int32(5), max_age)
        oracle.insert(np.asarray(h), kv, ev, want, 5)
        resident = int(np.asarray(t["valid"]).sum())
        assert resident > 0
        now = 5 + max_age + 1  # everything idle-expired
        for _ in range(nb // stride):
            t = _kernel_sweep(t, jnp.int32(now), max_age, stride)
            oracle.sweep(now, stride)
        assert int(np.asarray(t["valid"]).sum()) == 0
        assert oracle.valid.sum() == 0
        assert int(np.asarray(t["cursor"])) == 0  # wrapped home


class TestRepWindowStrategies:
    @pytest.mark.parametrize("nb,batch", [
        (1 << 6, 256),        # packed single-key sort (bits fit 31)
        (1 << 16, 1 << 16),   # idx_bits+bkt_bits = 32 > 31: the stable
                              # variadic-argsort FALLBACK — the branch
                              # the 10M-slot production geometry takes
                              # (2^22 buckets never fit beside any
                              # batch's index bits)
    ])
    def test_claim_equals_sort_across_bit_regimes(self, monkeypatch,
                                                  nb, batch):
        """The claim scatter-min ladder and BOTH sort-mode encodings of
        _bucket_reps are bit-identical by construction ON PENDING ROWS
        (module doc; non-pending rows are don't-care — every consumer
        in hashmap_insert masks by ``pending``, and the two strategies
        legitimately differ there: claim hands every packet its
        bucket's pending reps, sort groups non-pending packets into
        their own runs). Pinned at a geometry per sort encoding, so an
        edit that breaks only the over-31-bit fallback can't hide
        behind suites that never leave the packed path."""
        rng = np.random.default_rng(3)
        h = jnp.asarray(rng.integers(0, nb, batch).astype(np.int32))
        pending = jnp.asarray(rng.random(batch) < 0.6)
        out = {}
        for mode in ("claim", "sort"):
            monkeypatch.setenv("VPPT_SESS_ELECTION", mode)
            out[mode] = np.asarray(
                sess_ops._bucket_reps(h, pending, nb, WAYS))
        pen = np.asarray(pending)
        np.testing.assert_array_equal(out["claim"][pen], out["sort"][pen])
        # sanity: some buckets exercised the full rep window
        assert (out["sort"][pen] < batch).all(axis=1).any()


# --- fastpath hit rate under churn (dataplane level) -----------------


def make_churn_dp(stride=2):
    """Tiny dataplane with the fast path armed and an aggressive sweep
    (nb = 256/4 = 64 buckets, stride 2 -> full aging cycle every 32
    steps) so the sweep provably runs DURING the measured churn."""
    dp = Dataplane(DataplaneConfig(
        sess_slots=256, sess_ways=4, sess_sweep_stride=stride,
        sess_max_age=100, max_ifaces=8, fib_slots=16,
        fastpath=True, fastpath_min_rules=0,
    ))
    client = dp.add_pod_interface(("d", "c"))
    server = dp.add_pod_interface(("d", "s"))
    dp.builder.add_route("10.1.1.2/32", client, Disposition.LOCAL)
    dp.builder.add_route("10.1.1.3/32", server, Disposition.LOCAL)
    dp.builder.set_global_table(
        [ContivRule(action=Action.PERMIT, protocol=Protocol.ANY)])
    dp.swap()
    return dp, client, server


def fwd_batch(n, client):
    return make_packet_vector([
        {"src": "10.1.1.2", "dst": "10.1.1.3", "proto": 6,
         "sport": 1000 + i, "dport": 80, "rx_if": client}
        for i in range(n)], n=max(64, n))


def rep_batch(n, server):
    return make_packet_vector([
        {"src": "10.1.1.3", "dst": "10.1.1.2", "proto": 6,
         "sport": 80, "dport": 1000 + i, "rx_if": server}
        for i in range(n)], n=max(64, n))


class TestFastpathUnderChurn:
    @pytest.mark.jit_budget(4)
    def test_hit_rate_held_with_sweep_running(self, jit_compile_budget):
        """session_batch_summary must keep gating correctly while the
        in-step sweep ages buckets under it AND victim eviction churns
        the table. Under adversarial pressure a full bucket caps at W
        resident flows and rotates its overflow (the keepalive's
        re-insert victimizes a sibling whose pre-batch timestamp is
        oldest — way priorities are gathered PRE-batch), so the honest
        invariants are: (a) the dispatch predicate is exactly the
        all-hit condition, every batch; (b) the PACKET-level hit rate —
        the production fastpath_hit_pct signal — holds high; (c) the
        fast tier engages while the table is uncontended. Non-default
        sweep stride = its own step variant; the budget proves the
        whole loop compiles it once."""
        dp, client, server = make_churn_dp(stride=2)
        n = 48
        r0 = dp.process(fwd_batch(n, client), now=1)
        # no bucket got > W of the 48 core flows (deterministic hash)
        assert int(r0.stats.sess_insert_fail) == 0
        fast = reply_batches = hits_total = evicted = 0
        now = 1
        for cycle in range(12):
            # churn: 16 fresh one-shot flows -> full chain; full
            # buckets admit them by victim-evicting their oldest way
            now += 7
            pv = make_packet_vector([
                {"src": "10.1.1.2", "dst": "10.1.1.3", "proto": 6,
                 "sport": 5000 + cycle * 16 + i, "dport": 80,
                 "rx_if": client} for i in range(16)], n=64)
            res = dp.process(pv, now=now)
            assert int(res.stats.fastpath) == 0
            evicted += int(res.stats.sess_evict_victim)
            # forward keepalive: refreshes every resident core session
            # and re-admits evicted ones (never losing ground: the
            # bucket keeps W of its contenders resident)
            now += 7
            res = dp.process(fwd_batch(n, client), now=now)
            assert int(res.stats.sess_insert_fail) == 0
            evicted += int(res.stats.sess_evict_victim)
            for _ in range(3):  # reply traffic between churn bursts
                now += 7  # < max_age: refreshes keep sessions alive
                res = dp.process(rep_batch(n, server), now=now)
                fp, sh = int(res.stats.fastpath), int(res.stats.sess_hits)
                # (a) gating exactness: fast iff EVERY reply hit
                assert fp == (1 if sh == n else 0), f"cycle {cycle}"
                fast += fp
                hits_total += sh
                reply_batches += 1
        # (b) packet-level hit rate held under churn + sweep (observed
        # deterministic value: 0.970 — full buckets rotate 1-3 flows)
        assert hits_total / (reply_batches * n) >= 0.95
        # (c) the fast tier engaged while the table was uncontended
        assert fast >= 3
        # the churn was real: full buckets admitted by victim eviction
        assert evicted > 0
        # and the amortized sweep cycled the whole ring meanwhile
        # (1 process call per step, stride 2, 64 buckets)
        steps = 1 + 12 * 5
        assert int(np.asarray(dp.tables.sess_sweep_cursor)) == (
            steps * 2) % 64

    @pytest.mark.slow  # ~12 s: churn soak; sweep reclaim correctness is covered fast by the other churn tests
    def test_sweep_reclaims_expired_without_bulk_pass(self):
        """After flows idle past max_age, continuing to process
        (denied) traffic lets the IN-STEP sweep return their ways to
        the free pool — no expire_sessions() call — and expired
        sessions stop admitting replies (miss -> full chain)."""
        dp, client, server = make_churn_dp(stride=8)  # cycle = 8 steps
        n = 32
        dp.process(fwd_batch(n, client), now=1)
        assert int(np.asarray(dp.tables.sess_valid).sum()) == n
        # replies ride the fast path while live
        r = dp.process(rep_batch(n, server), now=50)
        assert int(r.stats.fastpath) == 1
        # idle far past max_age, then keep the pipeline ticking with
        # packets that never insert sessions: a DENY-ANY local table on
        # the client rx interface (the global table does not classify
        # pod-to-pod local traffic) -> denied -> not forwarded -> no
        # session want
        slot = dp.alloc_table_slot("deny")
        dp.builder.set_local_table(slot, [
            ContivRule(action=Action.DENY, protocol=Protocol.ANY)])
        dp.assign_pod_table(("d", "c"), "deny")
        dp.swap()  # swap carries session state over by reference
        assert int(np.asarray(dp.tables.sess_valid).sum()) == n
        now = 500  # > max_age past every last-hit
        denied = make_packet_vector([
            {"src": "10.1.1.2", "dst": "10.1.1.3", "proto": 6,
             "sport": 9000 + i, "dport": 23, "rx_if": client}
            for i in range(8)], n=64)
        for step in range(256 // 4 // 8):  # one full sweep cycle
            r = dp.process(denied, now=now + step)
            assert int(r.stats.sess_occupancy) == 0  # live-only gauge
        # the sweep (not any host bulk pass) freed the ways
        assert int(np.asarray(dp.tables.sess_valid).sum()) == 0
        # and the dead sessions no longer admit replies
        r = dp.process(rep_batch(n, server), now=now + 60)
        assert int(r.stats.fastpath) == 0
        assert int(r.stats.sess_hits) == 0
