"""Agent flavor tests: full DI wiring, the KSR→store→agent dataflow
spine, CNI integration, config loading, and graceful shutdown.

Reference model: the control-plane dataflow of SURVEY.md §1 — K8s API →
KSR reflectors → data store → agent watchers → policy/service plugins →
renderers → data plane — exercised end to end in-process with a shared
in-memory store standing in for ETCD.
"""

import textwrap

import pytest

from vpp_tpu.cmd import AgentConfig, ContivAgent, load_config
from vpp_tpu.cmd.ksr_main import KsrAgent
from vpp_tpu.cni.model import CNIRequest
from vpp_tpu.ksr import model as m
from vpp_tpu.kvstore.store import KVStore
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector


def boot(node_name="node-a"):
    store = KVStore()
    ksr = KsrAgent(store=store, serve_http=False)
    ksr.start()
    agent = ContivAgent(
        AgentConfig(node_name=node_name, serve_http=False), store=store
    )
    agent.start()
    return store, ksr, agent


def add_pod(agent, cid, name, ns="default"):
    reply = agent.cni_server.add(CNIRequest(
        container_id=cid,
        extra_args={"K8S_POD_NAME": name, "K8S_POD_NAMESPACE": ns},
    ))
    assert reply.result == 0
    return reply.interfaces[0].ip_addresses[0].address.split("/")[0]


def send(agent, src_pod, src_ip, dst_ip, dport, proto=6, sport=44444):
    pkts = make_packet_vector([
        {"src": src_ip, "dst": dst_ip, "proto": proto, "sport": sport,
         "dport": dport, "rx_if": agent.dataplane.pod_if[src_pod]}
    ])
    res = agent.dataplane.process(pkts)
    return Disposition(int(res.disp[0])), res


def reflect_pod(ksr, name, ip, labels, ns="default"):
    ksr.sources[m.Pod.TYPE].add(
        f"{ns}/{name}",
        m.Pod(name=name, namespace=ns, labels=labels, ip_address=ip),
    )


def test_agent_boots_and_allocates_node_id():
    store, ksr, agent = boot()
    assert agent.node_id == 1
    assert agent.statuscheck.liveness()["ready"] is True
    agent.close()


def test_full_spine_policy_enforcement():
    """KSR reflects pods+policy → agent watch bridge → renderers → verdicts."""
    store, ksr, agent = boot()
    ip_web = add_pod(agent, "c-web", "web")
    ip_db = add_pod(agent, "c-db", "db")
    ip_cli = add_pod(agent, "c-cli", "client")

    # KSR side: reflect the pods (as the k8s API would show them)
    reflect_pod(ksr, "web", ip_web, {"app": "web"})
    reflect_pod(ksr, "db", ip_db, {"app": "db"})
    reflect_pod(ksr, "client", ip_cli, {"app": "client"})
    ksr.sources[m.Namespace.TYPE].add(
        "default", m.Namespace(name="default", labels={})
    )

    # no policy yet: everything flows
    disp, _ = send(agent, ("default", "client"), ip_cli, ip_db, 5432)
    assert disp == Disposition.LOCAL

    # reflect a NetworkPolicy: db accepts only web on TCP:5432
    ksr.sources[m.Policy.TYPE].add("default/db-policy", m.Policy(
        name="db-policy", namespace="default",
        pods=m.LabelSelector(match_labels={"app": "db"}),
        policy_type=m.POLICY_INGRESS,
        ingress_rules=[m.PolicyRule(
            ports=[m.PolicyPort(protocol="TCP", port=5432)],
            peers=[m.PolicyPeer(
                pods=m.LabelSelector(match_labels={"app": "web"}))],
        )],
    ))

    disp, _ = send(agent, ("default", "client"), ip_cli, ip_db, 5432)
    assert disp == Disposition.DROP, "client is not app=web"
    disp, _ = send(agent, ("default", "web"), ip_web, ip_db, 5432)
    assert disp == Disposition.LOCAL, "web may reach db:5432"
    disp, _ = send(agent, ("default", "web"), ip_web, ip_db, 9999)
    assert disp == Disposition.DROP, "wrong port"

    # policy deleted → open again
    ksr.sources[m.Policy.TYPE].delete("default/db-policy")
    disp, _ = send(agent, ("default", "client"), ip_cli, ip_db, 5432)
    assert disp == Disposition.LOCAL
    agent.close()


def test_full_spine_service_nat():
    store, ksr, agent = boot()
    ip_cli = add_pod(agent, "c-cli", "client")
    ip_be = add_pod(agent, "c-be", "backend")

    ksr.sources[m.Service.TYPE].add("default/web", m.Service(
        name="web", namespace="default", cluster_ip="10.96.0.50",
        ports=[m.ServicePort(name="http", protocol="TCP", port=80,
                             target_port="http")],
    ))
    ksr.sources[m.Endpoints.TYPE].add("default/web", m.Endpoints(
        name="web", namespace="default",
        subsets=[m.EndpointSubset(
            addresses=[m.EndpointAddress(ip=ip_be, node_name="node-a")],
            ports=[m.EndpointPort(name="http", port=8080, protocol="TCP")],
        )],
    ))

    disp, res = send(agent, ("default", "client"), ip_cli, "10.96.0.50", 80)
    assert disp == Disposition.LOCAL
    assert int(res.pkts.dport[0]) == 8080, "DNAT to target port"
    agent.close()


def test_vpptcp_renderer_gets_policies_too():
    store, ksr, agent = boot()
    ip_web = add_pod(agent, "c-web", "web")
    ip_db = add_pod(agent, "c-db", "db")
    reflect_pod(ksr, "web", ip_web, {"app": "web"})
    reflect_pod(ksr, "db", ip_db, {"app": "db"})
    ksr.sources[m.Namespace.TYPE].add(
        "default", m.Namespace(name="default", labels={})
    )
    ksr.sources[m.Policy.TYPE].add("default/db-policy", m.Policy(
        name="db-policy", namespace="default",
        pods=m.LabelSelector(match_labels={"app": "db"}),
        policy_type=m.POLICY_INGRESS,
        ingress_rules=[m.PolicyRule(
            ports=[m.PolicyPort(protocol="TCP", port=5432)],
            peers=[m.PolicyPeer(
                pods=m.LabelSelector(match_labels={"app": "web"}))],
        )],
    ))
    assert agent.session_engine.num_rules > 0, "session rules installed"
    agent.close()


def test_agent_restart_resyncs_pods():
    store = KVStore()
    agent = ContivAgent(AgentConfig(node_name="n1", serve_http=False), store=store)
    agent.start()
    ip = add_pod(agent, "c1", "p1")
    agent.close()

    agent2 = ContivAgent(AgentConfig(node_name="n1", serve_http=False), store=store)
    agent2.start()
    assert ("default", "p1") in agent2.dataplane.pod_if
    assert agent2.node_id == 1, "same node keeps its ID"
    agent2.close()


def test_two_agents_get_distinct_node_ids_and_subnets():
    store = KVStore()
    a = ContivAgent(AgentConfig(node_name="n1", serve_http=False), store=store)
    b = ContivAgent(AgentConfig(node_name="n2", serve_http=False), store=store)
    assert (a.node_id, b.node_id) == (1, 2)
    assert a.ipam.pod_network != b.ipam.pod_network
    a.close(); b.close()


def test_agent_resyncs_preexisting_ksr_state():
    """KSR reflected objects BEFORE the agent started: the first resync
    must replay them into the policy cache and service processor."""
    store = KVStore()
    ksr = KsrAgent(store=store, serve_http=False)
    ksr.start()
    # reflect everything while no agent exists
    reflect_pod(ksr, "web", "10.1.1.10", {"app": "web"})
    reflect_pod(ksr, "db", "10.1.1.11", {"app": "db"})
    ksr.sources[m.Namespace.TYPE].add(
        "default", m.Namespace(name="default", labels={})
    )
    ksr.sources[m.Policy.TYPE].add("default/db-policy", m.Policy(
        name="db-policy", namespace="default",
        pods=m.LabelSelector(match_labels={"app": "db"}),
        policy_type=m.POLICY_INGRESS,
        ingress_rules=[m.PolicyRule(
            ports=[m.PolicyPort(protocol="TCP", port=5432)],
            peers=[m.PolicyPeer(
                pods=m.LabelSelector(match_labels={"app": "web"}))],
        )],
    ))

    agent = ContivAgent(AgentConfig(node_name="late", serve_http=False),
                        store=store)
    agent.start()
    # resync picked up the reflected objects
    assert agent.policy_cache.lookup_pod(("default", "web")) is not None
    ip_web = add_pod(agent, "c-web", "web")
    ip_db = add_pod(agent, "c-db", "db")
    # kubelet assigned real IPs; KSR re-reflects them
    reflect_pod(ksr, "web", ip_web, {"app": "web"})
    reflect_pod(ksr, "db", ip_db, {"app": "db"})
    # the pre-existing policy must be enforced
    disp, _ = send(agent, ("default", "web"), ip_web, ip_db, 9999)
    assert disp == Disposition.DROP, "pre-existing policy enforced"
    disp, _ = send(agent, ("default", "web"), ip_web, ip_db, 5432)
    assert disp == Disposition.LOCAL
    agent.close()


def test_node_events_install_and_remove_peer_routes():
    """Two agents on one store: each learns the other's subnets and
    routes them REMOTE via the peer VTEP (node_events.go analog)."""
    store = KVStore()
    a = ContivAgent(AgentConfig(node_name="n1", serve_http=False), store=store)
    a.start()
    b = ContivAgent(AgentConfig(node_name="n2", serve_http=False), store=store)
    b.start()

    ip_a = add_pod(a, "c1", "p1")
    # a pod on node A sending to node B's pod subnet → REMOTE toward B
    dst_b = str(b.ipam.pod_gateway_ip() + 5)
    disp, res = send(a, ("default", "p1"), ip_a, dst_b, 80)
    assert disp == Disposition.REMOTE
    assert int(res.node_id[0]) == b.node_id
    outer = a.dataplane.encap_remote(res)
    assert bool(outer.valid[0])
    assert int(outer.dst_ip[0]) == int(a.ipam.vxlan_ip_address(b.node_id))

    # B also learned A (it listed existing nodes at startup)
    ip_b = add_pod(b, "c2", "p2")
    disp_b, res_b = send(b, ("default", "p2"), ip_b, ip_a, 80)
    assert disp_b == Disposition.REMOTE
    assert int(res_b.node_id[0]) == a.node_id

    # node removal deletes the routes
    b.node_allocator.release()
    disp, _ = send(a, ("default", "p1"), ip_a, dst_b, 80)
    assert disp == Disposition.DROP
    a.close(); b.close()


def test_node_crash_lease_expiry_removes_peer_routes():
    """A node that dies WITHOUT cleanup (kill -9, partition) must lose
    its routes on peers once its liveness lease expires — the etcd-lease
    liveness mechanism (VERDICT r2 Next #8). Clean release() is covered
    above; this is the crash path: no delete is ever issued."""
    store = KVStore()
    a = ContivAgent(AgentConfig(node_name="n1", serve_http=False), store=store)
    a.start()
    b = ContivAgent(AgentConfig(node_name="n2", serve_http=False), store=store)
    b.node_allocator.liveness_ttl_s = 0.3
    b.start()

    ip_a = add_pod(a, "c1", "p1")
    dst_b = str(b.ipam.pod_gateway_ip() + 5)
    disp, _ = send(a, ("default", "p1"), ip_a, dst_b, 80)
    assert disp == Disposition.REMOTE

    # B "crashes": stop its maintenance loop (keepalives) without any
    # cleanup; its allocatedIDs claim stays (ID reuse on restart), but
    # the liveness key must expire
    b._closed.set()
    import time

    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        store.sweep_leases()
        disp, _ = send(a, ("default", "p1"), ip_a, dst_b, 80)
        if disp == Disposition.DROP:
            break
        time.sleep(0.1)
    assert disp == Disposition.DROP
    # the ID claim survives (restarting node-b reuses its ID)
    assert store.get("allocatedIDs/" + str(b.node_id)) is not None
    a.close(); b.close()


def test_config_yaml_roundtrip(tmp_path):
    cfg_file = tmp_path / "contiv.yaml"
    cfg_file.write_text(textwrap.dedent("""
        node_name: worker-7
        stats_port: 19999
        dataplane:
          max_tables: 8
          sess_slots: 512
        ipam:
          pod_subnet_cidr: 10.128.0.0/14
    """))
    cfg = load_config(str(cfg_file))
    assert cfg.node_name == "worker-7"
    assert cfg.stats_port == 19999
    assert cfg.dataplane.max_tables == 8
    assert cfg.ipam.pod_subnet_cidr == "10.128.0.0/14"
    # defaults survive partial files
    assert cfg.health_port == 9191

    bad = tmp_path / "bad.yaml"
    bad.write_text("nonsense_key: 1\n")
    with pytest.raises(ValueError, match="nonsense_key"):
        load_config(str(bad))


def test_maintenance_tick_ages_sessions_and_publishes():
    store, ksr, agent = boot()
    ip1 = add_pod(agent, "c1", "p1")
    ip2 = add_pod(agent, "c2", "p2")
    disp, res = send(agent, ("default", "p1"), ip1, ip2, 80)
    assert disp == Disposition.LOCAL
    import numpy as np
    assert int(np.asarray(agent.dataplane.tables.sess_valid).sum()) == 1
    agent.stats.update(res.stats)

    agent.session_max_age = 0  # everything idle > 0 frames expires
    agent.dataplane._now += 5
    agent.maintenance_tick()
    assert int(np.asarray(agent.dataplane.tables.sess_valid).sum()) == 0
    assert agent.stats.node_gauges["vpp_tpu_node_rx_packets"].get() == 1
    assert agent.statuscheck.liveness()["ready"] is True
    agent.close()


def test_close_is_idempotent_and_stops_watches():
    store, ksr, agent = boot()
    agent.close()
    agent.close()  # second close is a no-op
    # events after close must not reach the plugins
    ksr.sources[m.Pod.TYPE].add(
        "default/late",
        m.Pod(name="late", namespace="default", labels={}, ip_address="10.0.0.9"),
    )
    assert agent.policy_cache.lookup_pod(("default", "late")) is None


def test_cli_socket_serves_debug_commands(tmp_path):
    """A running agent answers vppctl-style commands over its CLI
    socket — the operator path `vpp-tpu-ctl "show interface"`."""
    from vpp_tpu.cmd.ctl import run_line

    store = KVStore()
    cfg = AgentConfig(
        node_name="n1", serve_http=True,
        stats_port=0, health_port=0,
        cni_socket=str(tmp_path / "cni.sock"),
        cli_socket=str(tmp_path / "cli.sock"),
    )
    agent = ContivAgent(cfg, store=store)
    agent.start()
    try:
        out = run_line(cfg.cli_socket, "show interface", timeout=10)
        assert "uplink" in out
        out = run_line(cfg.cli_socket, "show fib", timeout=10)
        assert "0.0.0.0/0" in out
        out = run_line(cfg.cli_socket, "help", timeout=10)
        assert "test connectivity" in out
        # unknown commands degrade to a message over the wire
        out = run_line(cfg.cli_socket, "bogus words", timeout=10)
        assert "unknown command" in out
        # the vppctl trace workflow: arm over the socket, traffic
        # through the dataplane, render the captured path
        out = run_line(cfg.cli_socket, "trace add 4", timeout=10)
        assert "tracing the next 4" in out
        from vpp_tpu.pipeline.vector import make_packet_vector

        agent.dataplane.process(make_packet_vector([
            {"src": "10.9.9.9", "dst": "10.9.9.10", "proto": 17,
             "sport": 1, "dport": 2, "rx_if": agent.uplink_if}
        ]))
        out = run_line(cfg.cli_socket, "show trace", timeout=10)
        # src shows post-SNAT (cluster egress rewrites to the node IP)
        assert "10.9.9.10" in out and "ip4-input" in out
        assert "cleared" in run_line(cfg.cli_socket, "trace clear", 10)
    finally:
        agent.close()
