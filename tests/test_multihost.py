"""Multi-host (DCN) fabric: the cluster step across REAL separate JAX
processes.

Two worker processes (2 virtual CPU devices each) form one 4-node
cluster mesh via jax.distributed; each stages only its local nodes,
publication and stepping are collective. Traffic crosses the
process boundary through the same all_to_all fabric the single-process
mesh uses — on TPU pods the identical program rides ICI within a host
and DCN between hosts (reference analog: the VXLAN full-mesh between
DaemonSet replicas, plugins/contiv/node_events.go:184-250).
"""

import json
import os
import socket
import subprocess
import sys

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_fabric():
    port = _free_port()
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "mh_worker.py")
    env = dict(os.environ)
    # the workers set their own JAX env; scrub the conftest's 8-device
    # forcing and any axon plugin so distributed init is clean
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":")
        if p and "axon" not in p)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in range(2)
    ]
    outs = {}
    try:
        for pid, p in enumerate(procs):
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker {pid}: {err[-800:]}"
            line = [ln for ln in out.splitlines()
                    if ln.startswith("VERDICT ")][-1]
            outs[pid] = json.loads(line[len("VERDICT "):])
    finally:
        # one worker failing leaves its peer parked in a collective —
        # never orphan it on the machine
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)

    # P0 fabric-routed all three packets
    assert outs[0]["local_nodes"] == [0, 1]
    assert outs[0]["sent_remote"] == 3
    # P1: pod2 got its packet on the right interface; node 3's global
    # table let port 80 through and dropped port 22
    assert outs[1]["local_nodes"] == [2, 3]
    assert outs[1]["pod2_delivered"] == 1
    assert outs[1]["pod2_txif_ok"] and outs[1]["pod2_dst_ok"]
    assert outs[1]["pod3_delivered"] == 1
    assert outs[1]["node3_acl_drops"] == 1
    # step 2: the reply crossed back P1 -> P0
    assert outs[0]["reply_delivered"] == 1
