"""Multi-host (DCN) fabric: the cluster step across REAL separate JAX
processes.

Two worker processes (2 virtual CPU devices each) form one 4-node
cluster mesh via jax.distributed; each stages only its local nodes,
publication and stepping are collective. Traffic crosses the
process boundary through the same all_to_all fabric the single-process
mesh uses — on TPU pods the identical program rides ICI within a host
and DCN between hosts (reference analog: the VXLAN full-mesh between
DaemonSet replicas, plugins/contiv/node_events.go:184-250).
"""

import contextlib
import json
import os
import socket
import subprocess
import sys
import time

import pytest

# slow: each case boots 2 real jax.distributed worker processes and
# compiles the cluster program per process — minutes of wall clock
# that the tier-1 `-m 'not slow'` budget cannot absorb now that the
# mesh suite actually RUNS on this toolchain (ISSUE 12 un-skipped it).
# The multi-process fabric additionally needs a CPU backend with
# cross-process collectives (newer jaxlib); `make chaos`-style full
# runs and TPU-pod deployments exercise these.
pytestmark = pytest.mark.slow

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env():
    """Workers set their own JAX env; scrub the conftest's 8-device
    forcing and any axon plugin so distributed init is clean."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":")
        if p and "axon" not in p)
    return env


def _collect_verdicts(procs, timeout: float):
    """communicate() every worker, assert clean exits, parse the
    VERDICT lines; reaps everyone on the way out — a failed worker
    must not orphan its peer inside a jax.distributed collective."""
    outs = {}
    try:
        for pid, p in enumerate(procs):
            out, err = p.communicate(timeout=timeout)
            assert p.returncode == 0, f"worker {pid}: {err[-800:]}"
            lines = [ln for ln in out.splitlines()
                     if ln.startswith("VERDICT ")]
            assert lines, (f"worker {pid} printed no VERDICT line; "
                           f"stderr: {err[-800:]}")
            outs[pid] = json.loads(lines[-1][len("VERDICT "):])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    return outs


def _run_workers(script: str, extra_args=(), n_procs: int = 2,
                 timeout: float = 240):
    """Spawn the worker processes and collect their VERDICT lines."""
    env = _worker_env()
    coord_port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, script), str(pid),
             str(n_procs), str(coord_port), *map(str, extra_args)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in range(n_procs)
    ]
    return _collect_verdicts(procs, timeout)


def test_two_process_fabric():
    outs = _run_workers("mh_worker.py")

    # P0 fabric-routed all three packets
    assert outs[0]["local_nodes"] == [0, 1]
    assert outs[0]["sent_remote"] == 3
    # P1: pod2 got its packet on the right interface; node 3's global
    # table let port 80 through and dropped port 22
    assert outs[1]["local_nodes"] == [2, 3]
    assert outs[1]["pod2_delivered"] == 1
    assert outs[1]["pod2_txif_ok"] and outs[1]["pod2_dst_ok"]
    assert outs[1]["pod3_delivered"] == 1
    assert outs[1]["node3_acl_drops"] == 1
    # step 2: the reply crossed back P1 -> P0
    assert outs[0]["reply_delivered"] == 1


@contextlib.contextmanager
def _kvserver(tmp_path):
    """Spawn a real TCP kvserver; yields its port, reaps on exit."""
    port_file = str(tmp_path / "kv.port")
    kv = subprocess.Popen(
        [sys.executable, "-m", "vpp_tpu.cmd.kvserver", "--host",
         "127.0.0.1", "--port", "0", "--port-file", port_file],
        env=_worker_env())
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not os.path.exists(port_file):
            assert kv.poll() is None, \
                f"kvserver died at startup (rc={kv.returncode})"
            time.sleep(0.2)
        assert os.path.exists(port_file), "kvserver never wrote its port"
        yield open(port_file).read().strip()
    finally:
        kv.kill()
        kv.wait(timeout=30)


def test_lockstep_commit_across_processes(tmp_path):
    """Control-plane half of multi-host: process 1 stages a policy
    change on its node and requests a commit through the shared
    kvstore; the LockstepDriver's collective min-agreement makes BOTH
    processes publish on the same tick — cross-process traffic that
    flowed on tick 1 is cut off cluster-wide from tick 2."""
    with _kvserver(tmp_path) as kv_port:
        outs = _run_workers("mh_lockstep_worker.py", [kv_port])

    v = outs[1]
    assert v["t1_delivered"] == 1          # flowing before the commit
    assert v["t2_epoch"] == 2              # both published on tick 2
    assert v["t2_delivered"] == 0          # cut off the same tick
    assert v["t2_acl_drops"] == 1
    assert v["t3_delivered"] == 0
    assert outs[0]["applied"] == 1 and outs[1]["applied"] == 1


def test_deployed_runtime_across_processes(tmp_path):
    """The DEPLOYED multi-host form (vpp-tpu-mesh-agent --coordinator
    shape): real ContivAgents on each process over a shared kvstore —
    CNI pod adds, node events resolving peers to mesh positions across
    the process boundary, fabric delivery, then a renderer-driven
    policy cutoff — every commit riding LockstepDriver epochs."""
    with _kvserver(tmp_path) as kv_port:
        outs = _run_workers("mh_runtime_worker.py", [kv_port])

    assert outs[0]["stage1_ok"] is True
    assert outs[1]["stage1_delivered"] >= 1       # fabric worked
    assert outs[1]["stage2_new_deliveries"] == 0  # policy cut it off
    assert outs[1]["stage2_acl_drops"] >= 1


def test_wire_path_across_processes(tmp_path):
    """io.enabled multi-host: real wire frames (Ethernet/IP/UDP bytes)
    pushed into one host's per-node rx ring ride the fabric — headers
    AND payload — across the process boundary and surface on the
    destination host's tx ring with the UDP body intact; a
    renderer-driven deny then cuts the wire path. The ClusterPump runs
    tick-driven (writer thread only), so its collective wire step
    interleaves deterministically with the lockstep driver."""
    with _kvserver(tmp_path) as kv_port:
        outs = _run_workers("mh_wire_worker.py", [kv_port])

    assert outs[0]["stage1_ok"] is True
    assert outs[0]["idle_steps_flat"] is True   # fleet-idle skips steps
    assert outs[1]["wire_delivered"] >= 1
    assert outs[1]["commit_stepped"] is True    # commit tick always steps
    assert outs[1]["stage2_cut"] is True


def test_mxu_selection_and_equivalence():
    """publish() agrees on the MXU classifier fleet-wide at
    bit-plane-compatible scale and its verdicts match the dense path
    packet-for-packet (the multi-host analog of the cluster MXU
    equivalence tests)."""
    outs = _run_workers("mh_mxu_worker.py", n_procs=1,
                        timeout=480)  # two clusters +
    # dense AND MXU step compiles share one core
    v = outs[0]
    assert v["mxu_selected"] is True
    assert v["verdicts_equal"] is True
    assert v["drop_acl"] >= 1        # some flows hit DENY rules
    assert v["delivered"] >= 1       # and some flows got through


def test_lockstep_survives_store_failover(tmp_path):
    """The multi-host fleet's coordination store dies mid-lockstep:
    witness-arbitrated failover promotes the standby, the workers'
    clients fail over (reads never stopped; writes resume at the
    bumped fencing epoch), and a policy commit REQUESTED THROUGH THE
    NEW PRIMARY still publishes on the same collective tick on both
    processes — the fenced store is transparent to the SPMD control
    loop (kvstore/witness.py + docs/MULTIHOST.md note)."""
    import signal

    from vpp_tpu.kvstore.client import RemoteKVStore
    from vpp_tpu.kvstore.witness import WitnessClient

    env = _worker_env()

    reap = []  # every spawned process, in spawn order — the finally
    #            tears down whatever managed to start, so a failed
    #            LATER spawn can't orphan the earlier servers

    def _spawn_store(name, argv):
        pf = str(tmp_path / f"{name}.port")
        p = subprocess.Popen(
            [sys.executable, "-m", argv[0], *argv[1:],
             "--port-file", pf], env=env)
        reap.append(p)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not os.path.exists(pf):
            assert p.poll() is None, f"{name} died at startup"
            time.sleep(0.2)
        assert os.path.exists(pf), f"{name} never wrote its port"
        return p, int(open(pf).read())

    cli = None
    procs = []
    try:
        witness, w_port = _spawn_store("w", [
            "vpp_tpu.cmd.kvwitness", "--host", "127.0.0.1",
            "--port", "0"])
        primary, kv_port = _spawn_store("kv", [
            "vpp_tpu.cmd.kvserver", "--host", "127.0.0.1", "--port", "0",
            "--witness", f"127.0.0.1:{w_port}", "--fence-ttl", "6"])
        standby, sb_port = _spawn_store("sb", [
            "vpp_tpu.cmd.kvserver", "--host", "127.0.0.1", "--port", "0",
            "--follow", f"127.0.0.1:{kv_port}",
            "--witness", f"127.0.0.1:{w_port}",
            "--fence-ttl", "6", "--promote-after", "3"])
        store_url = f"tcp://127.0.0.1:{kv_port},127.0.0.1:{sb_port}"

        coord_port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable,
                 os.path.join(HERE, "mh_lockstep_failover_worker.py"),
                 str(pid), "2", str(coord_port), store_url],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            for pid in range(2)
        ]
        cli = RemoteKVStore(
            "127.0.0.1", kv_port, request_timeout=60.0,
            reconnect_timeout=60.0,
            fallbacks=[("127.0.0.1", sb_port)])
        # both workers mid-run (tick 1 done) before the kill
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if cli.get("mhf/ready/0") == 1 and cli.get("mhf/ready/1") == 1:
                break
            assert all(p.poll() is None for p in procs), \
                "a worker died before the failover"
            time.sleep(0.5)
        else:
            raise AssertionError("workers never reached the ready point")

        primary.send_signal(signal.SIGKILL)
        primary.wait(timeout=15)
        wc = WitnessClient(f"127.0.0.1:{w_port}")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = wc.status()
            if st["primary"] == f"127.0.0.1:{sb_port}" and st["epoch"] >= 1:
                break
            time.sleep(0.5)
        else:
            raise AssertionError(f"standby never promoted: {wc.status()}")
        cli.put("mhf/go", 1)   # lands on the NEW primary, fenced

        outs = _collect_verdicts(procs, timeout=420)
    finally:
        if cli is not None:
            cli.close()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
        for p in reversed(reap):
            if p.poll() is None:
                p.terminate()
        for p in reap:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()

    for pid in (0, 1):
        # exactly ONE promotion happened: the primary adopted at epoch
        # 0 (renew, no bump), the standby's granted claim bumped to 1,
        # and both workers' post-failover writes carry it
        assert outs[pid]["fence_epoch"] == 1
        assert outs[pid]["applied"] == 1          # commit applied once
        assert outs[pid]["t3_epoch"] == 2         # same tick, both procs
    v = outs[1]
    assert v["t1_delivered"] == 1     # flowing before the failover
    assert v["t2_delivered"] == 1     # still flowing right after it
    assert v["t3_delivered"] == 0     # cut by the post-failover commit
