"""Debug CLI tests (vppctl `show ...` analog)."""

import ipaddress

from vpp_tpu.cli import DebugCLI
from vpp_tpu.ir import Action, ContivRule, Protocol
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, ip4, make_packet_vector
from vpp_tpu.trace import PacketTracer


def make_env():
    dp = Dataplane(DataplaneConfig(sess_slots=256))
    uplink = dp.add_uplink()
    a = dp.add_pod_interface(("default", "web"))
    dp.builder.add_route("10.1.1.2/32", a, Disposition.LOCAL)
    dp.builder.add_route("10.2.0.0/16", uplink, Disposition.REMOTE,
                         next_hop=ip4("192.168.16.2"), node_id=2)
    slot = dp.alloc_table_slot("T1")
    dp.builder.set_local_table(slot, [
        ContivRule(action=Action.PERMIT,
                   dest_network=ipaddress.ip_network("10.1.1.2/32"),
                   protocol=Protocol.TCP, dest_port=80),
        ContivRule(action=Action.DENY),
    ])
    dp.assign_pod_table(("default", "web"), "T1")
    dp.builder.set_nat_mapping(
        0, ext_ip=ip4("10.96.0.9"), ext_port=80, proto=6,
        backends=[(ip4("10.1.1.2"), 8080, 2), (ip4("10.1.1.3"), 8080, 1)],
        boff=0,
    )
    dp.swap()
    return dp, a, uplink


def test_show_interface_and_fib():
    dp, a, uplink = make_env()
    cli = DebugCLI(dp)
    out = cli.run("show interface")
    assert "default/web" in out and "uplink" in out
    out = cli.run("show fib")
    assert "10.1.1.2/32" in out
    assert "10.2.0.0/16" in out and "node 2" in out and "192.168.16.2" in out


def test_show_acl_and_nat():
    dp, a, uplink = make_env()
    cli = DebugCLI(dp)
    out = cli.run("show acl")
    assert "local table T1" in out
    assert "permit tcp" in out and ":80" in out
    assert "deny tcp" in out  # ContivRule default protocol is TCP
    out = cli.run("show nat44")
    assert "10.96.0.9:80" in out
    assert "weight 2" in out and "weight 1" in out


def test_show_session_and_trace_and_unknown():
    dp, a, uplink = make_env()
    tracer = PacketTracer()
    dp.tracer = tracer
    tracer.add(5)
    dp.process(make_packet_vector([
        dict(src="10.9.9.9", dst="10.1.1.2", proto=6, sport=1234, dport=80,
             rx_if=uplink)
    ]))
    cli = DebugCLI(dp, tracer=tracer)
    out = cli.run("show session")
    assert "1 established sessions" in out
    assert "10.9.9.9" in out
    out = cli.run("show trace")
    assert "10.9.9.9 -> 10.1.1.2" in out
    assert "unknown command" in cli.run("bogus thing")
    assert "show nat44" in cli.run("help")


def test_show_io_with_pump_and_daemon():
    """show io surfaces pump + daemon counters through the control
    socket (the vector-rates analog for the host IO path)."""
    import tempfile

    import numpy as np

    from vpp_tpu.cli import DebugCLI
    from vpp_tpu.io.control import IOControlClient, IOControlServer
    from vpp_tpu.io.daemon import IODaemon
    from vpp_tpu.io.pump import DataplanePump
    from vpp_tpu.io.rings import IORingPair
    from vpp_tpu.native.pktio import PacketCodec
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import VEC, Disposition

    dp = Dataplane(DataplaneConfig())
    a = dp.add_pod_interface(("default", "a"))
    b = dp.add_pod_interface(("default", "b"))
    dp.builder.add_route("10.1.1.3/32", b, Disposition.LOCAL)
    dp.swap()
    rings = IORingPair(n_slots=8)
    daemon = IODaemon(rings, {}, uplink_if=0).start()
    sock = tempfile.mktemp(suffix=".sock")
    control = IOControlServer(daemon, sock).start()
    pump = DataplanePump(dp, rings).start()
    try:
        # push one frame through so counters are non-trivial
        from wire import make_frame

        codec = PacketCodec()
        frame = make_frame("10.1.1.2", "10.1.1.3", proto=17, dport=53)
        scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        cols, n = codec.parse([frame], a, scratch)
        rings.rx.push(cols, n, payload=scratch)
        deadline = __import__("time").monotonic() + 60
        while pump.stats["frames"] < 1 and \
                __import__("time").monotonic() < deadline:
            __import__("time").sleep(0.05)

        cli = DebugCLI(dp, pump=pump, io_ctl=IOControlClient(sock))
        out = cli.run("show io")
        assert "pump (dispatch): 1 frames" in out
        assert "io-daemon: rx" in out
        assert "batch latency" in out
        assert "interfaces" in out
    finally:
        pump.stop()
        control.close()
        daemon.stop()
        rings.close()


def test_show_neighbors_lists_static_and_learned():
    """show neighbors renders the daemon's (ip → MAC) table over the
    control socket — the `show ip arp` analog; static entries carry S."""
    import tempfile

    from vpp_tpu.cli import DebugCLI
    from vpp_tpu.io.control import IOControlClient, IOControlServer
    from vpp_tpu.io.daemon import IODaemon
    from vpp_tpu.io.rings import IORingPair
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import ip4

    dp = Dataplane(DataplaneConfig())
    rings = IORingPair(n_slots=8)
    daemon = IODaemon(rings, {}, uplink_if=0)
    sock = tempfile.mktemp(suffix=".sock")
    control = IOControlServer(daemon, sock).start()
    try:
        client = IOControlClient(sock)
        client.set_mac(ip4("10.1.1.7"), bytes.fromhex("02aabbccddee"))
        daemon.mac.put(ip4("10.1.1.8"), bytes.fromhex("020102030405"),
                       pin=False)  # "learned"
        cli = DebugCLI(dp, io_ctl=client)
        out = cli.run("show neighbors")
        assert "10.1.1.7" in out and "02:aa:bb:cc:dd:ee" in out
        line7 = next(ln for ln in out.splitlines() if "10.1.1.7" in ln)
        line8 = next(ln for ln in out.splitlines() if "10.1.1.8" in ln)
        assert line7.rstrip().endswith("S")
        assert not line8.rstrip().endswith("S")
    finally:
        control.close()
        rings.close()


def test_connectivity_probe_reports_verdict_and_path():
    """`test connectivity` injects a synthetic packet from the pod's
    interface, traces it, and reports the verdict (the robot-suite
    ping/TCP checks as a one-shot vppctl command)."""
    import ipaddress

    from vpp_tpu.cli import DebugCLI
    from vpp_tpu.ir.rule import Action, ContivRule, Protocol
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import Disposition

    dp = Dataplane(DataplaneConfig())
    a = dp.add_pod_interface(("default", "a"))
    b = dp.add_pod_interface(("default", "b"))
    dp.builder.add_route("10.1.1.2/32", a, Disposition.LOCAL)
    dp.builder.add_route("10.1.1.3/32", b, Disposition.LOCAL)
    slot = dp.alloc_table_slot("t")
    dp.builder.set_local_table(slot, [
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                   dest_network=ipaddress.ip_network("10.1.1.3/32"),
                   dest_port=80),
        ContivRule(action=Action.DENY, protocol=Protocol.TCP),
    ])
    dp.assign_pod_table(("default", "a"), "t")
    dp.swap()

    cli = DebugCLI(dp)
    ok = cli.run("test connectivity 10.1.1.2 10.1.1.3 tcp 80")
    assert "FORWARDED" in ok and f"if {b}" in ok
    assert "ip4-input" in ok  # the traced path is shown

    denied = cli.run("test connectivity 10.1.1.2 10.1.1.3 tcp 443")
    assert "DROPPED" in denied

    unknown_src = cli.run("test connectivity 172.16.9.9 10.1.1.3 tcp 80")
    assert "no LOCAL route" in unknown_src

    # operator typos degrade to messages, not tracebacks
    assert "bad argument" in cli.run(
        "test connectivity pod-a 10.1.1.3 tcp 80")
    assert "bad argument" in cli.run(
        "test connectivity 10.1.1.2 10.1.1.3 tcp http")
    assert "bad argument" in cli.run(
        "test connectivity 10.1.1.2 10.1.1.300 tcp 80")  # octet > 255
    assert "bad argument" in cli.run(
        "test connectivity 10.1.1.2 10.1.1.3 tcp 99999999999")

    # the probe is side-effect free: no reflective session was
    # installed for the permitted flow (a debug command must not open
    # a return-traffic hole)
    import numpy as np
    assert int(np.asarray(dp.tables.sess_valid).sum()) == 0


def test_show_session_rules():
    """`show session-rules` dumps the VPPTCP renderer's filter tables
    (the `show session rules` analog); without an engine it degrades to
    a message."""
    from vpp_tpu.hoststack.session_rules import (
        RuleAction, RuleScope, SessionRule, SessionRuleEngine,
    )

    dp, a, uplink = make_env()
    assert "no session rule engine" in DebugCLI(dp).run(
        "show session-rules")

    eng = SessionRuleEngine()
    eng.apply(add=[
        SessionRule(scope=int(RuleScope.LOCAL), appns_index=4,
                    transport_proto=6, lcl_net=0, lcl_plen=0,
                    rmt_net=ip4("10.1.1.9"), rmt_plen=32,
                    lcl_port=0, rmt_port=443,
                    action=int(RuleAction.DENY)),
        SessionRule(scope=int(RuleScope.GLOBAL), appns_index=-1,
                    transport_proto=17, lcl_net=ip4("10.1.1.2"),
                    lcl_plen=32, rmt_net=0, rmt_plen=0,
                    lcl_port=53, rmt_port=0,
                    action=int(RuleAction.ALLOW)),
    ])
    out = DebugCLI(dp, session_engine=eng).run("show session-rules")
    assert "2 session rules" in out
    assert "LOCAL ns 4" in out and "10.1.1.9/32:443" in out
    assert "deny" in out
    assert "GLOBAL" in out and "10.1.1.2/32:53" in out and "allow" in out
    # `show session` (the flow table) still resolves independently
    assert "established sessions" in DebugCLI(dp).run("show session")


def test_show_mesh():
    """`show mesh` renders runtime state (nodes, lockstep counters,
    pump stats) from whatever runtime shape is attached; standalone
    agents degrade to a message."""
    import types

    dp, a, uplink = make_env()
    assert "not a mesh agent" in DebugCLI(dp).run("show mesh")

    fake = types.SimpleNamespace(
        cluster=types.SimpleNamespace(n_nodes=4, epoch=7,
                                      local_nodes=[0, 1]),
        driver=types.SimpleNamespace(ticks=123, applied=2,
                                     expire_every=512),
        agents=[types.SimpleNamespace(
            config=types.SimpleNamespace(node_name="mh-0"), node_id=3)],
        cluster_pump=None,
    )
    out = DebugCLI(dp, mesh_runtime=fake).run("show mesh")
    assert "4 nodes, epoch 7" in out
    assert "local mesh rows: [0, 1]" in out
    assert "tick 123" in out and "epoch-req 2" in out
    assert "mh-0(id 3)" in out


def test_show_store_remote_and_local():
    from vpp_tpu.kvstore.client import RemoteKVStore
    from vpp_tpu.kvstore.server import KVServer
    from vpp_tpu.kvstore.store import KVStore

    dp, _, _ = make_env()
    # in-process store
    local = KVStore()
    local.put("a", 1)
    out = DebugCLI(dp, store=local).run("show store")
    assert "in-process store" in out and "keys: 1" in out
    # served store with a fencing epoch: the agent-side view
    srv = KVServer(host="127.0.0.1", port=0).start()
    try:
        srv.store.fencing_epoch = 2
        client = RemoteKVStore("127.0.0.1", srv.port, request_timeout=5.0)
        out = DebugCLI(dp, store=client).run("show store")
        assert f"connected: 127.0.0.1:{srv.port}" in out
        assert "fencing epoch: 2" in out
        assert "ping" in out and "revision" in out
        client.close()
    finally:
        srv.close()
    assert "no store handle" in DebugCLI(dp).run("show store")


def test_kvwitness_status_cli(capsys):
    from vpp_tpu.cmd.kvwitness import main as wmain
    from vpp_tpu.kvstore.witness import QuorumWitness, WitnessClient

    w = QuorumWitness(host="127.0.0.1").start()
    try:
        WitnessClient(w.address).renew("10.0.0.1:12379", 0, ttl=5.0)
        assert wmain(["--status", w.address]) == 0
        out = capsys.readouterr().out
        assert "epoch 0" in out and "10.0.0.1:12379" in out
    finally:
        w.close()
    assert wmain(["--status", "127.0.0.1:1"]) == 1
