"""Shared wire-format helpers for IO tests: hand-rolled ethernet/IPv4/L4
frames with correct checksums (single source — the codec's accepted
wire format must only ever be updated in one place)."""

from __future__ import annotations

import ipaddress
import struct


def ip_checksum_ok(ip_hdr: bytes) -> bool:
    s = sum(struct.unpack(f"!{len(ip_hdr) // 2}H", ip_hdr))
    while s >> 16:
        s = (s & 0xFFFF) + (s >> 16)
    return s == 0xFFFF


def make_frame(src: str, dst: str, proto: int = 17, sport: int = 40000,
               dport: int = 80, payload: bytes = b"x" * 32,
               ttl: int = 64) -> bytes:
    """Ethernet + IPv4 + L4 frame with valid IP and L4 checksums."""
    eth = b"\x02\x00\x00\x00\x00\x02" + b"\x02\x00\x00\x00\x00\x01" \
        + b"\x08\x00"
    if proto == 17:
        l4 = struct.pack("!HHHH", sport, dport, 8 + len(payload), 0) + payload
    elif proto == 6:
        l4 = struct.pack("!HHIIBBHHH", sport, dport, 1, 0, 5 << 4, 0x02,
                         8192, 0, 0) + payload
    else:
        l4 = payload
    ip_len = 20 + len(l4)
    src_b = ipaddress.ip_address(src).packed
    dst_b = ipaddress.ip_address(dst).packed
    hdr = struct.pack("!BBHHHBBH4s4s", 0x45, 0, ip_len, 1, 0x4000, ttl,
                      proto, 0, src_b, dst_b)
    s = sum(struct.unpack("!10H", hdr))
    while s >> 16:
        s = (s & 0xFFFF) + (s >> 16)
    hdr = hdr[:10] + struct.pack("!H", ~s & 0xFFFF) + hdr[12:]
    # L4 checksum (TCP +16 / UDP +6) over pseudo-header
    if proto in (6, 17):
        pseudo = src_b + dst_b + struct.pack("!BBH", 0, proto, len(l4))
        data = pseudo + l4 + (b"\x00" if len(l4) % 2 else b"")
        s = sum(struct.unpack(f"!{len(data) // 2}H", data))
        while s >> 16:
            s = (s & 0xFFFF) + (s >> 16)
        ck = (~s & 0xFFFF) or 0xFFFF
        off = 16 if proto == 6 else 6
        l4 = l4[:off] + struct.pack("!H", ck) + l4[off + 2:]
    return eth + hdr + l4
