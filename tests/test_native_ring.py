"""Native frame-ring tests: build, SPSC semantics, wraparound, threaded
stress, cross-process shared memory, and end-to-end into the pipeline.

Reference model: govpp adapter tests + VPP frame-queue semantics — the
transport must deliver every committed frame exactly once, in order,
across a process boundary.
"""

import multiprocessing
import threading

import numpy as np
import pytest

from vpp_tpu.native import FrameRing, RING_COLUMNS, build_library
from vpp_tpu.pipeline.vector import VEC, ip4


def make_cols(seed: int, n: int = 4):
    rng = np.random.RandomState(seed)
    cols = {}
    for name, dtype in RING_COLUMNS:
        cols[name] = rng.randint(0, 1 << 16, VEC).astype(dtype)
    cols["flags"][:] = 0
    cols["flags"][:n] = 1
    cols["src_ip"][0] = np.uint32(seed)  # marker
    return cols


def test_build_and_layout():
    path = build_library()
    assert path.endswith(".so")
    r = FrameRing(bytearray(FrameRing.required_size(4)), n_slots=4)
    assert r.vec == VEC
    assert r.pending() == 0


def test_push_pop_fifo_and_full_empty():
    buf = bytearray(FrameRing.required_size(4))
    ring = FrameRing(buf, n_slots=4)
    assert ring.pop() is None  # empty
    for i in range(4):
        assert ring.push(make_cols(i), n_packets=i + 1, epoch=10 + i)
    assert not ring.push(make_cols(99), n_packets=1), "ring full"
    assert ring.pending() == 4
    for i in range(4):
        cols, n, epoch = ring.pop()
        assert n == i + 1 and epoch == 10 + i
        assert int(cols["src_ip"][0]) == i
    assert ring.pop() is None


def test_wraparound_many_times():
    buf = bytearray(FrameRing.required_size(3))
    ring = FrameRing(buf, n_slots=3)
    for i in range(50):
        assert ring.push(make_cols(i), n_packets=1)
        cols, _, _ = ring.pop()
        assert int(cols["src_ip"][0]) == i


def test_peek_views_zero_copy():
    buf = bytearray(FrameRing.required_size(2))
    ring = FrameRing(buf, n_slots=2)
    ring.push(make_cols(7), n_packets=3, epoch=42)
    cols, n, epoch = ring.peek_views()
    assert (n, epoch) == (3, 42)
    assert int(cols["src_ip"][0]) == 7
    for name, dtype in RING_COLUMNS:
        assert cols[name].dtype == dtype
        assert cols[name].shape == (VEC,)
    ring.release()
    assert ring.pending() == 0


def test_mismatched_release_rejected():
    buf = bytearray(FrameRing.required_size(2))
    ring = FrameRing(buf, n_slots=2)
    with pytest.raises(RuntimeError):
        ring.release()  # nothing pending
    ring.push(make_cols(1), n_packets=1)
    ring.release()
    with pytest.raises(RuntimeError):
        ring.release()  # double release
    # ring still usable after the rejected releases
    assert ring.push(make_cols(2), n_packets=1)
    cols, _, _ = ring.pop()
    assert int(cols["src_ip"][0]) == 2


def test_attach_validates_creator_slot_count():
    big = bytearray(FrameRing.required_size(8))
    FrameRing(big, n_slots=8, create=True)
    # attaching through a mapping that covers fewer bytes than the
    # creator's 8 slots must fail loudly, not corrupt memory
    short = memoryview(big)[: FrameRing.required_size(2)]
    with pytest.raises(ValueError, match="8 slots"):
        FrameRing(short, create=False)
    # full-size attach picks up the creator's slot count
    ring = FrameRing(big, create=False)
    assert ring.n_slots == 8


def test_threaded_producer_consumer():
    buf = bytearray(FrameRing.required_size(8))
    ring = FrameRing(buf, n_slots=8)
    N = 500
    seen = []

    import time
    deadline = time.monotonic() + 60

    def producer():
        i = 0
        while i < N and time.monotonic() < deadline:
            if ring.push(make_cols(i % 256), n_packets=1, epoch=i):
                i += 1

    def consumer():
        while len(seen) < N and time.monotonic() < deadline:
            got = ring.pop()
            if got is not None:
                seen.append(got[2])

    t1 = threading.Thread(target=producer, daemon=True)
    t2 = threading.Thread(target=consumer, daemon=True)
    t1.start(); t2.start()
    t1.join(timeout=60); t2.join(timeout=60)
    assert seen == list(range(N)), "every frame exactly once, in order"


def _child_producer(shm_name: str, n_slots: int, count: int):
    from multiprocessing import shared_memory

    from vpp_tpu.native import FrameRing

    shm = shared_memory.SharedMemory(name=shm_name)
    try:
        ring = FrameRing(shm.buf, n_slots=n_slots, create=False)
        i = 0
        while i < count:
            if ring.push(make_cols(i % 256), n_packets=1, epoch=i):
                i += 1
    finally:
        del ring
        shm.close()


def test_cross_process_transport():
    from multiprocessing import shared_memory

    n_slots, count = 8, 200
    shm = shared_memory.SharedMemory(
        create=True, size=FrameRing.required_size(n_slots)
    )
    try:
        ring = FrameRing(shm.buf, n_slots=n_slots, create=True)
        ctx = multiprocessing.get_context("spawn")
        p = ctx.Process(
            target=_child_producer, args=(shm.name, n_slots, count)
        )
        p.start()
        epochs = []
        while len(epochs) < count and (p.is_alive() or ring.pending()):
            got = ring.pop()
            if got is not None:
                epochs.append(got[2])
        p.join(timeout=60)
        assert p.exitcode == 0
        assert epochs == list(range(count))
    finally:
        del ring
        shm.close()
        shm.unlink()


def test_ring_frame_into_pipeline():
    """IO-process frame → ring → PacketVector → jitted pipeline step."""
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import Disposition

    dp = Dataplane(DataplaneConfig(sess_slots=256))
    pod = dp.add_pod_interface(("default", "a"))
    dp.builder.add_route("10.1.1.7/32", pod, Disposition.LOCAL)
    dp.swap()

    cols = {name: np.zeros(VEC, dtype) for name, dtype in RING_COLUMNS}
    cols["src_ip"][0] = ip4("10.1.1.9")
    cols["dst_ip"][0] = ip4("10.1.1.7")
    cols["proto"][0] = 6
    cols["sport"][0] = 1234
    cols["dport"][0] = 80
    cols["ttl"][0] = 64
    cols["pkt_len"][0] = 100
    cols["rx_if"][0] = pod
    cols["flags"][0] = 1

    buf = bytearray(FrameRing.required_size(2))
    ring = FrameRing(buf, n_slots=2)
    ring.push(cols, n_packets=1)
    got, n, _ = ring.peek_views()
    pkts = ring.to_packet_vector(got)
    ring.release()
    res = dp.process(pkts)
    assert int(res.disp[0]) == int(Disposition.LOCAL)
    assert int(res.tx_if[0]) == pod


class TestMacTable:
    def test_put_get_refresh(self):
        from vpp_tpu.native.pktio import MacTable

        t = MacTable(capacity=64)
        t.put(0x0A010102, b"\x02\x00\x00\x00\x00\x01")
        assert t.get(0x0A010102) == b"\x02\x00\x00\x00\x00\x01"
        assert t.get(0x0A010103) is None
        t.put(0x0A010102, b"\x02\x00\x00\x00\x00\x09")  # refresh
        assert t.get(0x0A010102) == b"\x02\x00\x00\x00\x00\x09"

    def test_unpin_releases_static_slot_to_eviction(self):
        """Unwiring an interface unpins its static entry: the entry
        stays resolvable (insert-only table, no tombstones) but loses
        its eviction immunity, so later pressure can reclaim the slot —
        it no longer counts against the pin budget forever."""
        from vpp_tpu.native.pktio import MacTable

        t = MacTable(capacity=64)
        ip = 0x0A010155
        t.put(ip, b"\x02\x00\x00\x00\x00\x05", pin=True)
        assert t.unpin(ip) is True
        assert t.unpin(0x0A010199) is False  # absent ip: not found
        # still resolvable after the unpin...
        assert t.get(ip) == b"\x02\x00\x00\x00\x00\x05"
        # ...but no longer pinned: an UNPINNED put into the same probe
        # run may now take the slot (before the unpin it could not)
        entries = {e[0]: e[2] for e in t.entries()}
        assert entries[ip] is False

    def test_pinned_static_entry_survives_learn_pressure(self):
        """A static (control-plane) entry for a silent pod must survive
        arbitrary learning churn — eviction may only take unpinned
        slots (the no-flood guarantee of set_static_mac)."""
        import numpy as np

        from vpp_tpu.io.rings import VEC
        from vpp_tpu.native.pktio import MacTable, PacketCodec

        t = MacTable(capacity=64)  # small: heavy collision pressure
        static_ip = 0x0A0101FE
        t.put(static_ip, b"\x02\xAA\xAA\xAA\xAA\xAA", pin=True)

        codec = PacketCodec(snap=256)
        scratch = np.zeros((VEC, 256), np.uint8)
        import struct

        def frame(src_int):
            eth = (b"\x02\x00\x00\x00\x00\x02"
                   + b"\x02" + struct.pack("!I", src_int)[:4] + b"\x01"
                   + b"\x08\x00")
            hdr = struct.pack("!BBHHHBBH4s4s", 0x45, 0, 28, 0, 0, 64, 17,
                              0, struct.pack("!I", src_int),
                              struct.pack("!I", 0x0A010103))
            return eth + hdr + struct.pack("!HHHH", 1, 2, 8, 0)

        # learn thousands of distinct IPs through a 64-slot table
        for wave in range(16):
            frames = [frame(0x0B000000 + wave * VEC + i)
                      for i in range(VEC)]
            cols, n = codec.parse(frames, 1, scratch)
            t.learn(cols, scratch, n)
        assert t.get(static_ip) == b"\x02\xAA\xAA\xAA\xAA\xAA"

    def test_concurrent_learn_put_get_yield_sane_macs(self):
        """rx learn, control put and tx get race GIL-free; every get
        must return either a fully-written MAC or None — never a torn
        mix (seqlock versioning)."""
        import threading

        from vpp_tpu.native.pktio import MacTable

        t = MacTable(capacity=256)
        valid = {bytes([0x02, i, i, i, i, i]) for i in range(8)}
        stop = threading.Event()
        torn = []

        def writer(k):
            mac = bytes([0x02, k, k, k, k, k])
            while not stop.is_set():
                for ip in range(0x0A000000, 0x0A000040):
                    t.put(ip, mac, pin=False)

        def reader():
            while not stop.is_set():
                for ip in range(0x0A000000, 0x0A000040):
                    got = t.get(ip)
                    if got is not None and got not in valid:
                        torn.append((ip, got))
                        return

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(4)] + [threading.Thread(target=reader)
                                         for _ in range(3)]
        for th in threads:
            th.start()
        import time

        time.sleep(2.0)
        stop.set()
        for th in threads:
            th.join(timeout=10)
        assert not torn, f"torn MAC reads: {torn[:3]}"
