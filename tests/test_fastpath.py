"""Two-tier established-flow fast path: differential equivalence.

The dispatch contract (pipeline/graph.py pipeline_step_auto): a batch
where EVERY valid packet hits a live reflective session (and none
DNAT-matches) runs a classify-free kernel; everything else falls
through to the full chain unchanged. These tests prove the contract
the only way that matters — bit-exact output equality against the
always-full-chain reference on identical inputs and identical session
state, across mixed established/fresh/deny traffic, plus the positive
proof that an all-established batch actually takes the fast kernel
(StepStats.fastpath == 1, the runtime branch signal).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.pipeline.dataplane import (
    Dataplane,
    pack_packet_columns,
    unpack_packet_result,
)
from vpp_tpu.pipeline.graph import (
    pipeline_step,
    pipeline_step_auto,
    pipeline_step_fast,
)
from vpp_tpu.pipeline.tables import SESSION_FIELDS, DataplaneConfig
from vpp_tpu.pipeline.vector import (
    FLAG_VALID,
    Disposition,
    ip4,
    make_packet_vector,
)

VIP = "10.96.0.1"


def build_dp(**over):
    cfg = DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=32, max_ifaces=8,
        fib_slots=16, sess_slots=256, nat_mappings=2, nat_backends=2,
        **over,
    )
    dp = Dataplane(cfg)
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("default", "web"))
    dp.builder.add_route("10.1.1.0/24", pod, Disposition.LOCAL)
    dp.builder.add_route("0.0.0.0/0", up, Disposition.REMOTE, node_id=1)
    dp.builder.set_global_table([
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                   dest_port=80),
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                   dest_port=8080),
        ContivRule(action=Action.DENY),
    ])
    # service VIP with one local backend (exercises DNAT + NAT session)
    dp.builder.set_nat_mapping(
        0, ext_ip=ip4(VIP), ext_port=80, proto=6,
        backends=[(ip4("10.1.1.2"), 8080, 1)], boff=0,
    )
    dp.swap()
    return dp, up, pod


def assert_results_equal(ref, got, *, expect_fast):
    """Field-for-field StepResult equality: dispositions, rewrites,
    attribution, session-table state, and every counter except the
    fastpath branch flag itself (the one designed difference)."""
    for f in ("disp", "tx_if", "node_id", "next_hop", "drop_cause",
              "established", "dnat_applied", "snat_applied"):
        assert np.array_equal(
            np.asarray(getattr(ref, f)), np.asarray(getattr(got, f))
        ), f"StepResult.{f} diverged"
    for f in ref.pkts._fields:
        assert np.array_equal(
            np.asarray(getattr(ref.pkts, f)),
            np.asarray(getattr(got.pkts, f)),
        ), f"pkts.{f} diverged (header rewrite mismatch)"
    for f in ref.stats._fields:
        if f == "fastpath":
            continue
        assert np.array_equal(
            np.asarray(getattr(ref.stats, f)),
            np.asarray(getattr(got.stats, f)),
        ), f"stats.{f} diverged"
    # touched session slots (timestamps included) must be identical —
    # the fast path's touch discipline is part of the contract
    for f in SESSION_FIELDS:
        assert np.array_equal(
            np.asarray(getattr(ref.tables, f)),
            np.asarray(getattr(got.tables, f)),
        ), f"tables.{f} diverged (session state mismatch)"
    assert int(got.stats.fastpath) == (1 if expect_fast else 0), (
        f"expected fastpath={'1' if expect_fast else '0'}, got "
        f"{int(got.stats.fastpath)}"
    )


def mixed_traffic(up, n=16):
    """Fresh permitted + fresh denied + VIP (DNAT) + invalid slots."""
    return make_packet_vector([
        {"src": "172.16.0.5", "dst": "10.1.1.7", "proto": 6,
         "sport": 4001, "dport": 80, "rx_if": up},
        {"src": "172.16.0.6", "dst": "10.1.1.8", "proto": 6,
         "sport": 4002, "dport": 80, "rx_if": up},
        {"src": "172.16.0.7", "dst": "10.1.1.9", "proto": 6,
         "sport": 4003, "dport": 9999, "rx_if": up},  # denied
        {"src": "172.16.0.8", "dst": VIP, "proto": 6,
         "sport": 4004, "dport": 80, "rx_if": up},    # DNAT'd
    ], n=n)


def replies_for(res, pod, n=16):
    """Reply vector for every forwarded packet of a step result: swap
    the POST-NAT endpoints (that is what the wire carries), ingress on
    the egress interface."""
    fwd = np.asarray(res.disp) != int(Disposition.DROP)
    pk = res.pkts
    pkts = []
    for i in np.nonzero(fwd)[0]:
        i = int(i)
        pkts.append({
            "src": int(np.asarray(pk.dst_ip)[i]),
            "dst": int(np.asarray(pk.src_ip)[i]),
            "proto": int(np.asarray(pk.proto)[i]),
            "sport": int(np.asarray(pk.dport)[i]),
            "dport": int(np.asarray(pk.sport)[i]),
            "rx_if": int(np.asarray(res.tx_if)[i]),
        })
    assert pkts, "no forwarded packets to build replies from"
    return make_packet_vector(pkts, n=n)


@pytest.fixture(scope="module")
def steps():
    return (jax.jit(pipeline_step), jax.jit(pipeline_step_auto),
            jax.jit(pipeline_step_fast))


class TestDifferential:
    def test_mixed_traffic_takes_full_chain_bit_exact(self, steps):
        step_full, step_auto, _ = steps
        dp, up, _pod = build_dp()
        pkts = mixed_traffic(up)
        ref = step_full(dp.tables, pkts, jnp.int32(5))
        got = step_auto(dp.tables, pkts, jnp.int32(5))
        # fresh flows present -> the predicate must fall through
        assert_results_equal(ref, got, expect_fast=False)
        # sanity on the mix itself: something forwarded, something
        # denied, something DNAT'd
        assert int(ref.stats.tx) >= 3
        assert int(ref.stats.drop_acl) == 1
        assert int(ref.stats.dnat) == 1

    def test_all_established_takes_classify_free_kernel(self, steps):
        step_full, step_auto, step_fast = steps
        dp, up, pod = build_dp()
        pkts = mixed_traffic(up)
        r1 = step_full(dp.tables, pkts, jnp.int32(5))
        rep = replies_for(r1, pod)
        ref = step_full(r1.tables, rep, jnp.int32(6))
        got = step_auto(r1.tables, rep, jnp.int32(6))
        # the positive proof: the classify-free kernel ran...
        assert_results_equal(ref, got, expect_fast=True)
        # ...and the batch really was established end to end: every
        # valid reply forwarded, the DNAT'd flow's reply un-NAT'd
        n_valid = int(np.asarray(rep.valid).sum())
        assert int(ref.stats.tx) == n_valid
        assert int(ref.stats.nat_reversed) == 1
        assert int(got.stats.sess_hits) == n_valid
        # the standalone fast kernel agrees too (bench uses it)
        raw = step_fast(r1.tables, rep, jnp.int32(6))
        assert np.array_equal(np.asarray(raw.disp), np.asarray(ref.disp))
        assert int(raw.stats.fastpath) == 1

    @pytest.mark.slow  # ~27 s: partial-hit compile of both chain forms; mixed-traffic full-chain bit-exact stays the fast differential anchor
    def test_partial_hit_batch_falls_through(self, steps):
        """One fresh flow mixed into established replies: the batch
        dispatch predicate must reject and the full chain must install
        the fresh session — outputs identical to the reference."""
        step_full, step_auto, _ = steps
        dp, up, pod = build_dp()
        pkts = mixed_traffic(up)
        r1 = step_full(dp.tables, pkts, jnp.int32(5))
        rep = replies_for(r1, pod, n=8)
        # graft one fresh (never-seen) flow into the reply batch
        flags = np.asarray(rep.flags).copy()
        src = np.asarray(rep.src_ip).copy()
        dst = np.asarray(rep.dst_ip).copy()
        sport = np.asarray(rep.sport).copy()
        dport = np.asarray(rep.dport).copy()
        rx_if = np.asarray(rep.rx_if).copy()
        slot = int(np.asarray(rep.valid).sum())
        assert flags[slot] == 0
        flags[slot] = FLAG_VALID
        src[slot] = ip4("172.16.9.9")
        dst[slot] = ip4("10.1.1.30")
        sport[slot], dport[slot] = 5005, 80
        rx_if[slot] = up
        rep = rep._replace(
            flags=jnp.asarray(flags), src_ip=jnp.asarray(src),
            dst_ip=jnp.asarray(dst), sport=jnp.asarray(sport),
            dport=jnp.asarray(dport), rx_if=jnp.asarray(rx_if),
        )
        ref = step_full(r1.tables, rep, jnp.int32(6))
        got = step_auto(r1.tables, rep, jnp.int32(6))
        assert_results_equal(ref, got, expect_fast=False)
        # the fresh flow's session WAS installed by both paths
        assert int(ref.stats.sess_hits) == slot  # the established ones

    def test_established_but_dnat_matching_reply_falls_through(self, steps):
        """The subtle predicate clause: a reply that rides a reflective
        session AND whose (un-NAT'd) destination matches a DNAT mapping
        must take the full chain — the full chain translates it and
        records NAT state the fast kernel elides. Constructed by making
        the forward flow originate FROM the VIP address on the mapping
        port, so the reply targets VIP:80 exactly."""
        step_full, step_auto, _ = steps
        dp, up, pod = build_dp()
        fwd = make_packet_vector([
            {"src": VIP, "dst": "10.1.1.7", "proto": 6,
             "sport": 80, "dport": 8080, "rx_if": up},
        ], n=8)
        r1 = step_full(dp.tables, fwd, jnp.int32(5))
        assert int(r1.stats.tx) == 1
        rep = make_packet_vector([
            {"src": "10.1.1.7", "dst": VIP, "proto": 6,
             "sport": 8080, "dport": 80, "rx_if": pod},
        ], n=8)
        ref = step_full(r1.tables, rep, jnp.int32(6))
        got = step_auto(r1.tables, rep, jnp.int32(6))
        # established (reflective hit) but DNAT-matching -> full chain
        assert bool(np.asarray(ref.established)[0])
        assert bool(np.asarray(ref.dnat_applied)[0])
        assert_results_equal(ref, got, expect_fast=False)

    def test_expired_sessions_fall_through(self, steps):
        """Sessions past sess_max_age are dead for the predicate too:
        the 'reply' is then a fresh flow and must take the full chain
        (where the ACL decides its fate)."""
        step_full, step_auto, _ = steps
        dp, up, pod = build_dp()
        pkts = mixed_traffic(up)
        r1 = step_full(dp.tables, pkts, jnp.int32(5))
        rep = replies_for(r1, pod)
        late = jnp.int32(5 + int(dp.config.sess_max_age) + 1)
        ref = step_full(r1.tables, rep, late)
        got = step_auto(r1.tables, rep, late)
        assert int(ref.stats.sess_hits) == 0
        assert_results_equal(ref, got, expect_fast=False)


class TestPackedAux:
    @pytest.mark.slow  # ~16 s: packed-aux variant compile; aux schema width parity stays fast in test_telemetry
    def test_packed_aux_reports_fast_dispatch(self):
        """The pump-facing telemetry: process_packed(with_aux=True)
        returns [fastpath, rx, sess_hits] from the same program, and
        the packed outputs stay identical to a fastpath-disabled
        dataplane fed the same batch."""
        dp, up, pod = build_dp()
        dp_ref, up2, pod2 = build_dp(fastpath=False)
        assert dp._use_fastpath and not dp_ref._use_fastpath
        step_full = jax.jit(pipeline_step)

        pkts = mixed_traffic(up)
        r1 = step_full(dp.tables, pkts, jnp.int32(5))
        rep = replies_for(r1, pod, n=8)
        cols = {
            f: np.asarray(getattr(rep, f))
            for f in ("src_ip", "dst_ip", "proto", "sport", "dport",
                      "ttl", "pkt_len", "rx_if", "flags")
        }
        flat = np.zeros((5, 8), np.int32)
        pack_packet_columns(flat.view(np.uint32), cols, 8)

        # both dataplanes primed with the identical forward step
        dp.tables = r1.tables
        dp_ref.tables = step_full(dp_ref.tables, pkts, jnp.int32(5)).tables

        out, aux = dp.process_packed(flat.copy(), now=6, with_aux=True)
        a = np.asarray(jax.device_get(aux))
        assert a[0] == 1, "all-established packed batch not fast-dispatched"
        n_valid = int(np.asarray(rep.valid).sum())
        assert a[1] == n_valid and a[2] == n_valid
        ref_out = dp_ref.process_packed(flat.copy(), now=6)
        got = unpack_packet_result(np.array(jax.device_get(out)))
        want = unpack_packet_result(np.array(jax.device_get(ref_out)))
        for k in want:
            assert np.array_equal(got[k], want[k]), k

    def test_disabled_fastpath_still_measures_regime(self):
        """With the fast path disengaged the full chain still reports
        the aux summary (fastpath=0, hits/alive measured) — the
        hit-percentage gauge must diagnose the disengaged regime, not
        read as 'no established traffic'."""
        import jax as _jax

        dp, up, _pod = build_dp(fastpath=False)
        from vpp_tpu.pipeline.dataplane import packed_input_zeros

        out, aux = dp.process_packed(packed_input_zeros(8), with_aux=True)
        a = np.asarray(_jax.device_get(aux))
        assert a[0] == 0 and a[1] == 0 and a[2] == 0

    def test_min_rules_threshold_gates_engagement(self):
        dp, up, _pod = build_dp(fastpath_min_rules=1000)
        assert dp.fastpath_enabled
        assert not dp._use_fastpath  # 3 global rules < 1000


class TestPumpWire:
    def test_pump_counts_fastpath_batches_on_reply_traffic(self):
        """End-to-end regime wiring: real wire frames through the
        dispatch pump. The fresh forward flow takes the full chain
        (fastpath_batches stays 0), its reply rides the reflective
        session and must be counted as a fast-dispatched batch with
        hit accounting behind the fastpath_hit_pct gauge."""
        import time as _time

        from wire import make_frame

        from vpp_tpu.io import (
            DataplanePump,
            IODaemon,
            IORingPair,
            SocketPairTransport,
        )

        dp, up, pod = build_dp()
        client_if = dp.add_pod_interface(("default", "client"))
        dp.builder.add_route("10.1.1.9/32", client_if, Disposition.LOCAL)
        dp.swap()
        # compile the packed auto kernel BEFORE wire traffic: the recv
        # timeouts must measure the data path, not the first jit trace
        from vpp_tpu.pipeline.dataplane import packed_input_zeros

        dp.process_packed(packed_input_zeros(256))
        rings = IORingPair(n_slots=8)
        transports = {}
        outside = {}
        for if_idx, name in ((client_if, "client"), (pod, "server")):
            inside, out = SocketPairTransport.pair(name)
            transports[if_idx] = inside
            outside[name] = out
        daemon = IODaemon(rings, transports, uplink_if=up).start()
        pump = DataplanePump(dp, rings).start()
        try:
            def recv(name, timeout=10.0):
                sock = outside[name].sock
                sock.setblocking(True)
                sock.settimeout(timeout)
                try:
                    return sock.recv(65535)
                finally:
                    sock.setblocking(False)

            # fresh forward flow client -> server pod (permitted: tcp/80)
            outside["client"].send_frame(make_frame(
                "10.1.1.9", "10.1.1.7", proto=6, sport=4001, dport=80))
            recv("server")
            assert pump.stats["fastpath_batches"] == 0
            assert pump.stats["fastpath_alive"] >= 1
            # the reply rides the reflective session -> fast dispatch
            outside["server"].send_frame(make_frame(
                "10.1.1.7", "10.1.1.9", proto=6, sport=80, dport=4001))
            recv("client")
            deadline = _time.monotonic() + 5
            while _time.monotonic() < deadline and \
                    pump.stats["fastpath_batches"] == 0:
                _time.sleep(0.01)
            assert pump.stats["fastpath_batches"] >= 1
            assert pump.stats["fastpath_hits"] >= 1
        finally:
            pump.stop()
            daemon.stop()
            for t in transports.values():
                t.close()
            for t in outside.values():
                t.close()
            rings.close()
