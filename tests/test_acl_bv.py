"""Bit-vector (BV) interval-bitmap classify vs the dense oracle.

The BV compilation (vpp_tpu.ops.acl_bv) must reproduce the dense
kernel's verdicts AND matched rule indices exactly for every rule
shape: prefixes (incl. /0 wildcards), exact protocols and proto=-1,
port edge cases (lo==hi, 0, 65535 and — unlike MXU — true ranges),
overlapping priorities and padding rows; for the global table and the
per-interface local tables. Also covers the incremental per-dimension
plane rebuild, the epoch-time classifier selection (auto/threshold/
memory cap), the policy-free local-classify skip, and the
``tools/lint.py --tables`` invariant pass (run from tier-1 here).
"""

import ipaddress

import numpy as np
import pytest

import jax.numpy as jnp

from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.ops.acl import acl_classify_global, acl_classify_local
from vpp_tpu.ops.acl_bv import (
    acl_classify_global_bv,
    acl_classify_local_bv,
    bv_first_match,
    bv_global_bytes,
    compile_bv,
)
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import (
    DataplaneConfig,
    InterfaceType,
    TableBuilder,
    pack_rules,
)
from vpp_tpu.pipeline.vector import (
    Disposition,
    PacketVector,
    make_packet_vector,
)


def _mask(plen):
    return ((1 << 32) - 1) ^ ((1 << (32 - plen)) - 1) if plen else 0


def random_rules(rng, n):
    """Seeded-random tables over every expressible shape: wildcard
    (mask 0 / no network), proto ANY, dport edge values 0/65535,
    overlapping priorities (duplicate prefixes at different actions)."""
    rules = []
    for i in range(n):
        plen = int(rng.integers(0, 33))
        net = ipaddress.ip_network(
            (int(rng.integers(0, 2**32)) & _mask(plen), plen))
        dplen = int(rng.integers(0, 33))
        dnet = ipaddress.ip_network(
            (int(rng.integers(0, 2**32)) & _mask(dplen), dplen))
        proto = [Protocol.ANY, Protocol.TCP, Protocol.UDP][
            int(rng.integers(0, 3))]
        dport = int(rng.choice([0, 80, 443, 8080, 65535]))
        rules.append(ContivRule(
            action=Action.PERMIT if rng.random() < 0.5 else Action.DENY,
            src_network=net if rng.random() < 0.7 else None,
            dest_network=dnet if rng.random() < 0.7 else None,
            protocol=proto,
            dest_port=dport if proto != Protocol.ANY else 0,
        ))
    return rules


def random_packets(rng, n, rules, rx_if=1, max_if=None):
    """Half random 5-tuples, half crafted into rule prefixes; rx_if
    scalar or per-packet choices."""
    src = rng.integers(0, 2**32, n, dtype=np.uint32)
    dst = rng.integers(0, 2**32, n, dtype=np.uint32)
    for i in range(n // 2):
        r = rules[int(rng.integers(0, len(rules)))]
        if r.src_network is not None:
            src[i] = int(r.src_network.network_address) + int(rng.integers(
                0, max(1, min(r.src_network.num_addresses, 1000))))
        if r.dest_network is not None:
            dst[i] = int(r.dest_network.network_address) + int(rng.integers(
                0, max(1, min(r.dest_network.num_addresses, 1000))))
    if max_if is None:
        rxi = np.full(n, rx_if, np.int32)
    else:
        rxi = rng.integers(0, max_if, n).astype(np.int32)
    return PacketVector(
        src_ip=jnp.asarray(src),
        dst_ip=jnp.asarray(dst),
        proto=jnp.asarray(rng.choice([1, 6, 17], n).astype(np.int32)),
        sport=jnp.asarray(rng.integers(0, 65536, n).astype(np.int32)),
        dport=jnp.asarray(
            rng.choice([0, 80, 443, 8080, 53, 65535], n).astype(np.int32)),
        ttl=jnp.full((n,), 64, jnp.int32),
        pkt_len=jnp.full((n,), 100, jnp.int32),
        rx_if=jnp.asarray(rxi),
        flags=jnp.ones((n,), jnp.int32),
    )


def _cfg(**kw):
    base = dict(max_tables=4, max_rules=32, max_global_rules=128,
                max_ifaces=8, fib_slots=16, sess_slots=64,
                nat_mappings=2, nat_backends=4, classifier="bv")
    base.update(kw)
    return DataplaneConfig(**base)


def _tables(rules, rng=None, n_local=0):
    """Builder-committed device tables: uplink on if 1 (global
    applies), pods on 2.. with local tables when asked."""
    b = TableBuilder(_cfg())
    b.set_interface(1, InterfaceType.UPLINK, apply_global=True)
    b.set_global_table(rules)
    for t in range(n_local):
        b.set_interface(2 + t, InterfaceType.POD, local_table=t)
        b.set_local_table(t, random_rules(rng, int(rng.integers(1, 28))))
    # one pod with NO local table: must be permitted by the local stage
    b.set_interface(2 + n_local, InterfaceType.POD, local_table=-1)
    return b, b.to_device()


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_global_bv_matches_dense(seed):
    rng = np.random.default_rng(seed)
    rules = random_rules(rng, 100)
    _, t = _tables(rules)
    pkts = random_packets(rng, 256, rules, rx_if=1)
    want = acl_classify_global(t, pkts)
    got = acl_classify_global_bv(t, pkts)
    np.testing.assert_array_equal(np.asarray(got.permit),
                                  np.asarray(want.permit))
    np.testing.assert_array_equal(np.asarray(got.rule_idx),
                                  np.asarray(want.rule_idx))


@pytest.mark.parametrize("seed", [4, 5])
def test_local_bv_matches_dense(seed):
    rng = np.random.default_rng(seed)
    rules = random_rules(rng, 40)
    _, t = _tables(rules, rng=rng, n_local=3)
    # packets across uplink, policied pods AND the tableless pod
    pkts = random_packets(rng, 256, rules, max_if=6)
    want = acl_classify_local(t, pkts)
    got = acl_classify_local_bv(t, pkts)
    np.testing.assert_array_equal(np.asarray(got.permit),
                                  np.asarray(want.permit))
    np.testing.assert_array_equal(np.asarray(got.rule_idx),
                                  np.asarray(want.rule_idx))


def test_port_ranges_and_padding_rows():
    """True port ranges are the BV scheme's home turf (the MXU planes
    fall back on them): inject ranges + collapsed (lo==hi) + full-span
    edges at the packed level and diff against the dense first-match."""
    from vpp_tpu.ops import acl

    cap = 16
    packed = pack_rules(
        [ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                    dest_port=80) for _ in range(6)], cap)
    packed["dport_lo"][0], packed["dport_hi"][0] = 100, 200     # range
    packed["dport_lo"][1], packed["dport_hi"][1] = 0, 0         # edge 0
    packed["dport_lo"][2], packed["dport_hi"][2] = 65535, 65535
    packed["dport_lo"][3], packed["dport_hi"][3] = 0, 65535     # any
    packed["sport_lo"][4], packed["sport_hi"][4] = 1000, 1000   # lo==hi
    bv, _, _ = compile_bv(packed, cap)
    assert bv.ok
    rng = np.random.default_rng(9)
    n = 256
    pkts = PacketVector(
        src_ip=jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32)),
        dst_ip=jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32)),
        proto=jnp.asarray(rng.choice([6, 7, 17], n).astype(np.int32)),
        sport=jnp.asarray(
            rng.choice([0, 999, 1000, 1001, 65535], n).astype(np.int32)),
        dport=jnp.asarray(
            rng.choice([0, 1, 80, 99, 100, 150, 200, 201, 65534, 65535],
                       n).astype(np.int32)),
        ttl=jnp.full((n,), 64, jnp.int32),
        pkt_len=jnp.full((n,), 100, jnp.int32),
        rx_if=jnp.ones((n,), jnp.int32),
        flags=jnp.ones((n,), jnp.int32),
    )
    matched, rule = bv_first_match(
        bv.bnd_src, bv.bnd_dst, bv.bnd_sport, bv.bnd_dport,
        jnp.asarray(bv.nbnd), jnp.asarray(bv.bm_src),
        jnp.asarray(bv.bm_dst), jnp.asarray(bv.bm_sport),
        jnp.asarray(bv.bm_dport), jnp.asarray(bv.bm_proto), pkts)
    dense = acl._first_match(
        pkts,
        jnp.asarray(packed["src_net"]), jnp.asarray(packed["src_mask"]),
        jnp.asarray(packed["dst_net"]), jnp.asarray(packed["dst_mask"]),
        jnp.asarray(packed["proto"]),
        jnp.asarray(packed["sport_lo"]), jnp.asarray(packed["sport_hi"]),
        jnp.asarray(packed["dport_lo"]), jnp.asarray(packed["dport_hi"]),
        jnp.asarray(packed["action"]), jnp.int32(6))
    np.testing.assert_array_equal(np.asarray(rule),
                                  np.asarray(dense.rule_idx))
    assert bool(np.asarray(matched).any())  # the crafted ports do hit


def test_non_prefix_mask_fails_closed():
    """A non-contiguous address mask is not one interval: the compile
    must flag ok=False AND exclude the rule (miss, never mismatch)."""
    packed = pack_rules(
        [ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                    dest_port=80)], 8)
    packed["src_mask"][0] = 0xFF00FF00
    packed["src_net"][0] = 0x0A000A00
    bv, _, _ = compile_bv(packed, 8)
    assert not bv.ok
    assert not bv.bm_src.any()  # the rule contributed no interval


class TestIncrementalRebuild:
    """The per-dimension incremental compile must (a) rebuild ONLY the
    planes whose intervals moved and (b) stay bit-identical to a
    from-scratch build across add/remove churn."""

    def _assert_equal(self, got, want):
        for f in ("bnd_src", "bnd_dst", "bnd_sport", "bnd_dport",
                  "nbnd", "bm_src", "bm_dst", "bm_sport", "bm_dport",
                  "bm_proto"):
            np.testing.assert_array_equal(
                getattr(got, f), getattr(want, f), err_msg=f)
        assert got.ok == want.ok

    def test_port_only_churn_keeps_address_planes(self):
        b = TableBuilder(_cfg())
        rules = [
            ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                       src_network=ipaddress.ip_network(f"10.{i}.0.0/16"),
                       dest_port=8000 + i)
            for i in range(20)
        ]
        b.set_global_table(rules)
        addr_src = b.glb_bv.bm_src
        churned = list(rules)
        churned[3] = ContivRule(
            action=Action.PERMIT, protocol=Protocol.TCP,
            src_network=rules[3].src_network, dest_port=9999)
        b.set_global_table(churned)
        # only the dport plane moved; src/dst/sport/proto carried over
        assert b.bv_rebuilt == ("dport",)
        assert b.glb_bv.bm_src is addr_src  # reference-carried, not rebuilt
        # and the carried structure still equals a from-scratch build
        want, _, _ = compile_bv(pack_rules(churned, 128), 128)
        self._assert_equal(b.glb_bv, want)

    def test_add_remove_parity_vs_scratch(self):
        rng = np.random.default_rng(7)
        b = TableBuilder(_cfg())
        rules = random_rules(rng, 30)
        for step in range(8):
            b.set_global_table(rules)
            want, _, _ = compile_bv(pack_rules(rules, 128), 128)
            self._assert_equal(b.glb_bv, want)
            rules = list(rules)
            op = step % 3
            if op == 0:
                rules.insert(2, ContivRule(action=Action.DENY,
                                           protocol=Protocol.UDP,
                                           dest_port=53))
            elif op == 1:
                del rules[4:9]
            else:
                rules.extend(random_rules(rng, 5))

    def test_snapshot_restore_invalidates_cache(self):
        b = TableBuilder(_cfg())
        r1 = [ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                         dest_port=80)]
        r2 = [ContivRule(action=Action.DENY, protocol=Protocol.TCP,
                         dest_port=443)]
        b.set_global_table(r1)
        snap = b.state_snapshot()
        b.set_global_table(r2)
        b.state_restore(snap)
        b.set_global_table(r2)
        want, _, _ = compile_bv(pack_rules(r2, 128), 128)
        self._assert_equal(b.glb_bv, want)


def _mk_dp(n_rules, **cfg_kw):
    cfg = DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=max(n_rules, 16),
        max_ifaces=8, fib_slots=16, sess_slots=64, nat_mappings=2,
        nat_backends=4, **cfg_kw)
    dp = Dataplane(cfg)
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("ns", "p"))
    dp.builder.add_route("10.1.1.2/32", pod, Disposition.LOCAL)
    rules = [
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                   dest_port=8000 + i)
        for i in range(n_rules - 1)
    ] + [ContivRule(action=Action.DENY)]
    dp.builder.set_global_table(rules)
    dp.swap()
    return dp, up


def test_auto_selection_regates_at_swap():
    """`classifier: auto` picks BV at/above the rule threshold and
    dense below it, re-gated at each epoch swap, with the selection
    visible in `show acl` and the Prometheus info gauge."""
    from vpp_tpu.cli import DebugCLI
    from vpp_tpu.stats.collector import StatsCollector

    dp, _ = _mk_dp(64, classifier="auto", classifier_bv_min_rules=32)
    dp.mxu_threshold = 1 << 30  # park MXU: this test walks bv<->dense
    dp.swap()
    assert dp.classifier_impl == "bv"  # threshold 32 <= 64 rules
    assert "classifier: bv" in DebugCLI(dp).run("show acl")
    coll = StatsCollector(dp)
    coll.publish()
    page = coll.registry.render("/stats")
    assert 'vpp_tpu_acl_classifier{impl="bv"} 1' in page
    assert 'vpp_tpu_acl_classifier{impl="dense"} 0' in page
    # shrink below the threshold: the SAME dataplane re-gates to dense
    dp.builder.set_global_table(
        [ContivRule(action=Action.DENY, protocol=Protocol.TCP,
                    dest_port=23)])
    dp.swap()
    assert dp.classifier_impl == "dense"
    assert "classifier: dense" in DebugCLI(dp).run("show acl")


def test_auto_selection_initial_epoch():
    """__init__ evaluates the selection against the (empty) staged
    builder — dense at 0 rules — and the first committing swap flips
    it to BV in the same dataplane."""
    cfg = DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=64, max_ifaces=8,
        fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=4,
        classifier="auto", classifier_bv_min_rules=8)
    dp = Dataplane(cfg)
    dp.mxu_threshold = 1 << 30
    assert dp.classifier_impl == "dense"
    dp.builder.set_global_table(
        [ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                    dest_port=8000 + i) for i in range(16)])
    dp.swap()
    assert dp.classifier_impl == "bv"


def test_memory_cap_disables_bv():
    """auto honors classifier_bv_mem_mb: a cap below the structure
    size keeps the builder off BV entirely (minimal placeholder
    shapes) and the selection on the dense/MXU ladder."""
    dp, _ = _mk_dp(64, classifier="auto", classifier_bv_min_rules=1,
                   classifier_bv_mem_mb=0)
    assert not dp.builder.bv_enabled
    assert int(dp.tables.glb_bv_src.shape[0]) == 2  # placeholder
    dp.swap()
    assert dp.classifier_impl != "bv"
    assert bv_global_bytes(64) > 0


def test_bv_end_to_end_matches_dense_dataplane():
    """Full pipeline differential: identical config except the
    classifier knob must produce identical dispositions/counters."""
    rng = np.random.default_rng(11)
    flows = [(int(rng.integers(1024, 65000)),
              int(rng.choice([8000, 8005, 23, 80])))
             for _ in range(64)]
    out = {}
    for knob in ("dense", "bv"):
        dp, up = _mk_dp(48, classifier=knob)
        if knob == "bv":
            assert dp.classifier_impl == "bv"
        pkts = make_packet_vector(
            [{"src": "1.2.3.4", "dst": "10.1.1.2", "proto": 6,
              "sport": sp, "dport": dp_, "rx_if": up}
             for sp, dp_ in flows])
        res = dp.process(pkts)
        out[knob] = (np.asarray(res.disp), np.asarray(res.drop_cause),
                     int(res.stats.drop_acl))
    np.testing.assert_array_equal(out["dense"][0], out["bv"][0])
    np.testing.assert_array_equal(out["dense"][1], out["bv"][1])
    assert out["dense"][2] == out["bv"][2]


@pytest.mark.slow  # ~11 s: gate/regate compile pair; the regate-at-swap bug class stays fast via test_lpm auto-regate
def test_skip_local_gate_regates_at_swap():
    """Policy-free nodes compile the local stage away; assigning a
    local table re-gates at the next swap with identical verdicts."""
    dp, up = _mk_dp(16, classifier="dense")
    assert dp._skip_local  # no interface points at a local table
    pkts = make_packet_vector(
        [{"src": "1.2.3.4", "dst": "10.1.1.2", "proto": 6,
          "sport": 1000, "dport": 8000, "rx_if": up}])
    permit_before = bool(np.asarray(dp.process(pkts).disp)[0]
                         == int(Disposition.LOCAL))
    slot = dp.alloc_table_slot("T1")
    dp.builder.set_local_table(
        slot, [ContivRule(action=Action.DENY)])
    dp.builder.set_if_local_table(dp.pod_if[("ns", "p")], slot)
    dp.swap()
    assert not dp._skip_local
    # the pod's local deny-all doesn't apply to uplink rx: verdict holds
    permit_after = bool(np.asarray(dp.process(pkts).disp)[0]
                        == int(Disposition.LOCAL))
    assert permit_before == permit_after
    # and unassigning flips the gate back
    dp.builder.set_if_local_table(dp.pod_if[("ns", "p")], -1)
    dp.swap()
    assert dp._skip_local


def test_tables_lint_invariants():
    """tools/lint.py --tables, run from tier-1: boundary sort, word
    width, padding inertness, capacity-constant consistency."""
    import sys
    from pathlib import Path

    tools = Path(__file__).resolve().parent.parent / "tools"
    sys.path.insert(0, str(tools))
    try:
        import lint as _lint

        assert _lint.tables_lint() == []
    finally:
        sys.path.remove(str(tools))
