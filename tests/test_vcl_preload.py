"""LD_PRELOAD session shim e2e: unmodified subprocesses get session-rule
admission on connect()/accept().

Reference analog: VPP's VCL ldpreload deployment (tests/ld_preload*,
the iperf/nginx suites run with LD_PRELOAD=libvcl_ldpreload.so and the
contiv-cri shim injecting that env) — app sockets are filtered by the
session rule tables the VPPTCP renderer programs
(plugins/policy/renderer/vpptcp/bin_api/session). Here libvclshim.so
(native/vcl_preload.c) asks the VclAdmissionServer
(hoststack/admission.py) for a verdict backed by the SAME
SessionRuleEngine, and the apps under test are real python subprocesses
that never import vpp_tpu.
"""

import socket
import struct
import subprocess
import sys
import threading
import time

import pytest

from vpp_tpu.hoststack.admission import (
    OP_CONNECT, VclAdmissionServer, _REQ,
)
from vpp_tpu.hoststack.preload import shim_path, vcl_env
from vpp_tpu.hoststack.session_rules import (
    GLOBAL_NS, RuleAction, RuleScope, SessionRule, SessionRuleEngine,
)


def ipi(a: str) -> int:
    return struct.unpack("!I", socket.inet_aton(a))[0]


def local_rule(appns, rmt_port, action, proto=6):
    return SessionRule(
        scope=int(RuleScope.LOCAL), appns_index=appns,
        transport_proto=proto, lcl_net=0, lcl_plen=0,
        rmt_net=ipi("127.0.0.1"), rmt_plen=32,
        lcl_port=0, rmt_port=rmt_port, action=int(action))


def global_rule(lcl_port, action, proto=6):
    return SessionRule(
        scope=int(RuleScope.GLOBAL), appns_index=GLOBAL_NS,
        transport_proto=proto, lcl_net=ipi("127.0.0.1"), lcl_plen=32,
        rmt_net=0, rmt_plen=0,
        lcl_port=lcl_port, rmt_port=0, action=int(action))


CONNECT_CODE = """
import socket, sys
s = socket.socket()
s.settimeout(10)
try:
    s.connect(("127.0.0.1", int(sys.argv[1])))
    print("CONNECTED")
except ConnectionRefusedError:
    print("REFUSED")
"""


@pytest.fixture()
def admission(tmp_path):
    engine = SessionRuleEngine()
    path = str(tmp_path / "vcl.sock")
    srv = VclAdmissionServer(engine, path).start()
    yield engine, path
    srv.stop()


@pytest.fixture()
def listener():
    socks = []

    def make(port=0):
        ls = socket.socket()
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind(("127.0.0.1", port))
        ls.listen(8)
        socks.append(ls)

        def drain():
            while True:
                try:
                    c, _ = ls.accept()
                    c.close()
                except OSError:
                    return

        threading.Thread(target=drain, daemon=True).start()
        return ls.getsockname()[1]

    yield make
    for s in socks:
        s.close()


def run_under_shim(env, code, *argv, timeout=60):
    out = subprocess.run([sys.executable, "-c", code, *map(str, argv)],
                         env=env, capture_output=True, text=True,
                         timeout=timeout)
    assert out.returncode == 0, out.stderr[-500:]
    return out.stdout.strip()


def test_connect_deny_and_allow(admission, listener):
    engine, sock = admission
    port = listener()
    engine.apply(add=[local_rule(7, port, RuleAction.DENY)])
    env = vcl_env(sock, appns_index=7)
    assert run_under_shim(env, CONNECT_CODE, port) == "REFUSED"
    # an unfiltered port on the same namespace still connects
    port2 = listener()
    assert run_under_shim(env, CONNECT_CODE, port2) == "CONNECTED"


def test_appns_scoping(admission, listener):
    """LOCAL rules bind to their app namespace: ns 7 denied, ns 8 not."""
    engine, sock = admission
    port = listener()
    engine.apply(add=[local_rule(7, port, RuleAction.DENY)])
    assert run_under_shim(vcl_env(sock, appns_index=7),
                          CONNECT_CODE, port) == "REFUSED"
    assert run_under_shim(vcl_env(sock, appns_index=8),
                          CONNECT_CODE, port) == "CONNECTED"


def test_udp_connect_filtered(admission, listener):
    engine, sock = admission
    engine.apply(add=[local_rule(3, 5353, RuleAction.DENY, proto=17)])
    code = """
import socket
s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
try:
    s.connect(("127.0.0.1", 5353))
    print("CONNECTED")
except ConnectionRefusedError:
    print("REFUSED")
"""
    assert run_under_shim(vcl_env(sock, appns_index=3), code) == "REFUSED"
    # TCP rule does not catch UDP and vice versa
    engine.flush()
    engine.apply(add=[local_rule(3, 5353, RuleAction.DENY, proto=6)])
    assert run_under_shim(vcl_env(sock, appns_index=3), code) == "CONNECTED"


ECHO_SERVER_CODE = """
import socket, sys
ls = socket.socket()
ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
ls.bind(("127.0.0.1", 0))
ls.listen(8)
print(ls.getsockname()[1], flush=True)
while True:
    c, _ = ls.accept()          # interposed: denied peers never surface
    data = c.recv(64)
    c.sendall(b"echo:" + data)
    c.close()
"""


def test_accept_side_global_deny(admission):
    """A server under the shim: denied inbound peers are closed before
    the app sees them (the VPP session layer resets filtered sessions);
    allowed peers get service."""
    engine, sock = admission
    srv = subprocess.Popen(
        [sys.executable, "-c", ECHO_SERVER_CODE],
        env=vcl_env(sock), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        port = int(srv.stdout.readline())
        engine.apply(add=[global_rule(port, RuleAction.DENY)])

        c = socket.create_connection(("127.0.0.1", port), timeout=10)
        c.sendall(b"hi")
        # kernel completed the handshake (backlog), but the shim closes
        # the connection before the app ever accepts it
        c.settimeout(10)
        try:
            got = c.recv(64)
        except ConnectionResetError:
            got = b""
        assert got == b"", got
        c.close()

        engine.apply(delete=[global_rule(port, RuleAction.DENY)])
        c = socket.create_connection(("127.0.0.1", port), timeout=10)
        c.sendall(b"hi")
        assert c.recv(64) == b"echo:hi"
        c.close()
    finally:
        srv.kill()
        srv.wait(timeout=10)


def test_fail_open_and_fail_closed(tmp_path, listener):
    port = listener()
    dead = str(tmp_path / "nobody.sock")
    assert run_under_shim(vcl_env(dead), CONNECT_CODE, port) == "CONNECTED"
    assert run_under_shim(vcl_env(dead, fail_closed=True),
                          CONNECT_CODE, port) == "REFUSED"


def test_no_shim_env_passthrough(listener):
    """LD_PRELOAD loaded but VPP_TPU_VCL_SOCK unset: pure pass-through."""
    import os

    port = listener()
    env = dict(os.environ)
    env["LD_PRELOAD"] = shim_path()
    env.pop("VPP_TPU_VCL_SOCK", None)
    assert run_under_shim(env, CONNECT_CODE, port) == "CONNECTED"


def test_agent_serves_admission(tmp_path):
    """vcl_socket in AgentConfig brings the endpoint up on the live
    agent, answering the shim protocol from the agent's own
    SessionRuleEngine (the one the VPPTCP renderer programs)."""
    from vpp_tpu.cmd import AgentConfig, ContivAgent
    from vpp_tpu.kvstore.store import KVStore

    path = str(tmp_path / "agent_vcl.sock")
    agent = ContivAgent(
        AgentConfig(node_name="n1", serve_http=False, vcl_socket=path),
        store=KVStore())
    agent.start()
    try:
        agent.session_engine.apply(add=[local_rule(5, 8080,
                                                   RuleAction.DENY)])
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.sendall(_REQ.pack(OP_CONNECT, 6, 0, 5, 0, ipi("127.0.0.1"),
                            0, 8080))
        assert s.recv(1) == b"\x00"      # denied
        s.sendall(_REQ.pack(OP_CONNECT, 6, 0, 6, 0, ipi("127.0.0.1"),
                            0, 8080))
        assert s.recv(1) == b"\x01"      # other namespace: allowed
        # publish BEFORE closing: the server decrements the live-client
        # gauge as soon as it sees our EOF, and losing that race would
        # flake the clients==1 assertion
        agent.stats.publish()
        g = agent.stats.vcl_gauges
        assert g["vpp_tpu_vcl_connect_checks"].get() == 2
        assert g["vpp_tpu_vcl_connect_denies"].get() == 1
        assert g["vpp_tpu_vcl_clients"].get() == 1
        s.close()
    finally:
        agent.close()


NONBLOCK_SERVER_CODE = """
import socket, sys, time
ls = socket.socket()
ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
ls.bind(("127.0.0.1", 0))
ls.listen(8)
print(ls.getsockname()[1], flush=True)
sys.stdin.readline()        # wait for GO (both peers queued)
ls.setblocking(False)
deadline = time.time() + 10
while True:
    try:
        c, peer = ls.accept()   # one wake must surface the ALLOWED peer
        print(peer[1], flush=True)
        break
    except BlockingIOError:
        if time.time() > deadline:
            print("EAGAIN-TIMEOUT", flush=True)
            break
        time.sleep(0.05)
c.recv(16)
"""


def test_nonblocking_accept_skips_denied_backlog(admission):
    """A denied peer queued AHEAD of an allowed one must not turn the
    wake into EAGAIN — edge-triggered pollers would never be re-notified
    for the allowed connection. The shim drains the denied peer and
    returns the allowed one from the same accept() call."""
    engine, sock = admission
    srv = subprocess.Popen(
        [sys.executable, "-c", NONBLOCK_SERVER_CODE],
        env=vcl_env(sock), stdin=subprocess.PIPE,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        port = int(srv.stdout.readline())
        # deny inbound from source port 23001 specifically
        engine.apply(add=[SessionRule(
            scope=int(RuleScope.GLOBAL), appns_index=GLOBAL_NS,
            transport_proto=6, lcl_net=ipi("127.0.0.1"), lcl_plen=32,
            rmt_net=ipi("127.0.0.1"), rmt_plen=32,
            lcl_port=port, rmt_port=23001,
            action=int(RuleAction.DENY))])

        denied = socket.socket()
        denied.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        denied.bind(("127.0.0.1", 23001))
        denied.connect(("127.0.0.1", port))   # queued first
        allowed = socket.create_connection(("127.0.0.1", port),
                                           timeout=10)
        time.sleep(0.3)                        # both in the backlog
        srv.stdin.write("GO\n")
        srv.stdin.flush()
        got = srv.stdout.readline().strip()
        assert got == str(allowed.getsockname()[1]), got
        denied.close()
        allowed.close()
    finally:
        srv.kill()
        srv.wait(timeout=10)


def test_threaded_client_concurrent_verdicts(admission, listener):
    """Per-thread admission channels: N app threads connect
    concurrently and every verdict lands on the right call (no
    cross-thread verdict mixups on a shared stream)."""
    engine, sock = admission
    port_deny = listener()
    port_allow = listener()
    engine.apply(add=[local_rule(9, port_deny, RuleAction.DENY)])
    code = """
import socket, sys, threading
deny_port, allow_port = int(sys.argv[1]), int(sys.argv[2])
results = {}
lock = threading.Lock()

def probe(i):
    port = deny_port if i % 2 == 0 else allow_port
    s = socket.socket()
    s.settimeout(10)
    try:
        s.connect(("127.0.0.1", port))
        out = "CONNECTED"
        s.close()
    except ConnectionRefusedError:
        out = "REFUSED"
    with lock:
        results[i] = out

threads = [threading.Thread(target=probe, args=(i,)) for i in range(16)]
for t in threads:
    t.start()
for t in threads:
    t.join()
bad = [i for i, r in results.items()
       if r != ("REFUSED" if i % 2 == 0 else "CONNECTED")]
print("BAD" if bad else "ALL-OK", bad)
"""
    out = run_under_shim(vcl_env(sock, appns_index=9), code,
                         port_deny, port_allow)
    assert out.startswith("ALL-OK"), out


def test_thread_exit_closes_admission_fd(admission, listener):
    """Per-thread channels must not leak fds when threads die — a
    thread-per-connection server would otherwise grow one admission fd
    per handled connection (TLS destructor closes them)."""
    engine, sock = admission
    port = listener()
    code = """
import os, socket, sys, threading
port = int(sys.argv[1])

def fds():
    return len(os.listdir("/proc/self/fd"))

def probe():
    s = socket.socket()
    s.settimeout(10)
    s.connect(("127.0.0.1", port))
    s.close()

# one warm round so lazy init (TLS key etc.) is paid
t = threading.Thread(target=probe); t.start(); t.join()
base = fds()
for _ in range(40):
    t = threading.Thread(target=probe)
    t.start()
    t.join()
print("LEAK" if fds() > base + 2 else "BOUNDED", base, fds())
"""
    out = run_under_shim(vcl_env(sock, appns_index=2), code, port)
    assert out.startswith("BOUNDED"), out


def test_engine_exception_answers_deny_not_disconnect(admission):
    """A per-request engine error (a JAX/device fault, a table bug)
    must answer DENY and keep serving — with the shim's default
    fail-open config, tearing down the serve loop would turn every
    later verdict on that app into an allow (policy bypass via an
    agent-side bug, not agent unavailability)."""
    engine, sock = admission

    boom = {"n": 1}
    real_check = engine.check_connect

    def flaky_check(batch):
        if boom["n"]:
            boom["n"] -= 1
            raise RuntimeError("injected engine fault")
        return real_check(batch)

    engine.check_connect = flaky_check

    c = socket.socket(socket.AF_UNIX)
    c.settimeout(10)
    c.connect(sock)
    req = _REQ.pack(OP_CONNECT, 6, 0, 0,
                    ipi("127.0.0.1"), ipi("127.0.0.1"), 0, 80)
    # request 1: engine raises -> deny byte, connection STAYS up
    c.sendall(req)
    assert c.recv(1) == b"\x00"
    # request 2 on the SAME connection: engine healthy again -> real
    # verdict (no rules -> allow), proving the serve loop survived
    c.sendall(req)
    assert c.recv(1) == b"\x01"
    c.close()
