"""Gateway fleet: consistent-hash steering, live migration, elastic
scale-out (ISSUE 18 tentpole).

What must hold:

* the NumPy steering hash is BIT-IDENTICAL to the device ``sym``
  session hash (differential over random tuples, hairpins included) —
  the whole design rests on the steering tier and the instances
  agreeing on every packet's bucket;
* rendezvous assignment is deterministic and disruption-bounded:
  adding a member moves only ranges the newcomer wins, removing one
  moves only its own ranges;
* steering conservation is EXACT: offered == steered + attributed
  drops at every instant, including mid-rebalance and after a crashed
  migration;
* live migration preserves sessions: reply-direction traffic after a
  range moves hits the fastpath on the NEW owner (hit rate >= 0.9,
  the warm-restart bar), and the source's released range serves
  nothing;
* fencing is absolute: from the fence CAS to the commit, NO steering
  tier (including a second tier sharing the store) routes the range
  anywhere — a crashed migration leaves attributed drops, never
  misdelivery, and ``recover()`` completes the move;
* per-tenant placement composes with tnt_sess_base/mask: a sliced
  tenant's bucket window projects onto multiple ranges and therefore
  multiple instances.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from vpp_tpu.fleet.hashring import (
    assign_ranges,
    buckets_of_packed,
    canon_mix_np,
    moved_ranges,
    range_span,
    tenant_ranges,
    tenant_spread,
)
from vpp_tpu.fleet.membership import FENCED, FleetMembership
from vpp_tpu.fleet.steering import FleetSteering
from vpp_tpu.io.fleet import FleetPump
from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.kvstore.store import KVStore
from vpp_tpu.pipeline.dataplane import Dataplane, pack_packet_columns
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector
from vpp_tpu.testing import faults


def build_dp(**over):
    base = dict(
        max_tables=2, max_rules=16, max_global_rules=16, max_ifaces=8,
        fib_slots=16, sess_slots=1024, sess_ways=4, nat_mappings=2,
        nat_backends=2, sess_sweep_stride=0, sess_hash="sym",
    )
    base.update(over)
    dp = Dataplane(DataplaneConfig(**base))
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("default", "web"))
    dp.builder.add_route("10.1.1.0/24", pod, Disposition.LOCAL)
    dp.builder.add_route("0.0.0.0/0", up, Disposition.REMOTE,
                         node_id=1)
    dp.builder.set_global_table([
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP),
        ContivRule(action=Action.DENY),
    ])
    dp.swap()
    return dp


def forward_pkts(n, base=0, rx_if=1):
    return make_packet_vector(
        [{"src": f"10.9.{(base + i) // 200}.{(base + i) % 200 + 1}",
          "dst": "10.1.1.2", "proto": 6,
          "sport": 1000 + (base + i) % 50000,
          "dport": 80, "rx_if": rx_if, "ttl": 64}
         for i in range(n)], n=n)


def reply_pkts(n, base=0, rx_if=2):
    return make_packet_vector(
        [{"src": "10.1.1.2",
          "dst": f"10.9.{(base + i) // 200}.{(base + i) % 200 + 1}",
          "proto": 6, "sport": 80,
          "dport": 1000 + (base + i) % 50000, "rx_if": rx_if,
          "ttl": 64}
         for i in range(n)], n=n)


def pack_pv(pv) -> np.ndarray:
    cols = {k: np.asarray(getattr(pv, k))
            for k in ("src_ip", "dst_ip", "proto", "sport", "dport",
                      "ttl", "pkt_len", "rx_if", "flags")}
    n = cols["src_ip"].shape[0]
    flat = np.zeros((5, n), np.int32)
    pack_packet_columns(flat.view(np.uint32), cols, n)
    return flat


def live_count(dp) -> int:
    return int(jnp.sum(dp.tables.sess_valid))


def build_fleet(names, n_ranges=8, store=None, **over):
    dps = {n: build_dp(**over) for n in names}
    membership = None
    if store is not None:
        membership = FleetMembership(store, name="steering")
    st = FleetSteering(dps, membership=membership, n_ranges=n_ranges)
    return dps, st


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    yield
    faults.uninstall()


# --- the hash pact ---------------------------------------------------


class TestHashTwin:
    def test_numpy_twin_is_bit_identical_to_device_sym_hash(self):
        from vpp_tpu.ops.session import canon_mix

        rng = np.random.default_rng(7)
        n = 8192
        src = rng.integers(0, 2**32, n, dtype=np.uint32)
        dst = rng.integers(0, 2**32, n, dtype=np.uint32)
        sp = rng.integers(0, 2**16, n, dtype=np.uint32)
        dp = rng.integers(0, 2**16, n, dtype=np.uint32)
        pr = rng.integers(0, 256, n, dtype=np.uint32)
        # force hairpins (src == dst) into the sample: the port
        # tie-break is exactly the case address ordering can't cover
        dst[: n // 8] = src[: n // 8]
        host = canon_mix_np(src, dst, sp, dp, pr)
        dev = np.asarray(canon_mix(
            jnp.asarray(src), jnp.asarray(dst), jnp.asarray(sp),
            jnp.asarray(dp), jnp.asarray(pr.astype(np.int32))))
        assert np.array_equal(host, dev.astype(np.uint32))

    def test_direction_invariance_including_hairpins(self):
        rng = np.random.default_rng(11)
        n = 4096
        src = rng.integers(0, 2**32, n, dtype=np.uint32)
        dst = rng.integers(0, 2**32, n, dtype=np.uint32)
        sp = rng.integers(0, 2**16, n, dtype=np.uint32)
        dp = rng.integers(0, 2**16, n, dtype=np.uint32)
        pr = rng.integers(0, 256, n, dtype=np.uint32)
        dst[: n // 8] = src[: n // 8]
        fwd = canon_mix_np(src, dst, sp, dp, pr)
        rev = canon_mix_np(dst, src, dp, sp, pr)
        assert np.array_equal(fwd, rev)

    def test_packed_frame_buckets_match_column_hash(self):
        pv = forward_pkts(100)
        flat = pack_pv(pv)
        got = buckets_of_packed(flat, 64)
        mix = canon_mix_np(np.asarray(pv.src_ip),
                           np.asarray(pv.dst_ip),
                           np.asarray(pv.sport),
                           np.asarray(pv.dport),
                           np.asarray(pv.proto))
        assert np.array_equal(got, (mix & np.uint32(63)).astype(np.int64))

    def test_sym_dataplane_buckets_replies_with_forward_flows(self):
        """The semantic the twin test can't see: on a sym instance the
        reply's bucket equals the forward insert's bucket, so a
        steering tier hashing the packet AS SEEN delivers both
        directions of a flow to one instance."""
        dp = build_dp()
        dp.process(forward_pkts(60, rx_if=1), now=10)
        before = live_count(dp)
        res = dp.process(reply_pkts(60, rx_if=2), now=11)
        hits = int(res.stats.sess_hits)
        assert before >= 54  # a few way-conflicts are table physics
        assert hits >= 54


# --- rendezvous ------------------------------------------------------


class TestRendezvous:
    def test_deterministic_and_total(self):
        a = assign_ranges(["gw0", "gw1", "gw2"], 64)
        b = assign_ranges(["gw2", "gw0", "gw1"], 64)
        assert a == b
        assert sorted(a) == list(range(64))
        assert set(a.values()) <= {"gw0", "gw1", "gw2"}

    def test_add_moves_only_ranges_the_newcomer_wins(self):
        old = assign_ranges(["gw0", "gw1", "gw2"], 128)
        new = assign_ranges(["gw0", "gw1", "gw2", "gw3"], 128)
        moved = moved_ranges(old, new)
        assert moved, "a 4th member must win some ranges"
        assert all(new[r] == "gw3" for r in moved)
        # bounded: roughly 1/N of ranges, never a reshuffle
        assert len(moved) < 128 // 2

    def test_remove_moves_only_the_departed_members_ranges(self):
        old = assign_ranges(["gw0", "gw1", "gw2"], 128)
        new = assign_ranges(["gw0", "gw1"], 128)
        moved = moved_ranges(old, new)
        assert moved
        assert all(old[r] == "gw2" for r in moved)

    def test_every_member_owns_something_at_scale(self):
        owners = assign_ranges([f"gw{i}" for i in range(4)], 256)
        counts = {m: 0 for m in (f"gw{i}" for i in range(4))}
        for m in owners.values():
            counts[m] += 1
        assert all(v > 0 for v in counts.values()), counts

    def test_range_span_covers_table_exactly_once(self):
        spans = [range_span(r, 64, 8) for r in range(8)]
        covered = sorted(b for s, n in spans for b in range(s, s + n))
        assert covered == list(range(64))


# --- tenant placement ------------------------------------------------


class TestTenantPlacement:
    def test_slice_projects_onto_its_ranges(self):
        # 64 buckets, 8 ranges of 8: a slice [16, 48) spans rids 2..5
        assert tenant_ranges(16, 31, 64, 8) == [2, 3, 4, 5]
        # a narrow slice inside one range stays on one range
        assert tenant_ranges(8, 7, 64, 8) == [1]

    def test_hot_tenant_spreads_across_instances(self):
        owners = assign_ranges(["gw0", "gw1", "gw2", "gw3"], 8)
        spread = tenant_spread(0, 63, 64, 8, owners)  # whole table
        assert len(spread) > 1
        narrow = tenant_spread(8, 7, 64, 8, owners)
        assert len(narrow) == 1

    def test_sliced_buckets_of_packed(self):
        pv = forward_pkts(50)
        flat = pack_pv(pv)
        base = np.array([0, 32], np.int64)
        mask = np.array([31, 31], np.uint32)
        tids = np.ones(50, np.int64)
        b = buckets_of_packed(flat, 64, tenant_ids=tids,
                              tnt_base=base, tnt_mask=mask)
        assert (b >= 32).all() and (b < 64).all()


# --- membership + epochs ---------------------------------------------


class TestMembership:
    def test_join_heartbeat_leave(self):
        store = KVStore()
        m1 = FleetMembership(store, "gw0", ttl_s=30.0)
        m2 = FleetMembership(store, "gw1", ttl_s=30.0)
        m1.join(), m2.join()
        assert m1.members() == ["gw0", "gw1"]
        assert m1.heartbeat()
        m2.leave()
        assert m1.members() == ["gw0"]
        assert not m2.heartbeat()  # revoked lease cannot keepalive

    def test_lease_expiry_removes_member(self):
        store = KVStore()
        m = FleetMembership(store, "gw0", ttl_s=0.001)
        m.join()
        store.sweep_leases(now=1e18)  # explicit clock, no sleeping
        assert m.members() == []
        assert not m.heartbeat()

    def test_watch_members_fires_on_change(self):
        store = KVStore()
        viewer = FleetMembership(store, "viewer")
        seen = []
        initial, cancel = viewer.watch_members(seen.append)
        assert initial == []
        m = FleetMembership(store, "gw0", ttl_s=30.0)
        m.join()
        assert seen[-1] == ["gw0"]
        m.leave()
        assert seen[-1] == []
        cancel()

    def test_epochs_fence_commit_and_only_advance(self):
        store = KVStore()
        m = FleetMembership(store, "steering")
        e1 = m.claim_range(3, "gw0")
        assert e1 == 1 and m.is_current(3, 1)
        e2 = m.fence_range(3, "gw1")
        assert e2 == 2
        assert not m.is_current(3, 1), "old epoch must die at fence"
        assert not m.is_current(3, 2), "fenced is not serving"
        assert m.fenced_ranges() == {
            3: {"epoch": 2, "state": FENCED, "owner": "gw0",
                "to": "gw1"}}
        assert m.commit_range(3, 2, "gw1")
        assert m.is_current(3, 2)
        assert m.range_state(3)["owner"] == "gw1"

    def test_stale_commit_is_refused(self):
        store = KVStore()
        m = FleetMembership(store, "steering")
        m.claim_range(0, "gw0")
        e = m.fence_range(0, "gw1")
        e2 = m.fence_range(0, "gw2")  # a second migrator supersedes
        assert e2 > e
        assert not m.commit_range(0, e, "gw1"), \
            "superseded fence must not commit"
        assert m.commit_range(0, e2, "gw2")


# --- steering --------------------------------------------------------


class TestSteering:
    def test_requires_sym_hash_and_uniform_geometry(self):
        fwd = build_dp(sess_hash="fwd")
        sym = build_dp()
        with pytest.raises(ValueError, match="sym"):
            FleetSteering({"a": fwd, "b": sym})
        other = build_dp(sess_slots=512)
        with pytest.raises(ValueError, match="geometry"):
            FleetSteering({"a": sym, "b": other})

    def test_partition_conserves_exactly(self):
        _dps, st = build_fleet(["gw0", "gw1"])
        flat = pack_pv(forward_pkts(200))
        groups, drops = st.partition(flat)
        routed = sum(idx.size for idx in groups.values())
        assert routed + drops["fenced"] + drops["no_owner"] == 200
        offered, accounted = st.conservation()
        assert offered == accounted == 200

    def test_steered_sessions_land_on_their_owner_only(self):
        dps, st = build_fleet(["gw0", "gw1"])
        pump = FleetPump(st, frame_width=64, queue_slots=32)
        pump.start()
        pump.submit(pack_pv(forward_pkts(200)))
        pump.stop()
        c = pump.conservation()
        assert c["offered"] == 200 and c["pending"] == 0
        assert (c["delivered"] + c["fenced_drops"] + c["no_owner_drops"]
                + c["queue_drops"]) == 200
        # each instance holds sessions ONLY in buckets of ranges it
        # owns — the single-writer-per-range law, checked on-device
        owners = st.owners()
        for name, dp in dps.items():
            valid = np.asarray(jnp.sum(dp.tables.sess_valid, axis=1))
            for rid in range(st.n_ranges):
                start, n = range_span(rid, st.n_buckets, st.n_ranges)
                in_range = int(valid[start:start + n].sum())
                if owners[rid] != name:
                    assert in_range == 0, (name, rid)

    def test_fenced_range_drops_attributed(self):
        _dps, st = build_fleet(["gw0", "gw1"])
        epoch = st.membership.fence_range(0, "gw1")
        # the epoch watch applied the fence to the route table
        flat = pack_pv(forward_pkts(300))
        groups, drops = st.partition(flat)
        b = buckets_of_packed(flat, st.n_buckets)
        expect = int((b // st._per == 0).sum())
        assert expect > 0, "sample must cover range 0"
        assert drops["fenced"] == expect
        offered, accounted = st.conservation()
        assert offered == accounted
        # and a second tier on the SAME store is fenced too
        st2 = FleetSteering(_dps, membership=st.membership,
                            n_ranges=st.n_ranges)
        _g2, d2 = st2.partition(flat)
        assert d2["fenced"] == expect
        assert st.membership.commit_range(0, epoch, "gw1")
        _g3, d3 = st.partition(flat)
        assert d3["fenced"] == 0


# --- live migration --------------------------------------------------


def _drive(st, flat, frame_width=64):
    pump = FleetPump(st, frame_width=frame_width, queue_slots=64)
    pump.start()
    pump.submit(flat)
    pump.stop()
    return pump


class TestMigration:
    def test_moved_range_serves_replies_on_new_owner(self):
        dps, st = build_fleet(["gw0", "gw1"])
        _drive(st, pack_pv(forward_pkts(240)))
        total_before = sum(live_count(d) for d in dps.values())

        # force EVERY range onto gw1, migrating gw0's live state
        target = {r: "gw1" for r in range(st.n_ranges)}
        before_owned_by_gw0 = [r for r, o in st.owners().items()
                               if o == "gw0"]
        moved = st.rebalance(target)
        assert moved == len(before_owned_by_gw0) > 0
        assert sum(live_count(d) for d in dps.values()) == total_before
        assert live_count(dps["gw0"]) == 0, "released ranges serve " \
            "nothing on the source"

        pump = _drive(st, pack_pv(reply_pkts(240)))
        aux = pump.stats_snapshot()["aux"]
        assert set(aux) == {"gw1"}, "all replies steered to new owner"
        rx = aux["gw1"]["rx"]
        hits = aux["gw1"]["sess_hits"]
        assert rx == 240
        assert hits / rx >= 0.9, (hits, rx)

    def test_migration_rebases_session_ages(self):
        """A session idle on the source stays the SAME age on the
        destination even when the two instances' tick clocks differ —
        the restore rebase law, applied live."""
        dps, st = build_fleet(["gw0", "gw1"])
        # skew the destination clock far ahead of the source
        dps["gw1"].advance_clock(1000.0)
        _drive(st, pack_pv(forward_pkts(240)))
        before = sum(live_count(d) for d in dps.values())
        st.rebalance({r: "gw1" for r in range(st.n_ranges)})
        assert sum(live_count(d) for d in dps.values()) == before
        # expire with the destination's clock: rebased entries are
        # YOUNG there (age preserved), so none expire within timeout
        dps["gw1"].advance_clock(1.0)
        dps["gw1"].expire_sessions()
        assert live_count(dps["gw1"]) == before

    def test_scale_out_migrates_only_moved_ranges(self):
        dps, st = build_fleet(["gw0", "gw1"])
        _drive(st, pack_pv(forward_pkts(240)))
        old = st.owners()
        dp2 = build_dp()
        st2 = FleetSteering({**dps, "gw2": dp2},
                            membership=st.membership,
                            n_ranges=st.n_ranges)
        target = st2.target_assignment(["gw0", "gw1", "gw2"])
        expected_moves = moved_ranges(old, target)
        assert all(target[r] == "gw2" for r in expected_moves), \
            "rendezvous: scale-out moves ranges only to the newcomer"
        moved = st2.rebalance(target)
        assert moved == len(expected_moves)
        s = st2.stats_snapshot()
        assert s["migrated_ranges"] == len(expected_moves)


# --- chaos: crashed migration, fencing, recovery ---------------------


class TestMigrationChaos:
    def _fleet_with_traffic(self):
        dps, st = build_fleet(["gw0", "gw1"])
        _drive(st, pack_pv(forward_pkts(240)))
        return dps, st

    @pytest.mark.parametrize("after", [0, 1])
    def test_crash_mid_drain_leaves_range_fenced_conserving(self,
                                                            after):
        dps, st = self._fleet_with_traffic()
        total = sum(live_count(d) for d in dps.values())
        plan = faults.FaultPlan(seed=18)
        plan.inject("fleet.migrate", action="error", after=after,
                    times=1)
        faults.install(plan)
        target = {r: "gw1" for r in range(st.n_ranges)}
        with pytest.raises(Exception) as ei:
            st.rebalance(target)
        assert isinstance(ei.value, faults.FaultInjected)
        faults.uninstall()

        fenced = st.membership.fenced_ranges()
        assert len(fenced) == 1, "crash fenced exactly the in-flight " \
            "range"
        (rid, st_rec), = fenced.items()
        assert st_rec["to"] == "gw1"
        # no session was lost: source still holds everything un-moved
        # (commit-before-release means pre-commit crashes never zero
        # the source)
        assert sum(live_count(d) for d in dps.values()) >= total

        # steering NEVER serves the fenced epoch: traffic for the
        # fenced range drops, attributed — conservation stays exact
        pump = _drive(st, pack_pv(forward_pkts(240)))
        c = pump.conservation()
        assert c["offered"] == (c["delivered"] + c["fenced_drops"]
                                + c["no_owner_drops"]
                                + c["queue_drops"] + c["pending"])
        assert c["fenced_drops"] > 0

        # recovery completes the move against the SAME epoch
        assert st.recover() == 1
        assert st.membership.fenced_ranges() == {}
        assert st.owners()[rid] == "gw1"
        assert sum(live_count(d) for d in dps.values()) == total

        # and the migrated flows serve replies on the new owner
        pump2 = _drive(st, pack_pv(reply_pkts(240)))
        aux = pump2.stats_snapshot()["aux"]
        rx = sum(a["rx"] for a in aux.values())
        hits = sum(a["sess_hits"] for a in aux.values())
        assert hits / rx >= 0.9, (hits, rx)

    def test_crash_before_commit_recovers_idempotently(self):
        dps, st = self._fleet_with_traffic()
        total = sum(live_count(d) for d in dps.values())
        plan = faults.FaultPlan(seed=7)
        # drain_bucket_range fires per chunk; the PRE-COMMIT seam is
        # the last fire of one migration — sessions adopted on the
        # destination but the epoch not flipped
        n_chunk_fires = (st.n_buckets // st.n_ranges) // 256 + 1
        plan.inject("fleet.migrate", action="error",
                    after=n_chunk_fires, times=1)
        faults.install(plan)
        with pytest.raises(Exception):
            st.rebalance({r: "gw1" for r in range(st.n_ranges)})
        faults.uninstall()
        assert len(st.membership.fenced_ranges()) == 1
        assert st.recover() == 1
        # re-drain + re-adopt overwrote, never duplicated
        assert sum(live_count(d) for d in dps.values()) == total

    def test_steer_fault_surfaces_not_swallowed(self):
        _dps, st = self._fleet_with_traffic()
        plan = faults.FaultPlan(seed=3)
        plan.inject("fleet.steer", action="error", times=1)
        faults.install(plan)
        with pytest.raises(Exception) as ei:
            st.partition(pack_pv(forward_pkts(10)))
        assert isinstance(ei.value, faults.FaultInjected)


# --- the pump tier ---------------------------------------------------


class TestFleetPump:
    def test_queue_overflow_drops_attributed(self):
        _dps, st = build_fleet(["gw0"])
        pump = FleetPump(st, frame_width=32, queue_slots=2)
        # workers NOT started: the queue fills, overflow must be
        # counted, never silent
        for _ in range(8):
            pump.submit(pack_pv(forward_pkts(32)))
        pump.flush()
        c = pump.conservation()
        assert c["queue_drops"] > 0
        assert c["offered"] == (c["delivered"] + c["fenced_drops"]
                                + c["no_owner_drops"]
                                + c["queue_drops"] + c["pending"])
        # drain what's queued so stop() doesn't wait on it
        pump.start()
        pump.stop()
        c = pump.conservation()
        assert c["pending"] == 0
        assert c["offered"] == (c["delivered"] + c["fenced_drops"]
                                + c["no_owner_drops"]
                                + c["queue_drops"])

    def test_partial_frames_pad_with_invalid_slots(self):
        dps, st = build_fleet(["gw0"])
        pump = FleetPump(st, frame_width=64, queue_slots=8)
        pump.start()
        pump.submit(pack_pv(forward_pkts(10)))  # far below one frame
        pump.stop()
        snap = pump.stats_snapshot()
        assert snap["delivered"]["gw0"] == 10
        # rx counts VALID packets only — pads are invisible
        assert snap["aux"]["gw0"]["rx"] == 10


# --- observability ---------------------------------------------------


class TestFleetObservability:
    def test_collector_exports_fleet_families(self):
        from vpp_tpu.stats.collector import STATS_PATH, StatsCollector

        dps, st = build_fleet(["gw0", "gw1"])
        pump = _drive(st, pack_pv(forward_pkts(100)))
        coll = StatsCollector(next(iter(dps.values())))
        coll.set_fleet(st, pump)
        coll.publish()
        text = coll.registry.render(STATS_PATH)
        assert 'vpp_tpu_fleet_instances 2' in text
        assert 'vpp_tpu_fleet_steered_total{instance="gw0"}' in text
        assert 'vpp_tpu_fleet_drops_total{cause="fenced"}' in text
        assert 'vpp_tpu_fleet_drops_total{cause="queue"}' in text

    def test_show_fleet(self):
        from vpp_tpu.cli import DebugCLI

        dps, st = build_fleet(["gw0", "gw1"])
        pump = _drive(st, pack_pv(forward_pkts(100)))
        cli = DebugCLI(next(iter(dps.values())), fleet=st,
                       fleet_pump=pump)
        out = cli.run("show fleet")
        assert "2 instances" in out
        assert "EXACT" in out
        assert "gw0" in out and "gw1" in out
        # unconfigured path stays useful
        cli2 = DebugCLI(next(iter(dps.values())))
        assert "not configured" in cli2.run("show fleet")


# --- NAT coldstarts across migration (ISSUE 19) ----------------------


VIP = "10.96.0.10"


def nat_pkts(n, base=0, rx_if=1):
    """Forward flows to the service VIP: DNAT'd on the owner, so each
    distinct flow leaves a live NAT session behind."""
    return make_packet_vector(
        [{"src": f"10.9.{(base + i) // 200}.{(base + i) % 200 + 1}",
          "dst": VIP, "proto": 6,
          "sport": 1000 + (base + i) % 50000, "dport": 80,
          "rx_if": rx_if, "ttl": 64}
         for i in range(n)], n=n)


def natsess_live(dp) -> int:
    return int(jnp.sum(dp.tables.natsess_valid))


class TestNatColdstarts:
    """Range migration moves the reflective session table but NOT the
    NAT table (NAT state keys on the post-NAT pair): the flows left
    behind are COUNTED exactly (``nat_coldstarts``), and the new owner
    re-establishes them from the mapping tables within one window."""

    def _fleet_with_nat(self):
        from vpp_tpu.pipeline.vector import ip4

        dps, st = build_fleet(["gw0", "gw1"])
        for dp in dps.values():
            with dp.commit_lock:
                dp.builder.set_nat_mapping(
                    0, ip4(VIP), 80, 6,
                    [(ip4("10.1.1.2"), 80, 1)], boff=0)
                dp.swap()
        return dps, st

    def test_migration_counts_and_conserves_nat_coldstarts(self):
        dps, st = self._fleet_with_nat()
        _drive(st, pack_pv(nat_pkts(240)))
        per = {n: natsess_live(d) for n, d in dps.items()}
        assert per["gw0"] > 0 and per["gw1"] > 0, per
        assert st.stats_snapshot()["nat_coldstarts"] == 0

        st.rebalance({r: "gw1" for r in range(st.n_ranges)})
        # exact conservation: the counter is precisely the live NAT
        # sessions the source held in moved ranges — no more, no less
        assert st.stats_snapshot()["nat_coldstarts"] == per["gw0"]

        # re-established within one steering window: the SAME flows
        # re-driven all steer to the new owner, DNAT again from the
        # mapping tables, and nothing goes unattributed
        pump = _drive(st, pack_pv(nat_pkts(240)))
        snap = pump.stats_snapshot()
        assert snap["delivered"].get("gw1", 0) == 240
        assert snap["aux"]["gw1"]["rx"] == 240
        assert natsess_live(dps["gw1"]) >= per["gw0"]

    def test_coldstart_counter_exported(self):
        from vpp_tpu.stats.collector import STATS_PATH, StatsCollector

        dps, st = self._fleet_with_nat()
        pump = _drive(st, pack_pv(nat_pkts(120)))
        st.rebalance({r: "gw0" for r in range(st.n_ranges)})
        cold = st.stats_snapshot()["nat_coldstarts"]
        assert cold > 0
        coll = StatsCollector(dps["gw0"])
        coll.set_fleet(st, pump)
        coll.publish()
        text = coll.registry.render(STATS_PATH)
        line = [l for l in text.splitlines()
                if l.startswith("vpp_tpu_fleet_nat_coldstarts_total")]
        assert line and float(line[0].split()[-1]) == float(cold)
