"""Service path end-to-end: K8s Service+Endpoints → NAT44 → packet verdicts.

Reference analog: plugins/service tests + the NAT44 semantics of
configurator_impl.go (weighted LB, nodeports, Local traffic policy).
"""

import numpy as np

from vpp_tpu.ir.rule import PodID
from vpp_tpu.ksr import model as m
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.vector import VEC, Disposition, ip4, ip4_str, make_packet_vector
from vpp_tpu.service import ServiceConfigurator, ServiceProcessor

CLIENT = PodID("default", "client")
BE1 = PodID("default", "be1")
BE2 = PodID("default", "be2")
IPS = {CLIENT: "10.1.1.2", BE1: "10.1.1.3", BE2: "10.1.1.4"}
NODE_IP = "192.168.16.1"


def make_env(node_name="node-a"):
    dp = Dataplane()
    uplink = dp.add_uplink()
    for pid in (CLIENT, BE1, BE2):
        idx = dp.add_pod_interface(pid)
        dp.builder.add_route(f"{IPS[pid]}/32", idx, Disposition.LOCAL)
    dp.builder.add_route("0.0.0.0/0", uplink, Disposition.REMOTE)
    dp.swap()
    cfg = ServiceConfigurator(dp, node_ips=[NODE_IP])
    proc = ServiceProcessor(cfg, node_name=node_name)
    return dp, cfg, proc


def web_service(cluster_ip="10.96.0.10", node_port=0, etp="Cluster"):
    return m.Service(
        name="web",
        namespace="default",
        cluster_ip=cluster_ip,
        external_traffic_policy=etp,
        ports=[m.ServicePort(name="http", protocol="TCP", port=80,
                             target_port="http", node_port=node_port)],
    )


def web_endpoints(node_for_be1="node-a", node_for_be2="node-b"):
    return m.Endpoints(
        name="web",
        namespace="default",
        subsets=[
            m.EndpointSubset(
                addresses=[
                    m.EndpointAddress(ip=IPS[BE1], node_name=node_for_be1),
                    m.EndpointAddress(ip=IPS[BE2], node_name=node_for_be2),
                ],
                ports=[m.EndpointPort(name="http", port=8080, protocol="TCP")],
            )
        ],
    )


def send(dp, src_ip, dst_ip, dport, rx_if, sport=40000):
    pkts = make_packet_vector(
        [{"src": src_ip, "dst": dst_ip, "proto": 6, "sport": sport,
          "dport": dport, "rx_if": rx_if}]
    )
    return dp.process(pkts)


def test_cluster_ip_service():
    dp, cfg, proc = make_env()
    proc.update_service(web_service())
    proc.update_endpoints(web_endpoints())

    r = send(dp, IPS[CLIENT], "10.96.0.10", 80, dp.pod_if[CLIENT])
    assert Disposition(int(r.disp[0])) == Disposition.LOCAL
    assert ip4_str(r.pkts.dst_ip[0]) in (IPS[BE1], IPS[BE2])
    assert int(r.pkts.dport[0]) == 8080


def test_local_backend_gets_double_weight():
    dp, cfg, proc = make_env(node_name="node-a")  # BE1 is local
    proc.update_service(web_service())
    proc.update_endpoints(web_endpoints())

    specs = [
        {"src": IPS[CLIENT], "dst": "10.96.0.10", "proto": 6,
         "sport": 20000 + i, "dport": 80, "rx_if": dp.pod_if[CLIENT]}
        for i in range(VEC)
    ]
    r = dp.process(make_packet_vector(specs))
    d = np.asarray(r.pkts.dst_ip)
    n1 = int((d == ip4(IPS[BE1])).sum())
    n2 = int((d == ip4(IPS[BE2])).sum())
    assert n1 + n2 == VEC
    assert n1 > n2  # local 2x weight

def test_nodeport():
    dp, cfg, proc = make_env()
    proc.update_service(web_service(node_port=30080))
    proc.update_endpoints(web_endpoints())
    # External client hits the node IP on the nodeport via the uplink.
    r = send(dp, "172.16.0.9", NODE_IP, 30080, dp.uplink_if)
    assert Disposition(int(r.disp[0])) == Disposition.LOCAL
    assert int(r.pkts.dport[0]) == 8080


def test_external_traffic_policy_local():
    dp, cfg, proc = make_env(node_name="node-a")
    proc.update_service(web_service(etp="Local"))
    proc.update_endpoints(web_endpoints())
    specs = [
        {"src": IPS[CLIENT], "dst": "10.96.0.10", "proto": 6,
         "sport": 20000 + i, "dport": 80, "rx_if": dp.pod_if[CLIENT]}
        for i in range(64)
    ]
    r = dp.process(make_packet_vector(specs))
    d = np.asarray(r.pkts.dst_ip)[:64]
    assert (d == ip4(IPS[BE1])).all()  # only the local backend


def test_service_delete_removes_mapping():
    dp, cfg, proc = make_env()
    proc.update_service(web_service())
    proc.update_endpoints(web_endpoints())
    assert Disposition(int(send(dp, IPS[CLIENT], "10.96.0.10", 80, dp.pod_if[CLIENT]).disp[0])) == Disposition.LOCAL

    proc.delete_service("default", "web")
    r = send(dp, IPS[CLIENT], "10.96.0.10", 80, dp.pod_if[CLIENT])
    # VIP no longer translated; routed to default (uplink) untouched.
    assert ip4_str(r.pkts.dst_ip[0]) == "10.96.0.10"


def test_endpoints_update_changes_backends():
    dp, cfg, proc = make_env()
    proc.update_service(web_service())
    proc.update_endpoints(web_endpoints())
    # Backend 2 disappears.
    eps = m.Endpoints(
        name="web", namespace="default",
        subsets=[m.EndpointSubset(
            addresses=[m.EndpointAddress(ip=IPS[BE1], node_name="node-a")],
            ports=[m.EndpointPort(name="http", port=8080, protocol="TCP")],
        )],
    )
    proc.update_endpoints(eps)
    for sport in range(41000, 41016):
        r = send(dp, IPS[CLIENT], "10.96.0.10", 80, dp.pod_if[CLIENT], sport=sport)
        assert ip4_str(r.pkts.dst_ip[0]) == IPS[BE1]


def test_service_without_endpoints_not_mapped():
    dp, cfg, proc = make_env()
    proc.update_service(web_service())
    r = send(dp, IPS[CLIENT], "10.96.0.10", 80, dp.pod_if[CLIENT])
    assert ip4_str(r.pkts.dst_ip[0]) == "10.96.0.10"  # untranslated


def test_service_ports_removed_withdraws_mapping():
    dp, cfg, proc = make_env()
    proc.update_service(web_service())
    proc.update_endpoints(web_endpoints())
    assert Disposition(int(send(dp, IPS[CLIENT], "10.96.0.10", 80, dp.pod_if[CLIENT]).disp[0])) == Disposition.LOCAL
    # Service updated with no ports: mappings must be withdrawn.
    svc = web_service()
    svc.ports = []
    proc.update_service(svc)
    r = send(dp, IPS[CLIENT], "10.96.0.10", 80, dp.pod_if[CLIENT])
    assert ip4_str(r.pkts.dst_ip[0]) == "10.96.0.10"  # untranslated
