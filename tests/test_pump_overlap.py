"""Overlapped fetch ladder + adaptive chaining (ISSUE 1 tentpole).

The pump's staged pipeline must hide fetch latency behind the in-flight
window WITHOUT changing observable semantics: delivery stays in-order
and loss-free under a slow result transport, dispatch backpressures at
``max_inflight`` instead of growing unboundedly, a chained fold
produces bit-identical per-frame results to unchained dispatches, and
persistent-mode stop() joins cleanly with traffic still in flight
(the ADVICE r5 shutdown race).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from wire import make_frame

from vpp_tpu.io import DataplanePump, IORingPair
from vpp_tpu.native.pktio import PacketCodec
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import VEC, Disposition

CLIENT_IP = "10.1.1.2"
SERVER_IP = "10.1.1.3"


def make_forwarding_dp():
    dp = Dataplane(DataplaneConfig())
    a = dp.add_pod_interface(("default", "a"))
    b = dp.add_pod_interface(("default", "b"))
    dp.builder.add_route(f"{CLIENT_IP}/32", a, Disposition.LOCAL)
    dp.builder.add_route(f"{SERVER_IP}/32", b, Disposition.LOCAL)
    dp.swap()
    return dp, a, b


def push_frames(rings, rx_if, n_frames, per=8, codec=None, scratch=None):
    """n_frames rx frames, frame k tagged sport=20000+k so order and
    identity survive the trip."""
    codec = codec or PacketCodec()
    if scratch is None:
        scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
    for k in range(n_frames):
        frames = [
            make_frame(CLIENT_IP, SERVER_IP, proto=17, sport=20000 + k,
                       dport=1000 + k * per + j)
            for j in range(per)
        ]
        cols, n = codec.parse(frames, rx_if, scratch)
        assert rings.rx.push(cols, n, payload=scratch)


def drain(rings, want, timeout=180):
    got = []
    deadline = time.monotonic() + timeout
    while len(got) < want and time.monotonic() < deadline:
        f = rings.tx.peek()
        if f is None:
            time.sleep(0.002)
            continue
        got.append((f.cols["sport"][:f.n].copy(),
                    f.cols["dport"][:f.n].copy(),
                    f.cols["rx_if"][:f.n].copy(), f.n))
        rings.tx.release()
    return got


class TestSlowFetchOverlap:
    def test_in_order_loss_free_under_slow_fetch(self):
        """Fault injection: every result fetch pays an extra delay
        (the remote-transport RTT analog), varied per batch so fetch
        COMPLETIONS happen out of dispatch order across the worker
        pool — the tx writer's reorder buffer must still deliver every
        frame exactly once, in dispatch order."""
        dp, a, b = make_forwarding_dp()
        rings = IORingPair(n_slots=32)
        n_frames, per = 12, 8
        push_frames(rings, a, n_frames, per)
        pump = DataplanePump(
            dp, rings, max_batch=VEC, fetch_workers=4, max_inflight=4,
            # batches 0,1,2,... sleep 60/20/40/... ms: batch 1 is ready
            # before batch 0, exercising the reorder path
            fetch_delay=lambda seq: (0.06, 0.02, 0.04)[seq % 3],
        )
        pump.warm()
        pump.start()
        try:
            got = drain(rings, n_frames)
            assert len(got) == n_frames
            for k, (sports, dports, tx_ifs, n) in enumerate(got):
                assert n == per
                assert (sports == 20000 + k).all()  # dispatch order
                assert list(dports) == [1000 + k * per + j
                                        for j in range(per)]
                assert (tx_ifs == b).all()
            assert pump.stats["frames"] == n_frames
            assert pump.stats["pkts"] == n_frames * per
            assert pump.stats["batch_errors"] == 0
            # the delay was experienced as overlapped wait, not copy
            assert pump.stats["t_fetch_wait"] > 0.0
        finally:
            assert pump.stop()
            rings.close()

    def test_backpressure_engages_at_max_inflight(self):
        """With fetches wedged, the dispatch stage must stop at the
        in-flight cap (queue capacity + one batch per fetch worker
        already holding an item) and leave the rest of the backlog in
        the rx ring, not dispatch it all blind."""
        dp, a, _b = make_forwarding_dp()
        rings = IORingPair(n_slots=64)
        # 40 × 64 pkts at a VEC-pkt batch cap = ten device batches of
        # backlog: far more than the window holds, so the cap is
        # actually contended (4-pkt frames would coalesce into ONE
        # batch and never touch it)
        n_frames = 40
        push_frames(rings, a, n_frames, per=64)
        max_inflight, workers = 3, 2
        pump = DataplanePump(
            dp, rings, max_batch=VEC, fetch_workers=workers,
            max_inflight=max_inflight, fetch_delay=0.4,
        )
        pump.warm()
        pump.start()
        try:
            # let the window fill: dispatch is far faster than the
            # wedged fetches, so it hits the cap almost immediately
            time.sleep(1.0)
            # hard ceiling: the queue holds max_inflight, each fetch
            # worker can hold one dequeued item, and the writer can
            # hold one completed-but-unwritten item
            cap = max_inflight + workers + 1
            assert pump.stats["inflight_peak"] <= cap
            assert pump.stats["inflight"] >= 1  # window actually in use
            with pump._held_lock:
                held = len(pump._taken) + len(pump._done_rids)
            assert held < n_frames  # backlog stayed in the ring
            # and the backlog still drains loss-free afterwards
            got = drain(rings, n_frames)
            assert len(got) == n_frames
            for k, (sports, _d, _i, n) in enumerate(got):
                assert n == 64
                assert (sports == 20000 + k).all()
            assert pump.stats["inflight_peak"] <= cap
        finally:
            assert pump.stop()
            rings.close()


class TestDispatchShutdown:
    def test_stop_under_load_never_hangs(self):
        """stop() while batches are dispatched and the (single) fetch
        worker is wedged: the stop sentinel can land AHEAD of a batch
        the dispatcher was still handing off, and the worker exits on
        the sentinel without processing it — the tx writer must rescue
        the stranded batch instead of spinning on its seq forever
        (every thread joins; the default unbounded join relies on it)."""
        dp, a, _b = make_forwarding_dp()
        rings = IORingPair(n_slots=64)
        try:
            for cycle in range(3):
                push_frames(rings, a, 12, per=64)
                pump = DataplanePump(dp, rings, max_batch=VEC,
                                     fetch_workers=1, max_inflight=2,
                                     fetch_delay=0.05)
                if cycle == 0:
                    pump.warm()
                pump.start()
                # stop at a different pipeline fill each cycle
                time.sleep(0.05 + cycle * 0.1)
                assert pump.stop(join_timeout=30), \
                    "pump threads did not join under load"
                # whatever was dispatched must be accounted: written
                # frames + error batches, never a silently stuck seq
                while rings.tx.peek() is not None:
                    rings.tx.release()
        finally:
            rings.close()


class TestAdaptiveChain:
    def _run(self, chain_k, n_frames=24, per=64):
        dp, a, _b = make_forwarding_dp()
        rings = IORingPair(n_slots=64)
        push_frames(rings, a, n_frames, per)
        # max_batch=2·VEC: 24×64 pkts of backlog is three full buckets,
        # so the chainer (when armed) must fold
        pump = DataplanePump(dp, rings, max_batch=2 * VEC,
                             chain_k=chain_k)
        pump.warm()
        pump.start()
        try:
            got = drain(rings, n_frames)
            stats = dict(pump.stats)
        finally:
            assert pump.stop()
            rings.close()
        return got, stats

    def test_chain_and_overlap_modes_identical_results(self):
        plain, s0 = self._run(chain_k=0)
        chained, s1 = self._run(chain_k=4)
        assert s0["chain_batches"] == 0
        assert s1["chain_batches"] >= 1 and s1["chain_k_peak"] >= 2
        # fewer device dispatches for the same traffic — that's the
        # whole point of the fold
        assert s1["batches"] < s0["batches"]
        assert len(plain) == len(chained)
        for (sa, da, ia, na), (sb, db, ib, nb) in zip(plain, chained):
            assert na == nb
            assert (sa == sb).all()
            assert (da == db).all()
            assert (ia == ib).all()

    @pytest.mark.slow  # ~15 s: adaptive-threshold behavior under light load; the chain==overlap bit-exact identity stays the fast anchor
    def test_light_load_never_pays_the_chain(self):
        """A single pending frame dispatches alone at the VEC bucket —
        the chainer only folds BACKLOG (its latency cost must not leak
        into the uncongested path)."""
        dp, a, _b = make_forwarding_dp()
        rings = IORingPair(n_slots=16)
        pump = DataplanePump(dp, rings, max_batch=4 * VEC, chain_k=4)
        pump.warm()
        pump.start()
        try:
            codec = PacketCodec()
            scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
            for k in range(3):
                push_frames(rings, a, 1, per=4, codec=codec,
                            scratch=scratch)
                assert len(drain(rings, 1)) == 1  # one at a time
            assert pump.stats["chain_batches"] == 0
            assert pump.stats["batches"] == 3
        finally:
            assert pump.stop()
            rings.close()


class TestPersistentShutdown:
    @pytest.mark.parametrize("seed_frames", [0, 10])
    def test_stop_joins_cleanly_under_load(self, seed_frames):
        """stop() while frames are mid-flight between the refill queue
        and the tx writer: every thread must exit (the ADVICE r5 race
        left the writer spinning on an orphaned seq forever), and
        every batch the dispatcher COUNTED must reach the writer."""
        dp, a, _b = make_forwarding_dp()
        rings = IORingPair(n_slots=32)
        if seed_frames:
            push_frames(rings, a, seed_frames, per=4)
        pump = DataplanePump(dp, rings, mode="persistent",
                             max_inflight=4)
        pump.warm()
        pump.start()
        try:
            if seed_frames:
                # stop mid-load: at least one frame through, the rest
                # anywhere in the refill/collect/write stages
                deadline = time.monotonic() + 120
                while (pump.stats["frames"] == 0
                       and time.monotonic() < deadline):
                    time.sleep(0.005)
                assert pump.stats["frames"] > 0
            assert pump.stop(join_timeout=60), \
                "persistent pump threads did not join"
            # no orphaned seq: everything dispatched was written or
            # accounted as an error, never silently dropped
            assert (pump.stats["frames"] + pump.stats["batch_errors"]
                    >= pump.stats["batches"] - pump.max_inflight)
        finally:
            rings.close()

    @pytest.mark.slow  # ~13 s: shutdown with resident frames; orderly persistent-pump shutdown is covered fast in test_io
    def test_stop_with_frames_resident_in_device_rings(self):
        """stop() while whole windows are still in flight on the
        device rings (ISSUE 7): every thread joins, the steady state
        made zero host callbacks, and every offered packet is either
        written, attributably dropped, or still resident in the rx
        ring — nothing vanishes silently."""
        dp, a, _b = make_forwarding_dp()
        rings = IORingPair(n_slots=64)
        n_frames, per = 30, 32
        push_frames(rings, a, n_frames, per)
        pump = DataplanePump(dp, rings, mode="persistent",
                             max_inflight=2, ring_slots=2,
                             ring_windows=2)
        pump.warm()
        pump.start()
        try:
            deadline = time.monotonic() + 120
            while (pump.stats["frames"] == 0
                   and time.monotonic() < deadline):
                time.sleep(0.002)
            assert pump.stats["frames"] > 0
            assert pump.stop(join_timeout=60), \
                "pump threads did not join with windows in flight"
            s = pump.stats
            assert s["io_callbacks"] == 0
            assert s["ring_windows"] >= 1
            assert s["batch_errors"] == 0
            # count packets still resident in the rx ring (includes
            # held frames abandoned by stop — those are the shutdown
            # drops)
            remaining, k = 0, 0
            while True:
                f = rings.rx.peek_nth(k)
                if f is None:
                    break
                remaining += f.n
                k += 1
            offered = n_frames * per
            assert s["pkts"] + s["drops_tx_stall"] + remaining \
                == offered
            assert s["drops_shutdown"] <= remaining
        finally:
            rings.close()

    def test_repeated_stop_start_cycles(self):
        """The dispatch-done gate must reset per pump instance — churn
        a few persistent pumps over the same rings under load."""
        dp, a, _b = make_forwarding_dp()
        rings = IORingPair(n_slots=32)
        try:
            for cycle in range(2):
                push_frames(rings, a, 4, per=4)
                pump = DataplanePump(dp, rings, mode="persistent")
                pump.warm()
                pump.start()
                got = drain(rings, 4)
                assert len(got) == 4
                assert pump.stop(join_timeout=60)
        finally:
            rings.close()
