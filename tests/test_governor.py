"""Reflex-plane latency governor + priority lane (ISSUE 13).

Three layers:

* **control law units** — the LatencyGovernor driven directly with
  synthetic observations: ladder shape, hysteresis hold, the
  anti-oscillation guarantee (a load step across the SLO boundary
  yields a MONOTONE window-fill trajectory, no flapping), the one-way
  brownout -> recovery -> normal state machine, express-mode queue
  semantics, and the wedge ladder (a crashed control loop freezes the
  window shape, flips only the governor degraded component, and never
  raises into the pump).
* **priority filter units** — port/prefix/proto rules + dynamic flow
  marks over real frame column blocks.
* **pump integration** — the express lane through a REAL pump:
  priority frames overtake a saturating bulk backlog with bounded
  queueing (p99 within 2x of the lone-frame floor — fetch_delay makes
  the device leg deterministic), bulk conservation holds exactly
  through brownout shedding (delivered + drops_overload == offered),
  and governing traces ZERO new jitted step variants (the host-side-
  only contract).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from wire import make_frame

from vpp_tpu.io import DataplanePump, IORingPair
from vpp_tpu.io.governor import (
    GOVERNOR_MODES,
    LatencyGovernor,
    PriorityFilter,
    validate_governor_config,
)
from vpp_tpu.native.pktio import PacketCodec
from vpp_tpu.pipeline.dataplane import Dataplane, jit_compile_totals
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import VEC, Disposition
from vpp_tpu.testing import faults

CLIENT_IP = "10.1.1.2"
SERVER_IP = "10.1.1.3"
PRI_PORT = 9999


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.uninstall()


def _gov(**kw):
    kw.setdefault("slots", 8)
    kw.setdefault("max_inflight", 8)
    kw.setdefault("tick_s", 0.0)  # every maybe_tick is due
    kw.setdefault("settle_ticks", 0)
    return LatencyGovernor(kw.pop("slo_us", 1000), **kw)


# --------------------------------------------------------------------
# control-law units
# --------------------------------------------------------------------


class TestLadder:
    def test_ladder_shape_and_resting_state(self):
        gov = _gov()
        s = gov.snapshot()
        # fill doubles to slots first, then inflight to max; the
        # resting state is the TOP of the ladder (the fill cap only
        # binds under backlog, so full throughput is the default)
        assert s["fill"] == 8 and s["inflight"] == 8
        assert s["level"] == s["levels"] - 1
        assert s["mode"] == "normal" and not s["shedding"]

    def test_inflight_floor_keeps_double_buffer(self):
        gov = _gov()
        for _ in range(60):
            gov.maybe_tick(10_000, 0, 0, fill_avg=4.0)
        s = gov.snapshot()
        assert s["fill"] == 1
        # depth 1 would serialize the ring's double buffer — the
        # ladder floors inflight at 2 when the pump allows it
        assert s["inflight"] == 2

    def test_bind_is_idempotent(self):
        gov = _gov()
        gov.bind(2, 2)
        assert gov.snapshot()["fill"] == 8


class TestControlLaw:
    def test_anti_oscillation_monotone_within_bands(self):
        """Step the offered load across the SLO boundary: the fill
        trajectory must fall monotonically, HOLD inside the
        hysteresis band (no flapping), then rise monotonically —
        direction changes bounded by the number of load steps."""
        gov = _gov(recover_ticks=2)
        fills = []

        def run(p99, n):
            for _ in range(n):
                gov.maybe_tick(p99, 0, 0, fill_avg=4.0)
                fills.append(gov.fill)

        run(500, 5)      # under band (hi=1000, lo=700): hold at top
        assert fills == [8] * 5
        run(5000, 12)    # over SLO: monotone descent
        over = fills[5:17]
        assert all(b <= a for a, b in zip(over, over[1:]))
        assert over[-1] == 1
        run(850, 10)     # INSIDE the band: hold exactly (anti-flap)
        assert fills[17:27] == [1] * 10
        run(200, 30)     # under band: monotone slow recovery
        up = fills[27:]
        assert all(b >= a for a, b in zip(up, up[1:]))
        assert up[-1] == 8
        # the whole trajectory changed direction at most twice —
        # once per load step, never a flap
        dirs = [np.sign(b - a) for a, b in zip(fills, fills[1:])
                if b != a]
        changes = sum(1 for a, b in zip(dirs, dirs[1:]) if a != b)
        assert changes <= 2

    def test_brownout_is_one_way_through_recovery(self):
        gov = _gov(brownout_ticks=3, recover_ticks=3)
        # over-SLO at lone windows with a standing queue: descend,
        # then declare the SLO unattainable
        for _ in range(40):
            gov.maybe_tick(5000, 500, 0, fill_avg=1.0)
        s = gov.snapshot()
        assert s["mode"] == "brownout" and s["shedding"]
        assert s["transitions"]["brownout"] == 1
        # load subsides: brownout must exit INTO recovery, then
        # normal — never straight back
        modes = []
        for i in range(40):
            gov.maybe_tick(100, 0, 10 + i)
            modes.append(gov.snapshot()["mode"])
        assert "recovery" in modes
        assert modes[-1] == "normal"
        assert modes.index("recovery") < modes.index("normal")
        s = gov.snapshot()
        assert not s["shedding"]
        assert s["transitions"] == {"normal": 1, "brownout": 1,
                                    "recovery": 1}

    def test_express_mode_brownout_keys_off_queue_only(self):
        """With a priority lane (queue_cap bound), a p99-only breach
        holds shape — shedding bulk cannot help a lane that bypasses
        the queue — while queue pressure beyond the cap sheds."""
        gov = _gov(brownout_ticks=2)
        gov.bind(8, 8, queue_cap=100)
        for _ in range(30):
            gov.maybe_tick(5000, 10, 0)   # p99 over, queue tiny
        assert gov.snapshot()["mode"] == "normal"
        assert gov.admit(False, 10)
        for _ in range(30):
            gov.maybe_tick(5000, 300, 0)  # queue over the cap
        s = gov.snapshot()
        assert s["mode"] == "brownout"
        # brownout trims bulk to the cap, never the priority lane
        assert not gov.admit(False, 300)
        assert gov.admit(False, 50)
        assert gov.admit(True, 10_000)

    def test_queue_estimate_sheds_without_express_lane(self):
        """No priority lane: backlog counts toward the envelope via
        the EWMA service-time estimator."""
        gov = _gov(brownout_ticks=2)
        t = [0.0]

        def clock():
            return t[0]

        gov._clock = clock
        # service rate: 100 frames per 0.1 s tick -> 1 ms/frame
        for i in range(30):
            t[0] += 0.1
            gov.maybe_tick(500, 2000, 100 * i, fill_avg=1.0)
        s = gov.snapshot()
        assert s["queue_est_us"] > s["slo_us"]
        assert s["mode"] == "brownout"
        # the shed bound follows the SLO budget, not a fixed pipe
        assert gov.admit(False, 1)
        assert not gov.admit(False, 2000)

    def test_wedge_freezes_shape_and_never_raises(self):
        gov = _gov()
        for _ in range(10):
            gov.maybe_tick(5000, 0, 0, fill_avg=4.0)
        shape = (gov.snapshot()["fill"], gov.snapshot()["inflight"])
        plan = faults.install(faults.FaultPlan(seed=3))
        plan.inject("governor.tick", times=-1)
        for _ in range(10):
            gov.maybe_tick(100, 0, 0)  # would recover — but crashes
        s = gov.snapshot()
        assert s["wedged"]
        assert s["tick_errors"] == 3  # wedged after WEDGE_LIMIT, then off
        assert (s["fill"], s["inflight"]) == shape  # frozen
        assert not gov.tick_due()
        faults.uninstall()
        # one-way: a healthy fault plan does not un-wedge it
        gov.maybe_tick(100, 0, 0)
        assert gov.snapshot()["wedged"]

    def test_single_tick_failure_does_not_wedge(self):
        gov = _gov()
        plan = faults.install(faults.FaultPlan(seed=4))
        plan.inject("governor.tick", times=1)
        for _ in range(5):
            gov.maybe_tick(100, 0, 0)
        s = gov.snapshot()
        assert s["tick_errors"] == 1 and not s["wedged"]
        assert s["ticks"] >= 2  # later ticks ran

    def test_validation(self):
        with pytest.raises(ValueError):
            LatencyGovernor(0)
        with pytest.raises(ValueError):
            LatencyGovernor(100, hysteresis_pct=100)
        with pytest.raises(ValueError):
            LatencyGovernor(100, brownout_ticks=0)
        with pytest.raises(ValueError):
            LatencyGovernor(100, shed_margin=0.0)

        class IoCfg:
            latency_slo_us = 100
            governor_tick_s = 0.05
            governor_hysteresis_pct = 30
            governor_brownout_ticks = 3
            governor_recover_ticks = 5
            priority_ports = (80,)
            priority_prefixes = ("10.0.0.0/8",)
            priority_protos = ()

        validate_governor_config(IoCfg())
        IoCfg.priority_prefixes = ("not-a-cidr",)
        with pytest.raises(ValueError):
            validate_governor_config(IoCfg())

    def test_modes_constant_matches_snapshot_transitions(self):
        assert set(_gov().snapshot()["transitions"]) == set(GOVERNOR_MODES)


# --------------------------------------------------------------------
# priority filter units
# --------------------------------------------------------------------


def _cols(rows):
    """rows: (src, dst, proto, sport, dport) tuples -> column arrays"""
    a = np.asarray(rows, np.int64)
    return (a[:, 0].astype(np.uint32), a[:, 1].astype(np.uint32),
            a[:, 2], a[:, 3], a[:, 4])


class TestPriorityFilter:
    def test_port_prefix_proto_rules(self):
        pf = PriorityFilter(ports=(PRI_PORT,),
                            prefixes=("10.9.0.0/16",), protos=(1,))
        src = (10 << 24) | (9 << 16) | 5
        mask = pf.match_mask(*_cols([
            (1, 2, 6, 1000, 80),          # no match
            (1, 2, 6, 1000, PRI_PORT),    # dport
            (1, 2, 6, PRI_PORT, 80),      # sport
            (src, 2, 6, 1000, 80),        # src prefix
            (2, src, 6, 1000, 80),        # dst prefix
            (1, 2, 1, 1000, 80),          # proto (ICMP)
        ]))
        assert mask.tolist() == [False, True, True, True, True, True]

    def test_dynamic_flow_marks_bounded(self):
        pf = PriorityFilter(max_flows=2)
        assert pf.mark_flow(1, 2)
        assert pf.mark_flow(3, 4)
        assert pf.mark_flow(1, 2)       # idempotent re-mark
        assert not pf.mark_flow(5, 6)   # full: refused, not evicted
        m = pf.match_mask(*_cols([(1, 2, 6, 1, 1), (2, 1, 6, 1, 1),
                                  (5, 6, 6, 1, 1)]))
        assert m.tolist() == [True, False, False]  # directional pair
        pf.unmark_flow(1, 2)
        assert pf.flow_count() == 1
        assert not pf.match_mask(*_cols([(1, 2, 6, 1, 1)]))[0]

    def test_frame_match_any_packet(self):
        pf = PriorityFilter(ports=(PRI_PORT,))

        class F:
            n = 2
            cols = {
                "src_ip": np.array([1, 2], np.uint32),
                "dst_ip": np.array([3, 4], np.uint32),
                "proto": np.array([6, 6], np.int32),
                "sport": np.array([1000, 1001], np.int32),
                "dport": np.array([80, PRI_PORT], np.int32),
            }

        assert pf.frame_match(F())
        F.cols["dport"] = np.array([80, 81], np.int32)
        assert not pf.frame_match(F())
        F.n = 0
        assert not pf.frame_match(F())

    def test_rejects_non_ipv4_prefix(self):
        with pytest.raises(ValueError):
            PriorityFilter(prefixes=("::1/128",))

    def test_rejects_unmatchable_ports_and_protos(self):
        # a rule that can never match must be refused at load, not
        # silently classify nothing (review finding: ISSUE 13)
        with pytest.raises(ValueError):
            PriorityFilter(ports=(99999,))
        with pytest.raises(ValueError):
            PriorityFilter(ports=(0,))
        with pytest.raises(ValueError):
            PriorityFilter(protos=(-1,))
        with pytest.raises(ValueError):
            PriorityFilter(protos=(256,))


# --------------------------------------------------------------------
# pump integration (real rings + dataplane)
# --------------------------------------------------------------------


def _forwarding_dp():
    dp = Dataplane(DataplaneConfig(sess_slots=256, sess_sweep_stride=0))
    a = dp.add_pod_interface(("default", "a"))
    b = dp.add_pod_interface(("default", "b"))
    dp.builder.add_route(f"{CLIENT_IP}/32", a, Disposition.LOCAL)
    dp.builder.add_route(f"{SERVER_IP}/32", b, Disposition.LOCAL)
    dp.swap()
    return dp, a, b


class _Harness:
    """Push sequence-tagged frames, drain tx, pair latencies by seq."""

    def __init__(self, rings, rx_if):
        self.rings = rings
        self.rx_if = rx_if
        self.codec = PacketCodec()
        self.scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        self.seq = 0
        self.pushed = {}     # seq -> (t, is_pri, n)
        self.drained = {}    # seq -> (lat_s, drain_order)
        self.order = 0
        self.offered = 0

    def push(self, n_pkts=4, pri=False, tag=0):
        dport = PRI_PORT if pri else 1000 + (tag % 100)
        frames = [make_frame(CLIENT_IP, SERVER_IP, proto=17,
                             sport=20000 + (tag % 1000) * 16 + j,
                             dport=dport) for j in range(n_pkts)]
        cols, n = self.codec.parse(frames, self.rx_if, self.scratch)
        cols["meta"][:n] = self.seq
        assert self.rings.rx.push(cols, n, payload=self.scratch)
        self.pushed[self.seq] = (time.perf_counter(), pri, n)
        self.seq += 1
        self.offered += n
        return self.seq - 1

    def drain(self, timeout=0.0, until=None):
        """Drain the tx ring for up to ``timeout`` seconds; with
        ``until`` set, return as soon as that many frames have
        drained in total (the floor/ordering phases wait for a
        specific frame, not for silence)."""
        deadline = time.monotonic() + timeout
        while until is None or len(self.drained) < until:
            g = self.rings.tx.peek()
            if g is None:
                if time.monotonic() >= deadline:
                    return
                time.sleep(0.002)
                continue
            s = int(g.cols["meta"][0])
            self.rings.tx.release()
            t, _pri, _n = self.pushed[s]
            self.drained[s] = (time.perf_counter() - t, self.order)
            self.order += 1

    def lat(self, seqs):
        return [self.drained[s][0] for s in seqs if s in self.drained]


def _accounted(pump):
    s = pump.stats
    return (s["pkts"] + s["drops_error"] + s["drops_shutdown"]
            + s["drops_tx_stall"] + s["drops_rx_full"]
            + s["drops_overload"])


class TestPriorityLaneOrdering:
    @pytest.mark.slow  # ~14 s: saturating-load soak; brownout shed/conservation stays the fast governor anchor
    def test_priority_bounded_queueing_under_saturating_bulk(self):
        """The ISSUE 13 ordering contract: under a saturating bulk
        burst, flagged frames observe bounded queueing — p99 within
        2x of the lone-frame floor — while bulk conservation holds
        exactly. fetch_delay makes the device leg deterministic
        (0.12 s per batch dwarfs scheduler noise); max_inflight=1
        bounds the express residual to one in-flight batch."""
        dp, a, b = _forwarding_dp()
        rings = IORingPair(n_slots=64)
        pump = DataplanePump(dp, rings, mode="dispatch",
                             max_batch=VEC, max_inflight=1,
                             fetch_delay=0.12,
                             priority=PriorityFilter(ports=(PRI_PORT,)))
        pump.start()
        h = _Harness(rings, a)
        try:
            # warm: the first dispatch pays the process-wide jit
            # compile — it must not pollute the floor samples
            h.push(4, tag=99)
            h.drain(timeout=180.0, until=1)
            # lone-frame floor: priority frames on an idle pump (max
            # over samples — the bound's denominator must absorb the
            # same scheduler noise the loaded samples see)
            floor_seqs = []
            for i in range(4):
                floor_seqs.append(h.push(1, pri=True, tag=i))
                h.drain(timeout=30.0, until=len(h.pushed))
            floor = max(h.lat(floor_seqs))
            assert floor >= 0.12  # the injected device leg is in it
            # saturating bulk burst: 40 x 64-pkt frames = 10 full
            # VEC batches = ~1.2 s of device work queued (max_batch
            # caps coalescing, so the backlog is real batches, not
            # one absorbed mega-batch)
            bulk_seqs = [h.push(64, tag=100 + i) for i in range(40)]
            # flagged frames land BEHIND the whole backlog
            pri_seqs = []
            for i in range(5):
                pri_seqs.append(h.push(1, pri=True, tag=200 + i))
                h.drain(timeout=0.15)
            deadline = time.monotonic() + 120
            while (len(h.drained) < len(h.pushed)
                   and time.monotonic() < deadline):
                h.drain(timeout=0.5)
            pri_lat = h.lat(pri_seqs)
            assert len(pri_lat) == 5
            p99 = float(np.percentile(np.asarray(pri_lat), 99))
            assert p99 <= 2.0 * floor, (p99, floor)
            # the express lane really overtook: every priority frame
            # drained before the LAST bulk frame despite arriving
            # after all of them
            last_bulk_order = max(h.drained[s][1] for s in bulk_seqs)
            assert all(h.drained[s][1] < last_bulk_order
                       for s in pri_seqs)
            # bulk conservation: nothing shed (no governor), nothing
            # lost — every offered packet delivered
            assert pump.stop(join_timeout=60.0)
            assert pump.stats["pkts"] == h.offered
            assert _accounted(pump) == h.offered
            assert pump.stats["priority_frames"] == 9
            assert pump.stats["drops_overload"] == 0
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()

    def test_brownout_sheds_bulk_never_priority_conserved(self):
        """Governed overload: offered bulk beyond capacity is shed
        with the attributed overload cause (never silent queue
        growth), priority frames are never shed, and conservation is
        exact: delivered + drops_overload == offered."""
        dp, a, b = _forwarding_dp()
        rings = IORingPair(n_slots=128)  # queue_cap = 64 frames
        gov = LatencyGovernor(100_000, tick_s=0.02, brownout_ticks=2,
                              recover_ticks=50)
        pump = DataplanePump(dp, rings, mode="dispatch",
                             max_batch=VEC, max_inflight=2,
                             fetch_delay=0.25, governor=gov,
                             priority=PriorityFilter(ports=(PRI_PORT,)))
        pump.start()
        h = _Harness(rings, a)
        try:
            pri_seqs = []
            # offered ~3x capacity (capacity = VEC pkts / 0.1 s; bulk
            # 64-pkt frames at ~75 fps), queue_cap = 32 frames
            deadline = time.monotonic() + 5.0
            k = 0
            while time.monotonic() < deadline:
                for _ in range(5):
                    if rings.rx.pending() < 120:
                        h.push(16, tag=300 + k)
                        k += 1
                if k % 9 == 0 and rings.rx.pending() < 126:
                    # headroom-gated like the bulk pushes: express
                    # results complete early but their rx slots only
                    # release with the ring-order done-prefix, so the
                    # ring must not be pushed to the brim
                    pri_seqs.append(h.push(1, pri=True, tag=400 + k))
                h.drain()
                time.sleep(0.04)
            deadline = time.monotonic() + 180
            while (_accounted(pump) < h.offered
                   and time.monotonic() < deadline):
                h.drain(timeout=0.5)
            assert pump.stop(join_timeout=60.0)
            h.drain(timeout=1.0)
            s = pump.stats
            assert _accounted(pump) == h.offered, dict(s)
            assert s["drops_overload"] > 0          # shedding happened
            assert gov.snapshot()["transitions"]["brownout"] >= 1
            # every priority frame was delivered, none shed
            assert all(sq in h.drained for sq in pri_seqs)
            assert s["priority_pkts"] == len(pri_seqs)
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()


class TestGovernorHostSideOnly:
    @pytest.mark.jit_budget(4)
    def test_governing_traces_zero_new_step_variants(self):
        """The jit-manifest contract (ISSUE 13 satellite): a governed
        persistent pump — across window-fill changes, in-flight
        changes and shedding — reuses exactly the step variants an
        ungoverned pump compiled. The governor is host-side shaping
        only; it must never enter the jit key."""
        dp, a, b = _forwarding_dp()
        rings = IORingPair(n_slots=64)
        pump = DataplanePump(dp, rings, mode="persistent").start()
        h = _Harness(rings, a)
        try:
            h.push(4, tag=1)
            h.drain(timeout=120.0, until=1)
        finally:
            assert pump.stop(join_timeout=60.0)
        labels0 = set(jit_compile_totals())
        # governed run on the SAME dataplane: a tiny SLO forces the
        # governor through its whole ladder + brownout
        gov = LatencyGovernor(50, tick_s=0.0, brownout_ticks=1,
                              recover_ticks=1, settle_ticks=0)
        pump = DataplanePump(dp, rings, mode="persistent",
                             governor=gov,
                             priority=PriorityFilter(ports=(PRI_PORT,)))
        pump.start()
        offered0 = h.offered  # the first pump's traffic is accounted
        try:                  # on ITS stats, not this one's
            for i in range(12):
                h.push(4, tag=10 + i)
                if i % 3 == 0:
                    h.push(1, pri=True, tag=50 + i)
            deadline = time.monotonic() + 120
            while (_accounted(pump) < h.offered - offered0
                   and time.monotonic() < deadline):
                h.drain(timeout=0.5)
            assert _accounted(pump) == h.offered - offered0
            assert gov.snapshot()["ticks"] > 0
            assert pump.stats["io_callbacks"] == 0
        finally:
            assert pump.stop(join_timeout=60.0)
            rings.close()
        assert set(jit_compile_totals()) == labels0

    def test_all_priority_burst_never_wedges(self):
        """Deadlock regression (review finding, ISSUE 13): a burst of
        priority-only frames deeper than the pump's hold capacity —
        the DDoS-reflex workload itself — must flow, not wedge. The
        scan frontier stalls at the express-queue cap and resumes as
        dispatched frames complete; refusing to POP queued express
        rids under hold pressure was the deadlock."""
        dp, a, b = _forwarding_dp()
        rings = IORingPair(n_slots=16)  # hold_cap = 12
        pump = DataplanePump(
            dp, rings, mode="dispatch",
            priority=PriorityFilter(ports=(PRI_PORT,))).start()
        h = _Harness(rings, a)
        try:
            for i in range(14):
                h.push(1, pri=True, tag=700 + i)
            deadline = time.monotonic() + 180
            while (_accounted(pump) < h.offered
                   and time.monotonic() < deadline):
                h.drain(timeout=0.2)
            h.drain(timeout=0.5)
            assert _accounted(pump) == h.offered
            assert pump.stats["pkts"] == h.offered  # all delivered
            assert pump.stats["priority_frames"] == 14
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()

    def test_stager_preempts_window_with_backlog_queued(self):
        """Deterministic stager preempt: bulk slots queued BEHIND a
        priority slot before the stager starts — the window must
        close at the priority slot with backlog provably waiting
        (priority_preempts counts ONLY genuinely early closes; a lone
        priority frame on an idle queue is not a preempt)."""
        from vpp_tpu.pipeline.dataplane import packed_input_zeros
        from vpp_tpu.pipeline.persistent import PersistentPump

        dp, a, b = _forwarding_dp()
        pp = PersistentPump(
            dp.tables, batch=VEC, ring_slots=8,
            fastpath=dp._use_fastpath,
            classifier=dp.classifier_impl,
            skip_local=getattr(dp, "_skip_local", False),
            sweep_stride=getattr(dp, "_sweep_stride", None))
        flat = packed_input_zeros(VEC)
        for _ in range(3):
            pp.submit(flat, now=2)
        pp.submit(flat, now=2, priority=True)
        pp.submit(flat, now=2)
        pp.start()
        try:
            for _ in range(5):
                pp.result(timeout=180.0)
            snap = pp.stats_snapshot()
            # window 1 = [bulk, bulk, bulk, PRI] closed early with a
            # bulk slot still queued; window 2 = the trailing bulk
            assert snap["priority_preempts"] == 1, snap
            assert snap["ring_windows"] == 2
            assert snap["io_callbacks"] == 0
        finally:
            pp.stop()

    def test_persistent_priority_lane_and_fill_limit(self):
        """The governed persistent pump classifies the lane end to
        end with zero host callbacks and exact conservation (the
        timing-dependent stager-preempt count is pinned by the
        deterministic test above)."""
        dp, a, b = _forwarding_dp()
        rings = IORingPair(n_slots=64)
        gov = LatencyGovernor(500, tick_s=0.005)
        pump = DataplanePump(dp, rings, mode="persistent",
                             governor=gov,
                             priority=PriorityFilter(ports=(PRI_PORT,)))
        pump.start()
        h = _Harness(rings, a)
        try:
            for burst in range(8):
                for i in range(4):
                    h.push(4, tag=burst * 8 + i)
                h.push(1, pri=True, tag=600 + burst)
                time.sleep(0.02)
            deadline = time.monotonic() + 120
            while (_accounted(pump) < h.offered
                   and time.monotonic() < deadline):
                h.drain(timeout=0.5)
            assert _accounted(pump) == h.offered
            s = pump.stats
            assert s["priority_frames"] == 8
            assert s["io_callbacks"] == 0
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()


# --------------------------------------------------------------------
# observability wiring
# --------------------------------------------------------------------


class TestGovernorObservability:
    def test_collector_families_and_degraded(self):
        from vpp_tpu.stats.collector import StatsCollector

        dp, a, b = _forwarding_dp()
        coll = StatsCollector(dp)

        class FakePump:
            stats = {"drops_overload": 11, "priority_pkts": 3,
                     "priority_preempts": 2}
            governor = _gov()

            def latency_us(self):
                return {"p50": 0.0, "p99": 0.0, "n": 0}

        coll.set_pump(FakePump())
        coll.publish()
        text = "\n".join(
            line for _p, fam in coll.registry.families()
            for line in fam.render())
        assert 'vpp_tpu_governor_mode{mode="normal"} 1' in text
        assert 'vpp_tpu_governor_mode{mode="off"} 0' in text
        assert 'vpp_tpu_pump_drops_total{reason="overload"} 11' in text
        assert "vpp_tpu_governor_fill_slots 8" in text
        assert 'vpp_tpu_degraded{component="governor"} 0' in text
        assert "vpp_tpu_pump_priority_preempts 2" in text
        # wedge it -> degraded flips; mode gauge tracks
        plan = faults.install(faults.FaultPlan(seed=9))
        plan.inject("governor.tick", times=-1)
        for _ in range(4):
            FakePump.governor.maybe_tick(1, 0, 0)
        faults.uninstall()
        coll.publish()
        text = "\n".join(
            line for _p, fam in coll.registry.families()
            for line in fam.render())
        assert 'vpp_tpu_degraded{component="governor"} 1' in text

    def test_cli_show_governor(self):
        from vpp_tpu.cli import DebugCLI

        dp, a, b = _forwarding_dp()

        class FakePump:
            stats = {"drops_overload": 5, "priority_frames": 2,
                     "priority_pkts": 7, "priority_preempts": 1,
                     "priority_starved": 0}
            governor = _gov()
            priority = PriorityFilter(ports=(PRI_PORT,),
                                      prefixes=("10.9.0.0/16",))

        cli = DebugCLI(dp, pump=FakePump())
        out = cli.run("show governor")
        assert "mode normal" in out
        assert "fill 8 slots" in out
        assert "priority lane: 2 frames / 7 pkts" in out
        assert "overload shed: 5 pkts" in out
        # no governor attached
        cli2 = DebugCLI(dp, pump=None)
        assert "no latency governor" in cli2.run("show governor")
        assert "show governor" in cli.run("help")
