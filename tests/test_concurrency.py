"""Concurrency stress: the `make test-race` analog.

Reference model: every reference package runs under Go's race detector
(Makefile:59-70). Python can't detect data races statically, so these
tests hammer the cross-thread seams instead — CNI adds/deletes racing
policy commits racing packet processing racing epoch swaps — and assert
the invariants that a torn update would break (no lost pods, verdicts
always from a consistent epoch, session state never corrupted).
"""

import threading

import numpy as np
import pytest

from vpp_tpu.cmd import AgentConfig, ContivAgent
from vpp_tpu.cni.model import CNIRequest
from vpp_tpu.ksr import model as m
from vpp_tpu.kvstore.store import KVStore
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector

N_THREADS = 4
N_OPS = 12


@pytest.mark.parametrize("parallel_commits", [False, True],
                         ids=["serial-renderers", "parallel-renderers"])
def test_concurrent_cni_and_traffic_and_policy(parallel_commits):
    """parallel_commits=True additionally exercises the reference's
    optional concurrent renderer commit (configurator_impl.go:211-233)
    under the same storm: both renderers committing from worker threads
    while CNI and traffic race them."""
    agent = ContivAgent(
        AgentConfig(node_name="n1", serve_http=False,
                    parallel_renderer_commits=parallel_commits),
        store=KVStore(),
    )
    agent.start()
    errors = []
    barrier = threading.Barrier(N_THREADS + 2)

    def cni_worker(tid):
        try:
            barrier.wait()
            for i in range(N_OPS):
                cid = f"c{tid}-{i}"
                r = agent.cni_server.add(CNIRequest(
                    container_id=cid,
                    extra_args={"K8S_POD_NAME": f"p{tid}-{i}",
                                "K8S_POD_NAMESPACE": "default"},
                ))
                assert r.result == 0, r.error
                if i % 3 == 2:
                    agent.cni_server.delete(CNIRequest(container_id=cid))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def policy_worker():
        try:
            barrier.wait()
            for i in range(N_OPS):
                agent.policy_cache.update_policy(m.Policy(
                    name=f"pol{i % 3}", namespace="default",
                    pods=m.LabelSelector(match_labels={"app": f"a{i % 3}"}),
                    policy_type=m.POLICY_INGRESS,
                    ingress_rules=[m.PolicyRule(
                        ports=[m.PolicyPort(protocol="TCP", port=80 + i)],
                        peers=[],
                    )],
                ))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def traffic_worker():
        try:
            barrier.wait()
            frame = make_packet_vector([
                dict(src="10.9.9.9", dst="10.1.1.2", proto=6, sport=1,
                     dport=80, rx_if=agent.uplink_if)
            ])
            for _ in range(N_OPS * 2):
                res = agent.dataplane.process(frame)
                # disposition must always be a legal value — a torn
                # epoch would produce garbage
                assert int(res.disp[0]) in (0, 1, 2, 3)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=cni_worker, args=(t,))
               for t in range(N_THREADS)]
    threads.append(threading.Thread(target=policy_worker))
    threads.append(threading.Thread(target=traffic_worker))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
        assert not t.is_alive(), "worker deadlocked"
    assert not errors, errors

    # invariants after the storm: every surviving container is wired
    # consistently across index, dataplane and IPAM
    survivors = agent.container_index.all()
    assert len(survivors) == len(agent.dataplane.pod_if)
    for cfg in survivors:
        assert agent.dataplane.pod_if[cfg.pod_id] == cfg.if_index
        assert agent.ipam.get_pod_ip(
            f"{cfg.pod_namespace}/{cfg.pod_name}"
        ) is not None
    assert agent.ipam.assigned_count() == len(survivors)
    agent.close()


def test_concurrent_swaps_and_processing_consistent_epochs():
    """Packets processed during continuous table swaps must always see a
    complete epoch: with rule sets {permit-all} and {deny-all} flipping,
    a frame's verdicts must be all-permit or all-deny, never mixed."""

    from vpp_tpu.ir import Action, ContivRule
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig

    dp = Dataplane(DataplaneConfig(sess_slots=256))
    pod = dp.add_pod_interface(("default", "a"))
    dst_pod = dp.add_pod_interface(("default", "b"))
    dp.builder.add_route("10.1.1.3/32", dst_pod, Disposition.LOCAL)
    slot = dp.alloc_table_slot("t")
    dp.builder.set_local_table(slot, [ContivRule(action=Action.PERMIT)])
    dp.assign_pod_table(("default", "a"), "t")
    dp.swap()

    stop = threading.Event()
    errors = []

    def swapper():
        flip = False
        while not stop.is_set():
            rules = [ContivRule(action=Action.DENY if flip else Action.PERMIT)]
            dp.builder.set_local_table(slot, rules)
            dp.swap()
            flip = not flip

    # UDP avoids sessions so each packet takes the ACL path every time
    frame = make_packet_vector([
        dict(src="10.1.1.2", dst="10.1.1.3", proto=17, sport=1000 + i,
             dport=53, rx_if=pod) for i in range(64)
    ])

    t = threading.Thread(target=swapper, daemon=True)
    t.start()
    try:
        for _ in range(40):
            res = dp.process(frame)
            disp = np.asarray(res.disp[:64])
            uniq = set(disp.tolist())
            assert len(uniq) == 1, f"mixed-epoch verdicts: {uniq}"
    finally:
        stop.set()
        t.join(timeout=60)
