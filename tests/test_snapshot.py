"""Crash-consistent session snapshot/restore (ISSUE 8 tentpole).

What must hold:

* a restored table is bit-identical to the snapshotted one (keys,
  payloads, valid flags; timestamps rebased so AGES are preserved);
* incremental drains ship only chunks whose content moved;
* a torn trailing chunk (crash mid-snapshot) leaves the previous
  manifest generation fully restorable — the PR-2 torn-journal
  discipline applied to bulk state;
* mid-chunk CRC corruption refuses the WHOLE restore cleanly (cold
  start), never a half-restored table;
* warm restart end-to-end: traffic → snapshot → kill → restore →
  fastpath hit rate >= 0.9 on the first post-restore batches with
  bit-exact verdicts vs an uninterrupted run, and exact session
  conservation (restored live + expired == snapshotted).
"""

from __future__ import annotations

import glob
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.snapshot import (
    MANIFEST,
    SessionSnapshotter,
    TABLE_COLS,
)
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector
from vpp_tpu.testing import faults


def build_dp(**over):
    base = dict(
        max_tables=2, max_rules=16, max_global_rules=16, max_ifaces=8,
        fib_slots=16, sess_slots=256, sess_ways=4, nat_mappings=2,
        nat_backends=2, sess_sweep_stride=0,
    )
    base.update(over)
    cfg = DataplaneConfig(**base)
    dp = Dataplane(cfg)
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("default", "web"))
    dp.builder.add_route("10.1.1.0/24", pod, Disposition.LOCAL)
    dp.builder.add_route("0.0.0.0/0", up, Disposition.REMOTE, node_id=1)
    dp.builder.set_global_table([
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP),
        ContivRule(action=Action.DENY),
    ])
    dp.swap()
    return dp, up, pod


def forward_pkts(n, base=0, rx_if=1):
    """n distinct TCP flows pod-ward (each establishes a session).
    Flow ``base + i`` is fully determined by its index, so
    ``reply_pkts`` with the same base/n is its exact reverse."""
    return make_packet_vector(
        [{"src": f"10.9.{(base + i) // 200}.{(base + i) % 200 + 1}",
          "dst": "10.1.1.2", "proto": 6,
          "sport": 1000 + (base + i) % 50000,
          "dport": 80, "rx_if": rx_if, "ttl": 64}
         for i in range(n)], n=max(64, n))


def reply_pkts(n, base=0, rx_if=2):
    """The reverse flows of forward_pkts — established return traffic."""
    return make_packet_vector(
        [{"src": "10.1.1.2",
          "dst": f"10.9.{(base + i) // 200}.{(base + i) % 200 + 1}",
          "proto": 6, "sport": 80,
          "dport": 1000 + (base + i) % 50000, "rx_if": rx_if,
          "ttl": 64}
         for i in range(n)], n=max(64, n))


def live_count(dp) -> int:
    return int(jnp.sum(dp.tables.sess_valid))


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    yield
    faults.uninstall()


class TestRoundtrip:
    def test_restore_is_bit_identical_with_rebased_ages(self, tmp_path):
        dp, up, pod = build_dp()
        dp.process(forward_pkts(40, rx_if=up), now=50)
        snap = SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16)
        assert snap.snapshot() == 1
        # the rebase origin is whatever `now` the drain captured (the
        # host clock may have ticked past our explicit test stamps
        # during jit compiles) — read it off the manifest
        with open(os.path.join(str(tmp_path), MANIFEST)) as f:
            snap_now = json.load(f)["now"]

        dp2, _, _ = build_dp()
        snap2 = SessionSnapshotter(dp2, str(tmp_path), chunk_buckets=16)
        assert snap2.restore_into()
        assert snap2.stats_snapshot()["restore_outcome"] == "restored"
        assert live_count(dp2) == live_count(dp) == 40
        for table, fields in TABLE_COLS.items():
            for f in fields:
                a = np.asarray(getattr(dp.tables, f))
                b = np.asarray(getattr(dp2.tables, f))
                if f.endswith("_time"):
                    # rebased: time' = time - snap_now, ages preserved
                    valid = np.asarray(
                        getattr(dp.tables, f.replace("_time", "_valid")))
                    assert np.array_equal(
                        (a.astype(np.int64) - snap_now)[valid == 1],
                        b.astype(np.int64)[valid == 1]), f
                else:
                    assert np.array_equal(a, b), f
        # sweep cursors ride the manifest scalars
        assert int(np.asarray(dp2.tables.sess_sweep_cursor)) == int(
            np.asarray(dp.tables.sess_sweep_cursor))

    def test_age_semantics_survive_the_restart(self, tmp_path):
        """An entry idle for (max_age - 100) ticks at snapshot must
        expire ~100 ticks into the new process, not get a fresh
        lease on life."""
        dp, up, pod = build_dp()
        dp.process(forward_pkts(8, rx_if=up), now=10)
        old_now = 10 + dp.config.sess_max_age - 100
        snap = SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16)
        dp._now = old_now  # age the entries without wall-clock sleeps
        assert snap.snapshot() == 1

        dp2, up2, _ = build_dp()
        snap2 = SessionSnapshotter(dp2, str(tmp_path), chunk_buckets=16)
        assert snap2.restore_into()
        # at restore the flows are still within max_age: replies hit
        # (and the hits REFRESH sess_time to now=50 — keepalive)
        r = dp2.process(reply_pkts(8), now=50)
        assert int(r.stats.sess_hits) == 8
        # ...then max_age of idle later they are gone — the restart
        # never granted a fresh lease, aging semantics carried over
        r2 = dp2.process(reply_pkts(8), now=50 + 3000 + 100)
        assert int(r2.stats.sess_hits) == 0

    def test_restore_refuses_geometry_mismatch(self, tmp_path):
        dp, up, _ = build_dp()
        dp.process(forward_pkts(4, rx_if=up), now=5)
        SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16).snapshot()
        dp2, _, _ = build_dp(sess_slots=512)
        snap2 = SessionSnapshotter(dp2, str(tmp_path), chunk_buckets=16)
        assert not snap2.restore_into()
        s = snap2.stats_snapshot()
        assert s["restore_outcome"] == "geometry"
        assert s["restores"]["geometry"] == 1
        assert live_count(dp2) == 0  # clean cold start


class TestIncremental:
    def test_clean_chunks_never_reship(self, tmp_path):
        dp, up, _ = build_dp()
        dp.process(forward_pkts(30, rx_if=up), now=5)
        snap = SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16)
        snap.snapshot()
        first = snap.stats_snapshot()["chunks_written"]
        assert first > 0
        snap.snapshot()  # nothing changed in between
        s = snap.stats_snapshot()
        assert s["chunks_written"] == first
        assert s["chunks_skipped"] == first

    def test_one_dirty_bucket_drains_one_chunk(self, tmp_path):
        dp, up, _ = build_dp()
        dp.process(forward_pkts(30, rx_if=up), now=5)
        snap = SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16)
        snap.snapshot()
        before = snap.stats_snapshot()["chunks_written"]
        # one new flow dirties exactly one bucket → one sess chunk
        dp.process(forward_pkts(1, base=7000, rx_if=up), now=6)
        snap.snapshot()
        assert snap.stats_snapshot()["chunks_written"] == before + 1

    def test_incremental_survives_process_restart(self, tmp_path):
        """A fresh snapshotter (new process) loads the manifest at
        ctor: the first snapshot after a restart is incremental too
        (content digests are state-free)."""
        dp, up, _ = build_dp()
        dp.process(forward_pkts(30, rx_if=up), now=5)
        SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16).snapshot()

        snap2 = SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16)
        assert snap2.stats_snapshot()["generation"] == 1
        assert snap2.snapshot() == 2
        s = snap2.stats_snapshot()
        assert s["chunks_written"] == 0
        assert s["chunks_skipped"] > 0

    def test_gc_drops_superseded_chunk_files(self, tmp_path):
        dp, up, _ = build_dp()
        dp.process(forward_pkts(30, rx_if=up), now=5)
        snap = SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16)
        snap.snapshot()
        dp.process(forward_pkts(30, base=5000, rx_if=up), now=6)
        snap.snapshot()
        with open(os.path.join(str(tmp_path), MANIFEST)) as f:
            m = json.load(f)
        live = {e["file"] for t in m["tables"].values()
                for e in t["chunks"]}
        on_disk = {os.path.basename(p) for p in
                   glob.glob(os.path.join(str(tmp_path), "*.chunk"))}
        assert on_disk == live


class TestTornSnapshots:
    """The PR-2 torn-journal regression discipline, for bulk state."""

    def test_torn_trailing_chunk_restores_previous_generation(
            self, tmp_path):
        dp, up, _ = build_dp()
        dp.process(forward_pkts(30, rx_if=up), now=5)
        snap = SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16)
        assert snap.snapshot() == 1
        baseline = np.asarray(dp.tables.sess_src).copy()

        # generation 2 tears on its 2nd chunk write (crash mid-file):
        # the manifest still points at generation 1, torn file is
        # unreferenced
        dp.process(forward_pkts(30, base=5000, rx_if=up), now=6)
        faults.install(faults.FaultPlan(seed=1)).inject(
            "snapshot.chunk", after=1, times=1)
        assert snap.snapshot() is None
        faults.uninstall()
        assert snap.degraded
        s = snap.stats_snapshot()
        assert s["generation"] == 1
        assert s["consecutive_failures"] == 1

        dp2, _, _ = build_dp()
        snap2 = SessionSnapshotter(dp2, str(tmp_path), chunk_buckets=16)
        assert snap2.restore_into()
        assert live_count(dp2) == 30  # generation 1's content
        assert np.array_equal(np.asarray(dp2.tables.sess_src), baseline)

        # ...and the NEXT snapshot heals: publishes gen 2 cleanly and
        # clears the degraded flag
        assert snap.snapshot() == 2
        assert not snap.degraded

    def test_torn_manifest_publish_keeps_previous_generation(
            self, tmp_path):
        dp, up, _ = build_dp()
        dp.process(forward_pkts(20, rx_if=up), now=5)
        snap = SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16)
        assert snap.snapshot() == 1
        dp.process(forward_pkts(20, base=4000, rx_if=up), now=6)
        faults.install(faults.FaultPlan(seed=2)).inject(
            "snapshot.manifest")
        assert snap.snapshot() is None
        faults.uninstall()
        dp2, _, _ = build_dp()
        snap2 = SessionSnapshotter(dp2, str(tmp_path), chunk_buckets=16)
        assert snap2.restore_into()
        assert live_count(dp2) == 20

    def test_crc_corruption_refuses_cleanly_cold_start(self, tmp_path):
        dp, up, _ = build_dp()
        dp.process(forward_pkts(30, rx_if=up), now=5)
        SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16).snapshot()
        # flip payload bytes mid-file in a REFERENCED chunk (bit rot)
        with open(os.path.join(str(tmp_path), MANIFEST)) as f:
            m = json.load(f)
        victim = m["tables"]["sess"]["chunks"][1]["file"]
        path = os.path.join(str(tmp_path), victim)
        with open(path, "r+b") as f:
            f.seek(200)
            f.write(b"\xff\xff\xff\xff")
        dp2, _, _ = build_dp()
        snap2 = SessionSnapshotter(dp2, str(tmp_path), chunk_buckets=16)
        assert not snap2.restore_into()
        s = snap2.stats_snapshot()
        assert s["restore_outcome"] == "crc_mismatch"
        # NEVER half-restored: the whole table is cold, not just the
        # corrupt chunk's buckets
        assert live_count(dp2) == 0

    def test_garbage_manifest_refuses_cleanly(self, tmp_path):
        dp, up, _ = build_dp()
        dp.process(forward_pkts(5, rx_if=up), now=5)
        SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16).snapshot()
        with open(os.path.join(str(tmp_path), MANIFEST), "w") as f:
            f.write('{"version": 1, "genera')  # torn JSON
        dp2, _, _ = build_dp()
        snap2 = SessionSnapshotter(dp2, str(tmp_path), chunk_buckets=16)
        assert not snap2.restore_into()
        assert snap2.stats_snapshot()["restore_outcome"] == "bad_manifest"
        assert live_count(dp2) == 0

    def test_missing_chunk_refuses_cleanly(self, tmp_path):
        dp, up, _ = build_dp()
        dp.process(forward_pkts(5, rx_if=up), now=5)
        SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16).snapshot()
        with open(os.path.join(str(tmp_path), MANIFEST)) as f:
            m = json.load(f)
        os.unlink(os.path.join(
            str(tmp_path), m["tables"]["sess"]["chunks"][0]["file"]))
        dp2, _, _ = build_dp()
        snap2 = SessionSnapshotter(dp2, str(tmp_path), chunk_buckets=16)
        assert not snap2.restore_into()
        assert snap2.stats_snapshot()["restore_outcome"] == "missing_chunk"


class TestWarmRestartE2E:
    def test_fastpath_survives_restart_bit_exact(self, tmp_path):
        """Run traffic, snapshot, 'kill' the process (fresh dataplane),
        restore, and prove the first post-restore batches (a) ride the
        classify-free fast path at hit rate >= 0.9 and (b) produce
        BIT-EXACT packed verdicts vs the uninterrupted dataplane."""
        n = 60
        # 2048 slots (512 buckets): 72 distinct flows never fill a
        # 4-way bucket, so the ledger below is free of victim noise
        dp, up, pod = build_dp(sess_slots=2048)
        # establish n flows at tick 1000; also plant 12 flows at tick 2
        # so at snap_now=3500 their age (3498) is past max_age (3000)
        # while the fresh set (age 2500) is alive — the conservation
        # ledger below then has a nonzero expired side
        dp.process(forward_pkts(n, rx_if=up), now=1000)
        dp.process(forward_pkts(12, base=9000, rx_if=up), now=2)
        snap_now = 3500
        dp._now = snap_now
        snap = SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16)
        assert snap.snapshot() == 1
        snapshotted = live_count(dp)
        assert snapshotted == n + 12

        # the restarted process: fresh dataplane, restore warm
        dp2, up2, pod2 = build_dp(sess_slots=2048)
        snap2 = SessionSnapshotter(dp2, str(tmp_path), chunk_buckets=16)
        assert snap2.restore_into()

        # session conservation EXACT: restored live + expired ==
        # snapshotted (the aged flows come back flagged, then reclaim)
        restored_flagged = live_count(dp2)
        expired = dp2.expire_sessions()
        assert restored_flagged == snapshotted
        assert live_count(dp2) + expired == snapshotted
        assert expired == 12

        # first post-restore batches: established return traffic.
        # Uninterrupted (dp) and restored (dp2) must agree bit-exactly;
        # dp's clock kept running, dp2's restarted at 0 — same ages by
        # the rebase, so the same `relative` now means the same state.
        for batch, base in ((0, 0), (1, 20), (2, 40)):
            pv = reply_pkts(20, base=base)
            ref = dp.process(pv, now=snap_now + 1 + batch)
            got = dp2.process(pv, now=1 + batch)
            hits = int(got.stats.sess_hits)
            rx = int(got.stats.rx)
            assert rx == 20
            assert hits / rx >= 0.9, f"post-restore hit rate {hits}/{rx}"
            assert int(got.stats.fastpath) == 1
            for f in ("disp", "tx_if", "next_hop", "drop_cause"):
                assert np.array_equal(
                    np.asarray(getattr(ref, f)),
                    np.asarray(getattr(got, f))), f
            for f in pv._fields:
                assert np.array_equal(
                    np.asarray(getattr(ref.pkts, f)),
                    np.asarray(getattr(got.pkts, f))), f

    def test_cold_start_without_snapshot_misses_fastpath(self, tmp_path):
        """The control: without the restore the same replies MISS the
        session table and fall down the full chain — i.e. the warm
        restart is what preserves the hit rate, not the traffic
        shape."""
        dp, up, pod = build_dp()
        dp.process(forward_pkts(20, rx_if=up), now=5)
        dp2, _, _ = build_dp()
        r = dp2.process(reply_pkts(20), now=6)
        assert int(r.stats.sess_hits) == 0
        assert int(r.stats.fastpath) == 0


class TestPersistentRingSync:
    def test_sync_sessions_freshens_tables_for_snapshot(self, tmp_path):
        """A persistent-mode pump threads session state privately
        through the resident ring — dp.tables stays at launch state.
        sync_sessions() must graft a consistent copy back so an
        interval snapshot captures the LIVE sessions (the ISSUE 8
        review gap: without it, ring-mode snapshots were stale by the
        whole ring uptime)."""
        import time as _time

        from wire import make_frame

        from vpp_tpu.io import DataplanePump, IORingPair
        from vpp_tpu.native.pktio import PacketCodec
        from vpp_tpu.pipeline.vector import VEC

        # default geometry so the window program comes from the same
        # process-wide jit cache the other persistent suites warmed
        dp = Dataplane(DataplaneConfig())
        a = dp.add_pod_interface(("default", "a"))
        b = dp.add_pod_interface(("default", "b"))
        dp.builder.add_route("10.1.1.2/32", a, Disposition.LOCAL)
        dp.builder.add_route("10.1.1.3/32", b, Disposition.LOCAL)
        dp.swap()
        rings = IORingPair(n_slots=32)
        pump = DataplanePump(dp, rings, mode="persistent").start()
        try:
            codec = PacketCodec()
            scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
            frames = [make_frame("10.1.1.2", "10.1.1.3", proto=17,
                                 sport=30000 + j, dport=40000 + j)
                      for j in range(8)]
            cols, nn = codec.parse(frames, a, scratch)
            assert rings.rx.push(cols, nn, payload=scratch)
            deadline = _time.monotonic() + 180.0
            while pump.stats["pkts"] < 8:
                assert _time.monotonic() < deadline, dict(pump.stats)
                _time.sleep(0.02)
            # the ring holds the 8 sessions privately; the published
            # tables are still the launch state
            assert live_count(dp) == 0
            assert pump.sync_sessions()
            assert live_count(dp) == 8
            snap = SessionSnapshotter(dp, str(tmp_path),
                                      chunk_buckets=64)
            assert snap.snapshot() == 1
        finally:
            assert pump.stop(join_timeout=60.0)
            rings.close()
        dp2 = Dataplane(DataplaneConfig())
        snap2 = SessionSnapshotter(dp2, str(tmp_path), chunk_buckets=64)
        assert snap2.restore_into()
        assert live_count(dp2) == 8


class TestObservabilityWiring:
    def test_collector_exports_resilience_families(self, tmp_path):
        from vpp_tpu.stats.collector import StatsCollector

        dp, up, _ = build_dp()
        dp.process(forward_pkts(8, rx_if=up), now=5)
        snap = SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16)
        snap.snapshot()
        snap.restore()  # outcome: restored
        coll = StatsCollector(dp)
        coll.set_snapshotter(snap)
        coll.publish()
        lines = []
        for _path, fam in coll.registry.families():
            lines.extend(fam.render())
        text = "\n".join(lines)
        assert 'vpp_tpu_degraded{component="snapshot"} 0' in text
        assert 'vpp_tpu_degraded{component="kvstore"} 0' in text
        assert 'vpp_tpu_degraded{component="ring"} 0' in text
        assert "vpp_tpu_snapshot_age_seconds" in text
        assert "vpp_tpu_snapshot_chunk_seconds" in text
        assert 'vpp_tpu_snapshot_restore_total{outcome="restored"} 1' \
            in text
        assert "vpp_tpu_snapshot_generation 1" in text
        assert "vpp_tpu_kvstore_staleness_seconds 0" in text

    def test_show_resilience_page(self, tmp_path):
        from vpp_tpu.cli import DebugCLI

        dp, up, _ = build_dp()
        dp.process(forward_pkts(8, rx_if=up), now=5)
        snap = SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16)
        snap.snapshot()
        cli = DebugCLI(dp, snapshotter=snap)
        out = cli.run("show resilience")
        assert "degraded: none" in out
        assert "generation 1" in out
        assert "chunks" in out
        # degraded snapshot shows up
        faults.install(faults.FaultPlan(seed=3)).inject("snapshot.chunk")
        dp.process(forward_pkts(1, base=8000, rx_if=up), now=9)
        snap.snapshot()
        faults.uninstall()
        out = cli.run("show resilience")
        assert "snapshot (last attempt failed)" in out

    def test_show_resilience_without_snapshotter(self):
        from vpp_tpu.cli import DebugCLI

        dp, _, _ = build_dp()
        out = DebugCLI(dp).run("show resilience")
        assert "snapshot: not configured" in out


class TestAgentWiring:
    def test_agent_snapshots_and_restores_across_restart(self, tmp_path):
        from vpp_tpu.cmd.agent import ContivAgent
        from vpp_tpu.cmd.config import AgentConfig
        from vpp_tpu.kvstore.store import KVStore
        from vpp_tpu.pipeline.tables import DataplaneConfig as DC

        def make_cfg():
            return AgentConfig(
                node_name="n1", serve_http=False,
                snapshot_path=str(tmp_path / "snaps"),
                snapshot_chunk_buckets=16,
                dataplane=DC(sess_slots=256, sess_sweep_stride=0),
            )

        store = KVStore()
        agent = ContivAgent(make_cfg(), store=store)
        agent.start()
        up = agent.uplink_if
        # a routable destination outside the pod-subnet drop routes
        # (empty global table permits; LOCAL route forwards → the
        # step installs reflective sessions)
        agent.dataplane.builder.add_route(
            "10.200.1.0/24", up, Disposition.LOCAL)
        agent.dataplane.swap()
        pv = make_packet_vector(
            [{"src": f"172.16.0.{i + 1}", "dst": f"10.200.1.{i + 1}",
              "proto": 6, "sport": 2000 + i, "dport": 443,
              "rx_if": up, "ttl": 64} for i in range(16)], n=64)
        agent.dataplane.process(pv, now=5)
        assert live_count(agent.dataplane) == 16
        agent.maintenance_tick()  # first interval-paced snapshot
        assert agent.snapshotter.stats_snapshot()["generation"] >= 1
        agent.close()  # parting snapshot

        agent2 = ContivAgent(make_cfg(), store=KVStore())
        agent2.start()
        assert live_count(agent2.dataplane) == 16
        s = agent2.snapshotter.stats_snapshot()
        assert s["restore_outcome"] == "restored"
        agent2.close()
