"""Differential suite for the Pallas kernel pass (ISSUE 16).

Holds each fused kernel (run in *interpret* mode so the suite is
tier-1 on the CPU harness) bit-exact against its jnp reference rung
and, where one exists, an independent NumPy oracle:

- ops/acl_bv.py  bv_first_set / bv_first_match_fused  vs  the
  _first_set_bit priority encode and a per-row Python bit-scan oracle;
- ops/session.py sess_probe_ways  vs  _probe_ways_reference with
  planted hits, expired entries and the no-age-check convention;
- ops/lpm.py     _fib_lookup_lpm_pallas  vs  fib_lookup_lpm and the
  NumPy LPM oracle (reused from tests/test_lpm.py) over staged tables;
- the CPU dispatch identities (the pallas-rung entry points ARE the
  jnp rungs off-TPU), the three selection ladders' pallas_ok bit, the
  config-time mesh rejection, the step-factory bit-exactness of a
  fully pallas-knobbed step, tuned-profile loading (tools/autotune.py
  consumer side), the VMEM fit gate and the PALLAS_KERNELS manifest
  lint (tools/analysis/registries.py, run from tier-1 here like the
  other registry passes).
"""

import json
import os
import sys
from pathlib import Path
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vpp_tpu.ops._pallas import pallas_available, use_pallas
from vpp_tpu.ops.acl_bv import (
    BV_ENC_MISS,
    _first_set_bit,
    acl_classify_global_bv,
    acl_classify_global_pallas,
    acl_classify_local_bv,
    acl_classify_local_pallas,
    bv_first_match,
    bv_first_match_fused,
    bv_first_set,
)
from vpp_tpu.ops.lpm import (
    _fib_lookup_lpm_pallas,
    fib_lookup_lpm,
    fib_lookup_lpm_fused,
)
from vpp_tpu.ops.session import (
    _BIG,
    SESS_PALLAS_VMEM_BUDGET,
    _probe_ways_reference,
    sess_probe_ways,
    session_pallas_fits,
)
from vpp_tpu.parallel.partition import (
    select_fib_impl,
    select_impl,
    select_session_impl,
    validate_partitioning,
)
from vpp_tpu.pipeline.graph import make_pipeline_step
from vpp_tpu.pipeline.tables import (
    DataplaneConfig,
    InterfaceType,
    TableBuilder,
)

from test_acl_bv import _cfg as _acl_cfg
from test_acl_bv import random_packets, random_rules
from test_lpm import (
    NumpyLpmOracle,
    _cfg as _lpm_cfg,
    _probe_traffic,
    _random_table,
    assert_fib_equal,
)

REPO = Path(__file__).resolve().parents[1]
TOOLS = REPO / "tools"

if not pallas_available():  # the image bakes in jax with pallas
    pytest.skip("jax.experimental.pallas unavailable",
                allow_module_level=True)


# --- bv_first_set: fused word-AND + first-set-bit ---------------------


def _np_first_rule(words: np.ndarray) -> np.ndarray:
    """Independent per-row bit-scan oracle: lowest set bit across the
    word vector, -1 when none (pure Python ints, no jnp tricks)."""
    p, w = words.shape
    out = np.full(p, -1, np.int64)
    for i in range(p):
        for j in range(w):
            v = int(words[i, j])
            if v:
                out[i] = j * 32 + ((v & -v).bit_length() - 1)
                break
    return out


@pytest.mark.parametrize("p,w,seed", [(1, 1, 0), (5, 3, 1), (300, 20, 2)])
def test_bv_first_set_matches_reference_and_oracle(p, w, seed):
    rng = np.random.default_rng(seed)
    rows = [rng.integers(0, 1 << 32, (p, w), dtype=np.uint32)
            for _ in range(5)]
    # sparsify so misses and single-bit survivors both occur; zeroing
    # one operand's row forces a guaranteed miss every third packet
    for r in rows[1:]:
        r &= rng.integers(0, 1 << 32, (p, w), dtype=np.uint32)
    for i in range(0, p, 3):
        rows[0][i] = 0
    jrows = [jnp.asarray(r) for r in rows]
    enc = np.asarray(bv_first_set(*jrows, interpret=True))

    combined = rows[0] & rows[1] & rows[2] & rows[3] & rows[4]
    matched, rule = _first_set_bit(jnp.asarray(combined))
    np.testing.assert_array_equal(enc != BV_ENC_MISS, np.asarray(matched))
    np.testing.assert_array_equal(
        np.where(enc != BV_ENC_MISS, enc, -1), np.asarray(rule))
    np.testing.assert_array_equal(
        np.where(enc != BV_ENC_MISS, enc, -1), _np_first_rule(combined))


@pytest.mark.parametrize("nrules", [1, 24])
def test_bv_first_match_fused_on_staged_tables(nrules):
    """Interpret-mode fused first-match over builder-committed BV
    planes agrees with bv_first_match on every packet (odd packet
    count exercises the tile padding)."""
    rng = np.random.default_rng(nrules)
    rules = random_rules(rng, nrules)
    b = TableBuilder(_acl_cfg())
    b.set_interface(1, InterfaceType.UPLINK, apply_global=True)
    b.set_global_table(rules)
    t = b.to_device()
    pkts = random_packets(rng, 257, rules)
    args = (t.glb_bv_bnd_src, t.glb_bv_bnd_dst, t.glb_bv_bnd_sport,
            t.glb_bv_bnd_dport, t.glb_bv_nbnd, t.glb_bv_src,
            t.glb_bv_dst, t.glb_bv_sport, t.glb_bv_dport,
            t.glb_bv_proto, pkts)
    m_ref, r_ref = bv_first_match(*args)
    m_fus, r_fus = bv_first_match_fused(*args, interpret=True)
    np.testing.assert_array_equal(np.asarray(m_fus), np.asarray(m_ref))
    np.testing.assert_array_equal(np.asarray(r_fus), np.asarray(r_ref))


def test_classify_pallas_is_bv_off_tpu():
    """The dispatch identity the safety net promises: on a non-TPU
    backend the pallas classify entry points ARE the bv rung —
    verdicts and rule indices identical, global and local."""
    assert not use_pallas()  # tier-1 runs on the CPU harness
    rng = np.random.default_rng(5)
    rules = random_rules(rng, 20)
    from test_acl_bv import _tables

    _, t = _tables(rules, rng=rng, n_local=2)
    pkts = random_packets(rng, 128, rules, max_if=4)
    for pal, ref in ((acl_classify_global_pallas, acl_classify_global_bv),
                     (acl_classify_local_pallas, acl_classify_local_bv)):
        vp, vr = pal(t, pkts), ref(t, pkts)
        np.testing.assert_array_equal(np.asarray(vp.permit),
                                      np.asarray(vr.permit))
        np.testing.assert_array_equal(np.asarray(vp.rule_idx),
                                      np.asarray(vr.rule_idx))


# --- sess_probe_ways: fused bucket probe + way election ---------------


def _sess_case(ways, seed, p=200, nb=32, plant=True, all_invalid=False):
    rng = np.random.default_rng(seed)
    valid = (rng.random((nb, ways)) < 0.5).astype(np.int32)
    src = rng.integers(0, 1 << 32, (nb, ways), dtype=np.uint32)
    dst = rng.integers(0, 1 << 32, (nb, ways), dtype=np.uint32)
    ports = rng.integers(0, 1 << 32, (nb, ways), dtype=np.uint32)
    proto = rng.integers(0, 256, (nb, ways)).astype(np.uint32)
    time = rng.integers(0, 1000, (nb, ways)).astype(np.int32)
    b = rng.integers(0, nb, p).astype(np.int32)
    key = [rng.integers(0, 1 << 32, p, dtype=np.uint32) for _ in range(3)]
    key.append(rng.integers(0, 256, p).astype(np.uint32))
    if plant:
        # guaranteed hits (some later overwritten by other plants on a
        # shared bucket — harmless, both sides see the final table) and
        # guaranteed-expired entries every 8th packet
        for i in range(0, p, 4):
            w = int(rng.integers(0, ways))
            bb = int(b[i])
            valid[bb, w] = 1
            src[bb, w], dst[bb, w] = key[0][i], key[1][i]
            ports[bb, w], proto[bb, w] = key[2][i], key[3][i]
            time[bb, w] = 100 if i % 8 == 0 else 950
    if all_invalid:
        valid[:] = 0
    return (jnp.asarray(b), *(jnp.asarray(k) for k in key),
            jnp.asarray(valid), jnp.asarray(src), jnp.asarray(dst),
            jnp.asarray(ports), jnp.asarray(proto), jnp.asarray(time))


@pytest.mark.parametrize("ways", [1, 2, 4])
def test_sess_probe_matches_reference(ways):
    """Planted hits, planted expired entries (now - time > max_age)
    and random misses across way counts: interpret-mode kernel ==
    gather-rung reference on both outputs."""
    args = _sess_case(ways, seed=17 + ways)
    now, max_age = 1000, 200  # time=100 plants are expired, 950 live
    f_k, w_k = sess_probe_ways(*args, now, max_age, interpret=True)
    f_r, w_r = _probe_ways_reference(*args, now, max_age)
    assert bool(np.asarray(f_k).any())  # plants actually landed
    np.testing.assert_array_equal(np.asarray(f_k), np.asarray(f_r))
    np.testing.assert_array_equal(np.asarray(w_k), np.asarray(w_r))


def test_sess_probe_all_miss_and_no_age_check():
    """All-invalid table: found all-False, way all-0 (the argmax
    convention). The callers' now=0/max_age=_BIG "no age check"
    convention is vacuous for non-negative time ticks."""
    args = _sess_case(4, seed=3, p=33, all_invalid=True)
    f_k, w_k = sess_probe_ways(*args, 1000, 200, interpret=True)
    assert not np.asarray(f_k).any()
    np.testing.assert_array_equal(np.asarray(w_k), 0)

    args = _sess_case(2, seed=9, p=65)
    f_k, w_k = sess_probe_ways(*args, 0, _BIG, interpret=True)
    f_r, w_r = _probe_ways_reference(*args, 0, _BIG)
    np.testing.assert_array_equal(np.asarray(f_k), np.asarray(f_r))
    np.testing.assert_array_equal(np.asarray(w_k), np.asarray(w_r))


def test_session_pallas_fits_budget():
    assert session_pallas_fits(SimpleNamespace(sess_slots=1 << 12))
    limit = SESS_PALLAS_VMEM_BUDGET // 24
    assert session_pallas_fits(SimpleNamespace(sess_slots=limit))
    assert not session_pallas_fits(SimpleNamespace(sess_slots=limit + 1))
    assert not session_pallas_fits(SimpleNamespace(sess_slots=0))
    assert not session_pallas_fits(SimpleNamespace())


# --- LPM: fused per-length binary search ------------------------------


@pytest.mark.parametrize("seed,n_routes,fib_slots",
                         [(3, 40, 64), (7, 200, 256)])
def test_lpm_pallas_matches_oracle(seed, n_routes, fib_slots):
    """Seeded random tables with ECMP groups: the interpret-mode fused
    lookup, the unrolled LPM walk and the NumPy oracle agree on every
    FibResult field (odd packet count exercises the tile padding)."""
    b = _random_table(seed, n_routes, fib_slots, ecmp_groups=4)
    t = b.to_device()
    rng = np.random.default_rng(seed + 2)
    pkts = _probe_traffic(b, rng, 257)
    oracle = NumpyLpmOracle(b).lookup(pkts)
    assert_fib_equal(_fib_lookup_lpm_pallas(t, pkts, interpret=True),
                     oracle)
    assert_fib_equal(fib_lookup_lpm(t, pkts), oracle)


def test_lpm_pallas_edge_tables():
    """Empty table (all-miss), /0-only (all-hit), /32 host routes and
    a duplicate prefix (lowest slot wins the tie): fused == unrolled
    == oracle through the same resolver."""
    from vpp_tpu.pipeline.vector import Disposition

    rng = np.random.default_rng(21)

    def check(b, pkts):
        oracle = NumpyLpmOracle(b).lookup(pkts)
        t = b.to_device()
        assert_fib_equal(
            _fib_lookup_lpm_pallas(t, pkts, interpret=True), oracle)
        assert_fib_equal(fib_lookup_lpm(t, pkts), oracle)

    empty = TableBuilder(_lpm_cfg(fib_slots=16, fib_impl="lpm"))
    empty.add_route("10.0.0.0/8", 1, Disposition.REMOTE, slot=0)
    pkts = _probe_traffic(empty, rng, 65)
    empty.del_route("10.0.0.0/8")
    check(empty, pkts)

    b = TableBuilder(_lpm_cfg(fib_slots=16, fib_impl="lpm"))
    b.add_route("0.0.0.0/0", 1, Disposition.REMOTE, next_hop=9)
    check(b, _probe_traffic(b, rng, 33))

    b = TableBuilder(_lpm_cfg(fib_slots=16, fib_impl="lpm"))
    b.add_route("10.1.1.7/32", 2, Disposition.LOCAL, slot=3)
    b.add_route("10.1.1.8/32", 3, Disposition.LOCAL, slot=1)
    b.add_route("10.1.1.0/24", 4, Disposition.REMOTE, slot=0)
    check(b, _probe_traffic(b, rng, 64))


def test_fib_fused_dispatch_is_lpm_off_tpu():
    assert not use_pallas()
    b = _random_table(13, 60, 64, ecmp_groups=2)
    t = b.to_device()
    pkts = _probe_traffic(b, np.random.default_rng(14), 128)
    r_f = fib_lookup_lpm_fused(t, pkts)
    r_l = fib_lookup_lpm(t, pkts)
    for a, c in zip(jax.tree_util.tree_leaves(r_f),
                    jax.tree_util.tree_leaves(r_l)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


# --- selection ladders and the mesh rejection -------------------------


def test_classifier_ladder_pallas_rung():
    kw = dict(nrules=100, bv_min_rules=8, mxu_threshold=16)
    sel = lambda knob, bv, mxu, pok: select_impl(  # noqa: E731
        knob, bv, mxu, pallas_ok=pok, **kw)
    assert sel("pallas", True, True, True) == "pallas"
    assert sel("pallas", True, True, False) == "bv"   # backend gate
    assert sel("pallas", False, True, True) == "mxu"  # structure gate
    assert sel("pallas", False, False, True) == "dense"
    assert sel("auto", True, True, True) == "pallas"
    assert sel("auto", True, True, False) == "bv"
    assert sel("bv", True, True, True) == "bv"        # explicit knob
    assert select_impl("auto", True, True, 4, 8, 2,
                       pallas_ok=True) == "mxu"       # under bv_min


def test_fib_ladder_pallas_rung():
    assert select_fib_impl("pallas", True, 10, 100, True) == "pallas"
    assert select_fib_impl("pallas", True, 10, 100, False) == "lpm"
    assert select_fib_impl("pallas", False, 10, 100, True) == "dense"
    assert select_fib_impl("auto", True, 200, 100, True) == "pallas"
    assert select_fib_impl("auto", True, 200, 100, False) == "lpm"
    assert select_fib_impl("auto", True, 50, 100, True) == "dense"
    assert select_fib_impl("lpm", True, 10, 100, True) == "lpm"


def test_session_ladder_pallas_rung():
    assert select_session_impl("gather", True) == "gather"
    assert select_session_impl("pallas", True) == "pallas"
    assert select_session_impl("pallas", False) == "gather"
    assert select_session_impl("auto", True) == "pallas"
    assert select_session_impl("auto", False) == "gather"


def _mesh_cfg(**kw):
    base = dict(max_tables=2, max_rules=8, max_global_rules=8,
                max_ifaces=8, fib_slots=16, sess_slots=64,
                nat_mappings=2, nat_backends=4)
    base.update(kw)
    return DataplaneConfig(**base)


@pytest.mark.parametrize("knob", ["classifier", "fib_impl",
                                  "session_impl"])
def test_mesh_rejects_explicit_pallas_knob(knob):
    """An explicit pallas knob on a sharded mesh fails at CONFIG time
    with a message naming PARTITION_RULES (never inside a pallas_call
    trace); rule_shards=1 and auto stay legal."""
    cfg = _mesh_cfg(**{knob: "pallas"})
    with pytest.raises(ValueError, match="PARTITION_RULES"):
        validate_partitioning(cfg, rule_shards=2)
    validate_partitioning(cfg, rule_shards=1)
    validate_partitioning(_mesh_cfg(), rule_shards=2)


def test_config_rejects_unknown_session_impl():
    from vpp_tpu.pipeline.tables import validate_dataplane_config

    with pytest.raises(ValueError, match="session_impl"):
        validate_dataplane_config(_mesh_cfg(session_impl="bogus"))
    for knob in ("gather", "pallas", "auto"):
        validate_dataplane_config(_mesh_cfg(session_impl=knob))


# --- step-level bit-exactness of a fully pallas-knobbed step ----------


def test_pallas_step_bitexact_vs_reference_step():
    """A step composed entirely of pallas rungs equals the bv/lpm/
    gather step leaf-for-leaf on the CPU harness (the dispatch safety
    net at full-pipeline scope: classify verdicts, FIB resolution,
    session state and counters all identical)."""
    from vpp_tpu.pipeline.vector import Disposition

    rng = np.random.default_rng(31)
    b = TableBuilder(_lpm_cfg(fib_slots=64, fib_impl="lpm",
                              classifier="bv"))
    b.set_interface(0, InterfaceType.UPLINK, apply_global=True)
    b.set_global_table(random_rules(rng, 6))
    b.add_route("0.0.0.0/0", 1, Disposition.REMOTE, next_hop=7)
    b.add_route("10.0.0.0/8", 2, Disposition.REMOTE)
    b.add_route("10.1.1.0/24", 3, Disposition.LOCAL)
    t = b.to_device()
    pkts = _probe_traffic(b, rng, 128)
    now = jnp.asarray(7, jnp.int32)

    step_ref = make_pipeline_step("bv", fib_impl="lpm",
                                  sess_impl="gather")
    step_pal = make_pipeline_step("pallas", fib_impl="pallas",
                                  sess_impl="pallas")
    r_ref = step_ref(t, pkts, now)
    r_pal = step_pal(t, pkts, now)
    for a, c in zip(jax.tree_util.tree_leaves(r_pal),
                    jax.tree_util.tree_leaves(r_ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


# --- tuned profiles (tools/autotune.py consumer side) -----------------


def _write_profile(tmp_path, **kw):
    prof = dict(backend="cpu", floor_us=50.0,
                knobs={"dataplane": {"sess_ways": 8},
                       "io": {"io_ring_slots": 16},
                       "env": {"VPPT_TEST_TUNED_KNOB": "4096"}})
    prof.update(kw)
    p = tmp_path / "prof.json"
    p.write_text(json.dumps(prof))
    return str(p)


@pytest.fixture
def _clean_env():
    saved = os.environ.pop("VPPT_TEST_TUNED_KNOB", None)
    yield
    if saved is None:
        os.environ.pop("VPPT_TEST_TUNED_KNOB", None)
    else:
        os.environ["VPPT_TEST_TUNED_KNOB"] = saved


def test_tuned_profile_knobs_are_defaults(tmp_path, _clean_env):
    """Profile knobs land as per-key DEFAULTS: explicit config wins,
    env knobs apply via setdefault, the floor clamps a sub-floor SLO
    up (and leaves 0 = disabled alone)."""
    from vpp_tpu.cmd.config import AgentConfig

    path = _write_profile(tmp_path)
    cfg = AgentConfig.from_dict({"tuned_profile": path})
    assert cfg.dataplane.sess_ways == 8
    assert cfg.io.io_ring_slots == 16
    assert os.environ["VPPT_TEST_TUNED_KNOB"] == "4096"

    cfg = AgentConfig.from_dict({
        "tuned_profile": path,
        "dataplane": {"sess_ways": 2},
        "io": {"io_ring_slots": 8, "latency_slo_us": 1},
    })
    assert cfg.dataplane.sess_ways == 2   # explicit config wins
    assert cfg.io.io_ring_slots == 8
    assert cfg.io.latency_slo_us == 50    # clamped up to the floor

    cfg = AgentConfig.from_dict({
        "tuned_profile": path,
        "io": {"latency_slo_us": 900},
    })
    assert cfg.io.latency_slo_us == 900   # above floor: untouched
    cfg = AgentConfig.from_dict({"tuned_profile": path})
    assert cfg.io.latency_slo_us == 0     # 0 = disabled stays disabled

    # exported environment beats the profile's env defaults
    os.environ["VPPT_TEST_TUNED_KNOB"] = "111"
    AgentConfig.from_dict({"tuned_profile": path})
    assert os.environ["VPPT_TEST_TUNED_KNOB"] == "111"


def test_tuned_profile_refuses_malformed(tmp_path):
    from vpp_tpu.cmd.config import load_tuned_profile

    with pytest.raises(ValueError, match="section"):
        load_tuned_profile(_write_profile(
            tmp_path, knobs={"bogus": {"x": 1}}))
    with pytest.raises(ValueError, match="VPPT_"):
        load_tuned_profile(_write_profile(
            tmp_path, knobs={"env": {"PATH": "/tmp"}}))
    with pytest.raises(ValueError):
        load_tuned_profile(str(tmp_path / "missing.json"))
    assert load_tuned_profile("") is None


def test_autotune_check_accepts_good_profile(tmp_path, _clean_env):
    if str(TOOLS) not in sys.path:
        sys.path.insert(0, str(TOOLS))
    import autotune

    assert autotune.check_profile(_write_profile(tmp_path)) == []
    problems = autotune.check_profile(
        _write_profile(tmp_path, floor_us="fast"))
    assert problems


# --- the PALLAS_KERNELS manifest lint (registry pass, run tier-1) -----


def test_pallas_manifest_lint_clean():
    if str(TOOLS) not in sys.path:
        sys.path.insert(0, str(TOOLS))
    from analysis.registries import partitions_lint

    assert partitions_lint() == []
