"""Statuscheck + STN daemon/watchdog tests.

Reference model: cn-infra statuscheck semantics (worst-of aggregation,
probe transitions) and cmd/contiv-stn behavior (steal/release/info,
restart persistence, watchdog reverting NICs after consecutive health
failures — main.go:486-537).
"""

import json
import urllib.error
import urllib.request

import pytest

from vpp_tpu.health import (
    FakeNetlink,
    PluginState,
    STNDaemon,
    StatusCheck,
)
from vpp_tpu.health.statuscheck import HealthHTTPServer
from vpp_tpu.health.stn import Watchdog


def test_statuscheck_aggregation_and_watchers():
    sc = StatusCheck()
    report_a = sc.register("ipam")
    report_b = sc.register("policy")
    assert sc.agent_state() == PluginState.INIT

    transitions = []
    sc.watch_state(lambda p, s: transitions.append((p, s)))

    report_a(PluginState.OK)
    report_b(PluginState.OK)
    assert sc.agent_state() == PluginState.OK
    report_b(PluginState.ERROR, "etcd down")
    assert sc.agent_state() == PluginState.ERROR
    assert sc.liveness()["alive"] is False
    assert ("policy", PluginState.ERROR) in transitions
    # repeated same-state report doesn't re-fire watchers
    n = len(transitions)
    report_b(PluginState.ERROR, "still down")
    assert len(transitions) == n

    report_b(PluginState.OK)
    assert sc.liveness()["ready"] is True


def test_statuscheck_probes():
    sc = StatusCheck()
    healthy = {"v": True}
    sc.register_probe("datastore", lambda: healthy["v"])
    sc.run_probes()
    assert sc.agent_state() == PluginState.OK
    healthy["v"] = False
    sc.run_probes()
    assert sc.agent_state() == PluginState.ERROR
    st = sc.plugin_status()["datastore"]
    assert st["state"] == "ERROR" and st["error"]

    sc.register_probe("broken", lambda: 1 / 0)
    sc.run_probes()
    assert "probe raised" in sc.plugin_status()["broken"]["error"]


def test_health_http_endpoints():
    sc = StatusCheck()
    rep = sc.register("core")
    server = HealthHTTPServer(sc, port=0)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        # INIT: alive but not ready
        body = json.loads(urllib.request.urlopen(f"{url}/liveness", timeout=10).read())
        assert body["alive"] is True
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{url}/readiness", timeout=10)
        assert e.value.code == 503

        rep(PluginState.OK)
        body = json.loads(urllib.request.urlopen(f"{url}/readiness", timeout=10).read())
        assert body["ready"] is True

        rep(PluginState.ERROR, "dead")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{url}/liveness", timeout=10)
        assert e.value.code == 503
    finally:
        server.close()


def nic_fixture():
    nl = FakeNetlink()
    nl.add_interface(
        "eth1", pci="0000:00:08.0", driver="mlx5_core",
        ips=["192.168.1.10/24"],
        routes=[{"dst": "0.0.0.0/0", "gw": "192.168.1.1"}],
    )
    return nl


def test_stn_steal_release_roundtrip(tmp_path):
    nl = nic_fixture()
    d = STNDaemon(nl, persist_path=str(tmp_path / "stn.json"))
    info = d.steal("eth1")
    assert info.ip_addresses == ["192.168.1.10/24"]
    assert nl.state["eth1"]["bound"] is False
    assert nl.state["eth1"]["ips"] == []
    # idempotent steal returns recorded info
    assert d.steal("eth1") == info
    assert d.stolen_interface_info("eth1") == info

    assert d.release("eth1") is True
    assert nl.state["eth1"]["bound"] is True
    assert nl.state["eth1"]["ips"] == ["192.168.1.10/24"]
    assert nl.state["eth1"]["routes"] == [{"dst": "0.0.0.0/0", "gw": "192.168.1.1"}]
    assert d.release("eth1") is False  # already released


def test_stn_restart_persistence(tmp_path):
    nl = nic_fixture()
    path = str(tmp_path / "stn.json")
    d = STNDaemon(nl, persist_path=path)
    d.steal("eth1")

    # daemon restart: new instance over same backend + persist file
    d2 = STNDaemon(nl, persist_path=path)
    info = d2.stolen_interface_info("eth1")
    assert info is not None and info.ip_addresses == ["192.168.1.10/24"]
    assert d2.release("eth1") is True
    assert nl.state["eth1"]["ips"] == ["192.168.1.10/24"]


def test_watchdog_reverts_after_grace_and_rearms():
    nl = nic_fixture()
    d = STNDaemon(nl)
    d.steal("eth1")
    healthy = {"v": True}
    wd = Watchdog(d, probe=lambda: healthy["v"], grace_failures=3)

    wd.tick()
    assert d.stolen_interface_info("eth1") is not None

    healthy["v"] = False
    wd.tick(); wd.tick()
    assert d.stolen_interface_info("eth1") is not None, "within grace"
    wd.tick()
    assert d.stolen_interface_info("eth1") is None, "reverted after grace"
    assert nl.state["eth1"]["bound"] is True

    # agent recovers and steals again; watchdog must re-arm
    healthy["v"] = True
    wd.tick()
    d.steal("eth1")
    healthy["v"] = False
    for _ in range(3):
        wd.tick()
    assert d.stolen_interface_info("eth1") is None


def test_watchdog_retries_failed_reverts():
    """A rebind failure must not kill the watchdog; the NIC stays
    tracked and the revert retries on later ticks."""
    nl = nic_fixture()
    d = STNDaemon(nl)
    d.steal("eth1")
    boom = {"v": True}
    orig_rebind = nl.rebind

    def flaky_rebind(iface):
        if boom["v"]:
            raise OSError("sysfs transient error")
        orig_rebind(iface)

    nl.rebind = flaky_rebind
    wd = Watchdog(d, probe=lambda: False, grace_failures=1)
    wd.tick()
    assert d.stolen_interface_info("eth1") is not None, "still tracked"
    assert wd.reverted is False
    boom["v"] = False
    wd.tick()
    assert d.stolen_interface_info("eth1") is None
    assert nl.state["eth1"]["bound"] is True


def test_release_failure_keeps_nic_tracked():
    nl = nic_fixture()
    d = STNDaemon(nl)
    d.steal("eth1")
    orig = nl.rebind
    nl.rebind = lambda iface: (_ for _ in ()).throw(OSError("busy"))
    with pytest.raises(OSError):
        d.release("eth1")
    assert d.stolen_interface_info("eth1") is not None
    nl.rebind = orig
    assert d.release("eth1") is True


def test_watchdog_probe_exception_counts_as_failure():
    nl = nic_fixture()
    d = STNDaemon(nl)
    d.steal("eth1")

    def probe():
        raise ConnectionError("agent down")

    wd = Watchdog(d, probe=probe, grace_failures=2)
    wd.tick(); wd.tick()
    assert d.stolen_interface_info("eth1") is None


def _can_netadmin_stn() -> bool:
    import subprocess

    try:
        r = subprocess.run(
            ["ip", "link", "add", "vpptstnck0", "type", "veth",
             "peer", "name", "vpptstnck1"],
            capture_output=True, timeout=10,
        )
        if r.returncode == 0:
            subprocess.run(["ip", "link", "del", "vpptstnck0"],
                           capture_output=True, timeout=10)
            return True
        return False
    except Exception:
        return False


@pytest.mark.skipif(not _can_netadmin_stn(),
                    reason="needs CAP_NET_ADMIN (veth)")
def test_stn_real_kernel_steal_crash_autorevert(tmp_path):
    """VERDICT r2 Next #6: steal → crash → auto-revert against a REAL
    kernel interface. A veth leg gets an address + route, the LinuxNetlink
    backend steals it (kernel addressing flushed), the watchdog sees the
    'agent' die and must restore the exact addresses and routes."""
    import subprocess

    from vpp_tpu.health.stn_netlink import LinuxNetlink

    def sh(*a):
        return subprocess.run(["ip", *a], capture_output=True, text=True)

    sh("link", "del", "vpptstn0")
    assert sh("link", "add", "vpptstn0", "type", "veth",
              "peer", "name", "vpptstn1").returncode == 0
    try:
        sh("link", "set", "vpptstn0", "up")
        sh("link", "set", "vpptstn1", "up")
        sh("addr", "add", "10.77.0.2/24", "dev", "vpptstn0")
        sh("route", "add", "10.78.0.0/24", "via", "10.77.0.1",
           "dev", "vpptstn0", "onlink")

        backend = LinuxNetlink()
        daemon = STNDaemon(backend,
                           persist_path=str(tmp_path / "stn.json"))
        info = daemon.steal("vpptstn0")
        assert "10.77.0.2/24" in info.ip_addresses
        assert any(r["dst"] == "10.78.0.0/24" and r["gw"] == "10.77.0.1"
                   for r in info.routes)
        # kernel addressing is gone (the data plane owns the wire now)
        assert "10.77.0.2" not in sh("-o", "addr", "show",
                                     "dev", "vpptstn0").stdout

        # the agent "crashes": health probe dead → watchdog reverts
        dog = Watchdog(daemon, probe=lambda: False, grace_failures=2)
        dog.tick()
        dog.tick()
        out = sh("-o", "addr", "show", "dev", "vpptstn0").stdout
        assert "10.77.0.2/24" in out
        routes = sh("route", "show", "10.78.0.0/24").stdout
        assert "10.77.0.1" in routes and "vpptstn0" in routes
        assert daemon.stolen_interface_info("vpptstn0") is None

        # recovered agent can steal again
        info2 = daemon.steal("vpptstn0")
        assert "10.77.0.2/24" in info2.ip_addresses
        daemon.release("vpptstn0")
        assert "10.77.0.2" in sh("-o", "addr", "show",
                                 "dev", "vpptstn0").stdout
    finally:
        sh("link", "del", "vpptstn0")
