"""End-to-end policy path: K8s NetworkPolicy objects all the way to packet
verdicts on the (CPU-simulated) TPU data plane.

This is the TPU analog of the reference's acl_renderer_test.go driven
through mock/aclengine: assertions are *connectivity semantics*.
"""


from vpp_tpu.ir.rule import PodID
from vpp_tpu.ksr import model as m
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector
from vpp_tpu.policy import PolicyCache, PolicyConfigurator, PolicyProcessor
from vpp_tpu.renderer.tpu import TpuRenderer

WEB1 = PodID("default", "web1")
WEB2 = PodID("default", "web2")
DB = PodID("default", "db")
CLIENT = PodID("default", "client")

IPS = {WEB1: "10.1.1.2", WEB2: "10.1.1.3", DB: "10.1.1.4", CLIENT: "10.1.1.5"}
LABELS = {WEB1: {"app": "web"}, WEB2: {"app": "web"}, DB: {"app": "db"}, CLIENT: {"app": "client"}}


class Env:
    def __init__(self):
        self.dp = Dataplane()
        self.dp.add_uplink()
        self.cache = PolicyCache()
        self.configurator = PolicyConfigurator(self.cache)
        self.renderer = TpuRenderer(self.dp)
        self.configurator.register_renderer(self.renderer)
        self.processor = PolicyProcessor(self.cache, self.configurator)

        self.cache.update_namespace(m.Namespace(name="default", labels={"team": "a"}))
        for pid in (WEB1, WEB2, DB, CLIENT):
            if_idx = self.dp.add_pod_interface(pid)
            self.dp.builder.add_route(f"{IPS[pid]}/32", if_idx, Disposition.LOCAL)
            self.cache.update_pod(
                m.Pod(name=pid.name, namespace=pid.namespace,
                      labels=LABELS[pid], ip_address=IPS[pid])
            )
        self.dp.swap()

    def send(self, src_pod, dst_pod, dport, proto=6, sport=33333):
        pkts = make_packet_vector([
            {"src": IPS[src_pod], "dst": IPS[dst_pod], "proto": proto,
             "sport": sport, "dport": dport, "rx_if": self.dp.pod_if[src_pod]}
        ])
        r = self.dp.process(pkts)
        return Disposition(int(r.disp[0]))


def db_policy():
    """K8s: pods labeled app=db accept ingress only from app=web on TCP:5432."""
    return m.Policy(
        name="db-allow-web",
        namespace="default",
        pods=m.LabelSelector(match_labels={"app": "db"}),
        policy_type=m.POLICY_INGRESS,
        ingress_rules=[
            m.PolicyRule(
                ports=[m.PolicyPort(protocol="TCP", port=5432)],
                peers=[m.PolicyPeer(pods=m.LabelSelector(match_labels={"app": "web"}))],
            )
        ],
    )


def test_parallel_renderer_commits():
    """configurator_impl.go:211-233 analog: with parallel_commits, both
    renderers land their tables and verdicts match the serial path."""
    from vpp_tpu.hoststack import SessionRuleEngine
    from vpp_tpu.renderer.vpptcp import VpptcpRenderer
    from vpp_tpu.policy import PolicyCache, PolicyConfigurator, PolicyProcessor

    dp = Dataplane()
    dp.add_uplink()
    cache = PolicyCache()
    configurator = PolicyConfigurator(cache, parallel_commits=True)
    engine = SessionRuleEngine(capacity=256)
    pod_ifs = {}
    configurator.register_renderer(TpuRenderer(dp))
    configurator.register_renderer(
        VpptcpRenderer(engine, lambda p: pod_ifs.get(p, -1))
    )
    processor = PolicyProcessor(cache, configurator)

    cache.update_namespace(m.Namespace(name="default", labels={}))
    for pid in (WEB1, DB):
        idx = dp.add_pod_interface(pid)
        pod_ifs[pid] = idx
        dp.builder.add_route(f"{IPS[pid]}/32", idx, Disposition.LOCAL)
        cache.update_pod(m.Pod(name=pid.name, namespace=pid.namespace,
                               labels=LABELS[pid], ip_address=IPS[pid]))
    dp.swap()
    cache.update_policy(db_policy())

    # both renderers committed: device tables deny, session rules exist
    pkts = make_packet_vector([
        {"src": IPS[WEB1], "dst": IPS[DB], "proto": 6, "sport": 1,
         "dport": 9999, "rx_if": pod_ifs[WEB1]}
    ])
    assert int(dp.process(pkts).disp[0]) == int(Disposition.DROP)
    assert engine.num_rules > 0


def test_no_policy_everything_allowed():
    env = Env()
    assert env.send(CLIENT, DB, 5432) == Disposition.LOCAL
    assert env.send(WEB1, CLIENT, 80) == Disposition.LOCAL


def test_ingress_policy_enforced_end_to_end():
    env = Env()
    env.cache.update_policy(db_policy())

    # web pods may reach db on 5432 only; others denied.
    assert env.send(WEB1, DB, 5432) == Disposition.LOCAL
    assert env.send(WEB2, DB, 5432) == Disposition.LOCAL
    assert env.send(WEB1, DB, 80) == Disposition.DROP
    assert env.send(CLIENT, DB, 5432) == Disposition.DROP
    assert env.send(CLIENT, DB, 5432, proto=17) == Disposition.DROP
    # unrelated traffic unaffected
    assert env.send(CLIENT, WEB1, 80) == Disposition.LOCAL

    # db's reply to an established web1 flow passes (reflective session).
    pkts = make_packet_vector([
        {"src": IPS[DB], "dst": IPS[WEB1], "proto": 6,
         "sport": 5432, "dport": 33333, "rx_if": env.dp.pod_if[DB]}
    ])
    r = env.dp.process(pkts)
    assert Disposition(int(r.disp[0])) == Disposition.LOCAL


def test_policy_delete_restores_connectivity():
    env = Env()
    env.cache.update_policy(db_policy())
    assert env.send(CLIENT, DB, 5432) == Disposition.DROP
    env.cache.delete_policy("default", "db-allow-web")
    assert env.send(CLIENT, DB, 5432) == Disposition.LOCAL


def test_policy_update_changes_port():
    env = Env()
    env.cache.update_policy(db_policy())
    p2 = db_policy()
    p2.ingress_rules[0].ports[0] = m.PolicyPort(protocol="TCP", port=5433)
    env.cache.update_policy(p2)
    assert env.send(WEB1, DB, 5432) == Disposition.DROP
    assert env.send(WEB1, DB, 5433) == Disposition.LOCAL


def test_new_peer_pod_gets_access():
    """A pod created later with app=web labels must be granted access
    (processor re-renders pods referencing it)."""
    env = Env()
    env.cache.update_policy(db_policy())
    web3 = PodID("default", "web3")
    if_idx = env.dp.add_pod_interface(web3)
    env.dp.builder.add_route("10.1.1.6/32", if_idx, Disposition.LOCAL)
    env.dp.swap()
    IPS[web3] = "10.1.1.6"
    try:
        env.cache.update_pod(
            m.Pod(name="web3", namespace="default", labels={"app": "web"},
                  ip_address="10.1.1.6")
        )
        assert env.send(web3, DB, 5432) == Disposition.LOCAL
        assert env.send(web3, DB, 80) == Disposition.DROP
    finally:
        del IPS[web3]


def test_pod_delete_removes_rules():
    env = Env()
    env.cache.update_policy(db_policy())
    assert env.send(WEB1, DB, 5432) == Disposition.LOCAL
    # db pod deleted: its tables must be withdrawn; senders re-rendered.
    env.cache.delete_pod(DB)
    # (db's IP may be reused; no rules should reference it anymore)
    t = env.renderer.cache
    for table in list(t.local_tables) + [t.get_global_table()]:
        for rule in table.rules:
            for net in (rule.src_network, rule.dest_network):
                assert net is None or str(net.network_address) != IPS[DB]


def test_ipblock_with_except():
    """Egress policy: client may reach 10.2.0.0/16 except 10.2.5.0/24."""
    env = Env()
    env.dp.builder.add_route("10.2.0.0/16", env.dp.uplink_if, Disposition.REMOTE, node_id=2)
    env.dp.swap()
    pol = m.Policy(
        name="client-egress",
        namespace="default",
        pods=m.LabelSelector(match_labels={"app": "client"}),
        policy_type=m.POLICY_EGRESS,
        egress_rules=[
            m.PolicyRule(
                peers=[m.PolicyPeer(ip_block=m.IPBlock(
                    cidr="10.2.0.0/16", except_cidrs=["10.2.5.0/24"]))],
            )
        ],
    )
    env.cache.update_policy(pol)

    def send_to(dst_ip, dport=80):
        pkts = make_packet_vector([
            {"src": IPS[CLIENT], "dst": dst_ip, "proto": 6, "sport": 1,
             "dport": dport, "rx_if": env.dp.pod_if[CLIENT]}
        ])
        return Disposition(int(env.dp.process(pkts).disp[0]))

    assert send_to("10.2.1.1") == Disposition.REMOTE
    assert send_to("10.2.5.7") == Disposition.DROP  # inside the except
    assert send_to("10.1.1.2") == Disposition.DROP  # outside the block


def test_shared_tables_for_identical_policy_sets():
    env = Env()
    env.cache.update_policy(db_policy())
    # web1 and web2 share identical rendering -> one shared local table.
    t1 = env.renderer.cache.get_local_table_by_pod(WEB1)
    t2 = env.renderer.cache.get_local_table_by_pod(WEB2)
    assert t1 is not None and t1 is t2


def test_named_port_fails_closed_until_resolvable():
    """An unresolvable named port must not widen the policy to all ports;
    once the selected pod declares the named containerPort it resolves."""
    env = Env()
    pol = db_policy()
    pol.ingress_rules[0].ports[0] = m.PolicyPort(protocol="TCP", port=None, port_name="pg")
    env.cache.update_policy(pol)
    # Unresolvable: no port permitted from web pods (fail closed).
    assert env.send(WEB1, DB, 5432) == Disposition.DROP
    # db pod now declares the named port -> policy resolves to 5432.
    env.cache.update_pod(
        m.Pod(name=DB.name, namespace=DB.namespace, labels=LABELS[DB],
              ip_address=IPS[DB],
              containers=[m.Container(name="pg", ports=[
                  m.ContainerPort(name="pg", container_port=5432)])])
    )
    assert env.send(WEB1, DB, 5432) == Disposition.LOCAL
    assert env.send(WEB1, DB, 80) == Disposition.DROP


def test_renderer_resync_publishes_clean_slate():
    env = Env()
    env.cache.update_policy(db_policy())
    assert env.send(CLIENT, DB, 5432) == Disposition.DROP
    # Resync with an empty world: device must stop enforcing old tables.
    txn = env.renderer.new_txn(resync=True)
    txn.commit()
    assert env.send(CLIENT, DB, 5432, sport=34001) == Disposition.LOCAL
