"""MXU bit-plane classify vs the dense first-match oracle.

The bit-plane compilation (vpp_tpu.ops.acl_mxu) must reproduce the dense
kernel's verdicts exactly for every MXU-compilable rule shape: prefixes,
exact protocols, exact and wildcard ports, first-match ordering, and the
unmatched defaults. Randomized rule/packet sets are cross-checked against
vpp_tpu.ops.acl, and the Pallas kernel itself runs in interpret mode.
"""

import ipaddress

import numpy as np
import pytest

import jax.numpy as jnp

from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.ops import acl
from vpp_tpu.ops.acl_mxu import (
    ENC_MISS,
    compile_bitplanes,
    mxu_first_match,
    mxu_first_match_reference,
    packet_bit_planes,
)
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig, pack_rules
from vpp_tpu.pipeline.vector import (
    Disposition,
    PacketVector,
    make_packet_vector,
)


def random_rules(rng, n, with_ranges=False):
    rules = []
    for _ in range(n):
        plen = int(rng.integers(0, 33))
        net = ipaddress.ip_network(
            (int(rng.integers(0, 2**32)) & acl_mask(plen), plen)
        )
        dplen = int(rng.integers(0, 33))
        dnet = ipaddress.ip_network(
            (int(rng.integers(0, 2**32)) & acl_mask(dplen), dplen)
        )
        proto = [Protocol.ANY, Protocol.TCP, Protocol.UDP][
            int(rng.integers(0, 3))
        ]
        dport = int(rng.choice([0, 80, 443, 8080, 65535]))
        rules.append(
            ContivRule(
                action=Action.PERMIT if rng.random() < 0.5 else Action.DENY,
                src_network=net if rng.random() < 0.7 else None,
                dest_network=dnet if rng.random() < 0.7 else None,
                protocol=proto,
                dest_port=dport if proto != Protocol.ANY else 0,
            )
        )
    return rules


def acl_mask(plen):
    return ((1 << 32) - 1) ^ ((1 << (32 - plen)) - 1) if plen else 0


def random_packets(rng, n, rules):
    """Half random 5-tuples, half crafted to land inside rule prefixes."""
    src = rng.integers(0, 2**32, n, dtype=np.uint32)
    dst = rng.integers(0, 2**32, n, dtype=np.uint32)
    for i in range(n // 2):
        r = rules[int(rng.integers(0, len(rules)))]
        if r.src_network is not None:
            src[i] = int(r.src_network.network_address) + int(
                rng.integers(0, max(1, min(r.src_network.num_addresses, 1000)))
            )
        if r.dest_network is not None:
            dst[i] = int(r.dest_network.network_address) + int(
                rng.integers(0, max(1, min(r.dest_network.num_addresses, 1000)))
            )
    return PacketVector(
        src_ip=jnp.asarray(src),
        dst_ip=jnp.asarray(dst),
        proto=jnp.asarray(rng.choice([1, 6, 17], n).astype(np.int32)),
        sport=jnp.asarray(rng.integers(0, 65536, n).astype(np.int32)),
        dport=jnp.asarray(
            rng.choice([0, 80, 443, 8080, 53, 65535], n).astype(np.int32)
        ),
        ttl=jnp.full((n,), 64, jnp.int32),
        pkt_len=jnp.full((n,), 100, jnp.int32),
        rx_if=jnp.zeros((n,), jnp.int32),
        flags=jnp.ones((n,), jnp.int32),
    )


def dense_encoded(packed, pkts, nrules):
    v = acl._first_match(
        pkts,
        jnp.asarray(packed["src_net"]), jnp.asarray(packed["src_mask"]),
        jnp.asarray(packed["dst_net"]), jnp.asarray(packed["dst_mask"]),
        jnp.asarray(packed["proto"]),
        jnp.asarray(packed["sport_lo"]), jnp.asarray(packed["sport_hi"]),
        jnp.asarray(packed["dport_lo"]), jnp.asarray(packed["dport_hi"]),
        jnp.asarray(packed["action"]),
        jnp.int32(nrules),
    )
    return v


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_bitplane_matches_dense(seed):
    rng = np.random.default_rng(seed)
    rules = random_rules(rng, 60)
    packed = pack_rules(rules, 64)
    table = compile_bitplanes(packed, 64)
    assert table.ok

    pkts = random_packets(rng, 128, rules)
    bits = packet_bit_planes(pkts)
    enc = mxu_first_match_reference(
        bits, jnp.asarray(table.coeff), jnp.asarray(table.k)
    )
    dense = dense_encoded(packed, pkts, len(rules))
    got_idx = np.where(np.asarray(enc) == ENC_MISS, -1, np.asarray(enc))
    np.testing.assert_array_equal(got_idx, np.asarray(dense.rule_idx))


@pytest.mark.parametrize("seed", [3, 4])
def test_pallas_kernel_interpret_matches_reference(seed):
    rng = np.random.default_rng(seed)
    rules = random_rules(rng, 100)
    packed = pack_rules(rules, 128)
    table = compile_bitplanes(packed, 128)
    pkts = random_packets(rng, 70, rules)  # odd size exercises padding
    bits = packet_bit_planes(pkts)
    coeff, k = jnp.asarray(table.coeff), jnp.asarray(table.k)
    ref = mxu_first_match_reference(bits, coeff, k)
    got = mxu_first_match(bits, coeff, k, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_range_rules_fall_back():
    rules = [
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                   dest_port=80),
    ]
    packed = pack_rules(rules, 8)
    # Inject a true port range (the ContivRule IR only carries exact
    # ports, but resynced/foreign tables may have ranges).
    packed["dport_lo"][0] = 100
    packed["dport_hi"][0] = 200
    table = compile_bitplanes(packed, 8)
    assert not table.ok
    # Fail closed: the range rule can never match in the MXU planes even
    # if a caller ignores ok=False — its coefficient column is zeroed and
    # k pinned to 1, so mismatch ≡ 1 for every possible packet.
    assert table.k[0] >= 1.0
    assert (table.coeff[:, 0] == 0.0).all()
    # Direct check: a proto-7 packet (one bit off TCP) must NOT match —
    # this was the spurious-match case before coeff zeroing.
    pkts = make_packet_vector([dict(src="1.2.3.4", dst="5.6.7.8", proto=7,
                                    sport=1, dport=150)])
    bits = packet_bit_planes(pkts)
    mism = bits.astype(jnp.float32) @ table.coeff + table.k
    assert float(mism[0, 0]) >= 1.0


def test_dataplane_flips_to_mxu_path():
    cfg = DataplaneConfig(max_global_rules=1024, sess_slots=256)
    dp = Dataplane(cfg)
    dp.mxu_threshold = 2  # small threshold for the test
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("ns", "p"))
    dp.builder.add_route("10.1.1.2/32", pod, Disposition.LOCAL)
    rules = [
        ContivRule(action=Action.DENY, protocol=Protocol.TCP, dest_port=23),
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP, dest_port=80),
        ContivRule(action=Action.DENY),
    ]
    dp.builder.set_global_table(rules)
    dp.swap()
    assert dp._use_mxu

    from vpp_tpu.pipeline.vector import make_packet_vector

    pkts = make_packet_vector(
        [
            {"src": "1.2.3.4", "dst": "10.1.1.2", "proto": 6,
             "sport": 999, "dport": 80, "rx_if": up},
            {"src": "1.2.3.4", "dst": "10.1.1.2", "proto": 6,
             "sport": 999, "dport": 23, "rx_if": up},
        ]
    )
    res = dp.process(pkts)
    disp = np.asarray(res.disp)
    assert disp[0] == int(Disposition.LOCAL)
    assert disp[1] == int(Disposition.DROP)
    assert int(res.stats.drop_acl) == 1
