"""MXU bit-plane classify vs the dense first-match oracle.

The bit-plane compilation (vpp_tpu.ops.acl_mxu) must reproduce the dense
kernel's verdicts exactly for every MXU-compilable rule shape: prefixes,
exact protocols, exact and wildcard ports, first-match ordering, and the
unmatched defaults. Randomized rule/packet sets are cross-checked against
vpp_tpu.ops.acl, and the Pallas kernel itself runs in interpret mode.
"""

import ipaddress

import numpy as np
import pytest

import jax.numpy as jnp

from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.ops import acl
from vpp_tpu.ops.acl_mxu import (
    ENC_MISS,
    compile_bitplanes,
    mxu_first_match,
    mxu_first_match_reference,
    packet_bit_planes,
)
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig, pack_rules
from vpp_tpu.pipeline.vector import (
    Disposition,
    PacketVector,
    make_packet_vector,
)


def random_rules(rng, n, with_ranges=False):
    rules = []
    for _ in range(n):
        plen = int(rng.integers(0, 33))
        net = ipaddress.ip_network(
            (int(rng.integers(0, 2**32)) & acl_mask(plen), plen)
        )
        dplen = int(rng.integers(0, 33))
        dnet = ipaddress.ip_network(
            (int(rng.integers(0, 2**32)) & acl_mask(dplen), dplen)
        )
        proto = [Protocol.ANY, Protocol.TCP, Protocol.UDP][
            int(rng.integers(0, 3))
        ]
        dport = int(rng.choice([0, 80, 443, 8080, 65535]))
        rules.append(
            ContivRule(
                action=Action.PERMIT if rng.random() < 0.5 else Action.DENY,
                src_network=net if rng.random() < 0.7 else None,
                dest_network=dnet if rng.random() < 0.7 else None,
                protocol=proto,
                dest_port=dport if proto != Protocol.ANY else 0,
            )
        )
    return rules


def acl_mask(plen):
    return ((1 << 32) - 1) ^ ((1 << (32 - plen)) - 1) if plen else 0


def random_packets(rng, n, rules):
    """Half random 5-tuples, half crafted to land inside rule prefixes."""
    src = rng.integers(0, 2**32, n, dtype=np.uint32)
    dst = rng.integers(0, 2**32, n, dtype=np.uint32)
    for i in range(n // 2):
        r = rules[int(rng.integers(0, len(rules)))]
        if r.src_network is not None:
            src[i] = int(r.src_network.network_address) + int(
                rng.integers(0, max(1, min(r.src_network.num_addresses, 1000)))
            )
        if r.dest_network is not None:
            dst[i] = int(r.dest_network.network_address) + int(
                rng.integers(0, max(1, min(r.dest_network.num_addresses, 1000)))
            )
    return PacketVector(
        src_ip=jnp.asarray(src),
        dst_ip=jnp.asarray(dst),
        proto=jnp.asarray(rng.choice([1, 6, 17], n).astype(np.int32)),
        sport=jnp.asarray(rng.integers(0, 65536, n).astype(np.int32)),
        dport=jnp.asarray(
            rng.choice([0, 80, 443, 8080, 53, 65535], n).astype(np.int32)
        ),
        ttl=jnp.full((n,), 64, jnp.int32),
        pkt_len=jnp.full((n,), 100, jnp.int32),
        rx_if=jnp.zeros((n,), jnp.int32),
        flags=jnp.ones((n,), jnp.int32),
    )


def dense_encoded(packed, pkts, nrules):
    v = acl._first_match(
        pkts,
        jnp.asarray(packed["src_net"]), jnp.asarray(packed["src_mask"]),
        jnp.asarray(packed["dst_net"]), jnp.asarray(packed["dst_mask"]),
        jnp.asarray(packed["proto"]),
        jnp.asarray(packed["sport_lo"]), jnp.asarray(packed["sport_hi"]),
        jnp.asarray(packed["dport_lo"]), jnp.asarray(packed["dport_hi"]),
        jnp.asarray(packed["action"]),
        jnp.int32(nrules),
    )
    return v


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_bitplane_matches_dense(seed):
    rng = np.random.default_rng(seed)
    rules = random_rules(rng, 60)
    packed = pack_rules(rules, 64)
    table = compile_bitplanes(packed, 64)
    assert table.ok

    pkts = random_packets(rng, 128, rules)
    bits = packet_bit_planes(pkts)
    enc = mxu_first_match_reference(
        bits, jnp.asarray(table.coeff), jnp.asarray(table.k)
    )
    dense = dense_encoded(packed, pkts, len(rules))
    got_idx = np.where(np.asarray(enc) == ENC_MISS, -1, np.asarray(enc))
    np.testing.assert_array_equal(got_idx, np.asarray(dense.rule_idx))


@pytest.mark.parametrize("seed", [3, 4])
def test_pallas_kernel_interpret_matches_reference(seed):
    rng = np.random.default_rng(seed)
    rules = random_rules(rng, 100)
    packed = pack_rules(rules, 128)
    table = compile_bitplanes(packed, 128)
    pkts = random_packets(rng, 70, rules)  # odd size exercises padding
    bits = packet_bit_planes(pkts)
    coeff, k = jnp.asarray(table.coeff), jnp.asarray(table.k)
    ref = mxu_first_match_reference(bits, coeff, k)
    got = mxu_first_match(bits, coeff, k, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_range_rules_fall_back():
    rules = [
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                   dest_port=80),
    ]
    packed = pack_rules(rules, 8)
    # Inject a true port range (the ContivRule IR only carries exact
    # ports, but resynced/foreign tables may have ranges).
    packed["dport_lo"][0] = 100
    packed["dport_hi"][0] = 200
    table = compile_bitplanes(packed, 8)
    assert not table.ok
    # Fail closed: the range rule can never match in the MXU planes even
    # if a caller ignores ok=False — its coefficient column is zeroed and
    # k pinned to 1, so mismatch ≡ 1 for every possible packet.
    assert table.k[0] >= 1.0
    assert (table.coeff[:, 0] == 0.0).all()
    # Direct check: a proto-7 packet (one bit off TCP) must NOT match —
    # this was the spurious-match case before coeff zeroing.
    pkts = make_packet_vector([dict(src="1.2.3.4", dst="5.6.7.8", proto=7,
                                    sport=1, dport=150)])
    bits = packet_bit_planes(pkts)
    mism = bits.astype(jnp.float32) @ table.coeff + table.k
    assert float(mism[0, 0]) >= 1.0


def test_dataplane_flips_to_mxu_path():
    cfg = DataplaneConfig(max_global_rules=1024, sess_slots=256)
    dp = Dataplane(cfg)
    dp.mxu_threshold = 2  # small threshold for the test
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("ns", "p"))
    dp.builder.add_route("10.1.1.2/32", pod, Disposition.LOCAL)
    rules = [
        ContivRule(action=Action.DENY, protocol=Protocol.TCP, dest_port=23),
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP, dest_port=80),
        ContivRule(action=Action.DENY),
    ]
    dp.builder.set_global_table(rules)
    dp.swap()
    assert dp._use_mxu

    from vpp_tpu.pipeline.vector import make_packet_vector

    pkts = make_packet_vector(
        [
            {"src": "1.2.3.4", "dst": "10.1.1.2", "proto": 6,
             "sport": 999, "dport": 80, "rx_if": up},
            {"src": "1.2.3.4", "dst": "10.1.1.2", "proto": 6,
             "sport": 999, "dport": 23, "rx_if": up},
        ]
    )
    res = dp.process(pkts)
    disp = np.asarray(res.disp)
    assert disp[0] == int(Disposition.LOCAL)
    assert disp[1] == int(Disposition.DROP)
    assert int(res.stats.drop_acl) == 1


class TestIncrementalCompile:
    """compile_bitplanes_update + pack_rules_incremental must be
    bit-identical to a from-scratch compile across every churn shape:
    in-place edits, inserts/removes (index shifts), table shrink/grow,
    range-port (non-compilable) rules entering and leaving, and
    builder snapshot/restore invalidation."""

    @staticmethod
    def _rand_rules(rng, n):
        rules = []
        for i in range(n):
            kind = rng.integers(0, 8)
            net = ipaddress.ip_network(
                f"10.{int(rng.integers(0, 200))}."
                f"{int(rng.integers(0, 200))}.0/24")
            if kind == 0:  # any-port rule (the IR cannot express port
                #            RANGES; the non-compilable bad-mask carry
                #            is covered at the packed level below)
                r = ContivRule(action=Action.DENY, dest_network=net,
                               protocol=Protocol.TCP)
            else:
                r = ContivRule(
                    action=Action.PERMIT if i % 3 else Action.DENY,
                    dest_network=net, protocol=Protocol.TCP,
                    dest_port=int(rng.integers(1, 60000)))
            rules.append(r)
        return rules

    def test_matches_full_compile_across_churn(self):
        from vpp_tpu.pipeline.tables import TableBuilder
        from vpp_tpu.ops.acl_mxu import compile_bitplanes_full
        from vpp_tpu.pipeline.tables import pack_rules as _pack

        rng = np.random.default_rng(3)
        cfg = DataplaneConfig(max_tables=2, max_rules=8,
                              max_global_rules=256, max_ifaces=4,
                              fib_slots=16, sess_slots=64,
                              nat_mappings=2, nat_backends=2)
        b = TableBuilder(cfg)
        rules = self._rand_rules(rng, 64)
        for step in range(12):
            b.set_global_table(rules)
            want, _ = compile_bitplanes_full(_pack(rules, 256), 256)
            got = b.glb_mxu
            assert np.array_equal(got.coeff, want.coeff), step
            assert np.array_equal(got.k, want.k), step
            assert np.array_equal(got.act, want.act), step
            assert got.ok == want.ok, step
            for key in b.glb:
                assert np.array_equal(
                    b.glb[key], _pack(rules, 256)[key]), (step, key)
            # next churn: mix of in-place edit / insert / remove
            rules = list(rules)
            op = step % 4
            if op == 0:    # in-place edits (the common policy churn)
                for j in rng.integers(0, len(rules), 5):
                    rules[int(j)] = ContivRule(
                        action=Action.PERMIT, protocol=Protocol.TCP,
                        dest_port=7000 + step)
            elif op == 1:  # insert early: everything after shifts
                rules.insert(3, ContivRule(action=Action.DENY,
                                           protocol=Protocol.UDP,
                                           dest_port=9))
            elif op == 2:  # remove a chunk: table shrinks
                del rules[5:15]
            else:          # grow with fresh random rules (some bad)
                rules.extend(self._rand_rules(rng, 7))

    def test_bad_mask_carries_across_updates(self):
        """Non-compilable (range-port) rows can only be expressed at
        the packed level (the ContivRule IR carries exact-or-ANY ports
        only — test_range_rules_fall_back). The incremental update
        must CARRY the bad mask: ok stays False while an untouched
        range row exists, and recovers only when the update recompiles
        that row into a compilable form."""
        from vpp_tpu.ops.acl_mxu import (
            compile_bitplanes_full, compile_bitplanes_update,
        )

        cap = 64
        packed = pack_rules(
            [ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                        dest_port=80) for _ in range(4)], cap)
        packed["dport_lo"][2], packed["dport_hi"][2] = 1000, 2000  # range
        mxu, bad = compile_bitplanes_full(packed, cap)
        assert not mxu.ok and bad[2]

        # churn a DIFFERENT row: badness must carry, not reset
        packed2 = {k: v.copy() for k, v in packed.items()}
        packed2["dport_lo"][0] = packed2["dport_hi"][0] = 8080
        mxu2, bad2 = compile_bitplanes_update(
            packed2, cap, mxu, bad, np.asarray([0], np.int64))
        want2, wbad2 = compile_bitplanes_full(packed2, cap)
        assert not mxu2.ok and bad2[2]
        assert np.array_equal(mxu2.coeff, want2.coeff)
        assert np.array_equal(mxu2.k, want2.k)
        assert np.array_equal(bad2, wbad2)

        # fix the range row: ok recovers through the incremental path
        packed3 = {k: v.copy() for k, v in packed2.items()}
        packed3["dport_lo"][2] = packed3["dport_hi"][2] = 1500
        mxu3, bad3 = compile_bitplanes_update(
            packed3, cap, mxu2, bad2, np.asarray([2], np.int64))
        want3, wbad3 = compile_bitplanes_full(packed3, cap)
        assert mxu3.ok and not bad3.any()
        assert np.array_equal(mxu3.coeff, want3.coeff)
        assert np.array_equal(mxu3.k, want3.k)
        assert np.array_equal(mxu3.act, want3.act)

    def test_snapshot_restore_invalidates_cache(self):
        from vpp_tpu.pipeline.tables import TableBuilder
        from vpp_tpu.ops.acl_mxu import compile_bitplanes_full
        from vpp_tpu.pipeline.tables import pack_rules as _pack

        cfg = DataplaneConfig(max_tables=2, max_rules=8,
                              max_global_rules=64, max_ifaces=4,
                              fib_slots=16, sess_slots=64,
                              nat_mappings=2, nat_backends=2)
        b = TableBuilder(cfg)
        r1 = [ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                         dest_port=80)]
        r2 = [ContivRule(action=Action.DENY, protocol=Protocol.TCP,
                         dest_port=443)]
        b.set_global_table(r1)
        snap = b.state_snapshot()
        b.set_global_table(r2)
        b.state_restore(snap)
        # post-restore commit must not trust the pre-restore identity
        # cache: committing r2 again must produce exactly r2's compile
        b.set_global_table(r2)
        want, _ = compile_bitplanes_full(_pack(r2, 64), 64)
        assert np.array_equal(b.glb_mxu.coeff, want.coeff)
        assert np.array_equal(b.glb_mxu.k, want.k)
        assert np.array_equal(b.glb_mxu.act, want.act)
