"""Robot-suite analog scenarios.

Reference model: tests/robot/suites/{one_node_two_pods,
two_node_two_pods, one_node_two_pods_policy_ingress}.robot — ping/UDP/
TCP pod↔pod, pod↔host, cross-node connectivity and policy cases, run
here as in-process scenarios against real agents over a shared store.
"""


from vpp_tpu.cmd import AgentConfig, ContivAgent
from vpp_tpu.cmd.ksr_main import KsrAgent
from vpp_tpu.cni.model import CNIRequest
from vpp_tpu.ksr import model as m
from vpp_tpu.kvstore.store import KVStore
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector


def boot(node_name="node-a", store=None):
    store = store or KVStore()
    ksr = KsrAgent(store=store, serve_http=False)
    ksr.start()
    agent = ContivAgent(AgentConfig(node_name=node_name, serve_http=False),
                        store=store)
    agent.start()
    return store, ksr, agent


def add_pod(agent, cid, name, ns="default"):
    reply = agent.cni_server.add(CNIRequest(
        container_id=cid,
        extra_args={"K8S_POD_NAME": name, "K8S_POD_NAMESPACE": ns},
    ))
    assert reply.result == 0
    return reply.interfaces[0].ip_addresses[0].address.split("/")[0]


def xmit(agent, rx_if, src, dst, proto=6, sport=33333, dport=80):
    pkts = make_packet_vector([
        dict(src=src, dst=dst, proto=proto, sport=sport, dport=dport,
             rx_if=rx_if)
    ])
    res = agent.dataplane.process(pkts)
    return Disposition(int(res.disp[0])), res


class TestOneNodeTwoPods:
    """one_node_two_pods.robot: ping/UDP/TCP pod↔pod + pod↔host."""

    def setup_method(self, _):
        self.store, self.ksr, self.agent = boot()
        self.ip1 = add_pod(self.agent, "c1", "pod1")
        self.ip2 = add_pod(self.agent, "c2", "pod2")
        self.if1 = self.agent.dataplane.pod_if[("default", "pod1")]
        self.if2 = self.agent.dataplane.pod_if[("default", "pod2")]

    def teardown_method(self, _):
        self.agent.close()

    def test_ping_pod_to_pod(self):  # ICMP both directions
        d, _ = xmit(self.agent, self.if1, self.ip1, self.ip2, proto=1,
                    sport=0, dport=0)
        assert d == Disposition.LOCAL
        d, _ = xmit(self.agent, self.if2, self.ip2, self.ip1, proto=1,
                    sport=0, dport=0)
        assert d == Disposition.LOCAL

    def test_udp_and_tcp_pod_to_pod(self):
        for proto in (6, 17):
            d, res = xmit(self.agent, self.if1, self.ip1, self.ip2,
                          proto=proto, dport=5201)
            assert d == Disposition.LOCAL
            assert int(res.tx_if[0]) == self.if2

    def test_pod_to_host(self):
        """Traffic to the node's own IP goes to the host stack."""
        agent = self.agent
        node_ip = str(agent.ipam.node_ip_address())
        agent.dataplane.builder.add_route(
            f"{node_ip}/32", agent.host_if, Disposition.HOST
        )
        agent.dataplane.swap()
        d, res = xmit(agent, self.if1, self.ip1, node_ip, dport=22)
        assert d == Disposition.HOST
        assert int(res.stats.punt) == 1

    def test_host_to_pod(self):
        d, res = xmit(self.agent, self.agent.host_if,
                      str(self.agent.ipam.veth_host_end_ip()), self.ip1,
                      dport=8080)
        assert d == Disposition.LOCAL
        assert int(res.tx_if[0]) == self.if1


class TestTwoNodeTwoPods:
    """two_node_two_pods.robot: cross-node pod↔pod over the overlay."""

    def setup_method(self, _):
        self.store = KVStore()
        _, self.ksr, self.a = boot("node-a", self.store)
        self.b = ContivAgent(
            AgentConfig(node_name="node-b", serve_http=False), store=self.store
        )
        self.b.start()
        self.ip_a = add_pod(self.a, "ca", "poda")
        self.ip_b = add_pod(self.b, "cb", "podb")

    def teardown_method(self, _):
        self.a.close()
        self.b.close()

    def test_cross_node_pod_to_pod_and_return(self):
        a, b = self.a, self.b
        if_a = a.dataplane.pod_if[("default", "poda")]
        # A-side: REMOTE toward node B, encapped to B's VTEP
        d, res = xmit(a, if_a, self.ip_a, self.ip_b, dport=5201)
        assert d == Disposition.REMOTE
        assert int(res.node_id[0]) == b.node_id
        outer = a.dataplane.encap_remote(res)
        assert int(outer.dst_ip[0]) == int(a.ipam.vxlan_ip_address(b.node_id))

        # B-side: decapped traffic enters via B's uplink and reaches podb
        d2, res2 = xmit(b, b.uplink_if, self.ip_a, self.ip_b, dport=5201)
        assert d2 == Disposition.LOCAL
        assert int(res2.tx_if[0]) == b.dataplane.pod_if[("default", "podb")]

        # return path B → A
        if_b = b.dataplane.pod_if[("default", "podb")]
        d3, res3 = xmit(b, if_b, self.ip_b, self.ip_a, sport=80, dport=33333)
        assert d3 == Disposition.REMOTE
        assert int(res3.node_id[0]) == a.node_id

    def test_nodeport_reaches_backend_on_other_node(self):
        """Service with a backend on node B, reached via B's pod from A's
        pod through the VIP (service spine over two agents)."""
        self.ksr.sources[m.Service.TYPE].add("default/svc", m.Service(
            name="svc", namespace="default", cluster_ip="10.96.0.77",
            ports=[m.ServicePort(name="p", protocol="TCP", port=80,
                                 target_port="p")],
        ))
        self.ksr.sources[m.Endpoints.TYPE].add("default/svc", m.Endpoints(
            name="svc", namespace="default",
            subsets=[m.EndpointSubset(
                addresses=[m.EndpointAddress(ip=self.ip_b,
                                             node_name="node-b")],
                ports=[m.EndpointPort(name="p", port=9000, protocol="TCP")],
            )],
        ))
        if_a = self.a.dataplane.pod_if[("default", "poda")]
        d, res = xmit(self.a, if_a, self.ip_a, "10.96.0.77", dport=80)
        # DNAT to the backend on node B → REMOTE disposition
        assert d == Disposition.REMOTE
        assert int(res.pkts.dport[0]) == 9000
        assert int(res.node_id[0]) == self.b.node_id


class TestPolicyIngressScenario:
    """one_node_two_pods_policy_ingress.robot analog."""

    def setup_method(self, _):
        self.store, self.ksr, self.agent = boot()

    def teardown_method(self, _):
        self.agent.close()

    def test_ingress_policy_blocks_then_unblocks(self):
        ksr, agent = self.ksr, self.agent
        ip1 = add_pod(agent, "c1", "server")
        ip2 = add_pod(agent, "c2", "client")
        for name, ip, labels in (("server", ip1, {"role": "server"}),
                                 ("client", ip2, {"role": "client"})):
            ksr.sources[m.Pod.TYPE].add(
                f"default/{name}",
                m.Pod(name=name, namespace="default", labels=labels,
                      ip_address=ip),
            )
        ksr.sources[m.Namespace.TYPE].add(
            "default", m.Namespace(name="default", labels={})
        )
        if_client = agent.dataplane.pod_if[("default", "client")]

        d, _ = xmit(agent, if_client, ip2, ip1, dport=80)
        assert d == Disposition.LOCAL, "open before policy"

        ksr.sources[m.Policy.TYPE].add("default/deny-all", m.Policy(
            name="deny-all", namespace="default",
            pods=m.LabelSelector(match_labels={"role": "server"}),
            policy_type=m.POLICY_INGRESS,
            ingress_rules=[],  # isolate: nothing allowed in
        ))
        d, _ = xmit(agent, if_client, ip2, ip1, dport=80)
        assert d == Disposition.DROP, "isolated by empty ingress policy"

        ksr.sources[m.Policy.TYPE].delete("default/deny-all")
        d, _ = xmit(agent, if_client, ip2, ip1, dport=80)
        assert d == Disposition.LOCAL, "open after policy removal"
