"""Service NAT44 backend churn (ISSUE 19): sticky way fill, DNAT
backend-pick stickiness across a rolling replacement, the
``service.churn`` chaos point (a half-applied backend set never
serves), and the incremental "svc" upload group (a one-row churn
ships a few-KB blob, never the full planes).
"""

import numpy as np
import pytest

from vpp_tpu.ksr import model as m
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig, svc_capacity
from vpp_tpu.pipeline.vector import (
    Disposition,
    ip4,
    ip4_str,
    make_packet_vector,
)
from vpp_tpu.service import ServiceConfigurator, ServiceProcessor
from vpp_tpu.testing import faults

VIP = "10.96.0.10"
KEY = (ip4(VIP), 80, 6)


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    yield
    faults.uninstall()


def mk_svc_dp(**over):
    base = dict(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=32, sess_slots=512, nat_mappings=2, nat_backends=4,
        svc_vips=16, svc_backend_ways=8,
    )
    base.update(over)
    dp = Dataplane(DataplaneConfig(**base))
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("default", "web"))
    dp.builder.add_route("10.1.1.0/24", pod, Disposition.LOCAL)
    dp.builder.add_route("10.200.0.0/16", pod, Disposition.LOCAL)
    dp.builder.add_route("0.0.0.0/0", up, Disposition.REMOTE)
    dp.swap()
    return dp, up, pod


def backends(n, base=10):
    return [(ip4(f"10.200.0.{base + j}"), 8080, 1) for j in range(n)]


def vip_flows(n, rx_if, vip=VIP, seed=0):
    return make_packet_vector(
        [{"src": f"10.9.{(seed + i) // 200}.{(seed + i) % 200 + 1}",
          "dst": vip, "proto": 6,
          "sport": 1024 + (37 * (seed + i)) % 50000, "dport": 80,
          "rx_if": rx_if, "ttl": 64}
         for i in range(n)], n=n)


class TestStickyFill:
    def test_survivors_keep_their_ways_on_replacement(self):
        """Roll one backend out of four: the six ways the survivors
        own stay EXACTLY where they were; only the rolled backend's
        two ways move, and both land on the replacement."""
        dp, up, pod = mk_svc_dp()
        bks = backends(4)
        with dp.commit_lock:
            dp.builder.set_service(*KEY, bks)
        a0 = list(dp.builder.services[KEY]["assign"])
        rolled = bks[3]
        new = (ip4("10.200.0.99"), 8080, 1)
        with dp.commit_lock:
            dp.builder.set_service(*KEY, bks[:3] + [new])
        a1 = list(dp.builder.services[KEY]["assign"])
        survivors = {(b[0], b[1]) for b in bks[:3]}
        moved = 0
        for w in range(len(a0)):
            if (a0[w][0], a0[w][1]) in survivors:
                assert a1[w] == a0[w], (w, a0[w], a1[w])
            else:
                assert (a0[w][0], a0[w][1]) == (rolled[0], rolled[1])
                assert (a1[w][0], a1[w][1]) == (new[0], new[1])
                moved += 1
        assert moved == 2  # 8 ways / 4 equal-weight backends

    def test_weight_change_alone_never_evicts_by_endpoint(self):
        """Re-staging the same endpoints with shifted weights reuses
        every way a backend keeps under its new share — matched by
        endpoint, so no way churns to a DIFFERENT survivor."""
        dp, up, pod = mk_svc_dp()
        bks = backends(2)
        with dp.commit_lock:
            dp.builder.set_service(*KEY, bks)          # 4 + 4 ways
        a0 = list(dp.builder.services[KEY]["assign"])
        heavier = [(bks[0][0], bks[0][1], 3), bks[1]]  # 6 + 2 ways
        with dp.commit_lock:
            dp.builder.set_service(*KEY, heavier)
        a1 = list(dp.builder.services[KEY]["assign"])
        for w in range(len(a0)):
            if (a1[w][0], a1[w][1]) == (bks[1][0], bks[1][1]):
                # every way backend 1 still owns is one it owned before
                assert (a0[w][0], a0[w][1]) == (bks[1][0], bks[1][1])
        # idempotent re-stage: byte-identical assignment
        with dp.commit_lock:
            dp.builder.set_service(*KEY, heavier)
        assert list(dp.builder.services[KEY]["assign"]) == a1

    def test_half_applied_rows_never_match(self):
        """The padding-row guard: a VIP row only matches once its
        whole backend set is staged (svc_bk_n > 0), and a refused
        set leaves the previous one serving."""
        dp, up, pod = mk_svc_dp()
        with dp.commit_lock:
            dp.builder.set_service(*KEY, backends(2))
            with pytest.raises(ValueError, match="weight"):
                dp.builder.set_service(*KEY, [
                    (ip4("10.200.0.50"), 8080, 0)])
            dp.swap()
        r = dp.probe(vip_flows(16, up), now=1)
        dsts = {ip4_str(d) for d in np.asarray(r.pkts.dst_ip)}
        assert dsts <= {"10.200.0.10", "10.200.0.11"}
        # padding rows (bk_n == 0) are inert on-device
        assert int(np.asarray(dp.tables.svc_bk_n)[1:].sum()) == 0


class TestDnatStickiness:
    def test_flow_picks_sticky_across_backend_roll(self):
        """256 flows through a 4-backend VIP, then one backend rolls:
        every flow that picked a survivor keeps its EXACT backend,
        every moved flow lands on the replacement, zero loss."""
        dp, up, pod = mk_svc_dp()
        bks = backends(4)
        with dp.commit_lock:
            dp.builder.set_service(*KEY, bks)
            dp.swap()
        flows = vip_flows(256, up)
        r0 = dp.probe(flows, now=1)
        picks0 = np.asarray(r0.pkts.dst_ip)
        assert (np.asarray(r0.disp)
                == int(Disposition.LOCAL)).all(), "zero loss before"
        assert (picks0 != ip4(VIP)).all(), "every flow DNAT'd"
        rolled_ip = bks[3][0]
        new_ip = ip4("10.200.0.99")
        with dp.commit_lock:
            dp.builder.set_service(*KEY, bks[:3]
                                   + [(new_ip, 8080, 1)])
            dp.swap()
        r1 = dp.probe(flows, now=2)
        picks1 = np.asarray(r1.pkts.dst_ip)
        assert (np.asarray(r1.disp)
                == int(Disposition.LOCAL)).all(), "zero loss after"
        on_survivor = picks0 != rolled_ip
        np.testing.assert_array_equal(picks1[on_survivor],
                                      picks0[on_survivor])
        moved = ~on_survivor
        assert moved.any(), "sample must cover the rolled backend"
        assert (picks1[moved] == new_ip).all()

    def test_add_backend_moves_only_freed_share(self):
        """Scale-out churn: adding a backend moves only the ways the
        rebalanced shares free up — surviving flows overwhelmingly
        keep their pick (>= 1 - 1/n of them exactly sticky)."""
        dp, up, pod = mk_svc_dp()
        bks = backends(3)
        with dp.commit_lock:
            dp.builder.set_service(*KEY, bks)
            dp.swap()
        flows = vip_flows(256, up, seed=1000)
        picks0 = np.asarray(dp.probe(flows, now=1).pkts.dst_ip)
        with dp.commit_lock:
            dp.builder.set_service(
                *KEY, bks + [(ip4("10.200.0.40"), 8080, 1)])
            dp.swap()
        picks1 = np.asarray(dp.probe(flows, now=2).pkts.dst_ip)
        kept = (picks0 == picks1).mean()
        assert kept >= 0.6, kept  # 6 of 8 ways stay put
        assert (picks1[picks0 != picks1]
                == ip4("10.200.0.40")).all()


class TestChurnChaos:
    """The ``service.churn`` fault point through the REAL configurator
    path: a crash mid-churn rolls the builder back, publishes nothing,
    and the pre-churn backend set keeps serving every offered flow."""

    def make_env(self):
        dp, up, pod = mk_svc_dp()
        cfg = ServiceConfigurator(dp, node_ips=[])
        proc = ServiceProcessor(cfg, node_name="node-a")
        return dp, up, cfg, proc

    def web_service(self):
        return m.Service(
            name="web", namespace="default", cluster_ip=VIP,
            external_traffic_policy="Cluster",
            ports=[m.ServicePort(name="http", protocol="TCP",
                                 port=80, target_port="http",
                                 node_port=0)],
        )

    def web_endpoints(self, ips):
        return m.Endpoints(
            name="web", namespace="default",
            subsets=[m.EndpointSubset(
                addresses=[m.EndpointAddress(ip=i, node_name="node-b")
                           for i in ips],
                ports=[m.EndpointPort(name="http", port=8080,
                                      protocol="TCP")],
            )],
        )

    def test_crash_mid_churn_rolls_back_and_old_set_serves(self):
        dp, up, cfg, proc = self.make_env()
        proc.update_service(self.web_service())
        proc.update_endpoints(self.web_endpoints(
            ["10.200.0.10", "10.200.0.11"]))
        flows = vip_flows(64, up, seed=500)
        before = np.asarray(dp.probe(flows, now=1).pkts.dst_ip)
        old_set = {ip4("10.200.0.10"), ip4("10.200.0.11")}
        assert set(before.tolist()) <= old_set

        t0 = dp.tables
        reg0 = {k: list(e["members"])
                for k, e in dp.builder.services.items()}
        plan = faults.install(faults.FaultPlan(seed=19))
        plan.inject("service.churn", after=0, times=1)
        with pytest.raises(faults.FaultInjected):
            proc.update_endpoints(self.web_endpoints(
                ["10.200.0.10", "10.200.0.77"]))
        # nothing published: same device epoch, registry rolled back
        assert dp.tables is t0
        assert {k: list(e["members"])
                for k, e in dp.builder.services.items()} == reg0
        # conservation + the half-applied guard: every offered flow
        # still DNATs to the OLD set; the new backend never serves
        during = dp.probe(flows, now=2)
        picks = np.asarray(during.pkts.dst_ip)
        assert (np.asarray(during.disp)
                == int(Disposition.LOCAL)).all()
        np.testing.assert_array_equal(picks, before)
        assert ip4("10.200.0.77") not in set(picks.tolist())

        # recovery: the SAME churn re-driven with the fault cleared
        # converges, and only then does the replacement serve
        faults.uninstall()
        proc.update_endpoints(self.web_endpoints(
            ["10.200.0.10", "10.200.0.77"]))
        assert dp.tables is not t0
        after = np.asarray(dp.probe(flows, now=3).pkts.dst_ip)
        new_set = {ip4("10.200.0.10"), ip4("10.200.0.77")}
        assert set(after.tolist()) <= new_set
        assert ip4("10.200.0.11") not in set(after.tolist())
        # sticky through the crash-and-retry: survivors keep flows
        on_kept = before == ip4("10.200.0.10")
        np.testing.assert_array_equal(after[on_kept], before[on_kept])

    def test_delete_service_mid_churn_rolls_back_too(self):
        dp, up, cfg, proc = self.make_env()
        proc.update_service(self.web_service())
        proc.update_endpoints(self.web_endpoints(["10.200.0.10"]))
        t0 = dp.tables
        plan = faults.install(faults.FaultPlan(seed=20))
        plan.inject("service.churn", after=0, times=1)
        with pytest.raises(faults.FaultInjected):
            proc.delete_service("default", "web")
        assert dp.tables is t0
        assert KEY in dp.builder.services
        r = dp.probe(vip_flows(8, up), now=1)
        assert (np.asarray(r.pkts.dst_ip)
                == ip4("10.200.0.10")).all(), "VIP still serves"
        faults.uninstall()
        cfg.resync(list(cfg.services.values()))
        assert KEY not in dp.builder.services


class TestIncrementalUpload:
    def test_one_row_churn_ships_blob_only(self):
        """The zero-reship pact at plane level: after a full 48-VIP
        stage, rolling ONE backend ships a few-KB scatter blob —
        zero full svc fields, zero ACL/ML/FIB/tenant bytes (device
        arrays identity-carried) — and the on-device planes equal
        the builder's host staging bit-exact."""
        dp, up, pod = mk_svc_dp(svc_vips=64, fib_slots=64)
        V, B = svc_capacity(dp.config)
        assert V == 64 and B == 8
        vips = [(ip4(f"10.96.{v // 250}.{2 + v % 250}"), 80, 6)
                for v in range(48)]
        with dp.commit_lock:
            for v, key in enumerate(vips):
                dp.builder.set_service(
                    *key, [(ip4(f"10.200.{v}.10") + j, 8080, 1)
                           for j in range(4)])
            dp.swap()
        full = dp.builder.svc_upload
        assert full["blob_bytes"] == 0 and len(full["fields"]) == 7
        pinned = (dp.tables.glb_src_net, dp.tables.acl_src_net,
                  dp.tables.fib_prefix, dp.tables.tnt_vni)
        with dp.commit_lock:
            v = 7
            dp.builder.set_service(
                *vips[v], [(ip4(f"10.200.{v}.10") + j, 8080, 1)
                           for j in range(3)]
                + [(ip4("10.200.99.99"), 8080, 1)])
            dp.swap()
        up_rec = dp.builder.svc_upload
        assert up_rec["fields"] == ()
        assert 0 < up_rec["blob_bytes"] < 8192, up_rec
        assert up_rec["blob_bytes"] < full["bytes"] / 4
        now = (dp.tables.glb_src_net, dp.tables.acl_src_net,
               dp.tables.fib_prefix, dp.tables.tnt_vni)
        for a, b in zip(pinned, now):
            assert a is b, "churn re-shipped a foreign plane"
        # the scatter blob applied EXACTLY the host staging
        for f, host in dp.builder.svc.items():
            np.testing.assert_array_equal(
                np.asarray(getattr(dp.tables, f)), host, err_msg=f)

    def test_unchanged_restage_ships_nothing(self):
        """Idempotent churn: re-staging an identical registry compiles
        byte-identical rows, so the svc group ships NOTHING."""
        dp, up, pod = mk_svc_dp()
        with dp.commit_lock:
            dp.builder.set_service(*KEY, backends(3))
            dp.swap()
        with dp.commit_lock:
            dp.builder.set_service(*KEY, backends(3))
            dp.swap()
        up_rec = dp.builder.svc_upload
        assert up_rec["fields"] == () and up_rec["blob_bytes"] == 0
