"""vpp-tpu-ldpreload-inject: manifest rewriting for the session shim.

Reference analog: the ldpreload-label-injector dev tool + the CRI
shim's env injection (cmd/tools/ldpreload-label-injector,
cmd/contiv-cri) — modernized as a yaml transform (SURVEY §7 excludes
the dockershim wrapper itself).
"""

import io

import yaml

from vpp_tpu.cmd.ldpreload_inject import inject_documents, main

DEPLOYMENT = """
apiVersion: apps/v1
kind: Deployment
metadata:
  name: web
spec:
  replicas: 2
  template:
    spec:
      containers:
      - name: nginx
        image: nginx:1.25
        env:
        - name: EXISTING
          value: keep
      - name: sidecar
        image: busybox
---
apiVersion: v1
kind: Pod
metadata:
  name: one-off
spec:
  containers:
  - name: app
    image: alpine
---
apiVersion: v1
kind: Service
metadata:
  name: web
spec:
  ports:
  - port: 80
"""


def _envmap(container):
    return {e["name"]: e["value"] for e in container["env"]}


def test_inject_deployment_pod_and_skip_service():
    docs = list(yaml.safe_load_all(DEPLOYMENT))
    n = inject_documents(docs, "/run/vpp-tpu/vcl.sock",
                         "/opt/vpp-tpu/lib", appns=3, fail_closed=False)
    assert n == 2  # Deployment template + Pod; Service untouched

    dep, pod, svc = docs
    for c in dep["spec"]["template"]["spec"]["containers"]:
        env = _envmap(c)
        assert env["LD_PRELOAD"] == "/opt/vpp-tpu/lib/libvclshim.so"
        assert env["VPP_TPU_VCL_SOCK"] == "/run/vpp-tpu/vcl.sock"
        assert env["VPP_TPU_APPNS"] == "3"
        assert "VPP_TPU_VCL_FAILCLOSED" not in env
        mounts = {m["name"]: m for m in c["volumeMounts"]}
        assert mounts["vpp-tpu-run"]["mountPath"] == "/run/vpp-tpu"
        assert mounts["vpp-tpu-lib"]["readOnly"] is True
    # existing env preserved
    assert _envmap(dep["spec"]["template"]["spec"]["containers"][0])[
        "EXISTING"] == "keep"
    vols = {v["name"]: v for v in dep["spec"]["template"]["spec"]["volumes"]}
    assert vols["vpp-tpu-run"]["hostPath"]["path"] == "/run/vpp-tpu"

    assert _envmap(pod["spec"]["containers"][0])["VPP_TPU_APPNS"] == "3"
    assert "env" not in svc["spec"].get("ports", [{}])[0]


def test_idempotent_and_fail_closed():
    docs = list(yaml.safe_load_all(DEPLOYMENT))
    inject_documents(docs, "/run/vpp-tpu/vcl.sock", "/opt/vpp-tpu/lib",
                     appns=1, fail_closed=True)
    once = yaml.safe_dump_all(docs, sort_keys=False)
    inject_documents(docs, "/run/vpp-tpu/vcl.sock", "/opt/vpp-tpu/lib",
                     appns=1, fail_closed=True)
    twice = yaml.safe_dump_all(docs, sort_keys=False)
    assert once == twice
    c = docs[0]["spec"]["template"]["spec"]["containers"][0]
    assert _envmap(c)["VPP_TPU_VCL_FAILCLOSED"] == "1"
    # exactly one copy of each mount/volume survived the re-run
    assert [m["name"] for m in c["volumeMounts"]].count("vpp-tpu-run") == 1
    vols = docs[0]["spec"]["template"]["spec"]["volumes"]
    assert [v["name"] for v in vols].count("vpp-tpu-lib") == 1


def test_cronjob_and_cli_roundtrip(tmp_path, capsys, monkeypatch):
    cron = """
apiVersion: batch/v1
kind: CronJob
spec:
  schedule: "0 * * * *"
  jobTemplate:
    spec:
      template:
        spec:
          containers:
          - name: task
            image: alpine
"""
    src = tmp_path / "cron.yaml"
    src.write_text(cron)
    out = tmp_path / "out.yaml"
    rc = main([str(src), "-o", str(out), "--appns", "9"])
    assert rc == 0
    doc = yaml.safe_load(out.read_text())
    c = doc["spec"]["jobTemplate"]["spec"]["template"]["spec"][
        "containers"][0]
    assert _envmap(c)["VPP_TPU_APPNS"] == "9"

    # stdin/stdout mode; a manifest with no pod template exits 1
    monkeypatch.setattr("sys.stdin",
                        io.StringIO("apiVersion: v1\nkind: Service\n"
                                    "spec: {ports: []}\n"))
    rc = main(["-"])
    assert rc == 1


def test_init_containers_and_ld_preload_chaining():
    """initContainers get the shim too (a wait-for-db init connect must
    not bypass admission), and an existing LD_PRELOAD is chained after,
    not clobbered (same contract as vcl_env)."""
    manifest = """
apiVersion: apps/v1
kind: Deployment
spec:
  template:
    spec:
      initContainers:
      - name: wait-db
        image: busybox
      containers:
      - name: app
        image: alpine
        env:
        - name: LD_PRELOAD
          value: /usr/lib/libjemalloc.so
"""
    docs = list(yaml.safe_load_all(manifest))
    inject_documents(docs, "/run/vpp-tpu/vcl.sock", "/opt/vpp-tpu/lib",
                     appns=2, fail_closed=False)
    tmpl = docs[0]["spec"]["template"]["spec"]
    init_env = _envmap(tmpl["initContainers"][0])
    assert init_env["LD_PRELOAD"] == "/opt/vpp-tpu/lib/libvclshim.so"
    assert init_env["VPP_TPU_APPNS"] == "2"
    app_env = _envmap(tmpl["containers"][0])
    assert app_env["LD_PRELOAD"] == (
        "/usr/lib/libjemalloc.so:/opt/vpp-tpu/lib/libvclshim.so")
    # idempotent: no double-chaining on a second run
    inject_documents(docs, "/run/vpp-tpu/vcl.sock", "/opt/vpp-tpu/lib",
                     appns=2, fail_closed=False)
    assert _envmap(tmpl["containers"][0])["LD_PRELOAD"] == (
        "/usr/lib/libjemalloc.so:/opt/vpp-tpu/lib/libvclshim.so")


def test_value_from_replaced():
    """An env entry carrying valueFrom must lose it when we set a
    literal value — value+valueFrom together is rejected by the API."""
    manifest = """
apiVersion: v1
kind: Pod
spec:
  containers:
  - name: app
    image: alpine
    env:
    - name: VPP_TPU_APPNS
      valueFrom:
        fieldRef:
          fieldPath: metadata.name
"""
    docs = list(yaml.safe_load_all(manifest))
    inject_documents(docs, "/run/vpp-tpu/vcl.sock", "/opt/vpp-tpu/lib",
                     appns=4, fail_closed=False)
    entry = [e for e in docs[0]["spec"]["containers"][0]["env"]
             if e["name"] == "VPP_TPU_APPNS"][0]
    assert entry == {"name": "VPP_TPU_APPNS", "value": "4"}


def test_null_documents_dropped():
    """A trailing '---' / comment-only section loads as None — it must
    not re-serialize as a literal 'null' document kubectl rejects."""
    import subprocess
    import sys

    manifest = """\
apiVersion: v1
kind: Pod
spec:
  containers:
  - name: app
    image: alpine
---
# just a comment
---
"""
    proc = subprocess.run(
        [sys.executable, "-m", "vpp_tpu.cmd.ldpreload_inject", "-"],
        input=manifest, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "null" not in proc.stdout
    docs = [d for d in yaml.safe_load_all(proc.stdout)]
    assert len(docs) == 1 and docs[0]["kind"] == "Pod"
