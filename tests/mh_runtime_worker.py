"""Worker for the MultiHostRuntime e2e (run directly, not collected).

One vpp-tpu-mesh-agent-shaped process of a 2-process deployment: REAL
ContivAgents per local mesh node over the shared kvstore, CNI pod
adds, node events resolving peers to mesh positions across the
process boundary, renderer-driven policy cutoff — all commits riding
LockstepDriver's agreed collective epochs while the tick thread steps
the fabric.
"""

import json
import logging
import os
import sys
import threading
import time

PROC_ID = int(sys.argv[1])
NUM_PROCS = int(sys.argv[2])
COORD_PORT = sys.argv[3]
KV_PORT = sys.argv[4]

if os.environ.get("MH_DEBUG"):
    logging.basicConfig(level=logging.INFO)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from vpp_tpu.parallel.multihost import (  # noqa: E402
    MultiHostRuntime, init_multihost,
)
from vpp_tpu.cmd import AgentConfig  # noqa: E402
from vpp_tpu.cni.model import CNIRequest  # noqa: E402
from vpp_tpu.pipeline.vector import Disposition  # noqa: E402

init_multihost(f"127.0.0.1:{COORD_PORT}", NUM_PROCS, PROC_ID,
               heartbeat_timeout_s=600)

import ipaddress  # noqa: E402


class Collector:
    """Per-tick accumulation of this host's delivered/drop counters."""

    def __init__(self):
        self.lock = threading.Lock()
        self.delivered_dst = {}   # dst ip int -> count
        self.drop_acl = 0
        self.runtime = None

    def __call__(self, res):
        rt = self.runtime
        disp = rt.cluster.local_rows(res.delivered.disp)
        dst = rt.cluster.local_rows(res.delivered.pkts.dst_ip)
        acl = rt.cluster.local_rows(res.stats.drop_acl)
        local = disp == int(Disposition.LOCAL)
        with self.lock:
            for d in dst[local].astype(np.uint32):
                d = int(d)
                self.delivered_dst[d] = self.delivered_dst.get(d, 0) + 1
            self.drop_acl += int(acl.sum())

    def count_for(self, ip: str) -> int:
        with self.lock:
            return self.delivered_dst.get(
                int(ipaddress.ip_address(ip)), 0)


collector = Collector()
cfg = AgentConfig(
    node_name="mh", serve_http=False,
    store_url=f"tcp://127.0.0.1:{KV_PORT}",
    # two worker processes share ONE core with XLA compiles: a 15 s
    # lease can expire while the keepalive thread is starved, peers
    # then drop this node's routes mid-test ("node removed")
    node_liveness_ttl_s=120.0,
)
runtime = MultiHostRuntime(4, cfg, tick_interval=0.02,
                           frame_n=8, on_result=collector)
collector.runtime = runtime
store = runtime.store
runtime.start()


def wait_for(pred, what, deadline_s=60.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.1)
    raise TimeoutError(f"waiting for {what}")


def add_pod(agent, cid, name):
    reply = agent.cni_server.add(CNIRequest(
        container_id=cid,
        extra_args={"K8S_POD_NAME": name, "K8S_POD_NAMESPACE": "default"},
    ))
    assert reply.result == 0, reply
    return reply.interfaces[0].ip_addresses[0].address.split("/")[0]


verdict = {"proc": PROC_ID, "local_nodes": runtime.cluster.local_nodes}

# each process adds a pod on its first local agent and publishes the IP
my_agent = runtime.agents[0]
pod_name = f"pod{runtime.cluster.local_nodes[0]}"
my_ip = add_pod(my_agent, f"cid-{pod_name}", pod_name)
store.put(f"/test/{pod_name}_ip", my_ip)

ip0 = wait_for(lambda: store.get("/test/pod0_ip"), "pod0 ip")
ip2 = wait_for(lambda: store.get("/test/pod2_ip"), "pod2 ip")

# wait until BOTH processes' commits (CNI adds + node-event routes)
# are applied fleet-wide, then a couple more ticks for quiescence
wait_for(lambda: runtime.driver.applied >= 1, "first epoch")
base_ticks = runtime.driver.ticks
wait_for(lambda: runtime.driver.ticks > base_ticks + 5, "tick settle")

# node events must have produced a fabric route toward the peer's pod
# subnet before stage-1 traffic is meaningful — observable as the
# peer's pod IP resolving REMOTE in our FIB... simplest honest check:
# inject and wait for delivery (the fabric either works or this times
# out, failing the test loudly).
if PROC_ID == 0:
    pod_if0 = my_agent.dataplane.pod_if[("default", "pod0")]

    def send(sport, dport=80):
        runtime.inject(runtime.cluster.local_nodes[0], [dict(
            src=my_ip, dst=ip2, proto=6, sport=sport, dport=dport,
            rx_if=pod_if0)])

    # stage 1: flowing (retry injection — node-event route propagation
    # on the peer races our first packets)
    def delivered():
        send(2000 + runtime.driver.ticks % 500)
        time.sleep(0.1)
        return int(store.get("/test/stage1_count") or 0) > 0

    wait_for(delivered, "stage-1 delivery", 120)
    verdict["stage1_ok"] = True
    # stage 2: wait for the peer's policy commit, then offer fresh flows
    wait_for(lambda: store.get("/test/stage2_ready"), "policy commit")
    start_ticks = runtime.driver.ticks
    for i in range(30):
        send(3000 + i)
        time.sleep(0.05)
    wait_for(lambda: runtime.driver.ticks > start_ticks + 10,
             "stage-2 ticks")
    store.put("/test/stage2_sent", True)
    # P1 still needs live ticks to evaluate stage 2 — a premature
    # request_stop() would halt the whole fleet's fabric
    wait_for(lambda: store.get("/test/p1_done"), "peer verdict", 120)
else:
    # P1 owns pod2's node: report deliveries for stage 1
    def got_one():
        n = collector.count_for(my_ip)
        if n:
            store.put("/test/stage1_count", n)
        return n

    wait_for(got_one, "stage-1 delivery at pod2", 120)
    verdict["stage1_delivered"] = collector.count_for(my_ip)

    # render a deny-all for pod2 on ITS node handle (the reference's
    # policy path: renderer txn -> commit -> epoch)
    from vpp_tpu.renderer.tpu import TpuRenderer
    from vpp_tpu.ir.rule import Action, ContivRule

    renderer = TpuRenderer(my_agent.dataplane)
    txn = renderer.new_txn()
    txn.render(("default", "pod2"),
               ipaddress.ip_network(f"{my_ip}/32"),
               ingress=[], egress=[ContivRule(action=Action.DENY)])
    txn.commit()
    applied_before = runtime.driver.applied
    wait_for(lambda: runtime.driver.applied > applied_before,
             "policy epoch applied")
    pre_count = collector.count_for(my_ip)
    pre_drops = collector.drop_acl
    store.put("/test/stage2_ready", True)
    wait_for(lambda: store.get("/test/stage2_sent"), "stage-2 sent", 120)
    base_ticks = runtime.driver.ticks
    wait_for(lambda: runtime.driver.ticks > base_ticks + 5,
             "stage-2 settle")
    verdict["stage2_new_deliveries"] = \
        collector.count_for(my_ip) - pre_count
    verdict["stage2_acl_drops"] = collector.drop_acl - pre_drops
    store.put("/test/p1_done", True)

runtime.close()
print("VERDICT " + json.dumps(verdict), flush=True)
