"""Shared scaffolding for the multi-host worker scripts (run as
standalone processes by tests/test_multihost.py, never collected)."""

from vpp_tpu.ipam.ipam import IPAM
from vpp_tpu.pipeline.vector import Disposition


def stage_full_mesh(cluster):
    """Uplink + one pod + full-mesh fabric routes on every LOCAL node;
    returns {nid: pod_if}. Pod addressing is the deterministic IPAM
    arithmetic, so every process can recompute any pod's IP."""
    pod_if = {}
    for nid in cluster.local_nodes:
        node = cluster.node(nid)
        uplink = node.add_uplink()
        ipam = IPAM(nid + 1)
        ip = ipam.next_pod_ip(f"ns/pod{nid}")
        pod_if[nid] = node.add_pod_interface(f"ns/pod{nid}")
        node.builder.add_route(f"{ip}/32", pod_if[nid],
                               Disposition.LOCAL)
        for other in range(cluster.n_nodes):
            if other != nid:
                node.builder.add_route(
                    str(ipam.other_node_pod_network(other + 1)),
                    uplink, Disposition.REMOTE, node_id=other)
    return pod_if


def pod_ips(n_nodes):
    return {n: str(IPAM(n + 1).next_pod_ip(f"ns/pod{n}"))
            for n in range(n_nodes)}


LOCKSTEP_N_NODES = 4


def lockstep_config():
    """The DataplaneConfig both lockstep workers build their 4-node
    cluster with — one literal, so the failover variant exercises the
    SAME cluster shape as the baseline lockstep test."""
    from vpp_tpu.pipeline.tables import DataplaneConfig

    return DataplaneConfig(
        max_tables=4, max_rules=16, max_global_rules=32, max_ifaces=8,
        fib_slots=32, sess_slots=256, nat_mappings=4, nat_backends=16,
    )


def lockstep_frames(cluster, proc_id, all_pod_ip, pod_if, sport):
    """pod0 (P0) -> pod2 (P1); fresh sport each tick so no tick rides
    the previous tick's reflective session."""
    f = [[] for _ in cluster.local_nodes]
    if proc_id == 0:
        f[0] = [dict(src=all_pod_ip[0], dst=all_pod_ip[2], proto=6,
                     sport=sport, dport=8080, rx_if=pod_if[0])]
    return f


def lockstep_deliveries(cluster, proc_id, res):
    """Delivered count on node 2's row (P1's first local node); -1 on
    the process that doesn't own it."""
    if proc_id != 1:
        return -1
    disp = cluster.local_rows(res.delivered.disp)
    return int((disp[0] == int(Disposition.LOCAL)).sum())
