"""Shared scaffolding for the multi-host worker scripts (run as
standalone processes by tests/test_multihost.py, never collected)."""

from vpp_tpu.ipam.ipam import IPAM
from vpp_tpu.pipeline.vector import Disposition


def stage_full_mesh(cluster):
    """Uplink + one pod + full-mesh fabric routes on every LOCAL node;
    returns {nid: pod_if}. Pod addressing is the deterministic IPAM
    arithmetic, so every process can recompute any pod's IP."""
    pod_if = {}
    for nid in cluster.local_nodes:
        node = cluster.node(nid)
        uplink = node.add_uplink()
        ipam = IPAM(nid + 1)
        ip = ipam.next_pod_ip(f"ns/pod{nid}")
        pod_if[nid] = node.add_pod_interface(f"ns/pod{nid}")
        node.builder.add_route(f"{ip}/32", pod_if[nid],
                               Disposition.LOCAL)
        for other in range(cluster.n_nodes):
            if other != nid:
                node.builder.add_route(
                    str(ipam.other_node_pod_network(other + 1)),
                    uplink, Disposition.REMOTE, node_id=other)
    return pod_if


def pod_ips(n_nodes):
    return {n: str(IPAM(n + 1).next_pod_ip(f"ns/pod{n}"))
            for n in range(n_nodes)}
