"""Tests for the ContivRule IR and its total order.

Mirrors the ordering invariants relied upon by the reference's renderer
cache (plugins/policy/renderer/api.go Compare + utils.go CompareIPNets).
"""

import ipaddress

from vpp_tpu.ir import (
    Action,
    ContivRule,
    ContivRuleTable,
    Protocol,
    compare_ip_nets,
    compare_ports,
    compare_rules,
)
from vpp_tpu.ir.rule import rule_matches


def net(s):
    return ipaddress.ip_network(s)


def test_compare_ports_any_is_highest():
    assert compare_ports(0, 0) == 0
    assert compare_ports(0, 80) == 1
    assert compare_ports(80, 0) == -1
    assert compare_ports(80, 443) == -1
    assert compare_ports(443, 80) == 1


def test_compare_ip_nets_subset_sorts_first():
    # a ⊂ b => a < b
    assert compare_ip_nets(net("10.1.1.0/24"), net("10.1.0.0/16")) == -1
    assert compare_ip_nets(net("10.1.0.0/16"), net("10.1.1.0/24")) == 1
    # None = 0/0 is the maximum
    assert compare_ip_nets(net("10.1.1.0/24"), None) == -1
    assert compare_ip_nets(None, net("10.1.1.0/24")) == 1
    assert compare_ip_nets(None, None) == 0
    # equal
    assert compare_ip_nets(net("10.1.1.0/24"), net("10.1.1.0/24")) == 0
    # disjoint but total
    a, b = net("10.1.1.0/24"), net("10.2.2.0/24")
    assert compare_ip_nets(a, b) == -compare_ip_nets(b, a) != 0
    # IPv4 before IPv6
    assert compare_ip_nets(net("10.0.0.0/8"), net("fd00::/8")) == -1


def test_rule_total_order_specific_first():
    specific = ContivRule(
        action=Action.DENY,
        src_network=net("10.1.1.3/32"),
        protocol=Protocol.TCP,
        dest_port=80,
    )
    wider = ContivRule(
        action=Action.PERMIT,
        src_network=net("10.1.1.0/24"),
        protocol=Protocol.TCP,
    )
    widest = ContivRule(action=Action.PERMIT, protocol=Protocol.TCP)
    assert compare_rules(specific, wider) == -1
    assert compare_rules(wider, widest) == -1
    assert sorted([widest, specific, wider]) == [specific, wider, widest]


def test_rule_order_protocol_dominates():
    tcp = ContivRule(action=Action.PERMIT, protocol=Protocol.TCP)
    udp = ContivRule(action=Action.PERMIT, protocol=Protocol.UDP)
    assert compare_rules(tcp, udp) == -1


def test_table_insert_dedup_and_order():
    t = ContivRuleTable("T1")
    r1 = ContivRule(action=Action.PERMIT, protocol=Protocol.TCP)
    r2 = ContivRule(action=Action.DENY, src_network=net("10.0.0.1/32"), protocol=Protocol.TCP)
    assert t.insert_rule(r1)
    assert t.insert_rule(r2)
    assert not t.insert_rule(r1)  # duplicate
    assert t.rules == [r2, r1]  # most specific first
    assert t.num_of_rules == 2


def test_rule_matches_oracle():
    r = ContivRule(
        action=Action.PERMIT,
        src_network=net("10.1.0.0/16"),
        protocol=Protocol.TCP,
        dest_port=8080,
    )
    assert rule_matches(r, "10.1.2.3", "1.2.3.4", Protocol.TCP, 1234, 8080)
    assert not rule_matches(r, "10.2.2.3", "1.2.3.4", Protocol.TCP, 1234, 8080)
    assert not rule_matches(r, "10.1.2.3", "1.2.3.4", Protocol.UDP, 1234, 8080)
    assert not rule_matches(r, "10.1.2.3", "1.2.3.4", Protocol.TCP, 1234, 80)
    any_rule = ContivRule(action=Action.PERMIT, protocol=Protocol.ANY)
    assert rule_matches(any_rule, "10.1.2.3", "1.2.3.4", Protocol.ICMP, 0, 0)
