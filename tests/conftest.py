"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Real TPU hardware in CI is a single chip; multi-chip sharding is validated
on virtual CPU devices (the driver separately dry-runs the multi-chip path
via __graft_entry__.dryrun_multichip).
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
