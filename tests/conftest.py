"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Real TPU hardware in CI is a single chip; multi-chip sharding is validated
on virtual CPU devices (the driver separately dry-runs the multi-chip path
via __graft_entry__.dryrun_multichip).
"""

import os

# Must be set before jax is imported anywhere.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The CI environment pins JAX_PLATFORMS to the real TPU tunnel and its
# plugin overrides the env var, so force the platform via jax.config.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# `make test-race`: amplify thread interleavings by forcing preemption
# every few microseconds (default 5 ms) — the Go `-race` analog for the
# concurrency stress tests; races surface as corrupted ring/table state.
if os.environ.get("VPP_TPU_RACE"):
    import sys

    sys.setswitchinterval(5e-6)
