"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Real TPU hardware in CI is a single chip; multi-chip sharding is validated
on virtual CPU devices (the driver separately dry-runs the multi-chip path
via __graft_entry__.dryrun_multichip).
"""

import os

# Must be set before jax is imported anywhere.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The CI environment pins JAX_PLATFORMS to the real TPU tunnel and its
# plugin overrides the env var, so force the platform via jax.config.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# `make test-race`: amplify thread interleavings by forcing preemption
# every few microseconds (default 5 ms) — the Go `-race` analog for the
# concurrency stress tests; races surface as corrupted ring/table state.
if os.environ.get("VPP_TPU_RACE"):
    import sys

    sys.setswitchinterval(5e-6)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "jit_budget(n): with the jit_compile_budget fixture, fail the "
        "test if it triggers more than n pipeline-step XLA compiles "
        "(pipeline/dataplane.py runtime jit-compile guard, ISSUE 5)",
    )
    config.addinivalue_line(
        "markers",
        "transfer_budget(n): with the transfer_budget fixture, fail "
        "the test if the counted fetch sites move more than n "
        "device->host bytes (pipeline/dataplane.py runtime "
        "device-transfer guard, ISSUE 20)",
    )
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection schedule (tests/test_chaos.py; "
        "vpp_tpu/testing/faults.py). Bounded runtime; `make chaos` "
        "runs the suite; also marked slow so the tier-1 `-m 'not "
        "slow'` timing budget never pays for it",
    )
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 `-m 'not slow'` run "
        "(ROADMAP.md); run explicitly (e.g. `make chaos`)",
    )


@pytest.fixture
def jit_compile_budget(request):
    """Opt-in compile-budget guard: a test that requests this fixture
    declares (via ``@pytest.mark.jit_budget(n)``, default 0) how many
    pipeline-step compiles it is allowed to trigger; exceeding the
    budget fails the test. Budget 0 == "my shapes and variants are
    already warm" — the regression fence for the PR-4 bug class."""
    from vpp_tpu.pipeline import dataplane as _dp

    marker = request.node.get_closest_marker("jit_budget")
    budget = int(marker.args[0]) if marker and marker.args else 0
    guard = _dp.jit_compile_budget(budget)
    guard.__enter__()
    yield guard
    try:
        guard.__exit__(None, None, None)
    except _dp.JitBudgetExceeded as e:
        pytest.fail(str(e))


@pytest.fixture
def transfer_budget(request):
    """Opt-in device-transfer budget guard: a test that requests this
    fixture declares (via ``@pytest.mark.transfer_budget(n)``, default
    0) how many device->host bytes its counted fetch sites may move;
    exceeding the budget fails the test. The runtime face of the
    static ``--transfers`` pass: the manifest pins WHERE fetches
    happen, this pins HOW MUCH they move."""
    from vpp_tpu.pipeline import dataplane as _dp

    marker = request.node.get_closest_marker("transfer_budget")
    budget = int(marker.args[0]) if marker and marker.args else 0
    guard = _dp.transfer_budget(budget)
    guard.__enter__()
    yield guard
    try:
        guard.__exit__(None, None, None)
    except _dp.TransferBudgetExceeded as e:
        pytest.fail(str(e))


def pytest_sessionfinish(session, exitstatus):
    """The process-wide compile-once contract, verified over the WHOLE
    tier-1 run: every pipeline-step variant compiles at most once per
    (impl, skip, fast, form, call-shape) key per process. Consults the
    counter only if the dataplane was imported — this hook must not
    pull jax into a run that never used it."""
    import sys

    dp = sys.modules.get("vpp_tpu.pipeline.dataplane")
    if dp is None:
        return
    recompiled = dp.jit_recompiles()
    if recompiled:
        lines = [
            f"  {label} @ {n} compiles, shapes {sig!r}"
            for (label, sig), n in sorted(recompiled.items())
        ]
        print(
            "\njit-compile guard: compile-once contract BROKEN — "
            "step variants re-traced at identical call shapes (the "
            "PR-4 fresh-closure regression class):\n"
            + "\n".join(lines),
            file=sys.stderr,
        )
        session.exitstatus = 1
