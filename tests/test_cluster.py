"""Multi-chip cluster data plane over the virtual 8-device mesh.

Covers the reference's multi-node behaviors (SURVEY.md §2.4): per-node
vswitch replicas, inter-node pod-to-pod forwarding over the fabric
(two_node_two_pods.robot analog), global-ACL filtering of fabric traffic,
and the rule-sharded global table recombination.
"""

import numpy as np
import pytest

from vpp_tpu.ipam import IPAM
import ipaddress

from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.parallel import ClusterDataplane, cluster_mesh
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, ip4


def build_cluster(n_nodes=4, rule_shards=2, global_rules=()):
    mesh = cluster_mesh(n_nodes, rule_shards)
    cfg = DataplaneConfig(
        max_tables=4, max_rules=16, max_global_rules=32, max_ifaces=8,
        fib_slots=32, sess_slots=256, nat_mappings=4, nat_backends=16,
    )
    cluster = ClusterDataplane(mesh, cfg)
    pod_ip = {}
    pod_if = {}
    for nid in range(n_nodes):
        node = cluster.node(nid)
        uplink = node.add_uplink()
        ipam = IPAM(nid + 1)
        for p in range(2):
            pod = f"ns/pod{nid}-{p}"
            ip = ipam.next_pod_ip(pod)
            idx = node.add_pod_interface(pod)
            pod_ip[pod] = str(ip)
            pod_if[pod] = idx
            node.builder.add_route(f"{ip}/32", idx, Disposition.LOCAL)
        # Routes to every other node's pod subnet go to the fabric.
        for other in range(n_nodes):
            if other == nid:
                continue
            other_net = ipam.other_node_pod_network(other + 1)
            node.builder.add_route(
                str(other_net), uplink, Disposition.REMOTE, node_id=other
            )
        if global_rules:
            node.builder.set_global_table(list(global_rules))
    cluster.swap()
    return cluster, pod_ip, pod_if


@pytest.mark.slow  # ~18 s: full renderer orchestration; node bring-up is covered by test_cross_node_forwarding fast
def test_renderer_drives_cluster_nodes():
    """The policy pipeline (renderer API) works unchanged against a
    cluster node: commits publish cluster epochs via swap delegation,
    and verdicts are enforced on fabric-delivered traffic."""
    from vpp_tpu.renderer.tpu import TpuRenderer

    cluster, pod_ip, pod_if = build_cluster(
        global_rules=[ContivRule(action=Action.PERMIT)]
    )
    # render a policy on node 2: its pods accept only TCP/80
    node2 = cluster.node(2)
    renderer = TpuRenderer(node2)
    dst_pod = "ns/pod2-0"
    txn = renderer.new_txn()
    txn.render(dst_pod, ipaddress.ip_network(f"{pod_ip[dst_pod]}/32"),
               ingress=[], egress=[
        ContivRule(action=Action.PERMIT,
                   dest_network=ipaddress.ip_network(f"{pod_ip[dst_pod]}/32"),
                   protocol=Protocol.TCP, dest_port=80),
        ContivRule(action=Action.DENY),
    ])
    txn.commit()  # delegated swap — publishes a full cluster epoch
    assert cluster.epoch >= 2

    src = pod_ip["ns/pod0-0"]
    frames = [[] for _ in range(4)]
    frames[0] = [
        dict(src=src, dst=pod_ip[dst_pod], proto=6, sport=1, dport=80,
             rx_if=pod_if["ns/pod0-0"]),
        dict(src=src, dst=pod_ip[dst_pod], proto=6, sport=2, dport=22,
             rx_if=pod_if["ns/pod0-0"]),
    ]
    res = cluster.step(cluster.make_frames(frames))
    # Node 0 forwards both packets into the fabric (the sender node has
    # no policy for the destination); enforcement happens at node 2's
    # global table, where fabric traffic enters via the uplink.
    local_disp = np.asarray(res.local.disp[0][:2])
    assert (local_disp == int(Disposition.REMOTE)).all()
    deliv_disp = np.asarray(res.delivered.disp[2])
    deliv_if = np.asarray(res.delivered.tx_if[2])
    delivered_local = deliv_disp == int(Disposition.LOCAL)
    assert delivered_local.sum() == 1, "only the port-80 packet delivered"
    assert (deliv_if[delivered_local] == pod_if[dst_pod]).all()
    assert int(np.asarray(res.stats.drop_acl)[2]) == 1, "port 22 denied at node 2"


def test_cross_node_forwarding():
    cluster, pod_ip, pod_if = build_cluster()
    src = pod_ip["ns/pod0-0"]
    dst = pod_ip["ns/pod2-1"]
    frames = [[] for _ in range(4)]
    frames[0] = [dict(src=src, dst=dst, proto=6, sport=1234, dport=80,
                      rx_if=pod_if["ns/pod0-0"])]
    res = cluster.step(cluster.make_frames(frames, n=8))

    # Pass 1 at node 0: routed to the fabric toward node 2.
    disp = np.asarray(res.local.disp)
    nid = np.asarray(res.local.node_id)
    assert disp[0, 0] == int(Disposition.REMOTE)
    assert nid[0, 0] == 2

    # Pass 2 at node 2: delivered to the pod interface.
    d_disp = np.asarray(res.delivered.disp)
    d_txif = np.asarray(res.delivered.tx_if)
    d_dst = np.asarray(res.delivered.pkts.dst_ip)
    slots = np.nonzero(d_disp[2] == int(Disposition.LOCAL))[0]
    assert len(slots) == 1
    assert d_txif[2, slots[0]] == pod_if["ns/pod2-1"]
    assert d_dst[2, slots[0]] == ip4(dst)
    # No other node saw the packet.
    for n in (0, 1, 3):
        assert not np.any(d_disp[n] == int(Disposition.LOCAL))
    # TTL decremented twice: once per vswitch hop.
    assert np.asarray(res.delivered.pkts.ttl)[2, slots[0]] == 62


def test_global_acl_filters_fabric_traffic_sharded():
    # Rules land in different shards (rule_shards=2 splits 32 rows at 16):
    # a deny for dport 23 in shard 1, a permit for dport 80 in shard 2;
    # unmatched TCP is denied by the kernel default (acl_unmatched_default).
    rules = [
        ContivRule(Action.DENY, None, None, Protocol.TCP, 0, 23),
        ContivRule(Action.PERMIT, None, None, Protocol.TCP, 0, 80),
    ]
    # Pad so the permit-all lands in the second shard (index >= 16).
    pad = [
        ContivRule(Action.DENY, ipaddress.ip_network("203.0.113.77/32"), None,
                   Protocol.TCP, 0, 9999)
        for i in range(15)
    ]
    rules = [rules[0]] + pad + [rules[1]]
    assert len(rules) == 17  # permit-80 is at index 16 → second shard
    cluster, pod_ip, pod_if = build_cluster(global_rules=rules)

    src = pod_ip["ns/pod1-0"]
    dst = pod_ip["ns/pod3-0"]
    frames = [[] for _ in range(4)]
    frames[1] = [
        dict(src=src, dst=dst, proto=6, sport=40000, dport=80,
             rx_if=pod_if["ns/pod1-0"]),
        dict(src=src, dst=dst, proto=6, sport=40001, dport=23,
             rx_if=pod_if["ns/pod1-0"]),
    ]
    res = cluster.step(cluster.make_frames(frames, n=8))
    d_disp = np.asarray(res.delivered.disp)
    d_dport = np.asarray(res.delivered.pkts.dport)
    delivered = np.nonzero(d_disp[3] == int(Disposition.LOCAL))[0]
    # Only the :80 packet survives the global ACL at the destination.
    assert len(delivered) == 1
    assert d_dport[3, delivered[0]] == 80
    stats = np.asarray(res.stats.drop_acl)
    assert stats[3] == 1


def test_same_node_traffic_stays_local():
    cluster, pod_ip, pod_if = build_cluster()
    src = pod_ip["ns/pod1-0"]
    dst = pod_ip["ns/pod1-1"]
    frames = [[] for _ in range(4)]
    frames[1] = [dict(src=src, dst=dst, proto=17, sport=53, dport=53,
                      rx_if=pod_if["ns/pod1-0"])]
    res = cluster.step(cluster.make_frames(frames, n=8))
    disp = np.asarray(res.local.disp)
    txif = np.asarray(res.local.tx_if)
    assert disp[1, 0] == int(Disposition.LOCAL)
    assert txif[1, 0] == pod_if["ns/pod1-1"]
    # Nothing crossed the fabric.
    assert not np.any(np.asarray(res.delivered.disp) == int(Disposition.LOCAL))


def test_sessions_persist_across_cluster_swap():
    cluster, pod_ip, pod_if = build_cluster()
    src = pod_ip["ns/pod0-0"]
    dst = pod_ip["ns/pod2-0"]
    frames = [[] for _ in range(4)]
    frames[0] = [dict(src=src, dst=dst, proto=6, sport=5555, dport=443,
                      rx_if=pod_if["ns/pod0-0"])]
    res = cluster.step(cluster.make_frames(frames, n=8))
    # Forward flow delivered → session installed at both hops.
    before = np.asarray(res.tables.sess_valid).sum()
    assert before >= 1
    cluster.swap()  # re-publish config epoch
    after = np.asarray(cluster.tables.sess_valid).sum()
    assert after == before


def _acl_scale_rules(n_rules):
    """gen-policy-shaped rule set: CIDR-block x exact-port permits with
    interleaved denies + terminal deny (the north-star regime shape,
    reference tests/policy/perf/gen-policy.py)."""
    rules = []
    i = 0
    while len(rules) < n_rules - 1:
        block = i % 1000
        port = 8000 + (i // 1000) % 20
        net = ipaddress.ip_network(f"172.{16 + block // 256}.{block % 256}.0/24")
        action = Action.DENY if i % 6 == 5 else Action.PERMIT
        rules.append(
            ContivRule(action=action, src_network=net,
                       protocol=Protocol.TCP, dest_port=port)
        )
        i += 1
    rules.append(ContivRule(action=Action.DENY))
    return rules


@pytest.mark.slow  # ~35 s: at-scale shard geometry; the small-geometry mxu-vs-dense differential stays fast
def test_mxu_sharded_equals_dense_sharded_at_scale():
    """The rule-sharded MXU bit-plane classify and the rule-sharded dense
    classify produce identical cluster verdicts at 10k+ rules (VERDICT r3
    Missing #2: the north-star kernel must run in the north-star regime).
    """
    n_rules = 10240
    mesh = cluster_mesh(2, 4)  # 2 nodes x 4 rule shards on the 8-dev mesh
    cfg = DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=n_rules, max_ifaces=8,
        fib_slots=16, sess_slots=256, nat_mappings=2, nat_backends=4,
    )
    rules = _acl_scale_rules(n_rules)

    def build(force_dense):
        # pin the knob per build: the auto ladder now tops out at the
        # word-sharded BV kernel on the mesh (ISSUE 12), so the
        # dense-vs-MXU comparison this test exists for names its rungs.
        # fastpath off: fresh-flow traffic never engages it, and the
        # two-tier dispatcher would double BOTH 10k-rule program
        # compiles for nothing
        cluster = ClusterDataplane(
            mesh, cfg._replace(classifier="dense" if force_dense
                               else "mxu", fastpath=False))
        pod_if = {}
        for nid in range(2):
            node = cluster.node(nid)
            node.builder.mxu_enabled = not force_dense
            uplink = node.add_uplink()
            idx = node.add_pod_interface(("ns", f"p{nid}"))
            pod_if[nid] = idx
            node.builder.add_route(f"10.1.{nid}.2/32", idx, Disposition.LOCAL)
            other = 1 - nid
            node.builder.add_route(
                f"10.1.{other}.0/24", uplink, Disposition.REMOTE, node_id=other
            )
            node.builder.set_global_table(rules)
        cluster.swap()
        return cluster, pod_if

    # Traffic from node 0 to node 1 crossing the fabric: a spread of
    # sources that hit permit rules, deny rules, and no rule at all.
    def frames(cluster, rx_if):
        pkts = []
        for i in range(48):
            block = (i * 131) % 1000
            port = 8000 + (i % 24)  # ports 8020+ match no rule
            pkts.append(dict(
                src=f"172.{16 + block // 256}.{block % 256}.9",
                dst="10.1.1.2", proto=6, sport=30000 + i, dport=port,
                rx_if=rx_if,
            ))
        return cluster.make_frames([pkts, []], n=64)

    dense, pod_if_d = build(force_dense=True)
    assert dense._use_mxu is False
    res_d = dense.step(frames(dense, pod_if_d[0]), now=1)

    mxu, pod_if_m = build(force_dense=False)
    assert pod_if_m == pod_if_d
    assert mxu._use_mxu is True
    res_m = mxu.step(frames(mxu, pod_if_m[0]), now=1)

    for field in ("disp", "tx_if"):
        d = np.asarray(getattr(res_d.delivered, field))
        m = np.asarray(getattr(res_m.delivered, field))
        np.testing.assert_array_equal(d, m)
    np.testing.assert_array_equal(
        np.asarray(res_d.stats.drop_acl), np.asarray(res_m.stats.drop_acl)
    )
    assert int(np.asarray(res_m.stats.drop_acl).sum()) > 0
    delivered = np.asarray(res_m.delivered.disp)[1]
    assert (delivered == int(Disposition.LOCAL)).sum() > 0


@pytest.mark.slow  # ~90 s: three cluster builds, two stepped (one
# shard_map compile per FIB rung). The tier-1 pin for the mesh flip is
# test_multihost_unit.py::test_publish_agrees_fib_rung_fleet_wide —
# same select_fib_impl agreement + lpm step, one in-process mesh.
def test_fib_lpm_sharded_equals_dense_sharded():
    """The auto FIB ladder reaches the LPM rung on the mesh (the
    ROUTING.md "mechanical when a mesh gateway needs it" flip): a
    cluster staging >= fib_lpm_min_routes eligible routes selects lpm,
    and its verdicts — including nested-prefix longest-match decisions
    — are bit-identical to the dense cluster's."""
    mesh = cluster_mesh(2, 2)
    base = DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=64, sess_slots=256, nat_mappings=2, nat_backends=4,
        fib_lpm_min_routes=8,
    )

    def build(cfg):
        cluster = ClusterDataplane(mesh, cfg)
        pod_if = {}
        for nid in range(2):
            node = cluster.node(nid)
            uplink = node.add_uplink()
            idx = node.add_pod_interface(("ns", f"p{nid}"))
            pod_if[nid] = idx
            node.builder.add_route(f"10.1.{nid}.2/32", idx, Disposition.LOCAL)
            other = 1 - nid
            node.builder.add_route(
                f"10.1.{other}.0/24", uplink, Disposition.REMOTE,
                node_id=other)
            # Nested prefixes: the /16 covers every 10.2.x dst, the
            # /24s override a slice of it back to a LOCAL pod — the
            # longest-match decision is where dense and lpm could
            # diverge, so the spread pins it.
            node.builder.add_route(
                "10.2.0.0/16", uplink, Disposition.REMOTE, node_id=other)
            for i in range(6):
                node.builder.add_route(
                    f"10.2.{2 * i}.0/24", idx, Disposition.LOCAL)
            node.builder.set_global_table(
                [ContivRule(action=Action.PERMIT)])
        cluster.swap()
        return cluster, pod_if

    def frames(cluster, rx_if):
        pkts = []
        for i in range(24):
            # alternate between /24-covered (LOCAL at this node) and
            # /16-only (REMOTE via fabric) dsts, plus a no-route miss
            dst = (f"10.2.{i % 14}.7" if i % 3 else "10.9.0.1")
            pkts.append(dict(src="10.1.0.2", dst=dst, proto=6,
                             sport=20000 + i, dport=80, rx_if=rx_if))
        pkts.append(dict(src="10.1.0.2", dst="10.1.1.2", proto=6,
                         sport=40000, dport=80, rx_if=rx_if))
        return cluster.make_frames([pkts, []], n=32)

    dense, pod_if_d = build(base._replace(fib_impl="dense"))
    assert dense.fib_impl == "dense"
    res_d = dense.step(frames(dense, pod_if_d[0]), now=1)

    lpm, pod_if_l = build(base)  # auto + 9 routes/node >= 8 -> lpm
    assert pod_if_l == pod_if_d
    assert lpm.fib_impl == "lpm"
    res_l = lpm.step(frames(lpm, pod_if_l[0]), now=1)

    for res in (res_d, res_l):
        disp = np.asarray(res.local.disp)[0]
        assert (disp == int(Disposition.LOCAL)).sum() > 0
        assert (disp == int(Disposition.REMOTE)).sum() > 0
    for field in ("disp", "tx_if", "node_id"):
        np.testing.assert_array_equal(
            np.asarray(getattr(res_d.local, field)),
            np.asarray(getattr(res_l.local, field)))
    for field in ("disp", "tx_if"):
        np.testing.assert_array_equal(
            np.asarray(getattr(res_d.delivered, field)),
            np.asarray(getattr(res_l.delivered, field)))

    # below the ladder's min_routes floor the same staged FIB stays
    # dense — the standalone Dataplane discipline, verbatim
    small, _ = build(base._replace(fib_lpm_min_routes=256))
    assert small.fib_impl == "dense"


@pytest.mark.slow  # ~19 s: payload-bearing wire variant compile; cross-node forwarding keeps the fabric anchor fast
def test_wire_step_carries_payload_across_fabric():
    """step_wire: packet BYTES ride the same all_to_all as the header
    columns — a fabric-delivered packet's payload row at the
    destination is the source node's original bytes."""
    cluster, pod_ip, pod_if = build_cluster()
    src = pod_ip["ns/pod0-0"]
    dst = pod_ip["ns/pod2-1"]
    frames = [[] for _ in range(4)]
    frames[0] = [dict(src=src, dst=dst, proto=6, sport=7777, dport=80,
                      rx_if=pod_if["ns/pod0-0"])]
    pkts = cluster.make_frames(frames, n=8)
    snap = 64
    payload = np.zeros((4, 8, snap), np.uint8)
    wire_bytes = (b"\xAB" * 14 + b"E" + b"\x00" * 29
                  + b"fabric-payload-bytes").ljust(snap, b"\x00")
    payload[0, 0] = np.frombuffer(wire_bytes, np.uint8)
    res, deliv_pay = cluster.step_wire(pkts, payload, now=1)
    d_disp = np.asarray(res.delivered.disp)
    slots = np.nonzero(d_disp[2] == int(Disposition.LOCAL))[0]
    assert len(slots) == 1
    got = np.asarray(deliv_pay)[2, slots[0]]
    assert bytes(got) == bytes(payload[0, 0]), "bytes crossed the fabric"
    # non-fabric rows carry zeroed payload (no cross-slot leakage)
    others = np.asarray(deliv_pay)[1]
    assert not others.any()
