"""Mesh-mode WIRE e2e: real packets between netns pods THROUGH THE
FABRIC.

The full deployed multi-chip path: a UDP datagram sent by a netns pod
on mesh node 0 crosses veth → AF_PACKET → node-0 IO daemon → node-0 rx
ring → ClusterPump → cluster step (two fused pipeline passes joined by
all_to_all collectives carrying headers AND payload bytes) → node-1 tx
ring → node-1 IO daemon → veth → the destination pod's netns on mesh
node 1. No VXLAN anywhere: the interconnect IS the overlay
(SURVEY §2.4; reference analog two_node_two_pods.robot over the
node_events.go VXLAN mesh).
"""

from __future__ import annotations

import subprocess
import sys
import time

import pytest

from vpp_tpu.cmd import AgentConfig
from vpp_tpu.cmd.config import IOConfig
from vpp_tpu.cmd.ksr_main import KsrAgent
from vpp_tpu.cni.model import CNIRequest, ResultCode
from vpp_tpu.cni.wiring import host_ifname
from vpp_tpu.io.control import IOControlServer
from vpp_tpu.io.daemon import IODaemon
from vpp_tpu.kvstore.store import KVStore
from vpp_tpu.parallel.runtime import MeshRuntime
from vpp_tpu.pipeline.tables import DataplaneConfig


def _can_netns() -> bool:
    try:
        r = subprocess.run(["ip", "netns", "add", "vpptmwselfns"],
                           capture_output=True, timeout=10)
        if r.returncode == 0:
            subprocess.run(["ip", "netns", "del", "vpptmwselfns"],
                           capture_output=True, timeout=10)
            return True
        return False
    except (OSError, subprocess.TimeoutExpired):
        return False


pytestmark = pytest.mark.skipif(
    not _can_netns(), reason="needs CAP_NET_ADMIN (netns/veth)"
)

NS_A, NS_B = "vpptmw-poda", "vpptmw-podb"
CID_A = "meshaaaa1111bbbb2222"
CID_B = "meshcccc3333dddd4444"


def _cleanup():
    for ns in (NS_A, NS_B):
        subprocess.run(["ip", "netns", "del", ns], capture_output=True)
    for cid in (CID_A, CID_B):
        subprocess.run(["ip", "link", "del", host_ifname(cid)],
                       capture_output=True)


@pytest.fixture()
def mesh_stack(tmp_path):
    """2-node MeshRuntime with per-node IO daemons + the ClusterPump."""
    _cleanup()
    for ns in (NS_A, NS_B):
        subprocess.run(["ip", "netns", "add", ns], check=True, timeout=10)

    store = KVStore()
    ksr = KsrAgent(store=store, serve_http=False)
    ksr.start()
    cfg = AgentConfig(
        node_name="meshw",
        serve_http=False,
        dataplane=DataplaneConfig(
            max_tables=4, max_rules=16, max_global_rules=32, max_ifaces=16,
            fib_slots=64, sess_slots=256, nat_mappings=4, nat_backends=16,
        ),
        io=IOConfig(
            enabled=True, n_slots=16, snap=512,
            control_socket=str(tmp_path / "io-ctl.sock"),
        ),
    )
    runtime = MeshRuntime(2, cfg, rule_shards=2, store=store)
    # one vpp-tpu-io per node, attached to that node's rings, serving
    # the control socket that node's agent wires CNI pods through
    daemons, controls = [], []
    try:
        for i, agent in enumerate(runtime.agents):
            d = IODaemon(runtime.ring_pairs[i], {},
                         uplink_if=agent.uplink_if).start()
            c = IOControlServer(d, agent.config.io.control_socket).start()
            daemons.append(d)
            controls.append(c)
        runtime.start()
        yield {"runtime": runtime, "daemons": daemons, "store": store}
    finally:
        for c in controls:
            c.close()
        # daemons first: they hold ring pointers and runtime.close()
        # frees the ring buffers (a live io thread would use-after-free)
        for d in daemons:
            d.stop()
            for t in d.transports.values():
                t.close()
        runtime.close()
        _cleanup()


def _add_pod(agent, cid, ns, name):
    reply = agent.cni_server.add(CNIRequest(
        container_id=cid, netns=f"/var/run/netns/{ns}", if_name="eth0",
        extra_args={"K8S_POD_NAME": name, "K8S_POD_NAMESPACE": "default"},
    ))
    assert reply.result == ResultCode.OK, reply.error
    return reply.interfaces[0].ip_addresses[0].address.split("/")[0]


@pytest.mark.slow  # ~60 s total: real netns + veth e2e per test (function-scoped mesh_stack); the same wire path is covered fast by test_cluster/test_mesh_agent unit analogs
class TestMeshWire:
    def test_udp_crosses_the_fabric_between_netns_pods(self, mesh_stack):
        runtime = mesh_stack["runtime"]
        a0, a1 = runtime.agents
        ip_a = _add_pod(a0, CID_A, NS_A, "pod-a")
        ip_b = _add_pod(a1, CID_B, NS_B, "pod-b")
        # pods live in DIFFERENT nodes' subnets (allocator ids 1 and 2)
        assert ip_a.split(".")[2] != ip_b.split(".")[2]

        recv = subprocess.Popen(
            ["ip", "netns", "exec", NS_B, sys.executable, "-c",
             "import socket\n"
             "s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
             "s.bind(('0.0.0.0', 6011))\n"
             "s.settimeout(45)\n"
             "data, peer = s.recvfrom(4096)\n"
             "print(data.decode() + '|' + peer[0], flush=True)\n"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        time.sleep(0.5)
        subprocess.run(
            ["ip", "netns", "exec", NS_A, sys.executable, "-c",
             "import socket, time\n"
             "s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
             "for _ in range(40):\n"
             f"    s.sendto(b'over-the-ici-fabric', ('{ip_b}', 6011))\n"
             "    time.sleep(0.1)\n"],
            check=True, timeout=60, capture_output=True,
        )
        out, err = recv.communicate(timeout=50)
        assert "over-the-ici-fabric" in out, (out, err)
        assert ip_a in out, "source IP preserved across the fabric"
        # the pump really moved fabric traffic (not a local shortcut)
        assert runtime.cluster_pump.stats["fabric_pkts"] > 0
        assert runtime.cluster_pump.stats["steps"] > 0

    def test_policy_cuts_fabric_wire_traffic(self, mesh_stack):
        from vpp_tpu.ksr import model as m

        runtime = mesh_stack["runtime"]
        store = mesh_stack["store"]
        a0, a1 = runtime.agents
        ip_a = _add_pod(a0, CID_A, NS_A, "pod-a")
        ip_b = _add_pod(a1, CID_B, NS_B, "pod-b")
        # reflect pods + an isolate-pod-b policy through the store
        # (KSR-shaped keys drive both agents' policy plugins)
        from vpp_tpu.cmd.agent import KSR_PREFIX
        from vpp_tpu.ksr.model import key_for

        for name, ip in (("pod-a", ip_a), ("pod-b", ip_b)):
            pod = m.Pod(name=name, namespace="default",
                        labels={"app": name}, ip_address=ip)
            store.put(
                KSR_PREFIX + key_for(m.Pod.TYPE, name, "default"),
                pod.to_dict(),
            )
        pol = m.Policy(
            name="isolate-b", namespace="default",
            pods=m.LabelSelector(match_labels={"app": "pod-b"}),
            policy_type=m.POLICY_INGRESS, ingress_rules=[],
        )
        store.put(
            KSR_PREFIX + key_for(m.Policy.TYPE, "isolate-b", "default"),
            pol.to_dict(),
        )
        time.sleep(0.5)

        fabric_before = runtime.cluster_pump.stats["fabric_pkts"]
        recv = subprocess.Popen(
            ["ip", "netns", "exec", NS_B, sys.executable, "-c",
             "import socket\n"
             "s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
             "s.bind(('0.0.0.0', 6012))\n"
             "s.settimeout(6)\n"
             "try:\n"
             "    data, peer = s.recvfrom(4096)\n"
             "    print('GOT ' + data.decode(), flush=True)\n"
             "except socket.timeout:\n"
             "    print('TIMEOUT', flush=True)\n"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        time.sleep(0.5)
        subprocess.run(
            ["ip", "netns", "exec", NS_A, sys.executable, "-c",
             "import socket, time\n"
             "s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
             "for _ in range(15):\n"
             f"    s.sendto(b'must-not-arrive', ('{ip_b}', 6012))\n"
             "    time.sleep(0.1)\n"],
            check=True, timeout=60, capture_output=True,
        )
        out, _ = recv.communicate(timeout=20)
        assert "TIMEOUT" in out and "must-not-arrive" not in out
        # the policy cut the traffic ON the fabric path (drop at the
        # destination node's global table), not before it
        assert runtime.cluster_pump.stats["steps"] > 0
        assert runtime.cluster_pump.stats["fabric_pkts"] == fabric_before

    def test_cluster_pump_exported_from_exactly_one_collector(
            self, mesh_stack):
        """The shared ClusterPump's counters are cluster-wide: exactly
        one agent's Prometheus collector may export them, else sum()
        over the mesh's /stats endpoints overcounts by n_nodes."""
        runtime = mesh_stack["runtime"]
        exporters = [a for a in runtime.agents
                     if a.stats.pump is not None]
        assert len(exporters) == 1
        assert exporters[0].stats.pump is runtime.cluster_pump
