"""Prometheus text-format 0.0.4 conformance + metrics hygiene (ISSUE 2
satellites).

Scrapes every registered family over real HTTP and validates the
exposition contract a Prometheus server relies on: one ``# TYPE`` per
family, escaped help/labels, cumulative ``_bucket`` series whose
``le="+Inf"`` equals ``_count``, and counters that never step
backwards across publishes. Also invokes the in-tree metrics lint so a
badly named/help-less/duplicate family fails tier-1.
"""

import re
import urllib.request
from pathlib import Path

from vpp_tpu.cni import ContainerIndex, RemoteCNIServer
from vpp_tpu.cni.model import CNIRequest
from vpp_tpu.ipam.ipam import IPAM
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import make_packet_vector
from vpp_tpu.stats import Gauge, Histogram, MetricsRegistry, StatsHTTPServer
from vpp_tpu.stats.collector import (
    STATS_PATH,
    StatsCollector,
    register_control_plane_metrics,
)

SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{.*\})?'
    r' (?P<value>[0-9eE.+-]+|NaN|[+-]Inf)$'
)
LABELS_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"'
)


def parse_exposition(body: str):
    """text-format 0.0.4 → (types, samples); asserts line-level shape."""
    types = {}
    samples = []  # (family-or-series name, labels dict, float value)
    for line in body.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert name not in types, f"second TYPE line for {name}"
            assert kind in ("gauge", "counter", "histogram"), line
            types[name] = kind
            continue
        if line.startswith("# HELP "):
            # escaped help: no raw newline can survive into a HELP line
            # by construction; the payload must round-trip the escapes
            payload = line.split(" ", 3)[3]
            assert "\n" not in payload
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        match = SAMPLE_RE.match(line)
        assert match, f"malformed sample line: {line!r}"
        labels = {}
        if match.group("labels"):
            inner = match.group("labels")[1:-1]
            labels = dict(LABELS_RE.findall(inner))
        samples.append((match.group("name"), labels, float(match.group("value"))))
    return types, samples


def family_of(series_name: str, types: dict) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        base = series_name[: -len(suffix)] if series_name.endswith(suffix) else None
        if base and types.get(base) == "histogram":
            return base
    return series_name


def wired_collector():
    dp = Dataplane(DataplaneConfig(sess_slots=256))
    dp.add_uplink()
    dp.add_host_interface()
    ipam = IPAM(node_id=1)
    index = ContainerIndex()
    srv = RemoteCNIServer(dp, ipam, index)
    srv.set_ready()
    coll = StatsCollector(dp, index)
    hists = register_control_plane_metrics(coll.registry)
    dp.propagation_hist = hists["config_propagation"]
    dp.txn_commit_hist = hists["txn_commit"]
    srv.duration_hist = hists["cni_request"]
    r1 = srv.add(CNIRequest(container_id="c1", extra_args={
        "K8S_POD_NAME": "web", "K8S_POD_NAMESPACE": "prod"}))
    r2 = srv.add(CNIRequest(container_id="c2", extra_args={
        "K8S_POD_NAME": "db", "K8S_POD_NAMESPACE": "prod"}))
    ip1 = r1.interfaces[0].ip_addresses[0].address.split("/")[0]
    ip2 = r2.interfaces[0].ip_addresses[0].address.split("/")[0]
    if1 = dp.pod_if[("prod", "web")]
    res = dp.process(make_packet_vector(
        [dict(src=ip1, dst=ip2, proto=6, sport=1000 + i, dport=80,
              len=100, rx_if=if1) for i in range(4)]
    ))
    coll.update(res.stats)
    # exercise the pump-latency histogram path directly (no pump here)
    coll.pump_batch_hist.observe(0.0007)
    coll.pump_batch_hist.observe(0.02)
    coll.publish()
    return dp, srv, coll, (ip1, ip2, if1)


def scrape(port: int, path: str) -> str:
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ).read().decode()


def validate_body(body: str):
    types, samples = parse_exposition(body)
    seen_series = set()
    for name, labels, _ in samples:
        fam = family_of(name, types)
        assert fam in types, f"sample {name} has no TYPE line"
        key = (name, tuple(sorted(labels.items())))
        assert key not in seen_series, f"duplicate series {key}"
        seen_series.add(key)
    # histogram contract: cumulative buckets, +Inf == _count, _sum there
    hists = [n for n, k in types.items() if k == "histogram"]
    for fam in hists:
        by_labelset = {}
        for name, labels, value in samples:
            if name != f"{fam}_bucket":
                continue
            key = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"))
            by_labelset.setdefault(key, []).append((labels["le"], value))
        counts = {
            tuple(sorted(labels.items())): value
            for name, labels, value in samples if name == f"{fam}_count"
        }
        sums = {
            tuple(sorted(labels.items())): value
            for name, labels, value in samples if name == f"{fam}_sum"
        }
        for key, buckets in by_labelset.items():
            values = [v for _, v in buckets]  # exposition order
            assert values == sorted(values), \
                f"{fam}{key}: non-cumulative buckets {buckets}"
            les = [le for le, _ in buckets]
            assert les[-1] == "+Inf", f"{fam}{key}: last bucket {les[-1]}"
            numeric = [float(le) for le in les[:-1]]
            assert numeric == sorted(numeric)
            assert key in counts and key in sums, f"{fam}{key} incomplete"
            assert values[-1] == counts[key], \
                f"{fam}{key}: +Inf {values[-1]} != _count {counts[key]}"
    return types, samples


def test_exposition_conformance_over_http():
    dp, srv, coll, (ip1, ip2, if1) = wired_collector()
    server = StatsHTTPServer(coll.registry, port=0)
    server.start()
    try:
        # every path in the '/' index validates
        index = scrape(server.port, "/").split()
        assert STATS_PATH in index
        bodies = {}
        for path in index:
            bodies[path] = validate_body(scrape(server.port, path))
        types, samples = bodies[STATS_PATH]
        # the new histogram families are all exposed
        for fam in ("vpp_tpu_config_propagation_seconds",
                    "vpp_tpu_txn_commit_seconds",
                    "vpp_tpu_cni_request_seconds",
                    "vpp_tpu_pump_batch_seconds"):
            assert types.get(fam) == "histogram", fam
        # per-packet ML stage families (ISSUE 10): the StepStats
        # mirrors are gauges, the load ledger is a counter, and the
        # info-style stage gauge exports every mode label
        for fam in ("vpp_tpu_ml_scored_packets",
                    "vpp_tpu_ml_flagged_packets",
                    "vpp_tpu_ml_dropped_packets",
                    "vpp_tpu_ml_stage", "vpp_tpu_ml_model_version"):
            assert types.get(fam) == "gauge", fam
        assert types.get("vpp_tpu_ml_load_total") == "counter"
        ml_modes = {l.get("mode") for n, l, _ in samples
                    if n == "vpp_tpu_ml_stage"}
        assert ml_modes == {"off", "score", "enforce"}
        # build-info anchor (ISSUE 11 satellite): exactly one
        # constant-1 series carrying the identity labels
        info = [(l, v) for n, l, v in samples
                if n == "vpp_tpu_build_info"]
        assert len(info) == 1 and info[0][1] == 1.0
        assert set(info[0][0]) == {"version", "jax", "backend",
                                   "classifier"}
        from vpp_tpu import __version__
        assert info[0][0]["version"] == __version__
        assert info[0][0]["classifier"] in ("dense", "mxu", "bv")
        # the device wire-latency family registers (TYPE-only while
        # telemetry is off) + the telemetry mode info gauge reads off
        assert types.get("vpp_tpu_wire_latency_seconds") == "histogram"
        tel_modes = {l.get("mode"): v for n, l, v in samples
                     if n == "vpp_tpu_telemetry"}
        assert tel_modes == {"off": 1.0, "latency": 0.0, "full": 0.0}
        degraded = {l.get("component") for n, l, _ in samples
                    if n == "vpp_tpu_degraded"}
        assert "ml" in degraded
        # counters monotonic across two publishes with more traffic
        first = {
            (n, tuple(sorted(l.items()))): v for n, l, v in samples
            if types.get(family_of(n, types)) in ("counter", "histogram")
        }
        res = dp.process(make_packet_vector(
            [dict(src=ip1, dst=ip2, proto=6, sport=4321, dport=80,
                  len=100, rx_if=if1)]
        ))
        coll.update(res.stats)
        coll.pump_batch_hist.observe(0.001)
        dp.swap()  # txn-commit histogram moves too
        coll.publish()
        types2, samples2 = validate_body(scrape(server.port, STATS_PATH))
        second = {
            (n, tuple(sorted(l.items()))): v for n, l, v in samples2
            if types2.get(family_of(n, types2)) in ("counter", "histogram")
        }
        assert second, "no counter/histogram samples scraped"
        moved = 0
        for key, v1 in first.items():
            v2 = second.get(key)
            assert v2 is not None and v2 >= v1, \
                f"counter went backwards/vanished: {key} {v1} -> {v2}"
            moved += v2 > v1
        assert moved, "second publish must advance at least one counter"
    finally:
        server.close()


def test_help_and_label_escaping_survive_http():
    reg = MetricsRegistry()
    g = Gauge("vpp_tpu_esc_gauge", 'tricky help \\ with "quotes"\nand newline')
    g.set(1, pod='we"ird\\pod\nname')
    reg.register("/x", g)
    h = Histogram("vpp_tpu_esc_seconds", "hist\nhelp", buckets=(0.1, 1.0))
    h.observe(0.5, op='a"b')
    reg.register("/x", h)
    server = StatsHTTPServer(reg, port=0)
    server.start()
    try:
        body = scrape(server.port, "/x")
        types, samples = validate_body(body)
        assert types == {"vpp_tpu_esc_gauge": "gauge",
                         "vpp_tpu_esc_seconds": "histogram"}
        assert r"tricky help \\ with" in body and r"\nand newline" in body
        labels = [lbl for n, lbl, _ in samples if n == "vpp_tpu_esc_gauge"]
        # the parser keeps the on-wire (escaped) form: quote escaped,
        # backslash doubled, newline as literal \n
        assert labels and labels[0]["pod"] == 'we\\"ird\\\\pod\\nname'
    finally:
        server.close()


def test_head_and_404_for_unknown_paths():
    reg = MetricsRegistry()
    reg.register("/stats", Gauge("vpp_tpu_x", "x"))
    server = StatsHTTPServer(reg, port=0)
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/stats", method="HEAD")
        resp = urllib.request.urlopen(req, timeout=10)
        assert resp.status == 200
        assert int(resp.headers["Content-Length"]) > 0
        assert resp.read() == b""
        for method in ("GET", "HEAD"):
            try:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{server.port}/nope", method=method),
                    timeout=10)
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
    finally:
        server.close()


def _load_lint_module():
    import importlib.util

    lint_path = Path(__file__).resolve().parent.parent / "tools" / "lint.py"
    spec = importlib.util.spec_from_file_location("vpp_tpu_lint", lint_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_metrics_lint_clean():
    """The tier-1 hook for the tools/lint.py metrics pass: every family
    the deployed processes register must satisfy the hygiene rules."""
    assert _load_lint_module().metrics_lint() == []


def test_counters_lint_clean():
    """The tier-1 hook for the tools/lint.py --counters parity pass:
    every StepStats field maps to a registered Prometheus family, and
    every vpp_tpu_pipeline_* family maps back to a StepStats field."""
    assert _load_lint_module().counters_lint() == []


def test_metrics_lint_catches_violations():
    reg = MetricsRegistry()
    reg.register("/a", Gauge("vpp_tpu_ok", "fine"))
    reg.register("/a", Gauge("not_namespaced", "x"))
    reg.register("/a", Gauge("vpp_tpu_no_help"))
    reg.register("/b", Gauge("vpp_tpu_ok", "duplicate across paths"))
    problems = reg.lint()
    assert any("not_namespaced" in p for p in problems)
    assert any("empty help" in p for p in problems)
    assert any("duplicate" in p and "vpp_tpu_ok" in p for p in problems)


def test_tenant_families_render_with_parity(tmp_path):
    """Multi-tenant gateway families (ISSUE 14): a tenancy-on
    dataplane with a registered tenant exports every
    ``vpp_tpu_tenant_*`` family as a per-tenant labelled gauge over
    real HTTP, the pump drop family carries the ``tenant_quota``
    reason, and the --counters/--metrics parity passes stay green
    with the tenancy maps in them (PUMP_DROP_KEYS <-> reasons
    lockstep, the tnt_* StepStats/aux rows)."""
    from vpp_tpu.pipeline.vector import Disposition
    from vpp_tpu.stats.collector import TENANT_GAUGES

    dp = Dataplane(DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=16, sess_slots=256, nat_mappings=2, nat_backends=2,
        tenancy="on", sess_sweep_stride=0))
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("default", "web"))
    dp.builder.add_route("10.1.1.0/24", pod, Disposition.LOCAL)
    dp.builder.set_tenant(1, prefixes=["10.50.0.0/16"], rate=1,
                          burst=2, weight=3)
    dp.swap()
    res = dp.process(make_packet_vector(
        [dict(src=f"10.50.0.{i + 1}", dst="10.1.1.2", proto=17,
              sport=7000 + i, dport=53, rx_if=up) for i in range(6)]
    ), now=100)
    coll = StatsCollector(dp)
    coll.update(res.stats)

    class FakePump:
        # a pump surface carrying the device quota drops (aux rider
        # row 10) — enough for the drop-reason label space to render
        stats = {"drops_tenant_quota": 4}

        def latency_us(self):
            return {"p50": 0.0, "p99": 0.0, "n": 0}

        def tenant_io_snapshot(self):
            return {"io": {1: {"frames": 2, "pkts": 6,
                               "shed_pkts": 0, "admitted_pkts": 6}},
                    "queued": {}, "weights": {1: 3},
                    "names": {1: "tenant-1"}}

    coll.set_pump(FakePump())
    coll.publish()
    server = StatsHTTPServer(coll.registry, port=0)
    server.start()
    try:
        types, samples = validate_body(scrape(server.port, STATS_PATH))
        for fam, _help in TENANT_GAUGES:
            assert types.get(fam) == "gauge", fam
        by_fam = {}
        for n, labels, v in samples:
            by_fam.setdefault(n, {})[labels.get("tenant")] = v
        # the device accounting planes made it out per tenant:
        # burst 2 admits 2 of 6, 4 rate-limited
        assert by_fam["vpp_tpu_tenant_rx_packets"]["1"] == 6.0
        assert by_fam["vpp_tpu_tenant_goodput_packets"]["1"] == 2.0
        assert by_fam["vpp_tpu_tenant_rl_dropped_packets"]["1"] == 4.0
        assert by_fam["vpp_tpu_tenant_weight"]["1"] == 3.0
        # the StepStats mirror + the pump drop reason label space
        assert by_fam["vpp_tpu_node_tenant_limited_packets"][None] \
            == 4.0
        reasons = {l.get("reason"): v for n, l, v in samples
                   if n == "vpp_tpu_pump_drops_total"}
        assert reasons.get("tenant_quota") == 4.0
        # the pump lane counters landed under the tenant label too
        assert by_fam["vpp_tpu_tenant_io_packets"]["1"] == 6.0
    finally:
        server.close()
    # parity: the lint passes carry the tenancy maps
    mod = _load_lint_module()
    assert mod.metrics_lint() == []
    assert mod.counters_lint() == []
