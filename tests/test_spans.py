"""Control-plane span tracing tests (trace/spans.py; ISSUE 2 tentpole).

Unit level: tracer nesting/context/recording semantics. Agent level:
the acceptance path — a pod/policy event driven through the full
KSR → kvstore → agent → render → swap pipeline must observe the
``vpp_tpu_config_propagation_seconds`` SLO and yield a `show spans`
timeline with the stages in pipeline order.
"""

import threading

from vpp_tpu.cli import DebugCLI
from vpp_tpu.cmd import AgentConfig, ContivAgent
from vpp_tpu.cmd.ksr_main import KsrAgent
from vpp_tpu.cni.model import CNIRequest
from vpp_tpu.ksr import model as m
from vpp_tpu.kvstore.store import KVStore
from vpp_tpu.trace import spans


# --- tracer unit tests ---
def test_span_nesting_and_trace_ids():
    tr = spans.SpanTracer()
    with tr.span("ksr", "root") as root:
        assert spans.active()
        assert spans.current_root() is root
        with tr.span("kvstore", "child") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
            assert spans.current_span() is child
            assert spans.current_root() is root
    assert not spans.active()
    with tr.span("cni", "other") as other:
        assert other.trace_id != root.trace_id
        assert other.parent_id is None
    entries = tr.entries()
    assert [s.name for s in entries] == ["child", "root", "other"]
    assert all(s.done for s in entries)


def test_span_recorder_is_bounded():
    tr = spans.SpanTracer(max_spans=8)
    for i in range(20):
        with tr.span("agent", f"s{i}"):
            pass
    entries = tr.entries()
    assert len(entries) == 8
    assert entries[0].name == "s12" and entries[-1].name == "s19"


def test_span_context_is_per_thread():
    tr = spans.SpanTracer()
    seen = {}

    def worker():
        seen["active"] = spans.active()
        with tr.span("agent", "on-thread") as s:
            seen["parent"] = s.parent_id

    with tr.span("ksr", "main-root"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen["active"] is False, "trace context must not leak threads"
    assert seen["parent"] is None


def test_traces_grouping_sorted_by_start():
    tr = spans.SpanTracer()
    with tr.span("ksr", "r1"):
        with tr.span("swap", "inner"):
            pass
    traces = tr.traces()
    assert len(traces) == 1
    (spans_,) = traces.values()
    # sorted by start time: root first even though it ENDED last
    assert [s.stage for s in spans_] == ["ksr", "swap"]


def test_format_traces_empty():
    assert "no spans" in spans.SpanTracer().format_traces()


# --- agent-level acceptance ---
def boot():
    store = KVStore()
    ksr = KsrAgent(store=store, serve_http=False)
    ksr.start()
    agent = ContivAgent(
        AgentConfig(node_name="span-node", serve_http=False), store=store
    )
    agent.start()
    return store, ksr, agent


def add_pod(agent, cid, name, ns="default"):
    reply = agent.cni_server.add(CNIRequest(
        container_id=cid,
        extra_args={"K8S_POD_NAME": name, "K8S_POD_NAMESPACE": ns},
    ))
    assert reply.result == 0
    return reply.interfaces[0].ip_addresses[0].address.split("/")[0]


def test_config_propagation_e2e_spans_and_slo():
    """Drive a pod + policy event through the full pipeline: the
    propagation histogram must observe it and `show spans` must show
    the KSR, kvstore, render and swap stages in pipeline order."""
    store, ksr, agent = boot()
    ip_web = add_pod(agent, "c-web", "web")
    ip_db = add_pod(agent, "c-db", "db")

    prop = agent.cp_metrics["config_propagation"]
    cni_count = prop.get_count(source="cni")
    assert cni_count >= 1, "CNI adds are config events too"

    spans.RECORDER.clear()
    base = prop.get_count(source="ksr")
    ksr.sources[m.Pod.TYPE].add("default/web", m.Pod(
        name="web", namespace="default", labels={"app": "web"},
        ip_address=ip_web))
    ksr.sources[m.Pod.TYPE].add("default/db", m.Pod(
        name="db", namespace="default", labels={"app": "db"},
        ip_address=ip_db))
    ksr.sources[m.Policy.TYPE].add("default/db-policy", m.Policy(
        name="db-policy", namespace="default",
        pods=m.LabelSelector(match_labels={"app": "db"}),
        policy_type=m.POLICY_INGRESS,
        ingress_rules=[m.PolicyRule(
            ports=[m.PolicyPort(protocol="TCP", port=5432)],
            peers=[m.PolicyPeer(
                pods=m.LabelSelector(match_labels={"app": "web"}))],
        )],
    ))

    # the SLO observed the KSR-sourced swaps
    assert prop.get_count(source="ksr") > base
    assert prop.get_sum(source="ksr") > 0.0

    # a full trace exists with the acceptance stages in pipeline order
    full = [
        [s.stage for s in trace_spans]
        for trace_spans in spans.RECORDER.traces().values()
    ]
    want = ["ksr", "kvstore", "render", "swap"]
    ordered = [
        [st for st in stages if st in want] for stages in full
    ]
    assert want in ordered, f"no trace carries {want} in order: {full}"

    # `show spans` renders the same timeline for the operator
    cli = DebugCLI(agent.dataplane, stats=agent.stats)
    out = cli.run("show spans 50")
    idx = [out.index(f"[{stage}") for stage in want]
    assert idx == sorted(idx), out
    assert "epoch" in out

    # the exposition carries the histogram family end to end
    text = agent.stats.registry.render("/stats")
    assert "# TYPE vpp_tpu_config_propagation_seconds histogram" in text
    assert 'vpp_tpu_config_propagation_seconds_count{source="ksr"}' in text
    agent.close()


def test_txn_commit_and_cni_histograms_observe():
    store, ksr, agent = boot()
    add_pod(agent, "c1", "p1")
    assert agent.cp_metrics["cni_request"].get_count(op="add") == 1
    assert agent.cp_metrics["txn_commit"].get_count() >= 1
    agent.cni_server.delete(CNIRequest(container_id="c1"))
    assert agent.cp_metrics["cni_request"].get_count(op="del") == 1
    agent.close()


def test_debug_pages_and_http_surface(tmp_path):
    """/debug/spans + /debug/txns serve JSON, '/' indexes them, HEAD
    answers — the agent's debug surface over the stats port."""
    import json
    import urllib.request

    store = KVStore()
    agent = ContivAgent(AgentConfig(
        node_name="dbg", serve_http=True, stats_port=0, health_port=0,
        cni_socket=str(tmp_path / "cni.sock"), cli_socket="",
        txn_journal_path=str(tmp_path / "txn.jsonl"),
    ), store=store)
    agent.start()
    try:
        add_pod(agent, "c1", "p1")
        port = agent.stats_http.port
        index = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10).read().decode()
        for path in ("/stats", "/debug/spans", "/debug/txns"):
            assert path in index
        body = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/spans", timeout=10
        ).read().decode())
        stages = {s["stage"] for t in body["traces"] for s in t["spans"]}
        assert "swap" in stages and "cni" in stages
        txns = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/txns", timeout=10
        ).read().decode())
        assert txns["torn_lines"] == 0
        assert txns["shown"] == len(txns["txns"]) >= 2
        assert any(t["label"] == "cni-add default/p1" for t in txns["txns"])
        traced = [t for t in txns["txns"] if t["stage_seconds"]]
        assert traced, "journal entries join their span timings by epoch"
        assert "swap" in traced[-1]["stage_seconds"]
        # HEAD answers on debug pages too
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/debug/txns", method="HEAD")
        resp = urllib.request.urlopen(req, timeout=10)
        assert resp.status == 200
        assert int(resp.headers["Content-Length"]) > 0
    finally:
        agent.close()
