"""The in-tree analysis framework (ISSUE 5): fixture suite for every
``--jax`` and ``--threads`` rule (known-bad firing + suppressed twin),
the ImportCollector gap regressions, the clean-tree tier-1 hooks (the
same pattern test_exposition.py uses for --metrics/--counters), and the
runtime jit-compile guard — including the deliberately-recompiling
dataplane fixture the compile-budget guard must fail.
"""

from __future__ import annotations

import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
TOOLS = REPO / "tools"
if str(TOOLS) not in sys.path:
    sys.path.insert(0, str(TOOLS))

from analysis.common import parse_suppressions  # noqa: E402
from analysis.imports import ImportCollector, style_problems  # noqa: E402
from analysis.jaxlint import jax_lint  # noqa: E402
from analysis.threadlint import threads_lint  # noqa: E402

MOD = "pkg/m.py"
SITE_MODULE = {(MOD, "<module>"): "test fixture"}


def run_jax(tmp_path, src, manifest=None, traced=None):
    (tmp_path / "pkg").mkdir(exist_ok=True)
    (tmp_path / "pkg" / "m.py").write_text(src)
    return jax_lint(tmp_path, roots=("pkg",),
                    jit_sites=manifest if manifest is not None else {},
                    traced_roots=traced if traced is not None else set())


def run_threads(tmp_path, src):
    (tmp_path / "pkg").mkdir(exist_ok=True)
    (tmp_path / "pkg" / "m.py").write_text(src)
    return threads_lint(tmp_path, roots=("pkg",))


def rules_of(findings):
    return sorted({f.rule for f in findings})


# --- tier-1 hooks: the passes must be CLEAN on the live tree ---------

def test_jax_lint_clean_tree():
    """Zero unsuppressed jax-pass findings on vpp_tpu/{ops,pipeline,
    parallel} — every host sync / tracer branch / jit site is either
    fixed or carries a reasoned `# jax-ok:` (ISSUE 5 acceptance)."""
    assert [str(f) for f in jax_lint(REPO)] == []


def test_threads_lint_clean_tree():
    """Zero unsuppressed lock-discipline findings on the concurrent
    modules — every shared attribute is locked, `_locked`-suffixed, or
    carries a reasoned `# unlocked:` (ISSUE 5 acceptance)."""
    assert [str(f) for f in threads_lint(REPO)] == []


# --- suppression syntax ----------------------------------------------

def test_bare_suppression_is_a_finding(tmp_path):
    src = "import threading\n# unlocked:\nX = 1\n"
    assert "bare-suppression" in rules_of(run_threads(tmp_path, src))
    src = "# jax-ok\nX = 1\n"
    assert "bare-suppression" in rules_of(run_jax(tmp_path, src))


def test_comment_block_suppression_covers_next_code_line():
    sup = parse_suppressions(
        "x = 1\n# jax-ok: spans the block\n# more words\ny = 2\n")
    assert 2 in sup.jax and 4 in sup.jax and 1 not in sup.jax


def test_suppression_token_in_string_literal_ignored():
    """A suppression-shaped token inside a STRING must not register —
    it would silently mask findings on that line (and a bare one must
    not fire the bare-suppression rule either)."""
    sup = parse_suppressions(
        'HELP = "annotate with # jax-ok: reason"\n'
        'MSG = "see # unlocked"\n')
    assert sup.jax == {} and sup.unlocked == {} and sup.problems == []


# --- --jax rules: firing + suppressed fixture per rule ---------------

KERNEL_ITEM = """\
import jax
import jax.numpy as jnp
def kernel(x):
    return x.item(){sup}
k = jax.jit(kernel)
"""


def test_jax_host_sync_item(tmp_path):
    bad = run_jax(tmp_path, KERNEL_ITEM.format(sup=""),
                  manifest=SITE_MODULE)
    assert rules_of(bad) == ["host-sync"]
    ok = run_jax(tmp_path,
                 KERNEL_ITEM.format(sup="  # jax-ok: test probe"),
                 manifest=SITE_MODULE)
    assert ok == []


def test_jax_host_sync_int_of_tracer(tmp_path):
    src = ("import jax\nimport jax.numpy as jnp\n"
           "def kernel(x):\n"
           "    y = jnp.sum(x)\n"
           "    return int(y){sup}\n"
           "k = jax.jit(kernel)\n")
    bad = run_jax(tmp_path, src.format(sup=""), manifest=SITE_MODULE)
    assert rules_of(bad) == ["host-sync"]
    ok = run_jax(tmp_path, src.format(sup="  # jax-ok: diagnostics"),
                 manifest=SITE_MODULE)
    assert ok == []
    # int() of a HOST value in traced code is fine
    good = ("import jax\n"
            "def kernel(x):\n"
            "    n = int(x.shape[0])\n"
            "    return x[:n]\n"
            "k = jax.jit(kernel)\n")
    assert run_jax(tmp_path, good, manifest=SITE_MODULE) == []


def test_jax_host_sync_np_asarray(tmp_path):
    src = ("import jax\nimport jax.numpy as jnp\nimport numpy as np\n"
           "def kernel(x):\n"
           "    z = jnp.abs(x)\n"
           "    return np.asarray(z){sup}\n"
           "k = jax.jit(kernel)\n")
    bad = run_jax(tmp_path, src.format(sup=""), manifest=SITE_MODULE)
    assert rules_of(bad) == ["host-sync"]
    ok = run_jax(tmp_path, src.format(sup="  # jax-ok: boundary copy"),
                 manifest=SITE_MODULE)
    assert ok == []
    # np.asarray of host constants in traced code is constant folding
    good = ("import jax\nimport numpy as np\n"
            "def kernel(x):\n"
            "    w = np.asarray([1, 2, 3])\n"
            "    return x + w.sum()\n"
            "k = jax.jit(kernel)\n")
    assert run_jax(tmp_path, good, manifest=SITE_MODULE) == []


def test_jax_tracer_branch(tmp_path):
    src = ("import jax\n"
           "def kernel(x):\n"
           "    if x > 0:{sup}\n"
           "        return x\n"
           "    return -x\n"
           "k = jax.jit(kernel)\n")
    bad = run_jax(tmp_path, src.format(sup=""), manifest=SITE_MODULE)
    assert rules_of(bad) == ["tracer-branch"]
    ok = run_jax(tmp_path,
                 src.format(sup="  # jax-ok: unit-test only path"),
                 manifest=SITE_MODULE)
    assert ok == []
    # `is None` is static at trace time — never a tracer branch
    good = ("import jax\n"
            "def kernel(x, now=None):\n"
            "    if now is not None:\n"
            "        x = x + now\n"
            "    return x\n"
            "k = jax.jit(kernel)\n")
    assert run_jax(tmp_path, good, manifest=SITE_MODULE) == []


def test_jax_tracer_while(tmp_path):
    src = ("import jax\nimport jax.numpy as jnp\n"
           "def kernel(x):\n"
           "    while jnp.any(x > 0):{sup}\n"
           "        x = x - 1\n"
           "    return x\n"
           "k = jax.jit(kernel)\n")
    bad = run_jax(tmp_path, src.format(sup=""), manifest=SITE_MODULE)
    assert rules_of(bad) == ["tracer-branch"]
    ok = run_jax(tmp_path, src.format(sup="  # jax-ok: bounded probe"),
                 manifest=SITE_MODULE)
    assert ok == []


def test_jax_host_sync_inside_except_handler(tmp_path):
    """except-handler bodies are traced code too (ast.excepthandler is
    neither stmt nor expr — a naive walker skips them)."""
    src = ("import jax\nimport jax.numpy as jnp\n"
           "def kernel(x):\n"
           "    try:\n"
           "        y = jnp.sum(x)\n"
           "    except ValueError:\n"
           "        return x.item()\n"
           "    return y\n"
           "k = jax.jit(kernel)\n")
    assert rules_of(run_jax(tmp_path, src,
                            manifest=SITE_MODULE)) == ["host-sync"]


PER_INSTANCE = """\
import jax
class Pump:
    def build(self):
        def loop(t):
            return t + self.k
        self.f = jax.jit(loop){sup}
"""


def test_jax_per_instance_jit(tmp_path):
    manifest = {(MOD, "Pump.build"): "test fixture"}
    bad = run_jax(tmp_path, PER_INSTANCE.format(sup=""),
                  manifest=manifest)
    assert rules_of(bad) == ["per-instance-jit"]
    ok = run_jax(
        tmp_path,
        PER_INSTANCE.format(sup="  # jax-ok: singleton by design"),
        manifest=manifest)
    assert ok == []
    # a module-level target resolved through the same method is fine
    good = ("import jax\n"
            "def chain(t):\n"
            "    return t\n"
            "class Pump:\n"
            "    def build(self):\n"
            "        self.f = jax.jit(chain)\n")
    assert run_jax(tmp_path, good, manifest=manifest) == []


def test_jax_jit_unregistered(tmp_path):
    src = ("import jax\n"
           "def kernel(x):\n"
           "    return x\n"
           "k = jax.jit(kernel){sup}\n")
    bad = run_jax(tmp_path, src.format(sup=""), manifest={})
    assert rules_of(bad) == ["jit-unregistered"]
    ok = run_jax(tmp_path,
                 src.format(sup="  # jax-ok: scratch experiment"),
                 manifest={})
    assert ok == []


def test_jax_manifest_stale(tmp_path):
    src = "import jax\nX = 1\n"
    bad = run_jax(tmp_path, src,
                  manifest={(MOD, "gone_factory"): "was removed"})
    assert rules_of(bad) == ["jit-manifest-stale"]
    bad = run_jax(tmp_path, src, traced={(MOD, "gone_kernel")})
    assert rules_of(bad) == ["jit-manifest-stale"]
    # stale entries anchor to line 1 of the named module: suppressible
    ok = run_jax(tmp_path, "# jax-ok: migration in flight\nX = 1\n",
                 manifest={(MOD, "gone_factory"): "was removed"})
    assert ok == []


def test_jax_float_literal_dtype(tmp_path):
    src = ("import jax\nimport jax.numpy as jnp\n"
           "def kernel(x):\n"
           "    return x * jnp.full((4,), 0.5){sup}\n"
           "k = jax.jit(kernel)\n")
    bad = run_jax(tmp_path, src.format(sup=""), manifest=SITE_MODULE)
    assert rules_of(bad) == ["float-literal-dtype"]
    ok = run_jax(tmp_path,
                 src.format(sup="  # jax-ok: f32-only test host"),
                 manifest=SITE_MODULE)
    assert ok == []
    good = ("import jax\nimport jax.numpy as jnp\n"
            "def kernel(x):\n"
            "    return x * jnp.full((4,), 0.5, dtype=jnp.float32)\n"
            "k = jax.jit(kernel)\n")
    assert run_jax(tmp_path, good, manifest=SITE_MODULE) == []
    # any float64 reference in the traced roots is drift
    bad = run_jax(tmp_path, "import jax.numpy as jnp\nD = jnp.float64\n")
    assert rules_of(bad) == ["float-literal-dtype"]


def test_jax_lru_cache_method(tmp_path):
    src = ("import functools\n"
           "class A:\n"
           "    @functools.lru_cache(maxsize=None)\n"
           "    def step(self, n):{sup}\n"
           "        return n\n")
    bad = run_jax(tmp_path, src.format(sup=""))
    assert rules_of(bad) == ["lru-cache-method"]
    ok = run_jax(tmp_path,
                 src.format(sup="  # jax-ok: frozen singleton"))
    # the finding anchors to the def line; the suppression rides it
    assert ok == []


def test_jax_unhashable_arg(tmp_path):
    src = ("import functools\n"
           "@functools.lru_cache(maxsize=None)\n"
           "def make(key):\n"
           "    return key\n"
           "make([1, 2]){sup}\n")
    bad = run_jax(tmp_path, src.format(sup=""))
    assert rules_of(bad) == ["unhashable-arg"]
    ok = run_jax(tmp_path, src.format(sup="  # jax-ok: raises in test"))
    assert ok == []
    good = src.replace("make([1, 2]){sup}\n", "make((1, 2))\n")
    assert run_jax(tmp_path, good) == []


# --- --threads rules: firing + suppressed fixture per rule -----------

UNLOCKED = """\
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
    def inc(self):
        with self._lock:
            self.n += 1
    def peek(self):
        return self.n{sup}
"""


def test_threads_unlocked_access(tmp_path):
    bad = run_threads(tmp_path, UNLOCKED.format(sup=""))
    assert rules_of(bad) == ["unlocked-access"]
    assert "C.n" in str(bad[0])
    ok = run_threads(
        tmp_path,
        UNLOCKED.format(sup="  # unlocked: monotonic counter peek"))
    assert ok == []


def test_threads_subscripted_access_still_seen(tmp_path):
    """`self._buf[0].x` / `self._buf[:n].any()` — the protected attr
    sits under a Subscript, so the OUTER attribute chain doesn't root
    at self; the inner access must still be recorded."""
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._buf = [0]\n"
           "    def put(self):\n"
           "        with self._lock:\n"
           "            self._buf = [1]\n"
           "    def peek(self):\n"
           "        return self._buf[0].bit_length()\n")
    bad = run_threads(tmp_path, src)
    assert rules_of(bad) == ["unlocked-access"]
    assert "C._buf" in str(bad[0])


def test_threads_unlocked_write(tmp_path):
    src = UNLOCKED.format(sup="") + (
        "    def reset(self):\n"
        "        self.n = 0\n")
    bad = run_threads(tmp_path, src)
    lines = [str(f) for f in bad]
    assert any("write in reset()" in s for s in lines)


def test_threads_locked_suffix_and_init_exempt(tmp_path):
    good = ("import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.n = 0\n"   # __init__ write: exempt
            "    def inc(self):\n"
            "        with self._lock:\n"
            "            self.n += 1\n"
            "    def _drain_locked(self):\n"  # caller holds the lock
            "        return self.n\n")
    assert run_threads(tmp_path, good) == []


def test_threads_closure_resets_held_locks(tmp_path):
    # a worker closure defined under `with self._lock` runs LATER —
    # its unlocked access must still be flagged
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self.n = 0\n"
           "    def go(self):\n"
           "        with self._lock:\n"
           "            self.n += 1\n"
           "            def worker():\n"
           "                return self.n\n"
           "            return worker\n")
    bad = run_threads(tmp_path, src)
    assert rules_of(bad) == ["unlocked-access"]


LOCK_ORDER = """\
import threading
class D:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
    def f(self):
        with self._a:
            with self._b:{sup}
                pass
    def g(self):
        with self._b:
            with self._a:
                pass
"""


def test_threads_lock_order(tmp_path):
    bad = run_threads(tmp_path, LOCK_ORDER.format(sup=""))
    assert rules_of(bad) == ["lock-order"]
    ok = run_threads(
        tmp_path,
        LOCK_ORDER.format(sup="  # unlocked: g() is shutdown-only"))
    assert ok == []
    consistent = LOCK_ORDER.format(sup="").replace(
        "with self._b:\n            with self._a:",
        "with self._a:\n            with self._b:")
    assert run_threads(tmp_path, consistent) == []


def test_threads_lock_alias_followed(tmp_path):
    # commit_lock = self._lock (the Dataplane idiom): acquiring the
    # alias counts as holding the lock
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self.commit_lock = self._lock\n"
           "        self.n = 0\n"
           "    def inc(self):\n"
           "        with self._lock:\n"
           "            self.n += 1\n"
           "    def inc2(self):\n"
           "        with self.commit_lock:\n"
           "            self.n += 1\n")
    bad = run_threads(tmp_path, src)
    # self.n is written under two DIFFERENT lock names; the majority
    # lock wins and the alias access is reported — unless the aliasing
    # is recognized. Either zero findings (alias unified) or none on
    # the locked sites; what must NOT happen is a false positive on
    # inc(). Current implementation treats the alias as its own lock
    # object, so inc2 keeps its own edge — assert no findings against
    # inc() itself.
    assert not any("inc()" in str(f) for f in bad)


# --- ImportCollector gap regressions (ISSUE 5 satellite) -------------

def _unused(src: str, tmp_path) -> list:
    p = tmp_path / "s.py"
    p.write_text(src)
    return [x for x in style_problems(p) if "unused import" in x]


def test_imports_string_annotation_counts_as_use(tmp_path):
    src = ("import collections\n"
           "def f(x: \"collections.OrderedDict\") -> None:\n"
           "    return None\n")
    assert _unused(src, tmp_path) == []
    src = ("from os import path\n"
           "def f() -> \"path\":\n"
           "    return None\n")
    assert _unused(src, tmp_path) == []


def test_imports_all_tuple_and_augassign(tmp_path):
    assert _unused("import os\n__all__ = (\"os\",)\n", tmp_path) == []
    assert _unused(
        "import os\n__all__ = []\n__all__ += [\"os\"]\n", tmp_path) == []
    assert _unused(
        "import os\n__all__: tuple = (\"os\",)\n", tmp_path) == []
    # a genuinely unused import still fires
    assert _unused("import os\n__all__ = (\"sys\",)\n", tmp_path) != []


def test_imports_dotted_alias_binds_alias(tmp_path):
    assert _unused("import os.path as p\nX = p.sep\n", tmp_path) == []
    out = _unused("import os.path as p\nX = 1\n", tmp_path)
    assert len(out) == 1 and "'p'" in out[0]


def test_imports_decorator_only_use(tmp_path):
    src = ("import functools\n"
           "@functools.lru_cache(maxsize=None)\n"
           "def f():\n"
           "    return 1\n")
    assert _unused(src, tmp_path) == []


# --- runtime jit-compile guard ---------------------------------------

def _tiny_dp():
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig

    dp = Dataplane(DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=4))
    dp.add_uplink()
    dp.swap()
    return dp


def _pkts(n):
    from vpp_tpu.pipeline.vector import make_packet_vector

    return make_packet_vector(
        [{"src": "10.1.0.1", "dst": "10.1.1.2", "proto": 6,
          "sport": 1000, "dport": 80, "rx_if": 1}], n=n)


def test_compile_once_across_instances():
    """Two dataplanes with identical config share every step compile
    (the process-wide _JIT_STEPS cache): the second instance spends 0."""
    from vpp_tpu.pipeline import dataplane as dpmod

    dp1 = _tiny_dp()
    pkts = _pkts(8)
    dp1.process(pkts)  # warm (may compile if this shape is first)
    dp2 = _tiny_dp()
    with dpmod.jit_compile_budget(0) as guard:
        dp2.process(pkts)
    assert guard.spent == 0
    assert dpmod.jit_recompiles() == {}


def test_compile_guard_fails_recompiling_dataplane():
    """The deliberately-recompiling dataplane fixture (ISSUE 5
    acceptance): simulate the PR-4 fresh-closure bug by clearing the
    process-wide step cache between two identical-shape dataplanes —
    the SAME (variant, shape) traces twice, and the compile-budget
    guard must fail. Counter + cache state is restored so the
    end-of-session compile-once check sees the real tree, not this
    sabotage."""
    from vpp_tpu.pipeline import dataplane as dpmod

    steps_snap = dict(dpmod._JIT_STEPS)
    with dpmod._JIT_COMPILES_LOCK:
        counts_snap = dict(dpmod._JIT_COMPILES)
    try:
        pkts = _pkts(8)
        dp1 = _tiny_dp()
        dpmod._JIT_STEPS.clear()  # cold start, warm or not
        with pytest.raises(dpmod.JitBudgetExceeded) as exc:
            with dpmod.jit_compile_budget(1):
                dp1.process(pkts)          # the one budgeted compile
                dpmod._JIT_STEPS.clear()   # the PR-4 bug, simulated
                dp2 = _tiny_dp()
                dp2.process(pkts)          # same key+shape: re-trace
        assert "budget" in str(exc.value)
        # the contract break is independently visible to the runtime
        assert dpmod.jit_recompiles() != {}
    finally:
        dpmod._JIT_STEPS.clear()
        dpmod._JIT_STEPS.update(steps_snap)
        with dpmod._JIT_COMPILES_LOCK:
            dpmod._JIT_COMPILES.clear()
            dpmod._JIT_COMPILES.update(counts_snap)


@pytest.mark.jit_budget(4)
def test_compile_budget_fixture_green(jit_compile_budget):
    """The opt-in fixture in its intended green mode: a test that
    declares a budget and stays inside it passes (two same-shape steps
    cost at most one auto-variant compile)."""
    dp = _tiny_dp()
    pkts = _pkts(8)
    dp.process(pkts)
    dp.process(pkts)
    assert jit_compile_budget.spent <= 4


def test_jit_compiles_exported_and_surfaced():
    """vpp_tpu_jit_compiles_total{step=} reaches the scrape output and
    `show io` prints the compile-once summary (ISSUE 5 tentpole #3)."""
    from vpp_tpu.cli import DebugCLI
    from vpp_tpu.stats.collector import StatsCollector

    dp = _tiny_dp()
    dp.process(_pkts(8))
    coll = StatsCollector(dp)
    coll.publish()
    out = coll.registry.render("/stats")
    assert "vpp_tpu_jit_compiles_total" in out
    assert 'step="' in out
    cli = DebugCLI(dp)
    io_out = cli.run("show io")
    assert "jit compiles:" in io_out
    assert "RECOMPILED" not in io_out


def test_debug_jit_page_json():
    """/debug/jit serves the guard's full state (agent debug page)."""
    import json

    from vpp_tpu.cmd.agent import ContivAgent

    dp = _tiny_dp()
    dp.process(_pkts(8))
    page = json.loads(ContivAgent.debug_jit_json())
    assert set(page) == {"totals", "compiles", "recompiled"}
    assert page["recompiled"] == []
    assert any(c["count"] >= 1 for c in page["compiles"])


# =====================================================================
# ISSUE 20: device-boundary dataflow passes (--uploads / --transfers /
# --donate) and the runtime device-transfer guard.
# =====================================================================

from types import SimpleNamespace  # noqa: E402

from analysis.donatelint import donate_lint  # noqa: E402
from analysis.transferlint import transfers_lint  # noqa: E402
from analysis.uploadlint import uploads_lint  # noqa: E402

TBL = "pkg/tables.py"

# A minimal but complete tables.py: two groups, one ledger field, a
# TableBuilder whose base methods mark correctly. Fixture variants
# append methods / perturb groups from this known-clean core.
MINI_HEAD = '''\
_UPLOAD_GROUPS = {
    "acl": ("acl_rules", "acl_count"),
    "fib": ("fib_next_hop",),
}
SESSION_FIELDS = {"sess_key0": "u32"}


class DataplaneTables:
    acl_rules: object
    acl_count: object
    fib_next_hop: object
    sess_key0: object


class TableBuilder:
    def __init__(self):
        self.acl = []
        self.fib_next_hop = {}
        self._dirty = set(_UPLOAD_GROUPS)
        self._fib_dirty = set()

    def add_rule(self, r):
        self.acl.append(r)
        self._mark("acl")

    def _mark(self, group):
        self._dirty.add(group)
'''

MINI_PLACEMENTS = {
    "acl_rules": "group:acl",
    "acl_count": "group:acl",
    "fib_next_hop": "group:fib",
    "sess_key0": "ledger:SESSION_FIELDS",
}
MINI_STAGED = {"acl": "acl", "fib_next_hop": "fib"}


def _upload_ns(placements=MINI_PLACEMENTS, staged=MINI_STAGED,
               exempt=None):
    return SimpleNamespace(FIELD_PLACEMENTS=dict(placements),
                           STAGED_ATTRS=dict(staged),
                           EXEMPT_METHODS=dict(exempt or {}))


def _mini(body):
    return MINI_HEAD + "\n" + body


def run_uploads(tmp_path, tables_src, extra="", manifest=None):
    (tmp_path / "pkg").mkdir(exist_ok=True)
    (tmp_path / "pkg" / "tables.py").write_text(tables_src)
    (tmp_path / "pkg" / "other.py").write_text(extra)
    if manifest is None:
        manifest = _upload_ns()
    return uploads_lint(tmp_path, tables_rel=TBL, roots=("pkg",),
                        manifest=manifest)


def run_transfers(tmp_path, src, sites=None):
    (tmp_path / "pkg").mkdir(exist_ok=True)
    (tmp_path / "pkg" / "m.py").write_text(src)
    return transfers_lint(
        tmp_path, roots=("pkg",),
        manifest=SimpleNamespace(TRANSFER_SITES=dict(sites or {})))


def run_donate(tmp_path, src, jit_sites=None, calls=None):
    (tmp_path / "pkg").mkdir(exist_ok=True)
    (tmp_path / "pkg" / "m.py").write_text(src)
    return donate_lint(
        tmp_path, roots=("pkg",),
        manifest=SimpleNamespace(
            DONATED_JIT_SITES=dict(jit_sites or {}),
            DONATING_CALLS=dict(calls or {})))


# --- tier-1 hooks: the new passes must be CLEAN on the live tree -----

def test_uploads_lint_clean_tree():
    """Zero unsuppressed upload-placement/staleness findings: every
    DataplaneTables field has exactly one reviewed placement and every
    TableBuilder mutator marks its group on every path (ISSUE 20)."""
    assert [str(f) for f in uploads_lint(REPO)] == []


def test_transfers_lint_clean_tree():
    """Zero unsuppressed device->host fetches outside the approved
    transfer manifest (ISSUE 20)."""
    assert [str(f) for f in transfers_lint(REPO)] == []


def test_donate_lint_clean_tree():
    """Zero unsuppressed use-after-donate hazards through the
    registered donating jit sites (ISSUE 20)."""
    assert [str(f) for f in donate_lint(REPO)] == []


# --- --uploads: mark dataflow ----------------------------------------

def test_upload_mini_fixture_clean(tmp_path):
    body = ("    def set_route(self, i, nh):\n"
            "        self.fib_next_hop[i] = nh\n"
            "        self._mark(\"fib\")\n")
    assert run_uploads(tmp_path, _mini(body)) == []


def test_upload_mark_missing_fires(tmp_path):
    """The deliberately-stale-group TableBuilder: a staged write whose
    method forgets to mark the group dirty."""
    body = ("    def set_route(self, i, nh):\n"
            "        self.fib_next_hop[i] = nh\n")
    f = run_uploads(tmp_path, _mini(body))
    assert rules_of(f) == ["upload-mark-missing"]
    assert "'fib'" in str(f[0])


def test_upload_mark_missing_suppressed(tmp_path):
    body = ("    def set_route(self, i, nh):\n"
            "        self.fib_next_hop[i] = nh  # upload-ok: fixture\n")
    assert run_uploads(tmp_path, _mini(body)) == []


def test_upload_mark_on_one_branch_only(tmp_path):
    """A path-sensitive miss: marked on the if-branch, forgotten on
    fall-through — still a stale-group hazard."""
    body = ("    def set_route(self, i, nh, flag):\n"
            "        self.fib_next_hop[i] = nh\n"
            "        if flag:\n"
            "            self._mark(\"fib\")\n")
    assert rules_of(run_uploads(tmp_path, _mini(body))) == \
        ["upload-mark-missing"]


def test_upload_raise_path_not_counted(tmp_path):
    """Paths that raise never reach to_device(): no finding."""
    body = ("    def set_route(self, i, nh):\n"
            "        self.fib_next_hop[i] = nh\n"
            "        if i < 0:\n"
            "            raise ValueError(i)\n"
            "        self._mark(\"fib\")\n")
    assert run_uploads(tmp_path, _mini(body)) == []


def test_upload_mark_all_assignment(tmp_path):
    """`self._dirty = set(_UPLOAD_GROUPS)` re-marks every group."""
    body = ("    def reset(self):\n"
            "        self.fib_next_hop = {}\n"
            "        self.acl = []\n"
            "        self._dirty = set(_UPLOAD_GROUPS)\n")
    assert run_uploads(tmp_path, _mini(body)) == []


def test_upload_dirty_field_foreign(tmp_path):
    """A field pushed into a sub-dirty set that its group does not
    own is never consulted by the incremental uploader."""
    body = ("    def poke(self):\n"
            "        self._fib_dirty.add(\"acl_rules\")\n")
    f = run_uploads(tmp_path, _mini(body))
    assert rules_of(f) == ["upload-dirty-field-foreign"]
    good = ("    def poke(self):\n"
            "        self._fib_dirty.add(\"fib_next_hop\")\n")
    assert run_uploads(tmp_path, _mini(good)) == []


# --- --uploads: placement + manifest rules ---------------------------

def test_upload_field_unplaced(tmp_path):
    src = _mini("    pass\n").replace(
        "    sess_key0: object",
        "    sess_key0: object\n    orphan: object")
    rules = rules_of(run_uploads(tmp_path, src))
    assert "upload-field-unplaced" in rules
    assert "upload-manifest-missing" in rules


def test_upload_field_multi(tmp_path):
    src = _mini("    pass\n").replace(
        '"fib": ("fib_next_hop",),',
        '"fib": ("fib_next_hop", "acl_rules"),')
    assert rules_of(run_uploads(tmp_path, src)) == ["upload-field-multi"]


def test_upload_group_stale(tmp_path):
    src = _mini("    pass\n").replace(
        '"fib": ("fib_next_hop",),',
        '"fib": ("fib_next_hop", "ghost"),')
    assert rules_of(run_uploads(tmp_path, src)) == ["upload-group-stale"]


def test_upload_manifest_stale_and_mismatch(tmp_path):
    man = _upload_ns(placements={**MINI_PLACEMENTS,
                                 "ghost": "group:acl",
                                 "fib_next_hop": "group:acl"})
    f = run_uploads(tmp_path, _mini("    pass\n"), manifest=man)
    assert rules_of(f) == ["upload-manifest-mismatch",
                           "upload-manifest-stale"]


def test_upload_exempt_stale(tmp_path):
    man = _upload_ns(exempt={"gone": "was removed"})
    f = run_uploads(tmp_path, _mini("    pass\n"), manifest=man)
    assert rules_of(f) == ["upload-exempt-stale"]


def test_upload_extern_write(tmp_path):
    """Writes to builder staged attrs from OUTSIDE TableBuilder bypass
    dirty-marking entirely."""
    extra = "def hack(dp):\n    dp.builder.acl[0] = 1\n"
    f = run_uploads(tmp_path, _mini("    pass\n"), extra=extra)
    assert rules_of(f) == ["upload-extern-write"]
    ok = ("def hack(dp):\n"
          "    dp.builder.acl[0] = 1  # upload-ok: fixture\n")
    assert run_uploads(tmp_path, _mini("    pass\n"), extra=ok) == []


def test_upload_seeded_mutation_dropped_mark(tmp_path):
    """ISSUE 20 acceptance: drop ONE dirty-mark from the real
    TableBuilder (a copy) — the pass must catch it with the default
    manifest. The unmutated tree is clean (clean-tree hook above)."""
    real = (REPO / "vpp_tpu" / "pipeline" / "tables.py").read_text()
    assert 'self._mark("acl")' in real
    mutated = real.replace('self._mark("acl")', "pass", 1)
    dst = tmp_path / "vpp_tpu" / "pipeline"
    dst.mkdir(parents=True)
    (dst / "tables.py").write_text(mutated)
    f = uploads_lint(tmp_path, roots=())
    assert "upload-mark-missing" in rules_of(f)
    assert any("'acl'" in str(x) for x in f)


# --- --transfers: host materialization of table columns --------------

PROBE = ("import numpy as np\n"
         "\n"
         "\n"
         "def probe(tables):\n"
         "    return np.asarray(tables.sess_key0)\n")


def test_transfer_host_fetch_fires(tmp_path):
    """ISSUE 20 acceptance: the seeded `np.asarray(tables.sess_key0)`
    mutation is a finding when its site is not in the manifest."""
    f = run_transfers(tmp_path, PROBE)
    assert rules_of(f) == ["transfer-host-fetch"]
    assert "probe" in str(f[0])


def test_transfer_host_fetch_suppressed(tmp_path):
    src = PROBE.replace(
        "np.asarray(tables.sess_key0)",
        "np.asarray(tables.sess_key0)  # transfer-ok: fixture")
    assert run_transfers(tmp_path, src) == []


def test_transfer_approved_site(tmp_path):
    assert run_transfers(
        tmp_path, PROBE, sites={(MOD, "probe"): "fixture"}) == []
    assert run_transfers(
        tmp_path, PROBE, sites={(MOD, "*"): "fixture"}) == []


def test_transfer_metadata_not_tainted(tmp_path):
    """shape/dtype/nbytes are host metadata, not device values."""
    src = ("import numpy as np\n"
           "\n"
           "\n"
           "def probe(tables):\n"
           "    return np.asarray(tables.sess_key0.shape)\n")
    assert run_transfers(tmp_path, src) == []


def test_transfer_scalar_sinks(tmp_path):
    """int()/.item() on a tables-reachable value sync the device too —
    taint flows through the local assignment."""
    src = ("def probe(tables):\n"
           "    a = tables.sess_time\n"
           "    return int(a), a.item()\n")
    f = run_transfers(tmp_path, src)
    assert rules_of(f) == ["transfer-host-fetch"]
    assert len(f) == 2


def test_transfer_site_stale(tmp_path):
    src = "def noop():\n    return 0\n"
    f = run_transfers(tmp_path, src,
                      sites={(MOD, "gone"): "x",
                             ("pkg/no.py", "*"): "x"})
    assert rules_of(f) == ["transfer-site-stale"]
    assert len(f) == 2


# --- --donate: use-after-donate --------------------------------------

DONATING = {(MOD, "run", "step"): ((0,), "fixture")}

USE_AFTER = ("def run(step, tables, x):\n"
             "    out = step(tables, x)\n"
             "    return out + tables.sum()\n")


def test_use_after_donate_fires(tmp_path):
    f = run_donate(tmp_path, USE_AFTER, calls=DONATING)
    assert rules_of(f) == ["use-after-donate"]
    assert "'tables'" in str(f[0])


def test_use_after_donate_suppressed(tmp_path):
    src = USE_AFTER.replace(
        "    return out + tables.sum()\n",
        "    return out + tables.sum()  # donate-ok: fixture\n")
    assert run_donate(tmp_path, src, calls=DONATING) == []


def test_use_after_donate_rebind_clears(tmp_path):
    """The threading idiom — rebinding from the call's result — is the
    sanctioned way to keep using the name."""
    src = ("def run(step, tables, x):\n"
           "    tables = step(tables, x)\n"
           "    return tables.sum()\n")
    assert run_donate(tmp_path, src, calls=DONATING) == []


def test_use_after_donate_loop_carried(tmp_path):
    """The NEXT iteration's call re-donates a buffer the first
    iteration already invalidated."""
    src = ("def run(step, tables):\n"
           "    for _ in range(3):\n"
           "        out = step(tables)\n"
           "    return out\n")
    f = run_donate(tmp_path, src, calls=DONATING)
    assert rules_of(f) == ["use-after-donate"]
    assert "NEXT iteration" in str(f[0])
    rebound = ("def run(step, tables):\n"
               "    for _ in range(3):\n"
               "        tables = step(tables)\n"
               "    return tables\n")
    assert run_donate(tmp_path, rebound, calls=DONATING) == []


JITSRC = ("import jax\n"
          "\n"
          "\n"
          "def build(g):\n"
          "    return jax.jit(g, donate_argnums=(0,))\n")


def test_donate_unregistered(tmp_path):
    f = run_donate(tmp_path, JITSRC)
    assert rules_of(f) == ["donate-unregistered"]
    assert run_donate(tmp_path, JITSRC,
                      jit_sites={(MOD, "build"): "fixture"}) == []
    empty = JITSRC.replace("donate_argnums=(0,)", "donate_argnums=()")
    assert run_donate(tmp_path, empty) == []


def test_donate_unregistered_suppressed(tmp_path):
    src = JITSRC.replace(
        "    return jax.jit(g, donate_argnums=(0,))\n",
        "    return jax.jit(g, donate_argnums=(0,))"
        "  # donate-ok: fixture\n")
    assert run_donate(tmp_path, src) == []


def test_donate_site_stale(tmp_path):
    src = "def noop():\n    return 0\n"
    f = run_donate(tmp_path, src,
                   jit_sites={(MOD, "gone"): "x"},
                   calls={(MOD, "noop", "step"): ((0,), "x")})
    assert rules_of(f) == ["donate-site-stale"]
    assert len(f) == 2


# --- runtime device-transfer guard -----------------------------------

def test_transfer_counter_and_totals():
    """count_device_transfer sums tree-leaf nbytes per site (8 B for
    leaves without nbytes, e.g. python scalars)."""
    import numpy as np

    from vpp_tpu.pipeline import dataplane as dpm

    with dpm._TRANSFER_LOCK:
        saved = dict(dpm._TRANSFER_BYTES)
        dpm._TRANSFER_BYTES.clear()
    try:
        dpm.count_device_transfer("t.site", np.zeros(4, np.uint32))
        dpm.count_device_transfer("t.site", (np.zeros(2, np.uint8), 7))
        assert dpm.device_transfer_totals()["t.site"] == 16 + 2 + 8
    finally:
        with dpm._TRANSFER_LOCK:
            dpm._TRANSFER_BYTES.clear()
            dpm._TRANSFER_BYTES.update(saved)


def test_transfer_budget_green():
    """An approved snapshot fetch under a generous budget: counted,
    inside budget, spent visible on the guard."""
    from vpp_tpu.pipeline import dataplane as dpm

    dp = _tiny_dp()
    with dpm.transfer_budget(1 << 20) as guard:
        snap = dp.fib_snapshot()
    assert snap is not None
    assert guard.spent > 0


def test_transfer_budget_oversized_fetch_fails():
    """ISSUE 20 acceptance: the deliberately-oversized fetch trips the
    budget with per-site attribution; process counters are restored."""
    from vpp_tpu.pipeline import dataplane as dpm

    dp = _tiny_dp()
    with dpm._TRANSFER_LOCK:
        saved = dict(dpm._TRANSFER_BYTES)
    try:
        with pytest.raises(dpm.TransferBudgetExceeded) as ei:
            with dpm.transfer_budget(4):
                dp.fib_snapshot()
        assert "fib.snapshot" in str(ei.value)
    finally:
        with dpm._TRANSFER_LOCK:
            dpm._TRANSFER_BYTES.clear()
            dpm._TRANSFER_BYTES.update(saved)


@pytest.mark.transfer_budget(1 << 20)
def test_transfer_budget_fixture(transfer_budget):
    """The opt-in pytest fixture mirrors jit_compile_budget: the
    marker sets the byte budget, exceeding it fails the test."""
    dp = _tiny_dp()
    dp.fib_snapshot()
    assert transfer_budget.spent > 0


def test_transfer_bytes_exported_and_cli():
    """vpp_tpu_device_transfer_bytes_total{site=} reaches the scrape
    output and `show io` prints the per-site transfer summary."""
    from vpp_tpu.cli import DebugCLI
    from vpp_tpu.stats.collector import StatsCollector

    dp = _tiny_dp()
    dp.fib_snapshot()
    coll = StatsCollector(dp)
    coll.publish()
    out = coll.registry.render("/stats")
    assert "vpp_tpu_device_transfer_bytes_total" in out
    assert 'site="' in out
    cli = DebugCLI(dp)
    assert "device transfer bytes:" in cli.run("show io")


def test_pump_window_fetch_is_rider_sized():
    """ISSUE 20 acceptance: a wire window through the pump fetches the
    packed descriptor rows + aux summary — never the VEC x snap payload
    matrix — proven with the runtime transfer budget around the run."""
    import time as _time

    import numpy as np
    from wire import make_frame

    from vpp_tpu.io import DataplanePump, IORingPair
    from vpp_tpu.native.pktio import PacketCodec
    from vpp_tpu.pipeline import dataplane as dpm
    from vpp_tpu.pipeline.dataplane import Dataplane, packed_input_zeros
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import VEC, Disposition

    dp = Dataplane(DataplaneConfig())
    a = dp.add_pod_interface(("default", "a"))
    b = dp.add_pod_interface(("default", "b"))
    dp.builder.add_route("10.1.1.2/32", a, Disposition.LOCAL)
    dp.builder.add_route("10.1.1.3/32", b, Disposition.LOCAL)
    dp.swap()
    dp.process_packed(packed_input_zeros(256))  # compile outside
    codec = PacketCodec()
    rings = IORingPair(n_slots=32)
    scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
    n_frames, per = 4, 8
    for k in range(n_frames):
        frames = [make_frame("10.1.1.2", "10.1.1.3", proto=17,
                             sport=20000 + k, dport=1000 + j)
                  for j in range(per)]
        cols, n = codec.parse(frames, a, scratch)
        assert rings.rx.push(cols, n, payload=scratch)
    payload_scale = VEC * rings.rx.snap  # one window of raw packet bytes
    with dpm.transfer_budget(64 * 1024) as guard:
        pump = DataplanePump(dp, rings).start()
        try:
            got = 0
            deadline = _time.monotonic() + 60
            while got < n_frames and _time.monotonic() < deadline:
                if rings.tx.peek() is None:
                    _time.sleep(0.005)
                    continue
                got += 1
                rings.tx.release()
            assert got == n_frames
        finally:
            pump.stop()
            rings.close()
    assert 0 < guard.spent < payload_scale
